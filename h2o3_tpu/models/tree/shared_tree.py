"""Level-wise distributed tree builder — successor of ``hex.tree.SharedTree``
/ ``DTree`` (``UndecidedNode``/``DecidedNode``, ``findBestSplitPoint``) /
``ScoreBuildHistogram2`` [UNVERIFIED upstream paths, SURVEY.md §2.2 §3.3].

Per level (SURVEY §3.3 call stack, TPU-native form), ALL fused into ONE
compiled device program (`_level_step`):
1. histogram pass — the ScoreBuildHistogram successor: {w,wy,wh} into
   (node,col,bin) cells per row shard, psum across the mesh (the wy² lane
   of upstream's DHistogram cancels in the gain — see _split_scan)
   (:mod:`h2o3_tpu.ops.histogram`).
2. split scan — DTree.findBestSplitPoint vectorized over all (node, col)
   pairs: SE-reduction gain over bin prefixes, NA-direction both ways
   (DHistogram's NA trick), categorical bins in mean-sorted order
   (DHistogram's categorical bin-sort).
3. leaf decision + child id assignment (compacted via device cumsum — the
   active-leaf frontier, NOT full 2^d indexing, so depth-20 DRF stays
   bounded by ``node_cap``).
4. partition update — the DecidedNode re-labeling: rows map to child nids;
   rows landing in finalized leaves add the leaf value to the running
   prediction and retire with nid=-1.
5. variable-importance scatter (per-split gain by column).

Device-residency is the design point: the driving host loop only *dispatches*
one program per level and never blocks on device→host transfers (on a
networked TPU a single transfer costs ~100ms — the former per-level host
round-trips dominated build time ~30:1 over compute). Recorded per-level
arrays stay on device; prediction replays them without ever touching host.
The only syncs are an occasional early-exit poll for deep trees and the
final scoring pulls.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

_NEG = -1e30


# ---------------------------------------------------------------------------
# build telemetry: host dispatches and program-cache traffic. The whole-tree
# design's contract is O(1) dispatches per tree (vs O(depth) for the
# host-driven level loop) and one compile per shape signature — these
# counters are how tests assert it and how bench.py reports it. The counts
# now live in the cluster metrics registry (utils/metrics.py, served over
# GET /3/Metrics); BUILD_STATS stays as a dict-shaped back-compat alias
# whose reads and writes go straight through to the registry counters
# (always=True: the accounting is a test contract, not optional telemetry,
# so H2O3_TPU_METRICS=0 does not switch it off).

from h2o3_tpu.utils import jobacct as _jobacct
from h2o3_tpu.utils import metrics as _metrics

_BUILD_COUNTERS = {
    # alias key -> registry counter
    "dispatches": _metrics.counter(
        "tree_dispatches_total",
        "device-program launches issued by the tree builders", always=True),
    "trees_built": _metrics.counter(
        "tree_trees_built_total", "trees those dispatches produced",
        always=True),
    "tree_programs_compiled": _metrics.counter(
        "tree_programs_compiled_total",
        "whole-tree/chunk program cache misses", always=True),
    "tree_program_cache_hits": _metrics.counter(
        "tree_program_cache_hits_total",
        "whole-tree/chunk program cache hits (same shape, no recompile)",
        always=True),
    # saturated-region while_loop iterations that actually EXECUTED (the
    # on-device early exit can skip the rest): read back per dispatch and
    # used to scale the sat-region byte tallies to actual volume
    "sat_levels_executed": _metrics.counter(
        "tree_sat_levels_total",
        "node_cap-saturated tree levels actually executed by the fused "
        "builds' while_loop (post-early-exit)", always=True),
}
_FUSED_SECONDS = _metrics.counter(
    "tree_fused_build_seconds_total",
    "wall seconds spent inside fused whole-tree/chunk build dispatch calls",
    always=True)

# Collective observability for the split pipeline (labeled by phase:
# hist_reduce = the histogram psum / psum_scatter, winner_gather = the
# sharded scan's per-block winner all-gather). Bytes use the replication-
# volume model (see ops/histogram.py record_collective): what the collective
# leaves on each device — the O(C·N·B·S) vs O(C·N·B·S/P) quantity the
# sharded pipeline shrinks — tallied from the traced program structure and
# replayed per dispatch, so bench's psum_bytes_per_tree is derived from what
# actually ran, not asserted. Seconds are filled by bench.py's collective
# calibration microbench (collectives inside a fused program cannot be
# host-timed individually).
_COLL_BYTES = _metrics.counter(
    "tree_collective_bytes_total",
    "per-device collective payload bytes moved by tree builds (replication-"
    "volume model), by phase", always=True)
_COLL_SECONDS = _metrics.counter(
    "tree_collective_seconds_total",
    "measured seconds of representative tree-phase collectives (bench "
    "calibration microbench), by phase", always=True)

# HBM-traffic model of the histogram+split phases, by pipeline path
# (``path``: fused = blocked Pallas histogram → Pallas split kernel, no
# unscramble pass; pallas_unfused = Pallas histogram + two HBM unscramble
# transposes + dense XLA scan; dense = scatter/matmul histogram + dense
# scan; fused_via_dense = the CPU correctness lane that re-blocks a dense
# histogram). Same traced-structure tally mechanism as the collective
# bytes (ops/histogram.record_hbm): one write per materialized
# intermediate + one read per consumed one, recorded at trace time and
# replayed per dispatch — so the fused pipeline's "no full-histogram HBM
# round-trip" claim is a measured artifact number, not prose. Terminal
# force-leaf levels skip the scan read the model counts: an upper bound,
# like the saturated-region collective tally.
_HIST_HBM_BYTES = _metrics.counter(
    "tree_hist_hbm_bytes_total",
    "modeled per-device HBM bytes moved by the histogram+split phases of "
    "tree builds, by pipeline path", always=True)

# Fallback observability (ISSUE 15): builds that WANT the fused Pallas lane
# (the knob/backend gate says fuse) but drop to a slow lane for a
# structural reason. ISSUE 16 closed the last structural reason (uplift's
# 4-lane scan now runs through the whole-tree fused program); the uplift /
# mono / cat_sharded reasons stay wired so a future regression of the
# closure is a counter bump, not an archaeology dig through MIGRATION.md —
# uplift only tallies on the legacy per-level loop (H2O3_TPU_WHOLE_TREE=0).
_FUSED_FALLBACKS = _metrics.counter(
    "tree_fused_fallbacks_total",
    "tree builds that fell back from the fused Pallas histogram→split lane "
    "while the fuse gate was ON, by structural reason", always=True)

# Wave-2 arithmetic-reduction observability (ISSUE 16). Rows-sampled is the
# MODELED kept-row volume of GOSS builds ((a+b) · padded rows · trees —
# the expected fraction, same modeled-volume convention as the HBM bytes);
# cols-bundled counts real feature columns EFB eliminated from the
# histogram grid, per build.
_ROWS_SAMPLED = _metrics.counter(
    "tree_rows_sampled_total",
    "modeled rows kept by GOSS one-side sampling across tree builds "
    "(expected (a+b) fraction of the padded row count, per tree)",
    always=True)
_COLS_BUNDLED = _metrics.counter(
    "tree_cols_bundled_total",
    "feature columns removed from the histogram C dimension by exclusive "
    "feature bundling, per build", always=True)

# program-key registry + per-program collective tallies: _run_counted
# captures a program's ((phase, lane, group) -> bytes) tally during its
# first (tracing) dispatch and replays it on every later one.
_PROG_KEY: dict[int, tuple] = {}
_PROG_COLL: dict = {}


def _run_counted(fn, args, mult: int = 1, sat_from=None):
    """Dispatch ``fn(*args)`` with collective byte accounting.

    ``mult`` scales the traced tally per dispatch (a scanned chunk's body
    traces once but executes once per tree). Entries recorded under
    ``tally_group("sat")`` — the node_cap-saturated while_loop body, traced
    once but executed a data-dependent number of times — are instead
    scaled by the EXECUTED iteration count, extracted from the program's
    output via ``sat_from(out)`` (the fused programs return it), so the
    counters report actual volume, not the old n_sat trace-time upper
    bound. Reading that scalar syncs the dispatch — one int32 pull, and
    only for programs that traced a saturated region at all (deep builds
    whose per-level cost dwarfs it; GBM-typical shallow trees never pay)."""
    from h2o3_tpu.ops.collectives import collective_tally
    from h2o3_tpu.utils import flightrec as _fr

    key = _PROG_KEY.get(id(fn), id(fn))
    # the flight-recorder dispatch event: the cached-program key already
    # carries shape bucket + mesh key + lane knobs (the jit cache key)
    _disp = _fr.dispatch("tree", program=str(key)[:160], mult=mult)
    agg = _PROG_COLL.get(key)
    if agg is None:
        entries: list = []
        with _disp, collective_tally(entries):
            out = fn(*args)
        agg = {}
        for ph, lane, grp, b in entries:
            k = (ph, lane, grp)
            agg[k] = agg.get(k, 0.0) + b
        _PROG_COLL[key] = agg
    else:
        with _disp:
            out = fn(*args)
    if agg:
        # per-dispatch collective phase tallies ride the ring too, so an
        # incident bundle shows what the dying dispatch was reducing
        by_phase: dict = {}
        for (ph, _lane, _grp), b in agg.items():
            by_phase[ph] = by_phase.get(ph, 0) + int(b)
        _fr.record("collectives", **by_phase)
    sat_n = None
    for (ph, lane, grp), b in agg.items():
        if grp == "sat":
            if sat_n is None:
                sat_n = (
                    int(jax.device_get(sat_from(out)))
                    if sat_from is not None else 0
                )
                BUILD_STATS["sat_levels_executed"] += sat_n
            m = sat_n
        else:
            m = mult
        if not b or not m:
            continue
        if ph.startswith("hbm/"):
            _HIST_HBM_BYTES.inc(b * m, path=ph[4:])
        else:
            _COLL_BYTES.inc(b * m, phase=ph)
            _COLL_BYTES.inc(b * m, phase=ph, lane=lane)
            # per-job attribution: the replayed tally charges the job whose
            # trace this dispatch ran under (utils/jobacct.py), lane-split
            _jobacct.on_collective_bytes(
                _metrics.current_trace(), b * m, lane=lane)
    return out


class _BuildStatsAlias:
    """Mapping view of the tree-build registry counters.

    ``BUILD_STATS["dispatches"] += 1`` and ``dict(BUILD_STATS)`` behave
    exactly as they did when this was a module-global dict — existing tests
    and bench code keep working — but the single source of truth is the
    registry, so /3/Metrics and bench artifacts cannot disagree."""

    def __getitem__(self, k: str) -> int:
        return int(_BUILD_COUNTERS[k].value())

    def __setitem__(self, k: str, v) -> None:
        _BUILD_COUNTERS[k].set_(float(v))

    def __iter__(self):
        return iter(_BUILD_COUNTERS)

    def __len__(self) -> int:
        return len(_BUILD_COUNTERS)

    def __contains__(self, k) -> bool:
        return k in _BUILD_COUNTERS

    def keys(self):
        return _BUILD_COUNTERS.keys()

    def items(self):
        return [(k, self[k]) for k in _BUILD_COUNTERS]

    def values(self):
        return [self[k] for k in _BUILD_COUNTERS]

    def __repr__(self) -> str:
        return repr(dict(self.items()))


BUILD_STATS = _BuildStatsAlias()


def reset_build_stats() -> dict:
    """Zero the counters and return the pre-reset snapshot."""
    snap = dict(BUILD_STATS.items())
    for k in BUILD_STATS:
        BUILD_STATS[k] = 0
    return snap


def _cached_program(key, make):
    """_STEP_CACHE lookup with compile/hit accounting for tree programs."""
    fn = _STEP_CACHE.get(key)
    if fn is None:
        BUILD_STATS["tree_programs_compiled"] += 1
        fn = make()
        _STEP_CACHE[key] = fn
    else:
        BUILD_STATS["tree_program_cache_hits"] += 1
    _PROG_KEY[id(fn)] = key
    return fn


# ---------------------------------------------------------------------------
# split finding (pure function, traced inside the level step)


def _split_scan(hist, is_cat, col_mask, min_rows, min_split_improvement, cat_cols=(),
                mono=None, node_lo=None, node_hi=None, node_totals=None):
    """Best split per node from hist (N, C, B, 3). Returns per-node arrays.

    Stats axis: 0=w, 1=wy, 2=wh. Bin 0 is the NA bin.

    DHistogram's squared-error gain is (wy2 - wy^2/w)_parent - (...)_L -
    (...)_R; since L, R and the NA side PARTITION the node's rows, the wy2
    terms cancel EXACTLY and the gain equals wy_L^2/w_L + wy_R^2/w_R -
    wy_tot^2/w_tot. The histogram therefore never accumulates a wy2 lane —
    a 25% MXU/HBM saving in the dominant phase at identical math (float
    rounding aside; ``fit`` below is the wy2-free per-side term).

    ``cat_cols`` is the STATIC tuple of categorical column indices: the
    mean-sorted categorical branch (two argsorts over (N, C, B-1) — by far
    the most expensive part of this scan on TPU) runs only on that column
    subset, and disappears entirely for all-numeric frames.

    ``mono`` (optional, (C,) int {-1,0,1}) activates monotone-constraint
    feasibility: numeric candidates whose bound-clamped child Newton values
    violate the direction are masked BEFORE the column argmax (so a feasible
    categorical or other-numeric split wins on merit), and the result gains
    ``mid``/``mono_col`` for child-bound propagation. The unconstrained path
    is untouched (this branch doesn't trace when mono is None).

    ``node_totals`` ((N, 3), optional) overrides the per-node {w, wy, wh}
    totals that feed ``parent_fit`` and the node stats. The replicated path
    derives them from column 0's bin sum ("any column sums to the node
    totals" — every row lights exactly one bin per column); the sharded
    path passes GLOBAL column 0's totals in, because a different column's
    bin partition sums the same rows in a different grouping and the float
    result can differ in the last bits — which would make per-block gains
    incomparable with the replicated scan's.
    """
    N, C, B, _ = hist.shape
    na = hist[:, :, 0, :]  # (N, C, 3)
    data = hist[:, :, 1:, :]  # (N, C, B-1, 3)

    def fit(s):  # SE with the cancelling wy2 term dropped: -wy^2/w
        w = s[..., 0]
        return -jnp.where(w > 0, s[..., 1] ** 2 / jnp.maximum(w, 1e-30), 0.0)

    if node_totals is None:
        node_totals = hist.sum(axis=2)[:, 0, :]  # (N, 3), from column 0
    parent_fit = fit(node_totals[:, None, :]).squeeze(1)  # same for every col: (N,)

    def gain_with_na(L, R):
        gl = fit(L)
        gr = fit(R)
        ok = (L[..., 0] >= min_rows) & (R[..., 0] >= min_rows)
        g = parent_fit[:, None, None] - gl - gr
        return jnp.where(ok, g, _NEG)

    # ---- numeric: prefix split over natural bin order ----
    cum = jnp.cumsum(data, axis=2)  # (N, C, B-1, 3)
    tot_nonna = cum[:, :, -1:, :]
    left_n = cum[:, :, :-1, :]  # split after data-bin t: left = bins 1..t+1
    right_n = tot_nonna - left_n

    g_naleft = gain_with_na(left_n + na[:, :, None, :], right_n)
    g_naright = gain_with_na(left_n, right_n + na[:, :, None, :])
    if mono is not None:

        def child_val(s):  # Newton child value wy/wh, clamped to node bounds
            v = jnp.where(s[..., 2] > 0, s[..., 1] / jnp.maximum(s[..., 2], 1e-30), 0.0)
            return jnp.clip(v, node_lo[:, None, None], node_hi[:, None, None])

        m = mono[None, :, None]
        na_b = na[:, :, None, :]
        ok_nl = (m == 0) | (m * (child_val(right_n) - child_val(left_n + na_b)) >= 0)
        ok_nr = (m == 0) | (m * (child_val(right_n + na_b) - child_val(left_n)) >= 0)
        g_naleft = jnp.where(ok_nl, g_naleft, _NEG)
        g_naright = jnp.where(ok_nr, g_naright, _NEG)
    g_num = jnp.maximum(g_naleft, g_naright)  # (N, C, B-2)
    num_best_t = jnp.argmax(g_num, axis=2)  # (N, C)
    num_best_gain = jnp.take_along_axis(g_num, num_best_t[:, :, None], 2).squeeze(2)
    num_na_left = (
        jnp.take_along_axis(g_naleft, num_best_t[:, :, None], 2).squeeze(2)
        >= jnp.take_along_axis(g_naright, num_best_t[:, :, None], 2).squeeze(2)
    )

    if cat_cols:
        # ---- categorical: prefix split in mean-sorted bin order, on the
        # categorical column subset only ----
        cat_idx = jnp.asarray(np.asarray(cat_cols, np.int32))
        Cc = len(cat_cols)
        data_c = data[:, cat_idx, :, :]  # (N, Cc, B-1, 3)
        na_c = na[:, cat_idx, :]
        w_bins = data_c[..., 0]
        mean = jnp.where(w_bins > 0, data_c[..., 1] / jnp.maximum(w_bins, 1e-30), jnp.inf)
        order = jnp.argsort(mean, axis=2)  # (N, Cc, B-1) empty bins (inf) last
        sdata = jnp.take_along_axis(data_c, order[..., None], axis=2)
        scum = jnp.cumsum(sdata, axis=2)
        s_tot = scum[:, :, -1:, :]
        s_left = scum[:, :, :-1, :]
        s_right = s_tot - s_left
        gc_naleft = gain_with_na(s_left + na_c[:, :, None, :], s_right)
        gc_naright = gain_with_na(s_left, s_right + na_c[:, :, None, :])
        g_cat = jnp.maximum(gc_naleft, gc_naright)
        cat_best_k = jnp.argmax(g_cat, axis=2)  # (N, Cc) prefix length-1
        cat_best_gain_c = jnp.take_along_axis(g_cat, cat_best_k[:, :, None], 2).squeeze(2)
        cat_na_left_c = (
            jnp.take_along_axis(gc_naleft, cat_best_k[:, :, None], 2).squeeze(2)
            >= jnp.take_along_axis(gc_naright, cat_best_k[:, :, None], 2).squeeze(2)
        )
        # scatter subset results back to full column axis
        cat_best_gain = jnp.full((N, C), _NEG, hist.dtype).at[:, cat_idx].set(cat_best_gain_c)
        col_gain = jnp.where(is_cat[None, :], cat_best_gain, num_best_gain)
    else:
        col_gain = num_best_gain

    # ---- choose best column per node ----
    col_gain = jnp.where(col_mask > 0, col_gain, _NEG)
    best_col = jnp.argmax(col_gain, axis=1)  # (N,)
    best_gain = jnp.take_along_axis(col_gain, best_col[:, None], 1).squeeze(1)

    take = lambda a: jnp.take_along_axis(a, best_col[:, None], 1).squeeze(1)
    bc_t = take(num_best_t)
    # split_bin: numeric → left iff 1 <= bin <= t+1
    split_bin = bc_t + 1

    if cat_cols:
        # position of each full col in the cat subset (0 for non-cat; gated
        # by bc_is_cat downstream so the garbage value is never used)
        pos_of_col = np.zeros(C, np.int32)
        pos_of_col[list(cat_cols)] = np.arange(Cc, dtype=np.int32)
        bc_is_cat = is_cat[best_col]
        best_pos = jnp.asarray(pos_of_col)[best_col]  # (N,)
        take_c = lambda a: jnp.take_along_axis(a, best_pos[:, None], 1).squeeze(1)
        bc_k = take_c(cat_best_k)
        bc_na_left = jnp.where(bc_is_cat, take_c(cat_na_left_c), take(num_na_left))
        # cat membership mask over ALL B bins (bin 0 NA handled separately):
        # rank of data-bin j (order position) <= k  → left
        ranks = jnp.argsort(order, axis=2)  # (N, Cc, B-1) rank of each data bin
        idx = jnp.broadcast_to(best_pos[:, None, None], (N, 1, ranks.shape[2]))
        best_ranks = jnp.take_along_axis(ranks, idx, axis=1).squeeze(1)  # (N, B-1)
        cat_left = best_ranks <= bc_k[:, None]  # (N, B-1) for data bins 1..B-1
        cat_mask = jnp.concatenate(
            [bc_na_left[:, None], cat_left], axis=1
        )  # (N, B): bin0 = NA direction
        # canonical form: numeric winners record an all-False mask (every
        # consumer gates on is_cat, and a garbage mask would differ between
        # the replicated and column-sharded scans)
        cat_mask = jnp.where(bc_is_cat[:, None], cat_mask, False)
    else:
        bc_is_cat = jnp.zeros(N, bool)
        bc_na_left = take(num_na_left)
        cat_mask = jnp.zeros((N, B), bool)

    node_w = node_totals[:, 0]
    node_wy = node_totals[:, 1]
    node_wh = node_totals[:, 2]
    ok_split = best_gain >= min_split_improvement

    # Chosen-split child stats {w, wy, wh} (N, 3) for the left/right
    # children, NA direction folded in. These feed (a) sibling subtraction —
    # next level builds only the smaller child's histogram and derives the
    # other as parent − built (the DHistogram/LightGBM work-halving trick) —
    # and (b) the final level's leaf values, which then need no histogram
    # pass at all.
    na_best = jnp.take_along_axis(na, best_col[:, None, None], 1).squeeze(1)  # (N,3)
    gidx = best_col[:, None, None, None]
    gnum = lambda arr: jnp.take_along_axis(
        jnp.take_along_axis(arr, gidx, 1).squeeze(1), bc_t[:, None, None], 1
    ).squeeze(1)  # (N, 3)
    Lraw, Rraw = gnum(left_n), gnum(right_n)
    if cat_cols:
        gidx_c = best_pos[:, None, None, None]
        gcat = lambda arr: jnp.take_along_axis(
            jnp.take_along_axis(arr, gidx_c, 1).squeeze(1), bc_k[:, None, None], 1
        ).squeeze(1)
        Lraw = jnp.where(bc_is_cat[:, None], gcat(s_left), Lraw)
        Rraw = jnp.where(bc_is_cat[:, None], gcat(s_right), Rraw)
    nl = bc_na_left[:, None]
    Lst = Lraw + jnp.where(nl, na_best, 0.0)
    Rst = Rraw + jnp.where(~nl, na_best, 0.0)

    out = {
        "Lst": Lst,
        "Rst": Rst,
        "gain": best_gain,
        "ok": ok_split,
        "col": best_col,
        "is_cat": bc_is_cat,
        "split_bin": split_bin,
        "na_left": bc_na_left,
        "cat_mask": cat_mask,
        "node_w": node_w,
        "node_wy": node_wy,
        "node_wh": node_wh,
    }
    if mono is not None:
        # chosen split's clamped child values -> mid for bound propagation
        # (categorical winners carry mono_col 0, so their mid is never used)
        vL = jnp.clip(
            jnp.where(Lst[:, 2] > 0, Lst[:, 1] / jnp.maximum(Lst[:, 2], 1e-30), 0.0),
            node_lo, node_hi,
        )
        vR = jnp.clip(
            jnp.where(Rst[:, 2] > 0, Rst[:, 1] / jnp.maximum(Rst[:, 2], 1e-30), 0.0),
            node_lo, node_hi,
        )
        out["mid"] = 0.5 * (vL + vR)
        out["mono_col"] = jnp.where(bc_is_cat, 0, mono[best_col])
    return out


# ---------------------------------------------------------------------------
# column-sharded split pipeline (H2O3_TPU_SPLIT_SHARD): the histogram
# reduction ends in a reduce-scatter over contiguous column blocks
# (histogram_in_jit col_sharded=True — each device keeps only its C/P
# columns, 1/P of the all-reduce's replication volume), the split scan runs
# on the local block only (FLOPs / P), and a tiny all-gather of per-block
# winner tuples feeds a merge that reproduces jnp.argmax's
# lowest-global-index tie-breaking bit-exactly.


def _split_shard_on() -> bool:
    """Single policy for the sharded split pipeline: on by default whenever
    the mesh deals >1 COLUMN block (``H2O3_TPU_SPLIT_SHARD=0`` restores the
    replicated scan). On the legacy 1-D mesh that is any >1-device mesh; on
    a 2-D rows×cols mesh the block count is the ``cols`` axis — an R×1 mesh
    has nothing to shard columns over and scans replicated (its histogram
    still reduces over the rows axis)."""
    from h2o3_tpu import config
    from h2o3_tpu.parallel.mesh import n_col_shards

    return config.get_bool("H2O3_TPU_SPLIT_SHARD") and n_col_shards() > 1


def _split_fuse_on() -> bool:
    """Policy knob for the fused Pallas histogram→split pipeline
    (``H2O3_TPU_SPLIT_FUSE``): 'auto' (default) = on for non-CPU backends
    (the Pallas kernels run native there); '1' forces it anywhere (CPU runs
    the kernels in the Pallas interpreter — the CI/parity lane, slower than
    the scatter+XLA path and never a default); '0' = the unfused path."""
    from h2o3_tpu import config

    v = config.get("H2O3_TPU_SPLIT_FUSE")
    if v in ("auto", ""):
        return jax.default_backend() != "cpu"
    return v not in ("0", "false", "False")


def _split_fuse_active(cat_cols: tuple, split_shard: bool,
                       uplift: bool = False) -> bool:
    """Whether a program being built NOW should trace the fused pipeline.

    The post-ISSUE-15 fallback matrix (docs/MIGRATION.md): monotone builds
    fuse (the per-bin feasibility mask runs inside the kernel grid step —
    ops/split_pallas._split_kernel_mono) and categorical columns on a
    column-sharded mesh fuse too (every block runs the mean-sort branch on
    a BLOCK-LOCAL dense gather, selecting per column — the dense sharded
    scan's own scheme, now fed from the blocked tiles). ISSUE 16 closed
    uplift too: its 4-lane scan runs through the whole-tree fused uplift
    program (models/uplift._uplift_tree_program), so ``uplift=True`` here
    is only reached from the LEGACY per-level uplift loop
    (H2O3_TPU_WHOLE_TREE=0 / depth cap); a structural fallback while the
    gate is ON tallies ``tree_fused_fallbacks_total{reason}``."""
    if not _split_fuse_on():
        return False
    if uplift:
        _FUSED_FALLBACKS.inc(reason="uplift")
        return False
    return True


def _kernel_key() -> tuple:
    """Program-cache component for everything that changes the TRACED
    kernels without changing any call-site argument: the fuse toggle, the
    Pallas tile triple, and the local-histogram override. Without these a
    cached program compiled under one setting would silently serve another
    (the --fused-ab sweep toggles H2O3_TPU_SPLIT_FUSE in-process)."""
    from h2o3_tpu import config
    from h2o3_tpu.ops.hist_pallas import _tiles

    # the RAW spec rides along because 'auto' (the tile autotuner) resolves
    # shape-dependent tiles inside the trace — _tiles() alone could not
    # distinguish 'auto' from the '' defaults; HIST_I16 changes the traced
    # local accumulation (ops/histogram._maybe_i16)
    return (_split_fuse_on(), _tiles(),
            config.get("H2O3_TPU_PALLAS_TILES").strip(),
            config.get("H2O3_TPU_HIST"),
            config.get_bool("H2O3_TPU_HIST_I16"))


def _split_scan_sharded_fused(
    blk, layout, is_cat, col_mask, min_rows, min_split_improvement,
    any_cat: bool = False, mono=None, node_lo=None, node_hi=None, mesh=None,
):
    """Column-sharded split scan on a BLOCKED histogram: each device runs
    the Pallas split kernel (ops/split_pallas.py) on its own 1/P tile range
    in VMEM — the full histogram never exists on any device — and the
    winner merge is byte-identical to the dense sharded path's: per-block
    winners all_gather (O(N·P) scalars), argmax over blocks picks the
    lowest block, blocks are contiguous ascending column ranges, and every
    block's gains are computed against GLOBAL column 0's node totals.

    ``any_cat`` (ISSUE 15) closes the cat+sharded fallback: block
    membership of a categorical column is dynamic (the traced body is
    one-per-mesh), so — exactly like the dense sharded scan — every block
    runs the mean-sort categorical branch on ALL its local columns via a
    BLOCK-LOCAL dense gather (``blocked_cols_dense`` over the local tiles,
    O(N·(C/P)·B·S) HBM, never the full histogram) and selects per column by
    the sliced ``is_cat``; the winner tuple then carries the (N, B)
    membership mask. Numeric columns stay on the kernel throughout.

    ``mono``/``node_lo``/``node_hi`` thread the monotone-constrained kernel
    variant per block (the direction lane slices like the column mask) and
    the winner tuple gains ``mid``/``mono_col`` for bound propagation."""
    import jax.tree_util as jtu

    from h2o3_tpu.ops.histogram import record_collective
    from h2o3_tpu.ops.hist_pallas import blocked_node_totals
    from h2o3_tpu.ops.split_pallas import fused_split_scan
    from h2o3_tpu.parallel.mesh import (
        col_axis_name, get_mesh, n_col_shards, shard_map,
    )
    from jax.sharding import PartitionSpec as P

    mesh = mesh or get_mesh()
    n_dev = n_col_shards(mesh)
    cax = col_axis_name(mesh)
    L = layout
    lloc = L.local(n_dev)
    N, B, S = L.n_nodes, L.n_bins, L.ns
    C = is_cat.shape[0]
    if L.cpad > C:  # layout padding columns: masked, can never win
        is_cat = jnp.pad(is_cat, (0, L.cpad - C))
        col_mask = jnp.pad(col_mask, ((0, 0), (0, L.cpad - C)))
        if mono is not None:
            mono = jnp.pad(mono, (0, L.cpad - C))
    # the dense sharded scan's scheme: every local column routes through
    # the categorical branch, per-column selection by is_cat
    local_cats = tuple(range(lloc.cpad)) if any_cat else ()

    if n_dev > 1:
        per_dev = N * (4 + 4 + 4 + 1 + 1 + 12 + 12 + 4 * S)
        if any_cat:
            per_dev += N * B
        if mono is not None:
            per_dev += N * 8
        record_collective("winner_gather", n_dev * per_dev)

    def body(blk_loc, cm, ic, mono_g, lo, hi):
        d = jax.lax.axis_index(cax)
        col0 = (d * lloc.cpad).astype(jnp.int32)
        # node totals from GLOBAL column 0 = block 0's local column 0
        tot_loc = blocked_node_totals(blk_loc, lloc)
        tot0 = jax.lax.all_gather(tot_loc, cax)[0]
        cm_blk = jax.lax.dynamic_slice_in_dim(cm, col0, lloc.cpad, axis=1)
        ic_blk = jax.lax.dynamic_slice_in_dim(ic, col0, lloc.cpad, axis=0)
        mono_blk = (
            None if mono_g is None
            else jax.lax.dynamic_slice_in_dim(mono_g, col0, lloc.cpad, axis=0)
        )
        sp = fused_split_scan(
            blk_loc, lloc, ic_blk, cm_blk, min_rows, min_split_improvement,
            local_cats, node_totals=tot0,
            mono=mono_blk, node_lo=lo, node_hi=hi,
        )
        win = {
            "gain": sp["gain"],
            "col": col0 + sp["col"].astype(jnp.int32),
            "split_bin": sp["split_bin"],
            "na_left": sp["na_left"],
            "is_cat": sp["is_cat"],
            "Lst": sp["Lst"],
            "Rst": sp["Rst"],
        }
        if any_cat:
            win["cat_mask"] = sp["cat_mask"]
        if mono_g is not None:
            win["mid"] = sp["mid"]
            win["mono_col"] = sp["mono_col"]
        g = jtu.tree_map(lambda a: jax.lax.all_gather(a, cax), win)
        # identical merge to the dense sharded path: argmax over the block
        # axis — first max wins, i.e. the LOWEST block
        bb = jnp.argmax(g["gain"], axis=0)  # (N,)

        def pick(a):
            idx = bb.reshape((1,) + bb.shape + (1,) * (a.ndim - 2))
            return jnp.take_along_axis(a, idx, axis=0).squeeze(0)

        out = {k: pick(v) for k, v in g.items()}
        out["ok"] = out["gain"] >= min_split_improvement
        out["node_w"] = tot0[:, 0]
        out["node_wy"] = tot0[:, 1]
        out["node_wh"] = tot0[:, 2]
        if not any_cat:
            out["cat_mask"] = jnp.zeros((N, B), bool)
        return out

    if mono is None:
        return shard_map(
            lambda b, cm, ic: body(b, cm, ic, None, None, None),
            mesh=mesh,
            in_specs=(P(cax), P(), P()),
            out_specs=P(),
            check_vma=False,
        )(blk, col_mask, is_cat)
    return shard_map(
        body,
        mesh=mesh,
        in_specs=(P(cax), P(), P(), P(), P(), P()),
        out_specs=P(),
        check_vma=False,
    )(blk, col_mask, is_cat, mono, node_lo, node_hi)


def _split_scan_sharded(
    hist, is_cat, col_mask, min_rows, min_split_improvement,
    any_cat: bool, mono=None, node_lo=None, node_hi=None, mesh=None,
):
    """Blockwise :func:`_split_scan` over a column-sharded histogram, merged
    bit-exactly against the replicated scan's ``jnp.argmax``.

    ``hist`` is (N, Cp, B, S) with the column axis sharded over the mesh
    (``histogram_in_jit(..., col_sharded=True)``'s layout; Cp = C padded to
    a multiple of the shard count). Each device scans ONLY its contiguous
    block of Cp/P columns, then every device gathers the per-block winner
    tuples — O(N·P) scalars, not the O(C·N·B·S) histogram — and merges them
    identically (replicated output).

    Bit-exactness, piece by piece:
    - each block's histogram cells equal the replicated reduction's
      (reduce-scatter and all-reduce combine shards in the same order);
    - every block computes gains against GLOBAL column 0's node totals
      (gathered once, (N, S)), because a different column's bin partition
      can change the float total in the last bits (``node_totals`` in
      :func:`_split_scan`) — so per-(node, col) gains are the identical
      floats the replicated scan compares;
    - the block-local argmax picks the lowest LOCAL index among ties, the
      merge's argmax over the gathered (P, N) gains picks the lowest BLOCK,
      and blocks are contiguous ascending column ranges — lexicographic
      (block, local) is exactly lowest-global-index.

    When the frame has categorical columns (``any_cat``), every block runs
    the mean-sort categorical branch on ALL its local columns (block
    membership is dynamic, the traced program is one-per-mesh) and selects
    per-column by the sliced ``is_cat`` — same per-column floats, so parity
    holds for categorical winners too; the winner tuple then carries the
    (N, B) membership mask, making the gather O(N·B·P) instead of O(N·P).
    """
    import jax.tree_util as jtu

    from h2o3_tpu.ops.histogram import record_collective
    from h2o3_tpu.parallel.mesh import (
        col_axis_name, get_mesh, n_col_shards, shard_map,
    )
    from jax.sharding import PartitionSpec as P

    mesh = mesh or get_mesh()
    n_dev = n_col_shards(mesh)
    cax = col_axis_name(mesh)
    N, Cp, B, S = hist.shape
    Cb = Cp // n_dev
    C = is_cat.shape[0]
    if Cp > C:  # histogram divisibility padding: zero hists, masked columns
        is_cat = jnp.pad(is_cat, (0, Cp - C))
        col_mask = jnp.pad(col_mask, ((0, 0), (0, Cp - C)))
        if mono is not None:
            mono = jnp.pad(mono, (0, Cp - C))
    local_cats = tuple(range(Cb)) if any_cat else ()

    # winner-gather payload per device (trace-time byte tally): the scalar
    # tuple + the block-0 node-totals broadcast, + the membership mask when
    # categorical columns exist
    if n_dev > 1:
        per_dev = N * (4 + 4 + 4 + 1 + 1 + 12 + 12 + 4 * S)
        if any_cat:
            per_dev += N * B
        if mono is not None:
            per_dev += N * 8
        record_collective("winner_gather", n_dev * per_dev)

    def body(h_blk, cm, ic, mono_g, lo, hi):
        d = jax.lax.axis_index(cax)
        col0 = (d * Cb).astype(jnp.int32)
        # node totals from GLOBAL column 0 = block 0's local column 0
        tot_loc = h_blk[:, 0, :, :].sum(axis=1)  # (N, S)
        tot0 = jax.lax.all_gather(tot_loc, cax)[0]
        cm_blk = jax.lax.dynamic_slice_in_dim(cm, col0, Cb, axis=1)
        ic_blk = jax.lax.dynamic_slice_in_dim(ic, col0, Cb, axis=0)
        mono_blk = (
            None if mono_g is None
            else jax.lax.dynamic_slice_in_dim(mono_g, col0, Cb, axis=0)
        )
        sp = _split_scan(
            h_blk, ic_blk, cm_blk, min_rows, min_split_improvement,
            local_cats, mono=mono_blk, node_lo=lo, node_hi=hi,
            node_totals=tot0,
        )
        win = {
            "gain": sp["gain"],
            "col": col0 + sp["col"].astype(jnp.int32),
            "split_bin": sp["split_bin"],
            "na_left": sp["na_left"],
            "is_cat": sp["is_cat"],
            "Lst": sp["Lst"],
            "Rst": sp["Rst"],
        }
        if any_cat:
            win["cat_mask"] = sp["cat_mask"]
        if mono_g is not None:
            win["mid"] = sp["mid"]
            win["mono_col"] = sp["mono_col"]
        g = jtu.tree_map(lambda a: jax.lax.all_gather(a, cax), win)
        # the merge, computed identically on every device: argmax over the
        # gathered block axis — first max wins, i.e. the LOWEST block
        bb = jnp.argmax(g["gain"], axis=0)  # (N,)

        def pick(a):
            idx = bb.reshape((1,) + bb.shape + (1,) * (a.ndim - 2))
            return jnp.take_along_axis(a, idx, axis=0).squeeze(0)

        out = {k: pick(v) for k, v in g.items()}
        out["ok"] = out["gain"] >= min_split_improvement
        out["node_w"] = tot0[:, 0]
        out["node_wy"] = tot0[:, 1]
        out["node_wh"] = tot0[:, 2]
        if not any_cat:
            out["cat_mask"] = jnp.zeros((N, B), bool)
        return out

    if mono is None:
        return shard_map(
            lambda h, cm, ic: body(h, cm, ic, None, None, None),
            mesh=mesh,
            in_specs=(P(None, cax), P(), P()),
            out_specs=P(),
            check_vma=False,
        )(hist, col_mask, is_cat)
    return shard_map(
        body,
        mesh=mesh,
        in_specs=(P(None, cax), P(), P(), P(), P(), P()),
        out_specs=P(),
        check_vma=False,
    )(hist, col_mask, is_cat, mono, node_lo, node_hi)


# ---------------------------------------------------------------------------
# partition update (DecidedNode re-labeling + leaf retirement)
# — also the prediction-replay op, so it keeps its own jit wrapper.


@jax.jit
def _partition_update(
    bins_u8, nid, preds, split_col, split_bin, is_cat, cat_mask, na_left, leaf_now, leaf_val, child_base
):
    active = nid >= 0
    node = jnp.where(active, nid, 0)
    col = split_col[node]
    b = jnp.take_along_axis(bins_u8, col[:, None].astype(jnp.int32), axis=1).squeeze(1).astype(jnp.int32)
    go_left = jnp.where(
        b == 0,
        na_left[node],
        jnp.where(is_cat[node], cat_mask[node, b], b <= split_bin[node]),
    )
    child = child_base[node] + jnp.where(go_left, 0, 1)
    retired = leaf_now[node]
    new_nid = jnp.where(active, jnp.where(retired, -1, child), -1)
    new_preds = preds + jnp.where(active & retired, leaf_val[node], 0.0)
    return new_nid.astype(jnp.int32), new_preds


# ---------------------------------------------------------------------------
# the fused level step


def _leaf_decide(
    ok, gain, node_w, node_wy, node_wh, split_col, split_bin,
    is_cat_n, cat_mask, na_left, learn_rate, max_abs_leaf, n_pad,
    node_lo=None, node_hi=None, reg_lambda=None, reg_alpha=None,
):
    """Leaf decision + child-id assignment + the replayable record — the
    partition-free head of :func:`_finish_level`, shared with the
    out-of-core streamed driver (:func:`build_trees_streamed`), which runs
    the partition update per row block instead of over one resident array.

    ``reg_lambda``/``reg_alpha`` (XGBoost leaf regularization, traced
    scalars): leaf = soft_threshold(Σwy, α) / (Σwh + λ) — xgboost's
    w* = −soft(G, α)/(H + λ) with our sign convention (wy ≡ −G, wh ≡ H).
    None keeps the unregularized trace byte-identical (the H2O GBM path).
    """
    leaf_now = ~ok
    if reg_lambda is not None:
        num = jnp.sign(node_wy) * jnp.maximum(jnp.abs(node_wy) - reg_alpha, 0.0)
        den = node_wh + reg_lambda
        leaf_val = jnp.where(den > 0, num / jnp.maximum(den, 1e-30), 0.0)
    else:
        leaf_val = jnp.where(node_wh > 0, node_wy / jnp.maximum(node_wh, 1e-30), 0.0)
    if node_lo is not None:
        leaf_val = jnp.clip(leaf_val, node_lo, node_hi)  # monotone bound clamp
    leaf_val = jnp.clip(leaf_val, -max_abs_leaf, max_abs_leaf) * learn_rate
    leaf_val = jnp.where(leaf_now, leaf_val, 0.0).astype(jnp.float32)

    cs = jnp.cumsum(ok.astype(jnp.int32))
    child_base = jnp.where(ok, 2 * (cs - 1), 0).astype(jnp.int32)
    n_split = cs[-1] if n_pad else jnp.int32(0)

    record = {
        "node_w": node_w.astype(jnp.float32),
        "split_col": split_col.astype(jnp.int32),
        "split_bin": split_bin.astype(jnp.int32),
        "is_cat": is_cat_n,
        "cat_mask": cat_mask,
        "na_left": na_left,
        "leaf_now": leaf_now,
        "leaf_val": leaf_val,
        "child_base": child_base,
        "gain": gain,
    }
    return leaf_now, leaf_val, child_base, cs, n_split, record


def _finish_level(
    bins_u8, nid, preds, varimp, ok, gain, node_w, node_wy, node_wh,
    split_col, split_bin, is_cat_n, cat_mask, na_left,
    learn_rate, max_abs_leaf, n_pad, node_lo=None, node_hi=None,
    reg_lambda=None, reg_alpha=None,
):
    """Shared tail of every level: leaf decision, child-id assignment,
    varimp scatter, partition update, and the replayable record.

    ``node_lo``/``node_hi`` (monotone-constraint bound state) clamp leaf
    values when given; None leaves the unconstrained trace byte-identical.
    """
    leaf_now, leaf_val, child_base, cs, n_split, record = _leaf_decide(
        ok, gain, node_w, node_wy, node_wh, split_col, split_bin,
        is_cat_n, cat_mask, na_left, learn_rate, max_abs_leaf, n_pad,
        node_lo=node_lo, node_hi=node_hi,
        reg_lambda=reg_lambda, reg_alpha=reg_alpha,
    )

    varimp = varimp.at[split_col].add(jnp.where(ok, gain, 0.0).astype(varimp.dtype))

    # ph_part: phase tag for tools/profile_fused.py
    with jax.named_scope("ph_part"):
        nid, preds = _partition_update(
            bins_u8, nid, preds, split_col, split_bin, is_cat_n, cat_mask,
            na_left, leaf_now, leaf_val, child_base,
        )
    return nid, preds, varimp, n_split, record, cs


def _child_bounds(ok, child_base, mono_col, mid, node_lo, node_hi,
                  n_pad_next: int):
    """Monotone child-bound propagation: children of a constrained split
    tighten to the parent's ``mid`` on the constrained side (left child at
    ``child_base``, right at ``child_base+1``; leaves drop out-of-bounds).
    Factored out of the per-level mono step so the fused whole-tree
    program, the streamed decide and the per-level loop scatter the SAME
    ops. Returns ``(new_lo, new_hi)`` sized ``n_pad_next``."""
    new_lo = jnp.full(n_pad_next, -jnp.inf, jnp.float32)
    new_hi = jnp.full(n_pad_next, jnp.inf, jnp.float32)
    inc = mono_col > 0
    dec = mono_col < 0
    l_lo = jnp.where(dec, mid, node_lo)
    l_hi = jnp.where(inc, mid, node_hi)
    r_lo = jnp.where(inc, mid, node_lo)
    r_hi = jnp.where(dec, mid, node_hi)
    li = jnp.where(ok, child_base, n_pad_next)  # OOB drop for leaves
    ri = jnp.where(ok, child_base + 1, n_pad_next)
    new_lo = new_lo.at[li].set(l_lo, mode="drop")
    new_lo = new_lo.at[ri].set(r_lo, mode="drop")
    new_hi = new_hi.at[li].set(l_hi, mode="drop")
    new_hi = new_hi.at[ri].set(r_hi, mode="drop")
    return new_lo, new_hi


def _level_core(
    hist, bins_u8, nid, preds, varimp, key, cols_enabled, is_cat,
    min_rows, min_split_improvement, learn_rate, max_abs_leaf, col_sample_rate,
    leaf_reg=None,
    *, n_pad: int, n_pad_next: int, cat_cols: tuple = (),
    n_cols_real: int | None = None, split_shard: bool = False,
    fuse_layout=None, mono=None, node_lo=None, node_hi=None,
    leaf_budget=None,
):
    """Split scan → decisions → partition for one level, given its histogram.

    ``split_shard`` selects the column-sharded scan: ``hist`` then arrives
    column-sharded (and possibly padded past the real column count — the
    sharded scan masks the pad), and the scan+merge reproduces the
    replicated path's decisions bit-exactly (:func:`_split_scan_sharded`).

    ``fuse_layout`` (a ``hist_pallas.HistLayout``) selects the fused Pallas
    pipeline: ``hist`` is then the BLOCKED histogram tensor and the scan
    runs as the VMEM-tile split kernel (``ops/split_pallas.py``) — sharded
    or replicated — emitting the same decision dict.

    ``mono`` ((C,) int, traced) + ``node_lo``/``node_hi`` ((n_pad,)) select
    monotone-constrained split finding on EVERY scan variant (fused or
    dense, sharded or replicated — ISSUE 15 closed the fused gap); the
    return then appends ``(new_lo, new_hi)`` sized ``n_pad_next`` for the
    caller's bound carry.

    ``leaf_budget`` (traced int32 scalar, ISSUE 16 ``grow_policy=lossguide``)
    rations this level's splits by gain rank: only the ``leaf_budget``
    highest-gain candidates split (each split adds one net leaf), and the
    return appends the decremented budget for the caller's carry. The
    ranking argsort is stable, so ties break toward the lower node slot —
    deterministic across backends. A budget ≥ the candidate count leaves
    the level's decisions bit-identical to depth-wise growth.

    Returns ``(nid, preds, varimp, n_split, record, pair_info)``.
    ``pair_info`` carries, per next-level child PAIR slot (``n_pad_next//2``
    slots; pair *i* holds children ``2i``/``2i+1``), everything sibling
    subtraction at the next level needs: ``parent_idx`` (which of this
    level's nodes split into that pair), ``valid`` (the slot is a real
    split), ``build_left`` (the lighter child — the one whose histogram is
    worth building), and the chosen split's exact left/right child stats
    ``Lst``/``Rst`` (so the final level derives leaf values with no
    histogram at all).

    Empty/padding nodes need no masking anywhere: their histograms are all
    zero, so every candidate split fails the min_rows check and they retire
    as zero-valued leaves that no row is assigned to.
    """
    C = bins_u8.shape[1]
    # per-(node,col) sampling mask (H2O col_sample_rate per split).
    # Fallback when a node draws no columns: use all (rare; H2O instead
    # redraws one uniformly — indistinguishable in expectation at our
    # histogram granularity). The draw runs at the REAL column count
    # (n_cols_real) so shape-bucketed column padding cannot perturb which
    # columns a node samples — bucketed builds stay bit-identical.
    Cr = n_cols_real or C
    col_mask = jnp.broadcast_to(cols_enabled[None, :], (n_pad, C))
    keep = jax.random.uniform(key, (n_pad, Cr)) < col_sample_rate
    keep = jnp.where(keep.any(axis=1, keepdims=True), keep, True)
    if Cr < C:
        keep = jnp.pad(keep, ((0, 0), (0, C - Cr)))
    col_mask = col_mask * keep
    # ph_split: phase tag for tools/profile_fused.py
    with jax.named_scope("ph_split"):
        if fuse_layout is not None and split_shard:
            sp = _split_scan_sharded_fused(
                hist, fuse_layout, is_cat, col_mask, min_rows,
                min_split_improvement, any_cat=bool(cat_cols),
                mono=mono, node_lo=node_lo, node_hi=node_hi,
            )
        elif fuse_layout is not None:
            from h2o3_tpu.ops.split_pallas import fused_split_scan

            sp = fused_split_scan(
                hist, fuse_layout, is_cat, col_mask, min_rows,
                min_split_improvement, cat_cols,
                mono=mono, node_lo=node_lo, node_hi=node_hi,
            )
        elif split_shard:
            sp = _split_scan_sharded(
                hist, is_cat, col_mask, min_rows, min_split_improvement,
                any_cat=bool(cat_cols),
                mono=mono, node_lo=node_lo, node_hi=node_hi,
            )
        else:
            sp = _split_scan(
                hist, is_cat, col_mask, min_rows, min_split_improvement,
                cat_cols, mono=mono, node_lo=node_lo, node_hi=node_hi,
            )
    ok = sp["ok"]
    # frontier cap: children must fit n_pad_next; later nodes go leaf
    fits = 2 * jnp.cumsum(ok.astype(jnp.int32)) <= n_pad_next
    ok = ok & fits
    new_budget = None
    if leaf_budget is not None:
        # loss-guide ration: keep only the budget's worth of highest-gain
        # candidates (stable argsort — ties go to the lower node slot)
        order = jnp.argsort(jnp.where(ok, -sp["gain"], jnp.inf))
        rank = jnp.zeros(n_pad, jnp.int32).at[order].set(
            jnp.arange(n_pad, dtype=jnp.int32)
        )
        ok = ok & (rank < leaf_budget)
        new_budget = (leaf_budget - ok.sum()).astype(jnp.int32)
    gain = jnp.where(ok, jnp.maximum(sp["gain"], 0.0), 0.0)

    rl, ra = (None, None) if leaf_reg is None else leaf_reg
    nid, preds, varimp, n_split, record, cs = _finish_level(
        bins_u8, nid, preds, varimp, ok, gain,
        sp["node_w"], sp["node_wy"], sp["node_wh"],
        sp["col"], sp["split_bin"], sp["is_cat"], sp["cat_mask"], sp["na_left"],
        learn_rate, max_abs_leaf, n_pad, node_lo=node_lo, node_hi=node_hi,
        reg_lambda=rl, reg_alpha=ra,
    )

    half = n_pad_next // 2
    pidx = jnp.where(ok, cs - 1, half)  # OOB drop for non-splitting nodes
    scat = lambda init, vals: init.at[pidx].set(vals, mode="drop")
    pair_info = {
        "valid": scat(jnp.zeros(half, bool), jnp.ones(n_pad, bool)),
        "parent_idx": scat(
            jnp.zeros(half, jnp.int32), jnp.arange(n_pad, dtype=jnp.int32)
        ),
        "build_left": scat(jnp.zeros(half, bool), sp["Lst"][:, 0] <= sp["Rst"][:, 0]),
        "Lst": scat(jnp.zeros((half, 3), sp["Lst"].dtype), sp["Lst"]),
        "Rst": scat(jnp.zeros((half, 3), sp["Rst"].dtype), sp["Rst"]),
    }
    extra = ()
    if mono is not None:
        new_lo, new_hi = _child_bounds(
            ok, record["child_base"], sp["mono_col"], sp["mid"],
            node_lo, node_hi, n_pad_next,
        )
        extra = (new_lo, new_hi)
    if leaf_budget is not None:
        extra = extra + (new_budget,)
    return (nid, preds, varimp, n_split, record, pair_info) + extra


def _force_leaf_from_stats(
    bins_u8, nid, preds, varimp, node_w, node_wy, node_wh,
    learn_rate, max_abs_leaf, n_pad, n_bins, leaf_reg=None,
    node_lo=None, node_hi=None,
):
    """Terminal level: every active node becomes a leaf (no split scan).
    ``node_lo``/``node_hi`` clamp the leaf values on monotone builds."""
    ok = jnp.zeros(n_pad, bool)
    zi = jnp.zeros(n_pad, jnp.int32)
    rl, ra = (None, None) if leaf_reg is None else leaf_reg
    nid, preds, varimp, n_split, record, _ = _finish_level(
        bins_u8, nid, preds, varimp, ok, jnp.zeros(n_pad, jnp.float32),
        node_w, node_wy, node_wh, zi, zi, jnp.zeros(n_pad, bool),
        jnp.zeros((n_pad, n_bins), bool), jnp.zeros(n_pad, bool),
        learn_rate, max_abs_leaf, n_pad, node_lo=node_lo, node_hi=node_hi,
        reg_lambda=rl, reg_alpha=ra,
    )
    return nid, preds, varimp, n_split, record


def _level_step_fn(
    bins_u8, nid, preds, varimp, w, wy, wh, key, cols_enabled, is_cat,
    min_rows, min_split_improvement, learn_rate, max_abs_leaf, col_sample_rate,
    leaf_reg=None,
    *, n_pad: int, n_pad_next: int, n_bins: int, force_leaf: bool,
    cat_cols: tuple = (), split_shard: bool = False,
    split_fuse: bool = False,
):
    """One whole tree level on device (histogram built from scratch).

    The per-level dispatch form: used by the CPU loop and as the building
    block the fused/subtraction path (:func:`_fused_levels`) specializes.
    Returns (nid, preds, varimp, n_split, record).
    """
    from h2o3_tpu.ops.histogram import histogram_in_jit

    hist = histogram_in_jit(
        bins_u8, nid, (w, wy, wh), n_pad, n_bins, col_sharded=split_shard,
        fused=split_fuse,
    )
    lay = None
    if split_fuse:
        hist, lay = hist

    if force_leaf:
        if split_fuse:
            from h2o3_tpu.ops.hist_pallas import blocked_node_totals

            tot = blocked_node_totals(hist, lay)  # global col 0 ≡ any col
        else:
            tot = hist[:, 0, :, :].sum(axis=1)  # (n_pad, 3); col 0 ≡ any col
        return _force_leaf_from_stats(
            bins_u8, nid, preds, varimp, tot[:, 0], tot[:, 1], tot[:, 2],
            learn_rate, max_abs_leaf, n_pad, n_bins, leaf_reg,
        )
    out = _level_core(
        hist, bins_u8, nid, preds, varimp, key, cols_enabled, is_cat,
        min_rows, min_split_improvement, learn_rate, max_abs_leaf,
        col_sample_rate, leaf_reg, n_pad=n_pad, n_pad_next=n_pad_next,
        cat_cols=cat_cols, split_shard=split_shard, fuse_layout=lay,
    )
    return out[:5]


# -- per-level bin adaptivity (DHistogram's per-level re-binning analog) ----
# Upstream re-derives histogram ranges per level (nbins_top_level halving to
# nbins); here deep levels coarsen the static quantile bins instead: the
# dense one-hot histogram's cost is ∝ bin count, and nodes deep in the tree
# hold few rows, where 63 quantile bins split as well as 254. Recorded
# splits are converted back to FULL-resolution thresholds (a coarse prefix
# split is exactly a full-res prefix split), so partition replay, MOJO
# export and the native scorer are untouched. Numeric-only: coarsening ENUM
# bins would merge arbitrary categories; frames with categorical features
# keep full bins at every level.

_BIN_ADAPT_START = 3  # first depth allowed to coarsen
_BIN_ADAPT_MIN = 63  # never fewer data bins than this


def _bin_shifts(max_depth: int, n_bins: int, cat_cols: tuple) -> list[int]:
    from h2o3_tpu import config

    if cat_cols or not config.get_bool("H2O3_TPU_BIN_ADAPT"):
        return [0] * (max_depth + 1)
    D = n_bins - 1  # data bins (bin 0 = NA)
    out = []
    for d in range(max_depth + 1):
        s = max(d - (_BIN_ADAPT_START - 1), 0)
        while s > 0 and (D >> s) < _BIN_ADAPT_MIN:
            s -= 1
        out.append(s)
    return out


def _coarse_nbins(n_bins: int, s: int) -> int:
    return (-(-(n_bins - 1) // (1 << s))) + 1 if s else n_bins


def _coarsen_bins(bins_u8, s: int):
    if s == 0:
        return bins_u8
    b = bins_u8.astype(jnp.int32)
    return jnp.where(b == 0, 0, ((b - 1) >> s) + 1).astype(jnp.uint8)


def _coarsen_hist(hist, ds: int):
    """Sum adjacent data-bin groups of 2**ds (NA bin passes through)."""
    if ds == 0:
        return hist
    N, C, _, S = hist.shape
    na = hist[:, :, :1, :]
    data = hist[:, :, 1:, :]
    D = data.shape[2]
    Dc = -(-D // (1 << ds))
    pad = Dc * (1 << ds) - D
    if pad:
        data = jnp.pad(data, ((0, 0), (0, 0), (0, pad), (0, 0)))
    data = data.reshape(N, C, Dc, 1 << ds, S).sum(3)
    return jnp.concatenate([na, data], axis=2)


def _sat_region(max_depth: int, node_cap: int, shifts: list[int]) -> tuple:
    """(start, count) of the node_cap-SATURATED level run rolled into a
    ``lax.while_loop``: levels where the frontier is pinned at ``node_cap``
    (so every iteration has identical shapes) and the bin-coarsening shift is
    constant from the preceding level on (so the parent-histogram carry needs
    no per-iteration re-coarsening). Unrolling those levels instead would
    compile O(depth) copies of the most expensive level body — the while_loop
    form compiles ONE body and early-exits on device the moment a level
    produces no splits (the deep-DRF regime where most levels are dead)."""
    for d in range(1, max_depth):
        if (
            min(1 << d, node_cap) == node_cap
            and len(set(shifts[d - 1 : max_depth])) == 1
        ):
            if max_depth - d >= 2:
                return d, max_depth - d
            break
    return None, 0


def _fused_levels(
    bins_u8, preds, varimp, w, wy, wh, tkey, cols_enabled, is_cat,
    min_rows, min_split_improvement, learn_rate, max_abs_leaf, col_sample_rate,
    leaf_reg=None,
    *, max_depth: int, n_bins: int, node_cap: int, cat_cols: tuple,
    subtract: bool = True, n_cols_real: int | None = None,
    split_shard: bool = False, split_fuse: bool = False, mono=None,
    max_leaves: int = 0, efb=None, bins_b=None,
):
    """All levels of one tree, traced into a single program, with the two
    histogram work reductions the reference's hot loop embodies
    (``DHistogram``'s build-smaller-child + derive-sibling, SURVEY §2.2):

    - levels 1..D-1 build histograms only for the LIGHTER child of each
      split pair (``n_pad//2`` node slots — the dense one-hot histogram's
      cost is ∝ node count); the heavier sibling is ``parent − built``.
      Building the lighter child keeps the subtraction cancellation error
      small relative to the surviving (heavier) histogram.
    - the terminal level needs NO histogram: every node's {w, wy, wh} totals
      are exactly its parent's chosen-split child stats, recorded by
      :func:`_level_core`.

    At depth 6 that is 1+1+2+4+8+16+0 = 32 node-histogram units vs 127 for
    the direct scheme — ~4× fewer MXU FLOPs in the phase that dominates
    tree time. ``subtract=False`` recovers the direct scheme (A/B testing,
    ``H2O3_TPU_HIST_SUBTRACT=0``).

    Level structure (one compiled program, zero host round-trips):
    frontier-GROWTH levels (node count 1, 2, 4, … < node_cap) unroll — each
    has its own shapes; the node_cap-SATURATED run rolls into a
    ``lax.while_loop`` whose predicate early-exits on device once a level
    splits nothing (see :func:`_sat_region`); the terminal level force-leafs.
    Skipped (post-exit) levels keep their pre-initialized placeholder records
    — all-leaf, zero-valued, reachable by no row — so replay, export and the
    level masks need no notion of "how deep did this tree actually go".

    ``mono`` ((Cp,) int, traced) threads monotone constraints through every
    level IN the fused program (ISSUE 15): per-node ``[lo, hi]`` bound
    state rides the level-to-level carry (including the saturated
    while_loop's), each level's scan masks infeasible candidates inside
    the kernel, and both force-leaf paths clamp their leaf values.

    ``max_leaves`` > 0 (ISSUE 16 ``grow_policy=lossguide``) threads an
    int32 remaining-leaf budget through the same carry: each level rations
    its splits by gain rank (:func:`_level_core`) and decrements the
    budget, so the finished tree has at most ``max_leaves`` leaves.

    ``efb``/``bins_b`` (ISSUE 16 exclusive feature bundling) accumulate
    every level's histogram from the BUNDLED code matrix ``bins_b``
    ((npad, Cb), Cb < C) and expand it back to real columns immediately
    after accumulation (:func:`~h2o3_tpu.models.tree.binning.expand_hist`),
    so subtraction, coarsening, the split scans and the partition walk are
    untouched — the O(rows · C) accumulation is the only thing that
    shrinks. EFB rides the replicated dense lane only (callers force
    ``split_shard=split_fuse=False``) and requires the bin-adapt shifts to
    be zero (bundle codes don't survive coarsening).
    """
    from h2o3_tpu.ops.histogram import histogram_in_jit

    efb_expand = None
    if efb is not None:
        from h2o3_tpu.models.tree.binning import expand_arrays, expand_hist

        assert not split_shard and not split_fuse, "EFB is dense-lane only"
        assert all(
            s == 0 for s in _bin_shifts(max_depth, n_bins, cat_cols)
        ), "EFB requires zero bin-adapt shifts"
        _efb_arrs = expand_arrays(efb, bins_u8.shape[1], n_bins)
        efb_expand = lambda h: expand_hist(_efb_arrs, h)

    # pair bookkeeping (children 2i/2i+1 share pair slot i) needs an even
    # frontier; round an odd node_cap down rather than trace-crash on the
    # stack/reshape interleave
    node_cap = max(2, node_cap - (node_cap % 2))
    nid = jnp.zeros(bins_u8.shape[0], jnp.int32)
    # monotone bound carry: level d's bounds are sized to its frontier
    # (level d-1's n_pad_next), starting from the unbounded root
    node_lo = jnp.full(1, -jnp.inf, jnp.float32) if mono is not None else None
    node_hi = jnp.full(1, jnp.inf, jnp.float32) if mono is not None else None
    # lossguide: remaining net-leaf budget (root is 1 leaf; a split adds 1)
    leaf_budget = jnp.int32(max_leaves - 1) if max_leaves else None
    recs = []
    parent_hist = None
    parent_lay = None  # static HistLayout of the blocked parent (fused path)
    pair_info = None
    n_split = None
    shifts = _bin_shifts(max_depth, n_bins, cat_cols)
    prev_shift = 0
    sat_start, n_sat = _sat_region(max_depth, node_cap, shifts)

    def level_hist(bins_d, nb_d, depth, nid, pair_info, parent_hist, sd,
                   parent_lay=None):
        """One level's histogram — direct or sibling-sub; returns
        ``(hist, layout)`` where ``layout`` is None on the dense path and
        the ``HistLayout`` of the blocked tensor on the fused one.
        Under ``split_shard`` the column axis comes back sharded (and padded
        to the shard count); subtraction, coarsening and the parent carry
        are columnwise (fused: tile-local reshape) ops, so they stay
        block-local and never transpose in HBM."""
        n_pad = min(1 << depth, node_cap)
        if depth == 0 or not subtract:
            h = histogram_in_jit(
                bins_b if efb_expand else bins_d, nid, (w, wy, wh), n_pad,
                nb_d, col_sharded=split_shard, fused=split_fuse,
            )
            if efb_expand:
                return efb_expand(h), None
            return h if split_fuse else (h, None)
        half = n_pad // 2
        row_pair = jnp.maximum(nid, 0) >> 1  # pair = nid//2 (child_base even)
        row_left = (nid & 1) == 0
        bl = pair_info["build_left"]
        build_row = (nid >= 0) & (row_left == bl[row_pair])
        nid_build = jnp.where(build_row, row_pair, -1)
        if split_fuse:
            from h2o3_tpu.ops.hist_pallas import (
                blocked_coarsen, relayout_nodes,
            )

            built, blay = histogram_in_jit(
                bins_d, nid_build, (w, wy, wh), half, nb_d,
                col_sharded=split_shard, fused=True,
            )
            # the blocked tensor's node axis is a pure row-reshape
            # (rows = node·S + stat), so sibling selection/stacking runs on
            # logical (n_ct, node, S, lanes) views with no lane transpose
            psel_blk, clay = blocked_coarsen(parent_hist, parent_lay, sd)
            lanes = clay.ct * clay.bpad
            v = psel_blk.reshape(clay.n_ct, clay.nn, clay.ns, lanes)
            psel = jnp.where(
                pair_info["valid"][None, :, None, None],
                v[:, pair_info["parent_idx"], :, :],
                0.0,
            )  # (n_ct, half, S, lanes)
            b4 = built.reshape(blay.n_ct, blay.nn, blay.ns, lanes)[:, :half]
            sib = psel - b4
            blb = bl[None, :, None, None]
            stacked = jnp.stack(
                [jnp.where(blb, b4, sib), jnp.where(blb, sib, b4)], axis=2
            ).reshape(blay.n_ct, n_pad, blay.ns, lanes)
            flay = relayout_nodes(blay, n_pad)
            if flay.nn > n_pad:
                stacked = jnp.pad(
                    stacked, ((0, 0), (0, flay.nn - n_pad), (0, 0), (0, 0))
                )
            return stacked.reshape(flay.shape), flay
        built = histogram_in_jit(
            bins_b if efb_expand else bins_d, nid_build, (w, wy, wh), half,
            nb_d, col_sharded=split_shard,
        )  # (half, C, Bc, 3) — EFB accumulates bundled, expands to real C
        if efb_expand:
            built = efb_expand(built)
        # parent histogram was built at the previous level's (finer)
        # binning — sum its data-bin groups down to this level's
        psel = jnp.where(
            pair_info["valid"][:, None, None, None],
            _coarsen_hist(parent_hist, sd)[pair_info["parent_idx"]],
            0.0,
        )
        sib = psel - built
        blb = bl[:, None, None, None]
        return jnp.stack(
            [jnp.where(blb, built, sib), jnp.where(blb, sib, built)], axis=1
        ).reshape(n_pad, *built.shape[1:]), None

    depth = 0
    sat_iters = jnp.int32(0)  # executed saturated-region levels (0 if none)
    while depth <= max_depth:
        n_pad = min(1 << depth, node_cap)
        n_pad_next = min(2 * n_pad, node_cap)
        force_leaf = depth == max_depth

        if depth == sat_start:
            # ---- saturated run: ONE compiled body, on-device early exit ----
            sd = shifts[depth]
            nb_d = _coarse_nbins(n_bins, sd)
            bins_d = _coarsen_bins(bins_u8, sd)
            if split_fuse and subtract and parent_lay.n_nodes < node_cap:
                from h2o3_tpu.ops.hist_pallas import blocked_pad_nodes

                parent_hist, parent_lay = blocked_pad_nodes(
                    parent_hist, parent_lay, node_cap
                )
            elif not split_fuse and subtract and parent_hist.shape[0] < node_cap:
                # first iteration's parent frontier may be node_cap/2 wide;
                # zero-pad so the carry shape is loop-invariant (the pad rows
                # are gated off by pair_info["valid"])
                parent_hist = jnp.pad(
                    parent_hist,
                    ((0, node_cap - parent_hist.shape[0]),) + ((0, 0),) * 3,
                )
            zf = jnp.zeros((n_sat, node_cap), jnp.float32)
            zi = jnp.zeros((n_sat, node_cap), jnp.int32)
            zb = jnp.zeros((n_sat, node_cap), bool)
            bufs = {
                "node_w": zf, "split_col": zi, "split_bin": zi,
                "is_cat": zb, "cat_mask": jnp.zeros((n_sat, node_cap, nb_d), bool),
                "na_left": zb, "leaf_now": jnp.ones((n_sat, node_cap), bool),
                "leaf_val": zf, "child_base": zi, "gain": zf,
            }

            def sat_cond(carry):
                return (carry[0] < n_sat) & (carry[4] > 0)

            def sat_body(carry):
                i, nid_c, preds_c, vi_c, _, phist, pinfo, bufs_c = carry[:8]
                lo_c = hi_c = bgt_c = None
                k = 8
                if mono is not None:
                    lo_c, hi_c = carry[8], carry[9]
                    k = 10
                if max_leaves:
                    bgt_c = carry[k]
                d = sat_start + i
                lkey = jax.random.fold_in(tkey, d)
                hist, hlay = level_hist(
                    bins_d, nb_d, sat_start, nid_c, pinfo, phist, 0,
                    parent_lay=parent_lay,
                )
                out = _level_core(
                    hist, bins_d, nid_c, preds_c, vi_c, lkey, cols_enabled,
                    is_cat, min_rows, min_split_improvement, learn_rate,
                    max_abs_leaf, col_sample_rate, leaf_reg,
                    n_pad=node_cap, n_pad_next=node_cap, cat_cols=cat_cols,
                    n_cols_real=n_cols_real, split_shard=split_shard,
                    fuse_layout=hlay, mono=mono, node_lo=lo_c, node_hi=hi_c,
                    leaf_budget=bgt_c,
                )
                nid_c, preds_c, vi_c, nsp, rec, pinfo = out[:6]
                if mono is not None:
                    lo_c, hi_c = out[6], out[7]
                if max_leaves:
                    bgt_c = out[-1]
                if sd:
                    rec = dict(rec, split_bin=rec["split_bin"] << sd)
                bufs_c = {k: bufs_c[k].at[i].set(rec[k]) for k in bufs_c}
                # direct mode threads a fixed dummy parent carry instead
                base = (i + 1, nid_c, preds_c, vi_c, nsp,
                        hist if subtract else phist, pinfo, bufs_c)
                base = base + ((lo_c, hi_c) if mono is not None else ())
                return base + ((bgt_c,) if max_leaves else ())

            if not subtract:
                # the direct scheme needs no parent-histogram/pair carry;
                # thread dummies of fixed shape so one body serves both
                parent_hist = jnp.zeros((node_cap, 1, 1, 1), jnp.float32)
                pair_info = pair_info or {}
            from h2o3_tpu.ops.collectives import tally_group

            # the saturated body traces ONCE but executes a data-dependent
            # number of times (on-device early exit): its tally entries are
            # tagged and scaled at DISPATCH time by the executed iteration
            # count returned below (_run_counted), so the byte counters
            # report actual volume, not the n_sat upper bound
            carry0 = (jnp.int32(0), nid, preds, varimp, n_split, parent_hist,
                      pair_info, bufs)
            if mono is not None:
                carry0 = carry0 + (node_lo, node_hi)
            if max_leaves:
                carry0 = carry0 + (leaf_budget,)
            with tally_group("sat"):
                out = jax.lax.while_loop(sat_cond, sat_body, carry0)
            (sat_iters, nid, preds, varimp, n_split, parent_hist,
             pair_info, bufs) = out[:8]
            if mono is not None:
                node_lo, node_hi = out[8], out[9]
            if max_leaves:
                leaf_budget = out[-1]
            prev_shift = sd
            for j in range(n_sat):
                recs.append({k: bufs[k][j] for k in bufs})
            depth = max_depth
            continue

        lkey = jax.random.fold_in(tkey, depth)
        sd = shifts[depth]
        nb_d = _coarse_nbins(n_bins, sd)
        bins_d = _coarsen_bins(bins_u8, sd)

        if force_leaf and subtract and pair_info is not None:
            # leaf stats straight from the parents' chosen splits
            node_stats = jnp.stack(
                [pair_info["Lst"], pair_info["Rst"]], axis=1
            ).reshape(n_pad, 3)
            nid, preds, varimp, _, rec = _force_leaf_from_stats(
                bins_u8, nid, preds, varimp,
                node_stats[:, 0], node_stats[:, 1], node_stats[:, 2],
                learn_rate, max_abs_leaf, n_pad, n_bins, leaf_reg,
                node_lo=node_lo, node_hi=node_hi,
            )
            recs.append(rec)
            break

        hist, hlay = level_hist(
            bins_d, nb_d, depth, nid, pair_info, parent_hist,
            sd - prev_shift, parent_lay=parent_lay,
        )

        if force_leaf:
            if split_fuse:
                from h2o3_tpu.ops.hist_pallas import blocked_node_totals

                tot = blocked_node_totals(hist, hlay)
            else:
                tot = hist[:, 0, :, :].sum(axis=1)
            nid, preds, varimp, _, rec = _force_leaf_from_stats(
                bins_u8, nid, preds, varimp, tot[:, 0], tot[:, 1], tot[:, 2],
                learn_rate, max_abs_leaf, n_pad, n_bins, leaf_reg,
                node_lo=node_lo, node_hi=node_hi,
            )
        else:
            out = _level_core(
                hist, bins_d, nid, preds, varimp, lkey, cols_enabled, is_cat,
                min_rows, min_split_improvement, learn_rate, max_abs_leaf,
                col_sample_rate, leaf_reg, n_pad=n_pad, n_pad_next=n_pad_next,
                cat_cols=cat_cols, n_cols_real=n_cols_real,
                split_shard=split_shard, fuse_layout=hlay,
                mono=mono, node_lo=node_lo, node_hi=node_hi,
                leaf_budget=leaf_budget,
            )
            nid, preds, varimp, n_split, rec, pair_info = out[:6]
            if mono is not None:
                node_lo, node_hi = out[6], out[7]
            if max_leaves:
                leaf_budget = out[-1]
            parent_hist = hist
            parent_lay = hlay
            prev_shift = sd
            if sd:
                # a coarse prefix split IS a full-res prefix split: convert
                # the recorded threshold so replay/export stay full-res.
                # (partition above already ran on the coarse bins — rows land
                # identically either way.) cat_mask is unused: numeric-only.
                rec = dict(rec, split_bin=rec["split_bin"] << sd)
        recs.append(rec)
        depth += 1
    return nid, preds, varimp, tuple(recs), sat_iters


def _subtract_enabled() -> bool:
    from h2o3_tpu import config

    return config.get_bool("H2O3_TPU_HIST_SUBTRACT")


def use_fused_trees(max_depth: int) -> bool:
    """Single policy for every fused/scanned-tree selector (build_tree, GBM
    and DRF scan paths): the device-resident whole-tree program on EVERY
    backend up to H2O3_TPU_FUSED_MAX_DEPTH. One dispatch per tree beats
    per-level dispatch gaps everywhere (tunnel latency on networked TPUs,
    Python/dispatch overhead × levels × trees on the CPU mesh), and the
    saturated-level ``lax.while_loop`` (see :func:`_fused_levels`) keeps the
    compile bounded at any depth — deep levels compile ONE body and early-
    exit on device. ``H2O3_TPU_WHOLE_TREE=0`` restores the host-driven
    per-level dispatch loop (debug/bisect escape hatch)."""
    from h2o3_tpu import config

    return (
        config.get_bool("H2O3_TPU_WHOLE_TREE")
        and max_depth <= config.get_int("H2O3_TPU_FUSED_MAX_DEPTH")
    )


# ---------------------------------------------------------------------------
# GOSS — gradient-based one-side sampling (ISSUE 16, after arXiv:1706.08359):
# keep the top-a fraction of rows by |gradient| exactly, sample a b fraction
# of the rest uniformly, and amplify the sampled rest by (1-a)/b so the
# histogram stat sums stay unbiased. Rows drop out the same way sample_rate
# rows do — weight 0 — so every downstream lane (hists, partition, streamed
# blocks, the 2-D mesh row axis) composes with no new code paths.


def _goss_ab() -> tuple[float, float] | None:
    """Parse ``H2O3_TPU_TREE_GOSS='a,b'``; None (knob empty) = GOSS off."""
    from h2o3_tpu import config

    raw = config.get("H2O3_TPU_TREE_GOSS").strip()
    if not raw:
        return None
    try:
        a_s, b_s = raw.split(",")
        a, b = float(a_s), float(b_s)
    except ValueError:
        raise ValueError(
            f"H2O3_TPU_TREE_GOSS must be 'a,b' (two floats), got {raw!r}"
        ) from None
    if not (0.0 <= a < 1.0):
        raise ValueError(f"GOSS top fraction a must be in [0, 1), got {a}")
    if not (0.0 < b <= 1.0 - a):
        raise ValueError(f"GOSS rest fraction b must be in (0, 1-a], got {b}")
    return a, b


def _goss_factor(w_tree, wy, gkey, a: float, b: float):
    """Traced per-row GOSS factor: 1 for the top-a rows by |weighted
    gradient|, (1-a)/b for the kept b-sample of the rest, 0 otherwise.

    The top set is selected by a rank-k threshold over the VALID rows
    (``w_tree > 0`` — bootstrap/sample_rate dropouts and row padding never
    count toward the top fraction), with ties at the threshold all kept
    (the cheap, deterministic resolution — the set can exceed a·n by the
    tie count). ``a == 0`` degrades to plain amplified row sampling at
    rate ``b``."""
    valid = w_tree > 0
    gmag = jnp.where(valid, jnp.abs(wy), -jnp.inf)
    n_valid = valid.sum()
    k = jnp.round(a * n_valid).astype(jnp.int32)
    srt = jnp.sort(gmag)[::-1]  # descending; invalid (-inf) rows sort last
    thr = srt[jnp.maximum(k - 1, 0)]
    top = valid & (gmag >= thr) & (k > 0)
    rest = valid & ~top
    keep_rest = rest & jax.random.bernoulli(gkey, b / (1.0 - a), w_tree.shape)
    amp = jnp.float32((1.0 - a) / b)
    return jnp.where(
        top, 1.0, jnp.where(keep_rest, amp, 0.0)
    ).astype(w_tree.dtype)


# ---------------------------------------------------------------------------
# monotone-constraint variant of the level step (GBM monotone_constraints).
# Kept separate so the unconstrained hot path compiles byte-identical; used
# only via build_tree's per-level loop when constraints are present.


def _level_step_mono_fn(
    bins_u8, nid, preds, varimp, w, wy, wh, key, cols_enabled, is_cat,
    min_rows, min_split_improvement, learn_rate, max_abs_leaf, col_sample_rate,
    mono, node_lo, node_hi, leaf_reg=None,
    *, n_pad: int, n_pad_next: int, n_bins: int, force_leaf: bool,
    cat_cols: tuple = (), split_shard: bool = False,
):
    """Monotone variant of _level_step_fn: leaf values clamp to the node's
    [lo, hi] bounds; children of a constrained split get tightened bounds."""
    from h2o3_tpu.ops.histogram import histogram_in_jit

    C = bins_u8.shape[1]
    hist = histogram_in_jit(
        bins_u8, nid, (w, wy, wh), n_pad, n_bins, col_sharded=split_shard
    )

    if force_leaf:
        tot = hist[:, 0, :, :].sum(axis=1)
        node_w, node_wy, node_wh = tot[:, 0], tot[:, 1], tot[:, 2]
        ok = jnp.zeros(n_pad, bool)
        gain = jnp.zeros(n_pad, jnp.float32)
        split_col = jnp.zeros(n_pad, jnp.int32)
        split_bin = jnp.zeros(n_pad, jnp.int32)
        is_cat_n = jnp.zeros(n_pad, bool)
        cat_mask = jnp.zeros((n_pad, n_bins), bool)
        na_left = jnp.zeros(n_pad, bool)
        mid = jnp.zeros(n_pad, jnp.float32)
        mono_col = jnp.zeros(n_pad, jnp.int32)
    else:
        col_mask = jnp.broadcast_to(cols_enabled[None, :], (n_pad, C))
        keep = jax.random.uniform(key, (n_pad, C)) < col_sample_rate
        keep = jnp.where(keep.any(axis=1, keepdims=True), keep, True)
        col_mask = col_mask * keep
        if split_shard:
            sp = _split_scan_sharded(
                hist, is_cat, col_mask, min_rows, min_split_improvement,
                any_cat=bool(cat_cols),
                mono=mono, node_lo=node_lo, node_hi=node_hi,
            )
        else:
            sp = _split_scan(
                hist, is_cat, col_mask, min_rows, min_split_improvement,
                cat_cols, mono=mono, node_lo=node_lo, node_hi=node_hi,
            )
        ok = sp["ok"]
        fits = 2 * jnp.cumsum(ok.astype(jnp.int32)) <= n_pad_next
        ok = ok & fits
        gain = jnp.where(ok, jnp.maximum(sp["gain"], 0.0), 0.0)
        node_w, node_wy, node_wh = sp["node_w"], sp["node_wy"], sp["node_wh"]
        split_col, split_bin = sp["col"], sp["split_bin"]
        is_cat_n, cat_mask, na_left = sp["is_cat"], sp["cat_mask"], sp["na_left"]
        mid, mono_col = sp["mid"], sp["mono_col"]

    rl, ra = (None, None) if leaf_reg is None else leaf_reg
    nid, preds, varimp, n_split, record, cs = _finish_level(
        bins_u8, nid, preds, varimp, ok, gain, node_w, node_wy, node_wh,
        split_col, split_bin, is_cat_n, cat_mask, na_left,
        learn_rate, max_abs_leaf, n_pad, node_lo=node_lo, node_hi=node_hi,
        reg_lambda=rl, reg_alpha=ra,
    )
    # child bounds scatter: left child at child_base, right at child_base+1
    new_lo, new_hi = _child_bounds(
        ok, record["child_base"], mono_col, mid, node_lo, node_hi, n_pad_next
    )
    return nid, preds, varimp, n_split, record, new_lo, new_hi


def _mesh_key():
    """Program-cache component for the process mesh: the traced collectives
    (and the sharded split's block layout) bake the mesh in at trace time,
    so a program compiled for one mesh must never serve another (tests swap
    sub-meshes of different sizes within one process)."""
    from h2o3_tpu.parallel.mesh import mesh_key

    return mesh_key()


def _level_step_mono(n_pad, n_pad_next, n_bins, force_leaf, cat_cols=(),
                     split_shard=False):
    # _kernel_key: the Pallas tile/override knobs change the traced
    # histogram kernel even though mono levels never fuse the split
    key = ("mono", n_pad, n_pad_next, n_bins, force_leaf, cat_cols,
           split_shard, _kernel_key(), _mesh_key(), jax.default_backend())
    fn = _STEP_CACHE.get(key)
    if fn is None:
        fn = jax.jit(
            partial(
                _level_step_mono_fn,
                n_pad=n_pad, n_pad_next=n_pad_next, n_bins=n_bins,
                force_leaf=force_leaf, cat_cols=cat_cols,
                split_shard=split_shard,
            )
        )
        _STEP_CACHE[key] = fn
    _PROG_KEY[id(fn)] = key
    return fn


_STEP_CACHE: dict = {}


def _level_step(
    n_pad: int, n_pad_next: int, n_bins: int, force_leaf: bool,
    cat_cols: tuple = (), split_shard: bool = False,
    split_fuse: bool = False,
):
    key = (n_pad, n_pad_next, n_bins, force_leaf, cat_cols, split_shard,
           split_fuse, _kernel_key(), _mesh_key(), jax.default_backend())
    fn = _STEP_CACHE.get(key)
    if fn is None:
        fn = jax.jit(
            partial(
                _level_step_fn,
                n_pad=n_pad, n_pad_next=n_pad_next,
                n_bins=n_bins, force_leaf=force_leaf, cat_cols=cat_cols,
                split_shard=split_shard, split_fuse=split_fuse,
            )
        )
        _STEP_CACHE[key] = fn
    _PROG_KEY[id(fn)] = key
    return fn


def _clamp_node_cap(node_cap: int, npad: int, min_rows) -> int:
    """node_cap can't usefully exceed the next power of two ≥ the row count:
    with min_rows ≥ 1 a split needs two rows, so the live frontier is bounded
    by the rows and every slot past that bound is provably-dead padding the
    fused program would still trace and execute. Capping it keeps small-frame
    whole-tree programs (tests, AutoML folds) proportionate. The split chain
    is unchanged by construction; only the RNG-draw width at depths past the
    clamped cap differs from an uncapped build."""
    if float(min_rows) < 1.0:
        return node_cap
    cap_rows = 1 << max(1, int(npad - 1).bit_length())
    return max(2, min(node_cap, cap_rows))


def _tree_program(
    max_depth: int, n_bins: int, node_cap: int, cat_cols: tuple,
    n_cols_real: int | None = None, n_cols_pad: int | None = None,
    mono: bool = False, max_leaves: int = 0, efb=None,
):
    """One jitted program building a WHOLE tree (growth levels unrolled, the
    saturated run as a lax.while_loop — see :func:`_fused_levels`).

    On a networked TPU every dispatch costs tens of ms of tunnel latency;
    per-level dispatch made the host gap the single largest per-tree cost
    (BENCH_r03 breakdown: 2.0 s/tree host vs 2.3 s device). One dispatch per
    tree removes it. ``preds``/``varimp`` are DONATED: tree t+1's dispatch
    reuses tree t's output buffers in place, so nothing is copied and no
    host sync sits between pipelined trees. ``n_cols_pad`` (shape bucketing)
    pads the column axis INSIDE the program — callers pass real-width arrays
    and get a real-width varimp back.
    """
    subtract = _subtract_enabled()
    if efb is not None:
        # EFB rides the replicated dense lane only: the bundled C axis is
        # too small to shard/fuse profitably, and the dense scans are
        # decision-equal to the sharded/fused ones by construction
        split_shard = split_fuse = False
    else:
        split_shard = _split_shard_on()
        split_fuse = _split_fuse_active(cat_cols, split_shard)
    key = ("tree", max_depth, n_bins, node_cap, cat_cols, subtract,
           n_cols_real, n_cols_pad, split_shard, split_fuse, bool(mono),
           int(max_leaves), None if efb is None else efb.key,
           _kernel_key(), _mesh_key(),
           tuple(_bin_shifts(max_depth, n_bins, cat_cols)),
           jax.default_backend())

    def make():
        def whole_tree(
            bins_u8, preds, varimp, w, wy, wh, key_, cols_enabled, is_cat,
            min_rows, min_split_improvement, learn_rate, max_abs_leaf,
            col_sample_rate, leaf_reg=None, mono_vec=None, bins_b=None,
        ):
            C = bins_u8.shape[1]
            Cp = n_cols_pad or C
            if Cp > C:  # bucketed column pad: code 0 (NA), masked everywhere
                bins_u8 = jnp.pad(bins_u8, ((0, 0), (0, Cp - C)))
                is_cat = jnp.pad(is_cat, (0, Cp - C))
                varimp = jnp.pad(varimp, (0, Cp - C))
                cols_enabled = jnp.pad(cols_enabled, (0, Cp - C))
                if mono_vec is not None:  # pad columns are unconstrained
                    mono_vec = jnp.pad(mono_vec, (0, Cp - C))
            nid, preds_, varimp_, records, sat_iters = _fused_levels(
                bins_u8, preds, varimp, w, wy, wh, key_, cols_enabled, is_cat,
                min_rows, min_split_improvement, learn_rate, max_abs_leaf,
                col_sample_rate, leaf_reg,
                max_depth=max_depth, n_bins=n_bins, node_cap=node_cap,
                cat_cols=cat_cols, subtract=subtract, n_cols_real=n_cols_real,
                split_shard=split_shard, split_fuse=split_fuse, mono=mono_vec,
                max_leaves=max_leaves, efb=efb, bins_b=bins_b,
            )
            return nid, preds_, varimp_[:C], records, sat_iters

        return jax.jit(whole_tree, donate_argnums=(1, 2))

    return _cached_program(key, make)


def build_trees_scanned(
    bins_u8,
    w,
    y,
    preds,
    varimp,
    base_key,
    n_trees: int,
    *,
    row_key=None,
    tree_offset: int = 0,
    grad_fn,
    grad_key,
    sample_rate: float,
    n_bins: int,
    is_cat_cols,
    max_depth: int,
    min_rows: float,
    min_split_improvement: float,
    learn_rates,
    max_abs_leaf: float,
    col_sample_rate: float,
    col_sample_rate_per_tree: float,
    node_cap: int = 2048,
    reg_lambda: float = 0.0,
    reg_alpha: float = 0.0,
    monotone=None,
    max_leaves: int = 0,
    efb=None,
    bins_b=None,
):
    """Build ``n_trees`` trees in ONE device dispatch (lax.scan over trees).

    On the tunneled TPU every dispatch costs ~66 ms once any device→host
    transfer has happened (see bench breakdown r03); per-tree dispatch made
    host latency the dominant cost. This scans whole scoring intervals.

    ``grad_fn(F, y, w_tree) -> (t, h)`` supplies per-tree pseudo-residuals
    and hessians (distribution-specific, traced); ``grad_key`` is a hashable
    cache token identifying it. ``learn_rates`` is a host array of length
    ``n_trees`` (annealing). ``row_key`` (defaults to ``base_key``) seeds the
    per-tree row bootstrap separately so DRF's K class-trees can share one
    bootstrap while drawing distinct column/level randomness. ``tree_offset``
    is the global index of the chunk's first tree, keeping per-tree key
    folds stable across chunk boundaries. Returns ``(preds, varimp,
    stacked)`` where ``stacked`` is a tuple over levels of record dicts with
    a leading ``n_trees`` axis — convert with :func:`trees_from_stacked`.
    """
    from h2o3_tpu.models.tree.binning import bucket_cols, bucket_nbins

    C = bins_u8.shape[1]
    Cp = bucket_cols(C)  # shape-bucketed column padding (inert, see binning)
    n_bins = bucket_nbins(n_bins)  # padded bins are empty → argmax-inert
    node_cap = _clamp_node_cap(node_cap, bins_u8.shape[0], min_rows)
    is_cat_np = np.asarray(is_cat_cols, bool)
    cat_cols = tuple(int(i) for i in np.nonzero(is_cat_np)[0])
    is_cat_dev = jnp.asarray(is_cat_np)

    subtract = _subtract_enabled()
    if efb is not None:
        split_shard = split_fuse = False  # EFB: replicated dense lane only
    else:
        split_shard = _split_shard_on()
        split_fuse = _split_fuse_active(cat_cols, split_shard)
    goss = _goss_ab()
    # the float rates are baked into the traced closure, so they MUST be part
    # of the cache key (a boolean would silently reuse another model's rates);
    # C (the real column count) likewise — it sizes the traced RNG draws;
    # goss (a, b floats) and the EFB plan fingerprint bake in the same way
    key = (
        "scan", n_trees, max_depth, n_bins, node_cap, cat_cols, grad_key, C,
        tuple(_bin_shifts(max_depth, n_bins, cat_cols)),
        float(sample_rate), float(col_sample_rate_per_tree), subtract,
        split_shard, split_fuse, monotone is not None, goss,
        int(max_leaves), None if efb is None else efb.key, _kernel_key(),
        _mesh_key(), jax.default_backend(),
    )

    def make():
        def whole_chunk(
            bins_u8, w, y, preds, varimp, base_key, row_key_, offset, lrs, is_cat,
            min_rows_, msi_, max_abs_leaf_, col_rate_, leaf_reg_,
            mono_vec=None, bins_b=None,
        ):
            if Cp > C:  # bucketed column pad: code 0 (NA) everywhere, masked
                bins_u8 = jnp.pad(bins_u8, ((0, 0), (0, Cp - C)))
                is_cat = jnp.pad(is_cat, (0, Cp - C))
                varimp = jnp.pad(varimp, (0, Cp - C))
                if mono_vec is not None:  # pad columns are unconstrained
                    mono_vec = jnp.pad(mono_vec, (0, Cp - C))

            def body(carry, per_tree):
                F, vi = carry
                i, lr = per_tree
                m = i + offset
                tkey = jax.random.fold_in(base_key, m)
                if sample_rate < 1.0:
                    mask = jax.random.bernoulli(
                        jax.random.fold_in(jax.random.fold_in(row_key_, m), 1 << 29),
                        sample_rate,
                        w.shape,
                    )
                    w_tree = w * mask.astype(w.dtype)
                else:
                    w_tree = w
                # ph_grad: phase tag for tools/profile_fused.py
                with jax.named_scope("ph_grad"):
                    t, h = grad_fn(F, y, w_tree)
                    wy = w_tree * t
                    wh = jnp.where(w_tree > 0, h, 0.0)
                if goss is not None:
                    gf = _goss_factor(
                        w_tree, wy, jax.random.fold_in(tkey, 1 << 28), *goss
                    )
                    w_tree = w_tree * gf
                    wy = wy * gf
                    wh = wh * gf
                # the per-tree column draw runs at the REAL column count C,
                # so bucketed padding cannot perturb the sampled columns
                if col_sample_rate_per_tree < 1.0:
                    keep = (
                        jax.random.uniform(jax.random.fold_in(tkey, 1 << 30), (C,))
                        < col_sample_rate_per_tree
                    )
                    keep = jnp.where(keep.any(), keep, True)
                    cols_enabled = keep.astype(jnp.float32)
                else:
                    cols_enabled = jnp.ones(C, jnp.float32)
                if Cp > C:
                    cols_enabled = jnp.pad(cols_enabled, (0, Cp - C))

                _, F, vi, recs, sat_i = _fused_levels(
                    bins_u8, F, vi, w_tree, wy, wh, tkey, cols_enabled,
                    is_cat, min_rows_, msi_, lr, max_abs_leaf_, col_rate_,
                    leaf_reg_,
                    max_depth=max_depth, n_bins=n_bins, node_cap=node_cap,
                    cat_cols=cat_cols, subtract=subtract, n_cols_real=C,
                    split_shard=split_shard, split_fuse=split_fuse,
                    mono=mono_vec,
                    max_leaves=max_leaves, efb=efb, bins_b=bins_b,
                )
                return (F, vi), (recs, sat_i)

            (preds, varimp), (stacked, sat_per_tree) = jax.lax.scan(
                body, (preds, varimp), (jnp.arange(n_trees), lrs)
            )
            # total executed saturated-region levels across the chunk's
            # trees — the dispatch-time weight for the sat byte tallies
            return preds, varimp[:C], stacked, sat_per_tree.sum()

        # preds/varimp donated: chunk t+1 reuses chunk t's output buffers in
        # place — the running prediction never copies between dispatches
        return jax.jit(whole_chunk, donate_argnums=(3, 4))

    prog = _cached_program(key, make)

    lrs = jnp.asarray(np.asarray(learn_rates, np.float32))
    leaf_reg = (
        None
        if reg_lambda == 0.0 and reg_alpha == 0.0
        else (jnp.float32(reg_lambda), jnp.float32(reg_alpha))
    )
    BUILD_STATS["dispatches"] += 1
    BUILD_STATS["trees_built"] += n_trees
    # host-side dispatch wall time (includes the trace/compile on a cache
    # miss; the device work itself completes asynchronously) — the
    # "fused-build seconds" lane of the registry
    import time as _time

    _t0 = _time.perf_counter()
    # the scan body traces once but runs once per tree: mult=n_trees; the
    # saturated-region tallies instead scale by the chunk's total EXECUTED
    # sat levels, returned as the program's last output
    mono_dev = (
        None if monotone is None
        else jnp.asarray(np.asarray(monotone, np.int32))
    )
    if goss is not None:
        # modeled expected kept-row volume, same convention as the HBM
        # byte tallies (host-side: the factor never leaves the program)
        _ROWS_SAMPLED.inc((goss[0] + goss[1]) * bins_u8.shape[0] * n_trees)
    if efb is not None:
        _COLS_BUNDLED.inc(C - efb.n_cols_b)
    out = _run_counted(
        prog,
        (
            bins_u8, w, y, preds, varimp, base_key,
            base_key if row_key is None else row_key,
            jnp.int32(tree_offset), lrs, is_cat_dev,
            jnp.float32(min_rows), jnp.float32(min_split_improvement),
            jnp.float32(max_abs_leaf), jnp.float32(col_sample_rate), leaf_reg,
            mono_dev, bins_b,
        ),
        mult=n_trees,
        sat_from=lambda o: o[3],
    )
    _FUSED_SECONDS.inc(_time.perf_counter() - _t0)
    return out[:3]


def scan_chunk_cap(
    max_depth: int, n_bins: int, node_cap: int = 2048, budget_bytes: int = 256 << 20
) -> int:
    """Max trees per scanned dispatch so stacked records fit the budget
    (cat_mask (T, N, B) dominates; deep DRF trees are ~6 MB each)."""
    per_tree = 0
    for depth in range(max_depth + 1):
        n = min(1 << depth, node_cap)
        per_tree += n * (n_bins + 40)
    return max(1, int(budget_bytes // max(per_tree, 1)))


# Record fields in pack order. The whole stacked chunk flattens into ONE
# uint8 buffer = ONE device→host transfer: a naive device_get(stacked) pulls
# ~70 leaves, and on the tunneled TPU each leaf is its own ~66 ms round-trip,
# which made record download cost more than building the trees (BENCH r4
# profile: 6.7 s of an 8.3 s 20-tree train). f32/i32 fields are bitcast to 4
# uint8 lanes (exact, any magnitude); bools ship as 1 byte each, so the
# payload stays byte-sized for cat_mask — the dominant field.
_PACK_I32 = ("split_col", "split_bin", "child_base")
_PACK_BOOL = ("is_cat", "na_left", "leaf_now", "cat_mask")
_PACK_F32 = ("node_w", "leaf_val", "gain")
_PACK_FIELDS = _PACK_F32 + _PACK_I32 + _PACK_BOOL


@jax.jit
def _pack_stacked(stacked):
    parts = []
    for lvl in stacked:
        assert set(lvl) == set(_PACK_FIELDS), sorted(set(lvl) ^ set(_PACK_FIELDS))
        T = lvl["node_w"].shape[0]
        for k in _PACK_FIELDS:
            v = lvl[k]
            if k in _PACK_BOOL:
                parts.append(v.astype(jnp.uint8).reshape(T, -1))
            else:
                parts.append(jax.lax.bitcast_convert_type(v, jnp.uint8).reshape(T, -1))
    return jnp.concatenate(parts, axis=1)


def trees_from_stacked(stacked, n_trees: int) -> list["Tree"]:
    """ONE device→host transfer for a whole chunk → numpy-backed Trees."""
    packed = np.asarray(jax.device_get(_pack_stacked(stacked)))  # (T, X) u8
    out = [Tree() for _ in range(n_trees)]
    off = 0
    for lvl in stacked:
        fields = {}
        for k in _PACK_FIELDS:
            shape = lvl[k].shape[1:]  # per-tree shape
            size = int(np.prod(shape)) if shape else 1
            nbytes = size if k in _PACK_BOOL else size * 4
            # contiguous per-field copy: the view below then holds only this
            # field's bytes, not the whole chunk buffer
            raw = np.ascontiguousarray(packed[:, off : off + nbytes])
            if k in _PACK_BOOL:
                v = raw.view(np.bool_).reshape(n_trees, *shape)
            elif k in _PACK_I32:
                v = raw.view(np.int32).reshape(n_trees, *shape)
            else:
                v = raw.view(np.float32).reshape(n_trees, *shape)
            fields[k] = v
            off += nbytes
        for ti in range(n_trees):
            out[ti].levels.append(TreeLevel(**{k: v[ti] for k, v in fields.items()}))
    return out


def replay_batch(bins_u8, stacked, preds):
    """Replay a whole stacked chunk of trees in ONE dispatch.

    ``stacked`` is the (device or host) tuple-over-levels of record dicts
    with leading tree axis, as returned by :func:`build_trees_scanned`.
    """
    n_levels = len(stacked)
    key = ("replay", n_levels, jax.default_backend())
    prog = _STEP_CACHE.get(key)
    if prog is None:

        def run(bins_u8, stacked, preds):
            def body(preds, tree_recs):
                nid = jnp.zeros(bins_u8.shape[0], jnp.int32)
                for rec in tree_recs:
                    nid, preds = _partition_update(
                        bins_u8, nid, preds, rec["split_col"], rec["split_bin"],
                        rec["is_cat"], rec["cat_mask"], rec["na_left"],
                        rec["leaf_now"], rec["leaf_val"], rec["child_base"],
                    )
                return preds, None

            preds, _ = jax.lax.scan(body, preds, stacked)
            return preds

        # preds donated: score-keeper replays pipeline behind the next
        # chunk's build without copying the running prediction
        prog = jax.jit(run, donate_argnums=(2,))
        _STEP_CACHE[key] = prog
    return prog(bins_u8, stacked, preds)


# ---------------------------------------------------------------------------
# recorded tree (for prediction replay; fields are DEVICE arrays)


@dataclass
class TreeLevel:
    split_col: jnp.ndarray
    split_bin: jnp.ndarray
    is_cat: jnp.ndarray
    cat_mask: jnp.ndarray
    na_left: jnp.ndarray
    leaf_now: jnp.ndarray
    leaf_val: jnp.ndarray
    child_base: jnp.ndarray
    gain: jnp.ndarray | None = None  # per-node split gain (varimp source)
    node_w: jnp.ndarray | None = None  # per-node weighted cover (TreeSHAP)


@dataclass
class Tree:
    levels: list[TreeLevel] = field(default_factory=list)

    def real_level_masks(self) -> list[np.ndarray]:
        """Boolean mask of REAL node slots per level, derived exactly from
        the split chain: level 0 has one real node; level i+1 has
        2 * (# real non-leaf nodes at level i) real slots (children are
        compacted to the front by child_base). Padding slots carry
        leaf_now=True with zero stats and must not count as leaves."""
        host = self.to_host() if any(
            not isinstance(lv.leaf_now, np.ndarray) for lv in self.levels
        ) else self
        masks = []
        n_real = 1
        for lv in host.levels:
            width = len(lv.leaf_now)
            m = np.arange(width) < n_real
            masks.append(m)
            n_real = 2 * int(np.sum(~lv.leaf_now & m))
        return masks

    @property
    def n_leaves(self) -> int:
        host = self.to_host() if any(
            not isinstance(lv.leaf_now, np.ndarray) for lv in self.levels
        ) else self
        return int(sum(
            int(np.sum(lv.leaf_now & m))
            for lv, m in zip(host.levels, host.real_level_masks())
        ))

    @property
    def depth(self) -> int:
        """Depth of the deepest REAL node (the recorded level count can
        exceed it when every branch retired early)."""
        host = self.to_host() if any(
            not isinstance(lv.leaf_now, np.ndarray) for lv in self.levels
        ) else self
        d = 0
        for li, m in enumerate(host.real_level_masks()):
            if m.any():
                d = li
        return d

    def replay(self, bins_u8, nid, preds):
        """Accumulate this tree's contribution into preds (device walk)."""
        for lv in self.levels:
            nid, preds = _partition_update(
                bins_u8, nid, preds,
                lv.split_col, lv.split_bin, lv.is_cat, lv.cat_mask,
                lv.na_left, lv.leaf_now, lv.leaf_val, lv.child_base,
            )
        return nid, preds

    def to_host(self) -> "Tree":
        """Pull every level to numpy (for export/inspection paths)."""
        out = Tree()
        import dataclasses as _dc

        fields = tuple(f.name for f in _dc.fields(TreeLevel))
        pulled = jax.device_get([[getattr(lv, f) for f in fields] for lv in self.levels])
        for vals in pulled:
            out.levels.append(TreeLevel(*[np.asarray(v) for v in vals]))
        return out


# ---------------------------------------------------------------------------
# the level-wise builder


def build_tree(
    bins_u8,
    w,
    t,
    h,
    *,
    n_bins: int,
    is_cat_cols,
    max_depth: int,
    min_rows: float,
    min_split_improvement: float,
    learn_rate: float,
    preds,
    key,
    varimp,
    col_sample_rate: float = 1.0,
    col_sample_rate_per_tree: float = 1.0,
    cols_enabled=None,
    max_abs_leaf: float = np.inf,
    node_cap: int = 2048,
    monotone=None,  # (C,) int {-1,0,1} per-column constraint directions
    reg_lambda: float = 0.0,
    reg_alpha: float = 0.0,
    max_leaves: int = 0,
    efb=None,
    bins_b=None,
):
    """Build one tree without any host↔device traffic in the level loop.

    Inputs are row-sharded device arrays: ``bins_u8`` (npad,C), per-row
    weight ``w`` (0 = out of this tree), target ``t`` (residual), hessian
    ``h``; ``key`` a jax PRNG key (column sampling), ``varimp`` a device (C,)
    accumulator. Returns ``(Tree, preds, varimp)`` — all device-resident.

    ALL rows walk the tree (sampled-out rows contribute nothing to hists via
    w=0, but must still receive leaf predictions — GBM's next-iteration
    gradients depend on F for every row).
    """
    from h2o3_tpu.models.tree.binning import bucket_cols, bucket_nbins

    C = bins_u8.shape[1]
    Cp = bucket_cols(C)  # shape-bucketed column padding (inert, see binning)
    n_bins = bucket_nbins(n_bins)  # padded bins are empty → argmax-inert
    node_cap = _clamp_node_cap(node_cap, bins_u8.shape[0], min_rows)
    is_cat_dev = jnp.asarray(np.asarray(is_cat_cols, bool))
    wy = w * t
    wh = jnp.where(w > 0, h, 0.0)  # sampled-out rows carry no hessian either
    goss = _goss_ab()
    if goss is not None:
        # GOSS composes with every build lane from here: the factor folds
        # into the row weights before any histogram sees them
        gf = _goss_factor(w, wy, jax.random.fold_in(key, 1 << 28), *goss)
        w = w * gf
        wy = wy * gf
        wh = wh * gf
        _ROWS_SAMPLED.inc((goss[0] + goss[1]) * w.shape[0])
    if efb is not None:
        _COLS_BUNDLED.inc(C - efb.n_cols_b)
    if cols_enabled is not None:
        cols_enabled_dev = jnp.asarray(np.asarray(cols_enabled, np.float32))
    elif col_sample_rate_per_tree < 1.0:
        # per-tree column subsample drawn on device (no host rng → no upload)
        keep = jax.random.uniform(jax.random.fold_in(key, 1 << 30), (C,)) < col_sample_rate_per_tree
        keep = jnp.where(keep.any(), keep, True)
        cols_enabled_dev = keep.astype(jnp.float32)
    else:
        cols_enabled_dev = jnp.ones(C, jnp.float32)

    cat_cols = tuple(int(i) for i in np.nonzero(np.asarray(is_cat_cols, bool))[0])
    tree = Tree()
    leaf_reg = (
        None
        if reg_lambda == 0.0 and reg_alpha == 0.0
        else (jnp.float32(reg_lambda), jnp.float32(reg_alpha))
    )

    # Monotone constraints carry per-node [lo, hi] bound state level to
    # level. With the fused Pallas lane active the whole constrained tree
    # runs as ONE whole-tree program (the ISSUE-15 closure: the feasibility
    # mask lives in the kernel grid step and the bound state rides the
    # level carry — see _fused_levels); with the fuse gate off, the legacy
    # per-level host loop below is today's path bit-for-bit.
    # level — a separate per-level loop (constrained builds trade the fused
    # dispatch for correctness; the default path is untouched).
    split_shard = _split_shard_on()
    if monotone is not None and np.any(np.asarray(monotone) != 0):
        mono_dev = jnp.asarray(np.asarray(monotone, np.int32))
        if _split_fuse_on() and use_fused_trees(max_depth):
            prog = _tree_program(
                max_depth, n_bins, node_cap, cat_cols, n_cols_real=C,
                n_cols_pad=Cp, mono=True, max_leaves=max_leaves, efb=efb,
            )
            BUILD_STATS["dispatches"] += 1
            BUILD_STATS["trees_built"] += 1
            import time as _time

            _t0 = _time.perf_counter()
            _, preds, varimp, records, _sat = _run_counted(
                prog,
                (
                    bins_u8, preds, varimp, w, wy, wh, key, cols_enabled_dev,
                    is_cat_dev,
                    jnp.float32(min_rows), jnp.float32(min_split_improvement),
                    jnp.float32(learn_rate), jnp.float32(max_abs_leaf),
                    jnp.float32(col_sample_rate), leaf_reg, mono_dev, bins_b,
                ),
                sat_from=lambda o: o[4],
            )
            _FUSED_SECONDS.inc(_time.perf_counter() - _t0)
            for rec in records:
                tree.levels.append(TreeLevel(**rec))
            return tree, preds, varimp
        if _split_fuse_on():
            # fuse gate on but the whole-tree program is off
            # (H2O3_TPU_WHOLE_TREE=0 / depth cap): the per-level mono loop
            # below runs the unfused scan — make that visible
            _FUSED_FALLBACKS.inc(reason="mono")
        nid = jnp.zeros(bins_u8.shape[0], jnp.int32)
        node_lo = jnp.full(1, -jnp.inf, jnp.float32)
        node_hi = jnp.full(1, jnp.inf, jnp.float32)
        for depth in range(max_depth + 1):
            n_pad = min(1 << depth, node_cap)
            n_pad_next = min(2 * n_pad, node_cap)
            force_leaf = depth == max_depth
            step = _level_step_mono(
                n_pad, n_pad_next, n_bins, force_leaf, cat_cols, split_shard
            )
            lkey = jax.random.fold_in(key, depth)
            BUILD_STATS["dispatches"] += 1
            nid, preds, varimp, n_split, rec, node_lo, node_hi = _run_counted(
                step,
                (
                    bins_u8, nid, preds, varimp, w, wy, wh, lkey,
                    cols_enabled_dev, is_cat_dev,
                    jnp.float32(min_rows), jnp.float32(min_split_improvement),
                    jnp.float32(learn_rate), jnp.float32(max_abs_leaf),
                    jnp.float32(col_sample_rate),
                    mono_dev, node_lo, node_hi, leaf_reg,
                ),
            )
            tree.levels.append(TreeLevel(**rec))
            if force_leaf:
                break
            if jax.default_backend() == "cpu" and int(n_split) == 0:
                break
        BUILD_STATS["trees_built"] += 1
        return tree, preds, varimp

    fused = use_fused_trees(max_depth)
    if (max_leaves or efb is not None) and not fused:
        raise ValueError(
            "grow_policy=lossguide / EFB need the fused whole-tree program "
            "(H2O3_TPU_WHOLE_TREE=1 within the fused depth cap)"
        )
    if fused:
        prog = _tree_program(
            max_depth, n_bins, node_cap, cat_cols, n_cols_real=C,
            n_cols_pad=Cp, max_leaves=max_leaves, efb=efb,
        )
        BUILD_STATS["dispatches"] += 1
        BUILD_STATS["trees_built"] += 1
        import time as _time

        _t0 = _time.perf_counter()
        _, preds, varimp, records, _sat = _run_counted(
            prog,
            (
                bins_u8, preds, varimp, w, wy, wh, key, cols_enabled_dev,
                is_cat_dev,
                jnp.float32(min_rows), jnp.float32(min_split_improvement),
                jnp.float32(learn_rate), jnp.float32(max_abs_leaf),
                jnp.float32(col_sample_rate), leaf_reg, None, bins_b,
            ),
            sat_from=lambda o: o[4],
        )
        _FUSED_SECONDS.inc(_time.perf_counter() - _t0)
        for rec in records:
            tree.levels.append(TreeLevel(**rec))
        return tree, preds, varimp

    nid = jnp.zeros(bins_u8.shape[0], jnp.int32)
    split_fuse = _split_fuse_active(cat_cols, split_shard)
    for depth in range(max_depth + 1):
        n_pad = min(1 << depth, node_cap)
        n_pad_next = min(2 * n_pad, node_cap)
        force_leaf = depth == max_depth
        step = _level_step(
            n_pad, n_pad_next, n_bins, force_leaf, cat_cols, split_shard,
            split_fuse,
        )
        lkey = jax.random.fold_in(key, depth)
        BUILD_STATS["dispatches"] += 1
        nid, preds, varimp, n_split, rec = _run_counted(
            step,
            (
                bins_u8, nid, preds, varimp, w, wy, wh, lkey,
                cols_enabled_dev, is_cat_dev,
                jnp.float32(min_rows), jnp.float32(min_split_improvement),
                jnp.float32(learn_rate), jnp.float32(max_abs_leaf),
                jnp.float32(col_sample_rate), leaf_reg,
            ),
        )
        tree.levels.append(TreeLevel(**rec))
        if force_leaf:
            break
        # Early-exit polling trades a blocking device→host pull against
        # dispatching useless empty levels. On a local CPU mesh the pull is
        # ~free, poll every level; past GBM-typical depths poll sparsely.
        if jax.default_backend() == "cpu":
            if int(n_split) == 0:
                break
        elif depth >= 8 and depth % 4 == 0 and int(n_split) == 0:
            break

    BUILD_STATS["trees_built"] += 1
    return tree, preds, varimp


# ---------------------------------------------------------------------------
# out-of-core streamed forest build (ISSUE 11, frame/chunkstore.py): the
# level math as a BLOCK-ACCUMULATE outer loop over a ChunkStore's row
# blocks. Histogram accumulation is associative over row blocks, so one
# level = Σ_blocks histogram_in_jit(block) (the existing fused histogram
# program — incl. its hist_reduce psum and the PR-9 collective lane — runs
# untouched inside each block), then ONE replicated split-scan/decide
# dispatch on the accumulated (n_pad, C, B, S) tensor (node-frontier sized,
# tiny next to the data), then one _partition_update per block. Per-row
# state (running score F, node ids) lives in the store's host tier between
# touches, so the device footprint is the HBM window, not the frame.
# Frames that fit the window never get here (ChunkStore.plan routes them
# to the resident whole-tree programs — bit-parity by construction).


def _stream_hist_prog(n_pad: int, n_bins: int):
    """One block's histogram contribution, accumulated in place: the
    donated ``acc`` buffer pipelines across block dispatches with no
    copies. Dense replicated mode — the streamed decide needs the full
    (n_pad, C, B, S) tensor on every device anyway, and it is bounded by
    the node frontier, not the rows."""
    from h2o3_tpu.ops.histogram import histogram_in_jit

    key = ("stream_hist", n_pad, n_bins, _kernel_key(), _mesh_key(),
           jax.default_backend())

    def make():
        def run(bins_u8, nid, wt, wy, wh, acc):
            return acc + histogram_in_jit(
                bins_u8, nid, (wt, wy, wh), n_pad, n_bins
            )

        return jax.jit(run, donate_argnums=(5,))

    return _cached_program(key, make)


def _stream_decide_prog(n_pad: int, n_pad_next: int, n_bins: int,
                        cat_cols: tuple, force_leaf: bool, n_cols: int,
                        mono: bool = False):
    """Split scan + leaf decision on the block-accumulated histogram —
    ``_level_core``'s math with the partition update factored out (it runs
    per block). Returns ``(varimp, n_split, record)``; with ``mono`` the
    inputs grow (mono_vec, node_lo, node_hi) and the return appends
    ``(new_lo, new_hi)`` — the constraint state is per-NODE, so it rides
    the host level loop untouched by the block structure (the ISSUE-15
    streamed-GBM gate fix)."""
    key = ("stream_decide", n_pad, n_pad_next, n_bins, cat_cols, force_leaf,
           n_cols, bool(mono), _mesh_key(), jax.default_backend())

    def make():
        def run(hist, key_, cols_enabled, is_cat, varimp, min_rows, msi,
                learn_rate, max_abs_leaf, col_sample_rate, leaf_reg=None,
                mono_vec=None, node_lo=None, node_hi=None):
            rl, ra = (None, None) if leaf_reg is None else leaf_reg
            if force_leaf:
                tot = hist[:, 0, :, :].sum(axis=1)  # col 0 ≡ any col
                ok = jnp.zeros(n_pad, bool)
                gain = jnp.zeros(n_pad, jnp.float32)
                zi = jnp.zeros(n_pad, jnp.int32)
                _, _, _, _, n_split, rec = _leaf_decide(
                    ok, gain, tot[:, 0], tot[:, 1], tot[:, 2], zi, zi,
                    jnp.zeros(n_pad, bool),
                    jnp.zeros((n_pad, n_bins), bool),
                    jnp.zeros(n_pad, bool), learn_rate, max_abs_leaf,
                    n_pad, node_lo=node_lo, node_hi=node_hi,
                    reg_lambda=rl, reg_alpha=ra,
                )
                if mono:
                    return (varimp, n_split, rec,
                            jnp.full(n_pad_next, -jnp.inf, jnp.float32),
                            jnp.full(n_pad_next, jnp.inf, jnp.float32))
                return varimp, n_split, rec
            # per-(node,col) sampling mask — same draw as _level_core at
            # the REAL column count (the streamed path never column-pads)
            col_mask = jnp.broadcast_to(cols_enabled[None, :], (n_pad, n_cols))
            keep = jax.random.uniform(key_, (n_pad, n_cols)) < col_sample_rate
            keep = jnp.where(keep.any(axis=1, keepdims=True), keep, True)
            col_mask = col_mask * keep
            sp = _split_scan(hist, is_cat, col_mask, min_rows, msi, cat_cols,
                             mono=mono_vec, node_lo=node_lo, node_hi=node_hi)
            ok = sp["ok"]
            fits = 2 * jnp.cumsum(ok.astype(jnp.int32)) <= n_pad_next
            ok = ok & fits
            gain = jnp.where(ok, jnp.maximum(sp["gain"], 0.0), 0.0)
            _, _, _, _, n_split, rec = _leaf_decide(
                ok, gain, sp["node_w"], sp["node_wy"], sp["node_wh"],
                sp["col"], sp["split_bin"], sp["is_cat"], sp["cat_mask"],
                sp["na_left"], learn_rate, max_abs_leaf, n_pad,
                node_lo=node_lo, node_hi=node_hi,
                reg_lambda=rl, reg_alpha=ra,
            )
            varimp = varimp.at[sp["col"]].add(
                jnp.where(ok, gain, 0.0).astype(varimp.dtype))
            if mono:
                new_lo, new_hi = _child_bounds(
                    ok, rec["child_base"], sp["mono_col"], sp["mid"],
                    node_lo, node_hi, n_pad_next,
                )
                return varimp, n_split, rec, new_lo, new_hi
            return varimp, n_split, rec

        return jax.jit(run)

    return _cached_program(key, make)


_STREAM_GRAD_CACHE: dict = {}


def _stream_grad_prog(grad_fn, grad_key, sample: bool, goss=None):
    """Per-block pseudo-residuals/hessians (+ the per-tree row bootstrap
    when sampling): (F, y, w, key, rate) -> (w_tree, wy, wh).

    ``goss`` ((a, b) floats) applies GOSS per BLOCK: the top-a threshold is
    taken over each block's rows rather than the whole frame — a documented
    approximation of the resident lanes' global threshold (same expected
    kept volume and amplification; the out-of-core frame never holds the
    global gradient ranking)."""
    key = ("stream_grad", grad_key, sample, goss, jax.default_backend())
    fn = _STREAM_GRAD_CACHE.get(key)
    if fn is None:

        def run(F, y, w, skey, rate):
            if sample:
                mask = jax.random.bernoulli(skey, rate, w.shape)
                wt = w * mask.astype(w.dtype)
            else:
                wt = w
            t, h = grad_fn(F, y, wt)
            wy = wt * t
            wh = jnp.where(wt > 0, h, 0.0)
            if goss is not None:
                gf = _goss_factor(
                    wt, wy, jax.random.fold_in(skey, 1 << 28), *goss
                )
                wt, wy, wh = wt * gf, wy * gf, wh * gf
            return wt, wy, wh

        fn = jax.jit(run)
        _STREAM_GRAD_CACHE[key] = fn
    return fn


def build_trees_streamed(
    store,
    n_trees: int,
    *,
    base_key,
    row_key=None,
    tree_offset: int = 0,
    grad_fn,
    grad_key,
    sample_rate: float,
    n_bins: int,
    is_cat_cols,
    max_depth: int,
    min_rows: float,
    min_split_improvement: float,
    learn_rates,
    max_abs_leaf: float,
    col_sample_rate: float,
    col_sample_rate_per_tree: float,
    varimp,
    node_cap: int = 2048,
    reg_lambda: float = 0.0,
    reg_alpha: float = 0.0,
    monotone=None,
):
    """Build ``n_trees`` trees over a :class:`~h2o3_tpu.frame.chunkstore.
    ChunkStore` whose rows exceed the HBM window.

    Lanes consumed: ``bins`` (uint8 (npad, C)), ``y``/``w``/``F`` (f32 —
    ``F`` is the running score, updated in place per level) plus the
    driver-owned scratch lanes ``wt``/``wy``/``wh`` (f32) and ``nid``
    (int32). Per tree: one gradient pass over the blocks, then per level
    one histogram-accumulate pass, one decide dispatch, one partition
    pass — O(levels · blocks) dispatches, the irreducible cost of touching
    every row per level out of core. The per-tree column subsample and the
    per-(node,col) draw use the scanned path's exact key folds; the row
    bootstrap additionally folds the block index (a per-block draw — the
    resident and streamed bootstraps are different RNG streams, same
    marginal rate).

    Returns ``(trees, varimp)`` with host-resident tree records (streamed
    frames are too big to keep per-level device state around).

    ``monotone`` ((C,) int {-1,0,1}) accepts constrained builds in the
    streamed lane (ISSUE 15): the per-node [lo, hi] bound state is
    frontier-sized — it rides the host level loop and the decide dispatch,
    untouched by the row-block structure.
    """
    from h2o3_tpu.models.tree.binning import bucket_nbins

    n_bins = bucket_nbins(n_bins)
    node_cap = _clamp_node_cap(node_cap, store.npad, min_rows)
    is_cat_np = np.asarray(is_cat_cols, bool)
    cat_cols = tuple(int(i) for i in np.nonzero(is_cat_np)[0])
    is_cat_dev = jnp.asarray(is_cat_np)
    C = len(is_cat_np)
    if row_key is None:
        row_key = base_key
    lrs = np.asarray(learn_rates, np.float32)
    leaf_reg = (
        None if reg_lambda == 0.0 and reg_alpha == 0.0
        else (jnp.float32(reg_lambda), jnp.float32(reg_alpha))
    )
    goss = _goss_ab()
    gprog = _stream_grad_prog(grad_fn, grad_key, sample_rate < 1.0, goss)
    if goss is not None:
        _ROWS_SAMPLED.inc((goss[0] + goss[1]) * store.npad * n_trees)
    mono_dev = None
    if monotone is not None and np.any(np.asarray(monotone) != 0):
        mono_dev = jnp.asarray(np.asarray(monotone, np.int32))
    trees: list[Tree] = []
    import time as _time

    for m in range(n_trees):
        g = m + tree_offset
        tkey = jax.random.fold_in(base_key, g)
        _t0 = _time.perf_counter()
        if col_sample_rate_per_tree < 1.0:
            keep = (
                jax.random.uniform(jax.random.fold_in(tkey, 1 << 30), (C,))
                < col_sample_rate_per_tree
            )
            keep = jnp.where(keep.any(), keep, True)
            cols_enabled = keep.astype(jnp.float32)
        else:
            cols_enabled = jnp.ones(C, jnp.float32)
        skey = jax.random.fold_in(jax.random.fold_in(row_key, g), 1 << 29)

        # gradient/bootstrap pass
        for bi, blk in store.stream(("F", "y", "w")):
            BUILD_STATS["dispatches"] += 1
            wt, wy, wh = gprog(
                blk["F"], blk["y"], blk["w"],
                jax.random.fold_in(skey, bi), jnp.float32(sample_rate),
            )
            store.update(bi, wt=wt, wy=wy, wh=wh)
        store.fill("nid", 0)

        tree = Tree()
        node_lo = node_hi = None
        if mono_dev is not None:
            node_lo = jnp.full(1, -jnp.inf, jnp.float32)
            node_hi = jnp.full(1, jnp.inf, jnp.float32)
        for depth in range(max_depth + 1):
            n_pad = min(1 << depth, node_cap)
            n_pad_next = min(2 * n_pad, node_cap)
            force_leaf = depth == max_depth
            hist = jnp.zeros((n_pad, C, n_bins, 3), jnp.float32)
            hprog = _stream_hist_prog(n_pad, n_bins)
            for bi, blk in store.stream(("bins", "nid", "wt", "wy", "wh")):
                BUILD_STATS["dispatches"] += 1
                hist = _run_counted(
                    hprog,
                    (blk["bins"], blk["nid"], blk["wt"], blk["wy"],
                     blk["wh"], hist),
                )
            dprog = _stream_decide_prog(
                n_pad, n_pad_next, n_bins, cat_cols, force_leaf, C,
                mono=mono_dev is not None,
            )
            BUILD_STATS["dispatches"] += 1
            dout = dprog(
                hist, jax.random.fold_in(tkey, depth), cols_enabled,
                is_cat_dev, varimp, jnp.float32(min_rows),
                jnp.float32(min_split_improvement), jnp.float32(lrs[m]),
                jnp.float32(max_abs_leaf), jnp.float32(col_sample_rate),
                leaf_reg, mono_dev, node_lo, node_hi,
            )
            if mono_dev is not None:
                varimp, n_split, rec, node_lo, node_hi = dout
            else:
                varimp, n_split, rec = dout
            for bi, blk in store.stream(("bins", "nid", "F")):
                BUILD_STATS["dispatches"] += 1
                nid_b, F_b = _partition_update(
                    blk["bins"], blk["nid"], blk["F"], rec["split_col"],
                    rec["split_bin"], rec["is_cat"], rec["cat_mask"],
                    rec["na_left"], rec["leaf_now"], rec["leaf_val"],
                    rec["child_base"],
                )
                store.update(bi, nid=nid_b, F=F_b)
            rec_host = jax.device_get(rec)
            tree.levels.append(
                TreeLevel(**{k: np.asarray(v) for k, v in rec_host.items()})
            )
            if force_leaf or int(n_split) == 0:
                break
        BUILD_STATS["trees_built"] += 1
        _FUSED_SECONDS.inc(_time.perf_counter() - _t0)
        trees.append(tree)
    return trees, varimp
