from h2o3_tpu.models.tree.gbm import GBM
from h2o3_tpu.models.tree.xgboost import XGBoost
from h2o3_tpu.models.tree.drf import DRF

__all__ = ["GBM", "DRF", "XGBoost"]
