"""TreeSHAP + tree inspection — successors of ``hex.tree.TreeSHAP*`` and
``hex.tree.TreeHandler`` [UNVERIFIED upstream paths, SURVEY.md §2.2].

``predict_contributions`` implements the exact TreeSHAP recursion (Lundberg
et al., Algorithm 2) over the recorded level arrays: per-node covers come
from the ``node_w`` histogram totals recorded during training, and split
decisions are evaluated in BIN space (the same uint8 codes the trees were
built on), so contributions are exactly consistent with prediction replay.
The local-accuracy identity Σ contributions + bias = raw margin holds to
float tolerance, matching the upstream contract.

``tree_view`` is the TreeHandler analog: a node-table dump of one tree
(ids, features, thresholds/level-sets, NA direction, leaf predictions).
"""

from __future__ import annotations

import numpy as np

from h2o3_tpu.frame.frame import Frame, Vec
from h2o3_tpu.genmodel import goes_left


class _Node:
    __slots__ = ("feature", "thr_bin", "is_cat", "cat_mask", "na_left",
                 "left", "right", "value", "cover", "is_leaf")


def _tree_nodes(tree) -> list[_Node]:
    """Flatten level arrays into an explicit node list (root = 0)."""
    host = tree.to_host()
    nodes: list[_Node] = []
    # frontier ids per level → node-list indices
    prev_ids: list[int] = []
    for li, lv in enumerate(host.levels):
        width = len(lv.split_col)
        cur_ids = []
        for i in range(width):
            nd = _Node()
            nd.is_leaf = bool(lv.leaf_now[i])
            nd.value = float(lv.leaf_val[i])
            nd.cover = float(lv.node_w[i]) if lv.node_w is not None else 0.0
            nd.feature = int(lv.split_col[i])
            nd.thr_bin = int(lv.split_bin[i])
            nd.is_cat = bool(lv.is_cat[i])
            nd.cat_mask = np.asarray(lv.cat_mask[i])
            nd.na_left = bool(lv.na_left[i])
            nd.left = nd.right = -1
            cur_ids.append(len(nodes))
            nodes.append(nd)
        if li > 0:
            plv = host.levels[li - 1]
            for pi, pid in enumerate(prev_ids):
                if not nodes[pid].is_leaf:
                    base = int(plv.child_base[pi])
                    nodes[pid].left = cur_ids[base]
                    nodes[pid].right = cur_ids[base + 1]
        prev_ids = cur_ids
    # prune: nodes with cover 0 that are leaves with value 0 are padding, but
    # they are unreachable from the root walk, so no pruning is needed.
    return nodes


def _goes_left(nd: _Node, b: int) -> bool:
    if b == 0:
        return nd.na_left
    if nd.is_cat:
        return bool(nd.cat_mask[b])
    return b <= nd.thr_bin


def _shap_one_tree(nodes: list[_Node], bins_row: np.ndarray, phi: np.ndarray):
    """Exact TreeSHAP (Lundberg Alg. 2) for one row over one tree."""

    # unique-path arrays: feature index d, fraction zero z, fraction one o, weight w
    def recurse(j, m, pd, pz, po, pw, pi1, pz1, po1):
        # m: path length; arrays copied per call (trees are shallow)
        pd = pd + [pi1]
        pz = pz + [pz1]
        po = po + [po1]
        pw = pw + [1.0 if m == 0 else 0.0]
        for i in range(m - 1, -1, -1):
            pw[i + 1] += po1 * pw[i] * (i + 1) / (m + 1)
            pw[i] = pz1 * pw[i] * (m - i) / (m + 1)

        nd = nodes[j]
        if nd.is_leaf:
            for i in range(1, m + 1):
                wsum = _unwound_sum(pd, pz, po, pw, m, i)
                phi[pd[i]] += wsum * (po[i] - pz[i]) * nd.value
            return
        b = int(bins_row[nd.feature])
        hot, cold = (nd.left, nd.right) if _goes_left(nd, b) else (nd.right, nd.left)
        hot_cover = nodes[hot].cover
        cold_cover = nodes[cold].cover
        parent_cover = nd.cover if nd.cover > 0 else hot_cover + cold_cover
        iz, io = 1.0, 1.0
        k = _path_index(pd, nd.feature, m)
        if k >= 0:  # feature already on the path: unwind it first
            iz, io = pz[k], po[k]
            pd, pz, po, pw, m2 = _unwind(pd, pz, po, pw, m, k)
            m = m2
        denom = parent_cover if parent_cover > 0 else 1.0
        recurse(hot, m + 1, pd, pz, po, pw, nd.feature, iz * hot_cover / denom, io)
        recurse(cold, m + 1, pd, pz, po, pw, nd.feature, iz * cold_cover / denom, 0.0)

    recurse(0, 0, [], [], [], [], -1, 1.0, 1.0)


def _path_index(pd, feature, m):
    for i in range(1, m + 1):
        if pd[i] == feature:
            return i
    return -1


def _unwind(pd, pz, po, pw, m, i):
    pd, pz, po, pw = list(pd), list(pz), list(po), list(pw)
    n = pw[m]
    for j in range(m - 1, -1, -1):
        if po[i] != 0:
            t = pw[j]
            pw[j] = n * (m + 1) / ((j + 1) * po[i])
            n = t - pw[j] * pz[i] * (m - j) / (m + 1)
        else:
            pw[j] = pw[j] * (m + 1) / (pz[i] * (m - j)) if pz[i] * (m - j) != 0 else pw[j]
    for j in range(i, m):
        pd[j] = pd[j + 1]
        pz[j] = pz[j + 1]
        po[j] = po[j + 1]
    return pd[:m], pz[:m], po[:m], pw[:m], m - 1


def _unwound_sum(pd, pz, po, pw, m, i):
    total = 0.0
    n = pw[m]
    if po[i] != 0:
        for j in range(m - 1, -1, -1):
            tmp = n / ((j + 1) * po[i]) * (m + 1)
            total += tmp
            n = pw[j] - tmp * pz[i] * (m - j) / (m + 1)
    else:
        for j in range(m - 1, -1, -1):
            if pz[i] * (m - j) != 0:
                total += pw[j] * (m + 1) / (pz[i] * (m - j))
    return total


def predict_contributions(model, frame: Frame) -> Frame:
    """Per-feature SHAP contributions on the margin scale + BiasTerm.

    Local accuracy: row-sum of the output equals the raw margin (before the
    link) that prediction replay produces. Supported for regression and
    binomial GBM/DRF (H2O's predict_contributions contract).
    """
    from h2o3_tpu.models.tree.binning import bin_frame

    out = model.output
    if out.get("n_tree_classes", 1) > 1:
        raise ValueError("predict_contributions supports regression/binomial models only")
    spec = out["bin_spec"]
    bins = np.asarray(bin_frame(spec, frame))[: frame.nrow]
    names = out["names"]
    C = len(names)
    n = frame.nrow

    phi = np.zeros((n, C + 1))  # + BiasTerm
    bias = 0.0
    for group in out["trees"]:
        nodes = _tree_nodes(group[0])
        # E[tree] under the cover distribution = bias contribution
        exp_val = _expected_value(nodes, 0)
        bias += exp_val
        for r in range(n):
            row_phi = np.zeros(C + 1)
            _shap_one_tree(nodes, bins[r], row_phi[:C])
            phi[r, :C] += row_phi[:C]
    if model.algo == "gbm":
        bias += float(np.asarray(out["init_f"]))
    ntrees = max(out["ntrees_actual"], 1)
    if model.algo in ("drf", "xrt"):
        phi[:, :C] /= ntrees
        bias /= ntrees
    phi[:, C] = bias
    return Frame(
        [Vec.from_numpy(phi[:, j], "real") for j in range(C + 1)],
        list(names) + ["BiasTerm"],
    )


def predict_leaf_node_assignment(model, frame: Frame, type: str = "Path") -> Frame:
    """Per-row terminal leaf of every tree — ``predict_leaf_node_assignment``
    [UNVERIFIED upstream hex/Model.java LeafNodeAssignment]: one column per
    (tree, class) named ``T{i}.C{k}``, either the root-to-leaf decision
    string ("LRLL", type="Path") or the node's index in the flattened node
    list (type="Node_ID"). The walk is vectorized numpy over the flattened
    node arrays (analysis-scale op; the hot scoring path stays on device).
    """
    from h2o3_tpu.models.tree.binning import bin_frame

    if type not in ("Path", "Node_ID"):
        raise ValueError(f"type must be 'Path' or 'Node_ID', got {type!r}")
    out = model.output
    spec = out["bin_spec"]
    bins = np.asarray(bin_frame(spec, frame))[: frame.nrow]  # (n, C) uint8
    trees = out["trees"]  # [iteration][class]
    K = out.get("n_tree_classes", 1)
    n = frame.nrow
    rows = np.arange(n)

    vecs = []
    names = []
    for ti, group in enumerate(trees):
        for k in range(K):
            nodes = _tree_nodes(group[k])
            feat = np.array([nd.feature for nd in nodes], np.int64)
            thr = np.array([nd.thr_bin for nd in nodes], np.int64)
            is_cat = np.array([nd.is_cat for nd in nodes], bool)
            na_left = np.array([nd.na_left for nd in nodes], bool)
            left = np.array([nd.left for nd in nodes], np.int64)
            right = np.array([nd.right for nd in nodes], np.int64)
            is_leaf = np.array([nd.is_leaf for nd in nodes], bool)
            # bin-adaptive levels record NARROWER cat_mask than full-bin
            # levels (numeric-only coarsening; the masks are unused there)
            # — pad to the widest so the stack is rectangular, same as
            # export.py does for the tmojo archive
            W = max(nd.cat_mask.shape[0] for nd in nodes)
            cat_mask = np.stack([
                np.pad(nd.cat_mask, (0, W - nd.cat_mask.shape[0]))
                for nd in nodes
            ])  # (N, W)

            depth = len(group[k].levels)
            cur = np.zeros(n, np.int64)
            steps = np.full((n, max(depth, 1)), "", dtype="<U1")
            for step in range(depth):
                at_leaf = is_leaf[cur]
                if at_leaf.all():
                    break
                b = bins[rows, feat[cur]].astype(np.int64)
                gl = goes_left(b, na_left[cur], cat_mask[cur, b], is_cat[cur],
                               thr[cur])
                adv = ~at_leaf
                steps[adv, step] = np.where(gl[adv], "L", "R")
                cur = np.where(adv, np.where(gl, left[cur], right[cur]), cur)

            name = f"T{ti + 1}.C{k + 1}"
            names.append(name)
            if type == "Node_ID":
                vecs.append(Vec.from_numpy(cur, "int", name=name))
            else:
                paths = np.array(["".join(r) for r in steps], dtype=object)
                domain = sorted(set(paths))
                codes = np.searchsorted(domain, paths)
                vecs.append(
                    Vec.from_numpy(codes, "enum", name=name, domain=tuple(domain))
                )
    return Frame(vecs, names)


def _expected_value(nodes: list[_Node], j: int) -> float:
    nd = nodes[j]
    if nd.is_leaf:
        return nd.value
    lc, rc = nodes[nd.left].cover, nodes[nd.right].cover
    tot = lc + rc
    if tot <= 0:
        return nd.value
    return (lc * _expected_value(nodes, nd.left) + rc * _expected_value(nodes, nd.right)) / tot


def tree_view(model, tree_number: int = 0, tree_class: int = 0) -> dict:
    """TreeHandler-style node table for one tree: parallel arrays keyed by
    node id (root 0, breadth-first)."""
    out = model.output
    tree = out["trees"][tree_number][tree_class]
    nodes = _tree_nodes(tree)
    names = out["names"]
    spec = out["bin_spec"]
    # breadth-first reachability from the root: level arrays are padded to
    # 2^depth slots and phantom nodes must not appear in the table
    reachable = set()
    stack = [0] if nodes else []
    while stack:
        i = stack.pop()
        reachable.add(i)
        nd = nodes[i]
        if not nd.is_leaf:
            stack.extend([nd.left, nd.right])
    rows = {
        "node_id": [], "left_child": [], "right_child": [], "feature": [],
        "threshold": [], "na_direction": [], "prediction": [], "cover": [],
        "is_leaf": [], "levels": [],
    }
    for i, nd in enumerate(nodes):
        if i not in reachable:
            continue
        rows["node_id"].append(i)
        rows["left_child"].append(nd.left)
        rows["right_child"].append(nd.right)
        rows["is_leaf"].append(nd.is_leaf)
        rows["prediction"].append(nd.value if nd.is_leaf else None)
        rows["cover"].append(nd.cover)
        if nd.is_leaf:
            rows["feature"].append(None)
            rows["threshold"].append(None)
            rows["na_direction"].append(None)
            rows["levels"].append(None)
            continue
        rows["feature"].append(names[nd.feature])
        rows["na_direction"].append("LEFT" if nd.na_left else "RIGHT")
        if nd.is_cat:
            dom = (spec.domains[nd.feature] or ()) if spec.domains else ()
            left_levels = [
                dom[b - 1] for b in range(1, len(nd.cat_mask))
                if nd.cat_mask[b] and b - 1 < len(dom)
            ]
            rows["threshold"].append(None)
            rows["levels"].append(left_levels)
        else:
            e = spec.edges[nd.feature]
            t = nd.thr_bin - 1  # left iff bin <= thr_bin; edge index
            thr = float(e[t]) if 0 <= t < len(e) and np.isfinite(e[t]) else float("inf")
            rows["threshold"].append(thr)
            rows["levels"].append(None)
    return rows
