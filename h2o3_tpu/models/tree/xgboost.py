"""XGBoost parameter surface mapped onto the TPU histogram tree engine —
successor of ``h2o-ext-xgboost`` (``hex/tree/xgboost/XGBoost.java``,
``XGBoostModel.java`` parameter mapping [UNVERIFIED upstream paths,
SURVEY.md §2.2/§2.4, §7 step 9]).

Upstream bundles the native xgboost library and translates H2O params onto
it; its ``gpu_hist`` CUDA builder is exactly what our Pallas histogram
kernel replaces (SURVEY §2.4). Here the translation runs the other
direction: the xgboost-style surface (``eta``, ``subsample``,
``colsample_bytree``, ``min_child_weight``, ``max_bin``, ``gamma``,
``reg_lambda``/``reg_alpha``, ``tree_method=hist``, ``scale_pos_weight``)
maps onto the SAME engine H2O GBM uses — one histogram tree builder, two
param dialects, like upstream where both route into SharedTree-shaped code.

Engine-semantic notes (documented deviations):
- ``tree_method``: only ``hist`` semantics exist (static quantile binning).
  ``auto``/``hist`` run as-is; ``exact``/``approx`` log a warning and use
  hist — mirroring upstream's behavior on big data, where H2O XGBoost
  forces hist.
- ``reg_lambda``/``reg_alpha`` apply xgboost's leaf-value formula
  w* = soft_threshold(Σ grad, α) / (Σ hess + λ) (see
  ``shared_tree._finish_level``); split selection keeps H2O's SE gain —
  λ/α do not enter the gain scan.
- ``min_child_weight`` is H2O's ``min_rows`` (upstream H2O XGBoost declares
  them synonyms): the constraint is on Σ row-weight per child, not Σ hess.
- ``grow_policy=lossguide``/``max_leaves`` are not supported (depth-wise
  builder); ``booster`` must be ``gbtree`` (no dart/gblinear).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

from h2o3_tpu.models.tree.binning import MAX_BINS
from h2o3_tpu.models.tree.gbm import GBM, GBMModel, GBMParams
from h2o3_tpu.utils.log import Log

# xgboost name -> canonical GBMParams field it aliases
_ALIASES = {
    "eta": "learn_rate",
    "subsample": "sample_rate",
    "colsample_bytree": "col_sample_rate_per_tree",
    "colsample_bylevel": "col_sample_rate",
    "min_child_weight": "min_rows",
    "max_bin": "nbins",
    "gamma": "min_split_improvement",
    "max_delta_step": "max_abs_leafnode_pred",  # 0=unlimited special-cased below
    "n_estimators": "ntrees",
}


@dataclass
class XGBoostParams(GBMParams):
    # xgboost defaults where they differ from H2O GBM's
    ntrees: int = 50
    max_depth: int = 6
    learn_rate: float = 0.3  # xgboost eta default
    min_rows: float = 1.0  # xgboost min_child_weight default
    min_split_improvement: float = 0.0  # xgboost gamma default
    reg_lambda: float = 1.0  # xgboost L2 default
    reg_alpha: float = 0.0
    tree_method: str = "auto"  # auto|hist|exact|approx (exact/approx -> hist)
    grow_policy: str = "depthwise"
    booster: str = "gbtree"
    scale_pos_weight: float = 1.0  # >0 (xgboost positive-class weight)
    dmatrix_type: str = "auto"  # accepted for surface parity; dense engine


class XGBoostModel(GBMModel):
    algo = "xgboost"


class XGBoost(GBM):
    """``H2OXGBoostEstimator``-compatible builder on the hist engine."""

    algo = "xgboost"
    PARAMS_CLS = XGBoostParams
    MODEL_CLS = XGBoostModel
    PARAM_ALIASES = _ALIASES  # estimator layer accepts the xgboost names too

    def __init__(self, **kwargs: Any):
        if "max_delta_step" in kwargs:
            mds = float(kwargs.pop("max_delta_step"))
            if mds < 0:
                raise ValueError("max_delta_step must be >= 0")
            if mds == 0:  # xgboost convention: 0 means unconstrained
                pass
            elif "max_abs_leafnode_pred" in kwargs:
                raise ValueError(
                    "'max_delta_step' and 'max_abs_leafnode_pred' are aliases — pass one"
                )
            else:
                kwargs["max_abs_leafnode_pred"] = mds
        for xgb_name, h2o_name in _ALIASES.items():
            if xgb_name == "max_delta_step":
                continue  # handled above
            if xgb_name in kwargs:
                if h2o_name in kwargs:
                    raise ValueError(
                        f"{xgb_name!r} and {h2o_name!r} are aliases — pass one"
                    )
                kwargs[h2o_name] = kwargs.pop(xgb_name)
        super().__init__(**kwargs)
        p: XGBoostParams = self.params
        if p.booster != "gbtree":
            raise ValueError(
                f"booster={p.booster!r} is not supported (gbtree only; "
                "dart/gblinear have no engine here)"
            )
        if p.grow_policy not in ("depthwise",):
            raise ValueError(
                "grow_policy='lossguide' is not supported (depth-wise builder)"
            )
        if p.tree_method not in ("auto", "hist", "exact", "approx"):
            raise ValueError(f"unknown tree_method {p.tree_method!r}")
        if p.scale_pos_weight <= 0:
            raise ValueError("scale_pos_weight must be > 0")
        if p.tree_method in ("exact", "approx"):
            Log.warn(
                f"tree_method={p.tree_method!r} has no exact-split engine; "
                "using hist (static quantile bins) — upstream H2O XGBoost "
                "likewise forces hist on large data"
            )
        if p.nbins > MAX_BINS:
            Log.warn(f"max_bin={p.nbins} clamped to engine maximum {MAX_BINS}")
            p.nbins = MAX_BINS
        if p.monotone_constraints and (p.reg_lambda or p.reg_alpha):
            # both paths exist but the mono level loop applies reg to leaf
            # values only, same as the fused path — nothing to reject; just
            # make the combination visible in logs for parity debugging
            Log.info(
                "XGBoost monotone_constraints with reg_lambda/reg_alpha: "
                "regularized leaves + constraint clamping"
            )
