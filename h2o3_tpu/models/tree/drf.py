"""DRF (distributed random forest) — successor of ``hex.tree.drf.DRF`` /
``DRFModel`` [UNVERIFIED upstream paths, SURVEY.md §2.2] on the shared
level-wise histogram builder.

Differences from GBM, mirroring H2O: bootstrap row sampling per tree
(``sample_rate`` without replacement ≈ bernoulli mask), per-split column
subsampling (``mtries``: √C for classification, C/3 for regression), deep
trees (default depth 20, enabled by the active-leaf frontier), leaf values =
node means (learn_rate 1), predictions averaged across trees; for multiclass
one tree per class per iteration on the one-hot indicator.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.cluster.job import Job
from h2o3_tpu.cluster.registry import DKV
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.model_base import ScoreKeeper, stopping_metric_direction
from h2o3_tpu.models.tree.binning import bin_frame, fit_bins, fit_bins_for
from h2o3_tpu.models.tree.gbm import SharedTreeModel, SharedTreeParams
from h2o3_tpu.models.tree.shared_tree import Tree, build_tree
from h2o3_tpu.models import metrics as MM
from h2o3_tpu.models.model_base import ModelBuilder
from h2o3_tpu.utils import faults
from h2o3_tpu.utils.log import Log


@dataclass
class DRFParams(SharedTreeParams):
    ntrees: int = 50
    max_depth: int = 20
    min_rows: float = 1.0
    mtries: int = -1
    sample_rate: float = 0.632
    binomial_double_trees: bool = False


class DRFModel(SharedTreeModel):
    algo = "drf"

    def _predict_raw(self, frame: Frame) -> np.ndarray:
        return np.asarray(self._predict_raw_dev(frame))

    def _predict_raw_dev(self, frame: Frame):
        # sum of per-tree leaf means, averaged
        raw = self._replay_all_dev(frame)[: frame.nrow]
        ntrees = max(self.output["ntrees_actual"], 1)
        avg = raw / ntrees
        if not self.is_classifier:
            return avg
        if self.nclasses == 2:
            p1 = jnp.clip(avg, 0.0, 1.0)
            return jnp.stack([1 - p1, p1], axis=1)
        P = jnp.clip(avg, 1e-9, None)
        return P / P.sum(axis=1, keepdims=True)


class DRF(ModelBuilder):
    algo = "drf"
    PARAMS_CLS = DRFParams
    MODEL_CLS = DRFModel

    # XRT ("extremely randomized trees") reuses this builder via the
    # histogram_type=Random analog — see XRT subclass below.
    _extra_random = False

    def _partial_model(self, key, p, spec, trees, n_out, domain, F, yn, wn,
                       nrow, K, classification, varimp_dev, history):
        """Interval-snapshot factory (see GBM._partial_model)."""
        out = {
            "bin_spec": spec,
            "trees": [list(g) for g in trees],
            "n_tree_classes": n_out,
            "names": list(self._x),
            "varimp": np.asarray(varimp_dev).astype(np.float64),
            "response_domain": domain,
            "ntrees_actual": len(trees),
        }
        m = self.MODEL_CLS(key, p, out)
        m.scoring_history = list(history)
        m.training_metrics = self._metrics_from_F(
            F, yn, wn, nrow, max(len(trees), 1), K, classification, domain=domain
        )
        return m

    def _build(self, job: Job, train: Frame, valid: Frame | None):
        p: DRFParams = self.params
        if p.ntrees < 1 or p.max_depth < 1:
            raise ValueError("ntrees and max_depth must be >= 1")
        yv = train.vec(p.response_column)
        classification = yv.is_categorical()
        K = yv.cardinality if classification and yv.cardinality > 2 else 1
        binary = classification and K == 1

        from h2o3_tpu.models.model_base import check_checkpoint_compat, resolve_checkpoint

        prior = resolve_checkpoint(p.checkpoint)
        if prior is not None:
            check_checkpoint_compat(
                prior, self,
                ("max_depth", "nbins", "min_rows", "mtries", "sample_rate"),
            )
            if p.ntrees <= prior.output["ntrees_actual"]:
                raise ValueError(
                    f"checkpoint continuation needs ntrees > {prior.output['ntrees_actual']}"
                )
            spec = prior.output["bin_spec"]
        else:
            spec = fit_bins_for(p, train, self._x)
        bins = bin_frame(spec, train)
        n_bins = spec.max_bins
        npad = train.npad
        C = len(self._x)

        mtries = p.mtries
        if mtries in (-1, 0):
            mtries = max(1, int(np.sqrt(C))) if classification else max(1, C // 3)
        elif mtries == -2:
            mtries = C
        col_rate = min(1.0, mtries / C)

        y_np = yv.to_numpy().astype(np.float64)
        w_np = np.zeros(npad, np.float32)
        w_np[: train.nrow] = 1.0
        if p.weights_column:
            w_np[: train.nrow] *= np.nan_to_num(
                train.vec(p.weights_column).to_numpy()
            ).astype(np.float32)
        w_np[: train.nrow] *= (y_np >= 0) if classification else ~np.isnan(y_np)
        ybuf = np.zeros(npad, np.float32)
        ybuf[: train.nrow] = np.nan_to_num(y_np, nan=0.0)
        w = jnp.asarray(w_np)
        y = jnp.asarray(ybuf)
        wn, yn = w_np, ybuf  # host copies already exist — never pull from device

        rngkey = jax.random.PRNGKey(abs(p.seed) if p.seed and p.seed > 0 else 5678)

        n_out = K if K > 1 else 1
        F = [jnp.zeros(npad, jnp.float32) for _ in range(n_out)]
        if K > 1:
            targets = [(y == k).astype(jnp.float32) for k in range(K)]
        else:
            targets = [y]

        metric_name, larger = stopping_metric_direction(
            p.stopping_metric, classification, K or 2
        )
        keeper = ScoreKeeper(p.stopping_rounds, p.stopping_tolerance, larger)
        trees: list[list[Tree]] = []
        varimp_dev = jnp.zeros(C, jnp.float32)
        history: list[dict] = []

        bins_v = yv_np = wv_np = Fv = None
        if valid is not None:
            bins_v = bin_frame(spec, valid)
            vv = valid.vec(p.response_column)
            from h2o3_tpu.models.model_base import _remap_response

            yv_np = (
                _remap_response(vv, yv.domain).astype(np.float64)
                if classification
                else vv.to_numpy().astype(np.float64)
            )
            wv_np = np.ones(valid.nrow, np.float32)
            Fv = [jnp.zeros(bins_v.shape[0], jnp.float32) for _ in range(n_out)]

        start_trees = 0
        if prior is not None:
            raw = prior._replay_all_dev(train)  # (npad,) or (npad, K) leaf-sum
            F = [raw[:, k] for k in range(K)] if n_out > 1 else [raw]
            trees.extend([list(g) for g in prior.output["trees"]])
            varimp_dev = jnp.asarray(np.asarray(prior.output["varimp"], np.float32))
            start_trees = prior.output["ntrees_actual"]
            if Fv is not None:
                rawv = prior._replay_all_dev(valid)
                Fv = [rawv[:, k] for k in range(K)] if n_out > 1 else [rawv]
            from h2o3_tpu.models.tree.shared_tree import use_fused_trees

            if not use_fused_trees(p.max_depth):
                # only the per-tree loop consumes the split chain; the
                # scanned path keys by global tree id off the pristine key
                for _ in range(start_trees):
                    rngkey, _ = jax.random.split(rngkey)

        # Chunk-scanned path (see gbm.py / build_trees_scanned): one device
        # dispatch per scoring interval per class, on every backend. The
        # bootstrap row mask is keyed by the shared row_key so all K
        # class-trees of iteration m draw the SAME bootstrap (H2O
        # semantics), while column/level randomness differs per class.
        # depth policy lives in use_fused_trees (depth-20 DRF — the H2O
        # default regime — runs its saturated levels as an on-device
        # lax.while_loop with early exit, so the scanned path holds at any
        # depth; H2O3_TPU_WHOLE_TREE=0 restores the per-level loop)
        from h2o3_tpu.models.tree.shared_tree import use_fused_trees

        use_scan = use_fused_trees(p.max_depth)
        if use_scan:
            from h2o3_tpu.models.tree.shared_tree import (
                build_trees_scanned,
                replay_batch,
                scan_chunk_cap,
                trees_from_stacked,
            )

            cap = scan_chunk_cap(p.max_depth, n_bins)
            interval = max(1, p.score_tree_interval)
            m_done = start_trees
            # first chunk always runs (≥1 tree even if max_runtime expired
            # during setup — upstream keeps a non-empty partial model)
            while m_done < p.ntrees and (
                m_done == start_trees or not job.stop_requested
            ):
                chunk = min(interval, cap, p.ntrees - m_done)
                chunk_trees: list[list[Tree]] = [[] for _ in range(chunk)]
                for k in range(n_out):
                    F[k], varimp_dev, stacked = build_trees_scanned(
                        bins, w, targets[k], F[k], varimp_dev,
                        jax.random.fold_in(rngkey, 7919 + k), chunk,
                        row_key=rngkey,
                        tree_offset=m_done,
                        grad_fn=lambda F_, y_, w_: (y_, w_),  # leaf = node mean
                        grad_key=("drf",),
                        sample_rate=p.sample_rate,
                        n_bins=n_bins,
                        is_cat_cols=spec.is_cat,
                        max_depth=p.max_depth,
                        min_rows=p.min_rows,
                        min_split_improvement=p.min_split_improvement,
                        learn_rates=np.ones(chunk, np.float32),
                        max_abs_leaf=float("inf"),
                        col_sample_rate=col_rate,
                        col_sample_rate_per_tree=1.0,
                    )
                    for ti, tr in enumerate(trees_from_stacked(stacked, chunk)):
                        chunk_trees[ti].append(tr)
                    if Fv is not None:
                        Fv[k] = replay_batch(bins_v, stacked, Fv[k])
                trees.extend(chunk_trees)
                m_done += chunk

                mval = self._train_metric(
                    F, yn, wn, train.nrow, m_done, K, classification, metric_name
                )
                entry = {"ntrees": m_done, f"training_{metric_name}": mval}
                stop_val = mval
                if Fv is not None:
                    vval = self._train_metric(
                        Fv, yv_np, wv_np, valid.nrow, m_done, K, classification,
                        metric_name,
                    )
                    entry[f"validation_{metric_name}"] = vval
                    stop_val = vval
                history.append(entry)
                keeper.record(stop_val)
                self._export_interval_checkpoint(
                    job,
                    lambda key: self._partial_model(
                        key, p, spec, trees, n_out,
                        tuple(yv.domain) if classification else None,
                        F, yn, wn, train.nrow, K, classification,
                        varimp_dev, history,
                    ),
                )
                faults.die_check(self.algo)  # chaos: worker death at boundary
                faults.abort_check(self.algo, m_done)
                faults.slow_check(self.algo)  # chaos: slow training interval
                if keeper.should_stop():
                    Log.info(f"DRF early stop at {m_done} trees")
                    break
                job.update(0.05 + 0.9 * m_done / p.ntrees)

        for m in range(start_trees if not use_scan else p.ntrees, p.ntrees):
            if job.stop_requested and m > start_trees:
                break  # always ≥1 tree (see scan loop comment)
            rngkey, sk = jax.random.split(rngkey)
            mask = jax.random.bernoulli(sk, p.sample_rate, (npad,)).astype(jnp.float32)
            w_tree = w * mask
            group = []
            tree_key = jax.random.fold_in(rngkey, m)
            for k in range(n_out):
                tree, fk, varimp_dev = build_tree(
                    bins,
                    w_tree,
                    targets[k],
                    w_tree,  # hessian = weight → leaf = node mean
                    n_bins=n_bins,
                    is_cat_cols=spec.is_cat,
                    max_depth=p.max_depth,
                    min_rows=p.min_rows,
                    min_split_improvement=p.min_split_improvement,
                    learn_rate=1.0,
                    preds=F[k],
                    key=jax.random.fold_in(tree_key, k),
                    varimp=varimp_dev,
                    col_sample_rate=col_rate,
                )
                group.append(tree)
                F[k] = fk
            trees.append(group)

            if Fv is not None:
                for k, tree in enumerate(group):
                    _, Fv[k] = tree.replay(
                        bins_v, jnp.zeros(bins_v.shape[0], jnp.int32), Fv[k]
                    )

            if (m + 1) % max(1, p.score_tree_interval) == 0 or m == p.ntrees - 1:
                mval = self._train_metric(F, yn, wn, train.nrow, m + 1, K, classification, metric_name)
                entry = {"ntrees": m + 1, f"training_{metric_name}": mval}
                stop_val = mval
                if Fv is not None:
                    vval = self._train_metric(
                        Fv, yv_np, wv_np, valid.nrow, m + 1, K, classification, metric_name
                    )
                    entry[f"validation_{metric_name}"] = vval
                    stop_val = vval
                history.append(entry)
                keeper.record(stop_val)
                self._export_interval_checkpoint(
                    job,
                    lambda key: self._partial_model(
                        key, p, spec, trees, n_out,
                        tuple(yv.domain) if classification else None,
                        F, yn, wn, train.nrow, K, classification,
                        varimp_dev, history,
                    ),
                )
                faults.die_check(self.algo)  # chaos: worker death at boundary
                faults.abort_check(self.algo, m + 1)
                faults.slow_check(self.algo)  # chaos: slow training interval
                if keeper.should_stop():
                    Log.info(f"DRF early stop at {m + 1} trees")
                    break
            job.update(0.05 + 0.9 * (m + 1) / p.ntrees)

        out = {
            "bin_spec": spec,
            "trees": trees,
            "n_tree_classes": n_out,
            "names": list(self._x),
            "varimp": np.asarray(varimp_dev).astype(np.float64),
            "response_domain": tuple(yv.domain) if classification else None,
            "ntrees_actual": len(trees),
        }
        model = DRFModel(DKV.make_key("drf"), p, out)
        model.scoring_history = history
        nt = max(len(trees), 1)
        dom = out["response_domain"]
        model.training_metrics = self._metrics_from_F(
            F, yn, wn, train.nrow, nt, K, classification, domain=dom
        )
        if valid is not None:
            model.validation_metrics = self._metrics_from_F(
                Fv, yv_np, wv_np, valid.nrow, nt, K, classification, domain=dom
            )
        from h2o3_tpu.models.calibration import maybe_fit_calibration

        maybe_fit_calibration(self, model)
        return model

    def _metrics_from_F(self, F, yn, wn, nrow, ntrees, K, classification, domain=None):
        """Full ModelMetrics from the running per-class sums (no replay)."""
        dev = jax.default_backend() != "cpu"
        avg = [(f[:nrow] if dev else np.asarray(f)[:nrow]) / ntrees for f in F]
        xp = jnp if dev else np
        if K > 1:
            P = xp.stack(avg, axis=1)
            P = xp.clip(P, 1e-9, None)
            P = P / P.sum(axis=1, keepdims=True)
            return MM.multinomial_metrics(
                yn[:nrow].astype(np.int64), P, wn[:nrow], domain=domain or ()
            )
        if classification:
            p1 = xp.clip(avg[0], 0.0, 1.0)
            return MM.binomial_metrics(
                yn[:nrow], p1, wn[:nrow], domain=domain or ("0", "1")
            )
        return MM.regression_metrics(yn[:nrow], avg[0], wn[:nrow])

    def _train_metric(self, F, yn, wn, nrow, ntrees, K, classification, metric_name) -> float:
        m = self._metrics_from_F(F, yn, wn, nrow, ntrees, K, classification)
        v = m._v.get(metric_name)
        if v is None:
            v = m._v.get("logloss" if classification else "rmse")
        return float(v)


class XRT(DRF):
    """Extremely-randomized-trees variant — H2O exposes XRT as DRF with
    ``histogram_type="Random"`` (random split points). Approximated here by
    stronger per-split column subsampling plus a distinct seed stream; true
    random-threshold selection is a planned histogram option."""

    algo = "xrt"
    _extra_random = True
