"""GBM distribution zoo — successor of H2O's ``DistributionFactory`` /
per-distribution gradient & GammaPass leaf math used by ``hex.tree.gbm.GBM``
[UNVERIFIED upstream paths, SURVEY.md §2.2].

Each distribution yields per-row (target t, hessian h) at the current raw
score F, plus the init score and the response transform for prediction.
Leaf values are Newton steps Σ(w·t)/Σh computed from the same histogram
stats (h2o's GammaPass folded into the histogram pass). Deviations from
h2o's exact leaf formulas (e.g. laplace's median leaves) are noted inline.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

_EPS = 1e-10


@partial(jax.jit, static_argnames=("dist",))
def grad_hess(dist: str, f, y, w, aux: float = 0.0):
    """Per-row pseudo-residual target and hessian for the next tree."""
    if dist == "gaussian":
        return y - f, w
    if dist == "bernoulli":
        p = jax.nn.sigmoid(f)
        return y - p, w * jnp.maximum(p * (1 - p), _EPS)
    if dist == "poisson":
        mu = jnp.exp(f)
        return y - mu, w * jnp.maximum(mu, _EPS)
    if dist == "gamma":
        e = jnp.exp(-f) * y
        return e - 1.0, w * jnp.maximum(e, _EPS)
    if dist == "tweedie":
        p = aux
        a = y * jnp.exp((1.0 - p) * f)
        b = jnp.exp((2.0 - p) * f)
        return a - b, w * jnp.maximum((2.0 - p) * b - (1.0 - p) * a, _EPS)
    if dist == "laplace":
        # gradient step on sign; h2o refits leaf medians [deviation noted]
        return jnp.sign(y - f), w
    if dist == "quantile":
        alpha = aux
        return jnp.where(y > f, alpha, alpha - 1.0), w
    if dist == "huber":
        delta = aux
        r = y - f
        return jnp.clip(r, -delta, delta), w
    raise ValueError(f"unknown distribution {dist}")


@partial(jax.jit, static_argnames=("K",))
def multinomial_grad_hess(F, Y1h, w, K: int):
    """(npad,K) targets/hessians; h scaled so Newton leaves carry the
    (K-1)/K LogitBoost factor h2o applies."""
    P = jax.nn.softmax(F, axis=1)
    T = Y1h - P
    H = w[:, None] * jnp.maximum(P * (1 - P), _EPS) * (K / max(K - 1.0, 1.0))
    return T, H


def init_score(dist: str, y: np.ndarray, w: np.ndarray, aux: float = 0.0) -> float:
    """f0 — the init value (h2o's initial prediction per distribution)."""
    sw = w.sum()
    mean = float((w * y).sum() / max(sw, _EPS))
    if dist == "gaussian" or dist == "huber":
        return mean
    if dist == "bernoulli":
        p = min(max(mean, 1e-6), 1 - 1e-6)
        return float(np.log(p / (1 - p)))
    if dist in ("poisson", "gamma", "tweedie"):
        return float(np.log(max(mean, _EPS)))
    if dist == "laplace":
        return float(_weighted_quantile(y, w, 0.5))
    if dist == "quantile":
        return float(_weighted_quantile(y, w, aux))
    raise ValueError(dist)


def _weighted_quantile(y, w, q):
    order = np.argsort(y)
    cw = np.cumsum(w[order])
    return y[order][np.searchsorted(cw, q * cw[-1])]


@partial(jax.jit, static_argnames=("dist",))
def response_transform(dist: str, f):
    """Raw score F -> prediction scale (linkinv)."""
    if dist == "bernoulli":
        return jax.nn.sigmoid(f)
    if dist in ("poisson", "gamma", "tweedie"):
        return jnp.exp(f)
    return f


def resolve_distribution(dist: str, yv, quantile_alpha: float, tweedie_power: float, huber_alpha: float):
    """AUTO resolution + aux parameter, mirroring h2o defaults."""
    d = (dist or "AUTO").lower()
    if d == "auto":
        if yv.is_categorical():
            d = "bernoulli" if yv.cardinality <= 2 else "multinomial"
        else:
            d = "gaussian"
    aux = 0.0
    if d == "tweedie":
        aux = float(tweedie_power)
    elif d == "quantile":
        aux = float(quantile_alpha)
    elif d == "huber":
        aux = float(huber_alpha)  # note: h2o derives delta from this quantile
    return d, aux
