"""GBM — successor of ``hex.tree.gbm.GBM`` / ``GBMModel`` [UNVERIFIED
upstream paths, SURVEY.md §2.2, §3.3] on the level-wise histogram builder.

The BASELINE.json north-star loop: per tree, distribution-specific
pseudo-residuals (one fused device op), then per level one ScoreBuildHistogram
pass + split scan + partition update — all XLA on the row-sharded binned
matrix, with psum as the only cross-chip traffic. Leaf values are Newton
steps from the same histogram stats, shrunk by ``learn_rate``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.cluster.job import Job
from h2o3_tpu.cluster.registry import DKV
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models import metrics as MM
from h2o3_tpu.models.model_base import (
    CommonParams,
    Model,
    ModelBuilder,
    ScoreKeeper,
    stopping_metric_direction,
)
from h2o3_tpu.models.tree.binning import MAX_BINS, BinSpec, bin_frame, fit_bins, fit_bins_for
from h2o3_tpu.models.tree.distributions import (
    grad_hess,
    init_score,
    multinomial_grad_hess,
    resolve_distribution,
    response_transform,
)
from h2o3_tpu.models.tree.shared_tree import Tree, build_tree
from h2o3_tpu.utils import faults
from h2o3_tpu.utils import metrics as _mx
from h2o3_tpu.utils.log import Log


@dataclass
class SharedTreeParams(CommonParams):
    ntrees: int = 50
    max_depth: int = 5
    min_rows: float = 10.0
    nbins: int = MAX_BINS  # static quantile bins (h2o re-bins per level at 20)
    # upstream's categorical-bin cap: domains wider than nbins_cats group
    # their tail levels into the last bin (ours additionally caps at the
    # uint8 code space, 254)
    nbins_cats: int = 1024
    # accepted for surface parity; upstream starts each tree at
    # nbins_top_level bins and halves per level down to nbins — the static
    # quantile design bins ONCE, so this knob has no effect here (the
    # H2O3_TPU_BIN_ADAPT env var is the per-level coarsening analog)
    nbins_top_level: int = 1024
    min_split_improvement: float = 1e-5
    sample_rate: float = 1.0
    col_sample_rate_per_tree: float = 1.0
    score_tree_interval: int = 5
    # ISSUE 16 leaf-wise growth: "depthwise" (default, upstream's level
    # order) or "lossguide" (xgboost-surface loss-guide — each level's
    # splits are rationed by gain rank against a max_leaves budget; runs on
    # the fused whole-tree lane). max_leaves bounds the leaf count and is
    # only consulted under lossguide.
    grow_policy: str = "depthwise"
    max_leaves: int = 0
    # probability calibration (upstream calibrate_model/calibration_frame on
    # tree models): fits Platt scaling or isotonic regression on a holdout
    # frame's predictions; predict() then appends cal_p0/cal_p1 columns
    calibrate_model: bool = False
    calibration_frame: Any = None
    calibration_method: str = "AUTO"  # AUTO -> PlattScaling | IsotonicRegression


@dataclass
class GBMParams(SharedTreeParams):
    learn_rate: float = 0.1
    learn_rate_annealing: float = 1.0
    distribution: str = "AUTO"
    col_sample_rate: float = 1.0
    max_abs_leafnode_pred: float = float("inf")
    quantile_alpha: float = 0.5
    tweedie_power: float = 1.5
    huber_alpha: float = 0.9
    # {col: +1|-1} monotone direction constraints (numeric features only;
    # enforced via split rejection + child-bound propagation, like upstream)
    monotone_constraints: Any = None


class SharedTreeModel(Model):
    """Common prediction/replay machinery for GBM/DRF/IF models."""

    _REPLAY_FIELDS = (
        "split_col", "split_bin", "is_cat", "cat_mask",
        "na_left", "leaf_now", "leaf_val", "child_base",
    )

    def _replay_all(self, frame: Frame) -> np.ndarray:
        out = self._replay_all_dev(frame)
        return np.asarray(out)[: frame.nrow]

    def _replay_all_dev(self, frame: Frame):
        """Sum of tree contributions per class, DEVICE-resident: (npad, K) or
        (npad,).

        Trees are re-stacked by depth and replayed with ONE dispatch per
        (class, depth) group — per-tree per-level dispatch costs ~66 ms each
        on the tunneled TPU once any D2H transfer has happened.
        """
        from collections import defaultdict

        from h2o3_tpu.models.tree.shared_tree import replay_batch

        spec: BinSpec = self.output["bin_spec"]
        bins = bin_frame(spec, frame)
        trees: list[list[Tree]] = self.output["trees"]  # [iter][class]
        K = self.output.get("n_tree_classes", 1)
        npad = bins.shape[0]
        preds = []
        for k in range(K):
            pk = jnp.zeros(npad, jnp.float32)
            by_depth: dict[int, list[Tree]] = defaultdict(list)
            for group in trees:
                t = group[k]
                by_depth[len(t.levels)].append(t)
            for depth, ts in by_depth.items():
                # ONE transfer for the whole group if levels are device-backed
                # (per-field np.asarray would be thousands of ~66 ms pulls)
                vals = jax.device_get(
                    [
                        [
                            [getattr(t.levels[li], f) for f in self._REPLAY_FIELDS]
                            for li in range(depth)
                        ]
                        for t in ts
                    ]
                )
                stacked = tuple(
                    {
                        f: np.stack([vals[ti][li][fi] for ti in range(len(ts))])
                        for fi, f in enumerate(self._REPLAY_FIELDS)
                    }
                    for li in range(depth)
                )
                pk = replay_batch(bins, stacked, pk)
            preds.append(pk)
        return jnp.stack(preds, axis=1) if K > 1 else preds[0]

    def _score_metrics(self, frame: Frame):
        """Device-stat scoring on accelerators: predictions never leave the
        device; metrics.py reduces sufficient statistics there (pulling a
        full prediction column over the tunnel costs seconds)."""
        if jax.default_backend() == "cpu":
            return super()._score_metrics(frame)
        from h2o3_tpu.models.model_base import _make_metrics

        raw = self._predict_raw_dev(frame)
        y, w = self._response_and_weights(frame)
        return _make_metrics(self, raw, y, w)

    def _predict_raw_dev(self, frame: Frame):
        raise NotImplementedError

    def _varimp_table(self):
        vi = self.output.get("varimp")
        if vi is None:
            return None
        names = self.output["names"]
        order = np.argsort(-vi)
        rel = vi / max(vi.max(), 1e-30)
        pct = vi / max(vi.sum(), 1e-30)
        return [
            {
                "variable": names[i],
                "relative_importance": float(vi[i]),
                "scaled_importance": float(rel[i]),
                "percentage": float(pct[i]),
            }
            for i in order
        ]

    def varimp(self):
        return self._varimp_table()

    def predict_contributions(self, frame: Frame) -> Frame:
        """Per-feature SHAP contributions + BiasTerm (hex.tree.TreeSHAP
        successor); Σ row = raw margin."""
        from h2o3_tpu.models.tree.shap import predict_contributions

        return predict_contributions(self, frame)

    def tree_view(self, tree_number: int = 0, tree_class: int = 0) -> dict:
        """Node-table dump of one tree (hex.tree.TreeHandler successor)."""
        from h2o3_tpu.models.tree.shap import tree_view

        return tree_view(self, tree_number, tree_class)

    def predict_leaf_node_assignment(self, frame: Frame, type: str = "Path") -> Frame:
        """Terminal leaf per (row, tree, class): decision-path strings or
        node ids (upstream Model.LeafNodeAssignment contract)."""
        from h2o3_tpu.models.tree.shap import predict_leaf_node_assignment

        return predict_leaf_node_assignment(self, frame, type)

    def model_summary(self) -> dict:
        """The upstream model_summary table for tree models: tree counts
        and the depth/leaf distribution over the forest. Computed once and
        cached (trees are immutable after build; device-backed levels pull
        one batched transfer per tree via Tree.to_host)."""
        cached = self.output.get("_model_summary_cache")
        if cached is not None:
            return cached
        trees = self.output.get("trees") or []
        flat = [t for group in trees for t in group]
        depths = [t.depth for t in flat]
        leaves = [t.n_leaves for t in flat]
        K = self.output.get("n_tree_classes", 1)
        out = {
            "number_of_trees": len(trees),
            "number_of_internal_trees": len(flat),
            "model_size_in_bytes": None,
            "min_depth": int(min(depths)) if depths else 0,
            "max_depth": int(max(depths)) if depths else 0,
            "mean_depth": float(np.mean(depths)) if depths else 0.0,
            "min_leaves": int(min(leaves)) if leaves else 0,
            "max_leaves": int(max(leaves)) if leaves else 0,
            "mean_leaves": float(np.mean(leaves)) if leaves else 0.0,
            "n_classes_per_iteration": K,
        }
        self.output["_model_summary_cache"] = out
        return out


class GBMModel(SharedTreeModel):
    algo = "gbm"

    def _predict_raw(self, frame: Frame) -> np.ndarray:
        # same math as the device flavor (jnp runs fine on the CPU backend);
        # a single implementation keeps the two paths from diverging
        return np.asarray(self._predict_raw_dev(frame))

    def _distribution_for_metrics(self) -> str:
        d = self.output["distribution"]
        return d if d in ("poisson", "gamma", "laplace") else "gaussian"

    def _predict_raw_dev(self, frame: Frame):
        """Device flavor of _predict_raw (same math, jnp end-to-end)."""
        dist = self.output["distribution"]
        raw = self._replay_all_dev(frame)
        if dist == "multinomial":
            F = raw + jnp.asarray(np.asarray(self.output["init_f"]))[None, :]
            return jax.nn.softmax(F, axis=1)[: frame.nrow]
        f = raw + self.output["init_f"]
        if self.params.offset_column and self.params.offset_column in frame:
            f = f + jnp.nan_to_num(frame.vec(self.params.offset_column).data)
        mu = response_transform(dist, f)
        if dist == "bernoulli":
            return jnp.stack([1 - mu, mu], axis=1)[: frame.nrow]
        return mu[: frame.nrow]


class GBM(ModelBuilder):
    algo = "gbm"
    PARAMS_CLS = GBMParams
    MODEL_CLS = GBMModel

    def _partial_model(self, key, p, spec, trees, K, dist, f0, varimp_dev,
                       domain, F, yn, wn, nrow, history) -> Model:
        """The interval-snapshot factory: a scoreable Model holding the
        forest SO FAR, shaped exactly like the final model so ``checkpoint=``
        resume (and plain predict) treat it as a short uninterrupted run."""
        out = {
            "bin_spec": spec,
            "trees": [list(g) for g in trees],
            "n_tree_classes": K,
            "distribution": dist,
            "init_f": f0,
            "names": list(self._x),
            "varimp": np.asarray(varimp_dev).astype(np.float64),
            "response_domain": domain,
            "ntrees_actual": len(trees),
        }
        m = self.MODEL_CLS(key, p, out)
        m.scoring_history = list(history)
        m.training_metrics = _metrics_from_F(dist, F, yn, wn, nrow, domain=domain)
        return m

    def _plan_streamed(self, train: Frame):
        """ChunkStore for this build's lanes, or None for the resident
        path: bins (1 B/row/col) + the six f32 per-row lanes + nid int32."""
        from h2o3_tpu.frame import chunkstore as cs

        return cs.ChunkStore.plan(train.npad, len(self._x) + 28)

    def _build_streamed(self, job, train, valid, p, spec, dist, aux, yv,
                        prior, store, classification, mono_vec=None):
        """Out-of-core GBM: per-block binning into the store's host tier,
        compressed device residency for the source columns, and the
        interval loop driving :func:`build_trees_streamed`. Metrics come
        from the running score lane (host tier) — no resident replay."""
        from collections import defaultdict

        from h2o3_tpu.frame import chunkstore as cs
        from h2o3_tpu.models.tree.shared_tree import (
            build_trees_streamed,
            replay_batch,
        )

        npad, nrow = train.npad, train.nrow
        n_bins = spec.max_bins
        C = len(self._x)
        K = 1
        Log.info(
            f"GBM out-of-core streaming: {store.n_blocks} blocks x "
            f"{store.block_rows} rows through a {store.window} B HBM window"
        )

        # response / weights (host tier; same rules as the resident build)
        y_np = yv.to_numpy().astype(np.float64)
        w_np = np.zeros(npad, np.float32)
        w_np[:nrow] = 1.0
        if p.weights_column:
            w_np[:nrow] *= np.nan_to_num(
                train.vec(p.weights_column).to_numpy()
            ).astype(np.float32)
        w_np[:nrow] *= ~np.isnan(y_np) if not classification else (y_np >= 0)
        ybuf = np.zeros(npad, np.float32)
        ybuf[:nrow] = np.nan_to_num(y_np, nan=0.0)
        spw = float(getattr(p, "scale_pos_weight", 1.0))
        w_train = w_np
        if spw != 1.0:
            if dist != "bernoulli":
                raise ValueError("scale_pos_weight requires a binary response")
            w_train = w_np.copy()
            w_train[:nrow] *= np.where(
                ybuf[:nrow] == 1.0, spw, 1.0
            ).astype(np.float32)
        offset_np = np.zeros(npad, np.float32)
        if p.offset_column:
            offset_np = np.nan_to_num(
                train.vec(p.offset_column).host_values().astype(np.float32)
            )
        wn, yn = w_np, ybuf

        store.add("y", ybuf)
        store.add("w", w_train)
        for name in ("F", "wt", "wy", "wh"):
            store.add_empty(name, (npad,), np.float32)
        store.add_empty("nid", (npad,), np.int32)

        # per-block binning: the binning transform is per-row, so each
        # block lane equals the resident bin_frame row-for-row
        bins_lane = store.add_empty("bins", (npad, C), np.uint8)
        for bi in range(store.n_blocks):
            lo, hi = store.span(bi)
            bf = cs.host_block_frame(train, list(spec.names), lo, hi)
            bins_lane[lo:hi] = np.asarray(
                jax.device_get(bin_frame(spec, bf)))
        # compressed residency: features now live as u8 codes in the host
        # tier; drop their f32/int device copies (lazy rebuild on demand)
        cs.release_frame_features(train, spec.names)

        rngkey = jax.random.PRNGKey(
            abs(p.seed) if p.seed and p.seed > 0 else 1234)
        metric_name, larger = stopping_metric_direction(
            p.stopping_metric, classification, 2)
        keeper = ScoreKeeper(p.stopping_rounds, p.stopping_tolerance, larger)
        history: list[dict] = []
        trees: list[list[Tree]] = []
        varimp_dev = jnp.zeros(C, jnp.float32)
        domain = tuple(yv.domain) if classification else None

        # validation stays resident (a holdout is window-sized in practice;
        # docs/MIGRATION.md fallback matrix)
        bins_v = yv_np = wv_np = Fv = None
        if valid is not None:
            bins_v = bin_frame(spec, valid)
            vv = valid.vec(p.response_column)
            from h2o3_tpu.models.model_base import _remap_response

            yv_np = (
                _remap_response(vv, yv.domain).astype(np.float64)
                if classification else vv.to_numpy().astype(np.float64)
            )
            wv_np = np.ones(valid.nrow, np.float32)
            if p.weights_column and p.weights_column in valid:
                wv_np *= np.nan_to_num(
                    valid.vec(p.weights_column).to_numpy()).astype(np.float32)

        start_trees = 0
        if prior is not None:
            f0 = prior.output["init_f"]
            trees.extend([list(g) for g in prior.output["trees"]])
            varimp_dev = jnp.asarray(
                np.asarray(prior.output["varimp"], np.float32))
            start_trees = prior.output["ntrees_actual"]
            # per-block replay of the prior forest into the running score
            # lane (the resident path's prior._replay_all_dev, blockwise)
            by_depth: dict[int, list[Tree]] = defaultdict(list)
            for group in trees:
                t = group[0]
                by_depth[len(t.levels)].append(t)
            stacked_by_depth = {}
            for depth, ts in by_depth.items():
                vals = jax.device_get(
                    [[[getattr(t.levels[li], f)
                       for f in SharedTreeModel._REPLAY_FIELDS]
                      for li in range(depth)] for t in ts]
                )
                stacked_by_depth[depth] = tuple(
                    {
                        f: np.stack([vals[ti][li][fi]
                                     for ti in range(len(ts))])
                        for fi, f in enumerate(SharedTreeModel._REPLAY_FIELDS)
                    }
                    for li in range(depth)
                )
            for bi, blk in store.stream(("bins",)):
                lo, hi = store.span(bi)
                pk = jnp.asarray(
                    np.float32(f0) + offset_np[lo:hi])
                for depth in stacked_by_depth:
                    pk = replay_batch(blk["bins"], stacked_by_depth[depth], pk)
                store.update(bi, F=pk)
        else:
            f0 = init_score(dist, yn[:nrow], wn[:nrow], aux)
            store.lane("F")[:] = np.float32(f0) + offset_np
        if bins_v is not None:
            offset_v = jnp.zeros(bins_v.shape[0], jnp.float32)
            if p.offset_column and p.offset_column in valid:
                offset_v = jnp.nan_to_num(valid.vec(p.offset_column).data)
            Fv = jnp.full(bins_v.shape[0], np.float32(f0), jnp.float32) + offset_v
            if prior is not None:
                Fv = Fv + prior._replay_all_dev(valid)

        lr = p.learn_rate * (p.learn_rate_annealing ** start_trees)
        interval = max(1, p.score_tree_interval)
        m_done = start_trees
        while m_done < p.ntrees and (
            m_done == start_trees or not job.stop_requested
        ):
            chunk = min(interval, p.ntrees - m_done)
            lrs = lr * (p.learn_rate_annealing ** np.arange(chunk))
            with _mx.span("gbm.build_tree", trees=chunk, tree_offset=m_done,
                          streamed=store.n_blocks):
                new_trees, varimp_dev = build_trees_streamed(
                    store, chunk, base_key=rngkey, tree_offset=m_done,
                    grad_fn=lambda F_, y_, w_: grad_hess(dist, F_, y_, w_, aux),
                    grad_key=("gbm", dist, aux),
                    sample_rate=p.sample_rate,
                    n_bins=n_bins,
                    is_cat_cols=spec.is_cat,
                    max_depth=p.max_depth,
                    min_rows=p.min_rows,
                    min_split_improvement=p.min_split_improvement,
                    learn_rates=lrs,
                    max_abs_leaf=p.max_abs_leafnode_pred,
                    col_sample_rate=p.col_sample_rate,
                    col_sample_rate_per_tree=p.col_sample_rate_per_tree,
                    varimp=varimp_dev,
                    reg_lambda=getattr(p, "reg_lambda", 0.0),
                    reg_alpha=getattr(p, "reg_alpha", 0.0),
                    monotone=mono_vec,
                )
            lr *= p.learn_rate_annealing ** chunk
            trees.extend([[t] for t in new_trees])
            if Fv is not None:
                for t in new_trees:
                    _, Fv = t.replay(
                        bins_v, jnp.zeros(bins_v.shape[0], jnp.int32), Fv)
            m_done += chunk

            F_host = store.lane("F")
            mval = _train_metric(dist, F_host, yn, wn, nrow, metric_name, K)
            entry = {"ntrees": m_done, f"training_{metric_name}": mval}
            stop_val = mval
            if Fv is not None:
                vval = _train_metric(
                    dist, Fv, yv_np, wv_np, valid.nrow, metric_name, K)
                entry[f"validation_{metric_name}"] = vval
                stop_val = vval
            history.append(entry)
            keeper.record(stop_val)
            self._export_interval_checkpoint(
                job,
                lambda key: self._partial_model(
                    key, p, spec, trees, K, dist, f0, varimp_dev, domain,
                    F_host, yn, wn, nrow, history,
                ),
            )
            faults.die_check(self.algo)  # chaos: worker death at boundary
            faults.abort_check(self.algo, m_done)
            faults.slow_check(self.algo)
            if keeper.should_stop():
                Log.info(
                    f"GBM early stop at {m_done} trees "
                    f"({metric_name}={stop_val:.5f})"
                )
                break
            job.update(0.05 + 0.9 * m_done / p.ntrees)

        out = {
            "bin_spec": spec,
            "trees": trees,
            "n_tree_classes": K,
            "distribution": dist,
            "init_f": f0,
            "names": list(self._x),
            "varimp": np.asarray(varimp_dev).astype(np.float64),
            "response_domain": domain,
            "ntrees_actual": len(trees),
        }
        model = self.MODEL_CLS(DKV.make_key(self.algo), p, out)
        model.scoring_history = history
        model.training_metrics = _metrics_from_F(
            dist, store.lane("F"), yn, wn, nrow, domain=domain)
        if valid is not None:
            model.validation_metrics = _metrics_from_F(
                dist, Fv, yv_np, wv_np, valid.nrow, domain=domain)
        store.close()
        from h2o3_tpu.models.calibration import maybe_fit_calibration

        maybe_fit_calibration(self, model)
        return model

    def _build(self, job: Job, train: Frame, valid: Frame | None) -> Model:
        p: GBMParams = self.params
        if p.ntrees < 1 or p.max_depth < 1:
            raise ValueError("ntrees and max_depth must be >= 1")
        yv = train.vec(p.response_column)
        dist, aux = resolve_distribution(
            p.distribution, yv, p.quantile_alpha, p.tweedie_power, p.huber_alpha
        )
        classification = dist in ("bernoulli", "multinomial")
        K = yv.cardinality if dist == "multinomial" else 1

        from h2o3_tpu.models.model_base import check_checkpoint_compat, resolve_checkpoint

        prior = resolve_checkpoint(p.checkpoint)
        if prior is not None:
            check_checkpoint_compat(
                prior, self,
                ("max_depth", "nbins", "min_rows", "distribution", "learn_rate",
                 "sample_rate", "col_sample_rate", "col_sample_rate_per_tree",
                 # xgboost-surface regime params (absent on plain GBMParams;
                 # compat check must tolerate missing fields)
                 "reg_lambda", "reg_alpha", "scale_pos_weight"),
            )
            if p.ntrees <= prior.output["ntrees_actual"]:
                raise ValueError(
                    f"checkpoint continuation needs ntrees > {prior.output['ntrees_actual']}"
                )
            # identical binning is what makes prior trees replayable here
            spec = prior.output["bin_spec"]
        else:
            spec = fit_bins_for(p, train, self._x)

        # monotone constraints resolve BEFORE the lane gates: both the
        # streamed and the scanned/fused lanes now accept them (ISSUE 15)
        mono_vec = None
        if p.monotone_constraints:
            if dist not in ("gaussian", "bernoulli", "tweedie", "quantile"):
                raise ValueError(
                    "monotone_constraints supports gaussian/bernoulli/"
                    "tweedie/quantile distributions"
                )
            mono_vec = np.zeros(len(self._x), np.int32)
            for cname, d in dict(p.monotone_constraints).items():
                if int(d) == 0:  # upstream accepts 0 = unconstrained
                    continue
                if cname not in self._x:
                    raise ValueError(f"monotone constraint on unknown column {cname!r}")
                ci = self._x.index(cname)
                if spec.is_cat[ci]:
                    raise ValueError(
                        f"monotone constraint on categorical column {cname!r}"
                    )
                if int(d) not in (-1, 1):
                    raise ValueError("monotone directions must be -1, 0 or 1")
                mono_vec[ci] = int(d)
            if not mono_vec.any():
                mono_vec = None

        # leaf-wise growth (ISSUE 16): lossguide rations each level's splits
        # by gain rank against the remaining max_leaves budget; the budget
        # rides the fused whole-tree program's level carry, so the policy is
        # fused-lane-only (the per-level host loop never sees it)
        if p.grow_policy not in ("depthwise", "lossguide"):
            raise ValueError(
                f"grow_policy must be 'depthwise' or 'lossguide', got {p.grow_policy!r}"
            )
        max_leaves = 0
        if p.grow_policy == "lossguide":
            from h2o3_tpu.models.tree.shared_tree import (
                _split_fuse_on as _sf_on,
                use_fused_trees as _fused_ok,
            )

            if p.max_leaves < 2:
                raise ValueError("grow_policy=lossguide requires max_leaves >= 2")
            if not _fused_ok(p.max_depth) or (
                mono_vec is not None and not _sf_on()
            ):
                raise ValueError(
                    "grow_policy=lossguide runs on the fused whole-tree lane "
                    "(H2O3_TPU_WHOLE_TREE=1 within H2O3_TPU_FUSED_MAX_DEPTH; "
                    "monotone lossguide additionally needs H2O3_TPU_SPLIT_FUSE)"
                )
            max_leaves = int(p.max_leaves)

        # out-of-core streaming (ISSUE 11, frame/chunkstore.py): when the
        # frame's per-row training lanes exceed the configured HBM window,
        # train as a block-accumulate outer loop around the existing
        # compiled programs instead of materializing the resident arrays.
        # Fallback matrix (docs/MIGRATION.md): multinomial (K per-class
        # trees share row state) stays resident; monotone builds stream
        # too since ISSUE 15 (the bound state is per-node, not per-block).
        if dist != "multinomial":
            stream = self._plan_streamed(train)
            if stream is not None:
                if max_leaves:
                    raise ValueError(
                        "grow_policy=lossguide is resident-only: raise the "
                        "HBM window (H2O3_TPU_HBM_WINDOW_MB) or drop the "
                        "frame below the streaming threshold"
                    )
                return self._build_streamed(
                    job, train, valid, p, spec, dist, aux, yv, prior, stream,
                    classification, mono_vec=mono_vec,
                )
        bins = bin_frame(spec, train)
        n_bins = spec.max_bins
        npad = train.npad

        # EFB (ISSUE 16, H2O3_TPU_TREE_EFB): host-side greedy bundling of
        # mutually-exclusive sparse/one-hot columns into shared u8 code
        # columns — the histogram grid accumulates over the bundled Cb < C
        # axis and expands back to real columns right after (split records,
        # varimp, MOJO and scoring never see bundle space). Fused
        # whole-tree lanes only; bin-adapt coarsening would scramble bundle
        # codes, so nonzero shifts (or a streamed build, which returns
        # above) skip bundling entirely.
        efb = bins_b = None
        from h2o3_tpu import config as _config

        if _config.get_bool("H2O3_TPU_TREE_EFB"):
            from h2o3_tpu.models.tree.binning import (
                bucket_nbins as _bnb,
                bundle_bins,
                fit_efb,
            )
            from h2o3_tpu.models.tree.shared_tree import (
                _bin_shifts,
                _split_fuse_on as _sf_on2,
                use_fused_trees as _fused_ok2,
            )

            _cats = tuple(
                int(i) for i in np.nonzero(np.asarray(spec.is_cat, bool))[0]
            )
            if (
                _fused_ok2(p.max_depth)
                and (mono_vec is None or _sf_on2())
                and all(
                    s == 0
                    for s in _bin_shifts(p.max_depth, _bnb(n_bins), _cats)
                )
            ):
                efb = fit_efb(spec, bins, nrow=train.nrow)
                if efb is not None:
                    bins_b = bundle_bins(efb, bins)

        # response / weights on device
        y_np = yv.to_numpy().astype(np.float64)
        w_np = np.zeros(npad, np.float32)
        w_np[: train.nrow] = 1.0
        if p.weights_column:
            w_np[: train.nrow] *= np.nan_to_num(
                train.vec(p.weights_column).to_numpy()
            ).astype(np.float32)
        w_np[: train.nrow] *= ~np.isnan(y_np) if not classification else (y_np >= 0)
        ybuf = np.zeros(npad, np.float32)
        ybuf[: train.nrow] = np.nan_to_num(y_np, nan=0.0)
        # xgboost-surface scale_pos_weight (XGBoostParams only): fold the
        # positive-class up-weighting into the TRAINING row weights only —
        # xgboost scales grad/hess (≡ row weights in our Newton leaves) but
        # evaluates metrics unweighted, so the metric weights (wn) must not
        # carry it
        spw = float(getattr(p, "scale_pos_weight", 1.0))
        w_train_np = w_np
        if spw != 1.0:
            if dist != "bernoulli":
                raise ValueError("scale_pos_weight requires a binary response")
            w_train_np = w_np.copy()
            w_train_np[: train.nrow] *= np.where(
                ybuf[: train.nrow] == 1.0, spw, 1.0
            ).astype(np.float32)
        w = jnp.asarray(w_train_np)
        y = jnp.asarray(ybuf)

        offset = jnp.zeros(npad, jnp.float32)
        if p.offset_column:
            offset = jnp.nan_to_num(train.vec(p.offset_column).data)

        rngkey = jax.random.PRNGKey(abs(p.seed) if p.seed and p.seed > 0 else 1234)

        wn, yn = w_np, ybuf  # host copies already exist — never pull from device
        trees: list[list[Tree]] = []
        varimp_dev = jnp.zeros(len(self._x), jnp.float32)
        history: list[dict] = []

        metric_name, larger = stopping_metric_direction(
            p.stopping_metric, classification, K or 2
        )
        keeper = ScoreKeeper(p.stopping_rounds, p.stopping_tolerance, larger)

        # validation scoring state: bin once, replay only new trees per
        # scoring event (H2O scores the validation frame with the current
        # model at each ScoreKeeper tick)
        bins_v = yv_np = wv_np = None
        if valid is not None:
            bins_v = bin_frame(spec, valid)
            vv = valid.vec(p.response_column)
            from h2o3_tpu.models.model_base import _remap_response

            yv_np = (
                _remap_response(vv, yv.domain).astype(np.float64)
                if classification
                else vv.to_numpy().astype(np.float64)
            )
            wv_np = np.ones(valid.nrow, np.float32)
            if p.weights_column and p.weights_column in valid:
                wv_np *= np.nan_to_num(valid.vec(p.weights_column).to_numpy()).astype(
                    np.float32
                )

        # validation offsets enter Fv at init so F-based validation metrics
        # match what a replay-scored prediction (init + offset + trees) gives
        offset_v = None
        if bins_v is not None:
            offset_v = jnp.zeros(bins_v.shape[0], jnp.float32)
            if p.offset_column and p.offset_column in valid:
                offset_v = jnp.nan_to_num(valid.vec(p.offset_column).data)

        if dist == "multinomial":
            prior_p = np.array(
                [max((wn * (yn == k)).sum() / max(wn.sum(), 1e-30), 1e-9) for k in range(K)]
            )
            f0 = np.log(prior_p).astype(np.float32)
            F = jnp.tile(jnp.asarray(f0)[None, :], (npad, 1)) + offset[:, None]
            Y1h = (y[:, None] == jnp.arange(K)[None, :]).astype(jnp.float32)
            Fv = (
                [jnp.full(bins_v.shape[0], f0[k], jnp.float32) + offset_v for k in range(K)]
                if bins_v is not None
                else None
            )
        else:
            f0 = init_score(dist, yn[: train.nrow], wn[: train.nrow], aux)
            F = jnp.full(npad, f0, jnp.float32) + offset
            Fv = (
                [jnp.full(bins_v.shape[0], f0, jnp.float32) + offset_v]
                if bins_v is not None
                else None
            )

        # Chunk-scanned path: build a whole scoring interval of trees in ONE
        # device dispatch (see build_trees_scanned). Default on EVERY backend
        # — on the tunneled TPU dispatch latency dominates once any D2H
        # transfer has happened, and on the CPU mesh per-level dispatch
        # overhead × levels × trees was ~a third of build wall-clock.
        # H2O3_TPU_WHOLE_TREE=0 restores the per-tree per-level loop.
        # Monotone builds take the scanned lane when the fused Pallas
        # pipeline is active (ISSUE 15: the constraint mask runs inside the
        # split kernel and the bound state rides the fused level carry);
        # with the fuse gate off they keep the legacy per-level loop
        # bit-for-bit.
        from h2o3_tpu.models.tree.shared_tree import (
            _split_fuse_on,
            use_fused_trees,
        )

        use_scan = (dist != "multinomial" and use_fused_trees(p.max_depth)
                    and (mono_vec is None or _split_fuse_on()))

        start_trees = 0
        if prior is not None:
            # continue exactly where the prior model stopped: its init score,
            # its trees replayed into F (identical bin spec), its varimp
            f0 = prior.output["init_f"]
            raw = prior._replay_all_dev(train)
            if dist == "multinomial":
                F = jnp.asarray(np.asarray(f0))[None, :] + offset[:, None] + raw
            else:
                F = jnp.full(npad, np.float32(f0)) + offset + raw
            trees.extend([list(g) for g in prior.output["trees"]])
            varimp_dev = jnp.asarray(np.asarray(prior.output["varimp"], np.float32))
            start_trees = prior.output["ntrees_actual"]
            if Fv is not None:
                rawv = prior._replay_all_dev(valid)
                if dist == "multinomial":
                    Fv = [
                        jnp.full(bins_v.shape[0], f0[k], jnp.float32) + offset_v + rawv[:, k]
                        for k in range(K)
                    ]
                else:
                    Fv = [jnp.full(bins_v.shape[0], np.float32(f0)) + offset_v + rawv]
            if p.sample_rate < 1.0 and not use_scan:
                # advance the per-tree loop's split chain so continuation
                # equals an uninterrupted run; the scanned path keys by the
                # global tree id off the PRISTINE key and must not advance
                for _ in range(start_trees):
                    rngkey, _ = jax.random.split(rngkey)

        lr = p.learn_rate * (p.learn_rate_annealing**start_trees)

        if use_scan:
            from h2o3_tpu.models.tree.shared_tree import (
                build_trees_scanned,
                replay_batch,
                scan_chunk_cap,
                trees_from_stacked,
            )

            cap = scan_chunk_cap(p.max_depth, n_bins)
            interval = max(1, p.score_tree_interval)
            m_done = start_trees
            # first chunk always runs: a max_runtime that expires during
            # setup/compile must still leave a scoreable 1+-tree model
            # (upstream keeps a non-empty partial model)
            while m_done < p.ntrees and (
                m_done == start_trees or not job.stop_requested
            ):
                chunk = min(interval, cap, p.ntrees - m_done)
                lrs = lr * (p.learn_rate_annealing ** np.arange(chunk))
                with _mx.span("gbm.build_tree", trees=chunk,
                              tree_offset=m_done):
                    F, varimp_dev, stacked = build_trees_scanned(
                        bins, w, y, F, varimp_dev, rngkey, chunk,
                        tree_offset=m_done,
                        grad_fn=lambda F_, y_, w_: grad_hess(dist, F_, y_, w_, aux),
                        grad_key=("gbm", dist, aux),
                        sample_rate=p.sample_rate,
                        n_bins=n_bins,
                        is_cat_cols=spec.is_cat,
                        max_depth=p.max_depth,
                        min_rows=p.min_rows,
                        min_split_improvement=p.min_split_improvement,
                        learn_rates=lrs,
                        max_abs_leaf=p.max_abs_leafnode_pred,
                        col_sample_rate=p.col_sample_rate,
                        col_sample_rate_per_tree=p.col_sample_rate_per_tree,
                        reg_lambda=getattr(p, "reg_lambda", 0.0),
                        reg_alpha=getattr(p, "reg_alpha", 0.0),
                        monotone=mono_vec,
                        max_leaves=max_leaves,
                        efb=efb,
                        bins_b=bins_b,
                    )
                lr *= p.learn_rate_annealing ** chunk
                with _mx.span("gbm.pull_records", trees=chunk):
                    trees.extend([[t] for t in trees_from_stacked(stacked, chunk)])
                if Fv is not None:
                    Fv[0] = replay_batch(bins_v, stacked, Fv[0])
                m_done += chunk

                mval = _train_metric(dist, F, yn, wn, train.nrow, metric_name, K)
                entry = {"ntrees": m_done, f"training_{metric_name}": mval}
                stop_val = mval
                if Fv is not None:
                    vval = _train_metric(
                        dist, Fv[0], yv_np, wv_np, valid.nrow, metric_name, K
                    )
                    entry[f"validation_{metric_name}"] = vval
                    stop_val = vval
                history.append(entry)
                keeper.record(stop_val)
                self._export_interval_checkpoint(
                    job,
                    lambda key: self._partial_model(
                        key, p, spec, trees, K, dist, f0, varimp_dev,
                        tuple(yv.domain) if classification else None,
                        F, yn, wn, train.nrow, history,
                    ),
                )
                faults.die_check(self.algo)  # chaos: worker death at boundary
                faults.abort_check(self.algo, m_done)
                faults.slow_check(self.algo)  # chaos: slow training interval
                if keeper.should_stop():
                    Log.info(
                        f"GBM early stop at {m_done} trees ({metric_name}={stop_val:.5f})"
                    )
                    break
                job.update(0.05 + 0.9 * m_done / p.ntrees)

        for m in range(start_trees if not use_scan else p.ntrees, p.ntrees):
            if job.stop_requested and m > start_trees:
                break  # always ≥1 tree (see scan loop comment)
            # row sampling (per tree)
            if p.sample_rate < 1.0:
                rngkey, sk = jax.random.split(rngkey)
                mask = jax.random.bernoulli(sk, p.sample_rate, (npad,)).astype(jnp.float32)
                w_tree = w * mask
            else:
                w_tree = w
            tree_key = jax.random.fold_in(rngkey, m)

            group: list[Tree] = []
            # manual enter/exit keeps the two dist branches unindented; an
            # exception between them kills the whole Job (and its context)
            # so the unexited span leaks nothing
            _tree_span = _mx.span("gbm.build_tree", tree=m)
            _tree_span.__enter__()
            if dist == "multinomial":
                T, H = multinomial_grad_hess(F, Y1h, w_tree, K)
                newF = []
                for k in range(K):
                    tree, fk, varimp_dev = build_tree(
                        bins,
                        w_tree,
                        T[:, k],
                        H[:, k],
                        n_bins=n_bins,
                        is_cat_cols=spec.is_cat,
                        max_depth=p.max_depth,
                        min_rows=p.min_rows,
                        min_split_improvement=p.min_split_improvement,
                        learn_rate=lr,
                        preds=F[:, k],
                        key=jax.random.fold_in(tree_key, k),
                        varimp=varimp_dev,
                        col_sample_rate=p.col_sample_rate,
                        col_sample_rate_per_tree=p.col_sample_rate_per_tree,
                        max_abs_leaf=p.max_abs_leafnode_pred,
                        reg_lambda=getattr(p, "reg_lambda", 0.0),
                        reg_alpha=getattr(p, "reg_alpha", 0.0),
                        max_leaves=max_leaves,
                        efb=efb,
                        bins_b=bins_b,
                    )
                    group.append(tree)
                    newF.append(fk)
                F = jnp.stack(newF, axis=1)
            else:
                t, h = grad_hess(dist, F, y, w_tree, aux)
                tree, F, varimp_dev = build_tree(
                    bins,
                    w_tree,
                    t,
                    h,
                    n_bins=n_bins,
                    is_cat_cols=spec.is_cat,
                    max_depth=p.max_depth,
                    min_rows=p.min_rows,
                    min_split_improvement=p.min_split_improvement,
                    learn_rate=lr,
                    preds=F,
                    key=tree_key,
                    varimp=varimp_dev,
                    col_sample_rate=p.col_sample_rate,
                    col_sample_rate_per_tree=p.col_sample_rate_per_tree,
                    max_abs_leaf=p.max_abs_leafnode_pred,
                    monotone=mono_vec,
                    reg_lambda=getattr(p, "reg_lambda", 0.0),
                    reg_alpha=getattr(p, "reg_alpha", 0.0),
                    max_leaves=max_leaves,
                    efb=efb,
                    bins_b=bins_b,
                )
                group.append(tree)
            _tree_span.__exit__(None, None, None)
            trees.append(group)
            lr *= p.learn_rate_annealing

            if Fv is not None:
                for k, tree in enumerate(group):
                    _, Fv[k] = tree.replay(
                        bins_v, jnp.zeros(bins_v.shape[0], jnp.int32), Fv[k]
                    )

            if (m + 1) % max(1, p.score_tree_interval) == 0 or m == p.ntrees - 1:
                mval = _train_metric(dist, F, yn, wn, train.nrow, metric_name, K)
                entry = {"ntrees": m + 1, f"training_{metric_name}": mval}
                stop_val = mval
                if Fv is not None:
                    Fv_s = jnp.stack(Fv, axis=1) if dist == "multinomial" else Fv[0]
                    vval = _train_metric(
                        dist, Fv_s, yv_np, wv_np, valid.nrow, metric_name, K
                    )
                    entry[f"validation_{metric_name}"] = vval
                    stop_val = vval
                history.append(entry)
                keeper.record(stop_val)
                self._export_interval_checkpoint(
                    job,
                    lambda key: self._partial_model(
                        key, p, spec, trees, K, dist, f0, varimp_dev,
                        tuple(yv.domain) if classification else None,
                        F, yn, wn, train.nrow, history,
                    ),
                )
                faults.die_check(self.algo)  # chaos: worker death at boundary
                faults.abort_check(self.algo, m + 1)
                faults.slow_check(self.algo)  # chaos: slow training interval
                if keeper.should_stop():
                    Log.info(f"GBM early stop at {m + 1} trees ({metric_name}={stop_val:.5f})")
                    break
            job.update(0.05 + 0.9 * (m + 1) / p.ntrees)

        out = {
            "bin_spec": spec,
            "trees": trees,
            "n_tree_classes": K,
            "distribution": dist,
            "init_f": f0,
            "names": list(self._x),
            "varimp": np.asarray(varimp_dev).astype(np.float64),
            "response_domain": tuple(yv.domain) if classification else None,
            "ntrees_actual": len(trees),
        }
        model = self.MODEL_CLS(DKV.make_key(self.algo), p, out)
        model.scoring_history = history
        dom = out["response_domain"]
        model.training_metrics = _metrics_from_F(
            dist, F, yn, wn, train.nrow, domain=dom
        )
        if valid is not None:
            Fv_s = jnp.stack(Fv, axis=1) if dist == "multinomial" else Fv[0]
            model.validation_metrics = _metrics_from_F(
                dist, Fv_s, yv_np, wv_np, valid.nrow, domain=dom
            )
        from h2o3_tpu.models.calibration import maybe_fit_calibration

        maybe_fit_calibration(self, model)
        return model


def _metrics_from_F(dist, F, yn, wn, nrow, domain=None) -> MM.ModelMetrics:
    """Full ModelMetrics from the RUNNING scores — replaying the recorded
    trees to re-derive F costs seconds on the tunneled TPU; the training
    loop already holds it. On accelerators the transformed scores stay on
    device (metrics.py reduces sufficient statistics there)."""
    conv = (
        (lambda x: x)
        if jax.default_backend() != "cpu" or jax.process_count() > 1
        else np.asarray
    )
    if dist == "multinomial":
        P = conv(jax.nn.softmax(F, axis=1))[:nrow]
        return MM.multinomial_metrics(
            yn[:nrow].astype(np.int64), P, wn[:nrow], domain=domain or ()
        )
    if dist == "bernoulli":
        p1 = conv(response_transform("bernoulli", F))[:nrow]
        return MM.binomial_metrics(yn[:nrow], p1, wn[:nrow], domain=domain or ("0", "1"))
    mu = conv(response_transform(dist, F))[:nrow]
    mdist = dist if dist in ("poisson", "gamma", "laplace") else "gaussian"
    return MM.regression_metrics(yn[:nrow], mu, wn[:nrow], mdist)


def _train_metric(dist, F, yn, wn, nrow, metric_name, K) -> float:
    """Cheap training metric from the running scores."""
    m = _metrics_from_F(dist, F, yn, wn, nrow)
    v = m._v.get(metric_name)
    if v is None:
        v = m._v.get("logloss" if dist in ("bernoulli", "multinomial") else "rmse")
    return float(v)
