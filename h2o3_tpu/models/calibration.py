"""Probability calibration for binary classifiers — successor of the
``calibrate_model`` / ``calibration_frame`` / ``calibration_method`` params
on upstream tree models (Platt scaling + isotonic, ``CalibrationHelper``)
[UNVERIFIED upstream paths, SURVEY.md §2.2].

Fit happens once on the holdout calibration frame's predictions (host
float64 — the data is one column); scoring applies the tiny calibrator to
the predicted p1 and appends ``cal_p0``/``cal_p1`` columns, matching the
upstream predict-frame layout.
"""

from __future__ import annotations

import numpy as np

from h2o3_tpu.utils.log import Log


def _logit(p: np.ndarray) -> np.ndarray:
    p = np.clip(p, 1e-12, 1 - 1e-12)
    return np.log(p / (1 - p))


def fit_platt(p1: np.ndarray, y: np.ndarray, w: np.ndarray) -> dict:
    """Platt scaling: logistic regression of y on logit(p1) (a, b).

    Robustified the way Platt (1999) prescribes: smoothed targets
    t+ = (N+ + 1)/(N+ + 2), t- = 1/(N- + 2) (prevents the separable-score
    blowup an overconfident model produces), standardized feature, a tiny
    ridge, and damped Newton steps.
    """
    f_raw = _logit(np.asarray(p1, np.float64))
    y = np.asarray(y, np.float64)
    n_pos = float(np.sum(w * (y > 0.5)))
    n_neg = float(np.sum(w * (y <= 0.5)))
    t = np.where(y > 0.5, (n_pos + 1.0) / (n_pos + 2.0), 1.0 / (n_neg + 2.0))
    mu_f = float(np.average(f_raw, weights=np.maximum(w, 1e-12)))
    sd_f = float(np.sqrt(np.average((f_raw - mu_f) ** 2,
                                    weights=np.maximum(w, 1e-12)))) or 1.0
    f = (f_raw - mu_f) / sd_f
    a, b = 1.0, 0.0
    ridge = 1e-6
    for _ in range(100):
        eta = np.clip(a * f + b, -30.0, 30.0)
        mu = np.clip(1.0 / (1.0 + np.exp(-eta)), 1e-10, 1 - 1e-10)
        W = w * mu * (1 - mu) + 1e-12
        z = eta + (t - mu) / (mu * (1 - mu) + 1e-12)
        s_ff = float(np.sum(W * f * f)) + ridge
        s_f = float(np.sum(W * f))
        s_1 = float(np.sum(W)) + ridge
        r_f = float(np.sum(W * f * z)) + ridge * a
        r_1 = float(np.sum(W * z)) + ridge * b
        det = s_ff * s_1 - s_f * s_f
        if abs(det) < 1e-30:
            break
        a_new = (r_f * s_1 - r_1 * s_f) / det
        b_new = (s_ff * r_1 - s_f * r_f) / det
        da, db = a_new - a, b_new - b
        step = min(1.0, 4.0 / max(abs(da), abs(db), 1e-12))  # damp big jumps
        a += step * da
        b += step * db
        if abs(da) + abs(db) < 1e-10:
            break
    # unstandardize: eta = a*(f_raw - mu_f)/sd_f + b
    return {"method": "PlattScaling",
            "a": float(a / sd_f), "b": float(b - a * mu_f / sd_f)}


def fit_isotonic(p1: np.ndarray, y: np.ndarray, w: np.ndarray) -> dict:
    """Isotonic calibration: PAV of y against p1."""
    from h2o3_tpu.models.isotonic import _pav

    order = np.argsort(p1, kind="stable")
    ys = np.asarray(y, np.float64)[order]
    ws = np.asarray(w, np.float64)[order]
    fitted = _pav(ys, ws)
    xs = np.asarray(p1, np.float64)[order]
    # Collapse to PAV block boundaries before storing: interior points of a
    # constant-y run contribute nothing to np.interp, but would bloat the
    # model output / MOJO with O(n) thresholds on big calibration frames.
    from h2o3_tpu.models.isotonic import pav_block_knots

    keep = pav_block_knots(fitted)
    xs, fitted = xs[keep], fitted[keep]
    return {
        "method": "IsotonicRegression",
        "thresholds_x": xs,
        "thresholds_y": fitted,
    }


def apply_calibration(cal: dict, p1: np.ndarray) -> np.ndarray:
    p1 = np.asarray(p1, np.float64)
    if cal["method"] == "PlattScaling":
        eta = np.clip(cal["a"] * _logit(p1) + cal["b"], -30.0, 30.0)
        return 1.0 / (1.0 + np.exp(-eta))
    x = cal["thresholds_x"]
    yv = cal["thresholds_y"]
    return np.clip(np.interp(p1, x, yv), 0.0, 1.0)


def validate_calibration_params(p, yv) -> None:
    """Early param check (called from ModelBuilder._validate, BEFORE the
    expensive build): misconfiguration must not cost a full training run."""
    if not getattr(p, "calibrate_model", False):
        return
    from h2o3_tpu.models.model_base import _resolve_frame

    if _resolve_frame(p.calibration_frame) is None:
        raise ValueError("calibrate_model requires calibration_frame")
    if not (yv.is_categorical() and yv.cardinality == 2):
        raise ValueError("calibrate_model supports binary classification only")


def maybe_fit_calibration(builder, model) -> None:
    """Shared tail for tree builders: honor calibrate_model params."""
    p = builder.params
    if not getattr(p, "calibrate_model", False):
        return
    from h2o3_tpu.models.model_base import _remap_response, _resolve_frame

    if not model.is_classifier or model.nclasses != 2:
        raise ValueError("calibrate_model supports binary classification only")
    frame = _resolve_frame(p.calibration_frame)
    if frame is None:
        raise ValueError("calibrate_model requires calibration_frame")
    frame = model._apply_preprocessors(frame)  # e.g. TE, like predict()
    raw = model._predict_raw(frame)
    p1 = np.asarray(raw)[:, 1]
    yv = frame.vec(p.response_column)
    if yv.is_categorical():
        y = _remap_response(yv, model.output["response_domain"]).astype(np.float64)
    else:
        y = yv.to_numpy().astype(np.float64)  # numeric 0/1 column
    ok = ~np.isnan(y) & (y >= 0)
    w = np.ones(frame.nrow)
    if p.weights_column and p.weights_column in frame:
        w = np.nan_to_num(frame.vec(p.weights_column).to_numpy())
    method = (p.calibration_method or "AUTO").lower().replace("_", "")
    if method in ("auto", "plattscaling", "platt"):
        cal = fit_platt(p1[ok], y[ok], w[ok])
    elif method in ("isotonicregression", "isotonic"):
        cal = fit_isotonic(p1[ok], y[ok], w[ok])
    else:
        raise ValueError(f"unknown calibration_method {p.calibration_method!r}")
    model.output["calibration"] = cal
    Log.info(f"{model.algo}: fitted {cal['method']} calibration on "
             f"{int(ok.sum())} holdout rows")
