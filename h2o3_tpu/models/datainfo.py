"""Design-matrix view — successor of ``hex.DataInfo`` [UNVERIFIED upstream
path, SURVEY.md §2.2].

H2O's DataInfo gives GLM/DL/KMeans/PCA a canonical numeric view of a Frame:
categoricals expanded to indicator blocks, numerics standardized, missing
values imputed or skipped. Here the view is materialized as one row-sharded
``(npad, p)`` float32 device matrix — dense one-hot is MXU-friendly and XLA
fuses the expansion into downstream matmuls. Train-time statistics (means,
sigmas, domains) are captured so the identical transform applies to
validation/test frames (the ``adaptTestForTrain`` contract).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.frame import CAT, Frame, Vec
from h2o3_tpu.parallel.mesh import row_sharding

MEAN_IMPUTATION = "mean_imputation"
SKIP = "skip"


@dataclass
class ColumnSpec:
    name: str
    kind: str  # "num" | "cat" | "hash"
    mean: float = 0.0
    sigma: float = 1.0
    domain: tuple[str, ...] = ()
    offset: int = 0  # first column index in the expanded matrix
    width: int = 1
    # interaction column (upstream `interactions`/`interaction_pairs`):
    # ("a", "b") source pair; kind "num" = numeric product (standardized like
    # any numeric), kind "cat" = onehot(cat) * raw numeric per level.
    # pair_means = TRAINING means of the numeric sources (NA imputation must
    # not depend on the scoring batch)
    pair: tuple[str, str] | None = None
    pair_means: tuple[float, float] | None = None
    # cat x cat combined-factor interaction (upstream enum-by-enum): the
    # TRAINING domains of both sources, kept so scoring frames remap each
    # source before forming combined code a*|domain_b| + b
    pair_domains: tuple[tuple[str, ...], tuple[str, ...]] | None = None


@dataclass
class DataInfo:
    """Fitted design-matrix spec. Build with :meth:`fit`, apply with
    :meth:`transform`."""

    columns: list[ColumnSpec] = field(default_factory=list)
    standardize: bool = True
    use_all_factor_levels: bool = True
    missing_handling: str = MEAN_IMPUTATION
    add_intercept: bool = False
    ncols_expanded: int = 0
    # feature hashing (the sparse-chunk / sparse-DMatrix successor for
    # Criteo-class cardinalities): cat columns wider than hash_buckets
    # levels expand to a FIXED hash_buckets-wide indicator block instead of
    # one column per level, bounding the design matrix at any cardinality.
    # Buckets come from a stable string hash of (column, level), so train
    # and scoring frames agree without any domain remap. Values <= 0 mean
    # "no hashing" (fit coerces them to None). Like the exact cat path,
    # use_all_factor_levels=False drops bucket 0 as the reference level —
    # otherwise the block sums to the intercept and the unregularized Gram
    # goes singular.
    hash_buckets: int | None = None
    # per-COLUMN device LUT cache (most-recent domain only): rebuilding
    # costs one crc32 per LEVEL (≈1M Python calls at Criteo cardinality)
    # and must not be paid again on every scoring call. Keyed by column
    # name alone — a long-lived scoring server cycling through frames with
    # distinct domain objects would otherwise pin every domain tuple +
    # device LUT it ever saw. Values hold the domain tuple so a hit can be
    # validated by identity and a stale entry is simply replaced.
    _hash_luts: dict = field(default_factory=dict, repr=False, compare=False)

    @staticmethod
    def fit(
        frame: Frame,
        x: list[str],
        standardize: bool = True,
        use_all_factor_levels: bool = True,
        missing_handling: str = MEAN_IMPUTATION,
        add_intercept: bool = False,
        interaction_pairs: list[tuple[str, str]] | None = None,
        hash_buckets: int | None = None,
    ) -> "DataInfo":
        hash_buckets = (
            int(hash_buckets) if hash_buckets and int(hash_buckets) > 0 else None
        )
        di = DataInfo(
            standardize=standardize,
            use_all_factor_levels=use_all_factor_levels,
            missing_handling=missing_handling,
            add_intercept=add_intercept,
            hash_buckets=hash_buckets,
        )
        off = 0
        # H2O orders the expanded matrix categoricals-first, then numerics
        # [UNVERIFIED]; we keep the user's column order for readability of
        # coefficient names — the math is order-invariant.
        for name in x:
            v = frame.vec(name)
            if v.is_categorical():
                k = v.cardinality
                if hash_buckets is not None and k > hash_buckets:
                    hw = (
                        hash_buckets
                        if use_all_factor_levels
                        else max(1, hash_buckets - 1)
                    )
                    di.columns.append(
                        ColumnSpec(name, "hash", offset=off, width=hw)
                    )
                    off += hw
                    continue
                width = k if use_all_factor_levels else max(1, k - 1)
                di.columns.append(
                    ColumnSpec(name, "cat", domain=v.domain or (), offset=off, width=width)
                )
                off += width
            else:
                s = v.stats()
                sigma = s["sigma"] if standardize else 1.0
                if not np.isfinite(sigma) or sigma == 0.0:
                    sigma = 1.0
                di.columns.append(
                    ColumnSpec(
                        name,
                        "num",
                        mean=s["mean"] if np.isfinite(s["mean"]) else 0.0,
                        sigma=sigma,
                        offset=off,
                    )
                )
                off += 1
        for a, b in interaction_pairs or ():
            va, vb = frame.vec(a), frame.vec(b)
            if va.is_categorical() and vb.is_categorical():
                # combined-factor column (upstream enum-by-enum interaction):
                # one level per (level_a, level_b) cross pair
                da = tuple(va.domain or ())
                db = tuple(vb.domain or ())
                dom = tuple(f"{x}_{y}" for x in da for y in db)
                k = len(dom)
                width = k if use_all_factor_levels else max(1, k - 1)
                di.columns.append(
                    ColumnSpec(f"{a}:{b}", "cat", domain=dom, offset=off,
                               width=width, pair=(a, b),
                               pair_domains=(da, db))
                )
                off += width
                continue
            if va.is_categorical() or vb.is_categorical():
                cv, nv = (va, vb) if va.is_categorical() else (vb, va)
                k = cv.cardinality
                width = k if use_all_factor_levels else max(1, k - 1)
                di.columns.append(
                    ColumnSpec(f"{cv.name}:{nv.name}", "cat",
                               domain=cv.domain or (), offset=off,
                               width=width, pair=(cv.name, nv.name),
                               pair_means=(0.0, float(nv.mean())))
                )
                off += width
            else:
                # product stats on device (one tiny reduction) so the
                # interaction standardizes like any other numeric column
                ma, mb = float(va.mean()), float(vb.mean())
                xa = jnp.nan_to_num(va.data, nan=ma)
                xb = jnp.nan_to_num(vb.data, nan=mb)
                prod = xa * xb
                mask = frame.row_mask()
                sw = jnp.maximum(mask.sum(), 1.0)
                mean = float(jnp.sum(prod * mask) / sw)
                sigma = float(
                    jnp.sqrt(jnp.sum(mask * (prod - mean) ** 2) / sw)
                ) if standardize else 1.0
                if not np.isfinite(sigma) or sigma == 0.0:
                    sigma = 1.0
                di.columns.append(
                    ColumnSpec(f"{a}:{b}", "num",
                               mean=mean if standardize else 0.0, sigma=sigma,
                               offset=off, pair=(a, b), pair_means=(ma, mb))
                )
                off += 1
        di.ncols_expanded = off + (1 if add_intercept else 0)
        return di

    # -- expanded-column names (for coefficient tables) ----------------------
    def coef_names(self) -> list[str]:
        names = []
        for c in self.columns:
            if c.kind == "hash":
                names += [f"{c.name}.hash{i}" for i in range(c.width)]
            elif c.kind == "cat":
                lo = 0 if self.use_all_factor_levels else 1
                if c.pair_domains is not None:  # cat x cat combined factor
                    names += [f"{c.name}.{d}" for d in c.domain[lo : lo + c.width]]
                elif c.pair is not None:  # cat x num interaction block
                    names += [
                        f"{c.pair[0]}.{d}:{c.pair[1]}"
                        for d in c.domain[lo : lo + c.width]
                    ]
                else:
                    names += [f"{c.name}.{d}" for d in c.domain[lo : lo + c.width]]
            else:
                names.append(c.name)
        if self.add_intercept:
            names.append("Intercept")
        return names

    def transform(self, frame: Frame):
        """Build the (npad, p) float32 design matrix on device, plus a row
        validity mask folding in padding and (if skip-handling) NA rows."""
        cols = []
        valid = frame.row_mask()
        for c in self.columns:
            if c.pair is not None:
                col, valid = self._transform_interaction(frame, c, valid)
                cols.append(col)
                continue
            v = frame.vec(c.name)
            if c.kind == "hash":
                buckets = self._hashed_codes(v, c)
                if self.missing_handling == SKIP:
                    valid = valid * (buckets >= 0).astype(jnp.float32)
                # use_all_factor_levels=False drops bucket 0 (reference),
                # exactly like the cat path — see the hash_buckets field doc
                cols.append(
                    _expand_cat(
                        buckets, self.hash_buckets, c.width,
                        self.use_all_factor_levels,
                    )
                )
            elif c.kind == "cat":
                codes = _adapt_codes(v, c.domain)
                if self.missing_handling == SKIP:
                    valid = valid * (codes >= 0).astype(jnp.float32)
                cols.append(_expand_cat(codes, len(c.domain), c.width, self.use_all_factor_levels))
            else:
                data = v.data
                isna = jnp.isnan(data)
                if self.missing_handling == SKIP:
                    valid = valid * (~isna).astype(jnp.float32)
                x = jnp.where(isna, c.mean, data)
                if self.standardize:
                    x = (x - c.mean) / c.sigma
                elif self.missing_handling == SKIP:
                    x = jnp.where(isna, 0.0, x)
                cols.append(x[:, None])
        if self.add_intercept:
            cols.append(jnp.ones((frame.npad, 1), jnp.float32))
        X = jnp.concatenate(cols, axis=1)
        X = jax.device_put(X, row_sharding())
        # zero out invalid rows so they contribute nothing to reductions
        X = X * valid[:, None]
        return X, valid

    def _hashed_codes(self, v: Vec, c: ColumnSpec):
        """Device bucket codes for a hashed column, LUT-cached per column
        (most-recent domain) so steady-state scoring never re-pays the
        O(cardinality) host hash loop and the cache stays bounded by the
        model's column count."""
        hit = self._hash_luts.get(c.name)
        if hit is not None and hit[0] is v.domain:
            lut_dev = hit[1]
        else:
            lut_dev = _hash_lut(v.domain or (), c.name, self.hash_buckets)
            self._hash_luts[c.name] = (v.domain, lut_dev)
        return jnp.where(v.data >= 0, lut_dev[jnp.clip(v.data, 0)], -1)

    def _transform_interaction(self, frame: Frame, c: ColumnSpec, valid):
        """Interaction block: numeric product or onehot(cat) * numeric.

        NA imputation uses the TRAINING means (c.pair_means) — never the
        scoring batch's — and missing_handling=SKIP invalidates rows with
        missing sources exactly like the base columns do.
        """
        if c.pair_domains is not None:  # cat x cat combined factor
            va, vb = frame.vec(c.pair[0]), frame.vec(c.pair[1])
            da, db = c.pair_domains
            # int32 BEFORE the product: enum codes may be stored int8/int16
            # (narrowest-dtype compression) and ca*len(db)+cb overflows there
            ca = _adapt_codes(va, da).astype(jnp.int32)
            cb = _adapt_codes(vb, db).astype(jnp.int32)
            codes = jnp.where((ca >= 0) & (cb >= 0), ca * len(db) + cb, -1)
            if self.missing_handling == SKIP:
                valid = valid * (codes >= 0).astype(jnp.float32)
            oh = _expand_cat(
                codes, len(c.domain), c.width, self.use_all_factor_levels
            )
            return oh, valid
        if c.kind == "num":
            va, vb = frame.vec(c.pair[0]), frame.vec(c.pair[1])
            ma, mb = c.pair_means or (0.0, 0.0)
            na = jnp.isnan(va.data) | jnp.isnan(vb.data)
            if self.missing_handling == SKIP:
                valid = valid * (~na).astype(jnp.float32)
            xa = jnp.nan_to_num(va.data, nan=ma)
            xb = jnp.nan_to_num(vb.data, nan=mb)
            x = xa * xb
            if self.standardize:
                x = (x - c.mean) / c.sigma
            return x[:, None], valid
        cv, nv = frame.vec(c.pair[0]), frame.vec(c.pair[1])
        codes = _adapt_codes(cv, c.domain)
        if self.missing_handling == SKIP:
            valid = valid * (codes >= 0).astype(jnp.float32)
            valid = valid * (~jnp.isnan(nv.data)).astype(jnp.float32)
        oh = _expand_cat(codes, len(c.domain), c.width, self.use_all_factor_levels)
        x = jnp.nan_to_num(nv.data, nan=(c.pair_means or (0.0, 0.0))[1])
        return oh * x[:, None], valid


def _hash_lut(domain: tuple[str, ...], col_name: str, n_buckets: int):
    """Device LUT: level code -> hash bucket.

    The bucket of a level is ``crc32(col_name \\0 level) % n_buckets`` — a
    STABLE string hash (Python's ``hash()`` is process-salted), seeded by the
    column name so two hashed columns decorrelate. Because the hash sees the
    level STRING, train and scoring frames land in identical buckets with no
    domain adaptation, at any cardinality. One crc32 per LEVEL, so callers
    must cache per domain (``DataInfo._hashed_codes`` does); NA codes (< 0)
    stay NA (-1) → all-zero indicator row.
    """
    import zlib

    prefix = col_name.encode() + b"\x00"
    lut = np.fromiter(
        (zlib.crc32(prefix + d.encode()) % n_buckets for d in domain),
        dtype=np.int32,
        count=len(domain),
    )
    return jnp.asarray(np.append(lut, -1))  # slot keeps the gather in-bounds
                                            # for an empty domain


def _hash_codes(v: Vec, col_name: str, n_buckets: int):
    """Uncached convenience wrapper (tests / one-off use)."""
    lut_dev = _hash_lut(v.domain or (), col_name, n_buckets)
    return jnp.where(v.data >= 0, lut_dev[jnp.clip(v.data, 0)], -1)


def _adapt_codes(v: Vec, train_domain: tuple[str, ...]):
    """Remap a categorical Vec's codes onto the training domain — the
    ``CategoricalWrappedVec`` / ``adaptTestForTrain`` successor. Unseen
    levels map to NA (-1), matching H2O's default warning path."""
    if v.domain == train_domain:
        return v.data
    lut = {d: i for i, d in enumerate(train_domain)}
    remap = np.full(len(v.domain or ()) + 1, -1, dtype=np.int32)
    for j, d in enumerate(v.domain or ()):
        remap[j] = lut.get(d, -1)
    remap_dev = jnp.asarray(remap)
    return jnp.where(v.data >= 0, remap_dev[jnp.clip(v.data, 0)], -1)


def _expand_cat(codes, card: int, width: int, use_all: bool):
    """Dense indicator block; NA (-1) rows get all-zeros (mode-free encoding,
    mirroring H2O's missing-as-zero-row for expanded categoricals)."""
    base = 0 if use_all else 1
    shifted = codes - base
    onehot = (shifted[:, None] == jnp.arange(width)[None, :]).astype(jnp.float32)
    return onehot
