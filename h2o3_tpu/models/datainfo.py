"""Design-matrix view — successor of ``hex.DataInfo`` [UNVERIFIED upstream
path, SURVEY.md §2.2].

H2O's DataInfo gives GLM/DL/KMeans/PCA a canonical numeric view of a Frame:
categoricals expanded to indicator blocks, numerics standardized, missing
values imputed or skipped. Here the view is materialized as one row-sharded
``(npad, p)`` float32 device matrix — dense one-hot is MXU-friendly and XLA
fuses the expansion into downstream matmuls. Train-time statistics (means,
sigmas, domains) are captured so the identical transform applies to
validation/test frames (the ``adaptTestForTrain`` contract).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.frame import CAT, Frame, Vec
from h2o3_tpu.parallel.mesh import row_sharding

MEAN_IMPUTATION = "mean_imputation"
SKIP = "skip"


@dataclass
class ColumnSpec:
    name: str
    kind: str  # "num" | "cat"
    mean: float = 0.0
    sigma: float = 1.0
    domain: tuple[str, ...] = ()
    offset: int = 0  # first column index in the expanded matrix
    width: int = 1


@dataclass
class DataInfo:
    """Fitted design-matrix spec. Build with :meth:`fit`, apply with
    :meth:`transform`."""

    columns: list[ColumnSpec] = field(default_factory=list)
    standardize: bool = True
    use_all_factor_levels: bool = True
    missing_handling: str = MEAN_IMPUTATION
    add_intercept: bool = False
    ncols_expanded: int = 0

    @staticmethod
    def fit(
        frame: Frame,
        x: list[str],
        standardize: bool = True,
        use_all_factor_levels: bool = True,
        missing_handling: str = MEAN_IMPUTATION,
        add_intercept: bool = False,
    ) -> "DataInfo":
        di = DataInfo(
            standardize=standardize,
            use_all_factor_levels=use_all_factor_levels,
            missing_handling=missing_handling,
            add_intercept=add_intercept,
        )
        off = 0
        # H2O orders the expanded matrix categoricals-first, then numerics
        # [UNVERIFIED]; we keep the user's column order for readability of
        # coefficient names — the math is order-invariant.
        for name in x:
            v = frame.vec(name)
            if v.is_categorical():
                k = v.cardinality
                width = k if use_all_factor_levels else max(1, k - 1)
                di.columns.append(
                    ColumnSpec(name, "cat", domain=v.domain or (), offset=off, width=width)
                )
                off += width
            else:
                s = v.stats()
                sigma = s["sigma"] if standardize else 1.0
                if not np.isfinite(sigma) or sigma == 0.0:
                    sigma = 1.0
                di.columns.append(
                    ColumnSpec(
                        name,
                        "num",
                        mean=s["mean"] if np.isfinite(s["mean"]) else 0.0,
                        sigma=sigma,
                        offset=off,
                    )
                )
                off += 1
        di.ncols_expanded = off + (1 if add_intercept else 0)
        return di

    # -- expanded-column names (for coefficient tables) ----------------------
    def coef_names(self) -> list[str]:
        names = []
        for c in self.columns:
            if c.kind == "cat":
                lo = 0 if self.use_all_factor_levels else 1
                names += [f"{c.name}.{d}" for d in c.domain[lo : lo + c.width]]
            else:
                names.append(c.name)
        if self.add_intercept:
            names.append("Intercept")
        return names

    def transform(self, frame: Frame):
        """Build the (npad, p) float32 design matrix on device, plus a row
        validity mask folding in padding and (if skip-handling) NA rows."""
        cols = []
        valid = frame.row_mask()
        for c in self.columns:
            v = frame.vec(c.name)
            if c.kind == "cat":
                codes = _adapt_codes(v, c.domain)
                if self.missing_handling == SKIP:
                    valid = valid * (codes >= 0).astype(jnp.float32)
                cols.append(_expand_cat(codes, len(c.domain), c.width, self.use_all_factor_levels))
            else:
                data = v.data
                isna = jnp.isnan(data)
                if self.missing_handling == SKIP:
                    valid = valid * (~isna).astype(jnp.float32)
                x = jnp.where(isna, c.mean, data)
                if self.standardize:
                    x = (x - c.mean) / c.sigma
                elif self.missing_handling == SKIP:
                    x = jnp.where(isna, 0.0, x)
                cols.append(x[:, None])
        if self.add_intercept:
            cols.append(jnp.ones((frame.npad, 1), jnp.float32))
        X = jnp.concatenate(cols, axis=1)
        X = jax.device_put(X, row_sharding())
        # zero out invalid rows so they contribute nothing to reductions
        X = X * valid[:, None]
        return X, valid


def _adapt_codes(v: Vec, train_domain: tuple[str, ...]):
    """Remap a categorical Vec's codes onto the training domain — the
    ``CategoricalWrappedVec`` / ``adaptTestForTrain`` successor. Unseen
    levels map to NA (-1), matching H2O's default warning path."""
    if v.domain == train_domain:
        return v.data
    lut = {d: i for i, d in enumerate(train_domain)}
    remap = np.full(len(v.domain or ()) + 1, -1, dtype=np.int32)
    for j, d in enumerate(v.domain or ()):
        remap[j] = lut.get(d, -1)
    remap_dev = jnp.asarray(remap)
    return jnp.where(v.data >= 0, remap_dev[jnp.clip(v.data, 0)], -1)


def _expand_cat(codes, card: int, width: int, use_all: bool):
    """Dense indicator block; NA (-1) rows get all-zeros (mode-free encoding,
    mirroring H2O's missing-as-zero-row for expanded categoricals)."""
    base = 0 if use_all else 1
    shifted = codes - base
    onehot = (shifted[:, None] == jnp.arange(width)[None, :]).astype(jnp.float32)
    return onehot
