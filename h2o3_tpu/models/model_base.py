"""Model/ModelBuilder framework — successor of ``hex.ModelBuilder`` /
``hex.Model`` / ``hex.ScoreKeeper`` [UNVERIFIED upstream paths, SURVEY.md
§2.2].

Responsibilities mirrored from H2O:
- parameter validation and train/validation frame adaptation,
- response handling (enum → classification, numeric → regression),
- the cross-validation driver (N fold models as sub-jobs, holdout
  predictions aggregated for Stacked Ensembles, CV metrics),
- early stopping via a ScoreKeeper ring,
- ``Model.predict`` (the ``BigScore`` successor: a batched device scoring
  pass writing a new Frame) and ``model_performance``.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Sequence

import numpy as np

from h2o3_tpu.cluster.job import Job
from h2o3_tpu.cluster.registry import DKV
from h2o3_tpu.frame.frame import CAT, Frame, Vec
from h2o3_tpu.models import metrics as MM
from h2o3_tpu.utils import metrics as _mx
from h2o3_tpu.utils.log import Log
from h2o3_tpu.utils.timer import Timer

_MODELS_BUILT = _mx.counter(
    "models_built_total", "models trained to completion, by algo")


@dataclass
class CommonParams:
    training_frame: Any = None
    validation_frame: Any = None
    response_column: str | None = None
    ignored_columns: Sequence[str] = field(default_factory=tuple)
    weights_column: str | None = None
    offset_column: str | None = None
    nfolds: int = 0
    fold_assignment: str = "modulo"  # modulo | random
    keep_cross_validation_predictions: bool = False
    seed: int = -1
    max_runtime_secs: float = 0.0
    stopping_rounds: int = 0
    stopping_metric: str = "AUTO"
    stopping_tolerance: float = 1e-3
    # key (or Model) of a previous model to CONTINUE training from — more
    # trees for GBM/DRF, more epochs for DeepLearning (ref upstream
    # hex/ModelBuilder checkpoint plumbing, SURVEY.md §5.4)
    checkpoint: Any = None
    export_checkpoints_dir: str | None = None


class ScoreKeeper:
    """Early-stopping ring — successor of ``hex.ScoreKeeper``. H2O stops when
    the moving average of the last k scores stops improving on the best of
    the earlier window by more than the relative tolerance."""

    def __init__(self, rounds: int, tolerance: float, larger_is_better: bool):
        self.rounds = rounds
        self.tol = tolerance
        self.larger = larger_is_better
        self.history: list[float] = []

    def record(self, value: float) -> None:
        self.history.append(float(value))

    def should_stop(self) -> bool:
        k = self.rounds
        if k <= 0 or len(self.history) < 2 * k:
            return False
        h = np.array(self.history, dtype=np.float64)
        recent = h[-k:].mean()
        ref = h[:-k]
        best_ref = ref.max() if self.larger else ref.min()
        if self.larger:
            return recent <= best_ref * (1 + self.tol) - (0 if best_ref >= 0 else 2 * best_ref * self.tol)
        return recent >= best_ref * (1 - self.tol) + (0 if best_ref >= 0 else -2 * best_ref * self.tol)


def stopping_metric_direction(metric: str, classification: bool, nclasses: int) -> tuple[str, bool]:
    """Resolve AUTO and return (metric_name, larger_is_better)."""
    m = metric.lower()
    if m == "auto":
        # AUTO: logloss for classification, deviance for regression (h2o);
        # rmse orders identically to gaussian deviance and is always present
        m = "logloss" if classification else "rmse"
    elif m == "deviance":
        m = "logloss" if classification else "mean_residual_deviance"
    larger = m in ("auc", "pr_auc", "accuracy", "f1", "r2", "lift_top_group")
    return m, larger


class Model:
    """A trained model. Subclasses implement ``_predict_raw``."""

    algo = "base"

    def __init__(self, key: str, params, output: dict):
        self.key = key
        self.params = params
        self.output = output  # names/domains/varimp/... (the Model._output analog)
        self.training_metrics: MM.ModelMetrics | None = None
        self.validation_metrics: MM.ModelMetrics | None = None
        self.cross_validation_metrics: MM.ModelMetrics | None = None
        self.cv_predictions: np.ndarray | None = None  # holdout preds (for SE)
        self.cv_models: list["Model"] = []
        self.scoring_history: list[dict] = []
        self.run_time_ms: int = 0
        # fitted feature transformers (e.g. AutoML target encoding) applied
        # to incoming frames before scoring; transforms must be idempotent
        self.preprocessors: list = []
        DKV.put(key, self)

    def download_mojo(self, path: str) -> str:
        # lazy bootstrap: importing models.export rebinds Model.download_mojo
        # / save_mojo to the real implementation (the h2o surface), so direct
        # model users don't depend on estimator-module import order
        import h2o3_tpu.models.export  # noqa: F401

        return type(self).download_mojo(self, path)

    save_mojo = download_mojo

    # -- to be provided by subclasses ---------------------------------------
    def _predict_raw(self, frame: Frame) -> np.ndarray:
        """Regression: (n,) predictions. Classification: (n, K) class probs."""
        raise NotImplementedError

    # -- public surface ------------------------------------------------------
    @property
    def is_classifier(self) -> bool:
        return self.output.get("response_domain") is not None

    @property
    def nclasses(self) -> int:
        d = self.output.get("response_domain")
        return len(d) if d else 1

    def _apply_preprocessors(self, frame: Frame) -> Frame:
        for pre in self.preprocessors:
            frame = pre.transform(frame)
        return frame

    def predict(self, frame: Frame) -> Frame:
        """``model.predict`` — returns a Frame with ``predict`` (+ per-class
        probability columns for classifiers), matching the H2O layout."""
        frame = self._apply_preprocessors(frame)
        raw = self._predict_raw(frame)
        if not self.is_classifier:
            return Frame([Vec.from_numpy(np.asarray(raw), "real")], ["predict"])
        domain = self.output["response_domain"]
        probs = np.asarray(raw)
        if probs.ndim == 1:
            probs = np.stack([1 - probs, probs], axis=1)
        if self.nclasses == 2:
            # H2O uses max-F1 threshold for the binary label, not argmax
            thr = 0.5
            if self.training_metrics is not None:
                thr = self.training_metrics._v.get("default_threshold", 0.5)
            labels = (probs[:, 1] >= thr).astype(np.int32)
        else:
            labels = probs.argmax(axis=1).astype(np.int32)
        vecs = [Vec.from_numpy(labels, CAT, domain=domain)]
        names = ["predict"]
        for k, d in enumerate(domain):
            vecs.append(Vec.from_numpy(probs[:, k], "real"))
            names.append(str(d))
        cal = self.output.get("calibration")
        if cal is not None and probs.shape[1] == 2:
            from h2o3_tpu.models.calibration import apply_calibration

            cp1 = apply_calibration(cal, probs[:, 1])
            vecs.append(Vec.from_numpy(1.0 - cp1, "real"))
            names.append("cal_p0")
            vecs.append(Vec.from_numpy(cp1, "real"))
            names.append("cal_p1")
        return Frame(vecs, names)

    def model_performance(self, test_data: Frame | None = None) -> MM.ModelMetrics:
        if test_data is None:
            return self.training_metrics
        return self._score_metrics(test_data)

    def _response_and_weights(self, frame: Frame):
        y_name = self.params.response_column
        yv = frame.vec(y_name)
        y = yv.to_numpy()
        if self.is_classifier and yv.is_categorical():
            y = _remap_response(yv, self.output["response_domain"])
        w = None
        if self.params.weights_column:
            w = frame.vec(self.params.weights_column).to_numpy()
        return y, w

    def _score_metrics(self, frame: Frame) -> MM.ModelMetrics:
        frame = self._apply_preprocessors(frame)
        raw = np.asarray(self._predict_raw(frame))
        y, w = self._response_and_weights(frame)
        return _make_metrics(self, raw, y, w)

    def _distribution_for_metrics(self) -> str:
        return getattr(self.params, "distribution", "gaussian") or "gaussian"

    # -- persistence hooks (export layer fills these in) ---------------------
    def summary(self) -> dict:
        return {
            "algo": self.algo,
            "key": self.key,
            "classification": self.is_classifier,
            "nclasses": self.nclasses,
            "training_metrics": self.training_metrics.to_dict()
            if self.training_metrics
            else None,
            "validation_metrics": self.validation_metrics.to_dict()
            if self.validation_metrics
            else None,
            "run_time_ms": self.run_time_ms,
        }


def _remap_response(yv: Vec, domain) -> np.ndarray:
    if yv.domain == tuple(domain):
        return yv.to_numpy()
    lut = {d: i for i, d in enumerate(domain)}
    remap = np.full(len(yv.domain or ()) + 1, -1, dtype=np.int32)
    for j, d in enumerate(yv.domain or ()):
        remap[j] = lut.get(d, -1)
    codes = yv.to_numpy()
    return np.where(codes >= 0, remap[np.clip(codes, 0, None)], -1)


def _make_metrics(model: Model, raw: np.ndarray, y: np.ndarray, w) -> MM.ModelMetrics:
    if not model.is_classifier:
        return MM.regression_metrics(y, raw, w, model._distribution_for_metrics())
    domain = model.output["response_domain"]
    if raw.ndim == 1 or raw.shape[1] == 1:
        raw = raw.reshape(-1)
        return MM.binomial_metrics(y, raw, w, domain=domain)
    if raw.shape[1] == 2:
        return MM.binomial_metrics(y, raw[:, 1], w, domain=domain)
    return MM.multinomial_metrics(y.astype(np.int64), raw, w, domain=domain)


class ModelBuilder:
    """Base builder. Subclasses set ``algo`` / ``PARAMS_CLS`` and implement
    ``_build(job, train, valid) -> Model``."""

    algo = "base"
    PARAMS_CLS = CommonParams
    SUPPORTS_CLASSIFICATION = True
    SUPPORTS_REGRESSION = True
    # builders that honor weights_column can use weight-mask CV folds;
    # the rest fall back to physical row subsetting
    SUPPORTS_WEIGHTS = True

    def __init__(self, **kwargs):
        import dataclasses

        # builder-declared param aliases (XGBoost's eta, GLM's upstream
        # "lambda") resolve to their canonical field name here so every
        # entry point (REST, estimators, direct construction) accepts both
        for alias, canon in (getattr(self, "PARAM_ALIASES", None) or {}).items():
            if alias in kwargs:
                if canon in kwargs:
                    raise ValueError(
                        f"{alias!r} and {canon!r} are aliases — pass one"
                    )
                kwargs[canon] = kwargs.pop(alias)
        valid_names = {f.name for f in dataclasses.fields(self.PARAMS_CLS)}
        unknown = set(kwargs) - valid_names
        if unknown:
            raise ValueError(f"{self.algo}: unknown parameter(s) {sorted(unknown)}")
        self.params = self.PARAMS_CLS(**kwargs)
        self.model: Model | None = None
        self._x: list[str] = []
        # stable key for this build's periodic in-training snapshot (minted
        # on first export; every interval overwrites the same file so the
        # latest interval wins — docs/RECOVERY.md)
        self._ckpt_key: str | None = None

    # -- feature selection (ignored_columns / x handling) --------------------
    def _features(self, frame: Frame, y: str | None) -> list[str]:
        drop = set(self.params.ignored_columns or ())
        if y:
            drop.add(y)
        for extra in (self.params.weights_column, self.params.offset_column, getattr(self.params, "fold_column", None)):
            if extra:
                drop.add(extra)
        feats = [n for n in frame.names if n not in drop and frame.vec(n).kind != "string"]
        return feats

    def train(
        self,
        x: Sequence[str] | None = None,
        y: str | None = None,
        training_frame: Frame | None = None,
        validation_frame: Frame | None = None,
        **kwargs,
    ) -> Model:
        p = self.params
        if training_frame is not None:
            p.training_frame = training_frame
        if validation_frame is not None:
            p.validation_frame = validation_frame
        if y is not None:
            p.response_column = y
        train = _resolve_frame(p.training_frame)
        valid = _resolve_frame(p.validation_frame) if p.validation_frame is not None else None
        assert train is not None, "training_frame is required"
        if x is not None:
            self._x = [train.names[c] if isinstance(c, int) else str(c) for c in x]
        else:
            self._x = self._features(train, p.response_column)

        job = Job(lambda j: self._drive(j, train, valid), f"{self.algo} build")
        job.run_sync()
        return self.model

    # -- the Job body --------------------------------------------------------
    def _drive(self, job: Job, train: Frame, valid: Frame | None):
        p = self.params
        t = Timer()
        if getattr(p, "max_runtime_secs", 0.0):
            # soft budget: iterative builders poll job.stop_requested and
            # keep the partial model (h2o's per-model max_runtime contract)
            import time as _time

            job.soft_deadline = _time.time() + float(p.max_runtime_secs)
        self._validate(train, valid)
        if getattr(p, "checkpoint", None) is not None and p.nfolds and p.nfolds > 1:
            raise ValueError("checkpoint cannot be combined with cross-validation")
        with _mx.span(f"{self.algo}.build"):
            model = self._build(job, train, valid)
        model.run_time_ms = int(t.time_ms())
        self.model = model
        _MODELS_BUILT.inc(algo=self.algo)
        # cross-validation driver (after main model, like modern H2O order)
        if p.nfolds and p.nfolds > 1:
            with _mx.span(f"{self.algo}.cv", nfolds=p.nfolds):
                self._cross_validate(job, train)
        if getattr(p, "export_checkpoints_dir", None):
            # H2O semantics: every finished model auto-saves to the dir
            import os

            from h2o3_tpu.persist import save_model

            os.makedirs(p.export_checkpoints_dir, exist_ok=True)
            save_model(model, p.export_checkpoints_dir, force=True)
        Log.info(f"{self.algo} model {model.key} built in {t}")
        return model

    def _validate(self, train: Frame, valid: Frame | None) -> None:
        p = self.params
        if p.response_column is not None:
            assert p.response_column in train, f"response {p.response_column!r} not in frame"
            yv = train.vec(p.response_column)
            if getattr(p, "calibrate_model", False):
                # reject misconfiguration BEFORE the expensive build
                from h2o3_tpu.models.calibration import validate_calibration_params

                validate_calibration_params(p, yv)
            if yv.is_categorical() and not self.SUPPORTS_CLASSIFICATION:
                raise ValueError(f"{self.algo} does not support classification")
            if not yv.is_categorical() and not self.SUPPORTS_REGRESSION and self.algo != "glm":
                raise ValueError(f"{self.algo} does not support regression")

    def _build(self, job: Job, train: Frame, valid: Frame | None) -> Model:
        raise NotImplementedError

    # -- periodic in-training checkpoints (crash durability, SURVEY §5.3) ----
    def _export_interval_checkpoint(self, job: Job | None, make_model) -> str | None:
        """Snapshot the partial model to ``export_checkpoints_dir`` at a
        scoring-interval boundary.

        ``make_model(key)`` builds a throwaway Model holding the CURRENT
        partial state; it is serialized through the standard persist path
        (every-rank device pull, coordinator-only atomic+retried write — the
        ``_exec_model_save`` contract) and removed from the registry again.
        A kill -9 any time after this call loses at most one scoring
        interval: restart, ``load_model`` the snapshot, and pass it as
        ``checkpoint=`` to reproduce the uninterrupted run (pinned by the
        chaos suite). No-op unless ``export_checkpoints_dir`` is set."""
        p = self.params
        ckdir = getattr(p, "export_checkpoints_dir", None)
        if not ckdir:
            return None
        from h2o3_tpu import persist
        from h2o3_tpu.cluster import spmd

        if self._ckpt_key is None:
            self._ckpt_key = DKV.make_key(f"{self.algo}_ckpt")
        key = self._ckpt_key
        model = make_model(key)
        try:
            data = persist.serialize_model(model)  # every-rank pull
            backend, pth = persist.model_path_in_dir(ckdir, key)
            if spmd.is_coordinator():
                persist.write_model_bytes(data, backend, pth, key)
        finally:
            DKV.remove(key)  # snapshots never linger in the registry
        if job is not None:
            # surfaced over /3/Jobs: operators polling a failed job see
            # where to resume from (api/server._job_schema). set_recovery
            # walks the parent chain so the OUTER (REST-visible) job carries
            # the pointer, not just the nested builder job
            info = {
                "checkpoint_key": key,
                "checkpoint_path": pth,
                "hint": "load_model(checkpoint_path), then rebuild with "
                        "checkpoint=checkpoint_key to resume",
            }
            if hasattr(job, "set_recovery"):
                job.set_recovery(info)
            else:  # follower _JobShim
                job.recovery = info
        return pth

    # -- CV driver (successor of ModelBuilder.computeCrossValidation) --------
    def _cross_validate(self, job: Job, train: Frame) -> None:
        p = self.params
        n = train.nrow
        nfolds = int(p.nfolds)
        seed = p.seed if p.seed and p.seed > 0 else 12345
        if getattr(p, "fold_column", None):
            fold = train.vec(p.fold_column).to_numpy().astype(np.int64)
            folds = sorted(set(fold.tolist()))
        elif p.fold_assignment == "random":
            rng = np.random.default_rng(seed)
            fold = rng.integers(0, nfolds, size=n)
            folds = list(range(nfolds))
        else:  # modulo (default, deterministic like h2o AUTO for small data)
            fold = np.arange(n) % nfolds
            folds = list(range(nfolds))

        main = self.model
        # Folds are WEIGHT MASKS over the one padded sharded frame — every
        # fold model trains and predicts on identical shapes, so the compiled
        # programs from fold 1 are reused verbatim by folds 2..k and nothing
        # is re-uploaded (former subset_rows CV re-uploaded and re-compiled
        # per fold). Holdout rows carry weight 0: they contribute nothing to
        # histograms/Gram/SGD, metrics, or leaf values. (Quantile bin edges
        # still see holdout FEATURE values — a label-free approximation.)
        user_w = None
        if getattr(p, "weights_column", None):
            user_w = np.nan_to_num(train.vec(p.weights_column).to_numpy())
        y_all, w_all = None, None
        holdout: np.ndarray | None = None
        fold_metrics = []
        for fi, f in enumerate(folds):
            te_mask = fold == f
            sub = type(self)(**_params_dict(p, drop_cv=True))
            sub.params.response_column = p.response_column
            if self.SUPPORTS_WEIGHTS:
                w_np = (~te_mask).astype(np.float32)
                if user_w is not None:
                    w_np = w_np * user_w.astype(np.float32)
                fr_f = _with_cv_weights(train, w_np)
                sub.params.weights_column = _CV_WEIGHTS
            else:  # weights-unaware builder: physically remove holdout rows
                fr_f = train.subset_rows(~te_mask)
            m = sub.train(x=self._x, y=p.response_column, training_frame=fr_f)
            m_raw = np.asarray(m._predict_raw(train))  # full frame: fold-invariant shapes
            if holdout is None:
                holdout = np.zeros((n,) + m_raw.shape[1:], dtype=np.float64)
            holdout[te_mask] = m_raw[te_mask]
            if y_all is None:
                y_all, w_all = main._response_and_weights(train)
            w_arr = w_all if w_all is not None else np.ones(n)
            fold_metrics.append(
                _make_metrics(m, m_raw[te_mask], y_all[te_mask], np.asarray(w_arr)[te_mask])
            )
            main.cv_models.append(m)
            job.update(0.9 + 0.1 * (fi + 1) / len(folds))

        main.cross_validation_metrics = _make_metrics(main, holdout, y_all, w_all)
        if p.keep_cross_validation_predictions:
            main.cv_predictions = holdout


def resolve_checkpoint(cp) -> "Model | None":
    """Checkpoint param → prior Model (key lookup, pass-through, or — the
    kill→restart→resume runbook — a saved model/snapshot FILE path loaded
    through persist when the key is not in the registry)."""
    if cp is None:
        return None
    if isinstance(cp, Model):
        return cp
    got = DKV.get(str(cp))
    if isinstance(got, Model):
        return got
    try:
        from h2o3_tpu import persist

        backend, p = persist._backend_for(str(cp))
        found = backend.exists(p) and not backend.is_dir(p)
    except (ValueError, NotImplementedError):
        found = False
    if found:
        return persist.load_model(str(cp))
    raise ValueError(
        f"checkpoint {cp!r} is not a model in the DKV (nor a readable "
        "model/snapshot file)"
    )


def check_checkpoint_compat(prior: "Model", builder: "ModelBuilder", frozen: Sequence[str]) -> None:
    """H2O-style checkpoint restrictions: same algo, same feature set, and
    the structural hyperparameters unchanged (only budget params may grow)."""
    if prior.algo != builder.algo:
        raise ValueError(
            f"checkpoint algo {prior.algo!r} does not match builder {builder.algo!r}"
        )
    if list(prior.output.get("names", [])) != list(builder._x):
        raise ValueError("checkpoint was trained on a different feature set")
    for f in frozen:
        a, b = getattr(prior.params, f, None), getattr(builder.params, f, None)
        if a != b:
            raise ValueError(
                f"checkpoint requires {f} unchanged (was {a!r}, now {b!r})"
            )


_CV_WEIGHTS = "__cv_weights__"


def _with_cv_weights(train: Frame, w_np: np.ndarray) -> Frame:
    """A frame SHARING every vec of ``train`` plus the fold-weight column —
    no data movement beyond the single weight upload."""
    from h2o3_tpu.frame.frame import Vec

    wv = Vec.from_numpy(w_np, "num", _CV_WEIGHTS)
    names = [n for n in train.names if n != _CV_WEIGHTS]
    vecs = [train.vec(nm) for nm in names]
    return Frame(vecs + [wv], names + [_CV_WEIGHTS])


def _params_dict(p, drop_cv: bool) -> dict:
    import dataclasses

    d = {f.name: getattr(p, f.name) for f in dataclasses.fields(p)}
    d.pop("training_frame", None)
    d.pop("validation_frame", None)
    if drop_cv:
        d["nfolds"] = 0
        d["keep_cross_validation_predictions"] = False
        # fold models must NOT inherit continuation or auto-save: a checkpoint
        # was trained on all rows (holdout leakage), and export dirs would be
        # overwritten by every fold
        d["checkpoint"] = None
        d["export_checkpoints_dir"] = None
        # fold models' predict frames are never consumed — refitting the
        # calibrator per fold would be pure waste
        if "calibrate_model" in d:
            d["calibrate_model"] = False
    return d


def _resolve_frame(fr) -> Frame | None:
    if fr is None or isinstance(fr, Frame):
        return fr
    got = DKV.get(str(fr))
    assert isinstance(got, Frame), f"no frame under key {fr!r}"
    return got
