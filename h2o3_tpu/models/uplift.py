"""Uplift DRF — successor of ``hex.tree.uplift.UpliftDRF`` [UNVERIFIED
upstream path, SURVEY.md §2.2]: random forest for heterogeneous treatment
effect estimation (Rzepakowski & Jaroszewicz divergence splitting).

TPU design: the shared histogram fabric (ops/histogram.histogram_in_jit)
carries 4 stat channels; uplift repurposes them as
{w_treat, w_treat·y, w_ctrl, w_ctrl·y} so ONE histogram pass per level
yields both treatment and control class distributions per (node, col, bin).
A custom split scan computes the divergence gain

    gain = (n_L/n)·D(P_t^L, P_c^L) + (n_R/n)·D(P_t^R, P_c^R) − D(P_t, P_c)

for D ∈ {KL, Euclidean, ChiSquared} over the binary outcome distributions,
with prefix splits in natural bin order (numeric) and observed-uplift-sorted
order (categorical). Leaves carry the uplift estimate p_t − p_c; prediction
replay and tree recording reuse TreeLevel/_partition_update unchanged.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.cluster.job import Job
from h2o3_tpu.cluster.registry import DKV
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.model_base import CommonParams, Model, ModelBuilder
from h2o3_tpu.models.tree.binning import bin_frame, fit_bins, fit_bins_for
from h2o3_tpu.models.tree.shared_tree import (
    Tree,
    TreeLevel,
    _partition_update,
)
from h2o3_tpu.ops.histogram import histogram_in_jit
from h2o3_tpu.utils.log import Log

_NEG = -1e30


@dataclass
class UpliftDRFParams(CommonParams):
    nbins_cats: int = 1024  # categorical bin cap (shared tree semantics)
    treatment_column: str = "treatment"
    uplift_metric: str = "KL"  # KL | ChiSquared | Euclidean
    ntrees: int = 50
    max_depth: int = 10
    min_rows: float = 10.0
    mtries: int = -2  # -2 -> all columns (h2o uplift default differs from DRF)
    sample_rate: float = 0.632
    nbins: int = 255
    min_split_improvement: float = 1e-5
    score_tree_interval: int = 10


def _divergence(pt, pc, metric: str):
    """D(P_t || P_c) for Bernoulli distributions given success probs."""
    eps = 1e-9
    pt = jnp.clip(pt, eps, 1 - eps)
    pc = jnp.clip(pc, eps, 1 - eps)
    if metric == "kl":
        return pt * jnp.log(pt / pc) + (1 - pt) * jnp.log((1 - pt) / (1 - pc))
    if metric == "chisquared":
        return (pt - pc) ** 2 / pc + ((1 - pt) - (1 - pc)) ** 2 / (1 - pc)
    # euclidean
    return (pt - pc) ** 2 + ((1 - pt) - (1 - pc)) ** 2


def _node_div(s, metric, min_rows):
    """Per-cell divergence + validity from stacked stats (..., 4)."""
    wt, wyt, wc, wyc = s[..., 0], s[..., 1], s[..., 2], s[..., 3]
    pt = jnp.where(wt > 0, wyt / jnp.maximum(wt, 1e-30), 0.0)
    pc = jnp.where(wc > 0, wyc / jnp.maximum(wc, 1e-30), 0.0)
    d = _divergence(pt, pc, metric)
    ok = (wt >= min_rows) & (wc >= min_rows)
    return d, ok, wt + wc


def _uplift_split_scan(hist, is_cat, col_mask, min_rows, min_split_improvement,
                       metric: str):
    """Best divergence-gain split per node from hist (N, C, B, 4).

    Stats axis: 0=w_t, 1=w_t·y, 2=w_c, 3=w_c·y. Bin 0 is the NA bin.
    """
    N, C, B, _ = hist.shape
    total = hist.sum(axis=2)  # (N, C, 4)
    na = hist[:, :, 0, :]
    data = hist[:, :, 1:, :]

    d_parent, _, n_parent = _node_div(total[:, 0, :], metric, 0.0)  # (N,)

    def gain_of(L, R):
        dl, okl, nl = _node_div(L, metric, min_rows)
        dr, okr, nr = _node_div(R, metric, min_rows)
        n = jnp.maximum(nl + nr, 1e-30)
        g = (nl / n) * dl + (nr / n) * dr - d_parent[:, None, None]
        return jnp.where(okl & okr, g, _NEG)

    # numeric prefix
    cum = jnp.cumsum(data, axis=2)
    tot_nonna = cum[:, :, -1:, :]
    left = cum[:, :, :-1, :]
    right = tot_nonna - left
    g_nl = gain_of(left + na[:, :, None, :], right)
    g_nr = gain_of(left, right + na[:, :, None, :])
    g_num = jnp.maximum(g_nl, g_nr)  # (N, C, B-2)
    num_t = jnp.argmax(g_num, axis=2)
    num_gain = jnp.take_along_axis(g_num, num_t[:, :, None], 2).squeeze(2)
    num_na_left = (
        jnp.take_along_axis(g_nl, num_t[:, :, None], 2).squeeze(2)
        >= jnp.take_along_axis(g_nr, num_t[:, :, None], 2).squeeze(2)
    )

    # categorical: prefix in observed-uplift-sorted bin order (all columns —
    # masked to cat columns at selection; B is small enough that the extra
    # argsort on numeric columns is noise at uplift's typical C)
    wt_b, wc_b = data[..., 0], data[..., 2]
    up = jnp.where(wt_b > 0, data[..., 1] / jnp.maximum(wt_b, 1e-30), jnp.inf) - \
        jnp.where(wc_b > 0, data[..., 3] / jnp.maximum(wc_b, 1e-30), 0.0)
    order = jnp.argsort(up, axis=2)
    sdata = jnp.take_along_axis(data, order[..., None], axis=2)
    scum = jnp.cumsum(sdata, axis=2)
    s_tot = scum[:, :, -1:, :]
    s_left = scum[:, :, :-1, :]
    s_right = s_tot - s_left
    gc_nl = gain_of(s_left + na[:, :, None, :], s_right)
    gc_nr = gain_of(s_left, s_right + na[:, :, None, :])
    g_cat = jnp.maximum(gc_nl, gc_nr)
    cat_k = jnp.argmax(g_cat, axis=2)
    cat_gain = jnp.take_along_axis(g_cat, cat_k[:, :, None], 2).squeeze(2)
    cat_na_left = (
        jnp.take_along_axis(gc_nl, cat_k[:, :, None], 2).squeeze(2)
        >= jnp.take_along_axis(gc_nr, cat_k[:, :, None], 2).squeeze(2)
    )

    col_gain = jnp.where(is_cat[None, :], cat_gain, num_gain)
    col_gain = jnp.where(col_mask > 0, col_gain, _NEG)
    best_col = jnp.argmax(col_gain, axis=1)
    best_gain = jnp.take_along_axis(col_gain, best_col[:, None], 1).squeeze(1)

    take = lambda a: jnp.take_along_axis(a, best_col[:, None], 1).squeeze(1)
    split_bin = take(num_t) + 1
    bc_is_cat = is_cat[best_col]
    bc_na_left = jnp.where(bc_is_cat, take(cat_na_left), take(num_na_left))
    ranks = jnp.argsort(order, axis=2)
    idx = jnp.broadcast_to(best_col[:, None, None], (N, 1, ranks.shape[2]))
    best_ranks = jnp.take_along_axis(ranks, idx, axis=1).squeeze(1)
    cat_left = best_ranks <= take(cat_k)[:, None]
    cat_mask = jnp.concatenate([bc_na_left[:, None], cat_left], axis=1)

    wt, wyt, wc, wyc = (total[:, 0, s] for s in range(4))
    uplift = jnp.where(wt > 0, wyt / jnp.maximum(wt, 1e-30), 0.0) - jnp.where(
        wc > 0, wyc / jnp.maximum(wc, 1e-30), 0.0
    )
    ok = best_gain >= min_split_improvement

    return {
        "gain": best_gain, "ok": ok, "col": best_col, "is_cat": bc_is_cat,
        "split_bin": split_bin, "na_left": bc_na_left, "cat_mask": cat_mask,
        "node_w": wt + wc, "uplift": uplift,
    }


def _uplift_level_fn(
    bins_u8, nid, preds, varimp, wt, wyt, wc, wyc, key, is_cat,
    min_rows, min_split_improvement, col_sample_rate,
    *, n_pad: int, n_pad_next: int, n_bins: int, force_leaf: bool, metric: str,
):
    C = bins_u8.shape[1]
    hist = histogram_in_jit(bins_u8, nid, (wt, wyt, wc, wyc), n_pad, n_bins)

    if force_leaf:
        tot = hist[:, 0, :, :].sum(axis=1)
        wt_n, wyt_n, wc_n, wyc_n = (tot[:, s] for s in range(4))
        uplift = jnp.where(wt_n > 0, wyt_n / jnp.maximum(wt_n, 1e-30), 0.0) - \
            jnp.where(wc_n > 0, wyc_n / jnp.maximum(wc_n, 1e-30), 0.0)
        ok = jnp.zeros(n_pad, bool)
        gain = jnp.zeros(n_pad, jnp.float32)
        split_col = jnp.zeros(n_pad, jnp.int32)
        split_bin = jnp.zeros(n_pad, jnp.int32)
        is_cat_n = jnp.zeros(n_pad, bool)
        cat_mask = jnp.zeros((n_pad, n_bins), bool)
        na_left = jnp.zeros(n_pad, bool)
        node_w = wt_n + wc_n
    else:
        col_mask = jnp.ones((n_pad, C), jnp.float32)
        keep = jax.random.uniform(key, (n_pad, C)) < col_sample_rate
        keep = jnp.where(keep.any(axis=1, keepdims=True), keep, True)
        col_mask = col_mask * keep
        sp = _uplift_split_scan(
            hist, is_cat, col_mask, min_rows, min_split_improvement, metric
        )
        ok = sp["ok"]
        fits = 2 * jnp.cumsum(ok.astype(jnp.int32)) <= n_pad_next
        ok = ok & fits
        gain = jnp.where(ok, jnp.maximum(sp["gain"], 0.0), 0.0)
        split_col, split_bin = sp["col"], sp["split_bin"]
        is_cat_n, cat_mask, na_left = sp["is_cat"], sp["cat_mask"], sp["na_left"]
        uplift, node_w = sp["uplift"], sp["node_w"]

    leaf_now = ~ok
    leaf_val = jnp.where(leaf_now, uplift, 0.0).astype(jnp.float32)
    cs = jnp.cumsum(ok.astype(jnp.int32))
    child_base = jnp.where(ok, 2 * (cs - 1), 0).astype(jnp.int32)
    n_split = cs[-1] if n_pad else jnp.int32(0)
    varimp = varimp.at[split_col].add(jnp.where(ok, gain, 0.0).astype(varimp.dtype))

    nid, preds = _partition_update(
        bins_u8, nid, preds, split_col, split_bin, is_cat_n, cat_mask,
        na_left, leaf_now, leaf_val, child_base,
    )
    record = {
        "node_w": node_w.astype(jnp.float32),
        "split_col": split_col.astype(jnp.int32),
        "split_bin": split_bin.astype(jnp.int32),
        "is_cat": is_cat_n, "cat_mask": cat_mask, "na_left": na_left,
        "leaf_now": leaf_now, "leaf_val": leaf_val, "child_base": child_base,
        "gain": gain,
    }
    return nid, preds, varimp, n_split, record


_STEP_CACHE: dict = {}


def _uplift_level(n_pad, n_pad_next, n_bins, force_leaf, metric):
    key = (n_pad, n_pad_next, n_bins, force_leaf, metric, jax.default_backend())
    fn = _STEP_CACHE.get(key)
    if fn is None:
        fn = jax.jit(
            partial(
                _uplift_level_fn,
                n_pad=n_pad, n_pad_next=n_pad_next, n_bins=n_bins,
                force_leaf=force_leaf, metric=metric,
            )
        )
        _STEP_CACHE[key] = fn
    return fn


def _uplift_tree_program(max_depth: int, n_bins: int, node_cap: int,
                         metric: str):
    """Whole-tree uplift program (ISSUE 16: the last fused-matrix closure).

    All levels of one uplift tree trace into a single jitted dispatch —
    the 4-lane (wt, wyt, wc, wyc) scan runs through the same unrolled
    level structure the GBM/DRF whole-tree programs use. Levels past the
    point where every branch retired produce all-leaf placeholder records
    (zero histograms → no splits) that replay inertly, exactly like the
    fused GBM program's post-exit levels, so the recorded tree is
    bit-equal to the legacy per-level loop's on every REAL level."""
    key = ("uplift_tree", max_depth, n_bins, node_cap, metric,
           jax.default_backend())
    fn = _STEP_CACHE.get(key)
    if fn is None:

        def whole_tree(bins_u8, preds, varimp, wt, wyt, wc, wyc, key_,
                       is_cat, min_rows, msi, col_rate):
            nid = jnp.zeros(bins_u8.shape[0], jnp.int32)
            recs = []
            for depth in range(max_depth + 1):
                n_pad = min(1 << depth, node_cap)
                n_pad_next = min(2 * n_pad, node_cap)
                nid, preds, varimp, _, rec = _uplift_level_fn(
                    bins_u8, nid, preds, varimp, wt, wyt, wc, wyc,
                    jax.random.fold_in(key_, depth), is_cat,
                    min_rows, msi, col_rate,
                    n_pad=n_pad, n_pad_next=n_pad_next, n_bins=n_bins,
                    force_leaf=depth == max_depth, metric=metric,
                )
                recs.append(rec)
            return nid, preds, varimp, tuple(recs)

        fn = jax.jit(whole_tree, donate_argnums=(1, 2))
        _STEP_CACHE[key] = fn
    return fn


def _build_uplift_tree(bins_u8, wt, y, wc, *, n_bins, is_cat_cols, max_depth,
                       min_rows, min_split_improvement, col_sample_rate,
                       preds, key, varimp, metric, node_cap=1024):
    from h2o3_tpu.models.tree.shared_tree import (
        _split_fuse_active,
        _split_shard_on,
        use_fused_trees,
    )

    is_cat_dev = jnp.asarray(np.asarray(is_cat_cols, bool))
    wyt = wt * y
    wyc = wc * y
    tree = Tree()
    if use_fused_trees(max_depth):
        prog = _uplift_tree_program(max_depth, n_bins, node_cap, metric)
        _, preds, varimp, records = prog(
            bins_u8, preds, varimp, wt, wyt, wc, wyc, key, is_cat_dev,
            jnp.float32(min_rows), jnp.float32(min_split_improvement),
            jnp.float32(col_sample_rate),
        )
        for rec in records:
            tree.levels.append(TreeLevel(**rec))
        return tree, preds, varimp
    # legacy per-level host loop (H2O3_TPU_WHOLE_TREE=0 / depth cap): the
    # only remaining structural fallback — tally it per tree when the fuse
    # gate wanted the fused lane (ISSUE 15/16 observability)
    _split_fuse_active((), _split_shard_on(), uplift=True)
    nid = jnp.zeros(bins_u8.shape[0], jnp.int32)
    for depth in range(max_depth + 1):
        n_pad = min(1 << depth, node_cap)
        n_pad_next = min(2 * n_pad, node_cap)
        force_leaf = depth == max_depth
        step = _uplift_level(n_pad, n_pad_next, n_bins, force_leaf, metric)
        nid, preds, varimp, n_split, rec = step(
            bins_u8, nid, preds, varimp, wt, wyt, wc, wyc,
            jax.random.fold_in(key, depth), is_cat_dev,
            jnp.float32(min_rows), jnp.float32(min_split_improvement),
            jnp.float32(col_sample_rate),
        )
        tree.levels.append(TreeLevel(**rec))
        if force_leaf:
            break
        if jax.default_backend() == "cpu" and int(n_split) == 0:
            break
    return tree, preds, varimp


class UpliftDRFModel(Model):
    algo = "upliftdrf"

    def _predict_raw(self, frame: Frame) -> np.ndarray:
        bins = bin_frame(self.output["bin_spec"], frame)
        preds = jnp.zeros(bins.shape[0], jnp.float32)
        for tree in self.output["trees"]:
            _, preds = tree.replay(
                bins, jnp.zeros(bins.shape[0], jnp.int32), preds
            )
        uplift = np.asarray(preds)[: frame.nrow] / max(
            self.output["ntrees_actual"], 1
        )
        return uplift

    def predict(self, frame: Frame) -> Frame:
        frame = self._apply_preprocessors(frame)
        u = self._predict_raw(frame)
        return Frame.from_arrays({"uplift_predict": u})

    def _score_metrics(self, frame: Frame):
        # AUUC (area under the uplift curve) — the uplift model's metric
        from h2o3_tpu.models import metrics as MM

        u = self._predict_raw(frame)
        y = frame.vec(self.params.response_column).to_numpy()
        t_codes = frame.vec(self.params.treatment_column).to_numpy()
        return _auuc_metrics(u, y, t_codes)


def _auuc_metrics(uplift: np.ndarray, y: np.ndarray, treat: np.ndarray,
                  n_bins: int = 1000):
    """Qini/AUUC from predicted uplift, actual outcome, treatment flag."""
    from h2o3_tpu.models.metrics import ModelMetrics

    order = np.argsort(-uplift)
    y_s = y[order]
    t_s = (treat[order] > 0).astype(np.float64)
    n = len(y_s)
    ct = np.cumsum(t_s)
    cc = np.cumsum(1 - t_s)
    cyt = np.cumsum(y_s * t_s)
    cyc = np.cumsum(y_s * (1 - t_s))
    with np.errstate(divide="ignore", invalid="ignore"):
        # qini-style cumulative uplift at each cut
        lift = cyt - np.where(cc > 0, cyc * ct / np.maximum(cc, 1), 0.0)
    idx = np.linspace(0, n - 1, min(n, n_bins)).astype(np.int64)
    auuc = float(np.trapezoid(lift[idx], idx) / n)
    # random-targeting baseline for qini coefficient
    total = lift[-1]
    rand_area = float(total * (n - 1) / 2.0 / n)
    qini = auuc - rand_area
    ate = float(
        (cyt[-1] / max(ct[-1], 1)) - (cyc[-1] / max(cc[-1], 1))
    )
    return ModelMetrics(
        "uplift",
        {"auuc": auuc, "qini": qini, "ate": ate, "nobs": float(n)},
    )


class UpliftDRF(ModelBuilder):
    algo = "upliftdrf"
    PARAMS_CLS = UpliftDRFParams
    SUPPORTS_REGRESSION = False

    def _build(self, job: Job, train: Frame, valid: Frame | None):
        p: UpliftDRFParams = self.params
        if p.ntrees < 1 or p.max_depth < 1:
            raise ValueError("ntrees and max_depth must be >= 1")
        yv = train.vec(p.response_column)
        if not yv.is_categorical() or yv.cardinality > 2:
            raise ValueError("upliftdrf needs a binary categorical response")
        tv = train.vec(p.treatment_column)
        if not tv.is_categorical() or tv.cardinality > 2:
            raise ValueError("treatment_column must be a 2-level factor")
        metric = p.uplift_metric.lower()
        if metric not in ("kl", "chisquared", "euclidean"):
            raise ValueError(f"unknown uplift_metric {p.uplift_metric!r}")

        feats = [n for n in self._x if n != p.treatment_column]
        spec = fit_bins_for(p, train, feats)
        bins = bin_frame(spec, train)
        npad = train.npad
        C = len(feats)

        y_np = yv.to_numpy().astype(np.float64)
        t_np = tv.to_numpy().astype(np.float64)
        base_w = np.zeros(npad, np.float32)
        base_w[: train.nrow] = 1.0
        if p.weights_column:
            base_w[: train.nrow] *= np.nan_to_num(
                train.vec(p.weights_column).to_numpy()
            ).astype(np.float32)
        base_w[: train.nrow] *= (y_np >= 0) & (t_np >= 0)
        ybuf = np.zeros(npad, np.float32)
        ybuf[: train.nrow] = np.clip(np.nan_to_num(y_np, nan=0.0), 0, 1)
        tbuf = np.zeros(npad, np.float32)
        tbuf[: train.nrow] = np.clip(np.nan_to_num(t_np, nan=0.0), 0, 1)
        w = jnp.asarray(base_w)
        y = jnp.asarray(ybuf)
        tr = jnp.asarray(tbuf)

        mtries = p.mtries
        if mtries in (-1, 0):
            mtries = max(1, int(np.sqrt(C)))
        elif mtries == -2:
            mtries = C
        col_rate = min(1.0, mtries / C)

        rngkey = jax.random.PRNGKey(abs(p.seed) if p.seed and p.seed > 0 else 97)
        preds = jnp.zeros(npad, jnp.float32)
        varimp = jnp.zeros(C, jnp.float32)
        trees: list[Tree] = []
        for m in range(p.ntrees):
            if job.stop_requested:
                break
            rngkey, sk = jax.random.split(rngkey)
            mask = jax.random.bernoulli(sk, p.sample_rate, (npad,)).astype(
                jnp.float32
            )
            w_tree = w * mask
            tree, preds, varimp = _build_uplift_tree(
                bins, w_tree * tr, y, w_tree * (1.0 - tr),
                n_bins=spec.max_bins, is_cat_cols=spec.is_cat,
                max_depth=p.max_depth, min_rows=p.min_rows,
                min_split_improvement=p.min_split_improvement,
                col_sample_rate=col_rate, preds=preds,
                key=jax.random.fold_in(rngkey, m), varimp=varimp,
                metric=metric,
            )
            trees.append(tree)
            job.update(0.05 + 0.9 * (m + 1) / p.ntrees)

        out = {
            "bin_spec": spec,
            "trees": trees,
            "names": feats,
            "varimp": np.asarray(varimp).astype(np.float64),
            "response_domain": tuple(yv.domain),
            "treatment_domain": tuple(tv.domain),
            "ntrees_actual": len(trees),
        }
        model = UpliftDRFModel(DKV.make_key("upliftdrf"), p, out)
        model.training_metrics = model._score_metrics(train)
        if valid is not None:
            model.validation_metrics = model._score_metrics(valid)
        return model
