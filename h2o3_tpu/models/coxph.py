"""Cox proportional hazards — successor of ``hex.coxph.CoxPH`` [UNVERIFIED
upstream path, SURVEY.md §2.2].

Newton–Raphson on the partial log-likelihood with Breslow or Efron tie
handling (Efron is H2O's default). The heavy per-iteration quantities —
risk-set sums of exp(Xβ), x·exp(Xβ), and xxᵀ·exp(Xβ) over rows sorted by
stop time — are reverse cumulative sums over the sorted design matrix, one
jitted device program per iteration; the p×p Newton solve runs on host in
float64 (p is small). Rows sort once at setup.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.cluster.job import Job
from h2o3_tpu.cluster.registry import DKV
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.metrics import ModelMetrics
from h2o3_tpu.models.model_base import CommonParams, Model, ModelBuilder


@dataclass
class CoxPHParams(CommonParams):
    start_column: str | None = None
    stop_column: str | None = None  # defaults to the response column
    ties: str = "efron"  # efron | breslow
    max_iterations: int = 20
    tolerance: float = 1e-8


class CoxPHModel(Model):
    algo = "coxph"

    def _predict_raw(self, frame: Frame) -> np.ndarray:
        """Linear predictor (log partial hazard), centered like H2O/R."""
        beta = self.output["coefficients"]
        means = self.output["x_means"]
        X = np.stack(
            [frame.vec(c).to_numpy().astype(np.float64) for c in self.output["names"]],
            axis=1,
        )
        return (np.nan_to_num(X) - means[None, :]) @ beta

    def concordance(self) -> float:
        return self.training_metrics.value("concordance")


class CoxPH(ModelBuilder):
    algo = "coxph"
    PARAMS_CLS = CoxPHParams
    SUPPORTS_CLASSIFICATION = False

    def _build(self, job: Job, train: Frame, valid: Frame | None):
        p: CoxPHParams = self.params
        # call shape: response_column = event indicator 0/1,
        # stop_column = time (the common (time, event) pair)
        assert p.stop_column, "CoxPH needs stop_column (the time column)"
        times = train.vec(p.stop_column).to_numpy().astype(np.float64)
        ev_v = train.vec(p.response_column)
        event = ev_v.to_numpy().astype(np.float64)
        if ev_v.is_categorical():
            event = (event == 1).astype(np.float64)
        cols = [c for c in self._x if c not in (p.stop_column, p.response_column)]
        X = np.stack([train.vec(c).to_numpy().astype(np.float64) for c in cols], axis=1)

        ok = ~np.isnan(times) & ~np.isnan(event) & ~np.isnan(X).any(axis=1)
        times, event, X = times[ok], event[ok], X[ok]
        x_means = X.mean(axis=0)
        Xc = X - x_means[None, :]

        # sort by DESCENDING time so risk sets are prefix sums
        order = np.argsort(-times, kind="mergesort")
        times, event, Xc = times[order], event[order], Xc[order]
        n, d = Xc.shape

        # tie groups (equal event times)
        _, grp_start = np.unique(-times, return_index=True)
        grp_id = np.zeros(n, np.int64)
        grp_id[grp_start] = 1
        grp_id = np.cumsum(grp_id) - 1

        Xd = jnp.asarray(Xc)
        ev = jnp.asarray(event)
        gid = jnp.asarray(grp_id)
        n_grp = int(grp_id.max()) + 1
        efron = p.ties.lower() == "efron"

        @jax.jit
        def ll_grad_hess(beta):
            eta = Xd @ beta
            r = jnp.exp(eta)
            # prefix sums over descending time = risk-set sums at each row
            S0 = jnp.cumsum(r)
            S1 = jnp.cumsum(Xd * r[:, None], axis=0)
            S2 = jnp.cumsum(r[:, None, None] * (Xd[:, :, None] * Xd[:, None, :]), axis=0)
            # per-group risk-set values = value at the group's LAST row
            glast = jax.ops.segment_max(jnp.arange(n), gid, n_grp)
            s0 = S0[glast]
            s1 = S1[glast]
            s2 = S2[glast]
            # per-group event sums
            dsum = jax.ops.segment_sum(ev, gid, n_grp)
            zsum = jax.ops.segment_sum(Xd * ev[:, None], gid, n_grp)
            esum0 = jax.ops.segment_sum(r * ev, gid, n_grp)
            esum1 = jax.ops.segment_sum(Xd * (r * ev)[:, None], gid, n_grp)
            esum2 = jax.ops.segment_sum(
                (r * ev)[:, None, None] * (Xd[:, :, None] * Xd[:, None, :]), gid, n_grp
            )
            ll_ev = jax.ops.segment_sum(eta * ev, gid, n_grp)

            MAXD = 32  # Efron correction unrolled over within-group event rank

            def group_terms(args):
                s0g, s1g, s2g, dg, e0, e1, e2, llg = args
                ll = llg
                g = jnp.zeros(d)
                H = jnp.zeros((d, d))
                for l in range(MAXD):
                    active = l < dg
                    frac = jnp.where(dg > 0, l / jnp.maximum(dg, 1.0), 0.0) if efron else 0.0
                    phi0 = s0g - frac * e0
                    phi1 = s1g - frac * e1
                    phi2 = s2g - frac * e2
                    phi0 = jnp.maximum(phi0, 1e-300)
                    ll = ll - jnp.where(active, jnp.log(phi0), 0.0)
                    g = g - jnp.where(active, phi1 / phi0, 0.0)
                    H = H - jnp.where(
                        active,
                        phi2 / phi0 - jnp.outer(phi1, phi1) / (phi0**2),
                        0.0,
                    )
                return ll, g, H

            lls, gs, Hs = jax.vmap(group_terms)(
                (s0, s1, s2, dsum, esum0, esum1, esum2, ll_ev)
            )
            grad = zsum.sum(axis=0) + gs.sum(axis=0)
            return lls.sum(), grad, Hs.sum(axis=0)

        beta = jnp.zeros(d)
        ll_prev = -np.inf
        iters = 0
        for it in range(p.max_iterations):
            ll, grad, H = ll_grad_hess(beta)
            ll = float(ll)
            Hn = np.asarray(H, np.float64)
            gn = np.asarray(grad, np.float64)
            try:
                delta = np.linalg.solve(Hn - 1e-9 * np.eye(d), -gn)
            except np.linalg.LinAlgError:
                break
            beta = beta + jnp.asarray(delta)
            iters = it + 1
            job.update(0.05 + 0.85 * (it + 1) / p.max_iterations)
            if abs(ll - ll_prev) < p.tolerance * (abs(ll) + 1e-9):
                break
            ll_prev = ll

        beta_np = np.asarray(beta, np.float64)
        out = {
            "coefficients": beta_np,
            "coef_names": cols,
            "names": cols,
            "x_means": x_means,
            "loglik": float(ll),
            "n": int(n),
            "n_events": int(event.sum()),
            "response_domain": None,
        }
        model = CoxPHModel(DKV.make_key("coxph"), p, out)
        # concordance (Harrell's C) on the training data
        eta = Xc @ beta_np
        conc = _concordance(times, event, eta)
        model.training_metrics = ModelMetrics(
            "coxph",
            {"loglik": float(ll), "concordance": conc, "iterations": iters,
             "n": int(n), "n_events": int(event.sum())},
        )
        return model


def _concordance(times, event, eta) -> float:
    """Harrell's C on (possibly subsampled) pairs — O(n²) capped at 3k rows."""
    n = len(times)
    if n > 3000:
        idx = np.random.default_rng(0).choice(n, 3000, replace=False)
        times, event, eta = times[idx], event[idx], eta[idx]
        n = 3000
    conc = ties = total = 0.0
    for i in range(n):
        if event[i] != 1:
            continue
        cmp = times > times[i]
        total += cmp.sum()
        conc += (eta[cmp] < eta[i]).sum()
        ties += (eta[cmp] == eta[i]).sum()
    return float((conc + 0.5 * ties) / total) if total > 0 else float("nan")
