"""RuleFit — successor of ``hex.rulefit.RuleFit`` / ``RuleFitModel``
[UNVERIFIED upstream paths, SURVEY.md §2.2].

Friedman-Popescu RuleFit (2008): (1) grow a depth-limited tree ensemble,
(2) turn every root->node path into a binary rule, (3) fit a sparse linear
model (LASSO GLM) over the rule indicators plus (optionally) the winsorised
linear terms.

TPU design: rules are *bin-mask conjunctions* over the shared uint8 binned
design matrix (models/tree/binning.py) — one (L, B) boolean mask table per
rule, evaluated on device as gather+all, so rule evaluation is a handful of
fused programs rather than per-rule host loops. The sparse fit reuses the
GLM builder (alpha=1 elastic net, ADMM); lambda is chosen on an internal
80/20 holdout by deviance, mirroring H2O's default-glm selection intent.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.cluster.job import Job
from h2o3_tpu.cluster.registry import DKV
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.model_base import CommonParams, Model, ModelBuilder
from h2o3_tpu.models.tree.binning import BinSpec, bin_frame
from h2o3_tpu.utils.log import Log


@dataclass
class RuleFitParams(CommonParams):
    algorithm: str = "AUTO"  # AUTO -> DRF (h2o default)
    min_rule_length: int = 3
    max_rule_length: int = 3
    max_num_rules: int = -1  # -1 -> derived cap (h2o: based on ntrees)
    model_type: str = "rules_and_linear"  # rules_and_linear | rules | linear
    rule_generation_ntrees: int = 50
    distribution: str = "AUTO"
    lambda_: float | None = None  # explicit LASSO lambda (skips holdout pick)
    remove_duplicates: bool = True


class _Rule:
    """Conjunction of per-column bin-mask conditions."""

    __slots__ = ("cols", "masks", "support", "name", "text")

    def __init__(self, cols: list[int], masks: list[np.ndarray]):
        self.cols = cols
        self.masks = masks  # each (B,) bool over bin codes
        self.support = 0.0
        self.name = ""
        self.text = ""

    def key(self) -> tuple:
        items = sorted(zip(self.cols, [m.tobytes() for m in self.masks]))
        return tuple(items)


def _node_condition_masks(nd, B: int):
    """Left/right bin-code masks for one split node (code 0 = NA)."""
    left = np.zeros(B, bool)
    if nd.is_cat:
        cm = np.asarray(nd.cat_mask).astype(bool)
        left[: min(B, len(cm))] = cm[:B]
        left[0] = nd.na_left
    else:
        left[1 : nd.thr_bin + 1] = True
        left[0] = nd.na_left
    right = ~left
    return left, right


def _extract_rules(trees, B: int, max_len: int) -> list[_Rule]:
    """Every root->node path (depth>=1) in every tree becomes a rule.

    Conditions on the same column along a path AND together into one mask.
    """
    from h2o3_tpu.models.tree.shap import _tree_nodes

    rules: list[_Rule] = []

    for tree in trees:
        nodes = _tree_nodes(tree)
        if not nodes:
            continue

        def walk(j: int, conds: dict[int, np.ndarray], depth: int):
            nd = nodes[j]
            if conds:
                cols = sorted(conds)
                rules.append(_Rule(cols, [conds[c].copy() for c in cols]))
            if nd.is_leaf or nd.left < 0 or depth >= max_len:
                return
            lmask, rmask = _node_condition_masks(nd, B)
            for child, m in ((nd.left, lmask), (nd.right, rmask)):
                nc = dict(conds)
                nc[nd.feature] = (nc[nd.feature] & m) if nd.feature in nc else m
                walk(child, nc, depth + 1)

        walk(0, {}, 0)
    return rules


_EVAL_PROG: dict = {}


def _eval_rules(bins, cols, masks, valid):
    """Device rule evaluation: (n, Rchunk) float32 membership matrix.

    bins (n, C) uint8; cols (R, L) int32; masks (R, L, B) bool; valid (R, L).
    """
    key = (cols.shape, masks.shape[-1], jax.default_backend())
    prog = _EVAL_PROG.get(key)
    if prog is None:

        def run(bins, cols, masks, valid):
            def per_rule(colr, maskr, validr):
                codes = bins[:, colr].astype(jnp.int32)  # (n, L)
                hit = jnp.take_along_axis(maskr.T, codes, axis=0)  # (n, L)
                sat = jnp.where(validr[None, :], hit, True)
                return sat.all(axis=1)

            out = jax.vmap(per_rule)(cols, masks, valid)  # (R, n)
            return out.T.astype(jnp.float32)

        prog = jax.jit(run)
        _EVAL_PROG[key] = prog
    return prog(bins, cols, masks, valid)


def _rule_text(rule: _Rule, spec: BinSpec) -> str:
    parts = []
    for col, mask in zip(rule.cols, rule.masks):
        name = spec.names[col]
        if spec.is_cat[col]:
            dom = spec.domains[col] if spec.domains else None
            lvls = [
                str(dom[b - 1]) if dom and b - 1 < len(dom) else str(b - 1)
                for b in range(1, len(mask))
                if mask[b]
            ]
            parts.append(f"{name} in {{{', '.join(lvls)}}}")
        else:
            nb = int(spec.nbins[col])
            e = spec.edges[col]
            data_bins = np.where(mask[1 : nb + 1])[0] + 1  # codes with mask set
            if len(data_bins) == 0:
                parts.append(f"{name} is NA")
                continue
            lo_b, hi_b = int(data_bins.min()), int(data_bins.max())
            seg = []
            if lo_b > 1:
                seg.append(f"{name} > {e[lo_b - 2]:.6g}")
            if hi_b < nb:
                seg.append(f"{name} <= {e[hi_b - 1]:.6g}")
            if not seg:
                seg.append(f"{name} any")
            parts.append(" & ".join(seg))
    return " & ".join(parts)


class RuleFitModel(Model):
    algo = "rulefit"

    def _rule_frame(self, frame: Frame) -> Frame:
        o = self.output
        cols: dict[str, np.ndarray] = {}
        for n in o["linear_names"]:
            cols[f"linear.{n}"] = frame.vec(n).to_numpy()
        if o["rule_names"]:
            bins = bin_frame(o["bin_spec"], frame)
            R = np.asarray(
                _eval_rules(
                    bins,
                    jnp.asarray(o["rule_cols"]),
                    jnp.asarray(o["rule_masks"]),
                    jnp.asarray(o["rule_valid"]),
                )
            )[: frame.nrow]
            for ri, n in enumerate(o["rule_names"]):
                cols[n] = R[:, ri]
        return Frame.from_arrays(cols)

    def _predict_raw(self, frame: Frame) -> np.ndarray:
        return self.output["glm_model"]._predict_raw(self._rule_frame(frame))

    def rule_importance(self) -> list[dict]:
        return self.output["rule_importance"]

    def _distribution_for_metrics(self) -> str:
        return "gaussian"


class RuleFit(ModelBuilder):
    algo = "rulefit"
    PARAMS_CLS = RuleFitParams

    def _build(self, job: Job, train: Frame, valid: Frame | None) -> Model:
        from h2o3_tpu.models.glm import GLM
        from h2o3_tpu.models.tree.drf import DRF
        from h2o3_tpu.models.tree.gbm import GBM

        p: RuleFitParams = self.params
        if p.min_rule_length > p.max_rule_length:
            raise ValueError("min_rule_length must be <= max_rule_length")
        yv = train.vec(p.response_column)
        classification = yv.is_categorical()
        family = "binomial" if classification and yv.cardinality <= 2 else (
            "multinomial" if classification else "gaussian"
        )
        if family == "multinomial":
            raise ValueError("rulefit supports regression and binomial only")

        rule_names: list[str] = []
        rules: list[_Rule] = []
        spec = None
        if p.model_type in ("rules_and_linear", "rules"):
            depths = list(range(p.min_rule_length, p.max_rule_length + 1))
            per_depth = max(1, p.rule_generation_ntrees // len(depths))
            algo = p.algorithm.upper()
            if algo == "AUTO":
                algo = "DRF"
            for di, depth in enumerate(depths):
                cls = DRF if algo == "DRF" else GBM
                kw = dict(
                    ntrees=per_depth,
                    max_depth=depth,
                    seed=(abs(p.seed) or 1) + di,
                    response_column=p.response_column,
                    ignored_columns=p.ignored_columns,
                )
                if cls is DRF:
                    kw["sample_rate"] = 0.5
                ens = cls(**kw).train(
                    y=p.response_column, training_frame=train, x=self._x
                )
                spec = ens.output["bin_spec"]
                B = spec.max_bins
                for group in ens.output["trees"]:
                    for tree in group:
                        rules.extend(_extract_rules([tree], B, depth))
                job.update(0.05 + 0.4 * (di + 1) / len(depths))

            if p.remove_duplicates:
                seen: dict[tuple, _Rule] = {}
                for r in rules:
                    seen.setdefault(r.key(), r)
                rules = list(seen.values())

            cap = p.max_num_rules if p.max_num_rules > 0 else 1500
            if len(rules) > cap:
                rules = rules[:cap]

        # evaluate rule matrix on the training frame
        cols_np: dict[str, np.ndarray] = {}
        linear_names: list[str] = []
        if p.model_type in ("rules_and_linear", "linear"):
            for n in self._x:
                v = train.vec(n)
                if v.is_numeric():
                    linear_names.append(n)
                    cols_np[f"linear.{n}"] = v.to_numpy()

        rule_cols = rule_masks = rule_valid = None
        if rules:
            L = max(len(r.cols) for r in rules)
            B = spec.max_bins
            Rn = len(rules)
            rule_cols = np.zeros((Rn, L), np.int32)
            rule_masks = np.zeros((Rn, L, B), bool)
            rule_valid = np.zeros((Rn, L), bool)
            for ri, r in enumerate(rules):
                for li, (c, m) in enumerate(zip(r.cols, r.masks)):
                    rule_cols[ri, li] = c
                    rule_masks[ri, li] = m[:B]
                    rule_valid[ri, li] = True
            bins = bin_frame(spec, train)
            chunks = []
            for s in range(0, Rn, 512):
                chunks.append(
                    np.asarray(
                        _eval_rules(
                            bins,
                            jnp.asarray(rule_cols[s : s + 512]),
                            jnp.asarray(rule_masks[s : s + 512]),
                            jnp.asarray(rule_valid[s : s + 512]),
                        )
                    )[: train.nrow]
                )
            Rmat = np.concatenate(chunks, axis=1)
            support = Rmat.mean(axis=0)
            # drop degenerate rules (all-0 / all-1)
            keep = (support > 1e-6) & (support < 1 - 1e-6)
            rules = [r for r, k in zip(rules, keep) if k]
            Rmat = Rmat[:, keep]
            rule_cols, rule_masks, rule_valid = (
                rule_cols[keep], rule_masks[keep], rule_valid[keep],
            )
            for ri, r in enumerate(rules):
                r.support = float(Rmat[:, ri].mean())
                r.name = f"rule_{ri}"
                r.text = _rule_text(r, spec)
                rule_names.append(r.name)
                cols_np[r.name] = Rmat[:, ri]
        job.update(0.55)

        # response + weights into the GLM frame
        y_np = yv.to_numpy()
        ydf = y_np
        ctypes = {}
        if classification:
            dom = yv.domain
            ydf = np.asarray(
                [dom[int(c)] if c >= 0 else None for c in y_np.astype(np.int64)],
                object,
            )
            ctypes["__y"] = "enum"
        cols_np["__y"] = ydf
        if p.weights_column:
            cols_np["__w"] = train.vec(p.weights_column).to_numpy()
        import pandas as pd

        glm_frame = Frame.from_pandas(pd.DataFrame(cols_np), column_types=ctypes)

        glm_kw = dict(
            family=family,
            alpha=1.0,
            standardize=True,
            weights_column="__w" if p.weights_column else None,
        )
        feat = [c for c in glm_frame.names if c not in ("__y", "__w")]

        if p.lambda_ is not None:
            lam = float(p.lambda_)
        else:
            # pick lambda on an internal 80/20 holdout by deviance
            tr, ho = glm_frame.split_frame([0.8], seed=abs(p.seed) or 99)
            probe = GLM(**glm_kw).train(y="__y", x=feat, training_frame=tr)
            lmax = probe.output["lambda_max"]
            cand = np.geomspace(lmax, lmax * 1e-3, 8)
            best_lam, best_dev = float(cand[-1]), np.inf
            for lam_c in cand:
                m = GLM(lambda_=float(lam_c), **glm_kw).train(
                    y="__y", x=feat, training_frame=tr, validation_frame=ho
                )
                dev = m.validation_metrics.value(
                    "logloss" if classification else "mse"
                )
                if dev < best_dev - 1e-12:
                    best_dev, best_lam = dev, float(lam_c)
            lam = best_lam
            Log.info(f"rulefit: selected lambda={lam:.6g} (holdout)")
        job.update(0.8)

        glm_model = GLM(lambda_=lam, **glm_kw).train(
            y="__y", x=feat, training_frame=glm_frame
        )

        coefs = glm_model.coef
        imp = []
        for r in rules:
            c = coefs.get(r.name, 0.0)
            if abs(c) > 1e-12:
                imp.append(
                    {"variable": r.name, "coefficient": float(c),
                     "support": r.support, "rule": r.text}
                )
        for n in linear_names:
            c = coefs.get(f"linear.{n}", 0.0)
            if abs(c) > 1e-12:
                imp.append(
                    {"variable": f"linear.{n}", "coefficient": float(c),
                     "support": 1.0, "rule": f"linear({n})"}
                )
        imp.sort(key=lambda d: -abs(d["coefficient"]))

        out = {
            "bin_spec": spec,
            "rule_cols": rule_cols,
            "rule_masks": rule_masks,
            "rule_valid": rule_valid,
            "rule_names": rule_names,
            "linear_names": linear_names,
            "glm_model": glm_model,
            "rule_importance": imp,
            "lambda": lam,
            "names": list(self._x),
            "response_domain": tuple(yv.domain) if classification else None,
        }
        model = RuleFitModel(DKV.make_key("rulefit"), p, out)
        model.training_metrics = model._score_metrics(train)
        if valid is not None:
            model.validation_metrics = model._score_metrics(valid)
        return model
