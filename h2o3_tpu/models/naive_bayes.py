"""Naive Bayes — successor of ``hex.naivebayes.NaiveBayes`` [UNVERIFIED
upstream path, SURVEY.md §2.2].

Sufficient statistics (per-class priors, per-class numeric mean/var,
per-class categorical level counts) are one fused device pass: class one-hot
matmuls against the design columns — the NB MRTask recast as MXU work.
Laplace smoothing and min_sdev/eps handling follow the h2o parameters.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.cluster.job import Job
from h2o3_tpu.cluster.registry import DKV
from h2o3_tpu.frame.frame import CAT, Frame
from h2o3_tpu.models.model_base import CommonParams, Model, ModelBuilder

_HI = jax.lax.Precision.HIGHEST


@dataclass
class NaiveBayesParams(CommonParams):
    laplace: float = 0.0
    min_sdev: float = 0.001
    eps_sdev: float = 0.0


class NaiveBayesModel(Model):
    algo = "naivebayes"

    def _predict_raw(self, frame: Frame) -> np.ndarray:
        out = self.output
        n = frame.nrow
        K = self.nclasses
        logp = np.tile(np.log(out["priors"])[None, :], (n, 1))
        for name, stats in out["num_stats"].items():
            x = frame.vec(name).to_numpy().astype(np.float64)
            mu, sd = stats["mean"], stats["sdev"]  # (K,)
            ok = ~np.isnan(x)
            ll = -0.5 * ((x[:, None] - mu[None, :]) / sd[None, :]) ** 2 - np.log(
                sd[None, :] * np.sqrt(2 * np.pi)
            )
            logp += np.where(ok[:, None], ll, 0.0)
        for name, tab in out["cat_stats"].items():
            v = frame.vec(name)
            from h2o3_tpu.models.datainfo import _adapt_codes

            codes = np.asarray(_adapt_codes(v, tab["domain"]))[:n]
            probs = tab["cond"]  # (levels, K)
            ok = codes >= 0
            ll = np.log(np.maximum(probs[np.clip(codes, 0, None)], 1e-30))
            logp += np.where(ok[:, None], ll, 0.0)
        logp -= logp.max(axis=1, keepdims=True)
        P = np.exp(logp)
        return P / P.sum(axis=1, keepdims=True)


class NaiveBayes(ModelBuilder):
    algo = "naivebayes"
    PARAMS_CLS = NaiveBayesParams
    SUPPORTS_WEIGHTS = False  # builder ignores weights_column
    SUPPORTS_REGRESSION = False

    def _build(self, job: Job, train: Frame, valid: Frame | None) -> Model:
        p: NaiveBayesParams = self.params
        yv = train.vec(p.response_column)
        assert yv.is_categorical(), "naivebayes requires an enum response"
        K = yv.cardinality
        npad = train.npad

        y = yv.data
        w = train.row_mask() * (y >= 0)
        Y1h = ((y[:, None] == jnp.arange(K)[None, :]) * w[:, None]).astype(jnp.float32)
        class_w = np.asarray(Y1h.sum(axis=0), np.float64)  # (K,)
        priors = class_w / class_w.sum()

        num_stats, cat_stats = {}, {}
        for name in self._x:
            v = train.vec(name)
            if v.is_categorical():
                L = v.cardinality
                codes = v.data
                oh = ((codes[:, None] == jnp.arange(L)[None, :])).astype(jnp.float32)
                counts = np.asarray(
                    jnp.einsum("nl,nk->lk", oh, Y1h, precision=_HI), np.float64
                )
                cond = (counts + p.laplace) / (
                    class_w[None, :] + p.laplace * L
                )
                cat_stats[name] = {"domain": v.domain, "counts": counts, "cond": cond}
            else:
                x = jnp.nan_to_num(v.data)
                ok = (~jnp.isnan(v.data)).astype(jnp.float32)
                Wk = np.asarray(jnp.einsum("n,nk->k", ok, Y1h, precision=_HI), np.float64)
                Sk = np.asarray(jnp.einsum("n,nk->k", x * ok, Y1h, precision=_HI), np.float64)
                S2k = np.asarray(
                    jnp.einsum("n,nk->k", x * x * ok, Y1h, precision=_HI), np.float64
                )
                mu = Sk / np.maximum(Wk, 1e-30)
                var = S2k / np.maximum(Wk, 1e-30) - mu**2
                sd = np.sqrt(np.maximum(var * Wk / np.maximum(Wk - 1, 1.0), 0.0))
                sd = np.maximum(sd, p.min_sdev) + p.eps_sdev
                num_stats[name] = {"mean": mu, "sdev": sd}
            job.update(0.9 * (len(num_stats) + len(cat_stats)) / len(self._x))

        out = {
            "priors": priors,
            "num_stats": num_stats,
            "cat_stats": cat_stats,
            "names": list(self._x),
            "response_domain": tuple(yv.domain),
        }
        model = NaiveBayesModel(DKV.make_key("naivebayes"), p, out)
        model.training_metrics = model._score_metrics(train)
        if valid is not None:
            model.validation_metrics = model._score_metrics(valid)
        return model
