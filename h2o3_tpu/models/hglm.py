"""HGLM — successor of ``hex.hglm.HGLM`` (hierarchical / mixed-effect GLM)
[UNVERIFIED upstream path, SURVEY.md §2.2]: gaussian response with random
intercepts per level of the ``random_columns`` factors.

Model: y = Xβ + Σ_j Z_j u_j + e,  u_j ~ N(0, σ²_{u_j} I),  e ~ N(0, σ²_e I).

TPU design: the combined design W = [X | onehot(Z_1) | …] lives row-sharded
on device; ONE fused Gram pass (ops/gram.weighted_gram) yields the entire
mixed-model-equation coefficient matrix WᵀW and right-hand side Wᵀy — the
MXU does all O(n) work. The EM-REML loop then iterates host-side in float64
on the (p+q)×(p+q) system (Henderson's MME; Searle/Mrode EM updates):

    σ²_{u_j} ← (û_jᵀû_j + σ²_e·tr(C_jj)) / q_j
    σ²_e     ← (yᵀy − β̂ᵀXᵀy − ûᵀZᵀy) / (n − p)

No per-iteration device work at all — variance-component iteration is free
once the Gram exists.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.cluster.job import Job
from h2o3_tpu.cluster.registry import DKV
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.datainfo import DataInfo
from h2o3_tpu.models.model_base import CommonParams, Model, ModelBuilder
from h2o3_tpu.ops.gram import weighted_gram
from h2o3_tpu.parallel.mesh import row_sharding
from h2o3_tpu.utils.log import Log


@dataclass
class HGLMParams(CommonParams):
    random_columns: list = field(default_factory=list)
    method: str = "EM"
    max_iterations: int = 100
    em_epsilon: float = 1e-6
    standardize: bool = False
    intercept: bool = True


class HGLMModel(Model):
    algo = "hglm"

    def _predict_raw(self, frame: Frame) -> np.ndarray:
        o = self.output
        di: DataInfo = o["datainfo"]
        X, _ = di.transform(frame)
        eta = np.asarray(X, np.float64)[: frame.nrow] @ o["beta"]
        # add BLUPs for known levels (unseen levels get 0 — the prior mean):
        # one vectorized frame-code -> u gather per random column
        for rc, (dom, u) in o["random_effects"].items():
            v = frame.vec(rc)
            lut = {d: i for i, d in enumerate(dom)}
            vdom = list(v.domain or ())
            # frame code -> u value (0.0 for NA / unseen levels), -1 slot last
            code_u = np.zeros(len(vdom) + 1, np.float64)
            for ci, d in enumerate(vdom):
                gi = lut.get(d)
                if gi is not None:
                    code_u[ci] = u[gi]
            codes = v.to_numpy().astype(np.int64)
            codes = np.where((codes < 0) | (codes >= len(vdom)), len(vdom), codes)
            eta += code_u[codes]
        return eta

    @property
    def coef(self) -> dict:
        return dict(zip(self.output["coef_names"], self.output["beta"]))

    def coefs_random(self, column: str) -> dict:
        dom, u = self.output["random_effects"][column]
        return dict(zip(dom, u))

    def _distribution_for_metrics(self) -> str:
        return "gaussian"


class HGLM(ModelBuilder):
    algo = "hglm"
    PARAMS_CLS = HGLMParams
    SUPPORTS_CLASSIFICATION = False

    def _build(self, job: Job, train: Frame, valid: Frame | None) -> Model:
        p: HGLMParams = self.params
        if not p.random_columns:
            raise ValueError("hglm requires random_columns")
        if p.max_iterations < 1:
            raise ValueError("max_iterations must be >= 1")
        yv = train.vec(p.response_column)
        if yv.is_categorical():
            raise ValueError("hglm supports gaussian (numeric) responses")

        fixed = [n for n in self._x if n not in p.random_columns]
        di = DataInfo.fit(
            train, fixed, standardize=p.standardize,
            use_all_factor_levels=False, add_intercept=p.intercept,
        )
        X, valid_mask = di.transform(train)
        P = di.ncols_expanded
        nrow = train.nrow
        npad = train.npad

        # one-hot random-effect blocks appended on device
        blocks: list[tuple[str, list, int]] = []  # (col, domain, q)
        parts = [X]
        for rc in p.random_columns:
            v = train.vec(rc)
            if not v.is_categorical():
                raise ValueError(f"random column {rc!r} must be categorical")
            q = v.cardinality
            codes = v.data  # device int codes, -1 for NA
            oh = (codes[:, None] == jnp.arange(q)[None, :]).astype(jnp.float32)
            parts.append(oh)
            blocks.append((rc, list(v.domain or ()), q))
        W = jax.device_put(jnp.concatenate(parts, axis=1), row_sharding())

        y_np = yv.to_numpy().astype(np.float64)
        w_np = np.asarray(valid_mask)[:npad].astype(np.float64).copy()
        w_np[:nrow] *= ~np.isnan(y_np)
        if p.weights_column:
            w_np[:nrow] *= np.nan_to_num(train.vec(p.weights_column).to_numpy())
        ybuf = np.zeros(npad, np.float32)
        ybuf[:nrow] = np.nan_to_num(y_np, nan=0.0)
        y = jnp.asarray(ybuf)
        w = jnp.asarray(w_np.astype(np.float32))

        G_d, b_d, sw_d = weighted_gram(W, w, y)
        M0 = np.asarray(G_d, np.float64)  # (p+q, p+q) = WᵀWW
        rhs = np.asarray(b_d, np.float64)
        n_eff = float(np.asarray(sw_d))
        yty = float(np.asarray(jnp.sum(w * y * y)))
        job.update(0.3)

        qs = [q for _, _, q in blocks]
        Q = sum(qs)
        sig_e = max(yty / max(n_eff, 1.0), 1e-8)
        sig_u = [sig_e / 2.0] * len(qs)

        beta = np.zeros(P)
        us: list[np.ndarray] = [np.zeros(q) for q in qs]
        ll_prev = np.inf
        for it in range(p.max_iterations):
            M = M0.copy()
            off = P
            for j, q in enumerate(qs):
                k = sig_e / max(sig_u[j], 1e-12)
                M[off : off + q, off : off + q] += k * np.eye(q)
                off += q
            try:
                C = np.linalg.inv(M + 1e-10 * np.eye(len(M)))
            except np.linalg.LinAlgError:
                C = np.linalg.pinv(M)
            sol = C @ rhs
            beta = sol[:P]
            off = P
            new_sig_u = []
            for j, q in enumerate(qs):
                u = sol[off : off + q]
                us[j] = u
                C_jj = C[off : off + q, off : off + q]
                new_sig_u.append(
                    max((u @ u + sig_e * np.trace(C_jj)) / q, 1e-10)
                )
                off += q
            # REML residual update: yᵀy − solᵀ·rhs = eᵀy
            sse = max(yty - sol @ rhs, 1e-12)
            new_sig_e = sse / max(n_eff - P, 1.0)
            delta = abs(new_sig_e - sig_e) / max(sig_e, 1e-12) + sum(
                abs(a - b_) / max(b_, 1e-12) for a, b_ in zip(new_sig_u, sig_u)
            )
            sig_e, sig_u = new_sig_e, new_sig_u
            job.update(0.3 + 0.6 * (it + 1) / p.max_iterations)
            if delta < p.em_epsilon:
                break
        Log.info(
            f"hglm: converged in {it + 1} EM iters; sigma_e^2={sig_e:.5g}, "
            f"sigma_u^2={[round(s, 5) for s in sig_u]}"
        )

        random_effects = {}
        for (rc, dom, q), u in zip(blocks, us):
            random_effects[rc] = (dom, u)

        out = {
            "datainfo": di,
            "beta": beta,
            "coef_names": di.coef_names(),
            "random_effects": random_effects,
            "sigma_e2": float(sig_e),
            "sigma_u2": {rc: float(s) for (rc, _, _), s in zip(blocks, sig_u)},
            "em_iterations": it + 1,
            "names": list(self._x),
            "response_domain": None,
        }
        model = HGLMModel(DKV.make_key("hglm"), p, out)
        model.training_metrics = model._score_metrics(train)
        if valid is not None:
            model.validation_metrics = model._score_metrics(valid)
        return model
