"""Isolation Forest — successor of ``hex.tree.isofor.IsolationForest``
[UNVERIFIED upstream path, SURVEY.md §2.2].

Trees are grown on tiny row subsamples (default 256) with uniform-random
(feature, threshold) splits — that construction is inherently host-scale, so
it runs in numpy; SCORING the full frame (the actual data-scale work: path
lengths of every row through every tree) is a vectorized device walk over
stacked per-level split arrays, the BigScore analog.

Score = 2^(−E[h(x)]/c(n)) with the standard c(n) normalizer; output matches
h2o's (predict=anomaly score, mean_length).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.cluster.job import Job
from h2o3_tpu.cluster.registry import DKV
from h2o3_tpu.frame.frame import Frame, Vec
from h2o3_tpu.models.metrics import ModelMetrics
from h2o3_tpu.models.model_base import CommonParams, Model, ModelBuilder


@dataclass
class IsolationForestParams(CommonParams):
    ntrees: int = 50
    sample_size: int = 256
    max_depth: int = 8
    mtries: int = -1


def _c(n: float) -> float:
    if n <= 1:
        return 0.0
    return 2.0 * (np.log(n - 1) + 0.5772156649) - 2.0 * (n - 1) / n


@partial(jax.jit, static_argnames=("n_levels",))
def _path_lengths(X, feat, thr, leaf_len, n_levels: int):
    """Walk all rows through one tree's stacked level arrays.

    feat/thr: (n_levels, max_nodes); leaf nodes have feat = -1 and
    leaf_len the partial path length at that node.
    """
    n = X.shape[0]
    nid = jnp.zeros(n, jnp.int32)
    done = jnp.zeros(n, bool)
    length = jnp.zeros(n, jnp.float32)

    def body(d, carry):
        nid, done, length = carry
        f = feat[d][nid]
        t = thr[d][nid]
        ll = leaf_len[d][nid]
        is_leaf = f < 0
        newly = is_leaf & ~done
        length = jnp.where(newly, ll, length)
        done = done | is_leaf
        x = jnp.take_along_axis(X, jnp.maximum(f, 0)[:, None], axis=1).squeeze(1)
        go_left = jnp.where(jnp.isnan(x), True, x < t)
        nid = jnp.where(done, nid, 2 * nid + jnp.where(go_left, 0, 1))
        return nid, done, length

    nid, done, length = jax.lax.fori_loop(0, n_levels, body, (nid, done, length))
    return length


class IsolationForestModel(Model):
    algo = "isolationforest"

    def _predict_raw(self, frame: Frame) -> np.ndarray:
        X = _feature_matrix(
            frame, self.output["names"],
            domains=self.output.get("feature_domains"))
        total = jnp.zeros(X.shape[0], jnp.float32)
        for feat, thr, ll in self.output["trees"]:
            total = total + _path_lengths(
                X, jnp.asarray(feat), jnp.asarray(thr), jnp.asarray(ll), feat.shape[0]
            )
        mean_len = np.asarray(total)[: frame.nrow] / len(self.output["trees"])
        cn = _c(self.params.sample_size)
        score = np.power(2.0, -mean_len / max(cn, 1e-9))
        return np.stack([score, mean_len], axis=1)

    def predict(self, frame: Frame) -> Frame:
        s = self._predict_raw(frame)
        return Frame(
            [Vec.from_numpy(s[:, 0], "real"), Vec.from_numpy(s[:, 1], "real")],
            ["predict", "mean_length"],
        )


def _feature_matrix(frame: Frame, names, domains=None) -> "jnp.ndarray":
    """Feature columns as f32: numerics as-is, categoricals as their codes.

    ``domains`` (the trained model's ``feature_domains`` output, ISSUE 14)
    remaps a scoring frame's frame-local codes into TRAINING-domain codes
    (unseen levels → -1, the NA code) so predictions do not depend on the
    scoring frame's own interning order — and so the serving tier's
    compiled iforest lane, which encodes row payloads straight into
    training codes (scorer._coerce_cat), is byte-equal to this path. The
    training frame itself remaps identically (its domains ARE the training
    domains), keeping pre-existing behavior bit-for-bit there; models
    saved before feature_domains existed pass None and keep raw codes."""
    cols = []
    for ci, n in enumerate(names):
        v = frame.vec(n)
        if not v.is_categorical():
            cols.append(v.data)
            continue
        dom = domains[ci] if domains is not None else None
        vdom = tuple(v.domain or ())
        if dom is None or tuple(dom) == vdom:
            cols.append(v.data.astype(jnp.float32))
            continue
        lut = {lv: i for i, lv in enumerate(dom)}
        remap = jnp.asarray(
            np.array([lut.get(lv, -1) for lv in vdom] or [-1], np.int32))
        codes = v.data.astype(jnp.int32)
        mapped = jnp.where(
            codes < 0, -1, remap[jnp.clip(codes, 0, len(vdom) - 1)])
        cols.append(mapped.astype(jnp.float32))
    return jnp.stack(cols, axis=1)


class IsolationForest(ModelBuilder):
    algo = "isolationforest"
    PARAMS_CLS = IsolationForestParams
    SUPPORTS_CLASSIFICATION = False

    def train(self, x=None, training_frame=None, **kw):
        return super().train(x=x, y=None, training_frame=training_frame, **kw)

    def _validate(self, train, valid):
        pass

    def _build(self, job: Job, train: Frame, valid: Frame | None) -> Model:
        p: IsolationForestParams = self.params
        rng = np.random.default_rng(abs(p.seed) if p.seed and p.seed > 0 else 1)
        names = self._x
        Xn = np.asarray(_feature_matrix(train, names))[: train.nrow]
        n, C = Xn.shape
        sample = min(p.sample_size, n)
        depth = min(p.max_depth, max(1, int(np.ceil(np.log2(max(sample, 2))))))
        mtries = C if p.mtries in (-1, 0) else min(p.mtries, C)

        trees = []
        for m in range(p.ntrees):
            idx = rng.choice(n, sample, replace=False)
            trees.append(self._grow(Xn[idx], depth, rng, mtries))
            job.update(0.9 * (m + 1) / p.ntrees)

        out = {
            "trees": trees, "names": list(names), "response_domain": None,
            "feature_kinds": [
                "cat" if train.vec(n).is_categorical() else "num"
                for n in names
            ],
            # training-domain codes (ISSUE 14): categorical features carry
            # the TRAINING frame's level domains, so scoring frames remap
            # into them (_feature_matrix) and the serving tier's compiled
            # walk lane can encode row payloads byte-identically
            # (scorer._coerce_cat against these domains) — categorical
            # forests no longer fall back to the generic lane
            "feature_domains": [
                tuple(train.vec(n).domain or ())
                if train.vec(n).is_categorical() else None
                for n in names
            ],
        }
        model = IsolationForestModel(DKV.make_key("isofor"), p, out)
        raw = model._predict_raw(train)
        model.training_metrics = ModelMetrics(
            "anomaly",
            {
                "mean_score": float(raw[:, 0].mean()),
                "mean_length": float(raw[:, 1].mean()),
            },
        )
        return model

    def _grow(self, S: np.ndarray, depth: int, rng, mtries: int):
        """Grow one random tree on sample S; emit stacked level arrays in
        full binary indexing (small: 2^depth ≤ 256 nodes)."""
        n_levels = depth + 1
        max_nodes = 1 << depth
        feat = np.full((n_levels, max_nodes), -1, np.int32)
        thr = np.zeros((n_levels, max_nodes), np.float32)
        leaf_len = np.zeros((n_levels, max_nodes), np.float32)
        C = S.shape[1]

        node_rows: dict[tuple[int, int], np.ndarray] = {(0, 0): np.arange(len(S))}
        for d in range(n_levels):
            next_rows = {}
            for (dd, i), rows in list(node_rows.items()):
                if dd != d:
                    continue
                sub = S[rows]
                uniq_ok = False
                if d < depth and len(rows) > 1:
                    cand = rng.choice(C, size=min(mtries, C), replace=False)
                    for f in cand:
                        col = sub[:, f]
                        col = col[~np.isnan(col)]
                        if len(col) and col.min() < col.max():
                            t = rng.uniform(col.min(), col.max())
                            feat[d, i] = f
                            thr[d, i] = t
                            go = np.where(np.isnan(sub[:, f]), True, sub[:, f] < t)
                            next_rows[(d + 1, 2 * i)] = rows[go]
                            next_rows[(d + 1, 2 * i + 1)] = rows[~go]
                            uniq_ok = True
                            break
                if not uniq_ok:
                    feat[d, i] = -1
                    leaf_len[d, i] = d + _c(float(len(rows)))
            node_rows.update(next_rows)
        return feat, thr, leaf_len
