"""AdaBoost — successor of ``hex.adaboost.AdaBoost`` [UNVERIFIED upstream
path, SURVEY.md §2.2].

Discrete AdaBoost (SAMME, binary) with shallow histogram trees as the weak
learners. Each iteration fits a weighted regression tree on the ±1 response
(leaf = weighted mean), takes sign(leaf) as the weak hypothesis, computes
alpha from the weighted error, and reweights. The recorded leaf values are
REWRITTEN to alpha·sign(leaf) at build time, so the final strong score
F(x) = Σ alpha_m h_m(x) replays through the standard batched tree walk in
one dispatch — no per-tree scoring pass.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.cluster.job import Job
from h2o3_tpu.cluster.registry import DKV
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.model_base import ModelBuilder
from h2o3_tpu.models.tree.binning import bin_frame, fit_bins, fit_bins_for
from h2o3_tpu.models.tree.gbm import SharedTreeModel, SharedTreeParams
from h2o3_tpu.models.tree.shared_tree import build_tree


@dataclass
class AdaBoostParams(SharedTreeParams):
    nlearners: int = 50
    weak_learner: str = "DT"  # upstream offers DRF/GBM/GLM weak learners too
    learn_rate: float = 0.5  # shrinkage on alpha (h2o's learn_rate)
    max_depth: int = 1  # stumps by default
    min_rows: float = 10.0


class AdaBoostModel(SharedTreeModel):
    algo = "adaboost"

    def _predict_raw_dev(self, frame: Frame):
        F = self._replay_all_dev(frame)[: frame.nrow]  # Σ alpha·h
        p1 = 1.0 / (1.0 + jnp.exp(-2.0 * F))  # logistic link on the margin
        return jnp.stack([1 - p1, p1], axis=1)

    def _predict_raw(self, frame: Frame) -> np.ndarray:
        return np.asarray(self._predict_raw_dev(frame))


class AdaBoost(ModelBuilder):
    algo = "adaboost"
    PARAMS_CLS = AdaBoostParams
    SUPPORTS_REGRESSION = False

    def _build(self, job: Job, train: Frame, valid: Frame | None):
        p: AdaBoostParams = self.params
        yv = train.vec(p.response_column)
        if not yv.is_categorical() or yv.cardinality != 2:
            raise ValueError("AdaBoost is a binary classifier")

        spec = fit_bins_for(p, train, self._x)
        bins = bin_frame(spec, train)
        npad = train.npad

        y_np = yv.to_numpy().astype(np.int64)
        valid_row = np.zeros(npad, np.float32)
        valid_row[: train.nrow] = (y_np >= 0).astype(np.float32)
        ypm_np = np.zeros(npad, np.float32)
        ypm_np[: train.nrow] = np.where(y_np == 1, 1.0, -1.0)
        ypm = jnp.asarray(ypm_np)
        base_w = valid_row.copy()
        if p.weights_column:
            base_w[: train.nrow] *= np.nan_to_num(
                train.vec(p.weights_column).to_numpy()
            ).astype(np.float32)
        w = jnp.asarray(base_w)
        # normalize to MEAN 1 (sum = n): split finding compares weighted node
        # counts against min_rows, so weights must stay O(1) per row
        n_eff = jnp.maximum((w > 0).sum().astype(jnp.float32), 1.0)
        w = w * n_eff / jnp.maximum(w.sum(), 1e-30)

        key = jax.random.PRNGKey(abs(p.seed) if p.seed and p.seed > 0 else 31)
        trees = []
        alphas = []
        varimp = jnp.zeros(len(self._x), jnp.float32)
        eps = 1e-10

        for m in range(p.nlearners):
            if job.stop_requested:
                break
            tree, fk, varimp = build_tree(
                bins, w, ypm, w,  # leaf = weighted mean of ±1 in [-1, 1]
                n_bins=spec.max_bins,
                is_cat_cols=spec.is_cat,
                max_depth=p.max_depth,
                min_rows=p.min_rows,
                min_split_improvement=p.min_split_improvement,
                learn_rate=1.0,
                preds=jnp.zeros(npad, jnp.float32),
                key=jax.random.fold_in(key, m),
                varimp=varimp,
            )
            h = jnp.sign(fk)  # weak hypothesis in {-1, 0, +1}
            err = float(jnp.sum(w * (h != ypm)) / jnp.maximum(jnp.sum(w), 1e-30))
            err = min(max(err, eps), 1 - eps)
            alpha = p.learn_rate * 0.5 * np.log((1 - err) / err)
            if err >= 0.5:  # no better than chance: stop (standard AdaBoost)
                break
            # reweight and renormalize (to sum = n, keeping weights O(1))
            w = w * jnp.exp(-alpha * ypm * h)
            w = w * n_eff / jnp.maximum(w.sum(), 1e-30)
            # bake alpha·sign into the recorded leaves → standard replay
            host = tree.to_host()
            for lv in host.levels:
                lv.leaf_val = (alpha * np.sign(lv.leaf_val)).astype(np.float32)
            trees.append([host])
            alphas.append(float(alpha))
            job.update(0.05 + 0.9 * (m + 1) / p.nlearners)

        out = {
            "bin_spec": spec,
            "trees": trees,
            "n_tree_classes": 1,
            "alphas": alphas,
            "names": list(self._x),
            "varimp": np.asarray(varimp).astype(np.float64),
            "response_domain": tuple(yv.domain),
            "ntrees_actual": len(trees),
        }
        model = AdaBoostModel(DKV.make_key("adaboost"), p, out)
        model.training_metrics = model._score_metrics(train)
        if valid is not None:
            model.validation_metrics = model._score_metrics(valid)
        return model
