"""Portable model export — successor of the MOJO writer side
(``hex.genmodel`` producers, ``/3/Models/{id}/mojo``) [UNVERIFIED upstream
paths, SURVEY.md §2.3 §5.4].

Format ("tmojo", .zip):
- ``model.json`` — algo, version, scoring metadata (domains, links,
  distributions, DataInfo standardization spec) — everything small.
- ``arrays.npz`` — the numeric payload (tree level arrays, GLM betas, DL
  weight matrices, KMeans centers, bin edges).

The artifact is scored WITHOUT a cluster and WITHOUT jax by
:mod:`h2o3_tpu.genmodel` (pure numpy) — the EasyPredictModelWrapper
successor — and parity with in-cluster ``model.predict`` is the numerical
regression net, exactly H2O's MOJO-parity test strategy (SURVEY.md §4).
"""

from __future__ import annotations

import io
import json
import zipfile

import numpy as np

from h2o3_tpu.models.model_base import Model

FORMAT_VERSION = "1.0"


def _datainfo_meta(di) -> dict:
    return {
        "standardize": di.standardize,
        "use_all_factor_levels": di.use_all_factor_levels,
        "missing_handling": di.missing_handling,
        "add_intercept": di.add_intercept,
        "ncols_expanded": di.ncols_expanded,
        # feature hashing: the offline scorer re-derives each "hash"
        # column's bucket from the raw level string, so the bucket count is
        # part of the scoring spec (None = no hashing anywhere)
        "hash_buckets": di.hash_buckets,
        "columns": [
            {"name": c.name, "kind": c.kind, "mean": float(c.mean),
             "sigma": float(c.sigma), "domain": list(c.domain),
             "offset": c.offset, "width": c.width,
             "pair": list(c.pair) if c.pair else None,
             "pair_means": list(c.pair_means) if c.pair_means else None,
             "pair_domains": [list(d) for d in c.pair_domains]
             if c.pair_domains else None}
            for c in di.columns
        ],
    }


def _export_trees(model, meta, arrays) -> None:
    out = model.output
    spec = out["bin_spec"]
    meta["distribution"] = out.get("distribution")
    meta["init_f"] = np.asarray(out["init_f"]).tolist() if "init_f" in out else None
    meta["n_tree_classes"] = out.get("n_tree_classes", 1)
    meta["ntrees_actual"] = out["ntrees_actual"]
    meta["names"] = out["names"]
    meta["bin_domains"] = [list(d) if d else None for d in (spec.domains or [])]
    meta["offset_column"] = getattr(model.params, "offset_column", None)
    arrays["bin_is_cat"] = np.asarray(spec.is_cat)
    arrays["bin_nbins"] = np.asarray(spec.nbins)
    arrays["bin_edges"] = np.asarray(spec.edges)
    cal = out.get("calibration")
    if cal is not None:
        meta["calibration_method"] = cal["method"]
        if cal["method"] == "PlattScaling":
            meta["calibration_platt"] = [cal["a"], cal["b"]]
        else:
            arrays["cal_thresholds_x"] = np.asarray(cal["thresholds_x"])
            arrays["cal_thresholds_y"] = np.asarray(cal["thresholds_y"])
    tree_shapes = []
    for ti, group in enumerate(out["trees"]):
        class_levels = []
        for ki, tree in enumerate(group):
            host = tree.to_host()
            class_levels.append(len(host.levels))
            for li, lv in enumerate(host.levels):
                pre = f"t{ti}_k{ki}_l{li}_"
                arrays[pre + "split_col"] = lv.split_col
                arrays[pre + "split_bin"] = lv.split_bin
                arrays[pre + "is_cat"] = lv.is_cat
                # bin-adaptive levels record a narrower cat_mask (unused for
                # numeric-only adaptivity); pad to the model's bin width so
                # every offline scorer sees one uniform B
                cm = np.asarray(lv.cat_mask)
                full_b = int(spec.max_bins)
                if cm.shape[1] < full_b:
                    cm = np.pad(cm, ((0, 0), (0, full_b - cm.shape[1])))
                arrays[pre + "cat_mask"] = cm
                arrays[pre + "na_left"] = lv.na_left
                arrays[pre + "leaf_now"] = lv.leaf_now
                arrays[pre + "leaf_val"] = lv.leaf_val
                arrays[pre + "child_base"] = lv.child_base
        tree_shapes.append(class_levels)
    meta["tree_levels"] = tree_shapes


def _export_glm(model, meta, arrays) -> None:
    out = model.output
    meta["family"] = out["family"]
    meta["link"] = out.get("link", "family_default")
    meta["datainfo"] = _datainfo_meta(out["datainfo"])
    meta["coef_names"] = out["coef_names"]
    if out.get("multinomial"):
        arrays["beta_multinomial_std"] = np.asarray(out["beta_multinomial_std"])
    elif out.get("ordinal"):
        arrays["beta_std"] = np.asarray(out["beta_std"])
        arrays["theta"] = np.asarray(out["theta"])  # ordered cuts (std scale)
    else:
        arrays["beta_std"] = np.asarray(out["beta_std"])
    meta["tweedie_link_power"] = getattr(model.params, "tweedie_link_power", 1.0)


def _export_deeplearning(model, meta, arrays) -> None:
    out = model.output
    meta["datainfo"] = _datainfo_meta(out["datainfo"])
    meta["activation"] = model.params.activation
    params = out["params"]["params"] if "params" in out["params"] else out["params"]
    layers = sorted(params.keys(), key=lambda k: int(k.split("_")[-1]))
    meta["n_layers"] = len(layers)
    for i, name in enumerate(layers):
        arrays[f"W{i}"] = np.asarray(params[name]["kernel"])
        arrays[f"b{i}"] = np.asarray(params[name]["bias"])
    pad = int(out.get("input_pad") or 0)
    if pad:  # MOJO scores the REAL design width; bucket pad rows are zero
        arrays["W0"] = arrays["W0"][:-pad]


def _export_kmeans(model, meta, arrays) -> None:
    out = model.output
    meta["datainfo"] = _datainfo_meta(out["datainfo"])
    arrays["centers_std"] = np.asarray(out["centers_std"])


_EXPORTERS = {
    "gbm": _export_trees,
    "xgboost": _export_trees,
    "drf": _export_trees,
    "xrt": _export_trees,
    "glm": _export_glm,
    "deeplearning": _export_deeplearning,
    "kmeans": _export_kmeans,
}


def _write_mojo(model: Model, dest) -> None:
    """Write the artifact to a path or file-like object."""
    if model.algo not in _EXPORTERS:
        raise ValueError(f"mojo export not supported for {model.algo!r}")
    thr = None
    if model.training_metrics is not None:
        thr = model.training_metrics._v.get("default_threshold")
    meta = {
        "format_version": FORMAT_VERSION,
        "algo": model.algo,
        "model_key": model.key,
        "default_threshold": thr,
        "response_column": model.params.response_column,
        "response_domain": list(model.output["response_domain"])
        if model.output.get("response_domain") else None,
    }
    arrays: dict[str, np.ndarray] = {}
    _EXPORTERS[model.algo](model, meta, arrays)

    buf = io.BytesIO()
    np.savez_compressed(buf, **arrays)
    with zipfile.ZipFile(dest, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr("model.json", json.dumps(meta))
        z.writestr("arrays.npz", buf.getvalue())


def export_mojo(model: Model, path: str) -> str:
    """Write the portable artifact; returns the path."""
    _write_mojo(model, path)
    return path


# attach to Model (h2o's model.download_mojo surface)
def export_pojo(model: Model, path: str) -> str:
    """POJO successor: ONE self-contained .py scoring file, no h2o3_tpu, no
    jax — just numpy (upstream compiles the model into one standalone Java
    class; the Python-native image of that is a single script embedding the
    scorer source + the model payload).

    Usage of the artifact:  ``python model.py data.csv > preds.csv``  or
    ``import model; model.MODEL.predict({...})``.
    """
    import base64
    import inspect

    from h2o3_tpu import genmodel as _gm

    buf = io.BytesIO()
    _write_mojo(model, buf)
    payload_b64 = base64.b64encode(buf.getvalue()).decode()
    src = inspect.getsource(_gm)
    chunks = [payload_b64[i : i + 100] for i in range(0, len(payload_b64), 100)]
    blob_lines = "\n".join(f'    "{c}"' for c in chunks)
    out = (
        # comments (not a docstring) so the embedded source's own
        # `from __future__` import stays legally placed
        f"# Standalone scorer for model {model.key} (algo={model.algo})\n"
        "# generated by h2o3_tpu.models.export.export_pojo — numpy only.\n"
        + src
        + "\n\n# --- embedded model payload "
        + "-" * 40 + "\n"
        + "_PAYLOAD_B64 = (\n" + blob_lines + "\n)\n"
        + '''

def _load_embedded() -> "MojoModel":
    import base64 as _b64
    import io as _io

    return MojoModel.load(_io.BytesIO(_b64.b64decode(_PAYLOAD_B64)))


MODEL = _load_embedded()


if __name__ == "__main__":
    import sys as _sys

    if len(_sys.argv) != 2:
        print("usage: python model.py data.csv", file=_sys.stderr)
        raise SystemExit(2)
    import csv as _csv

    with open(_sys.argv[1]) as _f:
        rows = list(_csv.DictReader(_f))
    table = {k: [r[k] for r in rows] for k in rows[0]}
    out = MODEL.predict(table)
    keys = list(out)
    w = _csv.writer(_sys.stdout)
    w.writerow(keys)
    for i in range(len(out[keys[0]])):
        w.writerow([out[k][i] for k in keys])
'''
    )
    with open(path, "w") as f:
        f.write(out)
    return path


def _download_mojo(self: Model, path: str) -> str:
    return export_mojo(self, path)


Model.download_mojo = _download_mojo
Model.save_mojo = _download_mojo
