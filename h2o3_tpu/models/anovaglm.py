"""ANOVA GLM — successor of ``hex.anovaglm.ANOVAGLM`` [UNVERIFIED upstream
path, SURVEY.md §2.2]: type-III ANOVA decomposition of a GLM.

For predictors {A, B, ...} the builder forms main-effect and interaction
terms up to ``highest_interaction_term`` (effect/sum-to-zero coding for
categoricals, standardized numerics — the coding that makes type-III SS
well-defined), fits the full GLM, then refits with each term deleted.

TPU design (gaussian): ONE device pass accumulates the full weighted Gram
over the expanded design; the full and every term-deleted model are then
sub-Gram Cholesky solves host-side in float64 — no per-term device work
(same sweep-operator economics as models/model_selection.py). Binomial
refits per term via IRLS on the shared Gram pass.

Reported per term: df, SS (or deviance delta), MS, F (or chi2), p-value.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np

from h2o3_tpu.cluster.job import Job
from h2o3_tpu.cluster.registry import DKV
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.model_base import CommonParams, Model, ModelBuilder
from h2o3_tpu.ops.gram import solve_cholesky, weighted_gram
from h2o3_tpu.parallel.mesh import row_sharding


@dataclass
class ANOVAGLMParams(CommonParams):
    family: str = "AUTO"
    highest_interaction_term: int = 0  # 0 -> number of predictors
    lambda_: float = 0.0
    standardize: bool = True


def _effect_code(codes: np.ndarray, k: int) -> np.ndarray:
    """Sum-to-zero coding: k levels -> k-1 columns; last level = -1 row."""
    n = len(codes)
    out = np.zeros((n, max(k - 1, 1)), np.float32)
    if k <= 1:
        return out
    for j in range(k - 1):
        out[:, j] = (codes == j).astype(np.float32)
    out[codes == k - 1, :] = -1.0
    out[codes < 0, :] = 0.0  # NA rows contribute nothing
    return out


class ANOVAGLMModel(Model):
    algo = "anovaglm"

    def _predict_raw(self, frame: Frame) -> np.ndarray:
        X = _design(frame, self.output["term_plan"])[: frame.nrow]
        eta = X @ self.output["beta_full"]
        if self.output["family"] == "binomial":
            mu = 1.0 / (1.0 + np.exp(-eta))
            return np.stack([1 - mu, mu], axis=1)
        return eta

    def anova_table(self) -> list[dict]:
        return self.output["anova_table"]

    def _distribution_for_metrics(self) -> str:
        return "gaussian"


def _design(frame: Frame, plan: dict) -> np.ndarray:
    """Build the effect-coded design matrix (host f64) from a fitted plan."""
    base: dict[str, np.ndarray] = {}
    for name, info in plan["bases"].items():
        v = frame.vec(name)
        if info["kind"] == "cat":
            codes = np.full(frame.nrow, -1, np.int64)
            raw = v.to_numpy()
            dom_map = {d: i for i, d in enumerate(info["domain"])}
            vdom = v.domain or ()
            for i, c in enumerate(raw.astype(np.int64)):
                if 0 <= c < len(vdom):
                    codes[i] = dom_map.get(vdom[c], -1)
            base[name] = _effect_code(codes, len(info["domain"]))
        else:
            x = v.to_numpy().astype(np.float64)
            x = np.where(np.isnan(x), info["mean"], x)
            base[name] = ((x - info["mean"]) / info["sigma"])[:, None]
    cols = []
    for term in plan["terms"]:
        mats = [base[n] for n in term]
        M = mats[0]
        for m2 in mats[1:]:
            M = (M[:, :, None] * m2[:, None, :]).reshape(len(M), -1)
        cols.append(M)
    cols.append(np.ones((frame.nrow, 1)))  # intercept last
    return np.concatenate(cols, axis=1)


class ANOVAGLM(ModelBuilder):
    algo = "anovaglm"
    PARAMS_CLS = ANOVAGLMParams

    def _build(self, job: Job, train: Frame, valid: Frame | None) -> Model:
        from scipy import stats as sps

        p: ANOVAGLMParams = self.params
        yv = train.vec(p.response_column)
        family = p.family.lower()
        if family == "auto":
            family = "binomial" if yv.is_categorical() else "gaussian"
        if family not in ("gaussian", "binomial"):
            raise ValueError("anovaglm supports gaussian and binomial")

        preds = list(self._x)
        order = p.highest_interaction_term or len(preds)
        order = min(order, len(preds))
        terms: list[tuple[str, ...]] = []
        for r in range(1, order + 1):
            terms.extend(itertools.combinations(preds, r))

        bases: dict[str, dict] = {}
        for n in preds:
            v = train.vec(n)
            if v.is_categorical():
                bases[n] = {"kind": "cat", "domain": list(v.domain or ())}
            else:
                x = v.to_numpy().astype(np.float64)
                mean = float(np.nanmean(x))
                sigma = float(np.nanstd(x)) or 1.0
                if not p.standardize:
                    mean, sigma = 0.0, 1.0
                bases[n] = {"kind": "num", "mean": mean, "sigma": sigma}
        plan = {"bases": bases, "terms": terms}

        Xh = _design(train, plan)  # (n, P) host f64
        nrow, P = Xh.shape
        # term -> column block
        blocks: list[tuple[tuple[str, ...], list[int]]] = []
        off = 0
        for term in terms:
            w_ = 1
            for n in term:
                info = bases[n]
                w_ *= (len(info["domain"]) - 1) if info["kind"] == "cat" else 1
                w_ = max(w_, 1)
            blocks.append((term, list(range(off, off + w_))))
            off += w_
        icpt = P - 1

        y_np = yv.to_numpy().astype(np.float64)
        if yv.is_categorical():
            y_np[y_np < 0] = np.nan
        w_np = np.ones(nrow, np.float64)
        if p.weights_column:
            w_np *= np.nan_to_num(train.vec(p.weights_column).to_numpy())
        w_np *= ~np.isnan(y_np)
        y_clean = np.nan_to_num(y_np, nan=0.0)

        # pad + ship to device once; the Gram is the only heavy compute
        npad = train.npad
        Xp = np.zeros((npad, P), np.float32)
        Xp[:nrow] = Xh
        wp = np.zeros(npad, np.float32)
        wp[:nrow] = w_np
        yp = np.zeros(npad, np.float32)
        yp[:nrow] = y_clean
        import jax

        Xd = jax.device_put(jnp.asarray(Xp), row_sharding())

        if family == "gaussian":
            G_d, b_d, sw_d = weighted_gram(Xd, jnp.asarray(wp), jnp.asarray(yp))
            G = np.asarray(G_d, np.float64)
            b = np.asarray(b_d, np.float64)
            sw = float(np.asarray(sw_d))
            yty = float(np.sum(w_np * y_clean * y_clean))

            def rss_of(cols: list[int]) -> tuple[float, np.ndarray]:
                Gs = G[np.ix_(cols, cols)]
                bs = b[cols]
                beta = solve_cholesky(Gs, bs, ridge=p.lambda_)
                return max(yty - beta @ bs, 0.0), beta

            full_cols = list(range(P))
            rss_full, beta_f = rss_of(full_cols)
            df_resid = max(sw - P, 1.0)
            mse = rss_full / df_resid
            table = []
            for term, cols in blocks:
                keep = [c for c in full_cols if c not in cols]
                rss_red, _ = rss_of(keep)
                ss = max(rss_red - rss_full, 0.0)
                df = len(cols)
                F = (ss / df) / max(mse, 1e-300)
                pv = float(sps.f.sf(F, df, df_resid))
                table.append(
                    {"term": ":".join(term), "df": df, "ss": ss,
                     "ms": ss / df, "f": F, "p_value": pv}
                )
            table.append(
                {"term": "Residual", "df": int(df_resid), "ss": rss_full,
                 "ms": mse, "f": float("nan"), "p_value": float("nan")}
            )
            beta_full = beta_f
        else:
            # binomial: IRLS on the shipped design; deviance tests per term
            def fit_cols(cols: list[int]):
                beta = np.zeros(len(cols), np.float64)
                Xc = Xh[:, cols]
                for _ in range(25):
                    eta = Xc @ beta
                    mu = 1.0 / (1.0 + np.exp(-eta))
                    mu = np.clip(mu, 1e-10, 1 - 1e-10)
                    W = w_np * mu * (1 - mu)
                    z = eta + (y_clean - mu) / (mu * (1 - mu))
                    G = (Xc * W[:, None]).T @ Xc
                    bb = (Xc * W[:, None]).T @ z
                    new = solve_cholesky(G, bb, ridge=p.lambda_ + 1e-10)
                    if np.max(np.abs(new - beta)) < 1e-8:
                        beta = new
                        break
                    beta = new
                eta = Xc @ beta
                mu = np.clip(1.0 / (1.0 + np.exp(-eta)), 1e-12, 1 - 1e-12)
                dev = -2.0 * float(
                    np.sum(w_np * (y_clean * np.log(mu) + (1 - y_clean) * np.log(1 - mu)))
                )
                return dev, beta

            full_cols = list(range(P))
            dev_full, beta_f = fit_cols(full_cols)
            table = []
            for term, cols in blocks:
                keep = [c for c in full_cols if c not in cols]
                dev_red, _ = fit_cols(keep)
                delta = max(dev_red - dev_full, 0.0)
                df = len(cols)
                pv = float(sps.chi2.sf(delta, df))
                table.append(
                    {"term": ":".join(term), "df": df, "ss": delta,
                     "ms": delta / df, "f": delta, "p_value": pv}
                )
            beta_full = np.zeros(P, np.float64)
            beta_full[full_cols] = beta_f

        job.update(0.95)
        out = {
            "term_plan": plan,
            "anova_table": table,
            "beta_full": beta_full,
            "family": family,
            "names": preds,
            "response_domain": tuple(yv.domain) if yv.is_categorical() else None,
        }
        model = ANOVAGLMModel(DKV.make_key("anovaglm"), p, out)
        model.training_metrics = model._score_metrics(train)
        if valid is not None:
            model.validation_metrics = model._score_metrics(valid)
        return model
