"""Word2Vec — successor of ``hex.word2vec.Word2Vec`` [UNVERIFIED upstream
path, SURVEY.md §2.2].

Skip-gram with negative sampling. Pair generation (vocab build, windowing,
unigram^0.75 negative table) is a host pass over the string column — string
data never lives on device by design — while training runs as jitted
minibatch SGD over embedding gathers: the (B, dim)·(B, dim) positive and
(B, neg, dim) negative dots are exactly the dense row-gather + matmul shape
the MXU wants. h2o surface parity: ``find_synonyms`` and ``transform``
(word → vector, sentence → average).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.cluster.job import Job
from h2o3_tpu.cluster.registry import DKV
from h2o3_tpu.frame.frame import Frame, Vec
from h2o3_tpu.models.metrics import ModelMetrics
from h2o3_tpu.models.model_base import CommonParams, Model, ModelBuilder


@dataclass
class Word2VecParams(CommonParams):
    vec_size: int = 100
    window_size: int = 5
    min_word_freq: int = 5
    epochs: int = 5
    learning_rate: float = 0.025
    negative_samples: int = 5
    sent_sample_rate: float = 1e-3  # frequent-word subsampling (h2o default)


class Word2VecModel(Model):
    algo = "word2vec"

    def find_synonyms(self, word: str, count: int = 10) -> dict[str, float]:
        vocab = self.output["vocab"]
        if word not in vocab:
            return {}
        E = self.output["embeddings"]
        v = E[vocab[word]]
        sims = E @ v / (np.linalg.norm(E, axis=1) * np.linalg.norm(v) + 1e-12)
        order = np.argsort(-sims)
        words = self.output["words"]
        out = {}
        for i in order:
            if words[i] == word:
                continue
            out[words[i]] = float(sims[i])
            if len(out) >= count:
                break
        return out

    def transform(self, frame: Frame, aggregate_method: str = "NONE") -> Frame:
        """words → vectors; AVERAGE aggregates consecutive rows per sentence
        (h2o treats NA rows as sentence separators)."""
        vocab = self.output["vocab"]
        E = self.output["embeddings"]
        words = frame.vec(0).to_numpy()
        dim = E.shape[1]
        rows = np.full((len(words), dim), np.nan)
        for i, w in enumerate(words):
            if w is not None and w in vocab:
                rows[i] = E[vocab[w]]
        if aggregate_method.upper() == "AVERAGE":
            sents, cur = [], []
            for i, w in enumerate(words):
                if w is None:
                    sents.append(np.nanmean(rows[cur], axis=0) if cur else np.full(dim, np.nan))
                    cur = []
                else:
                    cur.append(i)
            if cur:
                sents.append(np.nanmean(rows[cur], axis=0))
            rows = np.stack(sents) if sents else rows[:0]
        return Frame(
            [Vec.from_numpy(rows[:, j], "real") for j in range(dim)],
            [f"C{j + 1}" for j in range(dim)],
        )


class Word2Vec(ModelBuilder):
    algo = "word2vec"
    PARAMS_CLS = Word2VecParams
    SUPPORTS_CLASSIFICATION = False
    SUPPORTS_REGRESSION = False

    def train(self, x=None, training_frame=None, **kw):
        return super().train(x=x, y=None, training_frame=training_frame, **kw)

    def _validate(self, train, valid):
        pass

    def _features(self, train: Frame, response):
        return [train.names[0]]

    def _build(self, job: Job, train: Frame, valid: Frame | None):
        p: Word2VecParams = self.params
        words_raw = train.vec(0).to_numpy()
        tokens = [w for w in words_raw if w is not None]

        # vocab (min_word_freq floor), unigram^0.75 negative table
        from collections import Counter

        freq = Counter(tokens)
        words = sorted([w for w, c in freq.items() if c >= p.min_word_freq])
        vocab = {w: i for i, w in enumerate(words)}
        V = len(vocab)
        assert V >= 2, "word2vec needs at least 2 vocabulary words"
        counts = np.array([freq[w] for w in words], np.float64)
        neg_p = counts**0.75
        neg_p /= neg_p.sum()

        # sentence stream → (center, context) pairs with h2o's frequent-word
        # subsampling; NA rows separate sentences
        rng = np.random.default_rng(abs(p.seed) if p.seed and p.seed > 0 else 13)
        total = counts.sum()
        if p.sent_sample_rate > 0:
            keep_p = np.minimum(
                1.0, np.sqrt(p.sent_sample_rate * total / np.maximum(counts, 1))
            )
        else:
            keep_p = np.ones(V)
        sents: list[list[int]] = [[]]
        for w in words_raw:
            if w is None:
                if sents[-1]:
                    sents.append([])
                continue
            wi = vocab.get(w)
            if wi is not None and rng.random() < keep_p[wi]:
                sents[-1].append(wi)
        centers, contexts = [], []
        for s in sents:
            for i, c in enumerate(s):
                win = rng.integers(1, p.window_size + 1)
                for j in range(max(0, i - win), min(len(s), i + win + 1)):
                    if j != i:
                        centers.append(c)
                        contexts.append(s[j])
        if not centers:
            raise ValueError("no training pairs (corpus too small for the vocab/window)")
        centers = np.asarray(centers, np.int32)
        contexts = np.asarray(contexts, np.int32)

        dim = p.vec_size
        Ein = jnp.asarray((rng.random((V, dim)) - 0.5) / dim, jnp.float32)
        Eout = jnp.zeros((V, dim), jnp.float32)

        # batch scales with vocab: scatter-adds SUM per-pair gradients, so a
        # word repeated many times inside one batch takes one huge step and
        # diverges — keep expected repeats-per-batch O(1)
        npairs = len(centers)
        B = int(np.clip(2 * V, 16, 1024))
        B = min(B, npairs)  # tiny corpora: never exceed the pair count
        nbatch = max(1, npairs // B)
        neg = p.negative_samples

        @jax.jit
        def epoch(Ein, Eout, cen, ctx, negs, lr):
            def step(carry, xs):
                Ein, Eout = carry
                c, o, ng = xs  # (B,), (B,), (B, neg)
                vc = Ein[c]  # (B, dim)
                uo = Eout[o]
                un = Eout[ng]  # (B, neg, dim)
                pos = jax.nn.sigmoid(jnp.sum(vc * uo, axis=1))
                gpos = (pos - 1.0)[:, None]  # d/d(vc·uo)
                sneg = jax.nn.sigmoid(jnp.einsum("bd,bnd->bn", vc, un))
                # gradients
                dvc = gpos * uo + jnp.einsum("bn,bnd->bd", sneg, un)
                duo = gpos * vc
                dun = sneg[:, :, None] * vc[:, None, :]
                Ein = Ein.at[c].add(-lr * dvc)
                Eout = Eout.at[o].add(-lr * duo)
                Eout = Eout.at[ng].add(-lr * dun)
                return (Ein, Eout), None

            (Ein, Eout), _ = jax.lax.scan(
                step, (Ein, Eout),
                (cen.reshape(nbatch, B), ctx.reshape(nbatch, B), negs.reshape(nbatch, B, neg)),
            )
            return Ein, Eout

        for e in range(p.epochs):
            perm = rng.permutation(npairs)[: nbatch * B]
            negs = rng.choice(V, size=(nbatch * B, neg), p=neg_p).astype(np.int32)
            lr = p.learning_rate * (1.0 - e / max(p.epochs, 1))
            Ein, Eout = epoch(
                Ein, Eout, jnp.asarray(centers[perm]), jnp.asarray(contexts[perm]),
                jnp.asarray(negs), jnp.float32(max(lr, p.learning_rate * 1e-2)),
            )
            job.update(0.05 + 0.9 * (e + 1) / p.epochs)

        out = {
            "vocab": vocab,
            "words": words,
            "embeddings": np.asarray(Ein),
            "response_domain": None,
            "names": [train.names[0]],
        }
        model = Word2VecModel(DKV.make_key("w2v"), p, out)
        model.training_metrics = ModelMetrics(
            "word2vec", {"vocab_size": V, "train_pairs": int(npairs), "vec_size": dim}
        )
        return model
