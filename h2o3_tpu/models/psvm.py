"""PSVM — successor of ``hex.psvm.PSVM`` [UNVERIFIED upstream path,
SURVEY.md §2.2]: binary SVM with the gaussian (RBF) kernel.

Upstream solves the kernel dual with ICF (incomplete Cholesky factorization
of the kernel matrix) + an interior-point method. The TPU redesign keeps the
same low-rank idea but in its MXU-native form: a **Nyström feature map**
(``rank_ratio`` landmark rows; Φ = K_nm · K_mm^{-1/2}) — mathematically the
same kernel-approximation family as ICF — followed by a linear
**squared-hinge** primal solve with Nesterov-accelerated full-batch gradient
descent, where every iteration is two (n, m) matmuls on device. Labels are
±1 internally; ``predict`` reports the decision value and the sign label,
H2O-style (PSVM emits no calibrated probabilities; metrics use a logistic
squash of the margin, a documented deviation).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.cluster.job import Job
from h2o3_tpu.cluster.registry import DKV
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.datainfo import DataInfo
from h2o3_tpu.models.model_base import CommonParams, Model, ModelBuilder
from h2o3_tpu.utils.log import Log


@dataclass
class PSVMParams(CommonParams):
    kernel_type: str = "gaussian"
    gamma: float = -1.0  # -1 -> 1 / n_features
    hyper_param: float = 1.0  # the penalty C
    positive_weight: float = 1.0
    negative_weight: float = 1.0
    rank_ratio: float = -1.0  # landmark fraction; -1 -> min(0.1, 200/n)
    max_iterations: int = 200
    convergence_tol: float = 1e-6


@partial(jax.jit, static_argnames=())
def _rbf_features(X, Lm, Whalf, gamma):
    """Nyström map: Φ = K(X, Lm) @ Whalf, with K gaussian."""
    d2 = (
        jnp.sum(X * X, axis=1)[:, None]
        - 2.0 * X @ Lm.T
        + jnp.sum(Lm * Lm, axis=1)[None, :]
    )
    K = jnp.exp(-gamma * jnp.maximum(d2, 0.0))
    return K @ Whalf


@partial(jax.jit, static_argnames=("iters",))
def _sq_hinge_fit(Phi, yy, sw, C, iters: int):
    """Accelerated GD on 0.5||w||² + C·Σ s_i·max(0, 1 − y(Φw+b))²."""
    n, m = Phi.shape

    def loss_grad(wb):
        w, b = wb[:m], wb[m]
        marg = 1.0 - yy * (Phi @ w + b)
        act = jnp.maximum(marg, 0.0) * sw
        gw = w - 2.0 * C * Phi.T @ (act * yy)
        gb = -2.0 * C * jnp.sum(act * yy)
        obj = 0.5 * jnp.dot(w, w) + C * jnp.sum(act * marg)
        return obj, jnp.concatenate([gw, jnp.array([gb])])

    # Lipschitz constant: 1 + 2C·λmax(ΦᵀSΦ) via a few power iterations
    def pw(v, _):
        u = Phi.T @ (sw * (Phi @ v))
        return u / jnp.maximum(jnp.linalg.norm(u), 1e-30), None

    v0 = jnp.ones(m) / jnp.sqrt(m)
    v, _ = jax.lax.scan(pw, v0, None, length=8)
    lam = jnp.linalg.norm(Phi.T @ (sw * (Phi @ v)))
    L = 1.0 + 2.0 * C * lam
    step = 1.0 / L

    def body(carry, _):
        wb, v, t = carry
        obj, g = loss_grad(v)
        wb_new = v - step * g
        t_new = 0.5 * (1.0 + jnp.sqrt(1.0 + 4.0 * t * t))
        v_new = wb_new + ((t - 1.0) / t_new) * (wb_new - wb)
        return (wb_new, v_new, t_new), obj

    init = (jnp.zeros(m + 1), jnp.zeros(m + 1), jnp.float32(1.0))
    (wb, _, _), objs = jax.lax.scan(body, init, None, length=iters)
    return wb, objs


class PSVMModel(Model):
    algo = "psvm"

    def _decision(self, frame: Frame) -> np.ndarray:
        di: DataInfo = self.output["datainfo"]
        X, _ = di.transform(frame)
        Phi = _rbf_features(
            X,
            jnp.asarray(self.output["landmarks"]),
            jnp.asarray(self.output["whalf"]),
            jnp.float32(self.output["gamma"]),
        )
        w = jnp.asarray(self.output["w"])
        return np.asarray(Phi @ w + self.output["b"])[: frame.nrow]

    def _predict_raw(self, frame: Frame) -> np.ndarray:
        # margin squash (metrics only); clip so exp can't overflow on wide
        # margins (p saturates at ~1e-27 anyway)
        d = np.clip(self._decision(frame), -30.0, 30.0)
        p1 = 1.0 / (1.0 + np.exp(-2.0 * d))
        return np.stack([1 - p1, p1], axis=1)

    def _distribution_for_metrics(self) -> str:
        return "bernoulli"


class PSVM(ModelBuilder):
    algo = "psvm"
    PARAMS_CLS = PSVMParams
    SUPPORTS_REGRESSION = False

    def _build(self, job: Job, train: Frame, valid: Frame | None) -> Model:
        p: PSVMParams = self.params
        if p.kernel_type.lower() != "gaussian":
            raise ValueError("psvm supports the gaussian kernel")
        yv = train.vec(p.response_column)
        if not yv.is_categorical() or yv.cardinality > 2:
            raise ValueError("psvm needs a binary categorical response")

        di = DataInfo.fit(
            train, self._x, standardize=True, use_all_factor_levels=False,
            add_intercept=False,
        )
        X, valid_mask = di.transform(train)
        nrow = train.nrow
        y_np = yv.to_numpy().astype(np.float64)
        w_np = np.asarray(valid_mask)[:nrow].astype(np.float64).copy()
        w_np *= y_np >= 0
        yy_np = np.where(y_np > 0, 1.0, -1.0)
        yy_np[w_np == 0] = 0.0
        sw_np = np.where(yy_np > 0, p.positive_weight, p.negative_weight) * w_np
        npad = train.npad
        yy = jnp.asarray(np.pad(yy_np, (0, npad - nrow)).astype(np.float32))
        sw = jnp.asarray(np.pad(sw_np, (0, npad - nrow)).astype(np.float32))

        nf = di.ncols_expanded
        gamma = p.gamma if p.gamma > 0 else 1.0 / max(nf, 1)

        rr = p.rank_ratio
        if rr <= 0:
            rr = min(0.1, 200.0 / max(nrow, 1))
        m = int(np.clip(round(nrow * rr), 8, min(1024, nrow)))
        rng = np.random.default_rng(abs(p.seed) or 31)
        lm_idx = rng.choice(nrow, m, replace=False)
        Lm = np.asarray(X)[lm_idx]

        # K_mm^{-1/2} via eigh (host, m×m)
        d2 = (
            np.sum(Lm * Lm, axis=1)[:, None]
            - 2.0 * Lm @ Lm.T
            + np.sum(Lm * Lm, axis=1)[None, :]
        )
        Kmm = np.exp(-gamma * np.maximum(d2, 0.0))
        ev, U = np.linalg.eigh(Kmm + 1e-6 * np.eye(m))
        ev = np.maximum(ev, 1e-10)
        Whalf = (U / np.sqrt(ev)) @ U.T

        Phi = _rbf_features(
            X, jnp.asarray(Lm, jnp.float32), jnp.asarray(Whalf, jnp.float32),
            jnp.float32(gamma),
        )
        iters = p.max_iterations if p.max_iterations > 0 else 200
        wb, objs = _sq_hinge_fit(Phi, yy, sw, jnp.float32(p.hyper_param), iters)
        w = np.asarray(wb[:m], np.float64)
        b = float(wb[m])
        objs = np.asarray(objs)
        Log.info(f"psvm: objective {objs[0]:.4g} -> {objs[-1]:.4g} in {iters} iters")

        # support vectors: rows inside the margin
        dec = np.asarray(Phi @ jnp.asarray(w, jnp.float32) + b)[:nrow]
        sv = int(np.sum((yy_np * dec < 1.0) & (w_np > 0)))

        out = {
            "datainfo": di,
            "landmarks": Lm.astype(np.float32),
            "whalf": Whalf.astype(np.float32),
            "gamma": float(gamma),
            "w": w.astype(np.float32),
            "b": b,
            "svs_count": sv,
            "rank": m,
            "names": list(self._x),
            "response_domain": tuple(yv.domain),
        }
        model = PSVMModel(DKV.make_key("psvm"), p, out)
        model.training_metrics = model._score_metrics(train)
        if valid is not None:
            model.validation_metrics = model._score_metrics(valid)
        return model
