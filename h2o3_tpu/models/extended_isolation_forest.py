"""Extended Isolation Forest — successor of ``hex.isoforextended``
[UNVERIFIED upstream path, SURVEY.md §2.2].

EIF (Hariri et al.) replaces IF's axis-parallel cuts with random oblique
hyperplanes: a node splits on x·n < d with a random normal n (``extension_
level`` + 1 nonzero components) and intercept d drawn inside the node's
bounding box. Like the IF builder, trees grow on tiny row subsamples
(host-scale numpy); scoring the full frame walks all rows through stacked
per-level (normal, intercept) arrays on device — projections are row-wise
dots, MXU-friendly. NAs are mean-imputed for projection (deviation noted:
upstream EIF rejects NA rows).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.cluster.job import Job
from h2o3_tpu.cluster.registry import DKV
from h2o3_tpu.frame.frame import Frame, Vec
from h2o3_tpu.models.metrics import ModelMetrics
from h2o3_tpu.models.model_base import CommonParams, Model, ModelBuilder


@dataclass
class ExtendedIsolationForestParams(CommonParams):
    ntrees: int = 100
    sample_size: int = 256
    extension_level: int = -1  # -1 → fully extended (C-1)


def _c(n: float) -> float:
    if n <= 1:
        return 0.0
    return 2.0 * (np.log(n - 1) + 0.5772156649) - 2.0 * (n - 1) / n


def _grow(X: np.ndarray, depth: int, max_depth: int, ext: int, rng) -> dict:
    n, C = X.shape
    if depth >= max_depth or n <= 1:
        return {"leaf": True, "len": depth + _c(n)}
    normal = rng.normal(size=C)
    if ext < C - 1:  # zero out all but ext+1 components
        off = rng.choice(C, C - (ext + 1), replace=False)
        normal[off] = 0.0
    proj = X @ normal
    lo, hi = proj.min(), proj.max()
    if hi <= lo:
        return {"leaf": True, "len": depth + _c(n)}
    d = rng.uniform(lo, hi)
    left = proj < d
    return {
        "leaf": False,
        "normal": normal,
        "d": d,
        "l": _grow(X[left], depth + 1, max_depth, ext, rng),
        "r": _grow(X[~left], depth + 1, max_depth, ext, rng),
    }


def _stack_tree(root: dict, C: int, max_depth: int):
    """Level arrays: normals (L, maxnodes, C), intercepts, leaf flags/lens."""
    levels = []
    frontier = [root]
    for d in range(max_depth + 1):
        width = 1 << d
        normals = np.zeros((width, C), np.float32)
        ds = np.zeros(width, np.float32)
        is_leaf = np.ones(width, bool)
        lens = np.zeros(width, np.float32)
        nxt = [None] * (2 * width)
        for i, node in enumerate(frontier):
            if node is None:
                continue
            if node["leaf"]:
                lens[i] = node["len"]
            else:
                is_leaf[i] = False
                normals[i] = node["normal"]
                ds[i] = node["d"]
                nxt[2 * i] = node["l"]
                nxt[2 * i + 1] = node["r"]
        levels.append((normals, ds, is_leaf, lens))
        frontier = nxt
        if all(x is None for x in frontier):
            break
    return levels


@partial(jax.jit, static_argnames=("n_levels",))
def _eif_paths(X, normals, ds, is_leaf, lens, n_levels: int):
    """Path length of every row through one stacked tree."""
    n = X.shape[0]
    nid = jnp.zeros(n, jnp.int32)
    done = jnp.zeros(n, bool)
    length = jnp.zeros(n, jnp.float32)
    for d in range(n_levels):
        leaf_here = is_leaf[d][nid]
        length = jnp.where(~done & leaf_here, lens[d][nid], length)
        done = done | leaf_here
        nrm = normals[d][nid]  # (n, C) gather
        proj = jnp.sum(X * nrm, axis=1)
        go_left = proj < ds[d][nid]
        nid = jnp.where(done, nid, 2 * nid + jnp.where(go_left, 0, 1))
    return length


class ExtendedIsolationForestModel(Model):
    algo = "extendedisolationforest"

    def _predict_raw(self, frame: Frame) -> np.ndarray:
        cols = self.output["names"]
        means = self.output["col_means"]
        X_np = np.stack(
            [
                np.where(
                    np.isnan(frame.vec(c).to_numpy().astype(np.float64)),
                    means[i],
                    frame.vec(c).to_numpy().astype(np.float64),
                )
                for i, c in enumerate(cols)
            ],
            axis=1,
        ).astype(np.float32)
        X = jnp.asarray(X_np)
        total = jnp.zeros(X.shape[0], jnp.float32)
        for levels in self.output["stacked_trees"]:
            normals = tuple(jnp.asarray(lv[0]) for lv in levels)
            ds = tuple(jnp.asarray(lv[1]) for lv in levels)
            is_leaf = tuple(jnp.asarray(lv[2]) for lv in levels)
            lens = tuple(jnp.asarray(lv[3]) for lv in levels)
            total = total + _eif_paths(X, normals, ds, is_leaf, lens, len(levels))
        mean_len = np.asarray(total) / max(len(self.output["stacked_trees"]), 1)
        score = 2.0 ** (-mean_len / max(_c(self.output["sample_size"]), 1e-9))
        return np.stack([score, mean_len], axis=1)

    def predict(self, frame: Frame) -> Frame:
        raw = self._predict_raw(frame)
        return Frame(
            [Vec.from_numpy(raw[:, 0], "real"), Vec.from_numpy(raw[:, 1], "real")],
            ["anomaly_score", "mean_length"],
        )


class ExtendedIsolationForest(ModelBuilder):
    algo = "extendedisolationforest"
    PARAMS_CLS = ExtendedIsolationForestParams
    SUPPORTS_CLASSIFICATION = False
    SUPPORTS_REGRESSION = False

    def train(self, x=None, training_frame=None, **kw):
        return super().train(x=x, y=None, training_frame=training_frame, **kw)

    def _features(self, train: Frame, response: str | None):
        return [n for n in train.names if train.vec(n).is_numeric()]

    def _validate(self, train: Frame, valid: Frame | None) -> None:
        pass  # unsupervised

    def _build(self, job: Job, train: Frame, valid: Frame | None):
        p: ExtendedIsolationForestParams = self.params
        cols = self._x
        assert cols, "EIF needs at least one numeric column"
        C = len(cols)
        ext = C - 1 if p.extension_level in (-1,) else min(p.extension_level, C - 1)

        Xall = np.stack(
            [train.vec(c).to_numpy().astype(np.float64) for c in cols], axis=1
        )
        means = np.nanmean(Xall, axis=0)
        Xall = np.where(np.isnan(Xall), means[None, :], Xall)

        rng = np.random.default_rng(abs(p.seed) if p.seed and p.seed > 0 else 77)
        psi = min(p.sample_size, train.nrow)
        max_depth = int(np.ceil(np.log2(max(psi, 2))))
        stacked = []
        for t in range(p.ntrees):
            idx = rng.choice(train.nrow, psi, replace=False)
            root = _grow(Xall[idx], 0, max_depth, ext, rng)
            stacked.append(_stack_tree(root, C, max_depth))
            job.update(0.05 + 0.85 * (t + 1) / p.ntrees)

        out = {
            "names": list(cols),
            "col_means": means,
            "stacked_trees": stacked,
            "sample_size": psi,
            "response_domain": None,
        }
        model = ExtendedIsolationForestModel(DKV.make_key("eif"), p, out)
        raw = model._predict_raw(train)[: train.nrow]
        model.training_metrics = ModelMetrics(
            "anomaly",
            {"mean_score": float(raw[:, 0].mean()), "mean_length": float(raw[:, 1].mean()),
             "nobs": train.nrow},
        )
        return model
