"""Aggregator — successor of ``hex.aggregator.Aggregator`` [UNVERIFIED
upstream path, SURVEY.md §2.2]: reduce a frame to ~``target_num_exemplars``
representative rows with member counts, preserving data topology better than
uniform sampling.

Same scheme as upstream (radius-based single-pass agglomeration with radius
escalation), re-shaped for the device: rows stream in chunks; each chunk's
distances to the current exemplar set are ONE (chunk, E) matmul-powered
pairwise-distance program on the MXU; rows farther than the radius from
every exemplar spawn new exemplars (greedy within the chunk, host-side on
the small candidate subset). When the exemplar count overshoots
``target * (1 + rel_tol)``, the radius scales up and the exemplar set is
re-aggregated against itself (upstream's shrink step).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.cluster.job import Job
from h2o3_tpu.cluster.registry import DKV
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.model_base import CommonParams, Model, ModelBuilder


@dataclass
class AggregatorParams(CommonParams):
    target_num_exemplars: int = 5000
    rel_tol_num_exemplars: float = 0.5
    transform: str = "NORMALIZE"  # NONE | STANDARDIZE | NORMALIZE
    categorical_encoding: str = "AUTO"  # one-hot on the distance space


@jax.jit
def _dists_prog(X_chunk, E, e_valid):
    d = (
        jnp.sum(X_chunk * X_chunk, axis=1)[:, None]
        - 2.0 * X_chunk @ E.T
        + jnp.sum(E * E, axis=1)[None, :]
    )
    d = jnp.where(e_valid[None, :], d, jnp.inf)
    return jnp.min(d, axis=1), jnp.argmin(d, axis=1)


def _pow2(v: int) -> int:
    return 1 << max(int(np.ceil(np.log2(max(v, 1)))), 0)


def _chunk_dists(Xc: np.ndarray, E: np.ndarray):
    """Min distance + argmin exemplar per row, shape-bucketed to powers of
    two so the jitted program compiles O(log) times, not once per call."""
    nr, ne = len(Xc), len(E)
    nrp, nep = _pow2(nr), _pow2(ne)
    Xp = np.zeros((nrp, Xc.shape[1]), np.float32)
    Xp[:nr] = Xc
    Ep = np.zeros((nep, E.shape[1]), np.float32)
    Ep[:ne] = E
    valid = np.zeros(nep, bool)
    valid[:ne] = True
    dmin, amin = _dists_prog(
        jnp.asarray(Xp), jnp.asarray(Ep), jnp.asarray(valid)
    )
    return np.asarray(dmin)[:nr], np.asarray(amin)[:nr]


class AggregatorModel(Model):
    algo = "aggregator"

    def _predict_raw(self, frame: Frame) -> np.ndarray:
        raise NotImplementedError("aggregator is a data-prep model")

    @property
    def aggregated_frame(self) -> Frame:
        return self.output["aggregated_frame"]

    def _score_metrics(self, frame: Frame):
        from h2o3_tpu.models.metrics import ModelMetrics

        return ModelMetrics(
            "aggregator",
            {"num_exemplars": float(self.output["num_exemplars"]),
             "nobs": float(self.output["nobs"])},
        )


class Aggregator(ModelBuilder):
    algo = "aggregator"
    PARAMS_CLS = AggregatorParams

    def _build(self, job: Job, train: Frame, valid: Frame | None) -> Model:
        p: AggregatorParams = self.params
        feats = self._x
        # numeric design space: transformed numerics + one-hot categoricals
        cols = []
        for n in feats:
            v = train.vec(n)
            x = v.to_numpy()
            if v.is_categorical():
                codes = x.astype(np.int64)
                k = v.cardinality
                oh = np.zeros((len(codes), k), np.float32)
                ok = codes >= 0
                oh[np.arange(len(codes))[ok], codes[ok]] = 1.0
                cols.append(oh)
            else:
                x = x.astype(np.float64)
                med = np.nanmean(x)
                x = np.where(np.isnan(x), med, x)
                t = p.transform.upper()
                if t == "STANDARDIZE":
                    s = np.nanstd(x) or 1.0
                    x = (x - np.nanmean(x)) / s
                elif t == "NORMALIZE":
                    lo, hi = np.nanmin(x), np.nanmax(x)
                    x = (x - lo) / ((hi - lo) or 1.0)
                cols.append(x.astype(np.float32)[:, None])
        X = np.concatenate(cols, axis=1)
        n, d = X.shape

        target = max(1, p.target_num_exemplars)
        hi_cap = target * (1.0 + p.rel_tol_num_exemplars)
        radius = 1e-3 * d  # squared-distance radius, scaled by dimensionality

        exemplars = X[:1].copy()
        counts = np.ones(1, np.int64)
        members = np.zeros(n, np.int64)
        rng = np.random.default_rng(abs(p.seed) or 19)
        chunk = 8192
        i = 1
        while i < n:
            Xc = X[i : i + chunk]
            idx_c = np.arange(i, min(i + chunk, n))
            # rows of this chunk not yet assigned to an exemplar
            todo = np.arange(len(Xc))
            while len(todo):
                dmin, amin = _chunk_dists(Xc[todo], exemplars)
                within = dmin <= radius
                hit = todo[within]
                members[idx_c[hit]] = amin[within]
                np.add.at(counts, amin[within], 1)
                todo = todo[~within]
                if not len(todo):
                    break
                budget = int(hi_cap) - len(counts)
                if budget <= 0:
                    # over budget: widen the radius and re-merge exemplars
                    radius *= 2.0
                    exemplars, counts, members = _reaggregate(
                        exemplars, counts, members, radius
                    )
                    continue
                # batched spawn: greedy maximin over a sample of the
                # uncovered rows (host math on a <=128² block), then the
                # device pass above reassigns the rest against them
                cand = todo[rng.permutation(len(todo))[: min(128, budget, len(todo))]]
                picked: list[int] = []
                for j in cand:
                    x = Xc[j]
                    if picked:
                        d = np.sum((Xc[picked] - x) ** 2, axis=1)
                        if d.min() <= radius:
                            continue
                    picked.append(int(j))
                new_ex = Xc[picked]
                base = len(counts)
                exemplars = np.vstack([exemplars, new_ex])
                counts = np.concatenate([counts, np.zeros(len(picked), np.int64)])
                members[idx_c[picked]] = base + np.arange(len(picked))
                counts[base:] += 1
                todo = np.setdiff1d(todo, np.asarray(picked, np.int64), assume_unique=False)
            i += chunk
            job.update(0.05 + 0.85 * i / n)

        # final budget enforcement
        while len(counts) > hi_cap:
            radius *= 2.0
            exemplars, counts, members = _reaggregate(exemplars, counts, members, radius)

        counts_np = np.asarray(counts, np.int64)
        agg_cols: dict[str, np.ndarray] = {}
        # exemplar rows in ORIGINAL column space: take the first member row
        uniq, first_idx = np.unique(members, return_index=True)
        first_member = np.zeros(len(counts_np), np.int64)
        first_member[uniq] = first_idx
        for name in train.names:
            v = train.vec(name)
            raw = v.to_numpy()
            vals = raw[first_member]
            if v.is_categorical():
                dom = v.domain or ()
                agg_cols[name] = np.asarray(
                    [dom[int(c)] if c >= 0 else None for c in vals], object
                )
            else:
                agg_cols[name] = vals
        agg_cols["counts"] = counts_np
        agg = Frame.from_arrays(agg_cols)

        out = {
            "aggregated_frame": agg,
            "num_exemplars": len(counts_np),
            "nobs": n,
            "mapping": members,
            "radius": radius,
            "names": list(feats),
        }
        model = AggregatorModel(DKV.make_key("aggregator"), p, out)
        model.training_metrics = model._score_metrics(train)
        return model


def _reaggregate(exemplars, counts, members, radius):
    """Merge exemplars closer than radius (greedy, count-weighted)."""
    E = len(exemplars)
    order = np.argsort(-counts)  # biggest exemplars absorb first
    new_idx = np.full(E, -1, np.int64)
    kept: list[int] = []
    for ei in order:
        x = exemplars[ei]
        if kept:
            K = exemplars[kept]
            d = np.sum((K - x) ** 2, axis=1)
            h = np.argmin(d)
            if d[h] <= radius:
                new_idx[ei] = h
                continue
        new_idx[ei] = len(kept)
        kept.append(ei)
    new_ex = exemplars[kept]
    new_counts = np.zeros(len(kept), np.int64)
    for ei in range(E):
        new_counts[new_idx[ei]] += counts[ei]
    new_members = new_idx[members]
    return new_ex, new_counts, new_members
