"""GLM — successor of ``hex.glm.GLM`` / ``GLMTask.GLMIterationTask`` /
``hex.glm.GLMModel`` / ``ComputationState`` [UNVERIFIED upstream paths,
SURVEY.md §2.2, §3.3].

Architecture (the BASELINE.json north-star GLM path):
- Per IRLS iteration ONE fused device program computes the working response,
  weights, weighted Gram XᵀWX and XᵀWz over the row-sharded design matrix —
  the ``GLMIterationTask.doAll`` successor, with XLA's psum replacing the
  MRTask log-tree reduce.
- The (p,p) solve is host-side float64: Cholesky when no L1, ADMM
  soft-thresholding for elastic net — mirroring H2O's single-node solve.
- Families: gaussian, binomial, quasibinomial, fractionalbinomial, poisson,
  gamma, tweedie, negativebinomial, multinomial (cycling per-class IRLS).
- Regularization: elastic net (alpha/lambda), full lambda search path with
  warm starts, strong-rule-free (dense Gram is cheap on MXU).
- Standardization, P-values for unpenalized fits, coefficient
  destandardization — matching ``GLMModel`` outputs.

Default lambda: like H2O, when ``lambda_`` is unset and ``lambda_search`` is
off we apply light shrinkage ``lambda_max/1000`` [UNVERIFIED exact upstream
default — H2O derives a small data-dependent default].
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.cluster.job import Job
from h2o3_tpu.cluster.registry import DKV
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.datainfo import MEAN_IMPUTATION, SKIP, DataInfo
from h2o3_tpu.models.glm_families import get_family
from h2o3_tpu.models.model_base import CommonParams, Model, ModelBuilder
from h2o3_tpu.ops.gram import (
    admm_elastic_net,
    admm_elastic_net_device,
    cho_solve_jitter_device,
    gram_collective_bytes,
    solve_cholesky,
    weighted_gram,
)
from h2o3_tpu.utils import faults
from h2o3_tpu.utils import metrics as _mx
from h2o3_tpu.utils.log import Log

_HI = jax.lax.Precision.HIGHEST

_IRLS_ITERS = _mx.counter(
    "glm_irls_iterations_total", "IRLS iterations executed")
_IRLS_SECONDS = _mx.histogram(
    "glm_irls_iteration_seconds",
    "per-IRLS-iteration wall time (Gram pass + solve; the hex.glm hot loop)")
_IRLS_SOLVE_SECONDS = _mx.histogram(
    "glm_irls_solve_seconds",
    "host-side (p,p) solve wall time per IRLS iteration (Cholesky/ADMM), "
    "split out of glm_irls_iteration_seconds so the fused-IRLS A/B can "
    "attribute its win; the fused lane solves on-device and reports only "
    "the iteration histogram")
# host dispatches issued by the IRLS loop (the fused-lane acceptance
# metric: O(iterations) unfused vs O(iterations/K) fused) and program-cache
# traffic for the fused chunk programs — the BUILD_STATS-style contract
# counters (always on, like the tree builders')
_GLM_DISPATCHES = _mx.counter(
    "glm_dispatches_total",
    "device-program launches issued by the GLM IRLS loop", always=True)
_GLM_COMPILED = _mx.counter(
    "glm_programs_compiled_total",
    "fused IRLS chunk program cache misses", always=True)
_GLM_HITS = _mx.counter(
    "glm_program_cache_hits_total",
    "fused IRLS chunk program cache hits (same shape bucket, no recompile)",
    always=True)
# the PR-5 collective byte family grows GLM phases (gram_reduce = the
# psum_scatter of G row blocks + b/sw psums, gram_gather = the one
# all_gather that reassembles G for the solve); same replication-volume
# model, tallied per executed iteration at dispatch time
_COLL_BYTES = _mx.counter(
    "tree_collective_bytes_total",
    "per-device collective payload bytes moved by tree builds (replication-"
    "volume model), by phase", always=True)

# Fallback observability (ISSUE 15): fits that WANT the fused while_loop
# lane (the knob says fuse) but drop to a slow lane for a structural
# reason — out-of-core streaming needs per-block host accumulation, a
# singular-in-f32 chunk drops its lambda to the host f64 tail, and a
# rejected fused-ordinal optimum falls back to the scipy driver.
# (compute_p_values rode the host-f64 trajectory until ISSUE 16; it now
# fuses — the covariance comes from the final device Gram at the
# converged beta, so the p_values reason only fires on a regression.)
_GLM_FALLBACKS = _mx.counter(
    "glm_fuse_fallbacks_total",
    "GLM fits (or lambda steps) that fell back from the fused while_loop "
    "lane while the fuse knob was on, by structural reason", always=True)

# fused IRLS chunk program cache: (shape bucket, family, solver branch,
# mesh, backend) -> compiled chunk. The shape-bucket ladder (rows ride the
# frame's bucketed npad; design columns pad to a multiple of 4 below) makes
# AutoML/grid rebuilds of near-identical frames reuse one program.
_GLM_PROGRAMS: dict = {}


def _glm_fuse_chunk(params) -> int:
    """Iterations per fused dispatch (K); 0 = the unfused per-iteration
    path. ``auto`` fuses with K=8 everywhere (the chunk program is plain
    XLA — while_loop + Cholesky — so the CPU proxy runs it too); an integer
    forces that K. compute_p_values fits fuse too (ISSUE 16): the
    covariance derives from the final device Gram at the converged beta
    (:meth:`GLM._p_values` re-runs one ``_irls_pass``), so nothing about
    the trajectory lane constrains it. With export_checkpoints_dir set
    the chunk clamps to 1 so PR-2's per-iteration irls_state snapshots land
    at the same loop positions."""
    from h2o3_tpu import config

    raw = config.get("H2O3_TPU_GLM_FUSE").strip().lower()
    if raw == "0":
        return 0
    k = int(raw) if raw.isdigit() else 8
    if getattr(params, "export_checkpoints_dir", None):
        return 1
    return max(k, 1)


def _mesh_shards() -> int:
    from h2o3_tpu.parallel.mesh import get_mesh

    return int(get_mesh().devices.size)


def _glm_pad_cols(p_real: int) -> int:
    """Design-matrix width for the fused lane: the PR-1 shape-bucket ladder
    (multiple of 4 under H2O3_TPU_SHAPE_BUCKETS) and then a multiple of the
    shard count so the Gram psum_scatter deals equal row blocks. Padded
    columns are all-zero with a unit solve diagonal — their coefficients
    are exactly zero, proven inert in tests/test_glm_dl_fuse.py."""
    from h2o3_tpu import config
    from h2o3_tpu.parallel.mesh import pad_cols_to_shards

    p = p_real
    if config.get_bool("H2O3_TPU_SHAPE_BUCKETS"):
        p = -(-p // 4) * 4
    return pad_cols_to_shards(p)


@dataclass
class GLMParams(CommonParams):
    family: str = "AUTO"
    link: str = "family_default"
    solver: str = "AUTO"  # -> IRLSM
    alpha: float | None = None
    lambda_: Any = None  # scalar, list, or None (auto)
    lambda_search: bool = False
    nlambdas: int = -1
    lambda_min_ratio: float = -1.0
    standardize: bool = True
    intercept: bool = True
    max_iterations: int = -1
    beta_epsilon: float = 1e-4
    objective_epsilon: float = 1e-6
    tweedie_variance_power: float = 0.0
    tweedie_link_power: float = 1.0
    theta: float = 1e-5
    missing_values_handling: str = MEAN_IMPUTATION
    compute_p_values: bool = False
    non_negative: bool = False
    # upstream `interactions` (all pairwise among the listed columns) and
    # `interaction_pairs` (explicit pairs); num x num and cat x num supported
    interactions: Any = None
    interaction_pairs: Any = None
    # feature hashing for Criteo-class cardinalities: cat columns wider than
    # this expand to a fixed hash-bucket indicator block (datainfo.py)
    hash_buckets: Any = None


# ---------------------------------------------------------------------------
# device programs (cached per family via partial+jit)


def _irls_weights(fam, X, y, w, offset, beta):
    """The GLMIterationTask row math for the current beta: IRLS working
    weights W, working response z, and the deviance — shared op-for-op by
    the per-iteration pass and the fused while_loop body so the two lanes
    compute identical iterations."""
    eta = jnp.einsum("np,p->n", X, beta, precision=_HI) + offset
    mu = fam.link.inv(eta)
    d = fam.link.dinv(eta)
    d = jnp.where(d == 0, 1e-10, jnp.sign(d) * jnp.maximum(jnp.abs(d), 1e-10))
    var = fam.variance(mu)
    z = (eta - offset) + (y - mu) / d
    W = w * d * d / var
    dev = fam.deviance(y, mu, w)
    return W, z, dev


@partial(jax.jit, static_argnames=("family_key", "fam_args"))
def _irls_pass(X, y, w, offset, beta, family_key, fam_args):
    """One GLMIterationTask: Gram/XtWz for the current beta + deviance."""
    fam = get_family(family_key, *fam_args)
    W, z, dev = _irls_weights(fam, X, y, w, offset, beta)
    G, b, sw = weighted_gram(X, W, z)
    return G, b, dev


def _fused_chunk_program(npad, p_pad, family_key, fam_args, l1_on,
                         non_negative):
    """Build (or fetch) the compiled K-iterations-per-dispatch IRLS chunk.

    One ``lax.while_loop`` runs up to ``kmax`` IRLS iterations entirely on
    device: the Gram pass ends in a psum_scatter of contiguous G row blocks
    over the rows mesh axis (each device keeps p/P rows; one all_gather
    hands the full G to the replicated solve), and the Cholesky-with-jitter
    or ADMM solve runs in f32 on device. The loop exits early on
    convergence (``stop``) or a non-finite solve (``bad`` — the host f64
    lstsq fallback lane takes over). All regularization/convergence scalars
    are DYNAMIC arguments so one program serves the whole lambda path;
    ``beta`` is donated (the carry pipelines across chunk dispatches)."""
    from h2o3_tpu.parallel.mesh import get_mesh, mesh_key

    key = ("glm_irls_chunk", npad, p_pad, family_key, fam_args, bool(l1_on),
           bool(non_negative), mesh_key(), jax.default_backend())
    fn = _GLM_PROGRAMS.get(key)
    if fn is not None:
        _GLM_HITS.inc()
        return fn
    _GLM_COMPILED.inc()

    from jax.sharding import PartitionSpec as Spec

    from h2o3_tpu.parallel.mesh import col_axis_name, row_pspec

    fam = get_family(family_key, *fam_args)
    mesh = get_mesh()
    n_sh = int(mesh.devices.size)
    cax = col_axis_name(mesh)
    ar = jnp.arange(p_pad)

    def gram_dev_sharded(X, y, w, offset, beta):
        """One GLMIterationTask with the MRTask reduce made explicit and
        PACKED: the per-device row math (working weights, working response,
        local Gram/XtWz partials, local deviance) runs inside shard_map,
        the Gram reduction ends in a psum_scatter of contiguous G row
        blocks over the column-block axis (2-D meshes reduce the rows axis
        exactly first, inside the wrapper), b and the deviance ride ONE
        packed psum, and a single all_gather reassembles G for the solve —
        three collective rendezvous per iteration instead of five
        (collective count, not just volume, is what the CPU proxy pays
        for)."""
        def local(Xl, yl, wl, ol, beta):
            from h2o3_tpu.ops import collectives

            W, z, dev = _irls_weights(fam, Xl, yl, wl, ol, beta)
            Xw = Xl * W[:, None]
            G_l = jnp.einsum("np,nq->pq", Xw, Xl, precision=_HI)
            b_l = jnp.einsum("np,n->p", Xw, z, precision=_HI)
            # the bulk G reduce rides the collective lane (quantized with a
            # residual-correction pass when on — the solve consumes G, so
            # it keeps ~14 effective mantissa bits); the small packed
            # b/deviance psum and the solve's G gather stay exact f32 so
            # convergence tests and the solve RHS are untouched
            G_blk = collectives.psum_scatter(
                G_l, n_dev=n_sh, passes=2, mesh=mesh)
            vec = collectives.exact_psum(
                jnp.concatenate([b_l, dev[None]]), mesh)
            G = jax.lax.all_gather(G_blk, cax, axis=0, tiled=True)
            return G, vec[:p_pad], vec[p_pad]

        from h2o3_tpu.parallel.mesh import shard_map

        rspec = row_pspec(mesh)
        return shard_map(
            local, mesh,
            in_specs=(row_pspec(mesh, ndim=2), rspec, rspec, rspec, Spec()),
            out_specs=(Spec(), Spec(), Spec()),
            check_vma=False,
        )(X, y, w, offset, beta)

    def chunk(beta, dev_prev, X, y, w, offset, kmax, l1, l2,
              beta_eps, obj_eps, icpt, pad_diag, real_p):
        def cond(c):
            _, _, it, stop, bad = c
            return (it < kmax) & ~stop & ~bad

        def body(c):
            beta, dev_prev, it, stop, bad = c
            if n_sh > 1:
                G, b, dev = gram_dev_sharded(X, y, w, offset, beta)
            else:
                W, z, dev = _irls_weights(fam, X, y, w, offset, beta)
                G, b, _sw = weighted_gram(X, W, z)
            if l1_on:
                beta_new, ok = admm_elastic_net_device(
                    G, b, l1, l2, icpt, pad_diag, real_p,
                    non_negative=non_negative,
                )
            else:
                # Gp = G + l2*I with the intercept unpenalized (the host
                # path's Gp[icpt, icpt] -= l2), plus the unit diagonal that
                # keeps padded bucket columns invertible at exactly zero
                extra = l2 * jnp.where(ar == icpt, 0.0, 1.0) + pad_diag
                beta_new, ok = cho_solve_jitter_device(G, b, extra)
                if non_negative:
                    beta_new = jnp.where(
                        (ar != icpt) & (beta_new < 0), 0.0, beta_new
                    )
            bad = ~ok | ~jnp.all(jnp.isfinite(beta_new))
            delta = jnp.max(jnp.abs(beta_new - beta))
            stop = ~bad & (
                (delta < beta_eps)
                | (jnp.abs(dev_prev - dev)
                   / jnp.maximum(jnp.abs(dev), 1e-10) < obj_eps)
            )
            beta = jnp.where(bad, beta, beta_new)
            dev_prev = jnp.where(stop | bad, dev_prev, dev)
            it = it + jnp.where(bad, 0, 1)
            return beta, dev_prev, it, stop, bad

        return jax.lax.while_loop(
            cond, body,
            (beta, dev_prev, jnp.int32(0), jnp.asarray(False),
             jnp.asarray(False)),
        )

    fn = jax.jit(chunk, donate_argnums=(0,))
    _GLM_PROGRAMS[key] = fn
    return fn


def _fused_multinomial_program(npad, p_pad, K, l1_on, non_negative):
    """Build (or fetch) the compiled fused multinomial cycling-IRLS chunk
    (ISSUE 15): ONE ``lax.while_loop`` runs up to ``kmax`` outer iterations
    per dispatch, each iteration a ``lax.scan`` over the K classes — class
    k's Gram pass sees the classes already updated this iteration, exactly
    the host loop's in-place cycling — with the sharded-Gram psum_scatter
    and the on-device Cholesky/ADMM solve per class reused from the
    single-response lane. The convergence exit replays the host rule
    (relative -2LL change from the LAST class's pass); any non-finite f32
    class solve sets ``bad``, discards that iteration's Beta wholesale and
    exits so the host float64 cycling tail takes over mid-trajectory."""
    from jax.sharding import PartitionSpec as Spec

    from h2o3_tpu.parallel.mesh import (
        col_axis_name, get_mesh, mesh_key, row_pspec, shard_map,
    )

    key = ("glm_multinom_chunk", npad, p_pad, K, bool(l1_on),
           bool(non_negative), mesh_key(), jax.default_backend())
    fn = _GLM_PROGRAMS.get(key)
    if fn is not None:
        _GLM_HITS.inc()
        return fn
    _GLM_COMPILED.inc()

    mesh = get_mesh()
    n_sh = int(mesh.devices.size)
    cax = col_axis_name(mesh)
    ar = jnp.arange(p_pad)

    def row_math(Xl, Yl, wl, Beta, k):
        """The _multinomial_pass row ops for class k — shared by the
        replicated and sharded bodies so both lanes compute the identical
        per-row floats."""
        Eta = jnp.einsum("np,pk->nk", Xl, Beta, precision=_HI)
        Eta = Eta - jax.scipy.special.logsumexp(Eta, axis=1, keepdims=True)
        Mu = jnp.exp(Eta)
        mu_k = jnp.clip(
            jax.lax.dynamic_index_in_dim(Mu, k, 1, keepdims=False),
            1e-10, 1 - 1e-10)
        wk = wl * mu_k * (1 - mu_k)
        beta_k = jax.lax.dynamic_index_in_dim(Beta, k, 1, keepdims=False)
        eta_k = jnp.einsum("np,p->n", Xl, beta_k, precision=_HI)
        yk = jax.lax.dynamic_index_in_dim(Yl, k, 1, keepdims=False)
        z = eta_k + (yk - mu_k) / jnp.maximum(
            wk / jnp.maximum(wl, 1e-10), 1e-10)
        Xw = Xl * wk[:, None]
        G_l = jnp.einsum("np,nq->pq", Xw, Xl, precision=_HI)
        b_l = jnp.einsum("np,n->p", Xw, z, precision=_HI)
        ll_l = jnp.sum(wl * jnp.sum(Yl * Eta, axis=1))
        return G_l, b_l, ll_l

    def class_pass(X, Y1h, w, Beta, k):
        if n_sh <= 1:
            G, b, ll = row_math(X, Y1h, w, Beta, k)
            return G, b, -2.0 * ll

        def local(Xl, Yl, wl, Beta, k):
            from h2o3_tpu.ops import collectives

            G_l, b_l, ll_l = row_math(Xl, Yl, wl, Beta, k)
            # same collective shape as the single-response fused lane:
            # bulk G through the (possibly quantized, residual-corrected)
            # scatter, packed exact psum for b/ll, one exact G gather
            G_blk = collectives.psum_scatter(
                G_l, n_dev=n_sh, passes=2, mesh=mesh)
            vec = collectives.exact_psum(
                jnp.concatenate([b_l, ll_l[None]]), mesh)
            G = jax.lax.all_gather(G_blk, cax, axis=0, tiled=True)
            return G, vec[:p_pad], -2.0 * vec[p_pad]

        rspec = row_pspec(mesh)
        return shard_map(
            local, mesh,
            in_specs=(row_pspec(mesh, ndim=2), row_pspec(mesh, ndim=2),
                      rspec, Spec(), Spec()),
            out_specs=(Spec(), Spec(), Spec()),
            check_vma=False,
        )(X, Y1h, w, Beta, k)

    def chunk(Beta, ll_prev, X, Y1h, w, kmax, l1, l2, obj_eps, icpt,
              pad_diag, real_p):
        def cond(c):
            _, _, it, stop, bad = c
            return (it < kmax) & ~stop & ~bad

        def body(c):
            Beta0, ll_prev, it, stop, bad = c

            def cstep(carry, k):
                Beta, bad_c = carry
                G, b, m2ll = class_pass(X, Y1h, w, Beta, k)
                if l1_on:
                    beta_k, ok = admm_elastic_net_device(
                        G, b, l1, l2, icpt, pad_diag, real_p,
                        non_negative=non_negative,
                    )
                else:
                    extra = l2 * jnp.where(ar == icpt, 0.0, 1.0) + pad_diag
                    beta_k, ok = cho_solve_jitter_device(G, b, extra)
                    if non_negative:
                        beta_k = jnp.where(
                            (ar != icpt) & (beta_k < 0), 0.0, beta_k)
                bad_k = ~ok | ~jnp.all(jnp.isfinite(beta_k))
                Beta = jnp.where(
                    bad_k, Beta,
                    jax.lax.dynamic_update_slice(
                        Beta, beta_k[:, None], (0, k)),
                )
                return (Beta, bad_c | bad_k), m2ll

            (Beta_new, bad_it), m2lls = jax.lax.scan(
                cstep, (Beta0, jnp.asarray(False)),
                jnp.arange(K, dtype=jnp.int32),
            )
            ll_now = m2lls[-1]  # the host rule: the LAST class's pass
            bad = bad_it
            stop = ~bad & (
                jnp.abs(ll_prev - ll_now)
                / jnp.maximum(jnp.abs(ll_now), 1e-10) < obj_eps
            )
            # a bad iteration is discarded WHOLE: the host f64 tail redoes
            # it from the pre-iteration Beta (the single-response rule)
            Beta = jnp.where(bad, Beta0, Beta_new)
            ll_prev = jnp.where(stop | bad, ll_prev, ll_now)
            it = it + jnp.where(bad, 0, 1)
            return Beta, ll_prev, it, stop, bad

        return jax.lax.while_loop(
            cond, body,
            (Beta, ll_prev, jnp.int32(0), jnp.asarray(False),
             jnp.asarray(False)),
        )

    fn = jax.jit(chunk, donate_argnums=(0,))
    _GLM_PROGRAMS[key] = fn
    return fn


@partial(jax.jit, static_argnames=("family_key", "fam_args"))
def _glm_dev_grad(X, y, w, offset, beta, family_key, fam_args):
    """Full-batch deviance + gradient in one fused pass (L-BFGS objective)."""
    fam = get_family(family_key, *fam_args)

    def dev(b):
        eta = jnp.einsum("np,p->n", X, b, precision=_HI) + offset
        mu = fam.link.inv(eta)
        return fam.deviance(y, mu, w)

    return jax.value_and_grad(dev)(beta)


@partial(jax.jit, static_argnames=("family_key", "fam_args"))
def _deviance_pass(X, y, w, offset, beta, family_key, fam_args):
    fam = get_family(family_key, *fam_args)
    eta = jnp.einsum("np,p->n", X, beta, precision=_HI) + offset
    mu = fam.link.inv(eta)
    return fam.deviance(y, mu, w)


@partial(jax.jit, static_argnames=("K",))
def _multinomial_pass(X, Y1h, w, Beta, K, k):
    """Cycling-IRLS pass for class k of a multinomial model."""
    Eta = jnp.einsum("np,pk->nk", X, Beta, precision=_HI)
    Eta = Eta - jax.scipy.special.logsumexp(Eta, axis=1, keepdims=True)
    Mu = jnp.exp(Eta)
    mu_k = jnp.clip(Mu[:, k], 1e-10, 1 - 1e-10)
    wk = w * mu_k * (1 - mu_k)
    eta_k = jnp.einsum("np,p->n", X, Beta[:, k], precision=_HI)
    z = eta_k + (Y1h[:, k] - mu_k) / jnp.maximum(wk / jnp.maximum(w, 1e-10), 1e-10)
    G, b, sw = weighted_gram(X, wk, z)
    ll = jnp.sum(w * jnp.sum(Y1h * Eta, axis=1))
    return G, b, -2.0 * ll


@partial(jax.jit, static_argnames=())
def _softmax_probs(X, Beta):
    Eta = jnp.einsum("np,pk->nk", X, Beta, precision=_HI)
    return jax.nn.softmax(Eta, axis=1)


# ---------------------------------------------------------------------------
# ordinal (proportional odds): P(y<=j) = sigmoid(theta_j - x.beta).
# One fused device program computes NLL + gradient; the (tiny) parameter
# vector is driven by host L-BFGS — the GLM "L_BFGS" solver reuses the same
# loss-plus-grad-on-device / optimize-on-host split.


@partial(jax.jit, static_argnames=("K",))
def _ordinal_nll_grad(X, y, w, beta, raw_cuts, K):
    """NLL and grad for proportional odds with ordered cuts.

    Cuts parameterized as theta_1 = raw_1, theta_j = theta_{j-1} +
    exp(raw_j) so ordering is unconstrained in raw space.
    """
    def nll(params):
        b = params[: X.shape[1]]
        raw = params[X.shape[1] :]
        theta = jnp.cumsum(
            jnp.concatenate([raw[:1], jnp.exp(raw[1:])])
        )  # (K-1,) ordered
        eta = jnp.einsum("np,p->n", X, b, precision=_HI)
        # P(y<=j) for j=0..K-2 ; clip for the log
        cum = jax.nn.sigmoid(theta[None, :] - eta[:, None])  # (n, K-1)
        lo = jnp.concatenate([jnp.zeros((X.shape[0], 1)), cum], axis=1)
        hi = jnp.concatenate([cum, jnp.ones((X.shape[0], 1))], axis=1)
        pk = jnp.clip(hi - lo, 1e-12, 1.0)  # (n, K)
        yi = jnp.clip(y.astype(jnp.int32), 0, K - 1)
        ll = jnp.take_along_axis(jnp.log(pk), yi[:, None], axis=1)[:, 0]
        return -jnp.sum(w * ll)

    val, g = jax.value_and_grad(nll)(jnp.concatenate([beta, raw_cuts]))
    return val, g


@partial(jax.jit, static_argnames=("K", "maxiter"))
def _ordinal_fused_fit(X, y, w, x0, K, maxiter):
    """Whole-program ordinal fit (ISSUE 15): the SAME proportional-odds NLL
    as :func:`_ordinal_nll_grad`, minimized entirely on device by
    ``jax.scipy.optimize.minimize(method='BFGS')`` — one dispatch instead
    of one per scipy line-search evaluation. The objective is convex in
    this parameterization, so BFGS and the host L-BFGS-B driver converge to
    the same optimum (pinned within the f32 envelope); a non-finite or
    unconverged result routes the caller back to the scipy path. Returns
    ``(x, nll, ok)``."""
    P = X.shape[1]

    def nll(params):
        b = params[:P]
        raw = params[P:]
        theta = jnp.cumsum(jnp.concatenate([raw[:1], jnp.exp(raw[1:])]))
        eta = jnp.einsum("np,p->n", X, b, precision=_HI)
        cum = jax.nn.sigmoid(theta[None, :] - eta[:, None])
        lo = jnp.concatenate([jnp.zeros((X.shape[0], 1)), cum], axis=1)
        hi = jnp.concatenate([cum, jnp.ones((X.shape[0], 1))], axis=1)
        pk = jnp.clip(hi - lo, 1e-12, 1.0)
        yi = jnp.clip(y.astype(jnp.int32), 0, K - 1)
        ll = jnp.take_along_axis(jnp.log(pk), yi[:, None], axis=1)[:, 0]
        return -jnp.sum(w * ll)

    import jax.scipy.optimize as _jsp_opt  # lazy submodule: import explicitly

    res = _jsp_opt.minimize(
        nll, x0, method="BFGS",
        options={"maxiter": maxiter, "gtol": 1e-6},
    )
    ok = jnp.all(jnp.isfinite(res.x)) & jnp.isfinite(res.fun)
    return res.x, res.fun, ok


# ---------------------------------------------------------------------------


def _lambda_sequence(p: "GLMParams", lambda_max: float, nobs: float, P: int):
    """The lambda schedule shared by every solver: explicit values, the
    lambda_search geometric path, or the light-shrinkage default — one
    definition so switching solver cannot silently change regularization."""
    if p.lambda_ is not None:
        return np.atleast_1d(np.asarray(p.lambda_, np.float64))
    if p.lambda_search:
        nl = p.nlambdas if p.nlambdas > 0 else 100
        ratio = p.lambda_min_ratio if p.lambda_min_ratio > 0 else (
            1e-4 if nobs > P else 1e-2
        )
        return np.geomspace(lambda_max, lambda_max * ratio, nl)
    return np.array([lambda_max / 1e3])


class GLMModel(Model):
    algo = "glm"

    def _predict_raw(self, frame: Frame) -> np.ndarray:
        di: DataInfo = self.output["datainfo"]
        X, valid = di.transform(frame)
        if self.output.get("ordinal"):
            beta = np.asarray(self.output["beta_std"], np.float64)
            theta = np.asarray(self.output["theta"], np.float64)
            eta = np.asarray(X, np.float64)[: frame.nrow] @ beta
            cum = 1.0 / (1.0 + np.exp(-(theta[None, :] - eta[:, None])))
            lo = np.concatenate([np.zeros((len(eta), 1)), cum], axis=1)
            hi = np.concatenate([cum, np.ones((len(eta), 1))], axis=1)
            return np.clip(hi - lo, 1e-12, 1.0)
        if self.output.get("multinomial"):
            Beta = jnp.asarray(self.output["beta_multinomial_std"], jnp.float32)
            probs = np.asarray(_softmax_probs(X, Beta))[: frame.nrow]
            return probs
        beta = jnp.asarray(self.output["beta_std"], jnp.float32)
        offset = _offset_col(self.params, frame)
        eta = np.asarray(
            jnp.einsum("np,p->n", X, beta, precision=_HI) + offset
        )[: frame.nrow]
        fam = self.output["family_obj"]
        mu = np.asarray(fam.link.inv(jnp.asarray(eta)))
        if self.is_classifier:
            return np.stack([1 - mu, mu], axis=1)
        return mu

    @property
    def coef(self) -> dict:
        return dict(zip(self.output["coef_names"], self.output["beta_orig"]))

    def coef_norm(self) -> dict:
        return dict(zip(self.output["coef_names"], self.output["beta_std_report"]))

    def _distribution_for_metrics(self) -> str:
        fam = self.output["family"]
        return {"poisson": "poisson", "gamma": "gamma"}.get(fam, "gaussian")


def _offset_col(params, frame: Frame):
    if params.offset_column:
        off = frame.vec(params.offset_column).data
        return jnp.nan_to_num(off)
    return jnp.zeros(frame.npad, jnp.float32)


class GLM(ModelBuilder):
    """``h2o.glm`` builder."""

    algo = "glm"
    PARAMS_CLS = GLMParams
    # upstream's REST/R param is "lambda" (a Python keyword, hence the
    # dataclass field lambda_); accept both over REST and the estimators
    PARAM_ALIASES = {"lambda": "lambda_"}

    def _build(self, job: Job, train: Frame, valid: Frame | None) -> Model:
        p: GLMParams = self.params
        yv = train.vec(p.response_column)

        family = p.family.lower()
        if family == "auto":
            if yv.is_categorical():
                family = "binomial" if yv.cardinality <= 2 else "multinomial"
            else:
                family = "gaussian"
        classification = (
            family in ("binomial", "multinomial", "ordinal")
            and yv.is_categorical()
        )

        pairs: list[tuple[str, str]] = []
        if p.interactions:
            import itertools as _it

            pairs += list(_it.combinations([str(c) for c in p.interactions], 2))
        if p.interaction_pairs:
            pairs += [(str(a), str(b)) for a, b in p.interaction_pairs]
        di = DataInfo.fit(
            train,
            self._x,
            standardize=p.standardize,
            use_all_factor_levels=False,
            missing_handling=p.missing_values_handling,
            # ordinal: the K-1 ordered cuts ARE the intercepts
            add_intercept=p.intercept and family != "ordinal",
            interaction_pairs=pairs or None,
            hash_buckets=int(p.hash_buckets) if p.hash_buckets else None,
        )

        y_np = yv.to_numpy()
        if yv.is_categorical():
            y_np = y_np.astype(np.float32)
            y_np[y_np < 0] = np.nan
        ybuf = np.zeros(train.npad, np.float32)
        ybuf[: train.nrow] = np.nan_to_num(y_np, nan=0.0)
        yna = np.zeros(train.npad, np.float32)
        yna[: train.nrow] = np.isnan(y_np)

        # out-of-core streaming (ISSUE 11, frame/chunkstore.py): a design
        # matrix past the HBM window streams as row-block chunks through
        # the per-iteration Gram accumulation (the IRLS Gram is a sum over
        # row blocks). Fallback matrix (docs/MIGRATION.md): multinomial /
        # ordinal / L-BFGS / compute_p_values stay resident.
        stream = None
        if (family not in ("multinomial", "ordinal")
                and p.solver.upper().replace("-", "_") not in ("L_BFGS", "LBFGS")
                and not p.compute_p_values):
            stream = self._plan_streamed(train, di, p, ybuf, yna)
        if stream is not None:
            X = stream
            w = stream.lane("w")
            y = ybuf
            offset = stream.lane("offset")
        else:
            X, valid_mask = di.transform(train)
            w = valid_mask
            if p.weights_column:
                w = w * jnp.nan_to_num(train.vec(p.weights_column).data)
            offset = _offset_col(p, train)
            w = w * (1.0 - jnp.asarray(yna))  # NA-response rows get weight 0
            y = jnp.asarray(ybuf)

        nobs = float(np.asarray(w.sum()))
        job.update(0.05)

        from h2o3_tpu.models.model_base import (
            check_checkpoint_compat,
            resolve_checkpoint,
        )

        prior = resolve_checkpoint(p.checkpoint)
        response_domain = tuple(yv.domain) if classification else None
        if prior is not None:
            if family == "ordinal" or p.solver.upper().replace(
                "-", "_"
            ) in ("L_BFGS", "LBFGS"):
                raise ValueError(
                    "GLM checkpoint resume supports the IRLSM paths only"
                )
            check_checkpoint_compat(
                prior, self,
                ("family", "link", "solver", "alpha", "lambda_",
                 "lambda_search", "nlambdas", "lambda_min_ratio",
                 "standardize", "intercept", "missing_values_handling",
                 "max_iterations", "beta_epsilon", "objective_epsilon"),
            )
            st = prior.output.get("irls_state")
            if st is None:
                raise ValueError(
                    "GLM checkpoint resume needs an in-training snapshot "
                    "(a COMPLETED GLM fit has converged; there is nothing to "
                    "continue)"
                )
            if family == "multinomial":
                if not st.get("multinomial"):
                    raise ValueError(
                        "checkpoint is not a multinomial irls_state snapshot"
                    )
                if np.asarray(st["Beta"]).shape[0] != di.ncols_expanded:
                    raise ValueError("checkpoint design-matrix width differs")
            elif len(st["beta"]) != di.ncols_expanded:
                raise ValueError("checkpoint design-matrix width differs")

        if family == "multinomial":
            out = self._fit_multinomial(job, X, y, w, di, yv, p, nobs,
                                        prior=prior)
        elif family == "ordinal":
            out = self._fit_ordinal(job, X, y, w, di, yv, p)
        elif p.solver.upper().replace("-", "_") in ("L_BFGS", "LBFGS"):
            out = self._fit_lbfgs(job, X, y, w, offset, di, p, family, nobs)
        else:
            out = self._fit_irls(job, X, y, w, offset, di, p, family, nobs,
                                 prior=prior, response_domain=response_domain)

        out["datainfo"] = di
        out["response_domain"] = tuple(yv.domain) if classification else None
        out["names"] = list(self._x)
        model = GLMModel(DKV.make_key("glm"), p, out)
        if stream is not None:
            # streamed scoring: never re-materialize the resident design
            model.training_metrics = self._streamed_metrics(model, stream, train)
            stream.close()
        else:
            model.training_metrics = model._score_metrics(train)
        if valid is not None:
            model.validation_metrics = model._score_metrics(valid)
        return model

    def _plan_streamed(self, train: Frame, di, p: GLMParams, ybuf, yna):
        """ChunkStore with the block-transformed design lanes, or None for
        the resident path. The block transform reuses ``di.transform`` on
        host-block sub-frames — elementwise per row, so each lane equals
        the resident design matrix row-for-row — and the source feature
        columns then drop to compressed/host residency."""
        from h2o3_tpu.frame import chunkstore as cs

        P = di.ncols_expanded
        store = cs.ChunkStore.plan(train.npad, (P + 3) * 4)
        if store is None:
            return None
        npad = train.npad
        Log.info(
            f"GLM out-of-core streaming: {store.n_blocks} blocks x "
            f"{store.block_rows} rows, design width {P}"
        )
        Xlane = store.add_empty("X", (npad, P), np.float32)
        vmask = np.zeros(npad, np.float32)
        need: list[str] = []
        for c in di.columns:
            for nm in (c.pair if c.pair is not None else (c.name,)):
                if nm not in need:
                    need.append(nm)
        for bi in range(store.n_blocks):
            lo, hi = store.span(bi)
            bf = cs.host_block_frame(train, need, lo, hi)
            Xb, vb = di.transform(bf)
            Xlane[lo:hi] = np.asarray(jax.device_get(Xb))
            vmask[lo:hi] = np.asarray(jax.device_get(vb))
        cs.release_frame_features(train, need)
        w_np = vmask
        if p.weights_column:
            w_np = w_np * np.nan_to_num(
                train.vec(p.weights_column).host_values().astype(np.float32))
        w_np = (w_np * (1.0 - yna)).astype(np.float32)
        store.add("w", w_np)
        store.add("y", np.asarray(ybuf, np.float32))
        off = np.zeros(npad, np.float32)
        if p.offset_column:
            off = np.nan_to_num(
                train.vec(p.offset_column).host_values().astype(np.float32))
        store.add("offset", off)
        return store

    def _streamed_metrics(self, model: "GLMModel", store, frame: Frame):
        """Training metrics without re-materializing the resident design:
        per-block linear predictor + link inverse over the store's lanes,
        then the standard metric builder on the host-assembled raw."""
        from h2o3_tpu.models.model_base import _make_metrics

        fam = model.output["family_obj"]
        beta = jnp.asarray(model.output["beta_std"], jnp.float32)
        parts = []
        for bi, blk in store.stream(("X", "offset")):
            eta = jnp.einsum(
                "np,p->n", blk["X"], beta, precision=_HI) + blk["offset"]
            parts.append(np.asarray(fam.link.inv(eta)))
        mu = np.concatenate(parts)[: frame.nrow]
        raw = np.stack([1 - mu, mu], axis=1) if model.is_classifier else mu
        yh, wh = model._response_and_weights(frame)
        return _make_metrics(model, raw, yh, wh)

    # -- single-vector families ---------------------------------------------
    def _irls_snapshot(self, key, p: GLMParams, di, beta, family, fam,
                       response_domain, state: dict) -> GLMModel:
        """Interval-snapshot factory: a scoreable partial GLM carrying the
        exact IRLS loop position (``irls_state``) so ``checkpoint=`` resume
        re-enters the solver at the next iteration and reproduces the
        uninterrupted trajectory bit-for-bit."""
        out = self._coef_output(np.asarray(beta, np.float64), di, p)
        out.update(
            family=family,
            family_obj=fam,
            multinomial=False,
            datainfo=di,
            names=list(self._x),
            response_domain=response_domain,
            null_deviance=state["null_dev"],
            residual_deviance=(state["best"]["deviance"]
                               if state.get("best") else float("nan")),
            irls_state=state,
        )
        return GLMModel(key, p, out)

    def _fit_irls(self, job, X, y, w, offset, di, p: GLMParams, family, nobs,
                  prior=None, response_domain=None):
        fam_args = (
            p.link,
            float(p.tweedie_variance_power or 1.5),
            float(p.tweedie_link_power),
            float(p.theta),
        )
        fam = get_family(family, *fam_args)
        P = di.ncols_expanded
        icpt = P - 1 if p.intercept else None
        alpha = 0.5 if p.alpha is None else float(p.alpha)
        max_iter = p.max_iterations if p.max_iterations > 0 else 50

        # out-of-core lane: X is a ChunkStore of row-block design lanes;
        # every full-batch pass becomes a block-accumulate loop around the
        # SAME _irls_pass program (the Gram is a sum over row blocks) and
        # the solve stays on the host float64 path (fallback matrix: the
        # fused while_loop needs the whole design resident per dispatch)
        from h2o3_tpu.frame.chunkstore import ChunkStore

        streaming = isinstance(X, ChunkStore)

        # fused whole-program lane (H2O3_TPU_GLM_FUSE): pad the design to
        # the shape-bucket/mesh width up front — padded columns are
        # all-zero, contribute exactly zero to every Gram/gradient below,
        # and every host-side vector stays REAL length (padding happens at
        # the dispatch boundary only)
        if streaming:
            from h2o3_tpu import config as _cfg

            if _cfg.get("H2O3_TPU_GLM_FUSE").strip().lower() != "0":
                _GLM_FALLBACKS.inc(reason="streamed")
            fuse_k = 0
        else:
            fuse_k = _glm_fuse_chunk(p)
        p_pad = _glm_pad_cols(P) if fuse_k else P
        if p_pad > P:
            X = jnp.pad(X, ((0, 0), (0, p_pad - P)))

        beta = np.zeros(P, np.float64)
        if p.intercept:
            mu0 = float(np.asarray(jnp.sum(w * y) / jnp.maximum(jnp.sum(w), 1e-10)))
            if family in ("binomial", "quasibinomial", "fractionalbinomial"):
                mu0 = min(max(mu0, 1e-4), 1 - 1e-4)
            beta[icpt] = float(np.asarray(fam.link.fwd(jnp.asarray(mu0))))

        def pad_beta(b64):
            return np.concatenate([b64, np.zeros(p_pad - P)]) if p_pad > P else b64

        def gram_pass(b64):
            """One GLMIterationTask over ALL rows for host-f64 consumers:
            resident = one _irls_pass dispatch; streamed = the same program
            per row block with the Gram/XtWz/deviance partials accumulated
            in float64 on host (the reduce the MRTask log-tree did).
            Returns (G (P,P) f64, b (P,) f64, dev float)."""
            b32 = jnp.asarray(pad_beta(b64), jnp.float32)
            if not streaming:
                G, b, dev = _irls_pass(X, y, w, offset, b32, family, fam_args)
                return (np.asarray(G, np.float64)[:P, :P],
                        np.asarray(b, np.float64)[:P], float(dev))
            G = np.zeros((P, P), np.float64)
            bb = np.zeros(P, np.float64)
            dev = 0.0
            for _bi, blk in X.stream(("X", "y", "w", "offset")):
                _GLM_DISPATCHES.inc()
                Gb, bbb, db = _irls_pass(
                    blk["X"], blk["y"], blk["w"], blk["offset"], b32,
                    family, fam_args,
                )
                G += np.asarray(Gb, np.float64)
                bb += np.asarray(bbb, np.float64)
                dev += float(db)
            return G, bb, dev

        def dev_pass(b64):
            b32 = jnp.asarray(pad_beta(b64), jnp.float32)
            if not streaming:
                return float(
                    _deviance_pass(X, y, w, offset, b32, family, fam_args))
            return sum(
                float(_deviance_pass(
                    blk["X"], blk["y"], blk["w"], blk["offset"], b32,
                    family, fam_args))
                for _bi, blk in X.stream(("X", "y", "w", "offset"))
            )

        # lambda path
        G0, b0, dev0 = gram_pass(beta)
        g0 = b0 - G0 @ beta
        if icpt is not None:
            g0_pen = np.delete(g0, icpt)
        else:
            g0_pen = g0
        lambda_max = float(np.max(np.abs(g0_pen)) / max(alpha, 1e-3) / max(nobs, 1.0))

        lambdas = _lambda_sequence(p, lambda_max, nobs, P)

        best = None
        null_dev = float(dev0)
        path = []
        # checkpoint resume: the prologue above (beta init, lambda_max,
        # lambdas, null_dev) is a pure function of the data and params —
        # recomputed identically — so only the LOOP POSITION is restored
        li0, it0, iters0, dev_prev0 = 0, 0, 0, np.inf
        if prior is not None:
            st = prior.output["irls_state"]
            li0, it0 = int(st["li"]), int(st["it"])
            iters0 = int(st.get("iters", it0))
            dev_prev0 = float(st["dev_prev"])
            beta = np.asarray(st["beta"], np.float64).copy()
            best = ({k: (np.asarray(v).copy() if k == "beta" else v)
                     for k, v in st["best"].items()} if st.get("best") else None)
            path = [dict(e) for e in st.get("path", ())]
        tot_iters = 0  # this run's executed iterations (chaos abort site)
        fam_obj = fam

        def snapshot(li, it_pos, iters_done, dev_prev, beta):
            self._export_interval_checkpoint(
                job,
                lambda key: self._irls_snapshot(
                    key, p, di, beta, family, fam_obj, response_domain,
                    {"li": li, "it": it_pos, "iters": iters_done,
                     "dev_prev": dev_prev, "beta": beta.copy(),
                     "best": best, "path": [dict(e) for e in path],
                     "null_dev": null_dev},
                ),
            )

        def host_iteration(beta, l1, l2):
            """One per-iteration host-solve IRLS step (the pre-fused path,
            the fused lane's singular-tail fallback, and the out-of-core
            streamed lane): Gram on device — full batch or block-
            accumulated — float64 Cholesky/ADMM on host. Returns
            (beta, dev_now, delta)."""
            if not streaming:
                _GLM_DISPATCHES.inc()
            G, b, dev = gram_pass(beta)
            _solve_t0 = time.perf_counter()
            if l1 > 0:
                beta_new = admm_elastic_net(
                    G, b, l1, l2, icpt, non_negative=p.non_negative
                )
            else:
                Gp = G + l2 * np.eye(P)
                if icpt is not None:
                    Gp[icpt, icpt] -= l2
                beta_new = solve_cholesky(Gp, b)
                if p.non_negative:
                    mask = np.arange(P) != (icpt if icpt is not None else -1)
                    beta_new = np.where(mask & (beta_new < 0), 0.0, beta_new)
            _IRLS_SOLVE_SECONDS.observe(time.perf_counter() - _solve_t0)
            delta = np.max(np.abs(beta_new - beta))
            return beta_new, float(dev), delta

        coll_model = gram_collective_bytes(
            p_pad, _mesh_shards()) if fuse_k else None
        for li, lam in enumerate(lambdas):
            if li < li0:
                continue
            l1 = lam * alpha * nobs
            l2 = lam * (1 - alpha) * nobs
            dev_prev = dev_prev0 if li == li0 else np.inf
            # it_pos is the resume marker (max_iter once this lambda's
            # iterations finished); iters_done is the TRUE iteration count
            # reported in the regularization path
            it_pos = it0 if li == li0 else 0
            iters_done = iters0 if li == li0 else 0
            fused_ok = bool(fuse_k)  # a bad (singular-in-f32) chunk drops
            #                          this lambda to the host-f64 tail
            while it_pos < max_iter:
                if fused_ok:
                    prog = _fused_chunk_program(
                        X.shape[0], p_pad, family, fam_args, l1 > 0,
                        p.non_negative,
                    )
                    kmax = min(fuse_k, max_iter - iters_done)
                    _it_t0 = time.perf_counter()
                    _GLM_DISPATCHES.inc()
                    from h2o3_tpu.utils import flightrec as _fr

                    with _fr.dispatch("irls_chunk", rows=int(X.shape[0]),
                                      cols=int(p_pad), k=int(kmax)):
                        beta_j, devp_j, ndone_j, stop_j, bad_j = prog(
                            jnp.asarray(pad_beta(beta), jnp.float32),
                            jnp.float32(dev_prev), X, y, w, offset,
                            jnp.int32(kmax), jnp.float32(l1), jnp.float32(l2),
                            jnp.float32(p.beta_epsilon),
                            jnp.float32(p.objective_epsilon),
                            jnp.int32(icpt if icpt is not None else -1),
                            jnp.asarray(
                                (np.arange(p_pad) >= P).astype(np.float32)),
                            jnp.float32(P),
                        )
                        n_done = int(ndone_j)
                    stop, bad = bool(stop_j), bool(bad_j)
                    _dt = time.perf_counter() - _it_t0
                    if n_done:
                        beta = np.asarray(beta_j, np.float64)[:P]
                        dev_prev = float(devp_j)
                        _IRLS_ITERS.inc(n_done)
                        for _ in range(n_done):
                            _IRLS_SECONDS.observe(_dt / n_done)
                        for ph, lanes in coll_model.items():
                            for lane, nb in lanes.items():
                                if nb:
                                    _COLL_BYTES.inc(nb * n_done, phase=ph)
                                    _COLL_BYTES.inc(
                                        nb * n_done, phase=ph, lane=lane)
                    iters_done += n_done
                    it_pos = max_iter if stop else iters_done
                    snapshot(li, it_pos, iters_done, dev_prev, beta)
                    first = tot_iters + 1
                    tot_iters += n_done
                    faults.die_check("glm")  # chaos: worker death at boundary
                    for i in range(first, tot_iters + 1):
                        faults.abort_check("glm", i)
                    if bad:
                        Log.warn(
                            "GLM fused IRLS chunk hit a non-finite f32 "
                            "solve; falling back to the host float64 lane "
                            f"for lambda index {li}"
                        )
                        _GLM_FALLBACKS.inc(reason="singular")
                        fused_ok = False
                    if stop:
                        break
                    continue
                _it_t0 = time.perf_counter()
                beta_new, dev_now, delta = host_iteration(beta, l1, l2)
                beta = beta_new
                iters_done += 1
                it_pos = iters_done
                tot_iters += 1
                # the np.asarray(G) in host_iteration forced the device
                # sync, so this is the true Gram+solve iteration time
                # (checkpoint IO excluded; persist_write_seconds covers it)
                _IRLS_ITERS.inc()
                _IRLS_SECONDS.observe(time.perf_counter() - _it_t0)
                stop = delta < p.beta_epsilon or abs(dev_prev - dev_now) / max(
                    abs(dev_now), 1e-10
                ) < p.objective_epsilon
                if stop:
                    it_pos = max_iter
                else:
                    dev_prev = dev_now
                # snapshot AFTER the stop decision: the recorded (li, it)
                # is exactly where a resumed run re-enters the loop (it ==
                # max_iter marks "this lambda's iterations are finished")
                snapshot(li, it_pos, iters_done, dev_prev, beta)
                faults.die_check("glm")  # chaos: worker death at boundary
                faults.abort_check("glm", tot_iters)
                if stop:
                    break
            dev_final = dev_pass(beta)
            expl = 1 - dev_final / max(null_dev, 1e-30)
            path.append({"lambda": float(lam), "deviance": dev_final, "dev_ratio": expl, "iters": iters_done})
            if best is None or dev_final <= best["deviance"]:
                best = {"lambda": float(lam), "beta": beta.copy(), "deviance": dev_final}
            job.update(0.05 + 0.8 * (li + 1) / len(lambdas))
            if p.lambda_search and expl > 0.999:
                break

        beta = best["beta"]
        out = self._coef_output(beta, di, p)
        out.update(
            family=family,
            family_obj=fam,
            null_deviance=null_dev,
            residual_deviance=best["deviance"],
            lambda_best=best["lambda"],
            lambda_max=lambda_max,
            alpha=alpha,
            regularization_path=path,
            multinomial=False,
        )
        if p.compute_p_values:
            out.update(self._p_values(X, y, w, offset, beta, family, fam_args, di, p, nobs))
        return out

    def _coef_output(self, beta_std, di: DataInfo, p: GLMParams,
                     has_intercept: bool | None = None) -> dict:
        """Destandardize coefficients back to the original scale.

        ``has_intercept`` overrides ``p.intercept`` for fits whose design has
        no intercept column regardless of the param (ordinal: the cuts are
        the intercepts) — otherwise the shift correction would clobber the
        LAST feature's coefficient. The accumulated shift is returned so
        such fits can fold it into their own intercept-like parameters.
        """
        if has_intercept is None:
            has_intercept = p.intercept
        names = di.coef_names()
        beta_std = np.asarray(beta_std, np.float64)
        beta_orig = beta_std.copy()
        shift = 0.0
        if p.standardize:
            for c in di.columns:
                if c.kind == "num":
                    beta_orig[c.offset] = beta_std[c.offset] / c.sigma
                    shift += beta_std[c.offset] * c.mean / c.sigma
            if has_intercept:
                beta_orig[-1] = beta_std[-1] - shift
        return {
            "coef_names": names,
            "beta_std": beta_std,
            "beta_std_report": beta_std,
            "beta_orig": beta_orig,
            "destandardize_shift": shift,
        }

    def _p_values(self, X, y, w, offset, beta, family, fam_args, di, p, nobs) -> dict:
        P = int(np.shape(beta)[0])
        b32 = jnp.asarray(beta, jnp.float32)
        if X.shape[1] > P:
            # fused lane: the design was padded to the shape-bucket width up
            # front; the padded columns are all-zero so slicing the Gram back
            # to the real width reproduces the unpadded pass exactly
            b32 = jnp.pad(b32, (0, X.shape[1] - P))
        G, b, dev = _irls_pass(X, y, w, offset, b32, family, fam_args)
        G = np.asarray(G, np.float64)[:P, :P]
        fam = get_family(family, *fam_args)
        try:
            inv = np.linalg.inv(G)
        except np.linalg.LinAlgError:
            inv = np.linalg.pinv(G)
        dispersion = 1.0
        if not fam.dispersion_fixed:
            dispersion = float(dev) / max(nobs - P, 1.0)
        se = np.sqrt(np.maximum(np.diag(inv) * dispersion, 0.0))
        z = np.asarray(beta, np.float64) / np.maximum(se, 1e-30)
        from scipy import stats as sps

        if fam.dispersion_fixed:
            pv = 2 * sps.norm.sf(np.abs(z))
        else:
            pv = 2 * sps.t.sf(np.abs(z), df=max(nobs - P, 1.0))
        return {"std_errs": se, "z_values": z, "p_values": pv, "dispersion": dispersion}

    # -- ordinal (proportional odds) ----------------------------------------
    def _fit_ordinal(self, job, X, y, w, di, yv, p: GLMParams):
        from scipy import optimize as spo

        if p.offset_column:
            raise ValueError("ordinal does not support offset_column")
        if p.compute_p_values:
            raise ValueError("compute_p_values requires solver=IRLSM")
        if p.lambda_search:
            raise ValueError("lambda_search is not supported for ordinal")
        if p.lambda_ is not None and float(np.atleast_1d(np.asarray(p.lambda_))[0]) > 0:
            Log.warn("ordinal fits unpenalized; lambda_ is ignored")
        K = yv.cardinality
        if K < 2:
            raise ValueError("ordinal needs a categorical response with >=2 levels")
        P = di.ncols_expanded
        # init: zero betas; first cut below zero, the rest unit-spaced
        # (the exp parameterization keeps them ordered during optimization)
        raw0 = np.zeros(K - 1)
        raw0[0] = -1.0
        x0 = np.concatenate([np.zeros(P), raw0])
        maxiter = p.max_iterations if p.max_iterations > 0 else 200

        # fused lane (ISSUE 15): the whole BFGS optimization of the SAME
        # convex proportional-odds NLL runs as one device program — one
        # dispatch instead of one per scipy line-search evaluation; a
        # non-finite result falls back to the host scipy driver below
        x_fit = None
        fun_val = None
        if _glm_fuse_chunk(p):
            _GLM_DISPATCHES.inc()
            x_j, f_j, ok_j = _ordinal_fused_fit(
                X, y, w, jnp.asarray(x0, jnp.float32), K, maxiter
            )
            if bool(ok_j):
                x_fit = np.asarray(x_j, np.float64)
                fun_val = float(f_j)
            else:
                Log.warn(
                    "GLM fused ordinal BFGS returned a non-finite optimum; "
                    "falling back to the host L-BFGS-B driver"
                )
                _GLM_FALLBACKS.inc(reason="ordinal_opt")

        if x_fit is None:
            def fun(params):
                val, g = _ordinal_nll_grad(
                    X, y, w, jnp.asarray(params[:P], jnp.float32),
                    jnp.asarray(params[P:], jnp.float32), K,
                )
                return float(val), np.asarray(g, np.float64)

            res = spo.minimize(
                fun, x0, jac=True, method="L-BFGS-B",
                options={"maxiter": maxiter},
            )
            x_fit = res.x
            fun_val = float(res.fun)
        beta = x_fit[:P]
        raw = x_fit[P:]
        theta = np.cumsum(np.concatenate([raw[:1], np.exp(raw[1:])]))
        out = self._coef_output(beta, di, p, has_intercept=False)
        out.update(
            family="ordinal",
            family_obj=get_family("binomial"),
            ordinal=True,
            theta=theta,  # standardized scale — what _predict_raw consumes
            # original-scale cuts: eta_std = eta_orig_lin - shift, so the
            # same cumulative probabilities come from theta + shift
            theta_orig=theta + out["destandardize_shift"],
            residual_deviance=2.0 * fun_val,
            null_deviance=float("nan"),
            multinomial=False,
        )
        job.update(0.9)
        return out

    # -- L-BFGS solver (hex/optimization/L_BFGS successor): the device
    # computes the full-batch objective+gradient in one fused pass; the
    # low-memory quasi-Newton direction update runs host-side in scipy.
    def _fit_lbfgs(self, job, X, y, w, offset, di, p: GLMParams, family, nobs):
        from scipy import optimize as spo

        fam_args = (
            p.link,
            float(p.tweedie_variance_power or 1.5),
            float(p.tweedie_link_power),
            float(p.theta),
        )
        if p.compute_p_values:
            raise ValueError("compute_p_values requires solver=IRLSM")
        fam = get_family(family, *fam_args)
        P = di.ncols_expanded
        icpt = P - 1 if p.intercept else None
        alpha = 0.5 if p.alpha is None else float(p.alpha)

        # null model: intercept (or zero) coefficients; its deviance INCLUDES
        # the offset (IRLSM uses dev0 from the same pass — a constant-mu null
        # would inflate dev_ratio and fire the path early-stop at lambda_max
        # whenever an offset explains most of the response)
        beta0 = np.zeros(P, np.float64)
        if p.intercept:
            mu0 = float(np.asarray(jnp.sum(w * y) / jnp.maximum(jnp.sum(w), 1e-10)))
            if family in ("binomial", "quasibinomial", "fractionalbinomial"):
                mu0 = min(max(mu0, 1e-4), 1 - 1e-4)
            beta0[icpt] = float(np.asarray(fam.link.fwd(jnp.asarray(mu0))))
        nd_v, g_v = _glm_dev_grad(
            X, y, w, offset, jnp.asarray(beta0, jnp.float32), family, fam_args
        )
        null_dev = float(nd_v)
        g_dev0 = np.asarray(g_v, np.float64)
        # lambda_max from the null gradient on the HALF-deviance scale
        # (the IRLSM derivation, without paying its O(N P^2) Gram pass)
        g_half = g_dev0 / 2.0
        g_pen = np.delete(g_half, icpt) if icpt is not None else g_half
        lambda_max = float(np.max(np.abs(g_pen)) / max(alpha, 1e-3) / max(nobs, 1.0))
        lambdas = _lambda_sequence(p, lambda_max, nobs, P)

        maxiter = p.max_iterations if p.max_iterations > 0 else 200
        l1_mask = np.ones(P)
        if icpt is not None:
            l1_mask[icpt] = 0.0

        def smooth(b, l2):
            """Deviance + L2 part (value, gradient) — device pass."""
            val, g = _glm_dev_grad(
                X, y, w, offset, jnp.asarray(b, jnp.float32), family, fam_args
            )
            b64 = np.asarray(b, np.float64)
            g64 = np.asarray(g, np.float64)
            pen = b64 * l1_mask
            return float(val) + l2 * float(pen @ pen), g64 + 2.0 * l2 * pen

        def solve_one(lam, beta_init):
            """One elastic-net L-BFGS solve, warm-started at beta_init.

            Objective scale: h2o minimizes (1/N)(deviance/2) + lam*P_alpha
            with P_alpha = alpha*||b||_1 + (1-alpha)/2*||b||^2. On the
            DEVIANCE scale (x 2N): l2 = lam*(1-alpha)*N on ||b||^2 and
            l1 = 2*lam*alpha*N on ||b||_1 — the factor 2 mirrors ADMM's
            penalties living on the half-deviance (Gram) scale.
            """
            l2 = lam * (1 - alpha) * nobs
            l1 = 2.0 * lam * alpha * nobs
            if l1 > 0:
                # exact L1 via the bound-constrained split beta = b+ - b-,
                # b± >= 0 with penalty l1*Σ(b+ + b-): a smooth box problem
                # L-BFGS-B solves natively (the OWL-QN alternative without
                # a custom solver)
                l1_vec = l1 * l1_mask

                def fun2(z):
                    bp, bn = z[:P], z[P:]
                    val, g = smooth(bp - bn, l2)
                    val += float(l1_vec @ (bp + bn))
                    return val, np.concatenate([g + l1_vec, -g + l1_vec])

                z0 = np.concatenate([np.maximum(beta_init, 0.0),
                                     np.maximum(-beta_init, 0.0)])
                res = spo.minimize(
                    fun2, z0, jac=True, method="L-BFGS-B",
                    bounds=[(0.0, None)] * (2 * P),
                    options={"maxiter": maxiter},
                )
                b = res.x[:P] - res.x[P:]
                # the split leaves tiny +/- residue where the true coef is 0
                b[np.abs(b) < 1e-10] = 0.0
                return b
            res = spo.minimize(
                lambda bb: smooth(bb, l2), beta_init, jac=True,
                method="L-BFGS-B", options={"maxiter": maxiter},
            )
            return res.x

        best = None
        path = []
        beta = beta0.copy()
        for li, lam_i in enumerate(lambdas):
            beta = solve_one(float(lam_i), beta)  # warm start down the path
            dev_i = float(
                _deviance_pass(
                    X, y, w, offset, jnp.asarray(beta, jnp.float32), family,
                    fam_args,
                )
            )
            expl = 1 - dev_i / max(null_dev, 1e-30)
            path.append({"lambda": float(lam_i), "deviance": dev_i,
                         "dev_ratio": expl})
            if best is None or dev_i <= best["deviance"]:
                best = {"lambda": float(lam_i), "beta": beta.copy(),
                        "deviance": dev_i}
            job.update(0.05 + 0.8 * (li + 1) / len(lambdas))
            if p.lambda_search and expl > 0.999:
                break

        beta = best["beta"]
        out = self._coef_output(beta, di, p)
        out.update(
            family=family, family_obj=fam,
            null_deviance=null_dev, residual_deviance=best["deviance"],
            lambda_best=best["lambda"], lambda_max=lambda_max, alpha=alpha,
            regularization_path=path, multinomial=False, solver="L_BFGS",
        )
        job.update(0.9)
        return out

    # -- multinomial ---------------------------------------------------------
    def _multinomial_output(self, di, Beta) -> dict:
        names = di.coef_names()
        return {
            "coef_names": names,
            "beta_multinomial_std": Beta,
            "beta_std": Beta[:, -1],
            "beta_orig": Beta[:, -1],
            "beta_std_report": Beta[:, -1],
            "family": "multinomial",
            "family_obj": get_family("binomial"),
            "multinomial": True,
        }

    def _multinomial_snapshot(self, key, p: GLMParams, di, Beta,
                              response_domain, state: dict) -> GLMModel:
        """Interval-snapshot factory for the cycling IRLS: a scoreable
        partial multinomial GLM carrying the outer-iteration position
        (``irls_state``: it / ll_prev / Beta) so ``checkpoint=`` resume
        re-enters the cycle at the next iteration and reproduces the
        uninterrupted trajectory bit-for-bit (the fused lane clamps its
        chunk to one iteration whenever export_checkpoints_dir is set)."""
        out = self._multinomial_output(di, np.asarray(Beta, np.float64))
        out.update(
            datainfo=di,
            names=list(self._x),
            response_domain=response_domain,
            residual_deviance=state["ll_prev"],
            irls_state=state,
        )
        return GLMModel(key, p, out)

    def _fit_multinomial(self, job, X, y, w, di, yv, p: GLMParams, nobs,
                         prior=None):
        K = yv.cardinality
        P = di.ncols_expanded
        icpt = P - 1 if p.intercept else None
        alpha = 0.5 if p.alpha is None else float(p.alpha)
        lam = 0.0
        if p.lambda_ is not None:
            lam = float(np.atleast_1d(np.asarray(p.lambda_))[0])
        max_iter = p.max_iterations if p.max_iterations > 0 else 30
        l1 = lam * alpha * nobs
        l2 = lam * (1 - alpha) * nobs
        response_domain = tuple(yv.domain)

        Y1h = (y[:, None] == jnp.arange(K)[None, :]).astype(jnp.float32) * (
            w[:, None] > 0
        )
        # fused whole-program lane (ISSUE 15): the K-class cycling IRLS was
        # per-class-per-iteration host-dispatched — exactly the
        # many-dispatch regime the single-response fusion pays off in. The
        # fused chunk runs up to K_chunk outer iterations as one program
        # (lax.scan over classes inside one while_loop); the host f64
        # cycling tail below stays as the non-finite escape hatch.
        fuse_k = _glm_fuse_chunk(p)
        p_pad = _glm_pad_cols(P) if fuse_k else P
        Xf = jnp.pad(X, ((0, 0), (0, p_pad - P))) if p_pad > P else X

        Beta = np.zeros((P, K), np.float64)
        ll_prev = np.inf
        it = 0
        if prior is not None:
            st = prior.output["irls_state"]
            it = int(st["it"])
            ll_prev = float(st["ll_prev"])
            Beta = np.asarray(st["Beta"], np.float64).copy()

        def snapshot(it_pos, ll_prev_v, Beta_v):
            self._export_interval_checkpoint(
                job,
                lambda key: self._multinomial_snapshot(
                    key, p, di, Beta_v, response_domain,
                    {"multinomial": True, "it": it_pos,
                     "ll_prev": ll_prev_v, "Beta": Beta_v.copy()},
                ),
            )

        def pad_Beta(B64):
            if p_pad > P:
                return np.concatenate(
                    [B64, np.zeros((p_pad - P, K))], axis=0)
            return B64

        fused_ok = bool(fuse_k)
        stop = False
        while it < max_iter and not stop:
            if fused_ok:
                prog = _fused_multinomial_program(
                    Xf.shape[0], p_pad, K, l1 > 0, p.non_negative
                )
                kmax = min(fuse_k, max_iter - it)
                _GLM_DISPATCHES.inc()
                from h2o3_tpu.utils import flightrec as _fr

                with _fr.dispatch("irls_chunk", rows=int(Xf.shape[0]),
                                  cols=int(p_pad), k=int(kmax), classes=K):
                    Beta_j, llp_j, ndone_j, stop_j, bad_j = prog(
                        jnp.asarray(pad_Beta(Beta), jnp.float32),
                        jnp.float32(ll_prev), Xf, Y1h, w,
                        jnp.int32(kmax), jnp.float32(l1), jnp.float32(l2),
                        jnp.float32(p.objective_epsilon),
                        jnp.int32(icpt if icpt is not None else -1),
                        jnp.asarray(
                            (np.arange(p_pad) >= P).astype(np.float32)),
                        jnp.float32(P),
                    )
                    n_done = int(ndone_j)
                stop, bad = bool(stop_j), bool(bad_j)
                if n_done:
                    Beta = np.asarray(Beta_j, np.float64)[:P]
                    ll_prev = float(llp_j)
                first = it + 1
                it += n_done
                snapshot(it, ll_prev, Beta)
                faults.die_check("glm")  # chaos: worker death at boundary
                for i in range(first, it + 1):
                    faults.abort_check("glm", i)
                if bad:
                    Log.warn(
                        "GLM fused multinomial chunk hit a non-finite f32 "
                        "class solve; falling back to the host float64 "
                        "cycling lane"
                    )
                    _GLM_FALLBACKS.inc(reason="singular")
                    fused_ok = False
                job.update(0.05 + 0.8 * min(it + 1, max_iter) / max_iter)
                continue
            # host float64 cycling lane (the pre-fusion path and the
            # singular-tail fallback): one dispatch per (iteration, class)
            for k in range(K):
                _GLM_DISPATCHES.inc()
                G, b, m2ll = _multinomial_pass(
                    X, Y1h, w, jnp.asarray(Beta, jnp.float32), K, k
                )
                G = np.asarray(G, np.float64)
                b = np.asarray(b, np.float64)
                if l1 > 0:
                    Beta[:, k] = admm_elastic_net(G, b, l1, l2, icpt)
                else:
                    Gp = G + l2 * np.eye(P)
                    if icpt is not None:
                        Gp[icpt, icpt] -= l2
                    Beta[:, k] = solve_cholesky(Gp, b)
            ll_now = float(m2ll)
            it += 1
            stop = (
                abs(ll_prev - ll_now) / max(abs(ll_now), 1e-10)
                < p.objective_epsilon
            )
            if not stop:
                ll_prev = ll_now
            snapshot(it, ll_prev, Beta)
            faults.die_check("glm")  # chaos: worker death at boundary
            faults.abort_check("glm", it)
            job.update(0.05 + 0.8 * it / max_iter)

        out = self._multinomial_output(di, Beta)
        out["residual_deviance"] = ll_prev
        return out
