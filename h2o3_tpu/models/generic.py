"""Generic model — successor of ``hex.generic.GenericModel`` [UNVERIFIED
upstream path, SURVEY.md §2.2]: re-import a portable scoring artifact
(tmojo zip) as a LIVE server-side model. Scoring-only, like upstream — the
wrapped numpy scorer handles score0; predict returns the standard H2O
prediction frame layout and the model participates in the DKV/REST surface.
"""

from __future__ import annotations

import numpy as np

from h2o3_tpu.cluster.registry import DKV
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.genmodel import MojoModel
from h2o3_tpu.models.model_base import Model


class GenericModelParams:
    response_column = None
    weights_column = None
    offset_column = None


class GenericModel(Model):
    algo = "generic"

    def __init__(self, key: str, mojo: MojoModel):
        self._mojo = mojo
        out = {
            "names": mojo.meta.get("names", []),
            "response_domain": tuple(mojo.domain) if mojo.domain else None,
            "source_algo": mojo.algo,
        }
        params = GenericModelParams()
        params.response_column = mojo.meta.get("response_column")
        super().__init__(key, params, out)
        thr = mojo.meta.get("default_threshold")
        if thr is not None:
            from h2o3_tpu.models.metrics import ModelMetrics

            # carry the original max-F1 threshold so predict labels match
            self.training_metrics = ModelMetrics(
                "generic", {"default_threshold": float(thr)}
            )

    def _predict_raw(self, frame: Frame) -> np.ndarray:
        # to_pandas decodes enum codes to labels, which the offline scorer
        # maps through its own fitted domains
        table = self._mojo._rows_to_table(frame.to_pandas())
        return np.asarray(self._mojo.score_raw(table))


def import_mojo_model(path: str, model_id: str | None = None) -> GenericModel:
    """``h2o.import_mojo`` (server-side Generic) successor."""
    mojo = MojoModel.load(path)
    return GenericModel(model_id or DKV.make_key("generic"), mojo)
