"""Algorithm layer — successor of ``hex.*`` (h2o-algos) [UNVERIFIED upstream
paths, SURVEY.md §2.2]. Every algorithm is a ModelBuilder producing a Model,
expressed against the sharded Frame + map-reduce fabric only."""

from h2o3_tpu.models.model_base import Model, ModelBuilder
from h2o3_tpu.models.datainfo import DataInfo


_LAZY = {
    "GLM": ("h2o3_tpu.models.glm", "GLM"),
    "GBM": ("h2o3_tpu.models.tree.gbm", "GBM"),
    "XGBoost": ("h2o3_tpu.models.tree.xgboost", "XGBoost"),
    "DRF": ("h2o3_tpu.models.tree.drf", "DRF"),
    "XRT": ("h2o3_tpu.models.tree.drf", "XRT"),
    "KMeans": ("h2o3_tpu.models.kmeans", "KMeans"),
    "PCA": ("h2o3_tpu.models.pca", "PCA"),
    "SVD": ("h2o3_tpu.models.pca", "SVD"),
    "NaiveBayes": ("h2o3_tpu.models.naive_bayes", "NaiveBayes"),
    "IsolationForest": ("h2o3_tpu.models.isolation_forest", "IsolationForest"),
    "DeepLearning": ("h2o3_tpu.models.deeplearning", "DeepLearning"),
    "GridSearch": ("h2o3_tpu.models.grid", "GridSearch"),
    "Grid": ("h2o3_tpu.models.grid", "Grid"),
    "StackedEnsemble": ("h2o3_tpu.models.ensemble", "StackedEnsemble"),
    "IsotonicRegression": ("h2o3_tpu.models.isotonic", "IsotonicRegression"),
    "DT": ("h2o3_tpu.models.decision_tree", "DT"),
    "AdaBoost": ("h2o3_tpu.models.adaboost", "AdaBoost"),
    "ExtendedIsolationForest": ("h2o3_tpu.models.extended_isolation_forest", "ExtendedIsolationForest"),
    "TargetEncoder": ("h2o3_tpu.models.target_encoding", "TargetEncoder"),
    "GLRM": ("h2o3_tpu.models.glrm", "GLRM"),
    "CoxPH": ("h2o3_tpu.models.coxph", "CoxPH"),
    "Word2Vec": ("h2o3_tpu.models.word2vec", "Word2Vec"),
    "GenericModel": ("h2o3_tpu.models.generic", "GenericModel"),
    "RuleFit": ("h2o3_tpu.models.rulefit", "RuleFit"),
    "UpliftDRF": ("h2o3_tpu.models.uplift", "UpliftDRF"),
    "GAM": ("h2o3_tpu.models.gam", "GAM"),
    "ModelSelection": ("h2o3_tpu.models.model_selection", "ModelSelection"),
    "ANOVAGLM": ("h2o3_tpu.models.anovaglm", "ANOVAGLM"),
    "Aggregator": ("h2o3_tpu.models.aggregator", "Aggregator"),
    "Infogram": ("h2o3_tpu.models.infogram", "Infogram"),
    "PSVM": ("h2o3_tpu.models.psvm", "PSVM"),
    "HGLM": ("h2o3_tpu.models.hglm", "HGLM"),
}

__all__ = ["Model", "ModelBuilder", "DataInfo", *_LAZY]


def __getattr__(name):
    # lazy algo imports so `import h2o3_tpu` stays light
    if name in _LAZY:
        import importlib

        mod, attr = _LAZY[name]
        return getattr(importlib.import_module(mod), attr)
    raise AttributeError(name)


def __dir__():
    return sorted(set(globals()) | set(__all__))
