"""Algorithm layer — successor of ``hex.*`` (h2o-algos) [UNVERIFIED upstream
paths, SURVEY.md §2.2]. Every algorithm is a ModelBuilder producing a Model,
expressed against the sharded Frame + map-reduce fabric only."""

from h2o3_tpu.models.model_base import Model, ModelBuilder
from h2o3_tpu.models.datainfo import DataInfo

__all__ = ["Model", "ModelBuilder", "DataInfo"]
