"""Isotonic regression — successor of ``hex.isotonic.IsotonicRegression``
[UNVERIFIED upstream path, SURVEY.md §2.2].

Weighted pool-adjacent-violators on the single feature (PAV is inherently
sequential — an O(n) host pass after one device sort-key pull); prediction
is linear interpolation between fitted thresholds with H2O's ``clip``
out-of-bounds policy.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from h2o3_tpu.cluster.job import Job
from h2o3_tpu.cluster.registry import DKV
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.model_base import CommonParams, Model, ModelBuilder
from h2o3_tpu.models import metrics as MM


@dataclass
class IsotonicRegressionParams(CommonParams):
    out_of_bounds: str = "clip"  # clip | na


def _pav(y: np.ndarray, w: np.ndarray) -> np.ndarray:
    """Weighted pool-adjacent-violators: isotonic fit of y (sorted by x)."""
    n = len(y)
    fitted = y.astype(np.float64).copy()
    weight = w.astype(np.float64).copy()
    # block-merge stack: (start, value, weight)
    starts = np.zeros(n, np.int64)
    vals = np.zeros(n, np.float64)
    wts = np.zeros(n, np.float64)
    top = -1
    for i in range(n):
        top += 1
        starts[top], vals[top], wts[top] = i, fitted[i], weight[i]
        while top > 0 and vals[top - 1] > vals[top]:
            wsum = wts[top - 1] + wts[top]
            vals[top - 1] = (vals[top - 1] * wts[top - 1] + vals[top] * wts[top]) / wsum
            wts[top - 1] = wsum
            top -= 1
    out = np.empty(n, np.float64)
    for b in range(top + 1):
        end = starts[b + 1] if b < top else n
        out[starts[b] : end] = vals[b]
    return out


def pav_block_knots(fitted: np.ndarray) -> np.ndarray:
    """Mask keeping only PAV block-boundary knots (first/last of each
    constant run): np.interp over the kept knots is identical, and the
    stored threshold arrays stay O(blocks) instead of O(n)."""
    keep = np.ones(len(fitted), bool)
    if len(fitted) > 2:
        keep[1:-1] = (fitted[1:-1] != fitted[:-2]) | (fitted[1:-1] != fitted[2:])
    return keep


class IsotonicRegressionModel(Model):
    algo = "isotonicregression"

    def _predict_raw(self, frame: Frame) -> np.ndarray:
        x = frame.vec(self.output["feature"]).to_numpy().astype(np.float64)
        tx = self.output["thresholds_x"]
        ty = self.output["thresholds_y"]
        out = np.interp(x, tx, ty)
        if self.params.out_of_bounds == "na":
            out[(x < tx[0]) | (x > tx[-1])] = np.nan
        out[np.isnan(x)] = np.nan
        return out


class IsotonicRegression(ModelBuilder):
    algo = "isotonicregression"
    PARAMS_CLS = IsotonicRegressionParams
    SUPPORTS_CLASSIFICATION = False

    def _build(self, job: Job, train: Frame, valid: Frame | None) -> Model:
        p = self.params
        assert len(self._x) == 1, "isotonic regression takes exactly one feature"
        feat = self._x[0]
        x = train.vec(feat).to_numpy().astype(np.float64)
        y = train.vec(p.response_column).to_numpy().astype(np.float64)
        w = np.ones_like(y)
        if p.weights_column:
            w = np.nan_to_num(train.vec(p.weights_column).to_numpy()).astype(np.float64)
        ok = ~np.isnan(x) & ~np.isnan(y) & (w > 0)
        x, y, w = x[ok], y[ok], w[ok]
        order = np.argsort(x, kind="mergesort")
        x, y, w = x[order], y[order], w[order]
        # pool ties in x first (H2O's secondary aggregation)
        ux, inv = np.unique(x, return_inverse=True)
        wsum = np.bincount(inv, weights=w)
        ysum = np.bincount(inv, weights=w * y)
        ymean = ysum / np.maximum(wsum, 1e-300)
        fitted = _pav(ymean, wsum)
        # keep only breakpoints (H2O stores thresholds)
        keep = pav_block_knots(fitted)
        out = {
            "feature": feat,
            "thresholds_x": ux[keep],
            "thresholds_y": fitted[keep],
            "names": [feat],
            "response_domain": None,
        }
        model = IsotonicRegressionModel(DKV.make_key("isotonic"), p, out)
        pred = model._predict_raw(train)
        yy, ww = model._response_and_weights(train)
        model.training_metrics = MM.regression_metrics(yy, pred, ww)
        return model
