"""Stacked Ensembles — successor of ``hex.ensemble.StackedEnsemble`` /
``hex.ensemble.Metalearner*`` [UNVERIFIED upstream paths, SURVEY.md §2.2].

H2O's SE trains a metalearner (default: GLM with non-negative coefficients)
on the *cross-validation holdout predictions* of the base models, which must
have been built with identical nfolds/fold assignment and
``keep_cross_validation_predictions=True``. Scoring = run every base model,
assemble their prediction columns into the level-one frame, score the
metalearner on it. The same contract is kept here; the level-one frame is a
plain device matrix (base-model count is small, so this is host-cheap).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Sequence

import numpy as np

from h2o3_tpu.cluster.job import Job
from h2o3_tpu.cluster.registry import DKV
from h2o3_tpu.frame.frame import Frame, Vec
from h2o3_tpu.models.model_base import (
    CommonParams,
    Model,
    ModelBuilder,
    _make_metrics,
)


@dataclass
class StackedEnsembleParams(CommonParams):
    base_models: Sequence[Any] = field(default_factory=tuple)  # Model | key
    metalearner_algorithm: str = "AUTO"  # AUTO->glm | glm | gbm | drf | deeplearning
    metalearner_params: dict = field(default_factory=dict)
    # The metalearner is cross-validated by default (H2O default is 0): its
    # holdout predictions are the only honest estimate of ensemble
    # generalization for leaderboard ranking (see _build).
    metalearner_nfolds: int = 5


def _shape_prediction_columns(raw: np.ndarray, is_classifier: bool) -> np.ndarray:
    """One base model's level-one contribution: per binomial model P(c1);
    per multinomial model K prob columns; per regression model 1 column."""
    raw = np.asarray(raw, dtype=np.float64)
    if raw.ndim == 1:
        return raw[:, None]
    if raw.shape[1] == 2 and is_classifier:
        return raw[:, 1:2]
    return raw


def _level_one_matrix(models: list[Model], frame: Frame) -> np.ndarray:
    return np.concatenate(
        [_shape_prediction_columns(m._predict_raw(frame), m.is_classifier) for m in models],
        axis=1,
    )


def _level_one_cv_matrix(models: list[Model]) -> np.ndarray:
    cols = []
    for m in models:
        cv = m.cv_predictions
        assert cv is not None, (
            f"base model {m.key} lacks CV holdout predictions; train with "
            "nfolds>1 and keep_cross_validation_predictions=True"
        )
        cols.append(_shape_prediction_columns(cv, m.is_classifier))
    return np.concatenate(cols, axis=1)


class StackedEnsembleModel(Model):
    algo = "stackedensemble"

    def __init__(self, key, params, output, base_models, metalearner):
        super().__init__(key, params, output)
        self.base_models = base_models
        self.metalearner = metalearner

    def _predict_raw(self, frame: Frame) -> np.ndarray:
        L = _level_one_matrix(self.base_models, frame)
        lframe = _matrix_frame(L)
        return self.metalearner._predict_raw(lframe)


def _matrix_frame(
    L: np.ndarray,
    y: np.ndarray | None = None,
    domain=None,
    weights: np.ndarray | None = None,
) -> Frame:
    vecs = [Vec.from_numpy(L[:, j], "real") for j in range(L.shape[1])]
    names = [f"bm_{j}" for j in range(L.shape[1])]
    if y is not None:
        if domain is not None:
            vecs.append(Vec.from_numpy(y.astype(np.int32), "enum", domain=tuple(domain)))
        else:
            vecs.append(Vec.from_numpy(y, "real"))
        names.append("y")
    if weights is not None:
        vecs.append(Vec.from_numpy(weights, "real"))
        names.append("__se_weights")
    return Frame(vecs, names)


class StackedEnsemble(ModelBuilder):
    algo = "stackedensemble"
    PARAMS_CLS = StackedEnsembleParams

    def _build(self, job: Job, train: Frame, valid: Frame | None) -> Model:
        p: StackedEnsembleParams = self.params
        models = self._resolved_base  # resolved + checked in _validate
        ref = models[0]
        if p.response_column is None:
            p.response_column = ref.params.response_column
        classification = ref.is_classifier
        domain = ref.output.get("response_domain")

        L = _level_one_cv_matrix(models)
        y, w = ref._response_and_weights(train)
        self._meta_weights = w is not None
        lframe = _matrix_frame(L, y, domain if classification else None, weights=w)
        job.update(0.3)

        meta = self._make_metalearner(classification, len(domain) if domain else 1)
        meta_model = meta.train(y="y", training_frame=lframe)
        job.update(0.9)

        model = StackedEnsembleModel(
            DKV.make_key("stackedensemble"),
            p,
            {
                "response_domain": tuple(domain) if domain else None,
                "base_model_keys": [m.key for m in models],
                "metalearner_key": meta_model.key,
            },
            models,
            meta_model,
        )
        raw = model._predict_raw(train)
        model.training_metrics = _make_metrics(model, np.asarray(raw), y, w)
        if valid is not None:
            model.validation_metrics = model._score_metrics(valid)
        # Honest CV metrics: the metalearner is itself cross-validated on the
        # level-one frame, so its holdout predictions estimate the ensemble's
        # generalization (the metalearner training view would be optimistic
        # resubstitution error and over-rank the SE on leaderboards).
        if meta_model.cv_predictions is not None:
            model.cross_validation_metrics = _make_metrics(
                model, np.asarray(meta_model.cv_predictions), y, w
            )
        return model

    def _make_metalearner(self, classification: bool, nclasses: int) -> ModelBuilder:
        p: StackedEnsembleParams = self.params
        algo = p.metalearner_algorithm.lower()
        extra = dict(p.metalearner_params)
        extra.setdefault("seed", p.seed)
        if p.metalearner_nfolds:
            extra["nfolds"] = p.metalearner_nfolds
            extra["keep_cross_validation_predictions"] = True
        if self._meta_weights:
            extra["weights_column"] = "__se_weights"
        if algo in ("auto", "glm"):
            from h2o3_tpu.models.glm import GLM

            family = (
                "binomial"
                if classification and nclasses == 2
                else "multinomial"
                if classification
                else "gaussian"
            )
            # H2O AUTO metalearner = non-negative GLM without standardization
            extra.setdefault("non_negative", algo == "auto")
            extra.setdefault("family", family)
            return GLM(**extra)
        if algo == "gbm":
            from h2o3_tpu.models.tree.gbm import GBM

            return GBM(**extra)
        if algo == "drf":
            from h2o3_tpu.models.tree.drf import DRF

            return DRF(**extra)
        if algo == "deeplearning":
            from h2o3_tpu.models.deeplearning import DeepLearning

            return DeepLearning(**extra)
        raise ValueError(f"unknown metalearner_algorithm {p.metalearner_algorithm!r}")

    def _validate(self, train: Frame, valid: Frame | None) -> None:
        """Alignment checks the level-one stacking silently depends on:
        every base model must have been cross-validated on *this* training
        frame (same rows, same response, same fold plan) for its holdout
        predictions to line up row-for-row with ``train``."""
        p: StackedEnsembleParams = self.params
        models = [bm if isinstance(bm, Model) else DKV.get(str(bm)) for bm in p.base_models]
        if not models or not all(isinstance(m, Model) for m in models):
            raise ValueError("stackedensemble requires base_models trained in this session")
        self._resolved_base = models
        ref = models[0]
        if p.response_column and p.response_column != ref.params.response_column:
            raise ValueError(
                f"response_column {p.response_column!r} differs from base models' "
                f"{ref.params.response_column!r}"
            )
        ref_fold = (
            ref.params.nfolds,
            ref.params.fold_assignment,
            getattr(ref.params, "fold_column", None),
        )
        for m in models:
            cv = m.cv_predictions
            if cv is None:
                raise ValueError(
                    f"base model {m.key}: train with nfolds>1 and "
                    "keep_cross_validation_predictions=True"
                )
            if len(cv) != train.nrow:
                raise ValueError(
                    f"base model {m.key}: CV predictions cover {len(cv)} rows but "
                    f"training_frame has {train.nrow} — base models must be "
                    "cross-validated on the same frame"
                )
            if m.params.response_column != ref.params.response_column:
                raise ValueError("base models disagree on response_column")
            fold = (
                m.params.nfolds,
                m.params.fold_assignment,
                getattr(m.params, "fold_column", None),
            )
            if fold != ref_fold:
                raise ValueError(
                    f"base model {m.key}: fold plan {fold} differs from {ref_fold}; "
                    "all base models need identical nfolds/fold_assignment/fold_column"
                )
            if m.params.fold_assignment == "random" and m.params.seed != ref.params.seed:
                raise ValueError(
                    "random fold_assignment requires identical seeds across base models"
                )
