"""ModelSelection — successor of ``hex.modelselection.ModelSelection``
[UNVERIFIED upstream path, SURVEY.md §2.2]: best-subset GLM search with
modes ``allsubsets``, ``maxr``, ``maxrsweep``, ``forward``, ``backward``.

TPU design: for gaussian family the search never refits on device — ONE
fused pass accumulates the full weighted Gram XᵀWX / XᵀWy / yᵀWy over the
row-sharded design matrix (the MXU does the heavy lifting once), then every
candidate subset is evaluated host-side in float64 by a sub-Gram Cholesky
(the ``maxrsweep`` sweep-operator idea: subset RSS falls out of the normal
equations without touching the data again). Non-gaussian families fall back
to per-candidate IRLS fits via the GLM builder.

Outputs mirror the upstream model: per-size best predictor subsets, their
R² (``best_r2_values``), coefficients per size, and (backward mode)
per-step p-value eliminations.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np

from h2o3_tpu.cluster.job import Job
from h2o3_tpu.cluster.registry import DKV
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.datainfo import MEAN_IMPUTATION, DataInfo
from h2o3_tpu.models.model_base import CommonParams, Model, ModelBuilder
from h2o3_tpu.ops.gram import weighted_gram
from h2o3_tpu.utils.log import Log


@dataclass
class ModelSelectionParams(CommonParams):
    mode: str = "maxr"  # allsubsets | maxr | maxrsweep | forward | backward
    family: str = "AUTO"
    max_predictor_number: int = 1
    min_predictor_number: int = 1
    intercept: bool = True
    standardize: bool = True
    p_values_threshold: float = 0.0
    missing_values_handling: str = MEAN_IMPUTATION


class ModelSelectionModel(Model):
    algo = "modelselection"

    def _predict_raw(self, frame: Frame) -> np.ndarray:
        # score with the largest selected subset's model
        if self.output["family"] == "binomial":
            return self.output["final_glm"]._predict_raw(frame)
        di: DataInfo = self.output["datainfo"]
        X, _ = di.transform(frame)  # standardized expansion
        beta = self.output["beta_std_final"]
        return np.asarray(X, np.float64)[: frame.nrow] @ beta

    def _distribution_for_metrics(self) -> str:
        return "gaussian"

    # upstream accessor names
    def get_best_r2_values(self) -> list[float]:
        return list(self.output["best_r2_values"])

    def get_best_model_predictors(self) -> list[list[str]]:
        return [list(s) for s in self.output["best_predictor_subsets"]]

    def coef(self, size: int | None = None) -> dict:
        per = self.output["coef_per_size"]
        if size is None:
            return dict(per[-1])
        sizes = [len(s) for s in self.output["best_predictor_subsets"]]
        try:
            return dict(per[sizes.index(size)])
        except ValueError:
            raise ValueError(
                f"no model of size {size}; available sizes: {sizes}"
            ) from None


def _subset_r2(G, b, yty, sw, ysum, cols, icpt_idx):
    """R² of the gaussian submodel on predictor-group columns ``cols``."""
    idx = list(cols)
    if icpt_idx is not None:
        idx = idx + [icpt_idx]
    Gs = G[np.ix_(idx, idx)]
    bs = b[idx]
    try:
        beta = np.linalg.solve(Gs + 1e-10 * np.eye(len(idx)), bs)
    except np.linalg.LinAlgError:
        beta = np.linalg.lstsq(Gs, bs, rcond=None)[0]
    rss = max(yty - beta @ bs, 0.0)
    tss = max(yty - (ysum * ysum) / max(sw, 1e-30), 1e-30)
    return 1.0 - rss / tss, beta, idx


class ModelSelection(ModelBuilder):
    algo = "modelselection"
    PARAMS_CLS = ModelSelectionParams
    SUPPORTS_CLASSIFICATION = True

    def _build(self, job: Job, train: Frame, valid: Frame | None) -> Model:
        p: ModelSelectionParams = self.params
        yv = train.vec(p.response_column)
        family = p.family.lower()
        if family == "auto":
            family = "binomial" if yv.is_categorical() else "gaussian"
        if family not in ("gaussian", "binomial"):
            raise ValueError("modelselection supports gaussian and binomial")

        di = DataInfo.fit(
            train, self._x,
            standardize=p.standardize,
            use_all_factor_levels=False,
            missing_handling=p.missing_values_handling,
            add_intercept=p.intercept,
        )
        X, valid_mask = di.transform(train)
        w = valid_mask
        if p.weights_column:
            w = w * jnp.nan_to_num(train.vec(p.weights_column).data)
        y_np = yv.to_numpy().astype(np.float64)
        ybuf = np.zeros(train.npad, np.float32)
        ybuf[: train.nrow] = np.nan_to_num(y_np, nan=0.0)
        w = w * jnp.asarray(
            np.pad(~np.isnan(y_np), (0, train.npad - train.nrow)).astype(np.float32)
        )
        y = jnp.asarray(ybuf)

        # predictor-group -> expanded-column mapping (a categorical predictor
        # owns its whole one-hot block; selection is per PREDICTOR, like H2O)
        groups: dict[str, list[int]] = {}
        for c in di.columns:
            groups.setdefault(c.name, []).extend(
                range(c.offset, c.offset + c.width)
            )
        pred_names = [n for n in self._x if n in groups]
        icpt_idx = di.ncols_expanded - 1 if p.intercept else None

        kmax = min(max(p.max_predictor_number, 1), len(pred_names))
        # backward walks DOWN from the full set; max_predictor_number (which
        # defaults to 1 for the growing modes) must not clamp its floor
        if p.mode.lower() == "backward":
            kmin = min(max(p.min_predictor_number, 1), len(pred_names))
        else:
            kmin = min(max(p.min_predictor_number, 1), kmax)

        if family == "gaussian":
            G_d, b_d, sw_d = weighted_gram(X, w, y)
            G = np.asarray(G_d, np.float64)
            b = np.asarray(b_d, np.float64)
            sw = float(np.asarray(sw_d))
            yty = float(np.asarray(jnp.sum(w * y * y)))
            ysum = float(np.asarray(jnp.sum(w * y)))

            def score(subset: tuple[str, ...]):
                cols = [c for n in subset for c in groups[n]]
                r2, beta, idx = _subset_r2(G, b, yty, sw, ysum, cols, icpt_idx)
                return r2, (beta, idx)
        else:

            def score(subset: tuple[str, ...]):
                from h2o3_tpu.models.glm import GLM

                m = GLM(
                    family=family, lambda_=0.0, standardize=p.standardize,
                    intercept=p.intercept,
                    weights_column=p.weights_column,
                ).train(y=p.response_column, x=list(subset), training_frame=train)
                r2 = 1.0 - m.output["residual_deviance"] / max(
                    m.output["null_deviance"], 1e-30
                )
                return r2, m

        mode = p.mode.lower()
        best_subsets: list[tuple[str, ...]] = []
        best_r2: list[float] = []
        best_fit: list = []

        if mode in ("allsubsets", "maxr", "maxrsweep"):
            for k in range(1, kmax + 1):
                if mode == "allsubsets":
                    cands = itertools.combinations(pred_names, k)
                    top = max(
                        ((score(s), s) for s in cands), key=lambda t: t[0][0]
                    )
                    (r2, fit), sub = top
                else:
                    # maxr: grow the best (k-1)-subset by the best addition,
                    # then sequential-replacement sweeps until no swap helps
                    base = list(best_subsets[-1]) if best_subsets else []
                    avail = [n for n in pred_names if n not in base]
                    (r2, fit), add = max(
                        ((score(tuple(base + [a])), a) for a in avail),
                        key=lambda t: t[0][0],
                    )
                    sub = base + [add]
                    improved = True
                    while improved:
                        improved = False
                        for i in range(len(sub)):
                            rest = [n for n in pred_names if n not in sub]
                            for r in rest:
                                trial = sub[:i] + [r] + sub[i + 1 :]
                                (tr2, tfit) = score(tuple(trial))
                                if tr2 > r2 + 1e-12:
                                    r2, fit, sub = tr2, tfit, trial
                                    improved = True
                    sub = tuple(sub)
                best_subsets.append(tuple(sub))
                best_r2.append(float(r2))
                best_fit.append(fit)
                job.update(0.1 + 0.8 * k / kmax)
        elif mode == "forward":
            cur: list[str] = []
            for k in range(1, kmax + 1):
                avail = [n for n in pred_names if n not in cur]
                (r2, fit), add = max(
                    ((score(tuple(cur + [a])), a) for a in avail),
                    key=lambda t: t[0][0],
                )
                cur.append(add)
                best_subsets.append(tuple(cur))
                best_r2.append(float(r2))
                best_fit.append(fit)
                job.update(0.1 + 0.8 * k / kmax)
        elif mode == "backward":
            cur = list(pred_names)
            steps: list[dict] = []
            while len(cur) > kmin:
                # drop the predictor with the worst (highest) p-value
                from h2o3_tpu.models.glm import GLM

                m = GLM(
                    family=family, lambda_=0.0, standardize=p.standardize,
                    intercept=p.intercept, compute_p_values=True,
                    weights_column=p.weights_column,
                ).train(y=p.response_column, x=cur, training_frame=train)
                names = m.output["coef_names"]
                pv = m.output["p_values"]
                zv = np.abs(m.output["z_values"])
                per_pred = {}
                for n in cur:
                    idxs = [
                        i for i, cn in enumerate(names)
                        if cn == n or cn.startswith(n + ".")
                    ]
                    # highest p wins; |z| breaks ties once p underflows
                    per_pred[n] = (
                        min((pv[i] for i in idxs), default=1.0),
                        -max((zv[i] for i in idxs), default=0.0),
                    )
                worst = max(per_pred, key=per_pred.get)
                worst_p = per_pred[worst][0]
                if p.p_values_threshold > 0 and worst_p <= p.p_values_threshold:
                    break
                steps.append(
                    {"removed": worst, "p_value": float(worst_p),
                     "size": len(cur)}
                )
                cur.remove(worst)
                r2, fit = score(tuple(cur))
                best_subsets.append(tuple(cur))
                best_r2.append(float(r2))
                best_fit.append(fit)
                job.update(0.1 + 0.8 * (len(pred_names) - len(cur)) / max(
                    len(pred_names) - kmin, 1
                ))
            best_subsets.reverse()
            best_r2.reverse()
            best_fit.reverse()
        else:
            raise ValueError(f"unknown mode {p.mode!r}")

        # per-size coefficient dicts (original scale)
        coef_names = di.coef_names()
        coef_per_size: list[dict] = []
        beta_std_final = np.zeros(di.ncols_expanded, np.float64)
        final_glm = None
        for fit in best_fit:
            if family == "gaussian":
                beta_std, idx = fit
                beta_full = np.zeros(di.ncols_expanded, np.float64)
                beta_full[idx] = beta_std
                beta_std_final = beta_full
                beta_orig = beta_full.copy()
                if p.standardize:
                    shift = 0.0
                    for c in di.columns:
                        if c.kind == "num":
                            beta_orig[c.offset] = beta_full[c.offset] / c.sigma
                            shift += beta_full[c.offset] * c.mean / c.sigma
                    if p.intercept:
                        beta_orig[-1] = beta_full[-1] - shift
                coef_per_size.append(
                    {coef_names[i]: float(beta_orig[i])
                     for i in range(len(coef_names)) if beta_orig[i] != 0.0
                     or (p.intercept and i == len(coef_names) - 1)}
                )
            else:
                coef_per_size.append(dict(fit.coef))
                final_glm = fit

        out = {
            "beta_std_final": beta_std_final,
            "final_glm": final_glm,
            "datainfo": di,
            "family": family,
            "best_predictor_subsets": best_subsets,
            "best_r2_values": best_r2,
            "coef_per_size": coef_per_size,
            "mode": mode,
            "names": list(self._x),
            "response_domain": tuple(yv.domain) if yv.is_categorical() else None,
        }
        model = ModelSelectionModel(DKV.make_key("modelselection"), p, out)
        model.training_metrics = model._score_metrics(train)
        if valid is not None:
            model.validation_metrics = model._score_metrics(valid)
        return model
