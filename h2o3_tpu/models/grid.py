"""Hyperparameter grid search — successor of ``hex.grid.GridSearch`` /
``hex.grid.HyperSpaceWalker`` [UNVERIFIED upstream paths, SURVEY.md §2.2].

H2O walks a hyperparameter space over any ModelBuilder with either a
Cartesian walker or a seeded RandomDiscrete walker bounded by
``max_models`` / ``max_runtime_secs``, builds the models as (optionally
parallel) sub-jobs, and stores them on a ``Grid`` object sorted by a metric.
The same contract is kept here; model builds are driven sequentially on the
host (the device is the shared resource; H2O's ``parallelism`` option
multiplexed CPU cores, here XLA programs already saturate the chip).
"""

from __future__ import annotations

import itertools
import time
from typing import Any, Sequence, Type

import numpy as np

from h2o3_tpu.cluster.job import Job, JobCancelled
from h2o3_tpu.cluster.registry import DKV
from h2o3_tpu.models.model_base import (
    Model,
    ModelBuilder,
    ScoreKeeper,
    stopping_metric_direction,
)
from h2o3_tpu.utils import faults
from h2o3_tpu.utils import metrics as _mx
from h2o3_tpu.utils.log import Log

_GRID_MODELS = _mx.counter(
    "grid_models_total", "grid-search models finished, by outcome")
_GRID_MODEL_SECONDS = _mx.histogram(
    "grid_model_seconds", "wall time of one grid combo's model build")


class SearchCriteria:
    """``hyper_space_search_criteria`` analog: strategy + budgets."""

    def __init__(
        self,
        strategy: str = "Cartesian",
        max_models: int = 0,
        max_runtime_secs: float = 0.0,
        seed: int = -1,
        stopping_rounds: int = 0,
        stopping_metric: str = "AUTO",
        stopping_tolerance: float = 1e-3,
    ):
        s = strategy.lower()
        assert s in ("cartesian", "randomdiscrete"), strategy
        self.strategy = "Cartesian" if s == "cartesian" else "RandomDiscrete"
        self.max_models = int(max_models)
        self.max_runtime_secs = float(max_runtime_secs)
        self.seed = seed
        self.stopping_rounds = stopping_rounds
        self.stopping_metric = stopping_metric
        self.stopping_tolerance = stopping_tolerance


class Grid:
    """A trained grid: the models plus their hyperparameter assignments."""

    def __init__(self, key: str, builder_cls: Type[ModelBuilder], hyper_names: list[str]):
        self.key = key
        self.builder_cls = builder_cls
        self.hyper_names = hyper_names
        self.models: list[Model] = []
        self.hyper_values: list[dict] = []
        self.failures: list[tuple[dict, str]] = []
        DKV.put(key, self)

    @property
    def model_ids(self) -> list[str]:
        return [m.key for m in self.models]

    def sorted_metric_table(self, metric: str | None = None, decreasing: bool | None = None):
        """Rank (hyper-values, model key, metric) rows — the ``get_grid`` view."""
        if not self.models:
            return []
        m0 = self.models[0]
        name, larger = stopping_metric_direction(
            metric or "AUTO", m0.is_classifier, m0.nclasses
        )
        if decreasing is None:
            decreasing = larger
        rows = []
        for m, hv in zip(self.models, self.hyper_values):
            mm = m.cross_validation_metrics or m.validation_metrics or m.training_metrics
            val = mm.value(name) if mm is not None else float("nan")
            rows.append({**hv, "model_id": m.key, name: val})
        rows.sort(key=lambda r: (np.isnan(r[name]), -r[name] if decreasing else r[name]))
        return rows

    def best_model(self, metric: str | None = None) -> Model | None:
        tab = self.sorted_metric_table(metric)
        return DKV.get(tab[0]["model_id"]) if tab else None


def _space_size(hyper_params: dict[str, Sequence]) -> int:
    total = 1
    for v in hyper_params.values():
        total *= len(v)
    return total


def _walk(hyper_params: dict[str, Sequence], criteria: SearchCriteria):
    names = list(hyper_params)
    combos = [list(hyper_params[n]) for n in names]
    if criteria.strategy == "Cartesian":
        for values in itertools.product(*combos):
            yield dict(zip(names, values))
        return
    # RandomDiscrete: uniform sampling without replacement over the product
    # space, matching H2O's seeded walker (hex.grid.HyperSpaceWalker
    # RandomDiscreteValueWalker [UNVERIFIED]). Lazy rejection sampling keeps
    # memory bounded by the number of *consumed* combos, never the space size
    # (which can be astronomically large); seed<=0 means time-seeded, like
    # H2O's seed=-1 contract.
    sizes = [len(c) for c in combos]
    total = _space_size(hyper_params)
    rng = np.random.default_rng(criteria.seed if criteria.seed and criteria.seed > 0 else None)
    seen: set[tuple] = set()
    while len(seen) < total:
        idx = tuple(int(rng.integers(sz)) for sz in sizes)
        if idx in seen:
            continue
        seen.add(idx)
        yield {n: cand[i] for n, cand, i in zip(names, combos, idx)}


class GridSearch:
    """``H2OGridSearch`` successor.

    >>> gs = GridSearch(GBM, {"max_depth": [3, 5], "learn_rate": [0.1, 0.3]})
    >>> grid = gs.train(x=feats, y="label", training_frame=fr)
    """

    def __init__(
        self,
        builder_cls: Type[ModelBuilder],
        hyper_params: dict[str, Sequence],
        search_criteria: dict | SearchCriteria | None = None,
        grid_id: str | None = None,
        parallelism: int = 1,
        **base_params,
    ):
        if isinstance(search_criteria, dict):
            search_criteria = SearchCriteria(**search_criteria)
        self.criteria = search_criteria or SearchCriteria()
        self.builder_cls = builder_cls
        self.hyper_params = dict(hyper_params)
        self.base_params = base_params
        self.parallelism = max(1, int(parallelism))
        self.grid = Grid(
            grid_id or DKV.make_key("grid"), builder_cls, list(hyper_params)
        )
        self.job: Job | None = None

    def train(self, x=None, y=None, training_frame=None, validation_frame=None, **kw) -> Grid:
        self.job = Job(
            lambda j: self._drive(j, x, y, training_frame, validation_frame, kw),
            f"grid {self.grid.key} over {self.builder_cls.algo}",
        )
        self.job.run_sync()
        return self.grid

    def _drive(self, job: Job, x, y, training_frame, validation_frame, kw) -> Grid:
        if self.parallelism > 1:
            return self._drive_parallel(job, x, y, training_frame, validation_frame, kw)
        c = self.criteria
        t0 = time.time()
        n_planned = _space_size(self.hyper_params)
        if c.max_models:
            n_planned = min(n_planned, c.max_models)
        # checkpoint-dir recovery (hex.grid.GridSearch export_checkpoints_dir
        # [UNVERIFIED]): a manifest alongside the saved models lets a re-run
        # of the same grid_id skip (and reload) already-built combos
        ckdir = self.base_params.get("export_checkpoints_dir")
        done: dict[str, str] = {}
        fingerprint = None
        if ckdir:
            fingerprint = _grid_fingerprint(self.base_params, x, y, training_frame)
            done = _read_manifest(ckdir, self.grid.key, fingerprint)
        # grid-level early stopping on the leaderboard metric sequence,
        # via the same ScoreKeeper the per-model driver uses
        keeper: ScoreKeeper | None = None
        metric_name: str | None = None
        for i, hv in enumerate(_walk(self.hyper_params, c)):
            # max_models bounds models BUILT (failures don't consume budget)
            if c.max_models and len(self.grid.models) >= c.max_models:
                break
            if c.max_runtime_secs and time.time() - t0 > c.max_runtime_secs:
                Log.info(f"grid {self.grid.key}: max_runtime_secs reached after {i} models")
                break
            hv_key = _hv_key(hv)
            if hv_key in done:
                m = _load_checkpointed(ckdir, done[hv_key])
                if m is not None:
                    Log.info(f"grid {self.grid.key}: combo {hv} recovered from checkpoint dir")
                    self.grid.models.append(m)
                    self.grid.hyper_values.append({k: _canon(v) for k, v in hv.items()})
                    # recovered models feed the stopping keeper and progress
                    # exactly as freshly-built ones would
                    if c.stopping_rounds:
                        if keeper is None:
                            metric_name, larger = stopping_metric_direction(
                                c.stopping_metric, m.is_classifier, m.nclasses
                            )
                            keeper = ScoreKeeper(c.stopping_rounds, c.stopping_tolerance, larger)
                        mm = m.cross_validation_metrics or m.validation_metrics or m.training_metrics
                        keeper.record(mm.value(metric_name))
                        if keeper.should_stop():
                            break
                    job.update(min(1.0, (i + 1) / max(1, n_planned)))
                    continue
            try:
                _m_t0 = time.perf_counter()
                with _mx.span("grid.model", combo=_hv_key(hv)):
                    builder = self.builder_cls(**{**self.base_params, **hv})
                    m = builder.train(
                        x=x, y=y, training_frame=training_frame,
                        validation_frame=validation_frame, **kw,
                    )
                _GRID_MODELS.inc(outcome="built")
                _GRID_MODEL_SECONDS.observe(time.perf_counter() - _m_t0)
                self.grid.models.append(m)
                self.grid.hyper_values.append(dict(hv))
                if ckdir:
                    done[hv_key] = m.key
                    _write_manifest(ckdir, self.grid, done, fingerprint)
                faults.abort_check("grid", len(self.grid.models))
                if c.stopping_rounds:
                    if keeper is None:
                        metric_name, larger = stopping_metric_direction(
                            c.stopping_metric, m.is_classifier, m.nclasses
                        )
                        keeper = ScoreKeeper(c.stopping_rounds, c.stopping_tolerance, larger)
                    mm = m.cross_validation_metrics or m.validation_metrics or m.training_metrics
                    keeper.record(mm.value(metric_name))
                    if keeper.should_stop():
                        Log.info(f"grid {self.grid.key}: early stop after {i + 1} models")
                        break
            except faults.TrainAbort:
                raise  # simulated kill -9: the whole grid dies, manifest stays
            except JobCancelled:
                raise  # cancellation/drain is not a combo failure
            except Exception as e:  # a failing combo must not kill the grid (h2o keeps failures)
                _GRID_MODELS.inc(outcome="failed")
                self.grid.failures.append((dict(hv), repr(e)))
                Log.warn(f"grid {self.grid.key}: combo {hv} failed: {e!r}")
            job.update(min(1.0, (i + 1) / max(1, n_planned)))
        return self.grid

    # -- parallel walker (H2O GridSearch `parallelism` > 1) ------------------
    def _drive_parallel(self, job: Job, x, y, training_frame, validation_frame, kw) -> Grid:
        """Build up to ``parallelism`` combos concurrently.

        Threads overlap the host-side parts of different builds (Gram solves,
        pandas transforms, metric math) while XLA serializes their device
        programs — the useful concurrency on a single shared chip, and the
        direct analog of H2O's parallel model builds on one cluster.
        Manifest writes and the stopping keeper are lock-protected; results
        land in completion order (like upstream's parallel walker).
        """
        import threading
        from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait

        c = self.criteria
        t0 = time.time()
        n_planned = _space_size(self.hyper_params)
        if c.max_models:
            n_planned = min(n_planned, c.max_models)
        ckdir = self.base_params.get("export_checkpoints_dir")
        done: dict[str, str] = {}
        fingerprint = None
        if ckdir:
            fingerprint = _grid_fingerprint(self.base_params, x, y, training_frame)
            done = _read_manifest(ckdir, self.grid.key, fingerprint)
        lock = threading.Lock()
        stop_flag = threading.Event()
        keeper_box: list = [None, None]  # keeper, metric_name

        def record_model(m: Model, hv: dict, hv_key: str) -> None:
            with lock:
                self.grid.models.append(m)
                self.grid.hyper_values.append({k: _canon(v) for k, v in hv.items()})
                if ckdir:
                    done[hv_key] = m.key
                    _write_manifest(ckdir, self.grid, done, fingerprint)
                if c.stopping_rounds:
                    if keeper_box[0] is None:
                        name, larger = stopping_metric_direction(
                            c.stopping_metric, m.is_classifier, m.nclasses
                        )
                        keeper_box[0] = ScoreKeeper(
                            c.stopping_rounds, c.stopping_tolerance, larger
                        )
                        keeper_box[1] = name
                    mm = (m.cross_validation_metrics or m.validation_metrics
                          or m.training_metrics)
                    keeper_box[0].record(mm.value(keeper_box[1]))
                    if keeper_box[0].should_stop():
                        stop_flag.set()
                job.update(min(1.0, len(self.grid.models) / max(1, n_planned)))

        abort_box: list[BaseException] = []

        def build_one(hv: dict, hv_key: str) -> None:
            try:
                _m_t0 = time.perf_counter()
                with _mx.span("grid.model", combo=hv_key):
                    builder = self.builder_cls(**{**self.base_params, **hv})
                    m = builder.train(
                        x=x, y=y, training_frame=training_frame,
                        validation_frame=validation_frame, **kw,
                    )
                _GRID_MODELS.inc(outcome="built")
                _GRID_MODEL_SECONDS.observe(time.perf_counter() - _m_t0)
                record_model(m, hv, hv_key)
            except faults.TrainAbort as e:
                # simulated kill -9 from a worker thread: stop feeding the
                # pool and re-raise from the driver once in-flight work drains
                with lock:
                    abort_box.append(e)
                stop_flag.set()
            except Exception as e:
                _GRID_MODELS.inc(outcome="failed")
                with lock:
                    self.grid.failures.append((dict(hv), repr(e)))
                Log.warn(f"grid {self.grid.key}: combo {hv} failed: {e!r}")

        walker = _walk(self.hyper_params, c)
        pending: set = set()
        with ThreadPoolExecutor(max_workers=self.parallelism) as pool:
            exhausted = False
            while not exhausted or pending:
                while not exhausted and len(pending) < self.parallelism:
                    if stop_flag.is_set():
                        exhausted = True
                        break
                    with lock:
                        built = len(self.grid.models)
                    if c.max_models and built >= c.max_models:
                        exhausted = True
                        break
                    if c.max_models and built + len(pending) >= c.max_models:
                        break  # wait for in-flight builds before deciding
                    if c.max_runtime_secs and time.time() - t0 > c.max_runtime_secs:
                        Log.info(f"grid {self.grid.key}: max_runtime_secs reached")
                        exhausted = True
                        break
                    try:
                        hv = next(walker)
                    except StopIteration:
                        exhausted = True
                        break
                    hv_key = _hv_key(hv)
                    if hv_key in done:
                        m = _load_checkpointed(ckdir, done[hv_key])
                        if m is not None:
                            record_model(m, hv, hv_key)
                            continue
                    pending.add(pool.submit(build_one, hv, hv_key))
                if not pending:
                    if exhausted:
                        break
                    continue
                fin, pending = wait(pending, return_when=FIRST_COMPLETED)
                if stop_flag.is_set() and not c.max_models:
                    exhausted = True
        if abort_box:
            raise abort_box[0]
        return self.grid


# ---------------------------------------------------------------------------
# grid checkpointing (export_checkpoints_dir + manifest recovery)


def _canon(v):
    """numpy scalars → python so manifest keys are type-stable across runs."""
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, (np.bool_,)):
        return bool(v)
    if isinstance(v, (list, tuple)):
        return [_canon(x) for x in v]
    return v


def _hv_key(hv: dict) -> str:
    import json

    return json.dumps({k: _canon(v) for k, v in hv.items()}, sort_keys=True)


def _grid_fingerprint(base_params: dict, x, y, training_frame) -> str:
    """Invalidates checkpoint recovery when anything but hyper values changed."""
    import hashlib
    import json

    fr_key = getattr(training_frame, "key", str(training_frame))
    payload = json.dumps(
        {"base": {k: _canon(v) for k, v in sorted(base_params.items())
                  if k != "export_checkpoints_dir"},
         "x": list(x) if x else None, "y": y, "frame": fr_key},
        sort_keys=True, default=str,
    )
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


def _manifest_path(ckdir: str, grid_key: str) -> str:
    import os

    return os.path.join(ckdir, f"{grid_key}.grid.json")


def _read_manifest(ckdir: str, grid_key: str, fingerprint: str | None = None) -> dict[str, str]:
    import json
    import os

    path = _manifest_path(ckdir, grid_key)
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        payload = json.load(f)
    if fingerprint is not None and payload.get("fingerprint") not in (None, fingerprint):
        Log.warn(
            f"grid {grid_key}: checkpoint dir was built with different base "
            "params / data — ignoring it and rebuilding"
        )
        return {}
    return dict(payload.get("built", {}))


def _write_manifest(ckdir: str, grid: Grid, done: dict[str, str], fingerprint: str | None = None) -> None:
    import json

    payload = {
        "grid_id": grid.key,
        "algo": grid.builder_cls.algo,
        "hyper_names": grid.hyper_names,
        "fingerprint": fingerprint,
        "built": done,
        "failures": [[{k: _canon(v) for k, v in hv.items()}, msg] for hv, msg in grid.failures],
    }
    # atomic + retried through the persist layer: a crash mid-write must
    # never leave a torn manifest (it IS the grid's recovery record)
    from h2o3_tpu.persist import write_bytes

    write_bytes(json.dumps(payload).encode(), _manifest_path(ckdir, grid.key))


def _load_checkpointed(ckdir: str, model_key: str):
    import os

    from h2o3_tpu.persist import load_model

    got = DKV.get(model_key)
    if isinstance(got, Model):
        return got
    path = os.path.join(ckdir, model_key)
    if os.path.exists(path):
        return load_model(path)
    return None


def load_grid(ckdir: str, grid_id: str | None = None) -> Grid:
    """Rebuild a Grid from its checkpoint dir (H2O grid recovery)."""
    import glob
    import json
    import os

    if grid_id is None:
        manifests = glob.glob(os.path.join(ckdir, "*.grid.json"))
        if not manifests:
            raise FileNotFoundError(f"no grid manifest under {ckdir}")
        path = manifests[0]
    else:
        path = _manifest_path(ckdir, grid_id)
    with open(path) as f:
        payload = json.load(f)

    import importlib

    algo = payload["algo"]
    reg = {
        b.algo: b
        for b in _all_builders(importlib.import_module("h2o3_tpu.models"))
    }
    grid = Grid(payload["grid_id"], reg[algo], list(payload["hyper_names"]))
    for hv_key, model_key in payload["built"].items():
        m = _load_checkpointed(ckdir, model_key)
        if m is not None:
            grid.models.append(m)
            grid.hyper_values.append(json.loads(hv_key))
    grid.failures = [tuple(f) for f in payload.get("failures", [])]
    return grid


def _all_builders(mod):
    for name in dir(mod):
        obj = getattr(mod, name)
        if isinstance(obj, type) and issubclass(obj, ModelBuilder) and getattr(obj, "algo", None):
            yield obj
