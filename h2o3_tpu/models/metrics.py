"""Model metrics — successor of the ``hex.ModelMetrics*`` hierarchy
(``ModelMetricsRegression/Binomial/Multinomial/Clustering``; AUC machinery in
``hex.AUC2``) [UNVERIFIED upstream paths, SURVEY.md §2.2].

Two computation paths behind the same entry points:

- **host (CPU mesh / numpy inputs)**: exact float64 summaries on the pulled
  prediction column(s) — exact rank-statistic AUC, 400-point threshold table.
- **device (accelerator + jax-array inputs)**: device→host bandwidth over a
  tunneled TPU is ~10 MB/s, so pulling a 1M-row prediction column costs
  seconds. Instead the O(n) sufficient statistics are reduced ON DEVICE
  (weighted sums + a 1024-bucket score histogram — exactly H2O ``AUC2``'s
  400-bin design, finer) and only KBs come down; the criterion surface is
  assembled from buckets on host.
"""

from __future__ import annotations

import numpy as np

_EPS = 1e-15
_NBUCKETS = 1024


def _on_device(*arrays) -> bool:
    """True when we should take the device-stats path: an accelerator
    backend and at least one jax array among the inputs."""
    try:
        import jax

        if jax.default_backend() == "cpu":
            return False
        return any(isinstance(a, jax.Array) for a in arrays)
    except Exception:
        return False


class ModelMetrics:
    def __init__(self, kind: str, values: dict, domain=None):
        self.kind = kind
        self._v = dict(values)
        self.domain = domain

    def __getattr__(self, item):
        v = self.__dict__.get("_v", {})
        if item in v:
            return v[item]
        raise AttributeError(item)

    def gains_lift(self):
        """Gains/lift table rows (binomial metrics only; else None)."""
        return self._v.get("gains_lift_table")

    def kolmogorov_smirnov(self) -> float:
        return self.value("ks")

    def value(self, name: str) -> float:
        """Look up a scalar criterion by name (nan if absent) — the lookup
        used by grid ranking / early stopping / leaderboards."""
        v = self._v.get(name)
        if v is None and name == "mean_residual_deviance":
            v = self._v.get("mse")
        try:
            return float(v)
        except (TypeError, ValueError):
            return float("nan")

    def to_dict(self) -> dict:
        out = {"kind": self.kind}
        for k, v in self._v.items():
            out[k] = v.tolist() if isinstance(v, np.ndarray) else v
        return out

    def __repr__(self):
        keys = [
            k
            for k in (
                "rmse",
                "mae",
                "r2",
                "mean_residual_deviance",
                "auc",
                "pr_auc",
                "logloss",
                "mean_per_class_error",
                "gini",
            )
            if k in self._v
        ]
        body = ", ".join(f"{k}={self._v[k]:.6g}" for k in keys)
        return f"<ModelMetrics{self.kind.capitalize()} {body}>"


# --------------------------------------------------------------------------
# regression


def regression_metrics(
    actual: np.ndarray,
    pred: np.ndarray,
    weights: np.ndarray | None = None,
    distribution: str = "gaussian",
) -> ModelMetrics:
    if _on_device(actual, pred):
        return _regression_metrics_device(actual, pred, weights, distribution)
    a = np.asarray(actual, np.float64)
    p = np.asarray(pred, np.float64)
    w = np.ones_like(a) if weights is None else np.asarray(weights, np.float64)
    ok = ~np.isnan(a) & ~np.isnan(p) & (w > 0)
    a, p, w = a[ok], p[ok], w[ok]
    sw = w.sum()
    err = a - p
    mse = float((w * err**2).sum() / sw)
    mae = float((w * np.abs(err)).sum() / sw)
    mean_a = (w * a).sum() / sw
    ss_tot = float((w * (a - mean_a) ** 2).sum() / sw)
    rmsle = float("nan")
    if (a > -1).all() and (p > -1).all():
        rmsle = float(
            np.sqrt((w * (np.log1p(a) - np.log1p(p)) ** 2).sum() / sw)
        )
    dev = _mean_deviance(a, p, w, distribution)
    return ModelMetrics(
        "regression",
        {
            "mse": mse,
            "rmse": float(np.sqrt(mse)),
            "mae": mae,
            "rmsle": rmsle,
            "r2": float(1.0 - mse / ss_tot) if ss_tot > 0 else float("nan"),
            "mean_residual_deviance": dev,
            "nobs": int(ok.sum()),
        },
    )


def _mean_deviance(a, p, w, distribution: str) -> float:
    sw = w.sum()
    if distribution == "poisson":
        p = np.maximum(p, _EPS)
        with np.errstate(divide="ignore", invalid="ignore"):
            t = np.where(a > 0, a * np.log(a / p), 0.0)
        return float((2 * w * (t - (a - p))).sum() / sw)
    if distribution == "gamma":
        p = np.maximum(p, _EPS)
        a_ = np.maximum(a, _EPS)
        return float((2 * w * (-np.log(a_ / p) + (a_ - p) / p)).sum() / sw)
    if distribution == "laplace":
        return float((w * np.abs(a - p)).sum() / sw)
    return float((w * (a - p) ** 2).sum() / sw)  # gaussian & default


# --------------------------------------------------------------------------
# binomial


def binomial_metrics(
    actual: np.ndarray,
    prob: np.ndarray,
    weights: np.ndarray | None = None,
    domain: tuple[str, str] = ("0", "1"),
) -> ModelMetrics:
    """``actual`` is {0,1} int; ``prob`` is P(class 1)."""
    if _on_device(actual, prob):
        return _binomial_metrics_device(actual, prob, weights, domain)
    y = np.asarray(actual, np.float64)
    p = np.clip(np.asarray(prob, np.float64), _EPS, 1 - _EPS)
    w = np.ones_like(y) if weights is None else np.asarray(weights, np.float64)
    ok = ~np.isnan(y) & ~np.isnan(p) & (w > 0)
    y, p, w = y[ok], p[ok], w[ok]
    sw = w.sum()

    logloss = float(-(w * (y * np.log(p) + (1 - y) * np.log(1 - p))).sum() / sw)
    mse = float((w * (y - p) ** 2).sum() / sw)
    auc = _weighted_auc(y, p, w)
    pr_auc = _pr_auc(y, p, w)

    # threshold table (the AUC2 criterion surface)
    thresholds = np.unique(np.quantile(p, np.linspace(0, 1, 400)))
    table = _threshold_table(y, p, w, thresholds)
    f1 = table["f1"]
    best = int(np.nanargmax(f1)) if not np.all(np.isnan(f1)) else 0
    best_thr = float(thresholds[best])
    cm = _confusion(y, p, w, best_thr)

    mx = {}
    for name in ("f1", "f2", "f0point5", "accuracy", "precision", "recall",
                 "specificity", "mcc", "min_per_class_accuracy",
                 "mean_per_class_accuracy"):
        vals = table[name]
        if np.all(np.isnan(vals)):  # degenerate (e.g. constant predictions)
            mx[f"max_{name}"] = {"threshold": 0.5, "value": float("nan")}
        else:
            mx[f"max_{name}"] = {
                "threshold": float(thresholds[int(np.nanargmax(vals))]),
                "value": float(np.nanmax(vals)),
            }

    order = np.argsort(-p, kind="mergesort")
    ps = p[order]
    # collapse tied scores to one mass each: KS/gains are defined over
    # realizable thresholds — per-row cumulatives through a tie group would
    # make both depend on arbitrary input row order (a constant predictor
    # must give KS 0, not 1)
    first = np.concatenate([[0], np.nonzero(np.diff(ps))[0] + 1])
    gl_rows, ks = _gains_lift(
        np.add.reduceat((w * y)[order], first),
        np.add.reduceat((w * (1 - y))[order], first),
    )

    return ModelMetrics(
        "binomial",
        {
            "auc": auc,
            "pr_auc": pr_auc,
            "gini": 2 * auc - 1,
            "logloss": logloss,
            "mse": mse,
            "rmse": float(np.sqrt(mse)),
            "mean_per_class_error": float(
                1.0 - mx["max_mean_per_class_accuracy"]["value"]
            ),
            "default_threshold": best_thr,
            "confusion_matrix": cm,
            "max_criteria": mx,
            "nobs": int(ok.sum()),
            "gains_lift_table": gl_rows,
            "ks": ks,
        },
        domain=domain,
    )


def _gains_lift(wpos_desc, wneg_desc, groups: int = 16):
    """Gains/lift table + Kolmogorov-Smirnov from positive/negative weight
    mass ordered by DESCENDING score (per row on host, per score bucket on
    device) — the ModelMetricsBinomial GainsLift analog [UNVERIFIED
    upstream hex/GainsLift.java]. Returns (rows, ks)."""
    wpos = np.asarray(wpos_desc, np.float64)
    wneg = np.asarray(wneg_desc, np.float64)
    w = wpos + wneg
    cum_w = np.cumsum(w)
    cum_pos = np.cumsum(wpos)
    cum_neg = np.cumsum(wneg)
    tot, tot_pos, tot_neg = cum_w[-1], cum_pos[-1], cum_neg[-1]
    if tot <= 0 or tot_pos <= 0 or tot_neg <= 0:
        return [], float("nan")
    ks = float(np.max(np.abs(cum_pos / tot_pos - cum_neg / tot_neg)))
    overall = tot_pos / tot
    rows = []
    prev_i = -1
    prev_pos = prev_w = 0.0
    for g in range(1, groups + 1):
        i = int(np.searchsorted(cum_w, tot * g / groups - 1e-12))
        i = min(i, len(w) - 1)
        if i <= prev_i:
            continue  # degenerate tiny group (ties/few rows): merge forward
        grp_w = cum_w[i] - prev_w
        grp_pos = cum_pos[i] - prev_pos
        rows.append({
            "group": len(rows) + 1,
            "cumulative_data_fraction": float(cum_w[i] / tot),
            "lower_threshold_index": int(i),
            "response_rate": float(grp_pos / grp_w) if grp_w > 0 else float("nan"),
            "lift": float((grp_pos / grp_w) / overall) if grp_w > 0 else float("nan"),
            "cumulative_response_rate": float(cum_pos[i] / cum_w[i]),
            "cumulative_lift": float((cum_pos[i] / cum_w[i]) / overall),
            "capture_rate": float(grp_pos / tot_pos),
            "cumulative_capture_rate": float(cum_pos[i] / tot_pos),
            "gain": float(100.0 * ((grp_pos / grp_w) / overall - 1.0)) if grp_w > 0 else float("nan"),
            "cumulative_gain": float(100.0 * ((cum_pos[i] / cum_w[i]) / overall - 1.0)),
        })
        prev_i, prev_pos, prev_w = i, cum_pos[i], cum_w[i]
    return rows, ks


def _weighted_auc(y, p, w) -> float:
    order = np.argsort(p, kind="mergesort")
    y, p, w = y[order], p[order], w[order]
    wpos = w * (y == 1)
    wneg = w * (y == 0)
    tot_pos, tot_neg = wpos.sum(), wneg.sum()
    if tot_pos == 0 or tot_neg == 0:
        return float("nan")
    # rank-sum with tie handling: group equal scores
    cum_neg = np.cumsum(wneg)
    # for ties, positives at a tied score see half the tied negatives
    _, idx, inv = np.unique(p, return_index=True, return_inverse=True)
    grp_neg = np.add.reduceat(wneg, idx)
    below = np.concatenate([[0.0], np.cumsum(grp_neg)[:-1]])
    frac = below[inv] + 0.5 * grp_neg[inv]
    return float((wpos * frac).sum() / (tot_pos * tot_neg))


def _pr_auc(y, p, w) -> float:
    order = np.argsort(-p, kind="mergesort")
    y, w = y[order], w[order]
    tp = np.cumsum(w * (y == 1))
    fp = np.cumsum(w * (y == 0))
    tot_pos = tp[-1]
    if tot_pos == 0:
        return float("nan")
    precision = tp / np.maximum(tp + fp, _EPS)
    recall = tp / tot_pos
    return float(np.trapezoid(precision, recall))


def _threshold_table(y, p, w, thresholds):
    pred = p[None, :] >= thresholds[:, None]  # (T, n)
    wpos = (w * (y == 1))[None, :]
    wneg = (w * (y == 0))[None, :]
    tp = (pred * wpos).sum(1)
    fp = (pred * wneg).sum(1)
    fn = wpos.sum() - tp
    tn = wneg.sum() - fp
    with np.errstate(divide="ignore", invalid="ignore"):
        precision = tp / (tp + fp)
        recall = tp / (tp + fn)
        specificity = tn / (tn + fp)
        accuracy = (tp + tn) / (tp + fp + fn + tn)
        f1 = 2 * precision * recall / (precision + recall)
        f2 = 5 * precision * recall / (4 * precision + recall)
        f05 = 1.25 * precision * recall / (0.25 * precision + recall)
        mcc = (tp * tn - fp * fn) / np.sqrt((tp + fp) * (tp + fn) * (tn + fp) * (tn + fn))
        min_pca = np.minimum(recall, specificity)
        mean_pca = 0.5 * (recall + specificity)
    return {
        "f1": f1,
        "f2": f2,
        "f0point5": f05,
        "accuracy": accuracy,
        "precision": precision,
        "recall": recall,
        "specificity": specificity,
        "mcc": np.abs(mcc),
        "min_per_class_accuracy": min_pca,
        "mean_per_class_accuracy": mean_pca,
    }


def _confusion(y, p, w, thr) -> list[list[float]]:
    pred = (p >= thr).astype(np.float64)
    tp = float((w * ((y == 1) & (pred == 1))).sum())
    fp = float((w * ((y == 0) & (pred == 1))).sum())
    fn = float((w * ((y == 1) & (pred == 0))).sum())
    tn = float((w * ((y == 0) & (pred == 0))).sum())
    return [[tn, fp], [fn, tp]]


# --------------------------------------------------------------------------
# multinomial


def multinomial_metrics(
    actual: np.ndarray,
    probs: np.ndarray,
    weights: np.ndarray | None = None,
    domain: tuple[str, ...] = (),
) -> ModelMetrics:
    """``actual`` int class ids; ``probs`` (n, K)."""
    if _on_device(actual, probs):
        return _multinomial_metrics_device(actual, probs, weights, domain)
    y = np.asarray(actual)
    P = np.clip(np.asarray(probs, np.float64), _EPS, 1.0)
    w = np.ones(len(y), np.float64) if weights is None else np.asarray(weights, np.float64)
    ok = (y >= 0) & (w > 0) & ~np.isnan(P).any(axis=1)
    y, P, w = y[ok], P[ok], w[ok]
    sw = w.sum()
    K = P.shape[1]

    logloss = float(-(w * np.log(P[np.arange(len(y)), y])).sum() / sw)
    pred = P.argmax(axis=1)
    err = float((w * (pred != y)).sum() / sw)

    cm = np.zeros((K, K))
    np.add.at(cm, (y, pred), w)
    with np.errstate(divide="ignore", invalid="ignore"):
        per_class_err = 1.0 - np.diag(cm) / cm.sum(axis=1)
    mean_pce = float(np.nanmean(per_class_err))

    # top-k hit ratios (h2o reports up to 10)
    order = np.argsort(-P, axis=1)
    ranks = np.argmax(order == y[:, None], axis=1)
    topk = [float((w * (ranks <= k)).sum() / sw) for k in range(min(10, K))]

    onehot = np.zeros_like(P)
    onehot[np.arange(len(y)), y] = 1.0
    mse = float((w[:, None] * (onehot - P) ** 2).sum() / (sw))

    return ModelMetrics(
        "multinomial",
        {
            "logloss": logloss,
            "classification_error": err,
            "mean_per_class_error": mean_pce,
            "per_class_error": per_class_err,
            "confusion_matrix": cm,
            "hit_ratios": topk,
            "mse": mse,
            "rmse": float(np.sqrt(mse)),
            "nobs": int(ok.sum()),
        },
        domain=domain,
    )


# --------------------------------------------------------------------------
# device-stats path (accelerator backends; see module docstring)


def _to_dev(x, dtype=None):
    import jax.numpy as jnp

    return jnp.asarray(np.asarray(x) if not hasattr(x, "devices") else x, dtype)


def _bucket_hist(b, stats):
    """(n,) int32 buckets + (n, S) stats → (NBUCKETS, S) via chunked one-hot
    matmuls (scatter-add is pathological on TPU; this is MXU work)."""
    import jax
    import jax.numpy as jnp

    n, S = stats.shape
    chunk = 8192
    nchunks = -(-n // chunk)
    pad = nchunks * chunk - n
    if pad:
        b = jnp.pad(b, (0, pad))
        stats = jnp.pad(stats, ((0, pad), (0, 0)))
    b_c = b.reshape(nchunks, chunk)
    s_c = stats.reshape(nchunks, chunk, S)
    iota = jnp.arange(_NBUCKETS, dtype=jnp.int32)

    def body(acc, xs):
        bb, ss = xs
        oh = (bb[:, None] == iota[None, :]).astype(jnp.float32)
        return acc + jax.lax.dot_general(
            ss, oh, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        ), None

    acc0 = jnp.zeros((S, _NBUCKETS), jnp.float32)
    acc, _ = jax.lax.scan(body, acc0, (b_c, s_c))
    return acc.T  # (NBUCKETS, S)


def _binom_device_stats():
    import jax
    import jax.numpy as jnp

    @jax.jit
    def stats(y, p, w):
        ok = (~jnp.isnan(y)) & (~jnp.isnan(p)) & (w > 0)
        wok = jnp.where(ok, w, 0.0).astype(jnp.float32)
        # zero masked values BEFORE arithmetic: 0 * NaN = NaN would poison
        # the weighted sums the ok-mask is meant to exclude
        y = jnp.where(ok, y, 0.0)
        p = jnp.where(ok, p, 0.5)
        pc = jnp.clip(p, _EPS, 1 - _EPS)
        ypos = y == 1
        logloss_sum = -(wok * jnp.where(ypos, jnp.log(pc), jnp.log1p(-pc))).sum()
        mse_sum = (wok * (y - pc) ** 2).sum()
        sw = wok.sum()
        nobs = ok.sum()
        b = jnp.clip((pc * _NBUCKETS).astype(jnp.int32), 0, _NBUCKETS - 1)
        table = _bucket_hist(
            b, jnp.stack([wok * ypos, wok * (~ypos)], axis=1)
        )  # (B, 2): wpos, wneg
        # ONE packed output array = ONE device→host transfer (a 5-leaf tuple
        # costs 5 sequential ~66 ms round-trips on the tunneled TPU). nobs is
        # bitcast, not value-cast: int32 counts past 2^24 don't fit f32.
        nobs_bits = jax.lax.bitcast_convert_type(nobs.astype(jnp.int32), jnp.float32)
        head = jnp.stack([logloss_sum, mse_sum, sw, nobs_bits])
        return jnp.concatenate([head, table.reshape(-1)])

    return stats


_BINOM_STATS = None


def _binomial_metrics_device(actual, prob, weights, domain) -> ModelMetrics:
    global _BINOM_STATS
    if _BINOM_STATS is None:
        _BINOM_STATS = _binom_device_stats()
    import jax.numpy as jnp

    y = _to_dev(actual, jnp.float32)
    p = _to_dev(prob, jnp.float32)
    w = jnp.ones_like(p) if weights is None else _to_dev(weights, jnp.float32)
    packed32 = np.asarray(_BINOM_STATS(y, p, w))  # float32; [3] is int32 bits
    ll_s, mse_s, sw_ = packed32[:3].astype(np.float64)
    nobs_ = int(packed32[3:4].view(np.int32)[0])
    table = packed32[4:].astype(np.float64).reshape(_NBUCKETS, 2)
    sw = float(sw_)
    logloss = float(ll_s) / sw
    mse = float(mse_s) / sw
    wpos_b, wneg_b = table[:, 0], table[:, 1]
    tot_pos, tot_neg = wpos_b.sum(), wneg_b.sum()

    # AUC with the bucket-as-tie-group rank statistic (H2O AUC2 semantics)
    below_neg = np.concatenate([[0.0], np.cumsum(wneg_b)[:-1]])
    auc = (
        float((wpos_b * (below_neg + 0.5 * wneg_b)).sum() / (tot_pos * tot_neg))
        if tot_pos > 0 and tot_neg > 0
        else float("nan")
    )

    # threshold surface from bucket cumulatives: thr_b = b / NBUCKETS,
    # predicted-positive = buckets >= b
    tp = np.cumsum(wpos_b[::-1])[::-1]
    fp = np.cumsum(wneg_b[::-1])[::-1]
    fn = tot_pos - tp
    tn = tot_neg - fp
    thresholds = np.arange(_NBUCKETS) / _NBUCKETS
    with np.errstate(divide="ignore", invalid="ignore"):
        precision = tp / (tp + fp)
        recall = tp / np.maximum(tot_pos, _EPS)
        specificity = tn / np.maximum(tot_neg, _EPS)
        accuracy = (tp + tn) / sw
        f1 = 2 * precision * recall / (precision + recall)
        f2 = 5 * precision * recall / (4 * precision + recall)
        f05 = 1.25 * precision * recall / (0.25 * precision + recall)
        mcc = (tp * tn - fp * fn) / np.sqrt(
            (tp + fp) * (tp + fn) * (tn + fp) * (tn + fn)
        )
        min_pca = np.minimum(recall, specificity)
        mean_pca = 0.5 * (recall + specificity)
    tbl = {
        "f1": f1, "f2": f2, "f0point5": f05, "accuracy": accuracy,
        "precision": precision, "recall": recall, "specificity": specificity,
        "mcc": np.abs(mcc), "min_per_class_accuracy": min_pca,
        "mean_per_class_accuracy": mean_pca,
    }
    # PR-AUC over descending-threshold sweep
    order = np.argsort(-thresholds, kind="mergesort")
    pr = precision[order]
    rc = recall[order]
    okm = ~np.isnan(pr)
    pr_auc = float(np.trapezoid(pr[okm], rc[okm])) if okm.any() else float("nan")

    mx = {}
    for name, vals in tbl.items():
        if np.all(np.isnan(vals)):
            mx[f"max_{name}"] = {"threshold": 0.5, "value": float("nan")}
        else:
            i = int(np.nanargmax(vals))
            mx[f"max_{name}"] = {
                "threshold": float(thresholds[i]),
                "value": float(vals[i]),
            }
    bi = (
        int(np.nanargmax(tbl["f1"])) if not np.all(np.isnan(tbl["f1"])) else 0
    )
    best_thr = float(thresholds[bi])
    cm = [[float(tn[bi]), float(fp[bi])], [float(fn[bi]), float(tp[bi])]]
    gl_rows, ks = _gains_lift(wpos_b[::-1], wneg_b[::-1])

    return ModelMetrics(
        "binomial",
        {
            "auc": auc,
            "pr_auc": pr_auc,
            "gini": 2 * auc - 1,
            "logloss": logloss,
            "mse": mse,
            "rmse": float(np.sqrt(mse)),
            "mean_per_class_error": float(
                1.0 - mx["max_mean_per_class_accuracy"]["value"]
            ),
            "default_threshold": best_thr,
            "confusion_matrix": cm,
            "max_criteria": mx,
            "nobs": int(nobs_),
            "gains_lift_table": gl_rows,
            "ks": ks,
        },
        domain=domain,
    )


_REG_STATS = None


def _regression_metrics_device(actual, pred, weights, distribution) -> ModelMetrics:
    global _REG_STATS
    import jax
    import jax.numpy as jnp

    if _REG_STATS is None:

        @jax.jit
        def stats(a, p, w):
            ok = (~jnp.isnan(a)) & (~jnp.isnan(p)) & (w > 0)
            wok = jnp.where(ok, w, 0.0).astype(jnp.float32)
            a0 = jnp.where(ok, a, 0.0)
            p0 = jnp.where(ok, p, 0.0)
            sw = wok.sum()
            err = a0 - p0
            mse_s = (wok * err**2).sum()
            mae_s = (wok * jnp.abs(err)).sum()
            sa = (wok * a0).sum()
            # CENTERED second moment: E[a²]−E[a]² catastrophically cancels in
            # f32 for large-mean targets (measured r2 0.9999 vs true 0.75);
            # a second pass against the mean costs one more O(n) reduction
            mean_a = sa / jnp.maximum(sw, 1e-30)
            saa = (wok * (a0 - mean_a) ** 2).sum()
            loggable = jnp.all(jnp.where(ok, (a0 > -1) & (p0 > -1), True))
            le = jnp.log1p(jnp.maximum(a0, -1 + 1e-12)) - jnp.log1p(
                jnp.maximum(p0, -1 + 1e-12)
            )
            rmsle_s = (wok * le * le).sum()
            # deviances
            pe = jnp.maximum(p0, _EPS)
            ae = jnp.maximum(a0, _EPS)
            pois = (
                2
                * wok
                * (jnp.where(a0 > 0, a0 * jnp.log(ae / pe), 0.0) - (a0 - p0))
            ).sum()
            gam = (2 * wok * (-jnp.log(ae / pe) + (ae - pe) / pe)).sum()
            return sw, mse_s, mae_s, sa, saa, loggable, rmsle_s, pois, gam, ok.sum()

        _REG_STATS = stats

    a = _to_dev(actual, jnp.float32)
    p = _to_dev(pred, jnp.float32)
    w = jnp.ones_like(a) if weights is None else _to_dev(weights, jnp.float32)
    sw, mse_s, mae_s, sa, saa, loggable, rmsle_s, pois, gam, nobs = (
        np.asarray(v, np.float64) for v in _REG_STATS(a, p, w)
    )
    sw = float(sw)
    mse = float(mse_s) / sw
    mae = float(mae_s) / sw
    ss_tot = float(saa) / sw  # already centered on device
    rmsle = float(np.sqrt(float(rmsle_s) / sw)) if bool(loggable) else float("nan")
    if distribution == "poisson":
        dev = float(pois) / sw
    elif distribution == "gamma":
        dev = float(gam) / sw
    elif distribution == "laplace":
        dev = mae
    else:
        dev = mse
    return ModelMetrics(
        "regression",
        {
            "mse": mse,
            "rmse": float(np.sqrt(mse)),
            "mae": mae,
            "rmsle": rmsle,
            "r2": float(1.0 - mse / ss_tot) if ss_tot > 0 else float("nan"),
            "mean_residual_deviance": dev,
            "nobs": int(nobs),
        },
    )


_MULTI_STATS = {}


def _multinomial_metrics_device(actual, probs, weights, domain) -> ModelMetrics:
    import jax
    import jax.numpy as jnp

    P = _to_dev(probs, jnp.float32)
    K = int(P.shape[1])
    if K not in _MULTI_STATS:

        @jax.jit
        def stats(y, P, w):
            ok = (y >= 0) & (w > 0) & (~jnp.isnan(P).any(axis=1))
            wok = jnp.where(ok, w, 0.0).astype(jnp.float32)
            ysafe = jnp.clip(y, 0, K - 1).astype(jnp.int32)
            # zero masked rows before arithmetic (0 * NaN = NaN)
            P = jnp.where(ok[:, None], P, 1.0 / K)
            Pc = jnp.clip(P, _EPS, 1.0)
            p_true = jnp.take_along_axis(Pc, ysafe[:, None], axis=1)[:, 0]
            ll_s = -(wok * jnp.log(p_true)).sum()
            pred = jnp.argmax(Pc, axis=1)
            err_s = (wok * (pred != ysafe)).sum()
            oh_y = (ysafe[:, None] == jnp.arange(K)[None, :]).astype(jnp.float32)
            oh_p = (pred[:, None] == jnp.arange(K)[None, :]).astype(jnp.float32)
            cm = jax.lax.dot_general(
                oh_y * wok[:, None], oh_p, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            # rank of the true class (count of strictly-greater probs)
            rank = (Pc > p_true[:, None]).sum(axis=1)
            rank_hist = _bucket_hist(
                jnp.clip(rank, 0, _NBUCKETS - 1).astype(jnp.int32), wok[:, None]
            )[:, 0]
            mse_s = (wok[:, None] * (oh_y - Pc) ** 2).sum()
            return ll_s, err_s, cm, rank_hist, mse_s, wok.sum(), ok.sum()

        _MULTI_STATS[K] = stats

    y = _to_dev(actual, jnp.int32)
    w = (
        jnp.ones(P.shape[0], jnp.float32)
        if weights is None
        else _to_dev(weights, jnp.float32)
    )
    ll_s, err_s, cm, rank_hist, mse_s, sw_, nobs = (
        np.asarray(v, np.float64) for v in _MULTI_STATS[K](y, P, w)
    )
    sw = float(sw_)
    with np.errstate(divide="ignore", invalid="ignore"):
        per_class_err = 1.0 - np.diag(cm) / cm.sum(axis=1)
    topk = list(np.cumsum(rank_hist[: min(10, K)]) / sw)
    mse = float(mse_s) / sw
    return ModelMetrics(
        "multinomial",
        {
            "logloss": float(ll_s) / sw,
            "classification_error": float(err_s) / sw,
            "mean_per_class_error": float(np.nanmean(per_class_err)),
            "per_class_error": per_class_err,
            "confusion_matrix": cm,
            "hit_ratios": [float(t) for t in topk],
            "mse": mse,
            "rmse": float(np.sqrt(mse)),
            "nobs": int(nobs),
        },
        domain=domain,
    )


def make_metrics(predicted, actuals, weights=None, domain=None,
                 distribution: str = "gaussian") -> ModelMetrics:
    """``h2o.make_metrics`` successor [UNVERIFIED upstream
    water/api/ModelMetricsMaker]: ModelMetrics straight from prediction and
    actual vectors, no model required.

    ``predicted``: Vec/array of predictions — P(positive) for binomial,
    (n, K) class probabilities (Frame or array) for multinomial, plain
    numbers for regression. ``actuals``: numeric Vec/array, or a
    categorical Vec / string array for classification. ``domain`` forces
    classification with those labels; otherwise a categorical actuals
    column decides.
    """
    from h2o3_tpu.frame.frame import Frame, Vec

    def _vec_np(x):
        if isinstance(x, Frame):
            assert x.ncol == 1, "expected a single-column frame"
            x = x.vec(0)
        if isinstance(x, Vec):
            if x.is_categorical():
                # hand labels (not raw codes) downstream so a caller-supplied
                # domain in a different level order still maps correctly
                codes = x.to_numpy().astype(np.int64)
                lv = np.asarray(list(x.domain) + [None], dtype=object)
                return lv[np.where(codes < 0, len(lv) - 1, codes)], tuple(x.domain)
            return x.to_numpy(), None
        return np.asarray(x), None

    def _to_codes(y, dom):
        """labels/codes -> int codes in ``dom`` order; unknown/NA -> -1."""
        arr = np.asarray(y)
        if np.issubdtype(arr.dtype, np.number):
            out = np.asarray(arr, np.float64)
            out = np.where(np.isnan(out), -1, out)
            return out.astype(np.int64)
        lut = {str(d): i for i, d in enumerate(dom)}
        return np.array([-1 if v is None else lut.get(str(v), -1) for v in arr],
                        np.int64)

    w = None
    if weights is not None:
        w, _ = _vec_np(weights)

    # multinomial: predicted is (n, K) probabilities — Frame or 2-D array
    P = None
    if isinstance(predicted, Frame) and predicted.ncol > 1:
        P = np.stack([predicted.vec(i).to_numpy() for i in range(predicted.ncol)], axis=1)
    elif not isinstance(predicted, (Frame, Vec)):
        arr = np.asarray(predicted)
        if arr.ndim == 2 and arr.shape[1] > 1:
            P = arr
    if P is not None:
        y, adom = _vec_np(actuals)
        dom = tuple(domain) if domain else (adom or tuple(map(str, range(P.shape[1]))))
        if len(dom) != P.shape[1]:
            raise ValueError(
                f"predicted has {P.shape[1]} probability columns but the "
                f"domain has {len(dom)} labels")
        return multinomial_metrics(_to_codes(y, dom), P, w, dom)

    p, _ = _vec_np(predicted)
    y, adom = _vec_np(actuals)
    dom = tuple(domain) if domain else adom
    if dom and len(dom) == 2:
        yc = _to_codes(y, dom).astype(np.float64)
        # binomial_metrics filters only NaN; NA/unknown labels (-1) must not
        # enter the logloss/AUC sums as y=-1
        yc = np.where(yc < 0, np.nan, yc)
        return binomial_metrics(yc, np.asarray(p, np.float64), w, dom)
    if dom and len(dom) > 2:
        raise ValueError("multinomial make_metrics needs a (n, K) predicted frame")
    return regression_metrics(y, p, w, distribution)
