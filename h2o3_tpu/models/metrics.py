"""Model metrics — successor of the ``hex.ModelMetrics*`` hierarchy
(``ModelMetricsRegression/Binomial/Multinomial/Clustering``; AUC machinery in
``hex.AUC2``) [UNVERIFIED upstream paths, SURVEY.md §2.2].

Scoring passes run on device; the metric *summaries* here are computed
host-side in float64 on the pulled-down prediction column(s) — exactness
matters more than FLOPs for a one-shot O(n) summary, and it keeps AUC
bit-stable for the MOJO-parity regression net (SURVEY.md §4).

H2O's AUC2 builds 400 threshold bins; we compute the exact rank-statistic AUC
and a 400-point threshold table for the max-F1/confusion surface.
"""

from __future__ import annotations

import numpy as np

_EPS = 1e-15


class ModelMetrics:
    def __init__(self, kind: str, values: dict, domain=None):
        self.kind = kind
        self._v = dict(values)
        self.domain = domain

    def __getattr__(self, item):
        v = self.__dict__.get("_v", {})
        if item in v:
            return v[item]
        raise AttributeError(item)

    def value(self, name: str) -> float:
        """Look up a scalar criterion by name (nan if absent) — the lookup
        used by grid ranking / early stopping / leaderboards."""
        v = self._v.get(name)
        if v is None and name == "mean_residual_deviance":
            v = self._v.get("mse")
        try:
            return float(v)
        except (TypeError, ValueError):
            return float("nan")

    def to_dict(self) -> dict:
        out = {"kind": self.kind}
        for k, v in self._v.items():
            out[k] = v.tolist() if isinstance(v, np.ndarray) else v
        return out

    def __repr__(self):
        keys = [
            k
            for k in (
                "rmse",
                "mae",
                "r2",
                "mean_residual_deviance",
                "auc",
                "pr_auc",
                "logloss",
                "mean_per_class_error",
                "gini",
            )
            if k in self._v
        ]
        body = ", ".join(f"{k}={self._v[k]:.6g}" for k in keys)
        return f"<ModelMetrics{self.kind.capitalize()} {body}>"


# --------------------------------------------------------------------------
# regression


def regression_metrics(
    actual: np.ndarray,
    pred: np.ndarray,
    weights: np.ndarray | None = None,
    distribution: str = "gaussian",
) -> ModelMetrics:
    a = np.asarray(actual, np.float64)
    p = np.asarray(pred, np.float64)
    w = np.ones_like(a) if weights is None else np.asarray(weights, np.float64)
    ok = ~np.isnan(a) & ~np.isnan(p) & (w > 0)
    a, p, w = a[ok], p[ok], w[ok]
    sw = w.sum()
    err = a - p
    mse = float((w * err**2).sum() / sw)
    mae = float((w * np.abs(err)).sum() / sw)
    mean_a = (w * a).sum() / sw
    ss_tot = float((w * (a - mean_a) ** 2).sum() / sw)
    rmsle = float("nan")
    if (a > -1).all() and (p > -1).all():
        rmsle = float(
            np.sqrt((w * (np.log1p(a) - np.log1p(p)) ** 2).sum() / sw)
        )
    dev = _mean_deviance(a, p, w, distribution)
    return ModelMetrics(
        "regression",
        {
            "mse": mse,
            "rmse": float(np.sqrt(mse)),
            "mae": mae,
            "rmsle": rmsle,
            "r2": float(1.0 - mse / ss_tot) if ss_tot > 0 else float("nan"),
            "mean_residual_deviance": dev,
            "nobs": int(ok.sum()),
        },
    )


def _mean_deviance(a, p, w, distribution: str) -> float:
    sw = w.sum()
    if distribution == "poisson":
        p = np.maximum(p, _EPS)
        with np.errstate(divide="ignore", invalid="ignore"):
            t = np.where(a > 0, a * np.log(a / p), 0.0)
        return float((2 * w * (t - (a - p))).sum() / sw)
    if distribution == "gamma":
        p = np.maximum(p, _EPS)
        a_ = np.maximum(a, _EPS)
        return float((2 * w * (-np.log(a_ / p) + (a_ - p) / p)).sum() / sw)
    if distribution == "laplace":
        return float((w * np.abs(a - p)).sum() / sw)
    return float((w * (a - p) ** 2).sum() / sw)  # gaussian & default


# --------------------------------------------------------------------------
# binomial


def binomial_metrics(
    actual: np.ndarray,
    prob: np.ndarray,
    weights: np.ndarray | None = None,
    domain: tuple[str, str] = ("0", "1"),
) -> ModelMetrics:
    """``actual`` is {0,1} int; ``prob`` is P(class 1)."""
    y = np.asarray(actual, np.float64)
    p = np.clip(np.asarray(prob, np.float64), _EPS, 1 - _EPS)
    w = np.ones_like(y) if weights is None else np.asarray(weights, np.float64)
    ok = ~np.isnan(y) & ~np.isnan(p) & (w > 0)
    y, p, w = y[ok], p[ok], w[ok]
    sw = w.sum()

    logloss = float(-(w * (y * np.log(p) + (1 - y) * np.log(1 - p))).sum() / sw)
    mse = float((w * (y - p) ** 2).sum() / sw)
    auc = _weighted_auc(y, p, w)
    pr_auc = _pr_auc(y, p, w)

    # threshold table (the AUC2 criterion surface)
    thresholds = np.unique(np.quantile(p, np.linspace(0, 1, 400)))
    table = _threshold_table(y, p, w, thresholds)
    f1 = table["f1"]
    best = int(np.nanargmax(f1))
    best_thr = float(thresholds[best])
    cm = _confusion(y, p, w, best_thr)

    mx = {
        f"max_{name}": {
            "threshold": float(thresholds[int(np.nanargmax(table[name]))]),
            "value": float(np.nanmax(table[name])),
        }
        for name in ("f1", "f2", "f0point5", "accuracy", "precision", "recall", "specificity", "mcc", "min_per_class_accuracy", "mean_per_class_accuracy")
    }

    return ModelMetrics(
        "binomial",
        {
            "auc": auc,
            "pr_auc": pr_auc,
            "gini": 2 * auc - 1,
            "logloss": logloss,
            "mse": mse,
            "rmse": float(np.sqrt(mse)),
            "mean_per_class_error": float(
                1.0 - mx["max_mean_per_class_accuracy"]["value"]
            ),
            "default_threshold": best_thr,
            "confusion_matrix": cm,
            "max_criteria": mx,
            "nobs": int(ok.sum()),
        },
        domain=domain,
    )


def _weighted_auc(y, p, w) -> float:
    order = np.argsort(p, kind="mergesort")
    y, p, w = y[order], p[order], w[order]
    wpos = w * (y == 1)
    wneg = w * (y == 0)
    tot_pos, tot_neg = wpos.sum(), wneg.sum()
    if tot_pos == 0 or tot_neg == 0:
        return float("nan")
    # rank-sum with tie handling: group equal scores
    cum_neg = np.cumsum(wneg)
    # for ties, positives at a tied score see half the tied negatives
    _, idx, inv = np.unique(p, return_index=True, return_inverse=True)
    grp_neg = np.add.reduceat(wneg, idx)
    below = np.concatenate([[0.0], np.cumsum(grp_neg)[:-1]])
    frac = below[inv] + 0.5 * grp_neg[inv]
    return float((wpos * frac).sum() / (tot_pos * tot_neg))


def _pr_auc(y, p, w) -> float:
    order = np.argsort(-p, kind="mergesort")
    y, w = y[order], w[order]
    tp = np.cumsum(w * (y == 1))
    fp = np.cumsum(w * (y == 0))
    tot_pos = tp[-1]
    if tot_pos == 0:
        return float("nan")
    precision = tp / np.maximum(tp + fp, _EPS)
    recall = tp / tot_pos
    return float(np.trapezoid(precision, recall))


def _threshold_table(y, p, w, thresholds):
    pred = p[None, :] >= thresholds[:, None]  # (T, n)
    wpos = (w * (y == 1))[None, :]
    wneg = (w * (y == 0))[None, :]
    tp = (pred * wpos).sum(1)
    fp = (pred * wneg).sum(1)
    fn = wpos.sum() - tp
    tn = wneg.sum() - fp
    with np.errstate(divide="ignore", invalid="ignore"):
        precision = tp / (tp + fp)
        recall = tp / (tp + fn)
        specificity = tn / (tn + fp)
        accuracy = (tp + tn) / (tp + fp + fn + tn)
        f1 = 2 * precision * recall / (precision + recall)
        f2 = 5 * precision * recall / (4 * precision + recall)
        f05 = 1.25 * precision * recall / (0.25 * precision + recall)
        mcc = (tp * tn - fp * fn) / np.sqrt((tp + fp) * (tp + fn) * (tn + fp) * (tn + fn))
        min_pca = np.minimum(recall, specificity)
        mean_pca = 0.5 * (recall + specificity)
    return {
        "f1": f1,
        "f2": f2,
        "f0point5": f05,
        "accuracy": accuracy,
        "precision": precision,
        "recall": recall,
        "specificity": specificity,
        "mcc": np.abs(mcc),
        "min_per_class_accuracy": min_pca,
        "mean_per_class_accuracy": mean_pca,
    }


def _confusion(y, p, w, thr) -> list[list[float]]:
    pred = (p >= thr).astype(np.float64)
    tp = float((w * ((y == 1) & (pred == 1))).sum())
    fp = float((w * ((y == 0) & (pred == 1))).sum())
    fn = float((w * ((y == 1) & (pred == 0))).sum())
    tn = float((w * ((y == 0) & (pred == 0))).sum())
    return [[tn, fp], [fn, tp]]


# --------------------------------------------------------------------------
# multinomial


def multinomial_metrics(
    actual: np.ndarray,
    probs: np.ndarray,
    weights: np.ndarray | None = None,
    domain: tuple[str, ...] = (),
) -> ModelMetrics:
    """``actual`` int class ids; ``probs`` (n, K)."""
    y = np.asarray(actual)
    P = np.clip(np.asarray(probs, np.float64), _EPS, 1.0)
    w = np.ones(len(y), np.float64) if weights is None else np.asarray(weights, np.float64)
    ok = (y >= 0) & (w > 0) & ~np.isnan(P).any(axis=1)
    y, P, w = y[ok], P[ok], w[ok]
    sw = w.sum()
    K = P.shape[1]

    logloss = float(-(w * np.log(P[np.arange(len(y)), y])).sum() / sw)
    pred = P.argmax(axis=1)
    err = float((w * (pred != y)).sum() / sw)

    cm = np.zeros((K, K))
    np.add.at(cm, (y, pred), w)
    with np.errstate(divide="ignore", invalid="ignore"):
        per_class_err = 1.0 - np.diag(cm) / cm.sum(axis=1)
    mean_pce = float(np.nanmean(per_class_err))

    # top-k hit ratios (h2o reports up to 10)
    order = np.argsort(-P, axis=1)
    ranks = np.argmax(order == y[:, None], axis=1)
    topk = [float((w * (ranks <= k)).sum() / sw) for k in range(min(10, K))]

    onehot = np.zeros_like(P)
    onehot[np.arange(len(y)), y] = 1.0
    mse = float((w[:, None] * (onehot - P) ** 2).sum() / (sw))

    return ModelMetrics(
        "multinomial",
        {
            "logloss": logloss,
            "classification_error": err,
            "mean_per_class_error": mean_pce,
            "per_class_error": per_class_err,
            "confusion_matrix": cm,
            "hit_ratios": topk,
            "mse": mse,
            "rmse": float(np.sqrt(mse)),
            "nobs": int(ok.sum()),
        },
        domain=domain,
    )
