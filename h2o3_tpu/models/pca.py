"""PCA / SVD — successors of ``hex.pca.PCA`` and ``hex.svd.SVD`` [UNVERIFIED
upstream paths, SURVEY.md §2.2].

PCA (GramSVD method, h2o's default): one distributed Gram pass XᵀX on the
MXU (the ``hex.gram.Gram`` MRTask successor), then a host-side (p,p) eigen
decomposition — identical compute split to H2O (distributed accumulate,
local solve). SVD offers the randomized power-iteration method for tall
matrices (h2o's "Randomized" svd_method), all device matmuls.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.cluster.job import Job
from h2o3_tpu.cluster.registry import DKV
from h2o3_tpu.frame.frame import Frame, Vec
from h2o3_tpu.models.datainfo import DataInfo
from h2o3_tpu.models.model_base import CommonParams, Model, ModelBuilder

_HI = jax.lax.Precision.HIGHEST


@dataclass
class PCAParams(CommonParams):
    k: int = 1
    transform: str = "STANDARDIZE"  # NONE | DEMEAN | DESCALE | STANDARDIZE
    pca_method: str = "GramSVD"
    use_all_factor_levels: bool = False


class PCAModel(Model):
    algo = "pca"

    def _predict_raw(self, frame: Frame) -> np.ndarray:
        di: DataInfo = self.output["datainfo"]
        X, _ = di.transform(frame)
        V = jnp.asarray(self.output["eigenvectors"], jnp.float32)
        scores = jnp.einsum("np,pk->nk", X, V, precision=_HI)
        return np.asarray(scores)[: frame.nrow]

    def predict(self, frame: Frame) -> Frame:
        s = self._predict_raw(frame)
        vecs = [Vec.from_numpy(s[:, i], "real") for i in range(s.shape[1])]
        return Frame(vecs, [f"PC{i + 1}" for i in range(s.shape[1])])


class PCA(ModelBuilder):
    algo = "pca"
    PARAMS_CLS = PCAParams
    SUPPORTS_CLASSIFICATION = False

    def train(self, x=None, training_frame=None, **kw):
        return super().train(x=x, y=None, training_frame=training_frame, **kw)

    def _validate(self, train, valid):
        pass

    def _build(self, job: Job, train: Frame, valid: Frame | None) -> Model:
        p: PCAParams = self.params
        t = p.transform.upper()
        di = DataInfo.fit(
            train,
            self._x,
            standardize=(t == "STANDARDIZE"),
            use_all_factor_levels=p.use_all_factor_levels,
        )
        if t in ("NONE", "DESCALE"):
            for c in di.columns:
                if c.kind == "num":
                    c.mean = 0.0
        if t == "DESCALE":
            di.standardize = True
        X, w = di.transform(train)
        nobs = float(np.asarray(w.sum()))

        G = np.asarray(
            jnp.einsum("np,nq->pq", X, X, precision=_HI), np.float64
        )
        if t in ("DEMEAN", "STANDARDIZE"):
            pass  # columns already centered by DataInfo
        eigvals, eigvecs = np.linalg.eigh(G / max(nobs - 1, 1.0))
        order = np.argsort(-eigvals)
        eigvals = np.maximum(eigvals[order], 0.0)
        eigvecs = eigvecs[:, order]
        k = min(int(p.k), len(eigvals))

        std_dev = np.sqrt(eigvals[:k])
        prop = eigvals[:k] / max(eigvals.sum(), 1e-30)
        out = {
            "datainfo": di,
            "eigenvectors": eigvecs[:, :k],
            "eigenvalues": eigvals[:k],
            "std_deviation": std_dev,
            "proportion_of_variance": prop,
            "cumulative_proportion": np.cumsum(prop),
            "coef_names": di.coef_names(),
            "names": list(self._x),
            "response_domain": None,
        }
        model = PCAModel(DKV.make_key("pca"), p, out)
        from h2o3_tpu.models.metrics import ModelMetrics

        model.training_metrics = ModelMetrics(
            "pca", {"std_deviation": std_dev.tolist(), "k": k}
        )
        return model


@dataclass
class SVDParams(CommonParams):
    nv: int = 1
    transform: str = "NONE"
    svd_method: str = "Randomized"  # GramSVD | Power | Randomized
    max_iterations: int = 4


class SVDModel(Model):
    algo = "svd"

    def _predict_raw(self, frame: Frame) -> np.ndarray:
        di: DataInfo = self.output["datainfo"]
        X, _ = di.transform(frame)
        V = jnp.asarray(self.output["v"], jnp.float32)
        return np.asarray(jnp.einsum("np,pk->nk", X, V, precision=_HI))[: frame.nrow]


class SVD(ModelBuilder):
    algo = "svd"
    PARAMS_CLS = SVDParams
    SUPPORTS_CLASSIFICATION = False

    def train(self, x=None, training_frame=None, **kw):
        return super().train(x=x, y=None, training_frame=training_frame, **kw)

    def _validate(self, train, valid):
        pass

    def _build(self, job: Job, train: Frame, valid: Frame | None) -> Model:
        p: SVDParams = self.params
        di = DataInfo.fit(
            train, self._x, standardize=(p.transform.upper() == "STANDARDIZE")
        )
        X, w = di.transform(train)
        P = X.shape[1]
        nv = min(int(p.nv), P)
        rng = np.random.default_rng(abs(p.seed) if p.seed and p.seed > 0 else 1)

        if p.svd_method.lower() == "gramsvd" or P <= 64:
            G = np.asarray(jnp.einsum("np,nq->pq", X, X, precision=_HI), np.float64)
            evals, evecs = np.linalg.eigh(G)
            order = np.argsort(-evals)
            V = evecs[:, order[:nv]]
            d = np.sqrt(np.maximum(evals[order[:nv]], 0.0))
        else:
            # randomized subspace iteration: all heavy matmuls on device
            Q = jnp.asarray(rng.normal(size=(P, nv + 4)).astype(np.float32))
            for _ in range(max(1, p.max_iterations)):
                Y = jnp.einsum("np,pk->nk", X, Q, precision=_HI)
                Z = jnp.einsum("np,nk->pk", X, Y, precision=_HI)
                Q, _ = jnp.linalg.qr(Z)
            B = np.asarray(jnp.einsum("np,pk->nk", X, Q, precision=_HI))
            _, s, Vt = np.linalg.svd(B, full_matrices=False)
            V = (np.asarray(Q) @ Vt.T)[:, :nv]
            d = s[:nv]

        out = {
            "datainfo": di,
            "v": V,
            "d": d,
            "names": list(self._x),
            "response_domain": None,
        }
        model = SVDModel(DKV.make_key("svd"), p, out)
        from h2o3_tpu.models.metrics import ModelMetrics

        model.training_metrics = ModelMetrics("svd", {"d": d.tolist()})
        return model
