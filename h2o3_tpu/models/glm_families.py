"""GLM family/link zoo — successor of ``hex.glm.GLMModel.GLMParameters``
family/link math (``GLMTask``'s per-row link/variance evaluations)
[UNVERIFIED upstream paths, SURVEY.md §2.2].

Each family provides device-side: linkinv, link derivative (dmu/deta),
variance(mu), deviance(y, mu, w), and an initial-mu heuristic. All functions
are jax-traceable and close over static hyperparameters (tweedie powers,
negative-binomial theta).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

import jax.numpy as jnp

_EPS = 1e-10


def _clip01(x):
    return jnp.clip(x, _EPS, 1.0 - _EPS)


@dataclass(frozen=True)
class Link:
    name: str
    inv: Callable  # eta -> mu
    dinv: Callable  # eta -> dmu/deta
    fwd: Callable  # mu -> eta


LINKS = {
    "identity": Link("identity", lambda e: e, lambda e: jnp.ones_like(e), lambda m: m),
    "log": Link("log", jnp.exp, jnp.exp, lambda m: jnp.log(jnp.maximum(m, _EPS))),
    "logit": Link(
        "logit",
        lambda e: _clip01(jax_sigmoid(e)),
        lambda e: jnp.maximum(jax_sigmoid(e) * (1 - jax_sigmoid(e)), _EPS),
        lambda m: jnp.log(_clip01(m) / (1 - _clip01(m))),
    ),
    "inverse": Link(
        "inverse",
        lambda e: 1.0 / jnp.where(jnp.abs(e) < _EPS, _EPS, e),
        lambda e: -1.0 / jnp.square(jnp.where(jnp.abs(e) < _EPS, _EPS, e)),
        lambda m: 1.0 / jnp.where(jnp.abs(m) < _EPS, _EPS, m),
    ),
}


def jax_sigmoid(e):
    return 1.0 / (1.0 + jnp.exp(-e))


def tweedie_link(link_power: float) -> Link:
    if link_power == 0:
        return LINKS["log"]
    lp = float(link_power)
    return Link(
        f"tweedie_{lp}",
        lambda e: jnp.maximum(e, _EPS) ** (1.0 / lp),
        lambda e: (1.0 / lp) * jnp.maximum(e, _EPS) ** (1.0 / lp - 1.0),
        lambda m: jnp.maximum(m, _EPS) ** lp,
    )


@dataclass(frozen=True)
class Family:
    name: str
    link: Link
    variance: Callable  # mu -> var
    deviance: Callable  # (y, mu, w) -> scalar
    init_mu: Callable  # (y, w) -> mu0 array
    dispersion_fixed: bool  # True => dispersion 1 (binomial/poisson)


def _dev_gaussian(y, mu, w):
    return jnp.sum(w * (y - mu) ** 2)


def _dev_binomial(y, mu, w):
    mu = _clip01(mu)
    return -2.0 * jnp.sum(w * (y * jnp.log(mu) + (1 - y) * jnp.log(1 - mu)))


def _dev_poisson(y, mu, w):
    mu = jnp.maximum(mu, _EPS)
    t = jnp.where(y > 0, y * jnp.log(jnp.maximum(y, _EPS) / mu), 0.0)
    return 2.0 * jnp.sum(w * (t - (y - mu)))


def _dev_gamma(y, mu, w):
    mu = jnp.maximum(mu, _EPS)
    ys = jnp.maximum(y, _EPS)
    return 2.0 * jnp.sum(w * (-jnp.log(ys / mu) + (ys - mu) / mu))


def _dev_tweedie(p: float):
    def dev(y, mu, w):
        mu = jnp.maximum(mu, _EPS)
        ys = jnp.maximum(y, 0.0)
        if p == 1.0:
            return _dev_poisson(y, mu, w)
        if p == 2.0:
            return _dev_gamma(y, mu, w)
        t1 = jnp.where(
            ys > 0, ys ** (2.0 - p) / ((1.0 - p) * (2.0 - p)), 0.0
        )
        t2 = ys * mu ** (1.0 - p) / (1.0 - p)
        t3 = mu ** (2.0 - p) / (2.0 - p)
        return 2.0 * jnp.sum(w * (t1 - t2 + t3))

    return dev


def _dev_negbinomial(theta: float):
    def dev(y, mu, w):
        mu = jnp.maximum(mu, _EPS)
        ys = jnp.maximum(y, 0.0)
        it = 1.0 / theta
        t1 = jnp.where(ys > 0, ys * jnp.log(jnp.maximum(ys, _EPS) / mu), 0.0)
        t2 = (ys + it) * jnp.log((ys + it) / (mu + it))
        return 2.0 * jnp.sum(w * (t1 - t2))

    return dev


def get_family(
    name: str,
    link: str = "family_default",
    tweedie_variance_power: float = 1.5,
    tweedie_link_power: float = 0.0,
    theta: float = 1e-5,
) -> Family:
    name = name.lower()
    defaults = {
        "gaussian": "identity",
        "binomial": "logit",
        "quasibinomial": "logit",
        "fractionalbinomial": "logit",
        "poisson": "log",
        "gamma": "inverse",
        "tweedie": "tweedie",
        "negativebinomial": "log",
    }
    lname = defaults[name] if link in ("family_default", None) else link.lower()
    if name == "tweedie" or lname == "tweedie":
        lk = tweedie_link(tweedie_link_power)
    else:
        lk = LINKS[lname]

    wmean = lambda y, w: jnp.sum(w * y) / jnp.maximum(jnp.sum(w), _EPS)
    if name == "gaussian":
        return Family(name, lk, lambda m: jnp.ones_like(m), _dev_gaussian, wmean, False)
    if name in ("binomial", "quasibinomial", "fractionalbinomial"):
        return Family(
            name,
            lk,
            lambda m: jnp.maximum(_clip01(m) * (1 - _clip01(m)), _EPS),
            _dev_binomial,
            lambda y, w: jnp.clip(wmean(y, w), 0.01, 0.99) * jnp.ones_like(y),
            name == "binomial",
        )
    if name == "poisson":
        return Family(
            name,
            lk,
            lambda m: jnp.maximum(m, _EPS),
            _dev_poisson,
            lambda y, w: jnp.maximum(wmean(y, w), 0.1) * jnp.ones_like(y),
            True,
        )
    if name == "gamma":
        return Family(
            name,
            lk,
            lambda m: jnp.maximum(m, _EPS) ** 2,
            _dev_gamma,
            lambda y, w: jnp.maximum(wmean(y, w), _EPS) * jnp.ones_like(y),
            False,
        )
    if name == "tweedie":
        p = float(tweedie_variance_power)
        return Family(
            name,
            lk,
            lambda m: jnp.maximum(m, _EPS) ** p,
            _dev_tweedie(p),
            lambda y, w: jnp.maximum(wmean(y, w), 0.1) * jnp.ones_like(y),
            False,
        )
    if name == "negativebinomial":
        th = float(theta)
        return Family(
            name,
            lk,
            lambda m: jnp.maximum(m, _EPS) + th * jnp.maximum(m, _EPS) ** 2,
            _dev_negbinomial(th),
            lambda y, w: jnp.maximum(wmean(y, w), 0.1) * jnp.ones_like(y),
            False,
        )
    raise ValueError(f"unknown family {name}")
