"""InfoGram — successor of ``hex.Infogram.Infogram`` [UNVERIFIED upstream
path, SURVEY.md §2.2]: the information diagram for admissible machine
learning (Lee et al.).

Core infogram (no protected columns): per feature, x = *total information*
(predictive strength of the feature alone) and y = *net information*
(conditional strength given all other features — drop-one performance
delta), both normalized to [0, 1]. Fair infogram (``protected_columns``
set): x = *relevance* (strength the feature adds beyond the protected set)
and y = *safety* (one minus how well the feature predicts the protected
attributes — a proxy for I(x_i; protected), a documented deviation from
upstream's CMI estimator, which the empty reference mount left unverifiable).

Admissible features clear both ``safety_index_threshold`` and
``total_information_threshold``. All probe models are small GBMs on the
shared tree engine, so the whole diagram is a sequence of device builds.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from h2o3_tpu.cluster.job import Job
from h2o3_tpu.cluster.registry import DKV
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.model_base import CommonParams, Model, ModelBuilder


@dataclass
class InfogramParams(CommonParams):
    protected_columns: list = field(default_factory=list)
    safety_index_threshold: float = 0.1
    relevance_index_threshold: float = 0.1
    total_information_threshold: float = 0.1
    net_information_threshold: float = 0.1
    ntrees: int = 20
    max_depth: int = 5
    top_n_features: int = 50


def _strength(frame: Frame, y: str, xcols: list[str], classification: bool,
              ntrees: int, max_depth: int, seed: int) -> float:
    """Predictive strength of xcols for y: 1 - loss/null_loss in [0, 1]."""
    from h2o3_tpu.models.tree.gbm import GBM

    if not xcols:
        return 0.0
    m = GBM(ntrees=ntrees, max_depth=max_depth, seed=seed).train(
        y=y, x=xcols, training_frame=frame
    )
    mm = m.training_metrics
    if classification:
        ll = mm.value("logloss")
        yv = frame.vec(y)
        yn = yv.to_numpy()
        yn = yn[yn >= 0] if yv.is_categorical() else yn
        # null logloss from the class base rates
        _, cnt = np.unique(yn.astype(np.int64), return_counts=True)
        pr = cnt / cnt.sum()
        null = -float(np.sum(pr * np.log(np.clip(pr, 1e-15, 1))))
        return float(np.clip(1.0 - ll / max(null, 1e-12), 0.0, 1.0))
    mse = mm.value("mse")
    yn = frame.vec(y).to_numpy()
    null = float(np.nanvar(yn))
    return float(np.clip(1.0 - mse / max(null, 1e-12), 0.0, 1.0))


class InfogramModel(Model):
    algo = "infogram"

    def _predict_raw(self, frame: Frame) -> np.ndarray:
        raise NotImplementedError("infogram is a diagnostic model")

    def get_admissible_features(self) -> list[str]:
        return list(self.output["admissible_features"])

    def get_admissible_score_frame(self) -> list[dict]:
        return self.output["score_table"]

    def _score_metrics(self, frame: Frame):
        from h2o3_tpu.models.metrics import ModelMetrics

        return ModelMetrics(
            "infogram",
            {"n_admissible": float(len(self.output["admissible_features"]))},
        )


class Infogram(ModelBuilder):
    algo = "infogram"
    PARAMS_CLS = InfogramParams

    def _build(self, job: Job, train: Frame, valid: Frame | None) -> Model:
        p: InfogramParams = self.params
        yv = train.vec(p.response_column)
        classification = yv.is_categorical()
        seed = abs(p.seed) or 13
        protected = list(p.protected_columns or [])
        feats = [n for n in self._x if n not in protected]
        if len(feats) > p.top_n_features:
            feats = feats[: p.top_n_features]
        kw = dict(classification=classification, ntrees=p.ntrees,
                  max_depth=p.max_depth)

        table: list[dict] = []
        if not protected:
            # CORE: total info (solo strength), net info (drop-one delta)
            full = _strength(train, p.response_column, feats, seed=seed, **kw)
            solo: dict[str, float] = {}
            drop: dict[str, float] = {}
            for fi, f in enumerate(feats):
                solo[f] = _strength(
                    train, p.response_column, [f], seed=seed + 1 + fi, **kw
                )
                rest = [g for g in feats if g != f]
                drop[f] = max(full - _strength(
                    train, p.response_column, rest, seed=seed + 101 + fi, **kw
                ), 0.0)
                job.update(0.05 + 0.85 * (fi + 1) / len(feats))
            smax = max(solo.values()) or 1.0
            dmax = max(drop.values()) or 1.0
            for f in feats:
                ti = solo[f] / smax
                ni = drop[f] / dmax
                adm = (
                    ti >= p.total_information_threshold
                    and ni >= p.net_information_threshold
                )
                table.append(
                    {"column": f, "total_information": ti,
                     "net_information": ni, "admissible": adm}
                )
            xkey, ykey = "total_information", "net_information"
        else:
            # FAIR: relevance (gain beyond protected), safety (1 - protected
            # predictability from the feature)
            base = _strength(train, p.response_column, protected, seed=seed, **kw)
            rel: dict[str, float] = {}
            unsafe: dict[str, float] = {}
            for fi, f in enumerate(feats):
                rel[f] = max(
                    _strength(
                        train, p.response_column, protected + [f],
                        seed=seed + 1 + fi, **kw
                    ) - base,
                    0.0,
                )
                s = 0.0
                for pj, pc in enumerate(protected):
                    pv = train.vec(pc)
                    s = max(
                        s,
                        _strength(
                            train, pc, [f], classification=pv.is_categorical(),
                            ntrees=p.ntrees, max_depth=p.max_depth,
                            seed=seed + 201 + fi * 7 + pj,
                        ),
                    )
                unsafe[f] = s
                job.update(0.05 + 0.85 * (fi + 1) / len(feats))
            rmax = max(rel.values()) or 1.0
            umax = max(unsafe.values()) or 1.0
            for f in feats:
                rv = rel[f] / rmax
                sf = 1.0 - unsafe[f] / umax
                adm = (
                    rv >= p.relevance_index_threshold
                    and sf >= p.safety_index_threshold
                )
                table.append(
                    {"column": f, "relevance_index": rv, "safety_index": sf,
                     "admissible": adm}
                )
            xkey, ykey = "relevance_index", "safety_index"

        table.sort(key=lambda r: -(r[xkey] + r[ykey]))
        out = {
            "score_table": table,
            "admissible_features": [r["column"] for r in table if r["admissible"]],
            "x_axis": xkey,
            "y_axis": ykey,
            "names": feats,
        }
        model = InfogramModel(DKV.make_key("infogram"), p, out)
        model.training_metrics = model._score_metrics(train)
        return model
