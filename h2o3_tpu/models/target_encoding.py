"""Target encoding — successor of ``ai.h2o.targetencoding.TargetEncoder*``
[UNVERIFIED upstream paths, SURVEY.md §2.3].

Supervised categorical encoding with H2O's three holdout strategies:
``none`` (global per-level means), ``loo`` (leave-one-out: each row's own
target excluded from its level mean), ``kfold`` (per-fold out-of-fold
means), plus the blending formula lambda = 1/(1+exp(-(n-k)/f)) mixing the
level mean toward the global prior, and optional gaussian noise.

Level statistics are tiny (per-level sums); the group sums come off a host
pass over the pulled code/target columns — O(n) once, like H2O's single
MRTask pass — and the encoded column is rebuilt as a device Vec.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Sequence

import numpy as np

from h2o3_tpu.frame.frame import CAT, Frame, Vec


@dataclass
class TargetEncoderParams:
    holdout_type: str = "none"  # none | loo | kfold
    blending: bool = False
    inflection_point: float = 10.0  # k in lambda = 1/(1+exp(-(n-k)/f))
    smoothing: float = 20.0  # f
    noise: float = 0.0
    fold_column: str | None = None
    nfolds: int = 5
    seed: int = -1
    columns: Sequence[str] = field(default_factory=tuple)


class TargetEncoder:
    """fit/transform pair mirroring the h2o-py TargetEncoder surface."""

    algo = "targetencoder"
    PARAMS_CLS = TargetEncoderParams

    def __init__(self, **kw):
        self.params = TargetEncoderParams(**kw)
        self._stats: dict[str, tuple[np.ndarray, np.ndarray, tuple]] = {}
        self._prior: float = 0.0
        self._y: str | None = None

    def train(self, x=None, y=None, training_frame=None,
              validation_frame=None, **kw):
        """ModelBuilder-shaped entry so the REST surface and estimator
        classes can drive TE like any other algo (h2o exposes targetencoder
        as a regular builder)."""
        from h2o3_tpu.cluster.registry import DKV
        from h2o3_tpu.models.model_base import _resolve_frame

        fr = _resolve_frame(training_frame)
        self.fit(fr, y=y, columns=list(x) if x else None)
        self.key = DKV.make_key("targetencoder")
        DKV.put(self.key, self)
        return self

    # -- fit ----------------------------------------------------------------
    def fit(self, frame: Frame, y: str, columns: Sequence[str] | None = None):
        p = self.params
        cols = list(columns or p.columns) or [
            n for n in frame.names if frame.vec(n).is_categorical() and n != y
        ]
        yv = frame.vec(y)
        t = yv.to_numpy().astype(np.float64)
        if yv.is_categorical():
            if yv.cardinality != 2:
                raise ValueError("target encoding supports numeric or binary targets")
            t = (t == 1).astype(np.float64)
        ok = ~np.isnan(t) & (t >= 0)
        self._prior = float(t[ok].mean()) if ok.any() else 0.0
        self._y = y
        self._stats = {}
        for c in cols:
            v = frame.vec(c)
            if not v.is_categorical():
                continue
            codes = v.to_numpy().astype(np.int64)
            card = v.cardinality
            use = ok & (codes >= 0)
            cnt = np.bincount(codes[use], minlength=card).astype(np.float64)
            ssum = np.bincount(codes[use], weights=t[use], minlength=card)
            self._stats[c] = (cnt, ssum, tuple(v.domain or ()))
        return self

    # -- transform ----------------------------------------------------------
    def transform(self, frame: Frame, as_training: bool = False) -> Frame:
        """Append ``<col>_te`` columns. ``as_training=True`` applies the
        holdout strategy (loo/kfold need the frame's own target/folds);
        test-time transform always uses the full fitted means."""
        p = self.params
        rng = np.random.default_rng(abs(p.seed) if p.seed and p.seed > 0 else None)
        n = frame.nrow

        t = fold = None
        if as_training and p.holdout_type in ("loo", "kfold"):
            yv = frame.vec(self._y)
            t = yv.to_numpy().astype(np.float64)
            if yv.is_categorical():
                t = (t == 1).astype(np.float64)
            if p.holdout_type == "kfold":
                if p.fold_column:
                    fold = frame.vec(p.fold_column).to_numpy().astype(np.int64)
                else:
                    fold = np.arange(n) % p.nfolds

        new_vecs, new_names = list(frame._vecs), list(frame.names)
        for c, (cnt, ssum, dom) in self._stats.items():
            if c not in frame or f"{c}_te" in frame:  # idempotent re-apply
                continue
            v = frame.vec(c)
            codes = v.to_numpy().astype(np.int64)
            # remap to fit-time domain when the frame's domain differs
            if tuple(v.domain or ()) != dom:
                lut = {d: i for i, d in enumerate(dom)}
                remap = np.array(
                    [lut.get(d, -1) for d in (v.domain or ())], np.int64
                )
                codes = np.where(codes >= 0, remap[np.clip(codes, 0, None)], -1)
            enc = np.full(n, self._prior)
            seen = codes >= 0
            cs = np.clip(codes, 0, None)
            if as_training and p.holdout_type == "loo" and t is not None:
                own = np.where(~np.isnan(t), t, 0.0)
                cnt_i = cnt[cs] - 1.0
                sum_i = ssum[cs] - own
                mean = np.where(cnt_i > 0, sum_i / np.maximum(cnt_i, 1e-300), self._prior)
                nlev = cnt_i
            elif as_training and p.holdout_type == "kfold" and t is not None:
                # out-of-fold level stats = full stats − this fold's stats
                mean = np.full(n, self._prior)
                nlev = np.zeros(n)
                for f in np.unique(fold):
                    infold = fold == f
                    use = infold & seen & ~np.isnan(t)
                    card = len(cnt)
                    cf = np.bincount(cs[use], minlength=card).astype(np.float64)
                    sf = np.bincount(cs[use], weights=t[use], minlength=card)
                    oof_cnt = cnt - cf
                    oof_sum = ssum - sf
                    m = np.where(oof_cnt > 0, oof_sum / np.maximum(oof_cnt, 1e-300), self._prior)
                    mean[infold] = m[cs[infold]]
                    nlev[infold] = oof_cnt[cs[infold]]
            else:
                mean = np.where(cnt[cs] > 0, ssum[cs] / np.maximum(cnt[cs], 1e-300), self._prior)
                nlev = cnt[cs]
            if p.blending:
                lam = 1.0 / (1.0 + np.exp(-(nlev - p.inflection_point) / max(p.smoothing, 1e-9)))
                mean = lam * mean + (1 - lam) * self._prior
            enc[seen] = mean[seen]
            if as_training and p.noise > 0:
                enc = enc + rng.uniform(-p.noise, p.noise, n)
            new_vecs.append(Vec.from_numpy(enc, "real", name=f"{c}_te"))
            new_names.append(f"{c}_te")
        return Frame(new_vecs, new_names)
