"""Single decision tree (DT) — successor of ``hex.tree.dt.DT`` [UNVERIFIED
upstream path, SURVEY.md §2.2].

One CART-style tree on the shared level-wise histogram engine (leaf value =
weighted node mean of the 0/1 response or the numeric target). H2O's DT is
binary-classification only; regression is supported here as a superset.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.cluster.job import Job
from h2o3_tpu.cluster.registry import DKV
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.model_base import ModelBuilder
from h2o3_tpu.models.tree.binning import bin_frame, fit_bins, fit_bins_for
from h2o3_tpu.models.tree.gbm import SharedTreeModel, SharedTreeParams
from h2o3_tpu.models.tree.shared_tree import build_tree


@dataclass
class DTParams(SharedTreeParams):
    max_depth: int = 10
    min_rows: float = 10.0


class DTModel(SharedTreeModel):
    algo = "dt"

    def _predict_raw_dev(self, frame: Frame):
        raw = self._replay_all_dev(frame)[: frame.nrow]  # leaf means
        if not self.is_classifier:
            return raw
        p1 = jnp.clip(raw, 0.0, 1.0)
        return jnp.stack([1 - p1, p1], axis=1)

    def _predict_raw(self, frame: Frame) -> np.ndarray:
        return np.asarray(self._predict_raw_dev(frame))


class DT(ModelBuilder):
    algo = "dt"
    PARAMS_CLS = DTParams

    def _build(self, job: Job, train: Frame, valid: Frame | None):
        p: DTParams = self.params
        yv = train.vec(p.response_column)
        classification = yv.is_categorical()
        if classification and yv.cardinality > 2:
            raise ValueError("DT supports binary classification only (H2O parity)")

        spec = fit_bins_for(p, train, self._x)
        bins = bin_frame(spec, train)
        npad = train.npad

        y_np = yv.to_numpy().astype(np.float64)
        w_np = np.zeros(npad, np.float32)
        w_np[: train.nrow] = 1.0
        if p.weights_column:
            w_np[: train.nrow] *= np.nan_to_num(
                train.vec(p.weights_column).to_numpy()
            ).astype(np.float32)
        w_np[: train.nrow] *= (y_np >= 0) if classification else ~np.isnan(y_np)
        ybuf = np.zeros(npad, np.float32)
        ybuf[: train.nrow] = np.nan_to_num(y_np, nan=0.0)
        w = jnp.asarray(w_np)
        y = jnp.asarray(ybuf)

        tree, F, varimp = build_tree(
            bins, w, y, w,  # hessian = weight → leaf = weighted node mean
            n_bins=spec.max_bins,
            is_cat_cols=spec.is_cat,
            max_depth=p.max_depth,
            min_rows=p.min_rows,
            min_split_improvement=p.min_split_improvement,
            learn_rate=1.0,
            preds=jnp.zeros(npad, jnp.float32),
            key=jax.random.PRNGKey(abs(p.seed) if p.seed and p.seed > 0 else 42),
            varimp=jnp.zeros(len(self._x), jnp.float32),
        )

        out = {
            "bin_spec": spec,
            "trees": [[tree]],
            "n_tree_classes": 1,
            "names": list(self._x),
            "varimp": np.asarray(varimp).astype(np.float64),
            "response_domain": tuple(yv.domain) if classification else None,
            "ntrees_actual": 1,
        }
        model = DTModel(DKV.make_key("dt"), p, out)
        model.training_metrics = model._score_metrics(train)
        if valid is not None:
            model.validation_metrics = model._score_metrics(valid)
        return model
