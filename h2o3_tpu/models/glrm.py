"""GLRM — successor of ``hex.glrm.GLRM`` / ``GlrmLoss`` [UNVERIFIED upstream
paths, SURVEY.md §2.2].

Generalized low-rank model A ≈ X·Y (X: n×k archetypes weights, Y: k×d
archetypes) fit by H2O's alternating proximal-gradient scheme, TPU-native:
both factor updates are dense matmuls over the row-sharded (masked) data
matrix, jitted as ONE program per iteration with backtracking handled by
the objective trend (step halving on increase, growth on decrease — the
same adaptive step rule upstream uses). Missing cells simply carry weight 0
in the loss mask. Losses: quadratic (numeric), categorical one-hot quadratic
(a faithful stand-in for upstream's multinomial hinge on this engine);
regularizers: none / l2 / l1 (prox soft-threshold) / non-negative (prox
clip) on either factor.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.cluster.job import Job
from h2o3_tpu.cluster.registry import DKV
from h2o3_tpu.frame.frame import Frame, Vec
from h2o3_tpu.models.metrics import ModelMetrics
from h2o3_tpu.models.model_base import CommonParams, Model, ModelBuilder


@dataclass
class GLRMParams(CommonParams):
    k: int = 2
    loss: str = "Quadratic"
    regularization_x: str = "None"  # None | L2 | L1 | NonNegative
    regularization_y: str = "None"
    gamma_x: float = 0.0
    gamma_y: float = 0.0
    max_iterations: int = 100
    init_step_size: float = 1.0
    min_step_size: float = 1e-6
    tolerance_rel: float = 1e-7
    transform: str = "STANDARDIZE"  # NONE | DEMEAN | STANDARDIZE
    init: str = "SVD"  # SVD | Random


def _prox(M, reg: str, t: float, gamma: float):
    if reg == "L1":
        return jnp.sign(M) * jnp.maximum(jnp.abs(M) - t * gamma, 0.0)
    if reg == "L2":
        return M / (1.0 + 2.0 * t * gamma)
    if reg == "NonNegative":
        return jnp.maximum(M, 0.0)
    return M


def _reg_val(M, reg: str, gamma: float):
    if reg == "L1":
        return gamma * jnp.abs(M).sum()
    if reg == "L2":
        return gamma * (M**2).sum()
    return 0.0


class GLRMModel(Model):
    algo = "glrm"

    def _predict_raw(self, frame: Frame) -> np.ndarray:
        raise NotImplementedError("GLRM is a matrix factorization; use transform_frame/reconstruct")

    def transform_frame(self, frame: Frame) -> Frame:
        """Project new rows onto the fitted archetypes Y (ridge solve)."""
        A, mask = _design(frame, self.output["names"], self.output["means"], self.output["sigmas"])
        Y = jnp.asarray(self.output["archetypes"])
        G = Y @ Y.T + 1e-6 * jnp.eye(Y.shape[0])
        X = jnp.linalg.solve(G, Y @ (A * mask).T).T
        cols = [Vec.from_numpy(np.asarray(X[:, j])[: frame.nrow], "real") for j in range(X.shape[1])]
        return Frame(cols, [f"Arch{j + 1}" for j in range(X.shape[1])])

    def reconstruct(self, frame: Frame) -> Frame:
        Xf = self.transform_frame(frame)
        X = np.stack([Xf.vec(j).to_numpy() for j in range(Xf.ncol)], axis=1)
        R = X @ self.output["archetypes"]
        R = R * self.output["sigmas"][None, :] + self.output["means"][None, :]
        names = self.output["names"]
        return Frame(
            [Vec.from_numpy(R[:, i], "real") for i in range(len(names))],
            [f"reconstr_{n}" for n in names],
        )


def _design(frame: Frame, cols, means, sigmas):
    npad = frame.npad
    mats, masks = [], []
    for i, c in enumerate(cols):
        x = frame.vec(c).data
        m = ~jnp.isnan(x)
        x = (jnp.nan_to_num(x) - means[i]) / sigmas[i]
        mats.append(jnp.where(m, x, 0.0))
        masks.append(m.astype(jnp.float32))
    return jnp.stack(mats, axis=1), jnp.stack(masks, axis=1)


class GLRM(ModelBuilder):
    algo = "glrm"
    PARAMS_CLS = GLRMParams
    SUPPORTS_CLASSIFICATION = False
    SUPPORTS_REGRESSION = False

    def train(self, x=None, training_frame=None, **kw):
        return super().train(x=x, y=None, training_frame=training_frame, **kw)

    def _validate(self, train, valid):
        pass  # unsupervised

    def _features(self, train: Frame, response):
        return [n for n in train.names if train.vec(n).is_numeric()]

    def _build(self, job: Job, train: Frame, valid: Frame | None):
        p: GLRMParams = self.params
        cols = self._x
        assert cols, "GLRM needs numeric columns"
        d = len(cols)
        k = min(p.k, d)

        means = np.zeros(d)
        sigmas = np.ones(d)
        for i, c in enumerate(cols):
            x = train.vec(c).to_numpy()
            ok = ~np.isnan(x)
            if p.transform in ("DEMEAN", "STANDARDIZE"):
                means[i] = float(x[ok].mean()) if ok.any() else 0.0
            if p.transform == "STANDARDIZE":
                s = float(x[ok].std()) if ok.any() else 1.0
                sigmas[i] = s if s > 1e-12 else 1.0

        A, mask = _design(train, cols, means, sigmas)
        npad = A.shape[0]
        rng = np.random.default_rng(abs(p.seed) if p.seed and p.seed > 0 else 11)
        if p.init.upper() == "SVD":
            # randomized range finder on the zero-filled matrix (host svd of
            # a (d, d) gram is tiny)
            G = np.asarray((A * mask).T @ (A * mask))
            _, _, vt = np.linalg.svd(G)
            Y0 = vt[:k, :]
            X0 = np.asarray(A) @ Y0.T
        else:
            Y0 = rng.normal(size=(k, d)) * 0.1
            X0 = rng.normal(size=(npad, k)) * 0.1
        X = jnp.asarray(X0.astype(np.float32))
        Y = jnp.asarray(Y0.astype(np.float32))

        rx, ry = p.regularization_x, p.regularization_y
        gx, gy = float(p.gamma_x), float(p.gamma_y)

        @jax.jit
        def objective(X, Y):
            R = (X @ Y - A) * mask
            return 0.5 * (R**2).sum() + _reg_val(X, rx, gx) + _reg_val(Y, ry, gy)

        smooth = rx in ("None", "L2") and ry in ("None", "L2")
        eye = jnp.eye(k)

        @jax.jit
        def als_step(X, Y):
            # exact masked alternating ridge: per-row (and per-column) k×k
            # solves, batched — monotone and fast for the quadratic loss
            Gx = jnp.einsum("kd,nd,ld->nkl", Y, mask, Y) + (gx + 1e-8) * eye
            bx = jnp.einsum("kd,nd->nk", Y, A * mask)
            Xn = jnp.linalg.solve(Gx, bx[..., None])[..., 0]
            Gy = jnp.einsum("nk,nd,nl->dkl", Xn, mask, Xn) + (gy + 1e-8) * eye
            by = jnp.einsum("nk,nd->dk", Xn, A * mask)
            Yn = jnp.linalg.solve(Gy, by[..., None])[..., 0].T
            return Xn, Yn

        @jax.jit
        def prox_step(X, Y, alpha):
            # Lipschitz-scaled proximal gradient (spectral norms of the
            # factors bound the quadratic term's curvature)
            ly = jnp.linalg.norm(Y @ Y.T, 2) + 2 * gx + 1e-6
            R = (X @ Y - A) * mask
            gX = R @ Y.T + (2 * gx * X if rx == "L2" else 0.0)
            Xn = _prox(X - (alpha / ly) * gX, rx, alpha / ly, gx)
            lx = jnp.linalg.norm(Xn.T @ Xn, 2) + 2 * gy + 1e-6
            R2 = (Xn @ Y - A) * mask
            gY = Xn.T @ R2 + (2 * gy * Y if ry == "L2" else 0.0)
            Yn = _prox(Y - (alpha / lx) * gY, ry, alpha / lx, gy)
            return Xn, Yn

        nobs = float(jnp.maximum(mask.sum(), 1.0))
        alpha = p.init_step_size
        obj = float(objective(X, Y))
        history = [{"iteration": 0, "objective": obj, "step_size": alpha}]
        for it in range(p.max_iterations):
            if smooth:
                Xn, Yn = als_step(X, Y)
            else:
                Xn, Yn = prox_step(X, Y, jnp.float32(alpha))
            new_obj = float(objective(Xn, Yn))
            if np.isfinite(new_obj) and new_obj <= obj * (1 + 1e-7):
                converged = obj - new_obj < p.tolerance_rel * max(abs(obj), 1e-12)
                X, Y, obj = Xn, Yn, new_obj
                alpha *= 1.05  # upstream grows the step on success
                if converged and it > 2:
                    history.append({"iteration": it + 1, "objective": obj, "step_size": alpha})
                    break
            else:
                alpha *= 0.5  # and halves it on failure
                if alpha < p.min_step_size:
                    break
            history.append({"iteration": it + 1, "objective": obj, "step_size": alpha})
            job.update(0.05 + 0.9 * (it + 1) / p.max_iterations)

        out = {
            "names": list(cols),
            "archetypes": np.asarray(Y),
            "x_factor": np.asarray(X)[: train.nrow],
            "means": means,
            "sigmas": sigmas,
            "objective": obj,
            "response_domain": None,
        }
        model = GLRMModel(DKV.make_key("glrm"), p, out)
        model.scoring_history = history
        sse = obj
        model.training_metrics = ModelMetrics(
            "glrm", {"objective": obj, "sse": float(sse), "iterations": len(history) - 1, "nobs": int(nobs)}
        )
        return model
