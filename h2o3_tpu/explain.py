"""Model explainability — successor of ``h2o-py/h2o/explanation/*``
(``h2o.explain``) [UNVERIFIED upstream paths, SURVEY.md §2.3].

Data-first: every function returns plain numpy/dict artifacts (the upstream
module renders matplotlib figures; here the figure is optional — pass
``plot=True`` where matplotlib is available, but the contract is the data,
so headless coordinators and tests need no display stack).

Surface: variable importance (+ cross-model heatmap), partial dependence,
ICE, SHAP summary (tree models via predict_contributions), model
correlation, residual analysis, learning curves, and the one-call
``explain()`` driver that picks the applicable artifacts, matching the
upstream dispatch.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.model_base import Model


# ---------------------------------------------------------------------------
# variable importance


def varimp(model: Model, normalize: bool = True) -> dict[str, float]:
    vi = model.output.get("varimp")
    names = model.output.get("names", [])
    if vi is None:
        # GLM-family: |standardized coefficient| as importance (h2o does this)
        coefs = model.output.get("beta_std_report")
        cn = model.output.get("coef_names", [])
        if coefs is None:
            return {}
        pairs = {
            n: abs(float(c)) for n, c in zip(cn, coefs) if n != "Intercept"
        }
    else:
        pairs = {n: float(v) for n, v in zip(names, np.asarray(vi))}
    if normalize and pairs:
        mx = max(pairs.values()) or 1.0
        pairs = {k: v / mx for k, v in pairs.items()}
    return dict(sorted(pairs.items(), key=lambda kv: -kv[1]))


def varimp_heatmap(models: Sequence[Model]) -> dict:
    """Per-model normalized importances aligned on the feature union."""
    per = [varimp(m) for m in models]
    feats = sorted({f for p in per for f in p})
    mat = np.array([[p.get(f, 0.0) for f in feats] for p in per])
    return {
        "features": feats,
        "models": [m.key for m in models],
        "matrix": mat,  # (n_models, n_features)
    }


# ---------------------------------------------------------------------------
# partial dependence + ICE


def _col_grid(frame: Frame, column: str, nbins: int) -> np.ndarray:
    v = frame.vec(column)
    if v.is_categorical():
        return np.arange(v.cardinality)
    x = v.to_numpy()
    lo, hi = np.nanpercentile(x, [1, 99])
    return np.linspace(lo, hi, nbins)


def _predict_pos(model: Model, frame: Frame) -> np.ndarray:
    """Scalar prediction per row: positive-class prob or regression value."""
    raw = model._predict_raw(model._apply_preprocessors(frame))
    raw = np.asarray(raw)
    if raw.ndim == 2:
        return raw[:, -1] if raw.shape[1] == 2 else raw.max(axis=1)
    return raw


def partial_dependence(
    model: Model, frame: Frame, column: str, nbins: int = 20,
    sample_rows: int = 2000, seed: int = 7,
) -> dict:
    """PDP: mean prediction with ``column`` clamped to each grid value."""
    rng = np.random.default_rng(seed)
    n = frame.nrow
    idx = rng.permutation(n)[: min(n, sample_rows)]
    base = frame.to_pandas().iloc[np.sort(idx)].reset_index(drop=True)
    grid = _col_grid(frame, column, nbins)
    v = frame.vec(column)
    dom = v.domain if v.is_categorical() else None
    means, stds = [], []
    for g in grid:
        mod = base.copy()
        mod[column] = (dom[int(g)] if dom else float(g))
        sub = Frame.from_pandas(mod, column_types=frame.types)
        p = _predict_pos(model, sub)
        means.append(float(np.mean(p)))
        stds.append(float(np.std(p)))
    values = [dom[int(g)] for g in grid] if dom else [float(g) for g in grid]
    return {"column": column, "values": values,
            "mean_response": means, "stddev_response": stds}


def ice(
    model: Model, frame: Frame, column: str, nbins: int = 20,
    sample_rows: int = 50, seed: int = 11,
) -> dict:
    """Individual conditional expectation curves for a row sample."""
    rng = np.random.default_rng(seed)
    idx = np.sort(rng.permutation(frame.nrow)[: min(frame.nrow, sample_rows)])
    base = frame.to_pandas().iloc[idx].reset_index(drop=True)
    grid = _col_grid(frame, column, nbins)
    v = frame.vec(column)
    dom = v.domain if v.is_categorical() else None
    curves = np.zeros((len(base), len(grid)))
    for gi, g in enumerate(grid):
        mod = base.copy()
        mod[column] = (dom[int(g)] if dom else float(g))
        curves[:, gi] = _predict_pos(model, Frame.from_pandas(mod, column_types=frame.types))
    values = [dom[int(g)] for g in grid] if dom else [float(g) for g in grid]
    return {"column": column, "values": values, "rows": idx.tolist(),
            "curves": curves}


# ---------------------------------------------------------------------------
# SHAP summary


def shap_summary(model: Model, frame: Frame, top_n: int = 20) -> dict:
    """Mean |contribution| per feature + the raw contribution matrix."""
    if not hasattr(model, "predict_contributions"):
        raise ValueError(f"{model.algo} does not support predict_contributions")
    contrib = model.predict_contributions(frame)
    cols = [c for c in contrib.names if c != "BiasTerm"]
    mat = np.stack([contrib.vec(c).to_numpy() for c in cols], axis=1)
    mean_abs = np.abs(mat).mean(axis=0)
    order = np.argsort(-mean_abs)[:top_n]
    return {
        "features": [cols[i] for i in order],
        "mean_abs_contribution": mean_abs[order],
        "contributions": mat[:, order],
    }


# ---------------------------------------------------------------------------
# model correlation + residuals + learning curve


def model_correlation(models: Sequence[Model], frame: Frame) -> dict:
    preds = np.stack([_predict_pos(m, frame) for m in models], axis=1)
    return {"models": [m.key for m in models],
            "correlation": np.corrcoef(preds, rowvar=False)}


def residual_analysis(model: Model, frame: Frame) -> dict:
    y = frame.vec(model.params.response_column).to_numpy().astype(np.float64)
    fitted = _predict_pos(model, frame)
    resid = y - fitted
    return {"fitted": fitted, "residuals": resid,
            "rmse": float(np.sqrt(np.nanmean(resid**2)))}


def learning_curve(model: Model) -> dict:
    hist = getattr(model, "scoring_history", None) or []
    if not hist:
        return {"steps": [], "series": {}}
    keys = [k for k in hist[0] if k not in ("ntrees", "iteration", "epoch")]
    step_key = next(
        (k for k in ("ntrees", "iteration", "epoch") if k in hist[0]), None
    )
    steps = [h.get(step_key, i) for i, h in enumerate(hist)]
    return {
        "steps": steps,
        "series": {k: [h.get(k) for h in hist] for k in keys},
    }


# ---------------------------------------------------------------------------
# matplotlib renderings of the artifacts above — the h2o-py plot surface
# (model.varimp_plot() etc.). Figures use the Agg backend (headless
# coordinator); every function returns the Figure and optionally saves it.


def _fig():
    import sys

    import matplotlib

    # headless default, but NEVER hijack an interactive session's backend:
    # switch to Agg only if pyplot hasn't been imported/configured yet
    if "matplotlib.pyplot" not in sys.modules:
        matplotlib.use("Agg", force=False)
    import matplotlib.pyplot as plt

    return plt


def _finish(fig, save: str | None):
    fig.tight_layout()
    if save:
        fig.savefig(save, dpi=120)
        # saved figures are artifacts, not open windows: close so a
        # long-lived coordinator can't accumulate pyplot registry entries
        import matplotlib.pyplot as plt

        plt.close(fig)
    return fig


def varimp_plot(model: Model, num_of_features: int = 10, save: str | None = None):
    """Horizontal scaled-importance bars (upstream varimp_plot)."""
    plt = _fig()
    vi = varimp(model)
    items = sorted(vi.items(), key=lambda kv: kv[1])[-num_of_features:]
    fig, ax = plt.subplots(figsize=(7, 0.4 * len(items) + 1.2))
    ax.barh([k for k, _ in items], [v for _, v in items])
    ax.set_xlabel("scaled importance")
    ax.set_title(f"Variable importance: {model.key}")
    return _finish(fig, save)


def pd_plot(model: Model, frame: Frame, column: str, nbins: int = 20,
            save: str | None = None):
    """Partial-dependence curve with the ±1 SD band (upstream pd_plot)."""
    import numpy as _np

    plt = _fig()
    t = partial_dependence(model, frame, column, nbins=nbins)
    fig, ax = plt.subplots(figsize=(7, 4))
    m = _np.asarray(t["mean_response"])
    s = _np.asarray(t["stddev_response"])
    if all(isinstance(v, (int, float)) for v in t["values"]):
        xs = t["values"]
        ax.fill_between(xs, m - s, m + s, alpha=0.2)
        ax.plot(xs, m, marker="o")
    else:  # categorical grid: bar chart
        ax.bar([str(v) for v in t["values"]], m, yerr=s, capsize=3)
        ax.tick_params(axis="x", rotation=45)
    ax.set_xlabel(column)
    ax.set_ylabel("mean response")
    ax.set_title(f"Partial dependence of {column}")
    return _finish(fig, save)


def roc_plot(model: Model, save: str | None = None, valid: bool = False):
    """ROC curve from the stored threshold table (binomial models)."""
    import numpy as _np

    plt = _fig()
    mm = model.validation_metrics if valid else model.training_metrics
    if mm is None:
        raise ValueError(
            "no validation metrics on this model — train with a "
            "validation_frame or call roc_plot(valid=False)")
    auc = mm.value("auc")
    # rebuild the curve from the gains-style cumulatives when present;
    # fall back to the confusion-matrix point
    fig, ax = plt.subplots(figsize=(5.5, 5))
    gl = mm.gains_lift() or []
    if gl:
        pf = _pos_frac(mm)
        xs = [0.0]
        ys = [0.0]
        for r in gl:
            ys.append(r["cumulative_capture_rate"])
            # FPR from data fraction and capture: df*N = TP+FP; approximate
            # with the cumulative negatives fraction
            xs.append(
                (r["cumulative_data_fraction"]
                 - r["cumulative_capture_rate"] * pf) / max(1 - pf, 1e-9)
            )
        ax.plot(xs, ys, marker=".")
    ax.plot([0, 1], [0, 1], linestyle="--", linewidth=1)
    ax.set_xlabel("False positive rate")
    ax.set_ylabel("True positive rate")
    ax.set_title(f"ROC (AUC={auc:.4f})")
    return _finish(fig, save)


def _pos_frac(mm) -> float:
    cm = mm._v.get("confusion_matrix")
    if not cm:
        return 0.5
    tn, fp = cm[0]
    fn, tp = cm[1]
    tot = tn + fp + fn + tp
    return (tp + fn) / tot if tot else 0.5


def learning_curve_plot(model: Model, save: str | None = None):
    """Training-history curves (upstream learning_curve_plot)."""
    plt = _fig()
    lc = learning_curve(model)
    fig, ax = plt.subplots(figsize=(7, 4))
    for name, ys in lc["series"].items():
        vals = [v for v in ys if isinstance(v, (int, float))]
        if len(vals) == len(ys) and vals:
            ax.plot(lc["steps"], ys, label=name)
    ax.set_xlabel("step")
    ax.legend(loc="best", fontsize=8)
    ax.set_title(f"Learning curve: {model.key}")
    return _finish(fig, save)


def shap_summary_plot(model: Model, frame: Frame, top_n: int = 15,
                      save: str | None = None):
    """Mean-|SHAP| bars (the beeswarm's bar-summary form)."""
    plt = _fig()
    t = shap_summary(model, frame, top_n=top_n)
    fig, ax = plt.subplots(figsize=(7, 0.4 * len(t["features"]) + 1.2))
    ax.barh(t["features"][::-1], list(t["mean_abs_contribution"])[::-1])
    ax.set_xlabel("mean |SHAP contribution|")
    ax.set_title("SHAP summary")
    return _finish(fig, save)


# ---------------------------------------------------------------------------
# the one-call driver


def explain(models, frame: Frame, columns: Sequence[str] | None = None) -> dict:
    """``h2o.explain`` driver: run every applicable artifact.

    ``models`` may be one Model, a list, or an AutoML object (its leaderboard
    models are used, like upstream).
    """
    if hasattr(models, "leaderboard"):  # AutoML duck-type
        lb = models.leaderboard
        models = [m for m in getattr(models, "models", [])] or [models.leader]
    if isinstance(models, Model):
        models = [models]
    models = list(models)
    out: dict = {}
    m0 = models[0]
    out["varimp"] = {m.key: varimp(m) for m in models if varimp(m)}
    if len(models) > 1:
        out["varimp_heatmap"] = varimp_heatmap(
            [m for m in models if varimp(m)]
        )
        out["model_correlation"] = model_correlation(models, frame)
    feats = columns
    if feats is None:
        vi = varimp(m0)
        feats = list(vi)[:2] if vi else list(m0.output.get("names", []))[:2]
    out["pdp"] = {c: partial_dependence(m0, frame, c) for c in feats}
    if hasattr(m0, "predict_contributions"):
        try:
            out["shap_summary"] = shap_summary(m0, frame)
        except Exception:  # noqa: BLE001 — optional artifact
            pass
    if m0.params.response_column and not m0.is_classifier:
        out["residual_analysis"] = residual_analysis(m0, frame)
    lc = learning_curve(m0)
    if lc["steps"]:
        out["learning_curve"] = lc
    return out
