"""GENERATED FILE — do not edit. Regenerate with tools/gen_bindings.py.

Explicit per-algorithm estimator classes rendered from the builder params
dataclasses (the codegen analog of upstream's h2o-bindings output).
"""

from h2o3_tpu.estimators import _EstimatorBase



class H2OGradientBoostingEstimator(_EstimatorBase):
    """GBM estimator (generated).

    Parameters
    ----------
    response_column: str | None (default None)
    ignored_columns: Sequence[str] (default ())
    weights_column: str | None (default None)
    offset_column: str | None (default None)
    nfolds: int (default 0)
    fold_assignment: str (default 'modulo')
    keep_cross_validation_predictions: bool (default False)
    seed: int (default -1)
    max_runtime_secs: float (default 0.0)
    stopping_rounds: int (default 0)
    stopping_metric: str (default 'AUTO')
    stopping_tolerance: float (default 0.001)
    checkpoint: Any (default None)
    export_checkpoints_dir: str | None (default None)
    ntrees: int (default 50)
    max_depth: int (default 5)
    min_rows: float (default 10.0)
    nbins: int (default 255)
    nbins_cats: int (default 1024)
    nbins_top_level: int (default 1024)
    min_split_improvement: float (default 1e-05)
    sample_rate: float (default 1.0)
    col_sample_rate_per_tree: float (default 1.0)
    score_tree_interval: int (default 5)
    grow_policy: str (default 'depthwise')
    max_leaves: int (default 0)
    calibrate_model: bool (default False)
    calibration_frame: Any (default None)
    calibration_method: str (default 'AUTO')
    learn_rate: float (default 0.1)
    learn_rate_annealing: float (default 1.0)
    distribution: str (default 'AUTO')
    col_sample_rate: float (default 1.0)
    max_abs_leafnode_pred: float (default float("inf"))
    quantile_alpha: float (default 0.5)
    tweedie_power: float (default 1.5)
    huber_alpha: float (default 0.9)
    monotone_constraints: Any (default None)
    """

    _BUILDER = "GBM"

    def __init__(
        self,
        model_id=None,
        response_column=None,
        ignored_columns=(),
        weights_column=None,
        offset_column=None,
        nfolds=0,
        fold_assignment='modulo',
        keep_cross_validation_predictions=False,
        seed=-1,
        max_runtime_secs=0.0,
        stopping_rounds=0,
        stopping_metric='AUTO',
        stopping_tolerance=0.001,
        checkpoint=None,
        export_checkpoints_dir=None,
        ntrees=50,
        max_depth=5,
        min_rows=10.0,
        nbins=255,
        nbins_cats=1024,
        nbins_top_level=1024,
        min_split_improvement=1e-05,
        sample_rate=1.0,
        col_sample_rate_per_tree=1.0,
        score_tree_interval=5,
        grow_policy='depthwise',
        max_leaves=0,
        calibrate_model=False,
        calibration_frame=None,
        calibration_method='AUTO',
        learn_rate=0.1,
        learn_rate_annealing=1.0,
        distribution='AUTO',
        col_sample_rate=1.0,
        max_abs_leafnode_pred=float("inf"),
        quantile_alpha=0.5,
        tweedie_power=1.5,
        huber_alpha=0.9,
        monotone_constraints=None,
    ):
        kw = dict(
            response_column=response_column,
            ignored_columns=ignored_columns,
            weights_column=weights_column,
            offset_column=offset_column,
            nfolds=nfolds,
            fold_assignment=fold_assignment,
            keep_cross_validation_predictions=keep_cross_validation_predictions,
            seed=seed,
            max_runtime_secs=max_runtime_secs,
            stopping_rounds=stopping_rounds,
            stopping_metric=stopping_metric,
            stopping_tolerance=stopping_tolerance,
            checkpoint=checkpoint,
            export_checkpoints_dir=export_checkpoints_dir,
            ntrees=ntrees,
            max_depth=max_depth,
            min_rows=min_rows,
            nbins=nbins,
            nbins_cats=nbins_cats,
            nbins_top_level=nbins_top_level,
            min_split_improvement=min_split_improvement,
            sample_rate=sample_rate,
            col_sample_rate_per_tree=col_sample_rate_per_tree,
            score_tree_interval=score_tree_interval,
            grow_policy=grow_policy,
            max_leaves=max_leaves,
            calibrate_model=calibrate_model,
            calibration_frame=calibration_frame,
            calibration_method=calibration_method,
            learn_rate=learn_rate,
            learn_rate_annealing=learn_rate_annealing,
            distribution=distribution,
            col_sample_rate=col_sample_rate,
            max_abs_leafnode_pred=max_abs_leafnode_pred,
            quantile_alpha=quantile_alpha,
            tweedie_power=tweedie_power,
            huber_alpha=huber_alpha,
            monotone_constraints=monotone_constraints,
        )
        defaults = {
            'response_column': None,
            'ignored_columns': (),
            'weights_column': None,
            'offset_column': None,
            'nfolds': 0,
            'fold_assignment': 'modulo',
            'keep_cross_validation_predictions': False,
            'seed': -1,
            'max_runtime_secs': 0.0,
            'stopping_rounds': 0,
            'stopping_metric': 'AUTO',
            'stopping_tolerance': 0.001,
            'checkpoint': None,
            'export_checkpoints_dir': None,
            'ntrees': 50,
            'max_depth': 5,
            'min_rows': 10.0,
            'nbins': 255,
            'nbins_cats': 1024,
            'nbins_top_level': 1024,
            'min_split_improvement': 1e-05,
            'sample_rate': 1.0,
            'col_sample_rate_per_tree': 1.0,
            'score_tree_interval': 5,
            'grow_policy': 'depthwise',
            'max_leaves': 0,
            'calibrate_model': False,
            'calibration_frame': None,
            'calibration_method': 'AUTO',
            'learn_rate': 0.1,
            'learn_rate_annealing': 1.0,
            'distribution': 'AUTO',
            'col_sample_rate': 1.0,
            'max_abs_leafnode_pred': float("inf"),
            'quantile_alpha': 0.5,
            'tweedie_power': 1.5,
            'huber_alpha': 0.9,
            'monotone_constraints': None,
        }
        kw = {k: v for k, v in kw.items() if v != defaults[k]}
        super().__init__(model_id=model_id, **kw)


class H2OXGBoostEstimator(_EstimatorBase):
    """XGBoost estimator (generated).

    Parameters
    ----------
    response_column: str | None (default None)
    ignored_columns: Sequence[str] (default ())
    weights_column: str | None (default None)
    offset_column: str | None (default None)
    nfolds: int (default 0)
    fold_assignment: str (default 'modulo')
    keep_cross_validation_predictions: bool (default False)
    seed: int (default -1)
    max_runtime_secs: float (default 0.0)
    stopping_rounds: int (default 0)
    stopping_metric: str (default 'AUTO')
    stopping_tolerance: float (default 0.001)
    checkpoint: Any (default None)
    export_checkpoints_dir: str | None (default None)
    ntrees: int (default 50)
    max_depth: int (default 6)
    min_rows: float (default 1.0)
    nbins: int (default 255)
    nbins_cats: int (default 1024)
    nbins_top_level: int (default 1024)
    min_split_improvement: float (default 0.0)
    sample_rate: float (default 1.0)
    col_sample_rate_per_tree: float (default 1.0)
    score_tree_interval: int (default 5)
    grow_policy: str (default 'depthwise')
    max_leaves: int (default 0)
    calibrate_model: bool (default False)
    calibration_frame: Any (default None)
    calibration_method: str (default 'AUTO')
    learn_rate: float (default 0.3)
    learn_rate_annealing: float (default 1.0)
    distribution: str (default 'AUTO')
    col_sample_rate: float (default 1.0)
    max_abs_leafnode_pred: float (default float("inf"))
    quantile_alpha: float (default 0.5)
    tweedie_power: float (default 1.5)
    huber_alpha: float (default 0.9)
    monotone_constraints: Any (default None)
    reg_lambda: float (default 1.0)
    reg_alpha: float (default 0.0)
    tree_method: str (default 'auto')
    booster: str (default 'gbtree')
    scale_pos_weight: float (default 1.0)
    dmatrix_type: str (default 'auto')
    """

    _BUILDER = "XGBoost"

    def __init__(
        self,
        model_id=None,
        response_column=None,
        ignored_columns=(),
        weights_column=None,
        offset_column=None,
        nfolds=0,
        fold_assignment='modulo',
        keep_cross_validation_predictions=False,
        seed=-1,
        max_runtime_secs=0.0,
        stopping_rounds=0,
        stopping_metric='AUTO',
        stopping_tolerance=0.001,
        checkpoint=None,
        export_checkpoints_dir=None,
        ntrees=50,
        max_depth=6,
        min_rows=1.0,
        nbins=255,
        nbins_cats=1024,
        nbins_top_level=1024,
        min_split_improvement=0.0,
        sample_rate=1.0,
        col_sample_rate_per_tree=1.0,
        score_tree_interval=5,
        grow_policy='depthwise',
        max_leaves=0,
        calibrate_model=False,
        calibration_frame=None,
        calibration_method='AUTO',
        learn_rate=0.3,
        learn_rate_annealing=1.0,
        distribution='AUTO',
        col_sample_rate=1.0,
        max_abs_leafnode_pred=float("inf"),
        quantile_alpha=0.5,
        tweedie_power=1.5,
        huber_alpha=0.9,
        monotone_constraints=None,
        reg_lambda=1.0,
        reg_alpha=0.0,
        tree_method='auto',
        booster='gbtree',
        scale_pos_weight=1.0,
        dmatrix_type='auto',
    ):
        kw = dict(
            response_column=response_column,
            ignored_columns=ignored_columns,
            weights_column=weights_column,
            offset_column=offset_column,
            nfolds=nfolds,
            fold_assignment=fold_assignment,
            keep_cross_validation_predictions=keep_cross_validation_predictions,
            seed=seed,
            max_runtime_secs=max_runtime_secs,
            stopping_rounds=stopping_rounds,
            stopping_metric=stopping_metric,
            stopping_tolerance=stopping_tolerance,
            checkpoint=checkpoint,
            export_checkpoints_dir=export_checkpoints_dir,
            ntrees=ntrees,
            max_depth=max_depth,
            min_rows=min_rows,
            nbins=nbins,
            nbins_cats=nbins_cats,
            nbins_top_level=nbins_top_level,
            min_split_improvement=min_split_improvement,
            sample_rate=sample_rate,
            col_sample_rate_per_tree=col_sample_rate_per_tree,
            score_tree_interval=score_tree_interval,
            grow_policy=grow_policy,
            max_leaves=max_leaves,
            calibrate_model=calibrate_model,
            calibration_frame=calibration_frame,
            calibration_method=calibration_method,
            learn_rate=learn_rate,
            learn_rate_annealing=learn_rate_annealing,
            distribution=distribution,
            col_sample_rate=col_sample_rate,
            max_abs_leafnode_pred=max_abs_leafnode_pred,
            quantile_alpha=quantile_alpha,
            tweedie_power=tweedie_power,
            huber_alpha=huber_alpha,
            monotone_constraints=monotone_constraints,
            reg_lambda=reg_lambda,
            reg_alpha=reg_alpha,
            tree_method=tree_method,
            booster=booster,
            scale_pos_weight=scale_pos_weight,
            dmatrix_type=dmatrix_type,
        )
        defaults = {
            'response_column': None,
            'ignored_columns': (),
            'weights_column': None,
            'offset_column': None,
            'nfolds': 0,
            'fold_assignment': 'modulo',
            'keep_cross_validation_predictions': False,
            'seed': -1,
            'max_runtime_secs': 0.0,
            'stopping_rounds': 0,
            'stopping_metric': 'AUTO',
            'stopping_tolerance': 0.001,
            'checkpoint': None,
            'export_checkpoints_dir': None,
            'ntrees': 50,
            'max_depth': 6,
            'min_rows': 1.0,
            'nbins': 255,
            'nbins_cats': 1024,
            'nbins_top_level': 1024,
            'min_split_improvement': 0.0,
            'sample_rate': 1.0,
            'col_sample_rate_per_tree': 1.0,
            'score_tree_interval': 5,
            'grow_policy': 'depthwise',
            'max_leaves': 0,
            'calibrate_model': False,
            'calibration_frame': None,
            'calibration_method': 'AUTO',
            'learn_rate': 0.3,
            'learn_rate_annealing': 1.0,
            'distribution': 'AUTO',
            'col_sample_rate': 1.0,
            'max_abs_leafnode_pred': float("inf"),
            'quantile_alpha': 0.5,
            'tweedie_power': 1.5,
            'huber_alpha': 0.9,
            'monotone_constraints': None,
            'reg_lambda': 1.0,
            'reg_alpha': 0.0,
            'tree_method': 'auto',
            'booster': 'gbtree',
            'scale_pos_weight': 1.0,
            'dmatrix_type': 'auto',
        }
        kw = {k: v for k, v in kw.items() if v != defaults[k]}
        super().__init__(model_id=model_id, **kw)


class H2ORandomForestEstimator(_EstimatorBase):
    """DRF estimator (generated).

    Parameters
    ----------
    response_column: str | None (default None)
    ignored_columns: Sequence[str] (default ())
    weights_column: str | None (default None)
    offset_column: str | None (default None)
    nfolds: int (default 0)
    fold_assignment: str (default 'modulo')
    keep_cross_validation_predictions: bool (default False)
    seed: int (default -1)
    max_runtime_secs: float (default 0.0)
    stopping_rounds: int (default 0)
    stopping_metric: str (default 'AUTO')
    stopping_tolerance: float (default 0.001)
    checkpoint: Any (default None)
    export_checkpoints_dir: str | None (default None)
    ntrees: int (default 50)
    max_depth: int (default 20)
    min_rows: float (default 1.0)
    nbins: int (default 255)
    nbins_cats: int (default 1024)
    nbins_top_level: int (default 1024)
    min_split_improvement: float (default 1e-05)
    sample_rate: float (default 0.632)
    col_sample_rate_per_tree: float (default 1.0)
    score_tree_interval: int (default 5)
    grow_policy: str (default 'depthwise')
    max_leaves: int (default 0)
    calibrate_model: bool (default False)
    calibration_frame: Any (default None)
    calibration_method: str (default 'AUTO')
    mtries: int (default -1)
    binomial_double_trees: bool (default False)
    """

    _BUILDER = "DRF"

    def __init__(
        self,
        model_id=None,
        response_column=None,
        ignored_columns=(),
        weights_column=None,
        offset_column=None,
        nfolds=0,
        fold_assignment='modulo',
        keep_cross_validation_predictions=False,
        seed=-1,
        max_runtime_secs=0.0,
        stopping_rounds=0,
        stopping_metric='AUTO',
        stopping_tolerance=0.001,
        checkpoint=None,
        export_checkpoints_dir=None,
        ntrees=50,
        max_depth=20,
        min_rows=1.0,
        nbins=255,
        nbins_cats=1024,
        nbins_top_level=1024,
        min_split_improvement=1e-05,
        sample_rate=0.632,
        col_sample_rate_per_tree=1.0,
        score_tree_interval=5,
        grow_policy='depthwise',
        max_leaves=0,
        calibrate_model=False,
        calibration_frame=None,
        calibration_method='AUTO',
        mtries=-1,
        binomial_double_trees=False,
    ):
        kw = dict(
            response_column=response_column,
            ignored_columns=ignored_columns,
            weights_column=weights_column,
            offset_column=offset_column,
            nfolds=nfolds,
            fold_assignment=fold_assignment,
            keep_cross_validation_predictions=keep_cross_validation_predictions,
            seed=seed,
            max_runtime_secs=max_runtime_secs,
            stopping_rounds=stopping_rounds,
            stopping_metric=stopping_metric,
            stopping_tolerance=stopping_tolerance,
            checkpoint=checkpoint,
            export_checkpoints_dir=export_checkpoints_dir,
            ntrees=ntrees,
            max_depth=max_depth,
            min_rows=min_rows,
            nbins=nbins,
            nbins_cats=nbins_cats,
            nbins_top_level=nbins_top_level,
            min_split_improvement=min_split_improvement,
            sample_rate=sample_rate,
            col_sample_rate_per_tree=col_sample_rate_per_tree,
            score_tree_interval=score_tree_interval,
            grow_policy=grow_policy,
            max_leaves=max_leaves,
            calibrate_model=calibrate_model,
            calibration_frame=calibration_frame,
            calibration_method=calibration_method,
            mtries=mtries,
            binomial_double_trees=binomial_double_trees,
        )
        defaults = {
            'response_column': None,
            'ignored_columns': (),
            'weights_column': None,
            'offset_column': None,
            'nfolds': 0,
            'fold_assignment': 'modulo',
            'keep_cross_validation_predictions': False,
            'seed': -1,
            'max_runtime_secs': 0.0,
            'stopping_rounds': 0,
            'stopping_metric': 'AUTO',
            'stopping_tolerance': 0.001,
            'checkpoint': None,
            'export_checkpoints_dir': None,
            'ntrees': 50,
            'max_depth': 20,
            'min_rows': 1.0,
            'nbins': 255,
            'nbins_cats': 1024,
            'nbins_top_level': 1024,
            'min_split_improvement': 1e-05,
            'sample_rate': 0.632,
            'col_sample_rate_per_tree': 1.0,
            'score_tree_interval': 5,
            'grow_policy': 'depthwise',
            'max_leaves': 0,
            'calibrate_model': False,
            'calibration_frame': None,
            'calibration_method': 'AUTO',
            'mtries': -1,
            'binomial_double_trees': False,
        }
        kw = {k: v for k, v in kw.items() if v != defaults[k]}
        super().__init__(model_id=model_id, **kw)


class H2OXRTEstimator(_EstimatorBase):
    """XRT estimator (generated).

    Parameters
    ----------
    response_column: str | None (default None)
    ignored_columns: Sequence[str] (default ())
    weights_column: str | None (default None)
    offset_column: str | None (default None)
    nfolds: int (default 0)
    fold_assignment: str (default 'modulo')
    keep_cross_validation_predictions: bool (default False)
    seed: int (default -1)
    max_runtime_secs: float (default 0.0)
    stopping_rounds: int (default 0)
    stopping_metric: str (default 'AUTO')
    stopping_tolerance: float (default 0.001)
    checkpoint: Any (default None)
    export_checkpoints_dir: str | None (default None)
    ntrees: int (default 50)
    max_depth: int (default 20)
    min_rows: float (default 1.0)
    nbins: int (default 255)
    nbins_cats: int (default 1024)
    nbins_top_level: int (default 1024)
    min_split_improvement: float (default 1e-05)
    sample_rate: float (default 0.632)
    col_sample_rate_per_tree: float (default 1.0)
    score_tree_interval: int (default 5)
    grow_policy: str (default 'depthwise')
    max_leaves: int (default 0)
    calibrate_model: bool (default False)
    calibration_frame: Any (default None)
    calibration_method: str (default 'AUTO')
    mtries: int (default -1)
    binomial_double_trees: bool (default False)
    """

    _BUILDER = "XRT"

    def __init__(
        self,
        model_id=None,
        response_column=None,
        ignored_columns=(),
        weights_column=None,
        offset_column=None,
        nfolds=0,
        fold_assignment='modulo',
        keep_cross_validation_predictions=False,
        seed=-1,
        max_runtime_secs=0.0,
        stopping_rounds=0,
        stopping_metric='AUTO',
        stopping_tolerance=0.001,
        checkpoint=None,
        export_checkpoints_dir=None,
        ntrees=50,
        max_depth=20,
        min_rows=1.0,
        nbins=255,
        nbins_cats=1024,
        nbins_top_level=1024,
        min_split_improvement=1e-05,
        sample_rate=0.632,
        col_sample_rate_per_tree=1.0,
        score_tree_interval=5,
        grow_policy='depthwise',
        max_leaves=0,
        calibrate_model=False,
        calibration_frame=None,
        calibration_method='AUTO',
        mtries=-1,
        binomial_double_trees=False,
    ):
        kw = dict(
            response_column=response_column,
            ignored_columns=ignored_columns,
            weights_column=weights_column,
            offset_column=offset_column,
            nfolds=nfolds,
            fold_assignment=fold_assignment,
            keep_cross_validation_predictions=keep_cross_validation_predictions,
            seed=seed,
            max_runtime_secs=max_runtime_secs,
            stopping_rounds=stopping_rounds,
            stopping_metric=stopping_metric,
            stopping_tolerance=stopping_tolerance,
            checkpoint=checkpoint,
            export_checkpoints_dir=export_checkpoints_dir,
            ntrees=ntrees,
            max_depth=max_depth,
            min_rows=min_rows,
            nbins=nbins,
            nbins_cats=nbins_cats,
            nbins_top_level=nbins_top_level,
            min_split_improvement=min_split_improvement,
            sample_rate=sample_rate,
            col_sample_rate_per_tree=col_sample_rate_per_tree,
            score_tree_interval=score_tree_interval,
            grow_policy=grow_policy,
            max_leaves=max_leaves,
            calibrate_model=calibrate_model,
            calibration_frame=calibration_frame,
            calibration_method=calibration_method,
            mtries=mtries,
            binomial_double_trees=binomial_double_trees,
        )
        defaults = {
            'response_column': None,
            'ignored_columns': (),
            'weights_column': None,
            'offset_column': None,
            'nfolds': 0,
            'fold_assignment': 'modulo',
            'keep_cross_validation_predictions': False,
            'seed': -1,
            'max_runtime_secs': 0.0,
            'stopping_rounds': 0,
            'stopping_metric': 'AUTO',
            'stopping_tolerance': 0.001,
            'checkpoint': None,
            'export_checkpoints_dir': None,
            'ntrees': 50,
            'max_depth': 20,
            'min_rows': 1.0,
            'nbins': 255,
            'nbins_cats': 1024,
            'nbins_top_level': 1024,
            'min_split_improvement': 1e-05,
            'sample_rate': 0.632,
            'col_sample_rate_per_tree': 1.0,
            'score_tree_interval': 5,
            'grow_policy': 'depthwise',
            'max_leaves': 0,
            'calibrate_model': False,
            'calibration_frame': None,
            'calibration_method': 'AUTO',
            'mtries': -1,
            'binomial_double_trees': False,
        }
        kw = {k: v for k, v in kw.items() if v != defaults[k]}
        super().__init__(model_id=model_id, **kw)


class H2OGeneralizedLinearEstimator(_EstimatorBase):
    """GLM estimator (generated).

    Parameters
    ----------
    response_column: str | None (default None)
    ignored_columns: Sequence[str] (default ())
    weights_column: str | None (default None)
    offset_column: str | None (default None)
    nfolds: int (default 0)
    fold_assignment: str (default 'modulo')
    keep_cross_validation_predictions: bool (default False)
    seed: int (default -1)
    max_runtime_secs: float (default 0.0)
    stopping_rounds: int (default 0)
    stopping_metric: str (default 'AUTO')
    stopping_tolerance: float (default 0.001)
    checkpoint: Any (default None)
    export_checkpoints_dir: str | None (default None)
    family: str (default 'AUTO')
    link: str (default 'family_default')
    solver: str (default 'AUTO')
    alpha: float | None (default None)
    lambda_: Any (default None)
    lambda_search: bool (default False)
    nlambdas: int (default -1)
    lambda_min_ratio: float (default -1.0)
    standardize: bool (default True)
    intercept: bool (default True)
    max_iterations: int (default -1)
    beta_epsilon: float (default 0.0001)
    objective_epsilon: float (default 1e-06)
    tweedie_variance_power: float (default 0.0)
    tweedie_link_power: float (default 1.0)
    theta: float (default 1e-05)
    missing_values_handling: str (default 'mean_imputation')
    compute_p_values: bool (default False)
    non_negative: bool (default False)
    interactions: Any (default None)
    interaction_pairs: Any (default None)
    hash_buckets: Any (default None)
    """

    _BUILDER = "GLM"

    def __init__(
        self,
        model_id=None,
        response_column=None,
        ignored_columns=(),
        weights_column=None,
        offset_column=None,
        nfolds=0,
        fold_assignment='modulo',
        keep_cross_validation_predictions=False,
        seed=-1,
        max_runtime_secs=0.0,
        stopping_rounds=0,
        stopping_metric='AUTO',
        stopping_tolerance=0.001,
        checkpoint=None,
        export_checkpoints_dir=None,
        family='AUTO',
        link='family_default',
        solver='AUTO',
        alpha=None,
        lambda_=None,
        lambda_search=False,
        nlambdas=-1,
        lambda_min_ratio=-1.0,
        standardize=True,
        intercept=True,
        max_iterations=-1,
        beta_epsilon=0.0001,
        objective_epsilon=1e-06,
        tweedie_variance_power=0.0,
        tweedie_link_power=1.0,
        theta=1e-05,
        missing_values_handling='mean_imputation',
        compute_p_values=False,
        non_negative=False,
        interactions=None,
        interaction_pairs=None,
        hash_buckets=None,
    ):
        kw = dict(
            response_column=response_column,
            ignored_columns=ignored_columns,
            weights_column=weights_column,
            offset_column=offset_column,
            nfolds=nfolds,
            fold_assignment=fold_assignment,
            keep_cross_validation_predictions=keep_cross_validation_predictions,
            seed=seed,
            max_runtime_secs=max_runtime_secs,
            stopping_rounds=stopping_rounds,
            stopping_metric=stopping_metric,
            stopping_tolerance=stopping_tolerance,
            checkpoint=checkpoint,
            export_checkpoints_dir=export_checkpoints_dir,
            family=family,
            link=link,
            solver=solver,
            alpha=alpha,
            lambda_=lambda_,
            lambda_search=lambda_search,
            nlambdas=nlambdas,
            lambda_min_ratio=lambda_min_ratio,
            standardize=standardize,
            intercept=intercept,
            max_iterations=max_iterations,
            beta_epsilon=beta_epsilon,
            objective_epsilon=objective_epsilon,
            tweedie_variance_power=tweedie_variance_power,
            tweedie_link_power=tweedie_link_power,
            theta=theta,
            missing_values_handling=missing_values_handling,
            compute_p_values=compute_p_values,
            non_negative=non_negative,
            interactions=interactions,
            interaction_pairs=interaction_pairs,
            hash_buckets=hash_buckets,
        )
        defaults = {
            'response_column': None,
            'ignored_columns': (),
            'weights_column': None,
            'offset_column': None,
            'nfolds': 0,
            'fold_assignment': 'modulo',
            'keep_cross_validation_predictions': False,
            'seed': -1,
            'max_runtime_secs': 0.0,
            'stopping_rounds': 0,
            'stopping_metric': 'AUTO',
            'stopping_tolerance': 0.001,
            'checkpoint': None,
            'export_checkpoints_dir': None,
            'family': 'AUTO',
            'link': 'family_default',
            'solver': 'AUTO',
            'alpha': None,
            'lambda_': None,
            'lambda_search': False,
            'nlambdas': -1,
            'lambda_min_ratio': -1.0,
            'standardize': True,
            'intercept': True,
            'max_iterations': -1,
            'beta_epsilon': 0.0001,
            'objective_epsilon': 1e-06,
            'tweedie_variance_power': 0.0,
            'tweedie_link_power': 1.0,
            'theta': 1e-05,
            'missing_values_handling': 'mean_imputation',
            'compute_p_values': False,
            'non_negative': False,
            'interactions': None,
            'interaction_pairs': None,
            'hash_buckets': None,
        }
        kw = {k: v for k, v in kw.items() if v != defaults[k]}
        super().__init__(model_id=model_id, **kw)


class H2ODeepLearningEstimator(_EstimatorBase):
    """DeepLearning estimator (generated).

    Parameters
    ----------
    response_column: str | None (default None)
    ignored_columns: Sequence[str] (default ())
    weights_column: str | None (default None)
    offset_column: str | None (default None)
    nfolds: int (default 0)
    fold_assignment: str (default 'modulo')
    keep_cross_validation_predictions: bool (default False)
    seed: int (default -1)
    max_runtime_secs: float (default 0.0)
    stopping_rounds: int (default 0)
    stopping_metric: str (default 'AUTO')
    stopping_tolerance: float (default 0.001)
    checkpoint: Any (default None)
    export_checkpoints_dir: str | None (default None)
    hidden: Sequence[int] (default (200, 200))
    epochs: float (default 10.0)
    activation: str (default 'Rectifier')
    input_dropout_ratio: float (default 0.0)
    hidden_dropout_ratios: Sequence[float] | None (default None)
    l1: float (default 0.0)
    l2: float (default 0.0)
    adaptive_rate: bool (default True)
    rho: float (default 0.99)
    epsilon: float (default 1e-08)
    rate: float (default 0.005)
    rate_decay: float (default 1.0)
    momentum_start: float (default 0.0)
    mini_batch_size: int (default 32)
    standardize: bool (default True)
    loss: str (default 'Automatic')
    reproducible: bool (default True)
    autoencoder: bool (default False)
    hash_buckets: int | None (default None)
    """

    _BUILDER = "DeepLearning"

    def __init__(
        self,
        model_id=None,
        response_column=None,
        ignored_columns=(),
        weights_column=None,
        offset_column=None,
        nfolds=0,
        fold_assignment='modulo',
        keep_cross_validation_predictions=False,
        seed=-1,
        max_runtime_secs=0.0,
        stopping_rounds=0,
        stopping_metric='AUTO',
        stopping_tolerance=0.001,
        checkpoint=None,
        export_checkpoints_dir=None,
        hidden=(200, 200),
        epochs=10.0,
        activation='Rectifier',
        input_dropout_ratio=0.0,
        hidden_dropout_ratios=None,
        l1=0.0,
        l2=0.0,
        adaptive_rate=True,
        rho=0.99,
        epsilon=1e-08,
        rate=0.005,
        rate_decay=1.0,
        momentum_start=0.0,
        mini_batch_size=32,
        standardize=True,
        loss='Automatic',
        reproducible=True,
        autoencoder=False,
        hash_buckets=None,
    ):
        kw = dict(
            response_column=response_column,
            ignored_columns=ignored_columns,
            weights_column=weights_column,
            offset_column=offset_column,
            nfolds=nfolds,
            fold_assignment=fold_assignment,
            keep_cross_validation_predictions=keep_cross_validation_predictions,
            seed=seed,
            max_runtime_secs=max_runtime_secs,
            stopping_rounds=stopping_rounds,
            stopping_metric=stopping_metric,
            stopping_tolerance=stopping_tolerance,
            checkpoint=checkpoint,
            export_checkpoints_dir=export_checkpoints_dir,
            hidden=hidden,
            epochs=epochs,
            activation=activation,
            input_dropout_ratio=input_dropout_ratio,
            hidden_dropout_ratios=hidden_dropout_ratios,
            l1=l1,
            l2=l2,
            adaptive_rate=adaptive_rate,
            rho=rho,
            epsilon=epsilon,
            rate=rate,
            rate_decay=rate_decay,
            momentum_start=momentum_start,
            mini_batch_size=mini_batch_size,
            standardize=standardize,
            loss=loss,
            reproducible=reproducible,
            autoencoder=autoencoder,
            hash_buckets=hash_buckets,
        )
        defaults = {
            'response_column': None,
            'ignored_columns': (),
            'weights_column': None,
            'offset_column': None,
            'nfolds': 0,
            'fold_assignment': 'modulo',
            'keep_cross_validation_predictions': False,
            'seed': -1,
            'max_runtime_secs': 0.0,
            'stopping_rounds': 0,
            'stopping_metric': 'AUTO',
            'stopping_tolerance': 0.001,
            'checkpoint': None,
            'export_checkpoints_dir': None,
            'hidden': (200, 200),
            'epochs': 10.0,
            'activation': 'Rectifier',
            'input_dropout_ratio': 0.0,
            'hidden_dropout_ratios': None,
            'l1': 0.0,
            'l2': 0.0,
            'adaptive_rate': True,
            'rho': 0.99,
            'epsilon': 1e-08,
            'rate': 0.005,
            'rate_decay': 1.0,
            'momentum_start': 0.0,
            'mini_batch_size': 32,
            'standardize': True,
            'loss': 'Automatic',
            'reproducible': True,
            'autoencoder': False,
            'hash_buckets': None,
        }
        kw = {k: v for k, v in kw.items() if v != defaults[k]}
        super().__init__(model_id=model_id, **kw)


class H2OKMeansEstimator(_EstimatorBase):
    """KMeans estimator (generated).

    Parameters
    ----------
    response_column: str | None (default None)
    ignored_columns: Sequence[str] (default ())
    weights_column: str | None (default None)
    offset_column: str | None (default None)
    nfolds: int (default 0)
    fold_assignment: str (default 'modulo')
    keep_cross_validation_predictions: bool (default False)
    seed: int (default -1)
    max_runtime_secs: float (default 0.0)
    stopping_rounds: int (default 0)
    stopping_metric: str (default 'AUTO')
    stopping_tolerance: float (default 0.001)
    checkpoint: Any (default None)
    export_checkpoints_dir: str | None (default None)
    k: int (default 2)
    max_iterations: int (default 10)
    init: str (default 'Furthest')
    standardize: bool (default True)
    estimate_k: bool (default False)
    """

    _BUILDER = "KMeans"

    def __init__(
        self,
        model_id=None,
        response_column=None,
        ignored_columns=(),
        weights_column=None,
        offset_column=None,
        nfolds=0,
        fold_assignment='modulo',
        keep_cross_validation_predictions=False,
        seed=-1,
        max_runtime_secs=0.0,
        stopping_rounds=0,
        stopping_metric='AUTO',
        stopping_tolerance=0.001,
        checkpoint=None,
        export_checkpoints_dir=None,
        k=2,
        max_iterations=10,
        init='Furthest',
        standardize=True,
        estimate_k=False,
    ):
        kw = dict(
            response_column=response_column,
            ignored_columns=ignored_columns,
            weights_column=weights_column,
            offset_column=offset_column,
            nfolds=nfolds,
            fold_assignment=fold_assignment,
            keep_cross_validation_predictions=keep_cross_validation_predictions,
            seed=seed,
            max_runtime_secs=max_runtime_secs,
            stopping_rounds=stopping_rounds,
            stopping_metric=stopping_metric,
            stopping_tolerance=stopping_tolerance,
            checkpoint=checkpoint,
            export_checkpoints_dir=export_checkpoints_dir,
            k=k,
            max_iterations=max_iterations,
            init=init,
            standardize=standardize,
            estimate_k=estimate_k,
        )
        defaults = {
            'response_column': None,
            'ignored_columns': (),
            'weights_column': None,
            'offset_column': None,
            'nfolds': 0,
            'fold_assignment': 'modulo',
            'keep_cross_validation_predictions': False,
            'seed': -1,
            'max_runtime_secs': 0.0,
            'stopping_rounds': 0,
            'stopping_metric': 'AUTO',
            'stopping_tolerance': 0.001,
            'checkpoint': None,
            'export_checkpoints_dir': None,
            'k': 2,
            'max_iterations': 10,
            'init': 'Furthest',
            'standardize': True,
            'estimate_k': False,
        }
        kw = {k: v for k, v in kw.items() if v != defaults[k]}
        super().__init__(model_id=model_id, **kw)


class H2OPrincipalComponentAnalysisEstimator(_EstimatorBase):
    """PCA estimator (generated).

    Parameters
    ----------
    response_column: str | None (default None)
    ignored_columns: Sequence[str] (default ())
    weights_column: str | None (default None)
    offset_column: str | None (default None)
    nfolds: int (default 0)
    fold_assignment: str (default 'modulo')
    keep_cross_validation_predictions: bool (default False)
    seed: int (default -1)
    max_runtime_secs: float (default 0.0)
    stopping_rounds: int (default 0)
    stopping_metric: str (default 'AUTO')
    stopping_tolerance: float (default 0.001)
    checkpoint: Any (default None)
    export_checkpoints_dir: str | None (default None)
    k: int (default 1)
    transform: str (default 'STANDARDIZE')
    pca_method: str (default 'GramSVD')
    use_all_factor_levels: bool (default False)
    """

    _BUILDER = "PCA"

    def __init__(
        self,
        model_id=None,
        response_column=None,
        ignored_columns=(),
        weights_column=None,
        offset_column=None,
        nfolds=0,
        fold_assignment='modulo',
        keep_cross_validation_predictions=False,
        seed=-1,
        max_runtime_secs=0.0,
        stopping_rounds=0,
        stopping_metric='AUTO',
        stopping_tolerance=0.001,
        checkpoint=None,
        export_checkpoints_dir=None,
        k=1,
        transform='STANDARDIZE',
        pca_method='GramSVD',
        use_all_factor_levels=False,
    ):
        kw = dict(
            response_column=response_column,
            ignored_columns=ignored_columns,
            weights_column=weights_column,
            offset_column=offset_column,
            nfolds=nfolds,
            fold_assignment=fold_assignment,
            keep_cross_validation_predictions=keep_cross_validation_predictions,
            seed=seed,
            max_runtime_secs=max_runtime_secs,
            stopping_rounds=stopping_rounds,
            stopping_metric=stopping_metric,
            stopping_tolerance=stopping_tolerance,
            checkpoint=checkpoint,
            export_checkpoints_dir=export_checkpoints_dir,
            k=k,
            transform=transform,
            pca_method=pca_method,
            use_all_factor_levels=use_all_factor_levels,
        )
        defaults = {
            'response_column': None,
            'ignored_columns': (),
            'weights_column': None,
            'offset_column': None,
            'nfolds': 0,
            'fold_assignment': 'modulo',
            'keep_cross_validation_predictions': False,
            'seed': -1,
            'max_runtime_secs': 0.0,
            'stopping_rounds': 0,
            'stopping_metric': 'AUTO',
            'stopping_tolerance': 0.001,
            'checkpoint': None,
            'export_checkpoints_dir': None,
            'k': 1,
            'transform': 'STANDARDIZE',
            'pca_method': 'GramSVD',
            'use_all_factor_levels': False,
        }
        kw = {k: v for k, v in kw.items() if v != defaults[k]}
        super().__init__(model_id=model_id, **kw)


class H2OSingularValueDecompositionEstimator(_EstimatorBase):
    """SVD estimator (generated).

    Parameters
    ----------
    response_column: str | None (default None)
    ignored_columns: Sequence[str] (default ())
    weights_column: str | None (default None)
    offset_column: str | None (default None)
    nfolds: int (default 0)
    fold_assignment: str (default 'modulo')
    keep_cross_validation_predictions: bool (default False)
    seed: int (default -1)
    max_runtime_secs: float (default 0.0)
    stopping_rounds: int (default 0)
    stopping_metric: str (default 'AUTO')
    stopping_tolerance: float (default 0.001)
    checkpoint: Any (default None)
    export_checkpoints_dir: str | None (default None)
    nv: int (default 1)
    transform: str (default 'NONE')
    svd_method: str (default 'Randomized')
    max_iterations: int (default 4)
    """

    _BUILDER = "SVD"

    def __init__(
        self,
        model_id=None,
        response_column=None,
        ignored_columns=(),
        weights_column=None,
        offset_column=None,
        nfolds=0,
        fold_assignment='modulo',
        keep_cross_validation_predictions=False,
        seed=-1,
        max_runtime_secs=0.0,
        stopping_rounds=0,
        stopping_metric='AUTO',
        stopping_tolerance=0.001,
        checkpoint=None,
        export_checkpoints_dir=None,
        nv=1,
        transform='NONE',
        svd_method='Randomized',
        max_iterations=4,
    ):
        kw = dict(
            response_column=response_column,
            ignored_columns=ignored_columns,
            weights_column=weights_column,
            offset_column=offset_column,
            nfolds=nfolds,
            fold_assignment=fold_assignment,
            keep_cross_validation_predictions=keep_cross_validation_predictions,
            seed=seed,
            max_runtime_secs=max_runtime_secs,
            stopping_rounds=stopping_rounds,
            stopping_metric=stopping_metric,
            stopping_tolerance=stopping_tolerance,
            checkpoint=checkpoint,
            export_checkpoints_dir=export_checkpoints_dir,
            nv=nv,
            transform=transform,
            svd_method=svd_method,
            max_iterations=max_iterations,
        )
        defaults = {
            'response_column': None,
            'ignored_columns': (),
            'weights_column': None,
            'offset_column': None,
            'nfolds': 0,
            'fold_assignment': 'modulo',
            'keep_cross_validation_predictions': False,
            'seed': -1,
            'max_runtime_secs': 0.0,
            'stopping_rounds': 0,
            'stopping_metric': 'AUTO',
            'stopping_tolerance': 0.001,
            'checkpoint': None,
            'export_checkpoints_dir': None,
            'nv': 1,
            'transform': 'NONE',
            'svd_method': 'Randomized',
            'max_iterations': 4,
        }
        kw = {k: v for k, v in kw.items() if v != defaults[k]}
        super().__init__(model_id=model_id, **kw)


class H2ONaiveBayesEstimator(_EstimatorBase):
    """NaiveBayes estimator (generated).

    Parameters
    ----------
    response_column: str | None (default None)
    ignored_columns: Sequence[str] (default ())
    weights_column: str | None (default None)
    offset_column: str | None (default None)
    nfolds: int (default 0)
    fold_assignment: str (default 'modulo')
    keep_cross_validation_predictions: bool (default False)
    seed: int (default -1)
    max_runtime_secs: float (default 0.0)
    stopping_rounds: int (default 0)
    stopping_metric: str (default 'AUTO')
    stopping_tolerance: float (default 0.001)
    checkpoint: Any (default None)
    export_checkpoints_dir: str | None (default None)
    laplace: float (default 0.0)
    min_sdev: float (default 0.001)
    eps_sdev: float (default 0.0)
    """

    _BUILDER = "NaiveBayes"

    def __init__(
        self,
        model_id=None,
        response_column=None,
        ignored_columns=(),
        weights_column=None,
        offset_column=None,
        nfolds=0,
        fold_assignment='modulo',
        keep_cross_validation_predictions=False,
        seed=-1,
        max_runtime_secs=0.0,
        stopping_rounds=0,
        stopping_metric='AUTO',
        stopping_tolerance=0.001,
        checkpoint=None,
        export_checkpoints_dir=None,
        laplace=0.0,
        min_sdev=0.001,
        eps_sdev=0.0,
    ):
        kw = dict(
            response_column=response_column,
            ignored_columns=ignored_columns,
            weights_column=weights_column,
            offset_column=offset_column,
            nfolds=nfolds,
            fold_assignment=fold_assignment,
            keep_cross_validation_predictions=keep_cross_validation_predictions,
            seed=seed,
            max_runtime_secs=max_runtime_secs,
            stopping_rounds=stopping_rounds,
            stopping_metric=stopping_metric,
            stopping_tolerance=stopping_tolerance,
            checkpoint=checkpoint,
            export_checkpoints_dir=export_checkpoints_dir,
            laplace=laplace,
            min_sdev=min_sdev,
            eps_sdev=eps_sdev,
        )
        defaults = {
            'response_column': None,
            'ignored_columns': (),
            'weights_column': None,
            'offset_column': None,
            'nfolds': 0,
            'fold_assignment': 'modulo',
            'keep_cross_validation_predictions': False,
            'seed': -1,
            'max_runtime_secs': 0.0,
            'stopping_rounds': 0,
            'stopping_metric': 'AUTO',
            'stopping_tolerance': 0.001,
            'checkpoint': None,
            'export_checkpoints_dir': None,
            'laplace': 0.0,
            'min_sdev': 0.001,
            'eps_sdev': 0.0,
        }
        kw = {k: v for k, v in kw.items() if v != defaults[k]}
        super().__init__(model_id=model_id, **kw)


class H2OIsolationForestEstimator(_EstimatorBase):
    """IsolationForest estimator (generated).

    Parameters
    ----------
    response_column: str | None (default None)
    ignored_columns: Sequence[str] (default ())
    weights_column: str | None (default None)
    offset_column: str | None (default None)
    nfolds: int (default 0)
    fold_assignment: str (default 'modulo')
    keep_cross_validation_predictions: bool (default False)
    seed: int (default -1)
    max_runtime_secs: float (default 0.0)
    stopping_rounds: int (default 0)
    stopping_metric: str (default 'AUTO')
    stopping_tolerance: float (default 0.001)
    checkpoint: Any (default None)
    export_checkpoints_dir: str | None (default None)
    ntrees: int (default 50)
    sample_size: int (default 256)
    max_depth: int (default 8)
    mtries: int (default -1)
    """

    _BUILDER = "IsolationForest"

    def __init__(
        self,
        model_id=None,
        response_column=None,
        ignored_columns=(),
        weights_column=None,
        offset_column=None,
        nfolds=0,
        fold_assignment='modulo',
        keep_cross_validation_predictions=False,
        seed=-1,
        max_runtime_secs=0.0,
        stopping_rounds=0,
        stopping_metric='AUTO',
        stopping_tolerance=0.001,
        checkpoint=None,
        export_checkpoints_dir=None,
        ntrees=50,
        sample_size=256,
        max_depth=8,
        mtries=-1,
    ):
        kw = dict(
            response_column=response_column,
            ignored_columns=ignored_columns,
            weights_column=weights_column,
            offset_column=offset_column,
            nfolds=nfolds,
            fold_assignment=fold_assignment,
            keep_cross_validation_predictions=keep_cross_validation_predictions,
            seed=seed,
            max_runtime_secs=max_runtime_secs,
            stopping_rounds=stopping_rounds,
            stopping_metric=stopping_metric,
            stopping_tolerance=stopping_tolerance,
            checkpoint=checkpoint,
            export_checkpoints_dir=export_checkpoints_dir,
            ntrees=ntrees,
            sample_size=sample_size,
            max_depth=max_depth,
            mtries=mtries,
        )
        defaults = {
            'response_column': None,
            'ignored_columns': (),
            'weights_column': None,
            'offset_column': None,
            'nfolds': 0,
            'fold_assignment': 'modulo',
            'keep_cross_validation_predictions': False,
            'seed': -1,
            'max_runtime_secs': 0.0,
            'stopping_rounds': 0,
            'stopping_metric': 'AUTO',
            'stopping_tolerance': 0.001,
            'checkpoint': None,
            'export_checkpoints_dir': None,
            'ntrees': 50,
            'sample_size': 256,
            'max_depth': 8,
            'mtries': -1,
        }
        kw = {k: v for k, v in kw.items() if v != defaults[k]}
        super().__init__(model_id=model_id, **kw)


class H2OExtendedIsolationForestEstimator(_EstimatorBase):
    """ExtendedIsolationForest estimator (generated).

    Parameters
    ----------
    response_column: str | None (default None)
    ignored_columns: Sequence[str] (default ())
    weights_column: str | None (default None)
    offset_column: str | None (default None)
    nfolds: int (default 0)
    fold_assignment: str (default 'modulo')
    keep_cross_validation_predictions: bool (default False)
    seed: int (default -1)
    max_runtime_secs: float (default 0.0)
    stopping_rounds: int (default 0)
    stopping_metric: str (default 'AUTO')
    stopping_tolerance: float (default 0.001)
    checkpoint: Any (default None)
    export_checkpoints_dir: str | None (default None)
    ntrees: int (default 100)
    sample_size: int (default 256)
    extension_level: int (default -1)
    """

    _BUILDER = "ExtendedIsolationForest"

    def __init__(
        self,
        model_id=None,
        response_column=None,
        ignored_columns=(),
        weights_column=None,
        offset_column=None,
        nfolds=0,
        fold_assignment='modulo',
        keep_cross_validation_predictions=False,
        seed=-1,
        max_runtime_secs=0.0,
        stopping_rounds=0,
        stopping_metric='AUTO',
        stopping_tolerance=0.001,
        checkpoint=None,
        export_checkpoints_dir=None,
        ntrees=100,
        sample_size=256,
        extension_level=-1,
    ):
        kw = dict(
            response_column=response_column,
            ignored_columns=ignored_columns,
            weights_column=weights_column,
            offset_column=offset_column,
            nfolds=nfolds,
            fold_assignment=fold_assignment,
            keep_cross_validation_predictions=keep_cross_validation_predictions,
            seed=seed,
            max_runtime_secs=max_runtime_secs,
            stopping_rounds=stopping_rounds,
            stopping_metric=stopping_metric,
            stopping_tolerance=stopping_tolerance,
            checkpoint=checkpoint,
            export_checkpoints_dir=export_checkpoints_dir,
            ntrees=ntrees,
            sample_size=sample_size,
            extension_level=extension_level,
        )
        defaults = {
            'response_column': None,
            'ignored_columns': (),
            'weights_column': None,
            'offset_column': None,
            'nfolds': 0,
            'fold_assignment': 'modulo',
            'keep_cross_validation_predictions': False,
            'seed': -1,
            'max_runtime_secs': 0.0,
            'stopping_rounds': 0,
            'stopping_metric': 'AUTO',
            'stopping_tolerance': 0.001,
            'checkpoint': None,
            'export_checkpoints_dir': None,
            'ntrees': 100,
            'sample_size': 256,
            'extension_level': -1,
        }
        kw = {k: v for k, v in kw.items() if v != defaults[k]}
        super().__init__(model_id=model_id, **kw)


class H2OGeneralizedLowRankEstimator(_EstimatorBase):
    """GLRM estimator (generated).

    Parameters
    ----------
    response_column: str | None (default None)
    ignored_columns: Sequence[str] (default ())
    weights_column: str | None (default None)
    offset_column: str | None (default None)
    nfolds: int (default 0)
    fold_assignment: str (default 'modulo')
    keep_cross_validation_predictions: bool (default False)
    seed: int (default -1)
    max_runtime_secs: float (default 0.0)
    stopping_rounds: int (default 0)
    stopping_metric: str (default 'AUTO')
    stopping_tolerance: float (default 0.001)
    checkpoint: Any (default None)
    export_checkpoints_dir: str | None (default None)
    k: int (default 2)
    loss: str (default 'Quadratic')
    regularization_x: str (default 'None')
    regularization_y: str (default 'None')
    gamma_x: float (default 0.0)
    gamma_y: float (default 0.0)
    max_iterations: int (default 100)
    init_step_size: float (default 1.0)
    min_step_size: float (default 1e-06)
    tolerance_rel: float (default 1e-07)
    transform: str (default 'STANDARDIZE')
    init: str (default 'SVD')
    """

    _BUILDER = "GLRM"

    def __init__(
        self,
        model_id=None,
        response_column=None,
        ignored_columns=(),
        weights_column=None,
        offset_column=None,
        nfolds=0,
        fold_assignment='modulo',
        keep_cross_validation_predictions=False,
        seed=-1,
        max_runtime_secs=0.0,
        stopping_rounds=0,
        stopping_metric='AUTO',
        stopping_tolerance=0.001,
        checkpoint=None,
        export_checkpoints_dir=None,
        k=2,
        loss='Quadratic',
        regularization_x='None',
        regularization_y='None',
        gamma_x=0.0,
        gamma_y=0.0,
        max_iterations=100,
        init_step_size=1.0,
        min_step_size=1e-06,
        tolerance_rel=1e-07,
        transform='STANDARDIZE',
        init='SVD',
    ):
        kw = dict(
            response_column=response_column,
            ignored_columns=ignored_columns,
            weights_column=weights_column,
            offset_column=offset_column,
            nfolds=nfolds,
            fold_assignment=fold_assignment,
            keep_cross_validation_predictions=keep_cross_validation_predictions,
            seed=seed,
            max_runtime_secs=max_runtime_secs,
            stopping_rounds=stopping_rounds,
            stopping_metric=stopping_metric,
            stopping_tolerance=stopping_tolerance,
            checkpoint=checkpoint,
            export_checkpoints_dir=export_checkpoints_dir,
            k=k,
            loss=loss,
            regularization_x=regularization_x,
            regularization_y=regularization_y,
            gamma_x=gamma_x,
            gamma_y=gamma_y,
            max_iterations=max_iterations,
            init_step_size=init_step_size,
            min_step_size=min_step_size,
            tolerance_rel=tolerance_rel,
            transform=transform,
            init=init,
        )
        defaults = {
            'response_column': None,
            'ignored_columns': (),
            'weights_column': None,
            'offset_column': None,
            'nfolds': 0,
            'fold_assignment': 'modulo',
            'keep_cross_validation_predictions': False,
            'seed': -1,
            'max_runtime_secs': 0.0,
            'stopping_rounds': 0,
            'stopping_metric': 'AUTO',
            'stopping_tolerance': 0.001,
            'checkpoint': None,
            'export_checkpoints_dir': None,
            'k': 2,
            'loss': 'Quadratic',
            'regularization_x': 'None',
            'regularization_y': 'None',
            'gamma_x': 0.0,
            'gamma_y': 0.0,
            'max_iterations': 100,
            'init_step_size': 1.0,
            'min_step_size': 1e-06,
            'tolerance_rel': 1e-07,
            'transform': 'STANDARDIZE',
            'init': 'SVD',
        }
        kw = {k: v for k, v in kw.items() if v != defaults[k]}
        super().__init__(model_id=model_id, **kw)


class H2OCoxProportionalHazardsEstimator(_EstimatorBase):
    """CoxPH estimator (generated).

    Parameters
    ----------
    response_column: str | None (default None)
    ignored_columns: Sequence[str] (default ())
    weights_column: str | None (default None)
    offset_column: str | None (default None)
    nfolds: int (default 0)
    fold_assignment: str (default 'modulo')
    keep_cross_validation_predictions: bool (default False)
    seed: int (default -1)
    max_runtime_secs: float (default 0.0)
    stopping_rounds: int (default 0)
    stopping_metric: str (default 'AUTO')
    stopping_tolerance: float (default 0.001)
    checkpoint: Any (default None)
    export_checkpoints_dir: str | None (default None)
    start_column: str | None (default None)
    stop_column: str | None (default None)
    ties: str (default 'efron')
    max_iterations: int (default 20)
    tolerance: float (default 1e-08)
    """

    _BUILDER = "CoxPH"

    def __init__(
        self,
        model_id=None,
        response_column=None,
        ignored_columns=(),
        weights_column=None,
        offset_column=None,
        nfolds=0,
        fold_assignment='modulo',
        keep_cross_validation_predictions=False,
        seed=-1,
        max_runtime_secs=0.0,
        stopping_rounds=0,
        stopping_metric='AUTO',
        stopping_tolerance=0.001,
        checkpoint=None,
        export_checkpoints_dir=None,
        start_column=None,
        stop_column=None,
        ties='efron',
        max_iterations=20,
        tolerance=1e-08,
    ):
        kw = dict(
            response_column=response_column,
            ignored_columns=ignored_columns,
            weights_column=weights_column,
            offset_column=offset_column,
            nfolds=nfolds,
            fold_assignment=fold_assignment,
            keep_cross_validation_predictions=keep_cross_validation_predictions,
            seed=seed,
            max_runtime_secs=max_runtime_secs,
            stopping_rounds=stopping_rounds,
            stopping_metric=stopping_metric,
            stopping_tolerance=stopping_tolerance,
            checkpoint=checkpoint,
            export_checkpoints_dir=export_checkpoints_dir,
            start_column=start_column,
            stop_column=stop_column,
            ties=ties,
            max_iterations=max_iterations,
            tolerance=tolerance,
        )
        defaults = {
            'response_column': None,
            'ignored_columns': (),
            'weights_column': None,
            'offset_column': None,
            'nfolds': 0,
            'fold_assignment': 'modulo',
            'keep_cross_validation_predictions': False,
            'seed': -1,
            'max_runtime_secs': 0.0,
            'stopping_rounds': 0,
            'stopping_metric': 'AUTO',
            'stopping_tolerance': 0.001,
            'checkpoint': None,
            'export_checkpoints_dir': None,
            'start_column': None,
            'stop_column': None,
            'ties': 'efron',
            'max_iterations': 20,
            'tolerance': 1e-08,
        }
        kw = {k: v for k, v in kw.items() if v != defaults[k]}
        super().__init__(model_id=model_id, **kw)


class H2OIsotonicRegressionEstimator(_EstimatorBase):
    """IsotonicRegression estimator (generated).

    Parameters
    ----------
    response_column: str | None (default None)
    ignored_columns: Sequence[str] (default ())
    weights_column: str | None (default None)
    offset_column: str | None (default None)
    nfolds: int (default 0)
    fold_assignment: str (default 'modulo')
    keep_cross_validation_predictions: bool (default False)
    seed: int (default -1)
    max_runtime_secs: float (default 0.0)
    stopping_rounds: int (default 0)
    stopping_metric: str (default 'AUTO')
    stopping_tolerance: float (default 0.001)
    checkpoint: Any (default None)
    export_checkpoints_dir: str | None (default None)
    out_of_bounds: str (default 'clip')
    """

    _BUILDER = "IsotonicRegression"

    def __init__(
        self,
        model_id=None,
        response_column=None,
        ignored_columns=(),
        weights_column=None,
        offset_column=None,
        nfolds=0,
        fold_assignment='modulo',
        keep_cross_validation_predictions=False,
        seed=-1,
        max_runtime_secs=0.0,
        stopping_rounds=0,
        stopping_metric='AUTO',
        stopping_tolerance=0.001,
        checkpoint=None,
        export_checkpoints_dir=None,
        out_of_bounds='clip',
    ):
        kw = dict(
            response_column=response_column,
            ignored_columns=ignored_columns,
            weights_column=weights_column,
            offset_column=offset_column,
            nfolds=nfolds,
            fold_assignment=fold_assignment,
            keep_cross_validation_predictions=keep_cross_validation_predictions,
            seed=seed,
            max_runtime_secs=max_runtime_secs,
            stopping_rounds=stopping_rounds,
            stopping_metric=stopping_metric,
            stopping_tolerance=stopping_tolerance,
            checkpoint=checkpoint,
            export_checkpoints_dir=export_checkpoints_dir,
            out_of_bounds=out_of_bounds,
        )
        defaults = {
            'response_column': None,
            'ignored_columns': (),
            'weights_column': None,
            'offset_column': None,
            'nfolds': 0,
            'fold_assignment': 'modulo',
            'keep_cross_validation_predictions': False,
            'seed': -1,
            'max_runtime_secs': 0.0,
            'stopping_rounds': 0,
            'stopping_metric': 'AUTO',
            'stopping_tolerance': 0.001,
            'checkpoint': None,
            'export_checkpoints_dir': None,
            'out_of_bounds': 'clip',
        }
        kw = {k: v for k, v in kw.items() if v != defaults[k]}
        super().__init__(model_id=model_id, **kw)


class H2OAdaBoostEstimator(_EstimatorBase):
    """AdaBoost estimator (generated).

    Parameters
    ----------
    response_column: str | None (default None)
    ignored_columns: Sequence[str] (default ())
    weights_column: str | None (default None)
    offset_column: str | None (default None)
    nfolds: int (default 0)
    fold_assignment: str (default 'modulo')
    keep_cross_validation_predictions: bool (default False)
    seed: int (default -1)
    max_runtime_secs: float (default 0.0)
    stopping_rounds: int (default 0)
    stopping_metric: str (default 'AUTO')
    stopping_tolerance: float (default 0.001)
    checkpoint: Any (default None)
    export_checkpoints_dir: str | None (default None)
    ntrees: int (default 50)
    max_depth: int (default 1)
    min_rows: float (default 10.0)
    nbins: int (default 255)
    nbins_cats: int (default 1024)
    nbins_top_level: int (default 1024)
    min_split_improvement: float (default 1e-05)
    sample_rate: float (default 1.0)
    col_sample_rate_per_tree: float (default 1.0)
    score_tree_interval: int (default 5)
    grow_policy: str (default 'depthwise')
    max_leaves: int (default 0)
    calibrate_model: bool (default False)
    calibration_frame: Any (default None)
    calibration_method: str (default 'AUTO')
    nlearners: int (default 50)
    weak_learner: str (default 'DT')
    learn_rate: float (default 0.5)
    """

    _BUILDER = "AdaBoost"

    def __init__(
        self,
        model_id=None,
        response_column=None,
        ignored_columns=(),
        weights_column=None,
        offset_column=None,
        nfolds=0,
        fold_assignment='modulo',
        keep_cross_validation_predictions=False,
        seed=-1,
        max_runtime_secs=0.0,
        stopping_rounds=0,
        stopping_metric='AUTO',
        stopping_tolerance=0.001,
        checkpoint=None,
        export_checkpoints_dir=None,
        ntrees=50,
        max_depth=1,
        min_rows=10.0,
        nbins=255,
        nbins_cats=1024,
        nbins_top_level=1024,
        min_split_improvement=1e-05,
        sample_rate=1.0,
        col_sample_rate_per_tree=1.0,
        score_tree_interval=5,
        grow_policy='depthwise',
        max_leaves=0,
        calibrate_model=False,
        calibration_frame=None,
        calibration_method='AUTO',
        nlearners=50,
        weak_learner='DT',
        learn_rate=0.5,
    ):
        kw = dict(
            response_column=response_column,
            ignored_columns=ignored_columns,
            weights_column=weights_column,
            offset_column=offset_column,
            nfolds=nfolds,
            fold_assignment=fold_assignment,
            keep_cross_validation_predictions=keep_cross_validation_predictions,
            seed=seed,
            max_runtime_secs=max_runtime_secs,
            stopping_rounds=stopping_rounds,
            stopping_metric=stopping_metric,
            stopping_tolerance=stopping_tolerance,
            checkpoint=checkpoint,
            export_checkpoints_dir=export_checkpoints_dir,
            ntrees=ntrees,
            max_depth=max_depth,
            min_rows=min_rows,
            nbins=nbins,
            nbins_cats=nbins_cats,
            nbins_top_level=nbins_top_level,
            min_split_improvement=min_split_improvement,
            sample_rate=sample_rate,
            col_sample_rate_per_tree=col_sample_rate_per_tree,
            score_tree_interval=score_tree_interval,
            grow_policy=grow_policy,
            max_leaves=max_leaves,
            calibrate_model=calibrate_model,
            calibration_frame=calibration_frame,
            calibration_method=calibration_method,
            nlearners=nlearners,
            weak_learner=weak_learner,
            learn_rate=learn_rate,
        )
        defaults = {
            'response_column': None,
            'ignored_columns': (),
            'weights_column': None,
            'offset_column': None,
            'nfolds': 0,
            'fold_assignment': 'modulo',
            'keep_cross_validation_predictions': False,
            'seed': -1,
            'max_runtime_secs': 0.0,
            'stopping_rounds': 0,
            'stopping_metric': 'AUTO',
            'stopping_tolerance': 0.001,
            'checkpoint': None,
            'export_checkpoints_dir': None,
            'ntrees': 50,
            'max_depth': 1,
            'min_rows': 10.0,
            'nbins': 255,
            'nbins_cats': 1024,
            'nbins_top_level': 1024,
            'min_split_improvement': 1e-05,
            'sample_rate': 1.0,
            'col_sample_rate_per_tree': 1.0,
            'score_tree_interval': 5,
            'grow_policy': 'depthwise',
            'max_leaves': 0,
            'calibrate_model': False,
            'calibration_frame': None,
            'calibration_method': 'AUTO',
            'nlearners': 50,
            'weak_learner': 'DT',
            'learn_rate': 0.5,
        }
        kw = {k: v for k, v in kw.items() if v != defaults[k]}
        super().__init__(model_id=model_id, **kw)


class H2ODecisionTreeEstimator(_EstimatorBase):
    """DT estimator (generated).

    Parameters
    ----------
    response_column: str | None (default None)
    ignored_columns: Sequence[str] (default ())
    weights_column: str | None (default None)
    offset_column: str | None (default None)
    nfolds: int (default 0)
    fold_assignment: str (default 'modulo')
    keep_cross_validation_predictions: bool (default False)
    seed: int (default -1)
    max_runtime_secs: float (default 0.0)
    stopping_rounds: int (default 0)
    stopping_metric: str (default 'AUTO')
    stopping_tolerance: float (default 0.001)
    checkpoint: Any (default None)
    export_checkpoints_dir: str | None (default None)
    ntrees: int (default 50)
    max_depth: int (default 10)
    min_rows: float (default 10.0)
    nbins: int (default 255)
    nbins_cats: int (default 1024)
    nbins_top_level: int (default 1024)
    min_split_improvement: float (default 1e-05)
    sample_rate: float (default 1.0)
    col_sample_rate_per_tree: float (default 1.0)
    score_tree_interval: int (default 5)
    grow_policy: str (default 'depthwise')
    max_leaves: int (default 0)
    calibrate_model: bool (default False)
    calibration_frame: Any (default None)
    calibration_method: str (default 'AUTO')
    """

    _BUILDER = "DT"

    def __init__(
        self,
        model_id=None,
        response_column=None,
        ignored_columns=(),
        weights_column=None,
        offset_column=None,
        nfolds=0,
        fold_assignment='modulo',
        keep_cross_validation_predictions=False,
        seed=-1,
        max_runtime_secs=0.0,
        stopping_rounds=0,
        stopping_metric='AUTO',
        stopping_tolerance=0.001,
        checkpoint=None,
        export_checkpoints_dir=None,
        ntrees=50,
        max_depth=10,
        min_rows=10.0,
        nbins=255,
        nbins_cats=1024,
        nbins_top_level=1024,
        min_split_improvement=1e-05,
        sample_rate=1.0,
        col_sample_rate_per_tree=1.0,
        score_tree_interval=5,
        grow_policy='depthwise',
        max_leaves=0,
        calibrate_model=False,
        calibration_frame=None,
        calibration_method='AUTO',
    ):
        kw = dict(
            response_column=response_column,
            ignored_columns=ignored_columns,
            weights_column=weights_column,
            offset_column=offset_column,
            nfolds=nfolds,
            fold_assignment=fold_assignment,
            keep_cross_validation_predictions=keep_cross_validation_predictions,
            seed=seed,
            max_runtime_secs=max_runtime_secs,
            stopping_rounds=stopping_rounds,
            stopping_metric=stopping_metric,
            stopping_tolerance=stopping_tolerance,
            checkpoint=checkpoint,
            export_checkpoints_dir=export_checkpoints_dir,
            ntrees=ntrees,
            max_depth=max_depth,
            min_rows=min_rows,
            nbins=nbins,
            nbins_cats=nbins_cats,
            nbins_top_level=nbins_top_level,
            min_split_improvement=min_split_improvement,
            sample_rate=sample_rate,
            col_sample_rate_per_tree=col_sample_rate_per_tree,
            score_tree_interval=score_tree_interval,
            grow_policy=grow_policy,
            max_leaves=max_leaves,
            calibrate_model=calibrate_model,
            calibration_frame=calibration_frame,
            calibration_method=calibration_method,
        )
        defaults = {
            'response_column': None,
            'ignored_columns': (),
            'weights_column': None,
            'offset_column': None,
            'nfolds': 0,
            'fold_assignment': 'modulo',
            'keep_cross_validation_predictions': False,
            'seed': -1,
            'max_runtime_secs': 0.0,
            'stopping_rounds': 0,
            'stopping_metric': 'AUTO',
            'stopping_tolerance': 0.001,
            'checkpoint': None,
            'export_checkpoints_dir': None,
            'ntrees': 50,
            'max_depth': 10,
            'min_rows': 10.0,
            'nbins': 255,
            'nbins_cats': 1024,
            'nbins_top_level': 1024,
            'min_split_improvement': 1e-05,
            'sample_rate': 1.0,
            'col_sample_rate_per_tree': 1.0,
            'score_tree_interval': 5,
            'grow_policy': 'depthwise',
            'max_leaves': 0,
            'calibrate_model': False,
            'calibration_frame': None,
            'calibration_method': 'AUTO',
        }
        kw = {k: v for k, v in kw.items() if v != defaults[k]}
        super().__init__(model_id=model_id, **kw)


class H2OWord2vecEstimator(_EstimatorBase):
    """Word2Vec estimator (generated).

    Parameters
    ----------
    response_column: str | None (default None)
    ignored_columns: Sequence[str] (default ())
    weights_column: str | None (default None)
    offset_column: str | None (default None)
    nfolds: int (default 0)
    fold_assignment: str (default 'modulo')
    keep_cross_validation_predictions: bool (default False)
    seed: int (default -1)
    max_runtime_secs: float (default 0.0)
    stopping_rounds: int (default 0)
    stopping_metric: str (default 'AUTO')
    stopping_tolerance: float (default 0.001)
    checkpoint: Any (default None)
    export_checkpoints_dir: str | None (default None)
    vec_size: int (default 100)
    window_size: int (default 5)
    min_word_freq: int (default 5)
    epochs: int (default 5)
    learning_rate: float (default 0.025)
    negative_samples: int (default 5)
    sent_sample_rate: float (default 0.001)
    """

    _BUILDER = "Word2Vec"

    def __init__(
        self,
        model_id=None,
        response_column=None,
        ignored_columns=(),
        weights_column=None,
        offset_column=None,
        nfolds=0,
        fold_assignment='modulo',
        keep_cross_validation_predictions=False,
        seed=-1,
        max_runtime_secs=0.0,
        stopping_rounds=0,
        stopping_metric='AUTO',
        stopping_tolerance=0.001,
        checkpoint=None,
        export_checkpoints_dir=None,
        vec_size=100,
        window_size=5,
        min_word_freq=5,
        epochs=5,
        learning_rate=0.025,
        negative_samples=5,
        sent_sample_rate=0.001,
    ):
        kw = dict(
            response_column=response_column,
            ignored_columns=ignored_columns,
            weights_column=weights_column,
            offset_column=offset_column,
            nfolds=nfolds,
            fold_assignment=fold_assignment,
            keep_cross_validation_predictions=keep_cross_validation_predictions,
            seed=seed,
            max_runtime_secs=max_runtime_secs,
            stopping_rounds=stopping_rounds,
            stopping_metric=stopping_metric,
            stopping_tolerance=stopping_tolerance,
            checkpoint=checkpoint,
            export_checkpoints_dir=export_checkpoints_dir,
            vec_size=vec_size,
            window_size=window_size,
            min_word_freq=min_word_freq,
            epochs=epochs,
            learning_rate=learning_rate,
            negative_samples=negative_samples,
            sent_sample_rate=sent_sample_rate,
        )
        defaults = {
            'response_column': None,
            'ignored_columns': (),
            'weights_column': None,
            'offset_column': None,
            'nfolds': 0,
            'fold_assignment': 'modulo',
            'keep_cross_validation_predictions': False,
            'seed': -1,
            'max_runtime_secs': 0.0,
            'stopping_rounds': 0,
            'stopping_metric': 'AUTO',
            'stopping_tolerance': 0.001,
            'checkpoint': None,
            'export_checkpoints_dir': None,
            'vec_size': 100,
            'window_size': 5,
            'min_word_freq': 5,
            'epochs': 5,
            'learning_rate': 0.025,
            'negative_samples': 5,
            'sent_sample_rate': 0.001,
        }
        kw = {k: v for k, v in kw.items() if v != defaults[k]}
        super().__init__(model_id=model_id, **kw)


class H2OStackedEnsembleEstimator(_EstimatorBase):
    """StackedEnsemble estimator (generated).

    Parameters
    ----------
    response_column: str | None (default None)
    ignored_columns: Sequence[str] (default ())
    weights_column: str | None (default None)
    offset_column: str | None (default None)
    nfolds: int (default 0)
    fold_assignment: str (default 'modulo')
    keep_cross_validation_predictions: bool (default False)
    seed: int (default -1)
    max_runtime_secs: float (default 0.0)
    stopping_rounds: int (default 0)
    stopping_metric: str (default 'AUTO')
    stopping_tolerance: float (default 0.001)
    checkpoint: Any (default None)
    export_checkpoints_dir: str | None (default None)
    base_models: Sequence[Any] (default ())
    metalearner_algorithm: str (default 'AUTO')
    metalearner_params: dict (default {})
    metalearner_nfolds: int (default 5)
    """

    _BUILDER = "StackedEnsemble"

    def __init__(
        self,
        model_id=None,
        response_column=None,
        ignored_columns=(),
        weights_column=None,
        offset_column=None,
        nfolds=0,
        fold_assignment='modulo',
        keep_cross_validation_predictions=False,
        seed=-1,
        max_runtime_secs=0.0,
        stopping_rounds=0,
        stopping_metric='AUTO',
        stopping_tolerance=0.001,
        checkpoint=None,
        export_checkpoints_dir=None,
        base_models=(),
        metalearner_algorithm='AUTO',
        metalearner_params={},
        metalearner_nfolds=5,
    ):
        kw = dict(
            response_column=response_column,
            ignored_columns=ignored_columns,
            weights_column=weights_column,
            offset_column=offset_column,
            nfolds=nfolds,
            fold_assignment=fold_assignment,
            keep_cross_validation_predictions=keep_cross_validation_predictions,
            seed=seed,
            max_runtime_secs=max_runtime_secs,
            stopping_rounds=stopping_rounds,
            stopping_metric=stopping_metric,
            stopping_tolerance=stopping_tolerance,
            checkpoint=checkpoint,
            export_checkpoints_dir=export_checkpoints_dir,
            base_models=base_models,
            metalearner_algorithm=metalearner_algorithm,
            metalearner_params=metalearner_params,
            metalearner_nfolds=metalearner_nfolds,
        )
        defaults = {
            'response_column': None,
            'ignored_columns': (),
            'weights_column': None,
            'offset_column': None,
            'nfolds': 0,
            'fold_assignment': 'modulo',
            'keep_cross_validation_predictions': False,
            'seed': -1,
            'max_runtime_secs': 0.0,
            'stopping_rounds': 0,
            'stopping_metric': 'AUTO',
            'stopping_tolerance': 0.001,
            'checkpoint': None,
            'export_checkpoints_dir': None,
            'base_models': (),
            'metalearner_algorithm': 'AUTO',
            'metalearner_params': {},
            'metalearner_nfolds': 5,
        }
        kw = {k: v for k, v in kw.items() if v != defaults[k]}
        super().__init__(model_id=model_id, **kw)


class H2OTargetEncoderEstimator(_EstimatorBase):
    """TargetEncoder estimator (generated).

    Parameters
    ----------
    holdout_type: str (default 'none')
    blending: bool (default False)
    inflection_point: float (default 10.0)
    smoothing: float (default 20.0)
    noise: float (default 0.0)
    fold_column: str | None (default None)
    nfolds: int (default 5)
    seed: int (default -1)
    columns: Sequence[str] (default ())
    """

    _BUILDER = "TargetEncoder"

    def __init__(
        self,
        model_id=None,
        holdout_type='none',
        blending=False,
        inflection_point=10.0,
        smoothing=20.0,
        noise=0.0,
        fold_column=None,
        nfolds=5,
        seed=-1,
        columns=(),
    ):
        kw = dict(
            holdout_type=holdout_type,
            blending=blending,
            inflection_point=inflection_point,
            smoothing=smoothing,
            noise=noise,
            fold_column=fold_column,
            nfolds=nfolds,
            seed=seed,
            columns=columns,
        )
        defaults = {
            'holdout_type': 'none',
            'blending': False,
            'inflection_point': 10.0,
            'smoothing': 20.0,
            'noise': 0.0,
            'fold_column': None,
            'nfolds': 5,
            'seed': -1,
            'columns': (),
        }
        kw = {k: v for k, v in kw.items() if v != defaults[k]}
        super().__init__(model_id=model_id, **kw)


class H2ORuleFitEstimator(_EstimatorBase):
    """RuleFit estimator (generated).

    Parameters
    ----------
    response_column: str | None (default None)
    ignored_columns: Sequence[str] (default ())
    weights_column: str | None (default None)
    offset_column: str | None (default None)
    nfolds: int (default 0)
    fold_assignment: str (default 'modulo')
    keep_cross_validation_predictions: bool (default False)
    seed: int (default -1)
    max_runtime_secs: float (default 0.0)
    stopping_rounds: int (default 0)
    stopping_metric: str (default 'AUTO')
    stopping_tolerance: float (default 0.001)
    checkpoint: Any (default None)
    export_checkpoints_dir: str | None (default None)
    algorithm: str (default 'AUTO')
    min_rule_length: int (default 3)
    max_rule_length: int (default 3)
    max_num_rules: int (default -1)
    model_type: str (default 'rules_and_linear')
    rule_generation_ntrees: int (default 50)
    distribution: str (default 'AUTO')
    lambda_: float | None (default None)
    remove_duplicates: bool (default True)
    """

    _BUILDER = "RuleFit"

    def __init__(
        self,
        model_id=None,
        response_column=None,
        ignored_columns=(),
        weights_column=None,
        offset_column=None,
        nfolds=0,
        fold_assignment='modulo',
        keep_cross_validation_predictions=False,
        seed=-1,
        max_runtime_secs=0.0,
        stopping_rounds=0,
        stopping_metric='AUTO',
        stopping_tolerance=0.001,
        checkpoint=None,
        export_checkpoints_dir=None,
        algorithm='AUTO',
        min_rule_length=3,
        max_rule_length=3,
        max_num_rules=-1,
        model_type='rules_and_linear',
        rule_generation_ntrees=50,
        distribution='AUTO',
        lambda_=None,
        remove_duplicates=True,
    ):
        kw = dict(
            response_column=response_column,
            ignored_columns=ignored_columns,
            weights_column=weights_column,
            offset_column=offset_column,
            nfolds=nfolds,
            fold_assignment=fold_assignment,
            keep_cross_validation_predictions=keep_cross_validation_predictions,
            seed=seed,
            max_runtime_secs=max_runtime_secs,
            stopping_rounds=stopping_rounds,
            stopping_metric=stopping_metric,
            stopping_tolerance=stopping_tolerance,
            checkpoint=checkpoint,
            export_checkpoints_dir=export_checkpoints_dir,
            algorithm=algorithm,
            min_rule_length=min_rule_length,
            max_rule_length=max_rule_length,
            max_num_rules=max_num_rules,
            model_type=model_type,
            rule_generation_ntrees=rule_generation_ntrees,
            distribution=distribution,
            lambda_=lambda_,
            remove_duplicates=remove_duplicates,
        )
        defaults = {
            'response_column': None,
            'ignored_columns': (),
            'weights_column': None,
            'offset_column': None,
            'nfolds': 0,
            'fold_assignment': 'modulo',
            'keep_cross_validation_predictions': False,
            'seed': -1,
            'max_runtime_secs': 0.0,
            'stopping_rounds': 0,
            'stopping_metric': 'AUTO',
            'stopping_tolerance': 0.001,
            'checkpoint': None,
            'export_checkpoints_dir': None,
            'algorithm': 'AUTO',
            'min_rule_length': 3,
            'max_rule_length': 3,
            'max_num_rules': -1,
            'model_type': 'rules_and_linear',
            'rule_generation_ntrees': 50,
            'distribution': 'AUTO',
            'lambda_': None,
            'remove_duplicates': True,
        }
        kw = {k: v for k, v in kw.items() if v != defaults[k]}
        super().__init__(model_id=model_id, **kw)


class H2OUpliftRandomForestEstimator(_EstimatorBase):
    """UpliftDRF estimator (generated).

    Parameters
    ----------
    response_column: str | None (default None)
    ignored_columns: Sequence[str] (default ())
    weights_column: str | None (default None)
    offset_column: str | None (default None)
    nfolds: int (default 0)
    fold_assignment: str (default 'modulo')
    keep_cross_validation_predictions: bool (default False)
    seed: int (default -1)
    max_runtime_secs: float (default 0.0)
    stopping_rounds: int (default 0)
    stopping_metric: str (default 'AUTO')
    stopping_tolerance: float (default 0.001)
    checkpoint: Any (default None)
    export_checkpoints_dir: str | None (default None)
    nbins_cats: int (default 1024)
    treatment_column: str (default 'treatment')
    uplift_metric: str (default 'KL')
    ntrees: int (default 50)
    max_depth: int (default 10)
    min_rows: float (default 10.0)
    mtries: int (default -2)
    sample_rate: float (default 0.632)
    nbins: int (default 255)
    min_split_improvement: float (default 1e-05)
    score_tree_interval: int (default 10)
    """

    _BUILDER = "UpliftDRF"

    def __init__(
        self,
        model_id=None,
        response_column=None,
        ignored_columns=(),
        weights_column=None,
        offset_column=None,
        nfolds=0,
        fold_assignment='modulo',
        keep_cross_validation_predictions=False,
        seed=-1,
        max_runtime_secs=0.0,
        stopping_rounds=0,
        stopping_metric='AUTO',
        stopping_tolerance=0.001,
        checkpoint=None,
        export_checkpoints_dir=None,
        nbins_cats=1024,
        treatment_column='treatment',
        uplift_metric='KL',
        ntrees=50,
        max_depth=10,
        min_rows=10.0,
        mtries=-2,
        sample_rate=0.632,
        nbins=255,
        min_split_improvement=1e-05,
        score_tree_interval=10,
    ):
        kw = dict(
            response_column=response_column,
            ignored_columns=ignored_columns,
            weights_column=weights_column,
            offset_column=offset_column,
            nfolds=nfolds,
            fold_assignment=fold_assignment,
            keep_cross_validation_predictions=keep_cross_validation_predictions,
            seed=seed,
            max_runtime_secs=max_runtime_secs,
            stopping_rounds=stopping_rounds,
            stopping_metric=stopping_metric,
            stopping_tolerance=stopping_tolerance,
            checkpoint=checkpoint,
            export_checkpoints_dir=export_checkpoints_dir,
            nbins_cats=nbins_cats,
            treatment_column=treatment_column,
            uplift_metric=uplift_metric,
            ntrees=ntrees,
            max_depth=max_depth,
            min_rows=min_rows,
            mtries=mtries,
            sample_rate=sample_rate,
            nbins=nbins,
            min_split_improvement=min_split_improvement,
            score_tree_interval=score_tree_interval,
        )
        defaults = {
            'response_column': None,
            'ignored_columns': (),
            'weights_column': None,
            'offset_column': None,
            'nfolds': 0,
            'fold_assignment': 'modulo',
            'keep_cross_validation_predictions': False,
            'seed': -1,
            'max_runtime_secs': 0.0,
            'stopping_rounds': 0,
            'stopping_metric': 'AUTO',
            'stopping_tolerance': 0.001,
            'checkpoint': None,
            'export_checkpoints_dir': None,
            'nbins_cats': 1024,
            'treatment_column': 'treatment',
            'uplift_metric': 'KL',
            'ntrees': 50,
            'max_depth': 10,
            'min_rows': 10.0,
            'mtries': -2,
            'sample_rate': 0.632,
            'nbins': 255,
            'min_split_improvement': 1e-05,
            'score_tree_interval': 10,
        }
        kw = {k: v for k, v in kw.items() if v != defaults[k]}
        super().__init__(model_id=model_id, **kw)


class H2OGeneralizedAdditiveEstimator(_EstimatorBase):
    """GAM estimator (generated).

    Parameters
    ----------
    response_column: str | None (default None)
    ignored_columns: Sequence[str] (default ())
    weights_column: str | None (default None)
    offset_column: str | None (default None)
    nfolds: int (default 0)
    fold_assignment: str (default 'modulo')
    keep_cross_validation_predictions: bool (default False)
    seed: int (default -1)
    max_runtime_secs: float (default 0.0)
    stopping_rounds: int (default 0)
    stopping_metric: str (default 'AUTO')
    stopping_tolerance: float (default 0.001)
    checkpoint: Any (default None)
    export_checkpoints_dir: str | None (default None)
    family: str (default 'AUTO')
    gam_columns: list (default [])
    num_knots: list (default [])
    scale: list (default [])
    bs: list (default [])
    lambda_: float (default 0.0)
    standardize: bool (default True)
    intercept: bool (default True)
    max_iterations: int (default 50)
    beta_epsilon: float (default 1e-06)
    keep_gam_cols: bool (default False)
    """

    _BUILDER = "GAM"

    def __init__(
        self,
        model_id=None,
        response_column=None,
        ignored_columns=(),
        weights_column=None,
        offset_column=None,
        nfolds=0,
        fold_assignment='modulo',
        keep_cross_validation_predictions=False,
        seed=-1,
        max_runtime_secs=0.0,
        stopping_rounds=0,
        stopping_metric='AUTO',
        stopping_tolerance=0.001,
        checkpoint=None,
        export_checkpoints_dir=None,
        family='AUTO',
        gam_columns=[],
        num_knots=[],
        scale=[],
        bs=[],
        lambda_=0.0,
        standardize=True,
        intercept=True,
        max_iterations=50,
        beta_epsilon=1e-06,
        keep_gam_cols=False,
    ):
        kw = dict(
            response_column=response_column,
            ignored_columns=ignored_columns,
            weights_column=weights_column,
            offset_column=offset_column,
            nfolds=nfolds,
            fold_assignment=fold_assignment,
            keep_cross_validation_predictions=keep_cross_validation_predictions,
            seed=seed,
            max_runtime_secs=max_runtime_secs,
            stopping_rounds=stopping_rounds,
            stopping_metric=stopping_metric,
            stopping_tolerance=stopping_tolerance,
            checkpoint=checkpoint,
            export_checkpoints_dir=export_checkpoints_dir,
            family=family,
            gam_columns=gam_columns,
            num_knots=num_knots,
            scale=scale,
            bs=bs,
            lambda_=lambda_,
            standardize=standardize,
            intercept=intercept,
            max_iterations=max_iterations,
            beta_epsilon=beta_epsilon,
            keep_gam_cols=keep_gam_cols,
        )
        defaults = {
            'response_column': None,
            'ignored_columns': (),
            'weights_column': None,
            'offset_column': None,
            'nfolds': 0,
            'fold_assignment': 'modulo',
            'keep_cross_validation_predictions': False,
            'seed': -1,
            'max_runtime_secs': 0.0,
            'stopping_rounds': 0,
            'stopping_metric': 'AUTO',
            'stopping_tolerance': 0.001,
            'checkpoint': None,
            'export_checkpoints_dir': None,
            'family': 'AUTO',
            'gam_columns': [],
            'num_knots': [],
            'scale': [],
            'bs': [],
            'lambda_': 0.0,
            'standardize': True,
            'intercept': True,
            'max_iterations': 50,
            'beta_epsilon': 1e-06,
            'keep_gam_cols': False,
        }
        kw = {k: v for k, v in kw.items() if v != defaults[k]}
        super().__init__(model_id=model_id, **kw)


class H2OModelSelectionEstimator(_EstimatorBase):
    """ModelSelection estimator (generated).

    Parameters
    ----------
    response_column: str | None (default None)
    ignored_columns: Sequence[str] (default ())
    weights_column: str | None (default None)
    offset_column: str | None (default None)
    nfolds: int (default 0)
    fold_assignment: str (default 'modulo')
    keep_cross_validation_predictions: bool (default False)
    seed: int (default -1)
    max_runtime_secs: float (default 0.0)
    stopping_rounds: int (default 0)
    stopping_metric: str (default 'AUTO')
    stopping_tolerance: float (default 0.001)
    checkpoint: Any (default None)
    export_checkpoints_dir: str | None (default None)
    mode: str (default 'maxr')
    family: str (default 'AUTO')
    max_predictor_number: int (default 1)
    min_predictor_number: int (default 1)
    intercept: bool (default True)
    standardize: bool (default True)
    p_values_threshold: float (default 0.0)
    missing_values_handling: str (default 'mean_imputation')
    """

    _BUILDER = "ModelSelection"

    def __init__(
        self,
        model_id=None,
        response_column=None,
        ignored_columns=(),
        weights_column=None,
        offset_column=None,
        nfolds=0,
        fold_assignment='modulo',
        keep_cross_validation_predictions=False,
        seed=-1,
        max_runtime_secs=0.0,
        stopping_rounds=0,
        stopping_metric='AUTO',
        stopping_tolerance=0.001,
        checkpoint=None,
        export_checkpoints_dir=None,
        mode='maxr',
        family='AUTO',
        max_predictor_number=1,
        min_predictor_number=1,
        intercept=True,
        standardize=True,
        p_values_threshold=0.0,
        missing_values_handling='mean_imputation',
    ):
        kw = dict(
            response_column=response_column,
            ignored_columns=ignored_columns,
            weights_column=weights_column,
            offset_column=offset_column,
            nfolds=nfolds,
            fold_assignment=fold_assignment,
            keep_cross_validation_predictions=keep_cross_validation_predictions,
            seed=seed,
            max_runtime_secs=max_runtime_secs,
            stopping_rounds=stopping_rounds,
            stopping_metric=stopping_metric,
            stopping_tolerance=stopping_tolerance,
            checkpoint=checkpoint,
            export_checkpoints_dir=export_checkpoints_dir,
            mode=mode,
            family=family,
            max_predictor_number=max_predictor_number,
            min_predictor_number=min_predictor_number,
            intercept=intercept,
            standardize=standardize,
            p_values_threshold=p_values_threshold,
            missing_values_handling=missing_values_handling,
        )
        defaults = {
            'response_column': None,
            'ignored_columns': (),
            'weights_column': None,
            'offset_column': None,
            'nfolds': 0,
            'fold_assignment': 'modulo',
            'keep_cross_validation_predictions': False,
            'seed': -1,
            'max_runtime_secs': 0.0,
            'stopping_rounds': 0,
            'stopping_metric': 'AUTO',
            'stopping_tolerance': 0.001,
            'checkpoint': None,
            'export_checkpoints_dir': None,
            'mode': 'maxr',
            'family': 'AUTO',
            'max_predictor_number': 1,
            'min_predictor_number': 1,
            'intercept': True,
            'standardize': True,
            'p_values_threshold': 0.0,
            'missing_values_handling': 'mean_imputation',
        }
        kw = {k: v for k, v in kw.items() if v != defaults[k]}
        super().__init__(model_id=model_id, **kw)


class H2OANOVAGLMEstimator(_EstimatorBase):
    """ANOVAGLM estimator (generated).

    Parameters
    ----------
    response_column: str | None (default None)
    ignored_columns: Sequence[str] (default ())
    weights_column: str | None (default None)
    offset_column: str | None (default None)
    nfolds: int (default 0)
    fold_assignment: str (default 'modulo')
    keep_cross_validation_predictions: bool (default False)
    seed: int (default -1)
    max_runtime_secs: float (default 0.0)
    stopping_rounds: int (default 0)
    stopping_metric: str (default 'AUTO')
    stopping_tolerance: float (default 0.001)
    checkpoint: Any (default None)
    export_checkpoints_dir: str | None (default None)
    family: str (default 'AUTO')
    highest_interaction_term: int (default 0)
    lambda_: float (default 0.0)
    standardize: bool (default True)
    """

    _BUILDER = "ANOVAGLM"

    def __init__(
        self,
        model_id=None,
        response_column=None,
        ignored_columns=(),
        weights_column=None,
        offset_column=None,
        nfolds=0,
        fold_assignment='modulo',
        keep_cross_validation_predictions=False,
        seed=-1,
        max_runtime_secs=0.0,
        stopping_rounds=0,
        stopping_metric='AUTO',
        stopping_tolerance=0.001,
        checkpoint=None,
        export_checkpoints_dir=None,
        family='AUTO',
        highest_interaction_term=0,
        lambda_=0.0,
        standardize=True,
    ):
        kw = dict(
            response_column=response_column,
            ignored_columns=ignored_columns,
            weights_column=weights_column,
            offset_column=offset_column,
            nfolds=nfolds,
            fold_assignment=fold_assignment,
            keep_cross_validation_predictions=keep_cross_validation_predictions,
            seed=seed,
            max_runtime_secs=max_runtime_secs,
            stopping_rounds=stopping_rounds,
            stopping_metric=stopping_metric,
            stopping_tolerance=stopping_tolerance,
            checkpoint=checkpoint,
            export_checkpoints_dir=export_checkpoints_dir,
            family=family,
            highest_interaction_term=highest_interaction_term,
            lambda_=lambda_,
            standardize=standardize,
        )
        defaults = {
            'response_column': None,
            'ignored_columns': (),
            'weights_column': None,
            'offset_column': None,
            'nfolds': 0,
            'fold_assignment': 'modulo',
            'keep_cross_validation_predictions': False,
            'seed': -1,
            'max_runtime_secs': 0.0,
            'stopping_rounds': 0,
            'stopping_metric': 'AUTO',
            'stopping_tolerance': 0.001,
            'checkpoint': None,
            'export_checkpoints_dir': None,
            'family': 'AUTO',
            'highest_interaction_term': 0,
            'lambda_': 0.0,
            'standardize': True,
        }
        kw = {k: v for k, v in kw.items() if v != defaults[k]}
        super().__init__(model_id=model_id, **kw)


class H2OAggregatorEstimator(_EstimatorBase):
    """Aggregator estimator (generated).

    Parameters
    ----------
    response_column: str | None (default None)
    ignored_columns: Sequence[str] (default ())
    weights_column: str | None (default None)
    offset_column: str | None (default None)
    nfolds: int (default 0)
    fold_assignment: str (default 'modulo')
    keep_cross_validation_predictions: bool (default False)
    seed: int (default -1)
    max_runtime_secs: float (default 0.0)
    stopping_rounds: int (default 0)
    stopping_metric: str (default 'AUTO')
    stopping_tolerance: float (default 0.001)
    checkpoint: Any (default None)
    export_checkpoints_dir: str | None (default None)
    target_num_exemplars: int (default 5000)
    rel_tol_num_exemplars: float (default 0.5)
    transform: str (default 'NORMALIZE')
    categorical_encoding: str (default 'AUTO')
    """

    _BUILDER = "Aggregator"

    def __init__(
        self,
        model_id=None,
        response_column=None,
        ignored_columns=(),
        weights_column=None,
        offset_column=None,
        nfolds=0,
        fold_assignment='modulo',
        keep_cross_validation_predictions=False,
        seed=-1,
        max_runtime_secs=0.0,
        stopping_rounds=0,
        stopping_metric='AUTO',
        stopping_tolerance=0.001,
        checkpoint=None,
        export_checkpoints_dir=None,
        target_num_exemplars=5000,
        rel_tol_num_exemplars=0.5,
        transform='NORMALIZE',
        categorical_encoding='AUTO',
    ):
        kw = dict(
            response_column=response_column,
            ignored_columns=ignored_columns,
            weights_column=weights_column,
            offset_column=offset_column,
            nfolds=nfolds,
            fold_assignment=fold_assignment,
            keep_cross_validation_predictions=keep_cross_validation_predictions,
            seed=seed,
            max_runtime_secs=max_runtime_secs,
            stopping_rounds=stopping_rounds,
            stopping_metric=stopping_metric,
            stopping_tolerance=stopping_tolerance,
            checkpoint=checkpoint,
            export_checkpoints_dir=export_checkpoints_dir,
            target_num_exemplars=target_num_exemplars,
            rel_tol_num_exemplars=rel_tol_num_exemplars,
            transform=transform,
            categorical_encoding=categorical_encoding,
        )
        defaults = {
            'response_column': None,
            'ignored_columns': (),
            'weights_column': None,
            'offset_column': None,
            'nfolds': 0,
            'fold_assignment': 'modulo',
            'keep_cross_validation_predictions': False,
            'seed': -1,
            'max_runtime_secs': 0.0,
            'stopping_rounds': 0,
            'stopping_metric': 'AUTO',
            'stopping_tolerance': 0.001,
            'checkpoint': None,
            'export_checkpoints_dir': None,
            'target_num_exemplars': 5000,
            'rel_tol_num_exemplars': 0.5,
            'transform': 'NORMALIZE',
            'categorical_encoding': 'AUTO',
        }
        kw = {k: v for k, v in kw.items() if v != defaults[k]}
        super().__init__(model_id=model_id, **kw)


class H2OInfogramEstimator(_EstimatorBase):
    """Infogram estimator (generated).

    Parameters
    ----------
    response_column: str | None (default None)
    ignored_columns: Sequence[str] (default ())
    weights_column: str | None (default None)
    offset_column: str | None (default None)
    nfolds: int (default 0)
    fold_assignment: str (default 'modulo')
    keep_cross_validation_predictions: bool (default False)
    seed: int (default -1)
    max_runtime_secs: float (default 0.0)
    stopping_rounds: int (default 0)
    stopping_metric: str (default 'AUTO')
    stopping_tolerance: float (default 0.001)
    checkpoint: Any (default None)
    export_checkpoints_dir: str | None (default None)
    protected_columns: list (default [])
    safety_index_threshold: float (default 0.1)
    relevance_index_threshold: float (default 0.1)
    total_information_threshold: float (default 0.1)
    net_information_threshold: float (default 0.1)
    ntrees: int (default 20)
    max_depth: int (default 5)
    top_n_features: int (default 50)
    """

    _BUILDER = "Infogram"

    def __init__(
        self,
        model_id=None,
        response_column=None,
        ignored_columns=(),
        weights_column=None,
        offset_column=None,
        nfolds=0,
        fold_assignment='modulo',
        keep_cross_validation_predictions=False,
        seed=-1,
        max_runtime_secs=0.0,
        stopping_rounds=0,
        stopping_metric='AUTO',
        stopping_tolerance=0.001,
        checkpoint=None,
        export_checkpoints_dir=None,
        protected_columns=[],
        safety_index_threshold=0.1,
        relevance_index_threshold=0.1,
        total_information_threshold=0.1,
        net_information_threshold=0.1,
        ntrees=20,
        max_depth=5,
        top_n_features=50,
    ):
        kw = dict(
            response_column=response_column,
            ignored_columns=ignored_columns,
            weights_column=weights_column,
            offset_column=offset_column,
            nfolds=nfolds,
            fold_assignment=fold_assignment,
            keep_cross_validation_predictions=keep_cross_validation_predictions,
            seed=seed,
            max_runtime_secs=max_runtime_secs,
            stopping_rounds=stopping_rounds,
            stopping_metric=stopping_metric,
            stopping_tolerance=stopping_tolerance,
            checkpoint=checkpoint,
            export_checkpoints_dir=export_checkpoints_dir,
            protected_columns=protected_columns,
            safety_index_threshold=safety_index_threshold,
            relevance_index_threshold=relevance_index_threshold,
            total_information_threshold=total_information_threshold,
            net_information_threshold=net_information_threshold,
            ntrees=ntrees,
            max_depth=max_depth,
            top_n_features=top_n_features,
        )
        defaults = {
            'response_column': None,
            'ignored_columns': (),
            'weights_column': None,
            'offset_column': None,
            'nfolds': 0,
            'fold_assignment': 'modulo',
            'keep_cross_validation_predictions': False,
            'seed': -1,
            'max_runtime_secs': 0.0,
            'stopping_rounds': 0,
            'stopping_metric': 'AUTO',
            'stopping_tolerance': 0.001,
            'checkpoint': None,
            'export_checkpoints_dir': None,
            'protected_columns': [],
            'safety_index_threshold': 0.1,
            'relevance_index_threshold': 0.1,
            'total_information_threshold': 0.1,
            'net_information_threshold': 0.1,
            'ntrees': 20,
            'max_depth': 5,
            'top_n_features': 50,
        }
        kw = {k: v for k, v in kw.items() if v != defaults[k]}
        super().__init__(model_id=model_id, **kw)


class H2OSupportVectorMachineEstimator(_EstimatorBase):
    """PSVM estimator (generated).

    Parameters
    ----------
    response_column: str | None (default None)
    ignored_columns: Sequence[str] (default ())
    weights_column: str | None (default None)
    offset_column: str | None (default None)
    nfolds: int (default 0)
    fold_assignment: str (default 'modulo')
    keep_cross_validation_predictions: bool (default False)
    seed: int (default -1)
    max_runtime_secs: float (default 0.0)
    stopping_rounds: int (default 0)
    stopping_metric: str (default 'AUTO')
    stopping_tolerance: float (default 0.001)
    checkpoint: Any (default None)
    export_checkpoints_dir: str | None (default None)
    kernel_type: str (default 'gaussian')
    gamma: float (default -1.0)
    hyper_param: float (default 1.0)
    positive_weight: float (default 1.0)
    negative_weight: float (default 1.0)
    rank_ratio: float (default -1.0)
    max_iterations: int (default 200)
    convergence_tol: float (default 1e-06)
    """

    _BUILDER = "PSVM"

    def __init__(
        self,
        model_id=None,
        response_column=None,
        ignored_columns=(),
        weights_column=None,
        offset_column=None,
        nfolds=0,
        fold_assignment='modulo',
        keep_cross_validation_predictions=False,
        seed=-1,
        max_runtime_secs=0.0,
        stopping_rounds=0,
        stopping_metric='AUTO',
        stopping_tolerance=0.001,
        checkpoint=None,
        export_checkpoints_dir=None,
        kernel_type='gaussian',
        gamma=-1.0,
        hyper_param=1.0,
        positive_weight=1.0,
        negative_weight=1.0,
        rank_ratio=-1.0,
        max_iterations=200,
        convergence_tol=1e-06,
    ):
        kw = dict(
            response_column=response_column,
            ignored_columns=ignored_columns,
            weights_column=weights_column,
            offset_column=offset_column,
            nfolds=nfolds,
            fold_assignment=fold_assignment,
            keep_cross_validation_predictions=keep_cross_validation_predictions,
            seed=seed,
            max_runtime_secs=max_runtime_secs,
            stopping_rounds=stopping_rounds,
            stopping_metric=stopping_metric,
            stopping_tolerance=stopping_tolerance,
            checkpoint=checkpoint,
            export_checkpoints_dir=export_checkpoints_dir,
            kernel_type=kernel_type,
            gamma=gamma,
            hyper_param=hyper_param,
            positive_weight=positive_weight,
            negative_weight=negative_weight,
            rank_ratio=rank_ratio,
            max_iterations=max_iterations,
            convergence_tol=convergence_tol,
        )
        defaults = {
            'response_column': None,
            'ignored_columns': (),
            'weights_column': None,
            'offset_column': None,
            'nfolds': 0,
            'fold_assignment': 'modulo',
            'keep_cross_validation_predictions': False,
            'seed': -1,
            'max_runtime_secs': 0.0,
            'stopping_rounds': 0,
            'stopping_metric': 'AUTO',
            'stopping_tolerance': 0.001,
            'checkpoint': None,
            'export_checkpoints_dir': None,
            'kernel_type': 'gaussian',
            'gamma': -1.0,
            'hyper_param': 1.0,
            'positive_weight': 1.0,
            'negative_weight': 1.0,
            'rank_ratio': -1.0,
            'max_iterations': 200,
            'convergence_tol': 1e-06,
        }
        kw = {k: v for k, v in kw.items() if v != defaults[k]}
        super().__init__(model_id=model_id, **kw)


class H2OHGLMEstimator(_EstimatorBase):
    """HGLM estimator (generated).

    Parameters
    ----------
    response_column: str | None (default None)
    ignored_columns: Sequence[str] (default ())
    weights_column: str | None (default None)
    offset_column: str | None (default None)
    nfolds: int (default 0)
    fold_assignment: str (default 'modulo')
    keep_cross_validation_predictions: bool (default False)
    seed: int (default -1)
    max_runtime_secs: float (default 0.0)
    stopping_rounds: int (default 0)
    stopping_metric: str (default 'AUTO')
    stopping_tolerance: float (default 0.001)
    checkpoint: Any (default None)
    export_checkpoints_dir: str | None (default None)
    random_columns: list (default [])
    method: str (default 'EM')
    max_iterations: int (default 100)
    em_epsilon: float (default 1e-06)
    standardize: bool (default False)
    intercept: bool (default True)
    """

    _BUILDER = "HGLM"

    def __init__(
        self,
        model_id=None,
        response_column=None,
        ignored_columns=(),
        weights_column=None,
        offset_column=None,
        nfolds=0,
        fold_assignment='modulo',
        keep_cross_validation_predictions=False,
        seed=-1,
        max_runtime_secs=0.0,
        stopping_rounds=0,
        stopping_metric='AUTO',
        stopping_tolerance=0.001,
        checkpoint=None,
        export_checkpoints_dir=None,
        random_columns=[],
        method='EM',
        max_iterations=100,
        em_epsilon=1e-06,
        standardize=False,
        intercept=True,
    ):
        kw = dict(
            response_column=response_column,
            ignored_columns=ignored_columns,
            weights_column=weights_column,
            offset_column=offset_column,
            nfolds=nfolds,
            fold_assignment=fold_assignment,
            keep_cross_validation_predictions=keep_cross_validation_predictions,
            seed=seed,
            max_runtime_secs=max_runtime_secs,
            stopping_rounds=stopping_rounds,
            stopping_metric=stopping_metric,
            stopping_tolerance=stopping_tolerance,
            checkpoint=checkpoint,
            export_checkpoints_dir=export_checkpoints_dir,
            random_columns=random_columns,
            method=method,
            max_iterations=max_iterations,
            em_epsilon=em_epsilon,
            standardize=standardize,
            intercept=intercept,
        )
        defaults = {
            'response_column': None,
            'ignored_columns': (),
            'weights_column': None,
            'offset_column': None,
            'nfolds': 0,
            'fold_assignment': 'modulo',
            'keep_cross_validation_predictions': False,
            'seed': -1,
            'max_runtime_secs': 0.0,
            'stopping_rounds': 0,
            'stopping_metric': 'AUTO',
            'stopping_tolerance': 0.001,
            'checkpoint': None,
            'export_checkpoints_dir': None,
            'random_columns': [],
            'method': 'EM',
            'max_iterations': 100,
            'em_epsilon': 1e-06,
            'standardize': False,
            'intercept': True,
        }
        kw = {k: v for k, v in kw.items() if v != defaults[k]}
        super().__init__(model_id=model_id, **kw)


__all__ = [
    'H2OGradientBoostingEstimator',
    'H2OXGBoostEstimator',
    'H2ORandomForestEstimator',
    'H2OXRTEstimator',
    'H2OGeneralizedLinearEstimator',
    'H2ODeepLearningEstimator',
    'H2OKMeansEstimator',
    'H2OPrincipalComponentAnalysisEstimator',
    'H2OSingularValueDecompositionEstimator',
    'H2ONaiveBayesEstimator',
    'H2OIsolationForestEstimator',
    'H2OExtendedIsolationForestEstimator',
    'H2OGeneralizedLowRankEstimator',
    'H2OCoxProportionalHazardsEstimator',
    'H2OIsotonicRegressionEstimator',
    'H2OAdaBoostEstimator',
    'H2ODecisionTreeEstimator',
    'H2OWord2vecEstimator',
    'H2OStackedEnsembleEstimator',
    'H2OTargetEncoderEstimator',
    'H2ORuleFitEstimator',
    'H2OUpliftRandomForestEstimator',
    'H2OGeneralizedAdditiveEstimator',
    'H2OModelSelectionEstimator',
    'H2OANOVAGLMEstimator',
    'H2OAggregatorEstimator',
    'H2OInfogramEstimator',
    'H2OSupportVectorMachineEstimator',
    'H2OHGLMEstimator',
]
