"""h2o-py-style estimator classes — successor of ``h2o-py/h2o/estimators/*``
(generated per-algo classes) [UNVERIFIED upstream paths, SURVEY.md §2.3].

Upstream generates one estimator class per algorithm from the live REST
schemas (the h2o-bindings codegen); here the same thing falls out of the
params dataclasses directly: every estimator accepts its PARAMS_CLS fields
as constructor kwargs, ``train()`` fits and turns the estimator into a
model proxy (metric getters, predict, varimp, MOJO download all delegate),
so an ``h2o-py`` script like

    m = H2OGradientBoostingEstimator(ntrees=50, max_depth=5)
    m.train(x=feats, y="label", training_frame=fr)
    m.auc(); m.predict(test); m.download_mojo("/tmp")

runs against this framework unmodified (module path aside).
"""

from __future__ import annotations

import dataclasses
from typing import Any

from h2o3_tpu import models as _models
import h2o3_tpu.models.export  # noqa: F401 — attaches Model.download_mojo


class _EstimatorBase:
    """Builder + trained-model proxy, the h2o-py estimator contract."""

    _BUILDER: str = ""

    def __init__(self, model_id: str | None = None, **kwargs):
        cls = getattr(_models, self._BUILDER)
        valid = {f.name for f in dataclasses.fields(cls.PARAMS_CLS)}
        valid |= set(getattr(cls, "PARAM_ALIASES", ()))  # e.g. xgboost's eta
        unknown = set(kwargs) - valid
        if unknown:
            raise TypeError(
                f"{type(self).__name__}: unknown parameters {sorted(unknown)}"
            )
        self._kwargs = kwargs
        self._model_id = model_id
        self.model = None

    # -- training -----------------------------------------------------------
    def train(self, x=None, y=None, training_frame=None, validation_frame=None, **kw):
        cls = getattr(_models, self._BUILDER)
        builder = cls(**self._kwargs)
        self.model = builder.train(
            x=x, y=y, training_frame=training_frame,
            validation_frame=validation_frame, **kw,
        )
        return self

    # -- model proxy ---------------------------------------------------------
    @property
    def model_id(self) -> str | None:
        return self.model.key if self.model is not None else self._model_id

    def _m(self):
        if self.model is None:
            raise ValueError("estimator is not trained yet — call train()")
        return self.model

    def predict(self, test_data):
        return self._m().predict(test_data)

    def predict_contributions(self, test_data):
        """Per-feature SHAP contributions + BiasTerm (tree models)."""
        m = self._m()
        if not hasattr(m, "predict_contributions"):
            raise ValueError(f"{m.algo} does not support predict_contributions")
        return m.predict_contributions(test_data)

    def predict_leaf_node_assignment(self, test_data, type="Path"):
        """Terminal leaf per (row, tree, class): 'Path' strings or 'Node_ID'."""
        m = self._m()
        if not hasattr(m, "predict_leaf_node_assignment"):
            raise ValueError(
                f"{m.algo} does not support predict_leaf_node_assignment"
            )
        return m.predict_leaf_node_assignment(test_data, type=type)

    def model_performance(self, test_data=None):
        return self._m().model_performance(test_data)

    def _metric(self, name: str, valid: bool = False, xval: bool = False) -> float:
        m = self._m()
        mm = (
            m.cross_validation_metrics if xval
            else m.validation_metrics if valid
            else m.training_metrics
        )
        return mm.value(name) if mm is not None else float("nan")

    def auc(self, valid=False, xval=False):
        return self._metric("auc", valid, xval)

    def logloss(self, valid=False, xval=False):
        return self._metric("logloss", valid, xval)

    def rmse(self, valid=False, xval=False):
        return self._metric("rmse", valid, xval)

    def mse(self, valid=False, xval=False):
        return self._metric("mse", valid, xval)

    def mae(self, valid=False, xval=False):
        return self._metric("mae", valid, xval)

    def r2(self, valid=False, xval=False):
        return self._metric("r2", valid, xval)

    def _mm(self, valid=False, xval=False):
        m = self._m()
        return (m.cross_validation_metrics if xval
                else m.validation_metrics if valid else m.training_metrics)

    def gains_lift(self, valid=False, xval=False):
        mm = self._mm(valid, xval)
        return mm.gains_lift() if mm is not None else None

    def kolmogorov_smirnov(self, valid=False, xval=False):
        return self._metric("ks", valid, xval)

    def varimp_plot(self, num_of_features=10, save=None):
        from h2o3_tpu import explain as _ex

        return _ex.varimp_plot(self._m(), num_of_features, save)

    def learning_curve_plot(self, save=None):
        from h2o3_tpu import explain as _ex

        return _ex.learning_curve_plot(self._m(), save)

    def varimp(self, use_pandas: bool = False):
        vi = self._m().varimp() if hasattr(self._m(), "varimp") else None
        if use_pandas and vi is not None:
            import pandas as pd

            return pd.DataFrame(vi)
        return vi

    def download_mojo(self, path: str = ".") -> str:
        import os

        p = path
        if os.path.isdir(p):
            p = os.path.join(p, f"{self._m().key}.zip")
        return self._m().download_mojo(p)

    def save_mojo(self, path: str = ".") -> str:
        return self.download_mojo(path)

    def __getattr__(self, item) -> Any:
        # anything else (scoring_history, output, cv_models, ...) delegates
        # to the trained model
        model = self.__dict__.get("model")
        if model is not None and hasattr(model, item):
            return getattr(model, item)
        raise AttributeError(item)


def _make(name: str, builder: str):
    est = type(name, (_EstimatorBase,), {"_BUILDER": builder, "__doc__":
        f"h2o-py style estimator for the {builder} builder."})
    globals()[name] = est
    return name


__all__ = [
    _make("H2OGradientBoostingEstimator", "GBM"),
    _make("H2ORandomForestEstimator", "DRF"),
    _make("H2OXGBoostEstimator", "XGBoost"),  # xgboost param surface on the hist engine
    _make("H2OGeneralizedLinearEstimator", "GLM"),
    _make("H2ODeepLearningEstimator", "DeepLearning"),
    _make("H2OKMeansEstimator", "KMeans"),
    _make("H2OPrincipalComponentAnalysisEstimator", "PCA"),
    _make("H2OSingularValueDecompositionEstimator", "SVD"),
    _make("H2ONaiveBayesEstimator", "NaiveBayes"),
    _make("H2OIsolationForestEstimator", "IsolationForest"),
    _make("H2OExtendedIsolationForestEstimator", "ExtendedIsolationForest"),
    _make("H2OGeneralizedLowRankEstimator", "GLRM"),
    _make("H2OCoxProportionalHazardsEstimator", "CoxPH"),
    _make("H2OIsotonicRegressionEstimator", "IsotonicRegression"),
    _make("H2OAdaBoostEstimator", "AdaBoost"),
    _make("H2ODecisionTreeEstimator", "DT"),
    _make("H2OWord2vecEstimator", "Word2Vec"),
    _make("H2OStackedEnsembleEstimator", "StackedEnsemble"),
    _make("H2OTargetEncoderEstimator", "TargetEncoder"),
    _make("H2ORuleFitEstimator", "RuleFit"),
    _make("H2OUpliftRandomForestEstimator", "UpliftDRF"),
    _make("H2OGeneralizedAdditiveEstimator", "GAM"),
    _make("H2OModelSelectionEstimator", "ModelSelection"),
    _make("H2OANOVAGLMEstimator", "ANOVAGLM"),
    _make("H2OAggregatorEstimator", "Aggregator"),
    _make("H2OInfogramEstimator", "Infogram"),
    _make("H2OSupportVectorMachineEstimator", "PSVM"),
    _make("H2OHGLMEstimator", "HGLM"),
    "H2OAutoEncoderEstimator",
]


class H2OAutoEncoderEstimator(_EstimatorBase):
    """Upstream's autoencoder estimator: DeepLearning with autoencoder=True
    forced; train() needs no y. ``anomaly(frame)`` gives per-row
    reconstruction MSE."""

    _BUILDER = "DeepLearning"

    def __init__(self, model_id=None, **kwargs):
        kwargs["autoencoder"] = True
        super().__init__(model_id=model_id, **kwargs)

    def anomaly(self, test_data):
        return self._m().anomaly(test_data)

