"""The compute fabric — successor of ``water.MRTask`` [UNVERIFIED upstream path].

H2O's crown-jewel primitive is ``new MyTask().doAll(frame)``: the task is
RPC-cloned to every node holding chunks, each node fork-join maps over its
chunks, and partial results are reduced pairwise up a log-tree back to the
caller (SURVEY.md §2.1, §3.3).

The TPU-native equivalent collapses all of that into compiled SPMD:

- *clone to every node* → ``shard_map`` over the ``"rows"`` mesh axis (the
  program IS resident on every device; no serialization/Weaver needed),
- *map over local chunks* → the body runs on the device's row shard, fused
  and tiled by XLA,
- *log-tree reduce over the wire* → ``lax.psum`` over ICI.

Two idioms are offered:

1. :func:`map_reduce` — the explicit MRTask analog: a per-shard ``map_fn``
   whose outputs are psum-reduced. Use when you want the reduction stated in
   the program (histograms, Gram matrices, metric accumulators).
2. Plain ``jit`` on row-sharded arrays — for elementwise/new-column work XLA
   inserts collectives automatically; prefer it where no reduce exists
   (the ``map_only`` helper).
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import time

import jax
from jax.sharding import PartitionSpec as P

from h2o3_tpu.parallel.mesh import get_mesh, row_pspec, shard_map
from h2o3_tpu.utils import metrics

_DISPATCHES = metrics.counter(
    "mrtask_dispatches_total", "MRTask-style SPMD dispatches, by kind")
_DISPATCH_SECONDS = metrics.counter(
    "mrtask_dispatch_seconds_total",
    "host wall seconds inside MRTask dispatch calls (includes compiles on "
    "cache misses; device work completes asynchronously)")


# Compiled-task cache keyed on (map_fn, arity, mesh, reduce?) — the analog of
# H2O reusing a DTask class across doAll calls. Without it every invocation
# would retrace + recompile (seconds per call in a driver loop).
_cache: dict = {}


def _compiled(map_fn: Callable, nargs: int, mesh, reduce: bool):
    key = (map_fn, nargs, mesh, reduce)
    fn = _cache.get(key)
    if fn is not None:
        return fn

    rspec = row_pspec(mesh)
    if reduce:
        from h2o3_tpu.ops.collectives import exact_psum

        def body(*shards):
            out = map_fn(*shards)
            # staged rows-then-cols on a 2-D mesh — same float grouping as
            # every other exact reduce (ops/collectives.exact_psum)
            return jax.tree.map(lambda a: exact_psum(a, mesh), out)

        out_specs = P()
    else:
        body = map_fn
        out_specs = rspec

    fn = jax.jit(
        shard_map(
            body,
            mesh=mesh,
            in_specs=tuple(rspec for _ in range(nargs)),
            out_specs=out_specs,
            check_vma=False,
        )
    )
    _cache[key] = fn
    return fn


def map_reduce(map_fn: Callable, *cols, mesh=None):
    """Run ``map_fn(*shard_cols) -> pytree`` on each row shard and psum-reduce.

    ``map_fn`` receives the device-local slice of each column (leading axis =
    rows/shards) and returns a pytree of accumulators with row-free shapes;
    the pytree is summed across the mesh. This is semantically
    ``MRTask.map`` + an associative-``+`` ``MRTask.reduce``. Pass a stable
    (module-level) ``map_fn`` so the compilation cache hits.
    """
    _DISPATCHES.inc(kind="map_reduce")
    t0 = time.perf_counter()
    out = _compiled(map_fn, len(cols), mesh or get_mesh(), True)(*cols)
    _DISPATCH_SECONDS.inc(time.perf_counter() - t0)
    return out


def map_only(map_fn: Callable, *cols, mesh=None):
    """Row-local map producing new row-aligned columns (no reduce).

    Equivalent of an MRTask that only writes ``NewChunk`` outputs: the result
    keeps the row sharding of the inputs.
    """
    _DISPATCHES.inc(kind="map_only")
    t0 = time.perf_counter()
    out = _compiled(map_fn, len(cols), mesh or get_mesh(), False)(*cols)
    _DISPATCH_SECONDS.inc(time.perf_counter() - t0)
    return out
