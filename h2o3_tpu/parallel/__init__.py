from h2o3_tpu.parallel.mesh import (
    COLS_AXIS,
    ROWS_AXIS,
    get_mesh,
    set_mesh,
    make_mesh_2d,
    row_sharding,
    replicated_sharding,
    n_shards,
    n_col_shards,
    shard_rows,
    pad_to_shards,
)
from h2o3_tpu.parallel.mrtask import map_reduce, map_only

__all__ = [
    "COLS_AXIS",
    "ROWS_AXIS",
    "get_mesh",
    "set_mesh",
    "make_mesh_2d",
    "row_sharding",
    "replicated_sharding",
    "n_shards",
    "n_col_shards",
    "shard_rows",
    "pad_to_shards",
    "map_reduce",
    "map_only",
]
