from h2o3_tpu.parallel.mesh import (
    ROWS_AXIS,
    get_mesh,
    set_mesh,
    row_sharding,
    replicated_sharding,
    n_shards,
    shard_rows,
    pad_to_shards,
)
from h2o3_tpu.parallel.mrtask import map_reduce, map_only

__all__ = [
    "ROWS_AXIS",
    "get_mesh",
    "set_mesh",
    "row_sharding",
    "replicated_sharding",
    "n_shards",
    "shard_rows",
    "pad_to_shards",
    "map_reduce",
    "map_only",
]
