"""Device mesh management — the cluster-topology successor of H2O's cloud.

H2O forms a static cloud of JVM nodes (``water.H2O.CLOUD`` / ``water.Paxos``
[UNVERIFIED upstream paths, SURVEY.md §0]) and homes chunk *i* of every Vec on
a fixed node. Here the "cloud" is a ``jax.sharding.Mesh`` over all addressable
devices: every column of a Frame is sharded the same way along rows, which
reproduces H2O's aligned chunk layout (row-local compute) by construction.
Like the H2O cloud, the mesh is static once created.

Two mesh generations coexist (ISSUE 14):

- **1-D** ``("rows",)`` — the historical default: ONE device axis shards
  frame rows for the data-parallel phases AND re-shards histogram columns
  for the split phase (PR 5). Every pre-pod program ever compiled ran on
  this shape; it stays the single-process default bit-for-bit.
- **2-D** ``("rows", "cols")`` — the pod shape (``H2O3_TPU_MESH_ROWS``):
  frame rows shard over BOTH axes (cols-major, so row-shard *i* sits on
  ``jax.devices()[i]`` exactly like the 1-D mesh and per-process shard
  ranges stay contiguous — sharded ingest depends on it), histogram/Gram/
  gradient reductions run stage-1 EXACT over the ``rows`` axis (contiguous
  device runs — the ICI/intra-host level when rows = local device count)
  and stage-2 over ``cols`` (the DCN hop), and the split phase's column
  blocks shard over ``cols`` ONLY — row sharding and the PR-5/PR-6 column
  blocks finally compose instead of sharing one axis. This is
  hierarchy-aware reduction placement (arXiv:2110.10548) expressed as mesh
  structure; the PR-9 quantized lane then compresses exactly the cross-
  group stage (ops/collectives.py).

Multi-host (the H2O multi-node analog) rides the same mesh:
``jax.distributed`` initializes the coordination service
(cluster/multihost.py bootstraps it from env/args) and ``jax.devices()``
spans hosts; XLA collectives ride ICI within a slice and DCN across slices.
Nothing in the algorithm layer knows about hosts — exactly as H2O
algorithms never touch ``water.RPC`` directly.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

ROWS_AXIS = "rows"
COLS_AXIS = "cols"

# jax moved shard_map to the top level (and renamed check_rep -> check_vma)
# after 0.4.x; every shard_map in this codebase goes through this one shim so
# the whole stack runs on either API generation.
if hasattr(jax, "shard_map"):

    def shard_map(f, mesh, in_specs, out_specs, check_vma=True):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
else:  # jax 0.4.x: experimental module, kwarg named check_rep
    from jax.experimental.shard_map import shard_map as _shard_map_exp

    def shard_map(f, mesh, in_specs, out_specs, check_vma=True):
        return _shard_map_exp(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=check_vma,
        )


_mesh: Mesh | None = None

# Monotonic topology generation (ISSUE 17, elastic recovery): bumped ONLY by
# :func:`reform_mesh` — the supervised-recovery reshape point. Vec device
# arrays and host mirrors record the epoch they were padded/placed under and
# lazily re-pad + re-shard when it moves (frame/frame.py); ChunkStores refuse
# to serve blocks planned under a dead topology (frame/chunkstore.py).
# ``set_mesh`` deliberately does NOT bump it: tests swap sub-meshes and
# manage their frames' placement themselves — that contract stays bit-exact.
_mesh_epoch: int = 0


def mesh_epoch() -> int:
    """The current topology generation (see ``_mesh_epoch``)."""
    return _mesh_epoch


def set_mesh(mesh: Mesh | None) -> None:
    global _mesh
    _mesh = mesh


def _mesh_rows_knob(n_dev: int) -> int:
    """Resolved ``H2O3_TPU_MESH_ROWS``: how many ROWS-axis groups the
    process mesh factors into. 0/1/'' = the legacy 1-D mesh; 'auto' = each
    process's local device count when the cloud spans >1 process (rows =
    the ICI/intra-host level, cols = hosts) and 1-D otherwise; an integer
    forces that rows size (the CPU-proxy A/B + test lane — e.g. '2' makes
    the 8-device proxy a 2x4 pod stand-in). A value that does not divide
    the device count falls back to 1-D with a warning rather than refusing
    to form a cloud."""
    from h2o3_tpu import config
    from h2o3_tpu.utils.log import Log

    v = config.get("H2O3_TPU_MESH_ROWS").strip().lower()
    if v in ("", "0", "1", "false"):
        return 1
    if v == "auto":
        try:
            if jax.process_count() <= 1:
                return 1
            r = jax.local_device_count()
        except RuntimeError:
            return 1
    else:
        r = int(v)
    if r <= 1:
        return 1
    if n_dev % r != 0:
        Log.warn(
            f"H2O3_TPU_MESH_ROWS={v} does not divide the {n_dev}-device "
            "cloud; using the 1-D rows mesh")
        return 1
    return r


def make_mesh_2d(rows: int, cols: int, devices=None) -> Mesh:
    """A rows×cols mesh over the first rows*cols ``devices``. The device
    grid is filled COLS-MAJOR (``mesh.devices[r, c] = devices[c*rows + r]``)
    so each ``rows``-axis group is a contiguous run of the device list —
    the intra-host/ICI level when rows = local device count — and so the
    cols-major row-shard order (:func:`row_pspec`) lands shard *i* on
    ``devices[i]``, identical to the 1-D mesh's layout."""
    devices = np.array(jax.devices() if devices is None else devices)
    grid = devices[: rows * cols].reshape(cols, rows).T
    return Mesh(grid, (ROWS_AXIS, COLS_AXIS))


def get_mesh() -> Mesh:
    """The process-wide mesh, created lazily over all devices: 1-D
    ``("rows",)`` by default, rows×cols under ``H2O3_TPU_MESH_ROWS``."""
    global _mesh
    if _mesh is None:
        devices = np.array(jax.devices())
        r = _mesh_rows_knob(devices.size)
        if r > 1:
            _mesh = make_mesh_2d(r, devices.size // r, devices)
        else:
            _mesh = Mesh(devices, (ROWS_AXIS,))
    return _mesh


def is_2d(mesh: Mesh | None = None) -> bool:
    """Whether the mesh is the rows×cols pod shape (vs the legacy 1-D)."""
    return COLS_AXIS in (mesh or get_mesh()).axis_names


def row_axes(mesh: Mesh | None = None) -> tuple:
    """Mesh axes sharding FRAME ROWS, in shard-major order. 2-D meshes
    shard rows over BOTH axes, cols-major: shard index c*R + r sits on
    mesh.devices[r, c] = jax.devices()[c*R + r] — the same shard→device map
    as the 1-D mesh, which keeps per-process shard ranges contiguous (the
    sharded-ingest and make_array_from_callback contracts)."""
    m = mesh or get_mesh()
    return (COLS_AXIS, ROWS_AXIS) if is_2d(m) else (ROWS_AXIS,)


def row_pspec(mesh: Mesh | None = None, ndim: int = 1, axis: int = 0) -> P:
    """PartitionSpec sharding dimension ``axis`` of an ``ndim``-dim array
    over the frame-row axes (replicated elsewhere)."""
    ax = row_axes(mesh)
    spec = [None] * ndim
    spec[axis] = ax if len(ax) > 1 else ax[0]
    return P(*spec)


def col_axis_name(mesh: Mesh | None = None) -> str:
    """The mesh axis COLUMN BLOCKS shard over: ``cols`` on a 2-D mesh,
    the one shared ``rows`` axis on the legacy 1-D mesh."""
    return COLS_AXIS if is_2d(mesh) else ROWS_AXIS


def n_col_shards(mesh: Mesh | None = None) -> int:
    """How many column blocks the split/Gram/DL scatter phase deals."""
    m = mesh or get_mesh()
    return m.shape[col_axis_name(m)]


def n_row_groups(mesh: Mesh | None = None) -> int:
    """Width of the stage-1 exact reduce (the ``rows`` axis of a 2-D mesh;
    1 on the legacy mesh — no separate stage exists there)."""
    m = mesh or get_mesh()
    return m.shape[ROWS_AXIS] if is_2d(m) else 1


def plan_mesh(n_devices: int, n_hosts: int = 1) -> tuple[int, int]:
    """Re-plan the rows×cols factorization for a (possibly changed)
    formation of ``n_devices`` devices over ``n_hosts`` hosts — the elastic
    half of :func:`_mesh_rows_knob`. ``H2O3_TPU_MESH_ROWS=auto`` resolves
    against the NEW formation (rows = devices per host when the formation
    spans >1 host), not the boot-time one; an explicit integer is honored
    when it divides the new device count and falls back to 1-D with a
    warning otherwise; ''/'0'/'1' stays 1-D. Returns ``(rows, cols)`` with
    ``rows == 1`` meaning the legacy 1-D ``("rows",)`` mesh."""
    from h2o3_tpu import config
    from h2o3_tpu.utils.log import Log

    n_devices = int(n_devices)
    v = config.get("H2O3_TPU_MESH_ROWS").strip().lower()
    if v in ("", "0", "1", "false"):
        return 1, n_devices
    if v == "auto":
        if n_hosts <= 1:
            return 1, n_devices
        r = max(n_devices // max(n_hosts, 1), 1)
    else:
        r = int(v)
    if r <= 1:
        return 1, n_devices
    if n_devices % r != 0:
        Log.warn(
            f"H2O3_TPU_MESH_ROWS={v} does not divide the re-planned "
            f"{n_devices}-device formation; using the 1-D rows mesh")
        return 1, n_devices
    return r, n_devices // r


def reform_mesh(shape: tuple[int, int] | None = None) -> Mesh:
    """Drop the cached mesh and rebuild over the devices that are live NOW —
    the supervised-recovery reform step (cluster/recovery.py). The new Mesh
    is a distinct object, so every program cache keyed through
    :func:`mesh_key` (which includes ``id(mesh)``) misses and retraces
    against the re-formed topology instead of replaying a program compiled
    for the dead one.

    Elastic recovery (ISSUE 17): ``shape=(rows, cols)`` re-forms onto an
    EXPLICIT topology over the first ``rows*cols`` live devices — ``rows ==
    1`` builds the legacy 1-D ``("rows",)`` mesh, ``rows > 1`` the 2-D pod
    mesh — which is how a job resumes on fewer (or more) devices than it
    started with. ``shape=None`` keeps the same-topology behavior: re-plan
    from the knob over every live device. Either way the topology epoch
    (:func:`mesh_epoch`) ticks, so Vec placements and host mirrors padded
    for the old shard counts re-derive lazily on next touch."""
    global _mesh, _mesh_epoch
    _mesh_epoch += 1
    if shape is None:
        _mesh = None
        return get_mesh()
    rows, cols = int(shape[0]), int(shape[1])
    if rows < 1 or cols < 1:
        raise ValueError(f"reform_mesh: bad shape {shape!r}")
    devices = np.array(jax.devices())
    if rows * cols > devices.size:
        raise ValueError(
            f"reform_mesh: shape {rows}x{cols} needs {rows * cols} devices "
            f"but only {devices.size} are live")
    if rows > 1:
        _mesh = make_mesh_2d(rows, cols, devices)
    else:
        _mesh = Mesh(devices[:cols], (ROWS_AXIS,))
    return _mesh


def n_shards() -> int:
    """Row-shard count: the TOTAL device count of the process mesh (frame
    rows always shard over every device, on either mesh generation)."""
    return int(get_mesh().devices.size)


def row_sharding(mesh: Mesh | None = None) -> NamedSharding:
    """Sharding for a row-partitioned column (1-D or leading-row N-D array)."""
    m = mesh or get_mesh()
    return NamedSharding(m, row_pspec(m))


# ---------------------------------------------------------------------------
# column-block layout (the sharded split pipeline, shared_tree/_split_scan):
# on the legacy 1-D mesh the SAME device axis that shards rows for the
# histogram pass re-shards the histogram's column axis for the split phase;
# on the 2-D pod mesh column blocks shard over the ``cols`` axis ONLY (the
# ``rows`` axis finished its exact stage-1 reduce first), so device (r, c)
# owns the contiguous block of columns [c*Cb, (c+1)*Cb). Contiguity is
# load-bearing either way: lowest-block-then-lowest-local-index IS
# lowest-global-index, which is what lets the per-block winner merge
# reproduce jnp.argmax tie-breaking exactly.


def pad_cols_to_shards(n_cols: int, mesh: Mesh | None = None) -> int:
    """Smallest multiple of the column-block count >= n_cols (and >= the
    block count, so C < blocks still gives every block real shape — the
    extra blocks hold only zero-histogram padding columns that can never
    win a split)."""
    m = n_col_shards(mesh)
    return max(m, -(-n_cols // m) * m)


def col_block_size(n_cols: int, mesh: Mesh | None = None) -> int:
    """Columns per device block under :func:`pad_cols_to_shards` padding."""
    return pad_cols_to_shards(n_cols, mesh) // n_col_shards(mesh)


def col_block_spec(axis: int = 0, mesh: Mesh | None = None) -> P:
    """PartitionSpec sharding dimension ``axis`` over the column blocks."""
    return P(*((None,) * axis + (col_axis_name(mesh),)))


def block_quantum(mesh: Mesh | None = None, multiple: int = 8) -> int:
    """Smallest row count a streamed chunk can carry: one f32 sublane tile
    (``multiple``) per shard. Every out-of-core row block is a multiple of
    this, so a block slices into equal per-device shards with the same
    tiling-friendly layout the resident ``pad_to_shards`` rows get — and a
    block-sized sub-frame's device arrays divide the mesh exactly with no
    extra padding rows (padding would perturb block-local reductions)."""
    return int((mesh or get_mesh()).devices.size) * multiple


def stream_block_rows(npad: int, budget_rows: int, mesh: Mesh | None = None) -> int:
    """Row count per out-of-core chunk: the largest multiple of
    :func:`block_quantum` that fits ``budget_rows`` (the HBM-window share one
    resident block may occupy), clamped to [quantum, npad]. A window too
    small for even one quantum block still streams — the device footprint is
    then one quantum block, the documented floor (frame/chunkstore.py)."""
    q = block_quantum(mesh)
    b = max(q, (max(budget_rows, 0) // q) * q)
    return min(b, max(npad, q))


def pad_flat_to_shards(n: int, mesh: Mesh | None = None) -> int:
    """Smallest multiple of the SCATTER-block count >= max(n, blocks) — the
    padded length of a FLATTENED parameter/gradient vector so the gradient
    ``psum_scatter`` (over the col-block axis: the whole 1-D mesh, or the
    ``cols`` axis of a 2-D one after its exact rows stage) deals every
    block an equal slice (the DL sharded-gradient lane; padded tail entries
    are zero and their zero gradients keep elementwise optimizer state zero
    forever)."""
    m = n_col_shards(mesh)
    return max(m, -(-n // m) * m)


def mesh_key() -> tuple:
    """Program-cache component for the process mesh: traced collectives and
    shard_map block layouts bake the mesh in at trace time, so a program
    compiled for one mesh must never serve another (tests swap 1/2/8-device
    sub-meshes within one process). Shared by the tree, GLM and DL program
    caches. Includes the collective-lane key (ops/collectives.quant_key):
    the quant/hierarchy knobs change the traced reduce structure, so every
    program cache picks them up through this one chokepoint."""
    from h2o3_tpu.ops.collectives import quant_key

    m = get_mesh()
    shape = tuple(m.shape.items()) if hasattr(m, "shape") else ()
    return (shape, id(m), quant_key())


# ---------------------------------------------------------------------------
# hierarchical reduction placement (ops/collectives.py two-stage lane): the
# 1-D rows axis factors into contiguous INNER groups (the cheap interconnect
# level — ICI within a slice/host) × an OUTER level (the expensive hop —
# DCN across hosts). This module owns the mesh-level resolution so a future
# 2D mesh (ROADMAP item 2) changes exactly one function.


def hier_inner(n_dev: int | None = None) -> int:
    """Inner-group size of the two-stage hierarchical reduction WITHIN the
    collective lane's one reduce axis, or 0 for single-stage.
    ``H2O3_TPU_COLLECTIVE_HIER``: 'auto' groups by the devices each process
    contributes (the ICI/DCN boundary) when the mesh spans >1 process and
    the factorization is clean; an integer forces that inner size (the A/B
    + test lane — e.g. '2' splits the 8-device CPU proxy into 4 fake-ICI
    pairs); '0'/'' disables. On a 2-D rows×cols mesh the MESH is the
    hierarchy — stage 1 is the exact ``rows``-axis psum the reduce wrappers
    already run (ops/collectives.py) — so 'auto' resolves to 0 there and
    only an explicit integer further subdivides the ``cols`` lane."""
    from h2o3_tpu import config

    if n_dev is None:
        n_dev = n_col_shards()
    v = config.get("H2O3_TPU_COLLECTIVE_HIER").strip().lower()
    if v in ("0", "", "false"):
        return 0
    if v == "auto":
        if is_2d():
            return 0  # the rows axis already reduces the ICI level exactly
        try:
            inner = jax.local_device_count()
        except RuntimeError:
            return 0
        if jax.process_count() <= 1:
            return 0
    else:
        inner = int(v)
    if 1 < inner < n_dev and n_dev % inner == 0:
        return inner
    return 0


def hier_groups(n_dev: int, inner: int) -> tuple[list, list]:
    """(inner_groups, cross_groups) for :func:`hier_inner`'s factorization:
    inner groups are contiguous runs of ``inner`` device indices (stage-1
    exact reduce); cross groups tie position ``j`` of every inner group
    together (stage-2 quantized exchange). Ascending order inside every
    group is load-bearing: grouped collectives exchange by listed position,
    and the lane's chunk remap assumes position == outer index."""
    outer = n_dev // inner
    inner_groups = [
        list(range(g * inner, (g + 1) * inner)) for g in range(outer)
    ]
    cross_groups = [
        [g * inner + j for g in range(outer)] for j in range(inner)
    ]
    return inner_groups, cross_groups


def replicated_sharding(mesh: Mesh | None = None) -> NamedSharding:
    return NamedSharding(mesh or get_mesh(), P())


_ROW_BUCKET_MIN = 1 << 16  # frames below this keep exact shard-aligned pads


def _bucket_rows(n: int) -> int:
    """Row-count bucket: round up to 5 significant bits (steps ≤ 3.125%).

    Part of the shape-bucket ladder (H2O3_TPU_SHAPE_BUCKETS): AutoML/grid
    runs over frames of near-identical row counts (CV folds, sampled
    frames, train/valid splits) then share one compiled program per
    algorithm instead of recompiling per exact row count. Every padded row
    is real device work on every build, so the ladder is deliberately
    fine — ≤3.1% pad buys the collapse of the ±few-percent row-count
    variation that actually occurs; a coarser ladder charged the 1M-row
    headline ~5% forever. Only frames above _ROW_BUCKET_MIN bucket —
    small-frame compiles are cheap and exact shapes keep tests/debug
    predictable."""
    from h2o3_tpu import config

    if n <= _ROW_BUCKET_MIN or not config.get_bool("H2O3_TPU_SHAPE_BUCKETS"):
        return n
    step = 1 << (n.bit_length() - 5)
    return -(-n // step) * step


def pad_to_shards(n: int, mesh: Mesh | None = None, multiple: int = 8) -> int:
    """Padded row count: a multiple of (shards * multiple) ≥ n, bucketed to
    the row ladder above _ROW_BUCKET_MIN (see :func:`_bucket_rows`).

    The per-shard row count is kept a multiple of 8 (f32 sublane tile) so
    device layouts stay tiling-friendly.
    """
    m = int((mesh or get_mesh()).devices.size)
    block = m * multiple
    return max(block, ((_bucket_rows(n) + block - 1) // block) * block)


def shard_rows(arr, mesh: Mesh | None = None):
    """Place a host array onto the mesh, sharded along the leading axis.

    On a multi-process cloud the mesh spans non-addressable devices; each
    process holds the same full host array (SPMD command replication,
    cluster/spmd.py) and contributes its addressable shards."""
    sh = row_sharding(mesh)
    if jax.process_count() > 1:
        a = np.asarray(arr)
        return jax.make_array_from_callback(a.shape, sh, lambda idx: a[idx])
    return jax.device_put(arr, sh)


def pull_to_host(x):
    """Full host value of a (possibly cross-process) device array.

    Fully-addressable arrays device_get directly. Cross-process sharded
    arrays allgather — a COLLECTIVE: on a multi-process cloud this must run
    inside replicated execution (every rank calls it at the same point),
    which the spmd command layer guarantees for build/parse/predict."""
    if getattr(x, "is_fully_addressable", True):
        return jax.device_get(x)
    from h2o3_tpu.cluster import spmd

    if not spmd.in_replicated():
        # an allgather entered by one rank alone deadlocks the cloud — fail
        # fast instead (coordinator-only REST paths must stay off sharded
        # data or go through spmd.run)
        raise RuntimeError(
            "host pull of a cross-process array outside replicated "
            "execution (multi-process cloud): route through spmd.run"
        )
    from jax.experimental import multihost_utils as mh

    return np.asarray(mh.process_allgather(x, tiled=True))
