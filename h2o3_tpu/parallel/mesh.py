"""Device mesh management — the cluster-topology successor of H2O's cloud.

H2O forms a static cloud of JVM nodes (``water.H2O.CLOUD`` / ``water.Paxos``
[UNVERIFIED upstream paths, SURVEY.md §0]) and homes chunk *i* of every Vec on
a fixed node. Here the "cloud" is a 1-D ``jax.sharding.Mesh`` over all
addressable devices with a single ``"rows"`` axis: every column of a Frame is
sharded the same way along rows, which reproduces H2O's aligned chunk layout
(row-local compute) by construction. Like the H2O cloud, the mesh is static
once created.

Multi-host (the H2O multi-node analog) rides the same mesh: ``jax.distributed``
initializes the coordination service and ``jax.devices()`` spans hosts; XLA
collectives ride ICI within a slice and DCN across slices. Nothing in the
algorithm layer knows about hosts — exactly as H2O algorithms never touch
``water.RPC`` directly.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

ROWS_AXIS = "rows"

_mesh: Mesh | None = None


def set_mesh(mesh: Mesh | None) -> None:
    global _mesh
    _mesh = mesh


def get_mesh() -> Mesh:
    """The process-wide mesh, created lazily over all devices."""
    global _mesh
    if _mesh is None:
        devices = np.array(jax.devices())
        _mesh = Mesh(devices, (ROWS_AXIS,))
    return _mesh


def n_shards() -> int:
    return get_mesh().shape[ROWS_AXIS]


def row_sharding(mesh: Mesh | None = None) -> NamedSharding:
    """Sharding for a row-partitioned column (1-D or leading-row N-D array)."""
    return NamedSharding(mesh or get_mesh(), P(ROWS_AXIS))


def replicated_sharding(mesh: Mesh | None = None) -> NamedSharding:
    return NamedSharding(mesh or get_mesh(), P())


def pad_to_shards(n: int, mesh: Mesh | None = None, multiple: int = 8) -> int:
    """Padded row count: a multiple of (shards * multiple) ≥ n.

    The per-shard row count is kept a multiple of 8 (f32 sublane tile) so
    device layouts stay tiling-friendly.
    """
    m = (mesh or get_mesh()).shape[ROWS_AXIS]
    block = m * multiple
    return max(block, ((n + block - 1) // block) * block)


def shard_rows(arr, mesh: Mesh | None = None):
    """Place a host array onto the mesh, sharded along the leading axis."""
    return jax.device_put(arr, row_sharding(mesh))
