"""Device mesh management — the cluster-topology successor of H2O's cloud.

H2O forms a static cloud of JVM nodes (``water.H2O.CLOUD`` / ``water.Paxos``
[UNVERIFIED upstream paths, SURVEY.md §0]) and homes chunk *i* of every Vec on
a fixed node. Here the "cloud" is a 1-D ``jax.sharding.Mesh`` over all
addressable devices with a single ``"rows"`` axis: every column of a Frame is
sharded the same way along rows, which reproduces H2O's aligned chunk layout
(row-local compute) by construction. Like the H2O cloud, the mesh is static
once created.

Multi-host (the H2O multi-node analog) rides the same mesh: ``jax.distributed``
initializes the coordination service and ``jax.devices()`` spans hosts; XLA
collectives ride ICI within a slice and DCN across slices. Nothing in the
algorithm layer knows about hosts — exactly as H2O algorithms never touch
``water.RPC`` directly.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

ROWS_AXIS = "rows"

# jax moved shard_map to the top level (and renamed check_rep -> check_vma)
# after 0.4.x; every shard_map in this codebase goes through this one shim so
# the whole stack runs on either API generation.
if hasattr(jax, "shard_map"):

    def shard_map(f, mesh, in_specs, out_specs, check_vma=True):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
else:  # jax 0.4.x: experimental module, kwarg named check_rep
    from jax.experimental.shard_map import shard_map as _shard_map_exp

    def shard_map(f, mesh, in_specs, out_specs, check_vma=True):
        return _shard_map_exp(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=check_vma,
        )


_mesh: Mesh | None = None


def set_mesh(mesh: Mesh | None) -> None:
    global _mesh
    _mesh = mesh


def get_mesh() -> Mesh:
    """The process-wide mesh, created lazily over all devices."""
    global _mesh
    if _mesh is None:
        devices = np.array(jax.devices())
        _mesh = Mesh(devices, (ROWS_AXIS,))
    return _mesh


def reform_mesh() -> Mesh:
    """Drop the cached mesh and rebuild over the devices that are live NOW —
    the supervised-recovery reform step (cluster/recovery.py). The new Mesh
    is a distinct object, so every program cache keyed through
    :func:`mesh_key` (which includes ``id(mesh)``) misses and retraces
    against the re-formed topology instead of replaying a program compiled
    for the dead one."""
    global _mesh
    _mesh = None
    return get_mesh()


def n_shards() -> int:
    return get_mesh().shape[ROWS_AXIS]


def row_sharding(mesh: Mesh | None = None) -> NamedSharding:
    """Sharding for a row-partitioned column (1-D or leading-row N-D array)."""
    return NamedSharding(mesh or get_mesh(), P(ROWS_AXIS))


# ---------------------------------------------------------------------------
# column-block layout (the sharded split pipeline, shared_tree/_split_scan):
# the SAME 1-D device axis that shards rows for the histogram pass re-shards
# the histogram's column axis for the split phase — device d owns the
# contiguous block of columns [d*Cb, (d+1)*Cb). Contiguity is load-bearing:
# lowest-block-then-lowest-local-index IS lowest-global-index, which is what
# lets the per-block winner merge reproduce jnp.argmax tie-breaking exactly.


def pad_cols_to_shards(n_cols: int, mesh: Mesh | None = None) -> int:
    """Smallest multiple of the shard count >= n_cols (and >= shard count,
    so C < P still gives every device a block — the extra blocks hold only
    zero-histogram padding columns that can never win a split)."""
    m = (mesh or get_mesh()).shape[ROWS_AXIS]
    return max(m, -(-n_cols // m) * m)


def col_block_size(n_cols: int, mesh: Mesh | None = None) -> int:
    """Columns per device block under :func:`pad_cols_to_shards` padding."""
    return pad_cols_to_shards(n_cols, mesh) // (mesh or get_mesh()).shape[ROWS_AXIS]


def col_block_spec(axis: int = 0) -> P:
    """PartitionSpec sharding dimension ``axis`` over the column blocks."""
    return P(*((None,) * axis + (ROWS_AXIS,)))


def block_quantum(mesh: Mesh | None = None, multiple: int = 8) -> int:
    """Smallest row count a streamed chunk can carry: one f32 sublane tile
    (``multiple``) per shard. Every out-of-core row block is a multiple of
    this, so a block slices into equal per-device shards with the same
    tiling-friendly layout the resident ``pad_to_shards`` rows get — and a
    block-sized sub-frame's device arrays divide the mesh exactly with no
    extra padding rows (padding would perturb block-local reductions)."""
    return (mesh or get_mesh()).shape[ROWS_AXIS] * multiple


def stream_block_rows(npad: int, budget_rows: int, mesh: Mesh | None = None) -> int:
    """Row count per out-of-core chunk: the largest multiple of
    :func:`block_quantum` that fits ``budget_rows`` (the HBM-window share one
    resident block may occupy), clamped to [quantum, npad]. A window too
    small for even one quantum block still streams — the device footprint is
    then one quantum block, the documented floor (frame/chunkstore.py)."""
    q = block_quantum(mesh)
    b = max(q, (max(budget_rows, 0) // q) * q)
    return min(b, max(npad, q))


def pad_flat_to_shards(n: int, mesh: Mesh | None = None) -> int:
    """Smallest multiple of the shard count >= max(n, shard count) — the
    padded length of a FLATTENED parameter/gradient vector so a
    ``psum_scatter`` over the rows axis deals every device an equal slice
    (the DL sharded-gradient lane; padded tail entries are zero and their
    zero gradients keep elementwise optimizer state zero forever)."""
    m = (mesh or get_mesh()).shape[ROWS_AXIS]
    return max(m, -(-n // m) * m)


def mesh_key() -> tuple:
    """Program-cache component for the process mesh: traced collectives and
    shard_map block layouts bake the mesh in at trace time, so a program
    compiled for one mesh must never serve another (tests swap 1/2/8-device
    sub-meshes within one process). Shared by the tree, GLM and DL program
    caches. Includes the collective-lane key (ops/collectives.quant_key):
    the quant/hierarchy knobs change the traced reduce structure, so every
    program cache picks them up through this one chokepoint."""
    from h2o3_tpu.ops.collectives import quant_key

    m = get_mesh()
    return (
        m.shape[ROWS_AXIS] if hasattr(m, "shape") else 0, id(m), quant_key()
    )


# ---------------------------------------------------------------------------
# hierarchical reduction placement (ops/collectives.py two-stage lane): the
# 1-D rows axis factors into contiguous INNER groups (the cheap interconnect
# level — ICI within a slice/host) × an OUTER level (the expensive hop —
# DCN across hosts). This module owns the mesh-level resolution so a future
# 2D mesh (ROADMAP item 2) changes exactly one function.


def hier_inner(n_dev: int | None = None) -> int:
    """Inner-group size of the two-stage hierarchical reduction, or 0 for
    single-stage. ``H2O3_TPU_COLLECTIVE_HIER``: 'auto' groups by the
    devices each process contributes (the ICI/DCN boundary) when the mesh
    spans >1 process and the factorization is clean; an integer forces that
    inner size (the A/B + test lane — e.g. '2' splits the 8-device CPU
    proxy into 4 fake-ICI pairs); '0'/'' disables."""
    from h2o3_tpu import config

    if n_dev is None:
        n_dev = n_shards()
    v = config.get("H2O3_TPU_COLLECTIVE_HIER").strip().lower()
    if v in ("0", "", "false"):
        return 0
    if v == "auto":
        try:
            inner = jax.local_device_count()
        except RuntimeError:
            return 0
        if jax.process_count() <= 1:
            return 0
    else:
        inner = int(v)
    if 1 < inner < n_dev and n_dev % inner == 0:
        return inner
    return 0


def hier_groups(n_dev: int, inner: int) -> tuple[list, list]:
    """(inner_groups, cross_groups) for :func:`hier_inner`'s factorization:
    inner groups are contiguous runs of ``inner`` device indices (stage-1
    exact reduce); cross groups tie position ``j`` of every inner group
    together (stage-2 quantized exchange). Ascending order inside every
    group is load-bearing: grouped collectives exchange by listed position,
    and the lane's chunk remap assumes position == outer index."""
    outer = n_dev // inner
    inner_groups = [
        list(range(g * inner, (g + 1) * inner)) for g in range(outer)
    ]
    cross_groups = [
        [g * inner + j for g in range(outer)] for j in range(inner)
    ]
    return inner_groups, cross_groups


def replicated_sharding(mesh: Mesh | None = None) -> NamedSharding:
    return NamedSharding(mesh or get_mesh(), P())


_ROW_BUCKET_MIN = 1 << 16  # frames below this keep exact shard-aligned pads


def _bucket_rows(n: int) -> int:
    """Row-count bucket: round up to 5 significant bits (steps ≤ 3.125%).

    Part of the shape-bucket ladder (H2O3_TPU_SHAPE_BUCKETS): AutoML/grid
    runs over frames of near-identical row counts (CV folds, sampled
    frames, train/valid splits) then share one compiled program per
    algorithm instead of recompiling per exact row count. Every padded row
    is real device work on every build, so the ladder is deliberately
    fine — ≤3.1% pad buys the collapse of the ±few-percent row-count
    variation that actually occurs; a coarser ladder charged the 1M-row
    headline ~5% forever. Only frames above _ROW_BUCKET_MIN bucket —
    small-frame compiles are cheap and exact shapes keep tests/debug
    predictable."""
    from h2o3_tpu import config

    if n <= _ROW_BUCKET_MIN or not config.get_bool("H2O3_TPU_SHAPE_BUCKETS"):
        return n
    step = 1 << (n.bit_length() - 5)
    return -(-n // step) * step


def pad_to_shards(n: int, mesh: Mesh | None = None, multiple: int = 8) -> int:
    """Padded row count: a multiple of (shards * multiple) ≥ n, bucketed to
    the row ladder above _ROW_BUCKET_MIN (see :func:`_bucket_rows`).

    The per-shard row count is kept a multiple of 8 (f32 sublane tile) so
    device layouts stay tiling-friendly.
    """
    m = (mesh or get_mesh()).shape[ROWS_AXIS]
    block = m * multiple
    return max(block, ((_bucket_rows(n) + block - 1) // block) * block)


def shard_rows(arr, mesh: Mesh | None = None):
    """Place a host array onto the mesh, sharded along the leading axis.

    On a multi-process cloud the mesh spans non-addressable devices; each
    process holds the same full host array (SPMD command replication,
    cluster/spmd.py) and contributes its addressable shards."""
    sh = row_sharding(mesh)
    if jax.process_count() > 1:
        a = np.asarray(arr)
        return jax.make_array_from_callback(a.shape, sh, lambda idx: a[idx])
    return jax.device_put(arr, sh)


def pull_to_host(x):
    """Full host value of a (possibly cross-process) device array.

    Fully-addressable arrays device_get directly. Cross-process sharded
    arrays allgather — a COLLECTIVE: on a multi-process cloud this must run
    inside replicated execution (every rank calls it at the same point),
    which the spmd command layer guarantees for build/parse/predict."""
    if getattr(x, "is_fully_addressable", True):
        return jax.device_get(x)
    from h2o3_tpu.cluster import spmd

    if not spmd.in_replicated():
        # an allgather entered by one rank alone deadlocks the cloud — fail
        # fast instead (coordinator-only REST paths must stay off sharded
        # data or go through spmd.run)
        raise RuntimeError(
            "host pull of a cross-process array outside replicated "
            "execution (multi-process cloud): route through spmd.run"
        )
    from jax.experimental import multihost_utils as mh

    return np.asarray(mh.process_allgather(x, tiled=True))
