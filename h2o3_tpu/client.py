"""REST client — successor of ``h2o-py``'s ``backend/connection.py`` +
the thin REST flows in ``h2o/h2o.py`` [UNVERIFIED upstream paths, SURVEY.md
§2.3]. The native in-process API (``h2o3_tpu.init/import_file/models``) is
the primary surface; this client provides the same flows against a REMOTE
coordinator over the wire protocol, proving the REST layer end-to-end and
giving multi-process deployments the H2O client feel.

>>> conn = connect("http://host:54321")
>>> fr = conn.import_file("/data/train.csv")
>>> model = conn.train("gbm", y="label", training_frame=fr, ntrees=50)
>>> pred_key = conn.predict(model["model_id"]["name"], fr)
"""

from __future__ import annotations

import json
import time
import urllib.parse
import urllib.request
from typing import Any


class H2OClientError(Exception):
    def __init__(self, status: int, msg: str):
        super().__init__(f"HTTP {status}: {msg}")
        self.status = status


class H2OConnection:
    def __init__(self, url: str, timeout: float = 600.0, token: str | None = None):
        """``token`` authenticates against a server running with
        H2O3_TPU_AUTH_TOKEN (the hash_login analog); defaults to that same
        env var so client and in-process server pair up automatically."""
        self.url = url.rstrip("/")
        self.timeout = timeout
        if token is None:
            from h2o3_tpu import config

            token = config.get("H2O3_TPU_AUTH_TOKEN") or None
        self.token = token
        cloud = self.get("/3/Cloud")
        if not cloud.get("cloud_healthy"):
            raise H2OClientError(503, "cloud is not healthy")
        self.cloud = cloud

    # -- wire helpers -----------------------------------------------------
    def _request(self, method: str, path: str, payload: dict | None, as_json: bool):
        url = self.url + path
        data = None
        headers = {}
        if payload is not None:
            if as_json:
                data = json.dumps(payload).encode()
                headers["Content-Type"] = "application/json"
            else:
                data = urllib.parse.urlencode(
                    {k: json.dumps(v) if isinstance(v, (list, dict)) else v
                     for k, v in payload.items() if v is not None}
                ).encode()
        headers.update(self._auth_headers())
        req = urllib.request.Request(url, data=data, headers=headers, method=method)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                return json.loads(r.read())
        except urllib.error.HTTPError as e:
            try:
                body = json.loads(e.read())
                msg = body.get("msg", str(e))
            except Exception:
                msg = str(e)
            raise H2OClientError(e.code, msg) from None

    def get(self, path: str) -> dict:
        return self._request("GET", path, None, False)

    def post(self, path: str, payload: dict | None = None, as_json: bool = False) -> dict:
        return self._request("POST", path, payload, as_json)

    def delete(self, path: str) -> dict:
        return self._request("DELETE", path, None, False)

    # -- job polling (the h2o-py H2OJob.poll contract) --------------------
    def wait_job(self, job_key: str, poll_interval: float = 0.3) -> dict:
        t0 = time.time()
        while True:
            j = self.get(f"/3/Jobs/{job_key}")["jobs"][0]
            if j["status"] in ("DONE", "FAILED", "CANCELLED"):
                if j["status"] == "FAILED":
                    raise H2OClientError(500, j.get("exception") or "job failed")
                return j
            if time.time() - t0 > self.timeout:
                raise H2OClientError(408, f"job {job_key} timed out")
            time.sleep(poll_interval)

    # -- flows ------------------------------------------------------------
    def import_file(self, path: str, destination_frame: str | None = None) -> str:
        """Returns the frame key (sniff + parse, the h2o.import_file flow)."""
        self.post("/3/ImportFiles", {"path": path})
        setup = self.post("/3/ParseSetup", {"source_frames": path})
        resp = self.post("/3/Parse", {
            "source_frames": path,
            "destination_frame": destination_frame,
            "separator": setup.get("separator"),
        })
        self.wait_job(resp["job"]["key"]["name"])
        dest = resp.get("destination_frame")
        return dest["name"] if isinstance(dest, dict) else (dest or destination_frame)

    def frame(self, key: str) -> dict:
        return self.get(f"/3/Frames/{urllib.parse.quote(key, safe='')}")["frames"][0]

    def train(self, algo: str, y: str | None = None, training_frame: str | Any = None,
              validation_frame: str | Any = None, x=None, **params) -> dict:
        """Build a model synchronously; returns the model schema dict."""
        body = dict(params)
        body["training_frame"] = _key_of(training_frame)
        if validation_frame is not None:
            body["validation_frame"] = _key_of(validation_frame)
        if y is not None:
            body["response_column"] = y
        if x is not None:
            body["x"] = list(x)
        resp = self.post(f"/3/ModelBuilders/{algo}", body)
        job = self.wait_job(resp["job"]["key"]["name"])
        return self.get(f"/3/Models/{job['dest']['name']}")["models"][0]

    def predict(self, model_key: str, frame: str | Any, **options) -> str:
        """Returns the predictions frame key. ``options`` are the upstream
        predict options (predict_contributions=True,
        leaf_node_assignment=True, leaf_node_assignment_type="Node_ID")."""
        out = self.post(
            f"/3/Predictions/models/{model_key}/frames/{_key_of(frame)}",
            dict(options),
        )
        return out["predictions_frame"]["name"]

    def split_frame(self, frame: str | Any, ratios, destination_frames=None,
                    seed: int = 1234) -> list[str]:
        """Random row split via /3/SplitFrame; returns the part keys."""
        body = {"dataset": _key_of(frame), "ratios": list(ratios), "seed": seed}
        if destination_frames:
            body["destination_frames"] = list(destination_frames)
        out = self.post("/3/SplitFrame", body)
        return [d["name"] for d in out["destination_frames"]]

    def create_frame(self, dest: str | None = None, **spec) -> str:
        """Synthetic random frame via /3/CreateFrame; returns the key."""
        body = dict(spec)
        if dest:
            body["dest"] = dest
        return self.post("/3/CreateFrame", body)["destination_frame"]["name"]

    def model_performance(self, model_key: str, frame: str | Any) -> dict:
        out = self.post(
            f"/3/ModelMetrics/models/{model_key}/frames/{_key_of(frame)}", {}
        )
        return out["model_metrics"][0]

    def _auth_headers(self) -> dict:
        return {"Authorization": f"Bearer {self.token}"} if self.token else {}

    def _raw_post(self, path: str, body: bytes) -> dict:
        req = urllib.request.Request(
            self.url + path, data=body,
            headers={"Content-Type": "application/octet-stream",
                     **self._auth_headers()}, method="POST",
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            return json.loads(r.read())

    def upload_file(self, path: str, destination_frame: str | None = None) -> str:
        """Raw-body upload to a remote coordinator (POST /3/PostFile)."""
        import os

        qd = {"filename": os.path.basename(path)}
        if destination_frame:
            qd["destination_frame"] = destination_frame
        q = "?" + urllib.parse.urlencode(qd)
        with open(path, "rb") as f:
            body = f.read()
        out = self._raw_post(f"/3/PostFile{q}", body)
        return out["destination_frame"]

    def grid(self, algo: str, hyper_parameters: dict, y: str | None = None,
             training_frame=None, search_criteria: dict | None = None, **params) -> dict:
        """Run a grid search remotely (POST /99/Grid/{algo}); returns the
        sorted grid view."""
        import json as _json

        payload = {**params, "hyper_parameters": _json.dumps(hyper_parameters)}
        if search_criteria:
            payload["search_criteria"] = _json.dumps(search_criteria)
        if y is not None:
            payload["response_column"] = y
        if training_frame is not None:
            payload["training_frame"] = _key_of(training_frame)
        out = self.post(f"/99/Grid/{algo}", payload)
        self.wait_job(out["job"]["key"]["name"])
        return self.get(f"/99/Grids/{out['grid_id']['name']}")["grids"][0]

    def download_mojo(self, model_key: str, path: str) -> str:
        """GET /3/Models/{id}/mojo → local file."""
        import urllib.request

        req = urllib.request.Request(f"{self.url}/3/Models/{model_key}/mojo",
                                     headers=self._auth_headers())
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            data = r.read()
        with open(path, "wb") as f:
            f.write(data)
        return path

    def logs(self, tail: int = 200) -> str:
        return self.get(f"/3/Logs/nodes/0/files/default?tail={tail}")["log"]

    def rapids(self, ast: str) -> dict:
        return self.post("/99/Rapids", {"ast": ast})

    def download_csv(self, frame_key: str) -> bytes:
        """Raw CSV bytes of a frame via /3/DownloadDataset."""
        import urllib.parse
        import urllib.request

        q = urllib.parse.urlencode({"frame_id": frame_key})
        req = urllib.request.Request(f"{self.url}/3/DownloadDataset?{q}",
                                     headers=self._auth_headers())
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return resp.read()

    def lazy_frame(self, key_or_path: str) -> "Any":
        """A lazy client-side H2OFrame over a DKV key (or import a path)."""
        from h2o3_tpu.client_frame import H2OFrame

        if "/" in key_or_path or key_or_path.endswith(".csv"):
            return H2OFrame.import_file(self, key_or_path)
        return H2OFrame.from_key(self, key_or_path)

    def automl(self, y: str, training_frame: str | Any, max_models: int = 0,
               max_runtime_secs: float = 0.0, nfolds: int = 5, seed: int = -1,
               include_algos=None, exclude_algos=None) -> dict:
        spec = {
            "build_control": {
                "stopping_criteria": {"max_models": max_models,
                                      "max_runtime_secs": max_runtime_secs,
                                      "seed": seed},
                "nfolds": nfolds,
            },
            "input_spec": {"training_frame": {"name": _key_of(training_frame)},
                           "response_column": {"column_name": y}},
            "build_models": {},
        }
        if include_algos:
            spec["build_models"]["include_algos"] = list(include_algos)
        if exclude_algos:
            spec["build_models"]["exclude_algos"] = list(exclude_algos)
        resp = self.post("/99/AutoMLBuilder", spec, as_json=True)
        self.wait_job(resp["job"]["key"]["name"])
        return self.get(f"/99/AutoML/{resp['automl_id']['name']}")


def _key_of(frame) -> str:
    if frame is None:
        raise ValueError("frame required")
    return getattr(frame, "key", None) or str(frame)


def connect(url: str | None = None, **kw) -> H2OConnection:
    """``h2o.connect`` successor. Default URL tracks H2O3_TPU_PORT."""
    if url is None:
        from h2o3_tpu import config

        url = f"http://127.0.0.1:{config.get_int('H2O3_TPU_PORT')}"
    return H2OConnection(url, **kw)
