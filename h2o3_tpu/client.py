"""REST client — successor of ``h2o-py``'s ``backend/connection.py`` +
the thin REST flows in ``h2o/h2o.py`` [UNVERIFIED upstream paths, SURVEY.md
§2.3]. The native in-process API (``h2o3_tpu.init/import_file/models``) is
the primary surface; this client provides the same flows against a REMOTE
coordinator over the wire protocol, proving the REST layer end-to-end and
giving multi-process deployments the H2O client feel.

>>> conn = connect("http://host:54321")
>>> fr = conn.import_file("/data/train.csv")
>>> model = conn.train("gbm", y="label", training_frame=fr, ntrees=50)
>>> pred_key = conn.predict(model["model_id"]["name"], fr)
"""

from __future__ import annotations

import json
import time
import urllib.parse
import urllib.request
import zlib
from typing import Any

# HTTP statuses the server's admission/idempotency layer hands back for
# "try again shortly": 429 (in-flight gate full), 503 (job queue full /
# draining / memory shed), 409 (same Idempotency-Key still in flight). All
# three mean the request did NOT run — retrying is always safe. Memory
# sheds (body reason "memory") carry a COMPUTED Retry-After — the server's
# reservation-queue estimate of when HBM frees — which _backoff_delay
# honors as a floor like every other Retry-After.
_RETRYABLE_STATUSES = (409, 429, 503)


class H2OClientError(Exception):
    def __init__(self, status: int, msg: str, retry_after: float | None = None,
                 recovery: dict | None = None, reason: str | None = None):
        super().__init__(f"HTTP {status}: {msg}")
        self.status = status
        self.retry_after = retry_after
        # the failed/timed-out job's crash-recovery pointer (the /3/Jobs
        # `recovery` block: latest interval snapshot key + path) — scripts
        # resume with checkpoint=e.recovery["checkpoint_path"] without a
        # second /3/Jobs round-trip (docs/RECOVERY.md)
        self.recovery = recovery
        # the server's machine-readable shed reason ("memory", "draining",
        # "inflight_full", "job_queue_full") when the error body carried one
        self.reason = reason


class H2OConnection:
    def __init__(self, url: str, timeout: float = 600.0, token: str | None = None,
                 retries: int = 4, retry_backoff: float = 0.25,
                 retry_cap: float = 5.0):
        """``token`` authenticates against a server running with
        H2O3_TPU_AUTH_TOKEN (the hash_login analog); defaults to that same
        env var so client and in-process server pair up automatically.
        ``retries`` bounds transient-error retries (429/503/409 shed
        responses for any method; connection-level errors only for GETs and
        idempotency-keyed POSTs), with capped exponential backoff
        (``retry_backoff * 2^attempt`` up to ``retry_cap``) plus
        deterministic jitter and the server's ``Retry-After`` as a floor."""
        self.url = url.rstrip("/")
        self.timeout = timeout
        self.retries = int(retries)
        self.retry_backoff = float(retry_backoff)
        self.retry_cap = float(retry_cap)
        if token is None:
            from h2o3_tpu import config

            token = config.get("H2O3_TPU_AUTH_TOKEN") or None
        self.token = token
        cloud = self.get("/3/Cloud")
        if not cloud.get("cloud_healthy"):
            raise H2OClientError(503, "cloud is not healthy")
        self.cloud = cloud

    # -- wire helpers -----------------------------------------------------
    def _backoff_delay(self, path: str, attempt: int,
                       retry_after: float | None) -> float:
        base = min(self.retry_cap, self.retry_backoff * (2 ** attempt))
        # DETERMINISTIC jitter (keyed on path+attempt, like persist.py's
        # retry wrapper): reproducible runs, yet distinct clients desync
        frac = zlib.crc32(f"{self.url}{path}:{attempt}".encode()) % 1000
        delay = base * (1.0 + 0.5 * frac / 1000.0)
        if retry_after:
            delay = max(delay, float(retry_after))
        return delay

    def _request(self, method: str, path: str, payload: dict | None,
                 as_json: bool, idempotency_key: str | None = None):
        attempt = 0
        while True:
            try:
                return self._request_once(method, path, payload, as_json,
                                          idempotency_key)
            except H2OClientError as e:
                if e.status not in _RETRYABLE_STATUSES or attempt >= self.retries:
                    raise
                delay = self._backoff_delay(path, attempt, e.retry_after)
            except urllib.error.URLError:
                # connection-level failure: the server may or may not have
                # seen the request — only safe to retry when re-running it
                # is harmless (GET) or deduped (Idempotency-Key)
                if attempt >= self.retries or (
                    method != "GET" and not idempotency_key
                ):
                    raise
                delay = self._backoff_delay(path, attempt, None)
            time.sleep(delay)
            attempt += 1

    def _request_once(self, method: str, path: str, payload: dict | None,
                      as_json: bool, idempotency_key: str | None = None):
        url = self.url + path
        data = None
        headers = {}
        if payload is not None:
            if as_json:
                data = json.dumps(payload).encode()
                headers["Content-Type"] = "application/json"
            else:
                data = urllib.parse.urlencode(
                    {k: json.dumps(v) if isinstance(v, (list, dict)) else v
                     for k, v in payload.items() if v is not None}
                ).encode()
        if idempotency_key:
            headers["Idempotency-Key"] = idempotency_key
        headers.update(self._auth_headers())
        req = urllib.request.Request(url, data=data, headers=headers, method=method)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                return json.loads(r.read())
        except urllib.error.HTTPError as e:
            reason = None
            try:
                body = json.loads(e.read())
                msg = body.get("msg", str(e))
                reason = body.get("reason")
            except Exception:
                msg = str(e)
            try:
                ra = float(e.headers.get("Retry-After"))
            except (TypeError, ValueError):
                ra = None
            raise H2OClientError(e.code, msg, retry_after=ra,
                                 reason=reason) from None

    def get(self, path: str) -> dict:
        return self._request("GET", path, None, False)

    def post(self, path: str, payload: dict | None = None, as_json: bool = False,
             idempotency_key: str | None = None) -> dict:
        return self._request("POST", path, payload, as_json,
                             idempotency_key=idempotency_key)

    def delete(self, path: str) -> dict:
        return self._request("DELETE", path, None, False)

    # -- job polling (the h2o-py H2OJob.poll contract) --------------------
    def wait_job(self, job_key: str, poll_interval: float = 0.1,
                 poll_cap: float = 2.0) -> dict:
        """Poll ``/3/Jobs/{key}`` to a terminal state with capped
        exponential backoff (starts at ``poll_interval``, grows to
        ``poll_cap``). The wait budget (``self.timeout``) is measured from
        the job's OWN start time, so server queue time is never counted
        against the caller's training budget."""
        t0 = time.time()
        started: float | None = None
        delay = poll_interval
        while True:
            j = self.get(f"/3/Jobs/{job_key}")["jobs"][0]
            if j["status"] in ("DONE", "FAILED", "CANCELLED"):
                if j["status"] == "FAILED":
                    rec = j.get("recovery")
                    hint = (
                        f" — resumable: latest snapshot "
                        f"{rec.get('checkpoint_path')} (pass it as "
                        "checkpoint= to continue)" if rec else ""
                    )
                    raise H2OClientError(
                        500,
                        f"job {job_key} failed: "
                        f"{j.get('exception') or 'job failed'}{hint}",
                        recovery=rec,
                    )
                return j
            if started is None and (
                j.get("started_at") or j["status"] == "RUNNING"
            ):
                # CLIENT clock at first observed start (the server's
                # started_at is another machine's clock — skew-unsafe)
                started = time.time()
            elapsed = time.time() - (started if started is not None else t0)
            if elapsed > self.timeout:
                rec = j.get("recovery")
                hint = (
                    f" — resumable: latest snapshot "
                    f"{rec.get('checkpoint_path')}" if rec else ""
                )
                raise H2OClientError(
                    408, f"job {job_key} timed out after {elapsed:.1f}s "
                         f"(progress {j.get('progress', 0):.0%}){hint}",
                    recovery=rec)
            time.sleep(delay)
            delay = min(poll_cap, delay * 1.6)

    # -- flows ------------------------------------------------------------
    def import_file(self, path: str, destination_frame: str | None = None) -> str:
        """Returns the frame key (sniff + parse, the h2o.import_file flow)."""
        self.post("/3/ImportFiles", {"path": path})
        setup = self.post("/3/ParseSetup", {"source_frames": path})
        resp = self.post("/3/Parse", {
            "source_frames": path,
            "destination_frame": destination_frame,
            "separator": setup.get("separator"),
        })
        self.wait_job(resp["job"]["key"]["name"])
        dest = resp.get("destination_frame")
        return dest["name"] if isinstance(dest, dict) else (dest or destination_frame)

    def frame(self, key: str) -> dict:
        return self.get(f"/3/Frames/{urllib.parse.quote(key, safe='')}")["frames"][0]

    def train(self, algo: str, y: str | None = None, training_frame: str | Any = None,
              validation_frame: str | Any = None, x=None, **params) -> dict:
        """Build a model synchronously; returns the model schema dict."""
        import uuid

        body = dict(params)
        body["training_frame"] = _key_of(training_frame)
        if validation_frame is not None:
            body["validation_frame"] = _key_of(validation_frame)
        if y is not None:
            body["response_column"] = y
        if x is not None:
            body["x"] = list(x)
        # one key per LOGICAL build: a transparent retry of this POST (shed
        # response, dropped connection) replays the first response instead
        # of training a second model
        resp = self.post(f"/3/ModelBuilders/{algo}", body,
                         idempotency_key=uuid.uuid4().hex)
        job = self.wait_job(resp["job"]["key"]["name"])
        return self.get(f"/3/Models/{job['dest']['name']}")["models"][0]

    def predict(self, model_key: str, frame: str | Any, **options) -> str:
        """Returns the predictions frame key. ``options`` are the upstream
        predict options (predict_contributions=True,
        leaf_node_assignment=True, leaf_node_assignment_type="Node_ID")."""
        out = self.post(
            f"/3/Predictions/models/{model_key}/frames/{_key_of(frame)}",
            dict(options),
        )
        return out["predictions_frame"]["name"]

    def predict_rows(self, model_key: str, rows) -> dict:
        """Low-latency row scoring (``POST /3/Predictions/rows``): ``rows``
        is a list of ``{column: value}`` dicts or a ``{column: [values]}``
        table — no frame upload, no DKV round-trip. Returns the
        ``predictions`` column table (``predict`` + per-class
        probabilities). Requests are coalesced server-side into batched
        device dispatches (the scoring tier; see docs/MIGRATION.md)."""
        out = self.post("/3/Predictions/rows",
                        {"model": model_key, "rows": rows}, as_json=True)
        return out["predictions"]

    def split_frame(self, frame: str | Any, ratios, destination_frames=None,
                    seed: int = 1234) -> list[str]:
        """Random row split via /3/SplitFrame; returns the part keys."""
        body = {"dataset": _key_of(frame), "ratios": list(ratios), "seed": seed}
        if destination_frames:
            body["destination_frames"] = list(destination_frames)
        out = self.post("/3/SplitFrame", body)
        return [d["name"] for d in out["destination_frames"]]

    def create_frame(self, dest: str | None = None, **spec) -> str:
        """Synthetic random frame via /3/CreateFrame; returns the key."""
        body = dict(spec)
        if dest:
            body["dest"] = dest
        return self.post("/3/CreateFrame", body)["destination_frame"]["name"]

    def model_performance(self, model_key: str, frame: str | Any) -> dict:
        out = self.post(
            f"/3/ModelMetrics/models/{model_key}/frames/{_key_of(frame)}", {}
        )
        return out["model_metrics"][0]

    def _auth_headers(self) -> dict:
        return {"Authorization": f"Bearer {self.token}"} if self.token else {}

    def _raw_post(self, path: str, body: bytes) -> dict:
        req = urllib.request.Request(
            self.url + path, data=body,
            headers={"Content-Type": "application/octet-stream",
                     **self._auth_headers()}, method="POST",
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            return json.loads(r.read())

    def upload_file(self, path: str, destination_frame: str | None = None) -> str:
        """Raw-body upload to a remote coordinator (POST /3/PostFile)."""
        import os

        qd = {"filename": os.path.basename(path)}
        if destination_frame:
            qd["destination_frame"] = destination_frame
        q = "?" + urllib.parse.urlencode(qd)
        with open(path, "rb") as f:
            body = f.read()
        out = self._raw_post(f"/3/PostFile{q}", body)
        return out["destination_frame"]

    def grid(self, algo: str, hyper_parameters: dict, y: str | None = None,
             training_frame=None, search_criteria: dict | None = None, **params) -> dict:
        """Run a grid search remotely (POST /99/Grid/{algo}); returns the
        sorted grid view."""
        import json as _json

        payload = {**params, "hyper_parameters": _json.dumps(hyper_parameters)}
        if search_criteria:
            payload["search_criteria"] = _json.dumps(search_criteria)
        if y is not None:
            payload["response_column"] = y
        if training_frame is not None:
            payload["training_frame"] = _key_of(training_frame)
        out = self.post(f"/99/Grid/{algo}", payload)
        self.wait_job(out["job"]["key"]["name"])
        return self.get(f"/99/Grids/{out['grid_id']['name']}")["grids"][0]

    def download_mojo(self, model_key: str, path: str) -> str:
        """GET /3/Models/{id}/mojo → local file."""
        import urllib.request

        req = urllib.request.Request(f"{self.url}/3/Models/{model_key}/mojo",
                                     headers=self._auth_headers())
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            data = r.read()
        with open(path, "wb") as f:
            f.write(data)
        return path

    def logs(self, tail: int = 200) -> str:
        return self.get(f"/3/Logs/nodes/0/files/default?tail={tail}")["log"]

    def rapids(self, ast: str) -> dict:
        return self.post("/99/Rapids", {"ast": ast})

    def download_csv(self, frame_key: str) -> bytes:
        """Raw CSV bytes of a frame via /3/DownloadDataset."""
        import urllib.parse
        import urllib.request

        q = urllib.parse.urlencode({"frame_id": frame_key})
        req = urllib.request.Request(f"{self.url}/3/DownloadDataset?{q}",
                                     headers=self._auth_headers())
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return resp.read()

    def lazy_frame(self, key_or_path: str) -> "Any":
        """A lazy client-side H2OFrame over a DKV key (or import a path)."""
        from h2o3_tpu.client_frame import H2OFrame

        if "/" in key_or_path or key_or_path.endswith(".csv"):
            return H2OFrame.import_file(self, key_or_path)
        return H2OFrame.from_key(self, key_or_path)

    def automl(self, y: str, training_frame: str | Any, max_models: int = 0,
               max_runtime_secs: float = 0.0, nfolds: int = 5, seed: int = -1,
               include_algos=None, exclude_algos=None) -> dict:
        spec = {
            "build_control": {
                "stopping_criteria": {"max_models": max_models,
                                      "max_runtime_secs": max_runtime_secs,
                                      "seed": seed},
                "nfolds": nfolds,
            },
            "input_spec": {"training_frame": {"name": _key_of(training_frame)},
                           "response_column": {"column_name": y}},
            "build_models": {},
        }
        if include_algos:
            spec["build_models"]["include_algos"] = list(include_algos)
        if exclude_algos:
            spec["build_models"]["exclude_algos"] = list(exclude_algos)
        resp = self.post("/99/AutoMLBuilder", spec, as_json=True)
        self.wait_job(resp["job"]["key"]["name"])
        return self.get(f"/99/AutoML/{resp['automl_id']['name']}")


def _key_of(frame) -> str:
    if frame is None:
        raise ValueError("frame required")
    return getattr(frame, "key", None) or str(frame)


def connect(url: str | None = None, **kw) -> H2OConnection:
    """``h2o.connect`` successor. Default URL tracks H2O3_TPU_PORT."""
    if url is None:
        from h2o3_tpu import config

        url = f"http://127.0.0.1:{config.get_int('H2O3_TPU_PORT')}"
    return H2OConnection(url, **kw)
