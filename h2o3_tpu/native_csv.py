"""ctypes binding + build driver for the native chunked CSV parser
(``native/fastcsv.cpp``) — the ParseDataset tokenizer analog (SURVEY.md
§2.1: upstream's parser tokenizes/coerces chunks in parallel native code).

Same auto-build contract as :mod:`h2o3_tpu.native` (tmojo): g++ on first
use, atomic publish, graceful degradation — ``parse_csv_native`` returns
None whenever the file is outside the fast path (quoted fields, type
surprises, no compiler) and the caller falls back to pandas, so behavior
never diverges, only speed.

Fast-path contract (enforced in C, rc < 0 on violation):
single-char sep, no double quotes anywhere, columns pre-typed from the
caller's sample as numeric or enum, NA spellings EXACTLY pandas' default
na_values set (see kNA in fastcsv.cpp), blank lines skipped like pandas.
Ragged rows or a non-numeric token in a numeric column bail to pandas
rather than re-implementing pandas' type-flip semantics.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_LOCK = threading.Lock()
_LIB: ctypes.CDLL | None = None
_BUILD_FAILED = False

_SRC = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    "native", "fastcsv.cpp")
_SO = os.path.join(os.path.dirname(_SRC), "libfastcsv.so")

_F64P = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
_I32P = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")


def _build() -> str | None:
    if os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
        return _SO
    tmp = f"{_SO}.{os.getpid()}.tmp"
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
           _SRC, "-o", tmp]
    try:
        r = subprocess.run(cmd, capture_output=True, timeout=120)
        if r.returncode == 0:
            os.replace(tmp, _SO)
            return _SO
    except (OSError, subprocess.TimeoutExpired):
        pass
    finally:
        if os.path.exists(tmp):
            try:
                os.unlink(tmp)
            except OSError:
                pass
    return None


def _lib() -> ctypes.CDLL | None:
    global _LIB, _BUILD_FAILED
    with _LOCK:
        if _LIB is not None or _BUILD_FAILED:
            return _LIB
        so = _build()
        if so is None:
            _BUILD_FAILED = True
            return None
        lib = ctypes.CDLL(so)
        lib.fastcsv_parse.restype = ctypes.c_void_p
        lib.fastcsv_parse.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_char, ctypes.c_int,
            ctypes.c_int, _I32P, ctypes.c_int, ctypes.POINTER(ctypes.c_int),
        ]
        lib.fastcsv_nrows.restype = ctypes.c_int64
        lib.fastcsv_nrows.argtypes = [ctypes.c_void_p]
        lib.fastcsv_get_numeric.argtypes = [ctypes.c_void_p, ctypes.c_int, _F64P]
        lib.fastcsv_get_codes.argtypes = [ctypes.c_void_p, ctypes.c_int, _I32P]
        lib.fastcsv_domain_size.restype = ctypes.c_int64
        lib.fastcsv_domain_size.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.fastcsv_domain_bytes.restype = ctypes.c_int64
        lib.fastcsv_domain_bytes.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.fastcsv_get_domain.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                           ctypes.c_char_p]
        lib.fastcsv_free.argtypes = [ctypes.c_void_p]
        _LIB = lib
        return _LIB


def available() -> bool:
    return _lib() is not None


def parse_csv_native(data: bytes, names: list[str], kinds: list[int],
                     sep: str = ",", has_header: bool = True,
                     n_threads: int | None = None):
    """Parse a CSV byte buffer with pre-typed columns.

    ``kinds[i]``: 0 numeric (float64 out), 1 enum (codes + domain out).
    Returns a pandas DataFrame (numeric columns as float64 — callers
    integral-narrow afterwards if needed; enum columns as Categorical with
    SORTED categories, matching the pandas path's sorted-level interning),
    or None when the buffer is outside the fast path. Non-UTF-8 level
    bytes return None too — the pandas path then raises its own decode
    error, keeping error behavior identical.
    """
    import pandas as pd

    lib = _lib()
    if lib is None or len(sep) != 1:
        return None
    kinds_arr = np.asarray(kinds, np.int32)
    rc = ctypes.c_int(0)
    if n_threads is None:
        n_threads = min(max(os.cpu_count() or 1, 1), 16)
    h = lib.fastcsv_parse(
        data, len(data), sep.encode()[0], int(has_header), len(names),
        kinds_arr, n_threads, ctypes.byref(rc),
    )
    if not h:
        return None  # rc tells why; every reason means "use pandas"
    try:
        n = lib.fastcsv_nrows(h)
        cols = {}
        for i, name in enumerate(names):
            if kinds[i] == 0:
                out = np.empty(n, np.float64)
                if n:
                    lib.fastcsv_get_numeric(h, i, out)
                cols[name] = out
            else:
                codes = np.empty(n, np.int32)
                if n:
                    lib.fastcsv_get_codes(h, i, codes)
                nbytes = lib.fastcsv_domain_bytes(h, i)
                buf = ctypes.create_string_buffer(int(nbytes) or 1)
                lib.fastcsv_get_domain(h, i, buf)
                raw = buf.raw[: int(nbytes)]
                try:
                    domain = raw.decode("utf-8").split("\n")[:-1]
                except UnicodeDecodeError:
                    return None  # pandas raises the canonical error
                # sort levels + remap codes: the pandas path interns object
                # levels in SORTED order and Vec domains must not depend on
                # which parser ran
                order = np.argsort(np.asarray(domain, object), kind="stable")
                remap = np.empty(len(domain) + 1, np.int32)
                remap[order] = np.arange(len(domain), dtype=np.int32)
                remap[-1] = -1  # NA slot
                codes = remap[codes]
                domain = [domain[j] for j in order]
                cols[name] = pd.Categorical.from_codes(
                    codes, categories=pd.Index(domain, dtype=object)
                )
        return pd.DataFrame(cols)
    finally:
        lib.fastcsv_free(h)
