"""sklearn-compatible wrappers — successor of ``h2o-py/h2o/sklearn/*``
[UNVERIFIED upstream paths, SURVEY.md §2.3].

Every estimator gains a ``...Classifier`` / ``...Regressor`` face with the
sklearn contract: ``fit(X, y)`` / ``predict(X)`` / ``predict_proba(X)`` /
``score`` / ``get_params`` / ``set_params``, accepting numpy arrays or
pandas DataFrames. Frames are built internally; the response is cast to
enum for classifiers. Compatible with sklearn model_selection utilities
(``cross_val_score``, ``GridSearchCV``) via ``sklearn.base`` duck typing.
"""

from __future__ import annotations

from typing import Any

import numpy as np
import pandas as pd

from h2o3_tpu.frame.frame import Frame

_RESP = "__sk_response__"


def _to_df(X) -> pd.DataFrame:
    if isinstance(X, pd.DataFrame):
        return X.reset_index(drop=True)
    X = np.asarray(X)
    return pd.DataFrame(X, columns=[f"x{i}" for i in range(X.shape[1])])


class _SkBase:
    _BUILDER = ""
    _CLASSIFIER = False

    def __init__(self, **params):
        self._params = params
        self._model = None
        self._classes: np.ndarray | None = None

    # -- sklearn plumbing ----------------------------------------------------
    def get_params(self, deep: bool = True) -> dict:
        return dict(self._params)

    def set_params(self, **params) -> "Any":
        self._params.update(params)
        return self

    # -- the contract --------------------------------------------------------
    def fit(self, X, y, sample_weight=None):
        from h2o3_tpu import models as M

        df = _to_df(X).copy()
        y = np.asarray(y)
        ctypes = {}
        if self._CLASSIFIER:
            self._classes = np.unique(y)
            df[_RESP] = y.astype(str)
            ctypes[_RESP] = "enum"
        else:
            df[_RESP] = y.astype(np.float64)
        kw = dict(self._params)
        if sample_weight is not None:
            df["__sk_w__"] = np.asarray(sample_weight, np.float64)
            kw["weights_column"] = "__sk_w__"
        fr = Frame.from_pandas(df, column_types=ctypes)
        feats = [c for c in fr.names if c not in (_RESP, "__sk_w__")]
        builder = getattr(M, self._BUILDER)(**kw)
        self._model = builder.train(x=feats, y=_RESP, training_frame=fr)
        if self._CLASSIFIER:
            # align classes_ with the model's (lexicographic) enum domain so
            # predict_proba columns and classes_ agree even for numeric labels
            dom = self._model.output.get("response_domain")
            if dom:
                lut = {str(c): c for c in self._classes}
                self._classes = np.asarray([lut[d] for d in dom])
        return self

    def _scored(self, X) -> Frame:
        if self._model is None:
            raise RuntimeError("estimator is not fitted")
        return self._model.predict(Frame.from_pandas(_to_df(X)))

    def predict(self, X) -> np.ndarray:
        out = self._scored(X)
        pred = out.vec("predict").to_numpy()
        if self._CLASSIFIER:
            dom = out.vec("predict").domain or [str(c) for c in self._classes]
            labels = np.asarray([dom[int(c)] for c in pred.astype(np.int64)])
            # map back to the original dtype of y
            lut = {str(c): c for c in self._classes}
            return np.asarray([lut.get(l, l) for l in labels])
        return pred

    def predict_proba(self, X) -> np.ndarray:
        if not self._CLASSIFIER:
            raise AttributeError("predict_proba is classification-only")
        out = self._scored(X)
        # per-class probability columns only (cal_p0/cal_p1 are extras)
        cols = [n for n in out.names
                if n != "predict" and not n.startswith("cal_p")]
        return np.stack([out.vec(c).to_numpy() for c in cols], axis=1)

    def score(self, X, y, sample_weight=None) -> float:
        y = np.asarray(y)
        if self._CLASSIFIER:
            return float(np.mean(self.predict(X) == y))
        pred = self.predict(X)
        ssr = float(np.sum((y - pred) ** 2))
        sst = float(np.sum((y - y.mean()) ** 2))
        return 1.0 - ssr / max(sst, 1e-300)

    @property
    def classes_(self) -> np.ndarray:
        if self._classes is None:
            raise AttributeError("classes_")
        return self._classes

    @property
    def model(self):
        return self._model


def _mk(name: str, builder: str, classifier: bool) -> str:
    cls = type(
        name, (_SkBase,),
        {"_BUILDER": builder, "_CLASSIFIER": classifier,
         "__doc__": f"sklearn-style wrapper over the {builder} builder."},
    )
    globals()[name] = cls
    return name


__all__ = [
    _mk("H2OGradientBoostingClassifier", "GBM", True),
    _mk("H2OGradientBoostingRegressor", "GBM", False),
    _mk("H2ORandomForestClassifier", "DRF", True),
    _mk("H2ORandomForestRegressor", "DRF", False),
    _mk("H2OGeneralizedLinearClassifier", "GLM", True),
    _mk("H2OGeneralizedLinearRegressor", "GLM", False),
    _mk("H2ODeepLearningClassifier", "DeepLearning", True),
    _mk("H2ODeepLearningRegressor", "DeepLearning", False),
    _mk("H2ONaiveBayesClassifier", "NaiveBayes", True),
    _mk("H2OSupportVectorMachineClassifier", "PSVM", True),
]
