"""Unified runtime configuration — successor of the upstream flag/config
tree (``H2O.OptArgs`` launcher args + system properties) [UNVERIFIED
upstream paths, SURVEY.md §5.6].

One place defines every environment knob, its default, and its doc; every
subsystem reads through :func:`get` so ``python -c "import h2o3_tpu.config as
c; print(c.describe())"`` is the single source of truth for operators.

Knobs (env var → meaning):
- ``H2O3_TPU_NATIVE``        "0" disables the C++ scoring runtime (native.py)
- ``H2O3_TPU_HIST``          "matmul" forces the XLA matmul histogram over Pallas
- ``H2O3_TPU_HIST_SUBTRACT`` "0" disables sibling-subtraction in the fused
                             tree builder (direct per-node histograms)
- ``H2O3_TPU_STREAM_BYTES``  CSV size threshold that flips parse to streaming
- ``H2O3_TPU_PORT``          default REST port
- ``H2O3_TPU_ALLOWED_HOSTS`` extra Hosts allowed for state-changing REST
                             requests ('*' disables the CSRF guard)
- ``H2O3_TPU_LOG_LEVEL``     default log level for init()
"""

from __future__ import annotations

import os

_KNOBS: dict[str, tuple[str, str]] = {
    # name -> (default, doc)
    "H2O3_TPU_NATIVE": ("1", "C++ scoring runtime on (1) / off (0)"),
    "H2O3_TPU_NATIVE_PARSE": (
        "1", "native chunked CSV parser fast path on (1) / off (0); files "
             "outside the strict dialect always fall back to pandas"),
    "H2O3_TPU_HIST": (
        "", "histogram impl override: '' auto (scatter on CPU, Pallas on "
            "TPU), 'matmul' forces the plain-XLA MXU path, 'scatter' forces "
            "the scatter-add path (TPU-side debug A/B — all three local "
            "impls are reachable on any backend)"),
    "H2O3_TPU_HIST_SUBTRACT": (
        "1", "fused tree builder: build lighter child's histogram, derive "
        "sibling by parent subtraction (0 = direct per-node histograms)"),
    "H2O3_TPU_SPLIT_FUSE": (
        "auto", "fused Pallas histogram→split pipeline: the histogram kernel "
                "emits its native VMEM tile layout (no HBM unscramble "
                "passes), the cross-device reduce-scatter ships whole column "
                "tiles, and a Pallas split-scan kernel consumes the tiles "
                "block-by-block in VMEM so only per-(node,col) winner "
                "candidates reach HBM. 'auto' = on for non-CPU backends; "
                "'1' forces it on any backend (CPU runs the kernels in the "
                "Pallas interpreter — the CI/parity lane); '0' = the "
                "unfused path (dense histogram + XLA split scan). Monotone "
                "builds and categorical columns on sharded meshes fuse too "
                "(ISSUE 15), and uplift trees run their 4-lane scan through "
                "the whole-tree fused program (ISSUE 16) — "
                "tree_fused_fallbacks_total only tallies on the legacy "
                "per-level uplift loop (H2O3_TPU_WHOLE_TREE=0); see the "
                "docs/MIGRATION.md fallback matrix"),
    "H2O3_TPU_PALLAS_TILES": (
        "", "Pallas histogram/split kernel tile sizes as 'ROW,COL,NODE' "
            "(e.g. '512,8,64' — the built-in defaults). Tiles are a static "
            "compile key: every setting gets its own executable, so the "
            "tile sweep (tools/bench_kernel_sweep.py, run_tpu_backlog.sh) "
            "varies them via the environment with no monkeypatching. "
            "'auto' = the tile AUTOTUNER: a first-build micro-sweep over a "
            "small tile grid, cached per (shape-bucket, mesh) in the "
            "persistent compile-cache dir — same-bucket rebuilds (and "
            "later processes) perform zero new sweeps "
            "(pallas_tile_sweeps_total); explicit values bypass the sweep "
            "unchanged. '' = built-in defaults"),
    "H2O3_TPU_SPLIT_SHARD": (
        "1", "column-sharded split pipeline on meshes with >1 device: the "
             "histogram reduction ends in a reduce-scatter over column "
             "blocks (each device keeps only its C/P columns), the split "
             "scan runs on the local block, and a tiny all-gather of "
             "per-block winners merges bit-exactly against jnp.argmax's "
             "lowest-index tie-breaking. 0 = replicated histogram + "
             "replicated split scan (the pre-sharding path)"),
    "H2O3_TPU_GLM_FUSE": (
        "auto", "whole-program GLM IRLS (the PR-1 tree pattern ported to "
                "hex.glm): the IRLS loop runs as a compiled lax.while_loop "
                "executing up to K iterations per host dispatch, the Gram "
                "pass ends in a psum_scatter of contiguous G row blocks over "
                "the rows mesh axis (gathered once for the solve), and the "
                "Cholesky-with-jitter / ADMM solve moves on-device "
                "(float32); the host float64 lstsq lane remains as the "
                "singular-tail fallback. 'auto' = on with K=8; an integer "
                "N>=1 forces chunk size N; '0' restores the per-iteration "
                "host-solve path bit-for-bit. With export_checkpoints_dir "
                "set the chunk is clamped to 1 so PR-2 irls_state snapshots "
                "land at every iteration boundary (multinomial included — "
                "its cycling IRLS now fuses as a lax.scan over classes "
                "inside one while_loop, and ordinal fits run one on-device "
                "BFGS program; ISSUE 15). compute_p_values rides the fused "
                "lane too (ISSUE 16): the covariance comes from the final "
                "device Gram at the converged beta, so p-values no longer "
                "force the per-iteration host trajectory. Fallback matrix "
                "(docs/MIGRATION.md): L_BFGS and out-of-core streamed fits "
                "stay on their existing paths (glm_fuse_fallbacks_total "
                "tallies)"),
    "H2O3_TPU_MUNGE_FUSE": (
        "1", "compiled sharded data-munging plane (frame/munge.py + "
             "frame/lazy.py): group-by aggregation runs as ONE mesh-sharded "
             "segment-reduce program per .agg() call (all value columns "
             "stacked; sum lanes through the ops/collectives.py psum wrapper "
             "so the quant lane and 2-D rows×cols hierarchy apply, min/max "
             "through the exact pmax lane), merge expands (li, ri) ON DEVICE "
             "instead of host np.repeat — single-key inner joins on >1-device "
             "meshes additionally take the radix-partition all_to_all "
             "exchange lane — sort compiles key prep + lexsort into one "
             "cached program, and elementwise/ifelse chains build lazy "
             "expression graphs (frame/lazy.py LazyExprVec) that fuse into "
             "ONE jitted dispatch at first touch (munge_dispatches_total "
             "proves the reduction; streamed block materialization through "
             "the ChunkStore window when one is configured — the PR-11 "
             "residency fix). Ineligible shapes (string ops, STR/TIME keys, "
             "pivot, rank_within_group_by, host aggs like median/mode) stay "
             "eager and tally munge_fuse_fallbacks_total{reason}; see the "
             "docs/MIGRATION.md fallback matrix. '0' restores every eager "
             "seed path bit-for-bit"),
    "H2O3_TPU_DL_EPOCH_CHUNK": (
        "auto", "DeepLearning epoch fusion: fold this many epochs into ONE "
                "compiled program per dispatch with donated (params, "
                "opt_state) buffers; the shuffle permutations are "
                "precomputed host-side and the dropout RNG threads through "
                "the carry, so epoch trajectories are bit-identical to the "
                "per-epoch path. 'auto' = 8; '1' = one dispatch per epoch "
                "(the pre-fusion cadence). Clamped to 1 when "
                "export_checkpoints_dir, early stopping (stopping_rounds>0) "
                "or fault injection is active so per-epoch snapshots/stops "
                "keep their positions"),
    "H2O3_TPU_DL_GRAD_SHARD": (
        "auto", "DeepLearning minibatch gradient reduction sharded over the "
                "mesh: each device grads its local batch rows, the flat "
                "gradient is psum_scatter'd (1/P per device), the optimizer "
                "updates only its parameter shard and the updated params "
                "all_gather for the next step (ZeRO-style; replaces the "
                "replicated allreduce+update). 'auto' = on for >1-device "
                "meshes when eligible (elementwise optimizer state, "
                "mini_batch_size divisible by the shard count; dropout "
                "composes since ISSUE 15 — each device folds its shard "
                "index into the dropout key); '0' = always replicated "
                "(today's full-batch masks); '1' = on when eligible; "
                "'ctl' = the replicated PARITY CONTROL drawing the sharded "
                "lane's exact per-chunk dropout masks (the A/B lane). "
                "Ineligible configs use the replicated reduce and tally "
                "dl_shard_fallbacks_total"),
    "H2O3_TPU_COLLECTIVE_QUANT": (
        "auto", "block-quantized collective lane (ops/collectives.py, "
                "EQuARX-style) for the hot reduces — the tree histogram "
                "hist_reduce, the GLM Gram gram_reduce, the DL gradient "
                "dl_grad_reduce: each device's contribution crosses the "
                "wire as an int8 payload + one f32 power-of-two scale per "
                "block (all_to_all + dequantize-sum), ~4x fewer reduce "
                "bytes; gain/solve-critical side payloads (b/deviance "
                "psums, node totals, winner gathers, solve/param gathers) "
                "stay exact f32, and the Gram/gradient reduces add a "
                "residual-correction pass (~14 effective mantissa bits). "
                "'auto' = on only when the mesh spans >1 process (the "
                "ICI+DCN regime); '1' forces it anywhere (the A/B + parity "
                "lane); '0' restores the stock f32 collectives bit-for-bit"),
    "H2O3_TPU_COLLECTIVE_QUANT_BLOCK": (
        "256", "elements per quantization block (one f32 scale each) in the "
               "quantized collective lane; smaller blocks = tighter scales "
               "= more accuracy and more scale overhead"),
    "H2O3_TPU_COLLECTIVE_HIER": (
        "auto", "two-stage hierarchical reduction placement for the "
                "collective lane (arXiv:2110.10548): reduce exactly within "
                "each contiguous inner sub-axis group first (the cheap ICI "
                "level), then move only the — quantized, under "
                "COLLECTIVE_QUANT — chunk payloads across groups (the "
                "expensive DCN hop). 'auto' = group by each process's "
                "devices when the mesh spans >1 process; an integer forces "
                "that inner-group size (the A/B/test lane on the CPU "
                "proxy); '0' = single-stage"),
    "H2O3_TPU_FRAME_COMPRESS": (
        "1", "compressed device residency for the out-of-core data plane "
             "(frame/chunkstore.py): tree features live on device as the "
             "uint8 bin codes the histogram kernels consume (4x capacity "
             "vs f32, zero accuracy cost), categoricals as their narrow "
             "int8/int16 codes, and f32 columns materialize only at "
             "dispatch boundaries — streaming builds release the f32 "
             "device copies of binned feature columns to the host tier "
             "and rebuild them lazily on next touch. '0' disables the "
             "whole plane (no spill, no streaming, no release) and "
             "restores the fully-resident behavior bit-for-bit, even "
             "when H2O3_TPU_HBM_WINDOW_BYTES is set"),
    "H2O3_TPU_HBM_WINDOW_BYTES": (
        "0", "device-memory budget for one training pipeline's frame "
             "residency (the out-of-core streaming window): a frame whose "
             "per-row lanes exceed it trains as a block-accumulate outer "
             "loop — row-block chunks stream host->device through an LRU "
             "window of this many bytes (double-buffered prefetch, "
             "H2O3_TPU_PREFETCH_DEPTH) while evicted chunks park as host "
             "arrays, so GBM histograms / GLM IRLS Grams / DL epochs run "
             "at rows >> HBM through a fixed device footprint. Frames "
             "that fit take the resident path unchanged (bit-parity by "
             "construction). '0' (default) = unbounded, everything "
             "resident (today's behavior)"),
    "H2O3_TPU_PREFETCH_DEPTH": (
        "1", "how many row-block chunks ahead the out-of-core streaming "
             "loop issues host->device transfers (frame/chunkstore.py): "
             "1 = double buffering (block k+1 uploads while block k "
             "computes — jax device_put is async), higher values deepen "
             "the pipeline at the cost of a proportionally larger share "
             "of the HBM window; 0 = synchronous fetches (the A/B "
             "control for frame_prefetch_overlap_seconds)"),
    "H2O3_TPU_STREAM_BYTES": (str(256 * 1024 * 1024),
                              "CSV bytes above which parse streams in chunks"),
    "H2O3_TPU_INGEST_SHARDS": (
        "0", "coordinator-free sharded CSV ingest (frame/parse.py "
             "parse_sharded): how many byte ranges ONE process splits the "
             "source into and parses independently (each range located by "
             "a streaming newline scan and tokenized by the native "
             "byte-range parser) before concatenating — the single-process "
             "test/A-B lane of the per-host sharded parse, pinned "
             "byte-equal to the plain parse. 0 = one range per process "
             "(multi-process clouds still parse per-rank ranges)"),
    "H2O3_TPU_PORT": ("54321", "default REST port"),
    "H2O3_TPU_AUTH_TOKEN": (
        "", "opt-in REST auth token ('' = open, upstream default); when set "
            "every route requires Bearer/Basic auth (hash_login analog)"),
    "H2O3_TPU_ALLOWED_HOSTS": (
        "", "extra Host header names accepted for state-changing REST "
        "requests (comma list; '*' disables the CSRF/rebinding guard)"),
    "H2O3_TPU_LOG_LEVEL": ("INFO", "default log level"),
    "H2O3_TPU_BIN_ADAPT": (
        "0", "per-level bin coarsening in the fused tree builder (numeric "
             "frames): depth>=3 halves data bins per level, floor 63 — "
             "DHistogram's per-level re-binning analog. Off by default: "
             "measured 5% SLOWER on TPU v5e at 1M x 28 depth 6 (2.42 vs "
             "2.55 trees/sec, BENCH_builder_20260731T010117Z*) — the extra "
             "full-matrix coarsen copies outweigh the smaller histograms at "
             "the subtraction path's already-reduced node counts"),
    "H2O3_TPU_TREE_GOSS": (
        "", "gradient-based one-side sampling for tree builds (arXiv:"
            "1706.08359, ISSUE 16): 'a,b' keeps the top-a fraction of rows "
            "by |gradient| plus a uniformly-sampled b fraction of the rest, "
            "with the sampled rows' stat lanes amplified by (1-a)/b so "
            "split gains stay unbiased — each tree then streams ~(a+b) of "
            "the rows' stats through the unchanged fused level programs. "
            "Composes with sample_rate (GOSS applies after the bootstrap "
            "mask), the streamed out-of-core lane (per-block threshold) "
            "and the 2-D mesh row axis (global sort). '' = off "
            "(bit-for-bit today's path); tree_rows_sampled_total counts "
            "rows kept"),
    "H2O3_TPU_TREE_EFB": (
        "0", "exclusive feature bundling (arXiv:1706.08359, ISSUE 16): a "
             "host-side greedy pass at BinSpec build time packs columns "
             "that are almost-everywhere at their dominant bin code "
             "(sparse/one-hot suites) into shared u8 bundle columns, "
             "shrinking the histogram C dimension before the kernel grid "
             "sees it; the device histogram is expanded back to real "
             "columns right after accumulation so split records, varimp, "
             "MOJO and scoring are unchanged (bundling requires ZERO "
             "conflicts, so expanded histograms — and split decisions — "
             "are bit-equal). Dense-histogram lane only (the fused Pallas "
             "split path and streamed blocks skip bundling); "
             "tree_cols_bundled_total counts columns eliminated. "
             "0 = off (today's path bit-for-bit)"),
    "H2O3_TPU_HIST_I16": (
        "0", "int16 histogram accumulation lanes (arXiv:1806.11248, ISSUE "
             "16): per-(node,stat) rescaled gradient/hessian codes "
             "accumulate through the scatter/matmul histogram impls in a "
             "+-32767 integer budget and rescale back after the reduce — "
             "exact on in-range integer stats (weights/counts), ~15-bit "
             "mantissa otherwise. An overflow latch recomputes the full "
             "f32 histogram on-device when any cell would exceed the "
             "budget (tree_hist_i16_overflows_total tallies). Applies to "
             "the non-Pallas local impls; 0 = off (f32 accumulation, "
             "today's path bit-for-bit)"),
    "H2O3_TPU_TREE_U8CACHE": (
        "1", "u8-code-native frames (ISSUE 16): bin_frame memoizes the "
             "binned u8 code matrix on the frame keyed by the BinSpec "
             "fingerprint, so repeated builds over one frame (AutoML, "
             "grids, CV, checkpoint restarts) re-read the cached codes "
             "instead of re-binning every f32 column per build — "
             "tree_hist_hbm_bytes_total{path=rebin} accounts the traffic "
             "actually spent binning and stays flat on cache hits. 0 = "
             "re-bin every call (today's path; a hit returns the identical "
             "buffer, so this knob is bit-for-bit by construction)"),
    "H2O3_TPU_FUSED_MAX_DEPTH": (
        "20", "deepest tree the whole-tree fused program is built for; "
              "beyond it the per-level dispatch loop takes over"),
    "H2O3_TPU_WHOLE_TREE": (
        "1", "device-resident whole-tree build: the level loop runs INSIDE "
             "the compiled program (unrolled growth levels + a lax.while_loop "
             "over the node_cap-saturated levels with an on-device early-exit "
             "predicate), one dispatch per tree/chunk on every backend. "
             "0 = host-driven per-level dispatch loop (debug escape hatch)"),
    "H2O3_TPU_SHAPE_BUCKETS": (
        "1", "shape-bucketed padding: round rows (above 64k, ~12.5% geometric "
             "ladder), feature columns (multiple of 8) and histogram bins "
             "(power of two) up to a small ladder so AutoML/grid builds of "
             "near-identical shapes reuse one compiled program instead of "
             "recompiling per shape. Padding is masked out and proven inert "
             "(bucketed builds score identically); 0 = exact shapes"),
    "H2O3_TPU_COMPILE_CACHE": ("", "XLA compile-cache dir ('' = <pkg>/.jax_cache)"),
    "H2O3_TPU_NPS_DIR": (
        "", "NodePersistentStorage root (saved Flow notebooks; '' = "
        "~/.h2o3tpu/nps)"),
    "H2O3_TPU_HEARTBEAT_TIMEOUT": (
        "100", "multi-host dead-member detection bound, seconds "
        "(jax coordination-service heartbeat timeout)"),
    "H2O3_TPU_MESH_ROWS": (
        "", "2-D rows×cols pod mesh (parallel/mesh.py): how many ROWS-axis "
            "groups the device mesh factors into. Frame rows still shard "
            "over EVERY device (cols-major, so shard i sits on device i "
            "exactly like the 1-D mesh); histogram/Gram/gradient reduces "
            "run stage-1 EXACT over the rows axis (the contiguous-device / "
            "ICI level) and the collective lane proper over cols, and the "
            "split phase's column blocks shard over cols only — row "
            "sharding and the PR-5/PR-6 column blocks compose instead of "
            "sharing one axis, and the PR-9 quantized lane compresses "
            "exactly the cross-group stage. ''/'0'/'1' = the legacy 1-D "
            "rows mesh (bit-for-bit today's programs); 'auto' = rows = "
            "each process's local device count on multi-process clouds "
            "(rows=ICI, cols=DCN) and 1-D otherwise; an integer forces "
            "that rows size (the CPU-proxy A/B lane — '2' makes the "
            "8-device proxy a 2x4 pod stand-in). Non-dividing values fall "
            "back to 1-D with a warning"),
    "H2O3_TPU_COORDINATOR": (
        "", "jax.distributed coordinator address host:port for env-driven "
            "pod bootstrap (cluster/multihost.py): when set, launch.py and "
            "bootstrap_from_env() initialize the coordination service "
            "before any backend touch — the k8s StatefulSet points every "
            "pod at the rank-0 pod's headless-service DNS name. '' = "
            "single-host (no distributed init)"),
    "H2O3_TPU_NUM_PROCESSES": (
        "0", "process count of the env-driven pod bootstrap (must equal "
             "the StatefulSet replica count); 0 = unset"),
    "H2O3_TPU_PROCESS_ID": (
        "", "this process's rank in the env-driven pod bootstrap; '' = "
            "derive from the trailing ordinal of H2O3_TPU_POD_NAME / "
            "POD_NAME / HOSTNAME (the k8s StatefulSet convention "
            "pod-name-N), the launcher arg, or fail loudly"),
    "H2O3_TPU_POD_EXIT_DEGRADED": (
        "0", "pod-restart recovery loop (cluster/multihost.py): on a "
             "MULTI-PROCESS cloud whose degraded latch persists past this "
             "many seconds, the process EXITS (code 23) instead of holding "
             "a survivor island — the JAX runtime cannot re-initialize "
             "in-process, so on k8s the restartPolicy brings every rank "
             "back, the cloud re-forms, and the PR-10 supervisor resumes "
             "from the interval snapshot (recovery_seconds lands in the "
             "flight recorder + metrics). '0' = never exit (the in-process "
             "survivor island keeps serving — single-host default and the "
             "two-process test fixture's mode)"),
    "H2O3_TPU_PERSIST_RETRIES": (
        "4", "transient persist IO failures are retried this many times "
             "before surfacing (deterministic errors — bad path, collision, "
             "corrupt file — always fail fast, preserving spmd lockstep)"),
    "H2O3_TPU_PERSIST_BACKOFF": (
        "0.2", "base persist retry backoff, seconds: delay = base * 2^attempt "
               "plus up to +50% DETERMINISTIC jitter (keyed on op+attempt, "
               "identical on every rank and every run)"),
    "H2O3_TPU_METRICS": (
        "1", "observability layer on (1) / off (0): the /3/Metrics registry, "
             "span tracing and timing histograms (utils/metrics.py). Read "
             "ONCE at import — hot paths must not re-read the environment. "
             "The tree-build counters behind BUILD_STATS keep counting "
             "either way (test/bench contract, not optional telemetry)"),
    "H2O3_TPU_FAULTS": (
        "", "fault-injection spec for the chaos suite (utils/faults.py): "
            "';'-separated entries — 'site=N' fails the first N IO calls at "
            "the site, 'site@K' aborts training at iteration K, 'death:site' "
            "raises a synthetic coordination-service death error, "
            "'die:site' raises one at a COLLECTIVE BOUNDARY site (the "
            "worker-death-mid-collective stand-in the supervised-recovery "
            "drills use), 'blackout:SECS' fails EVERY persist IO for a "
            "wall-clock window of SECS from arming (storage-outage "
            "stand-in), 'stall:site:SECS' sleeps once at the site "
            "(wedged-collective stand-in), 'slow:site:SECS' sleeps at EVERY "
            "call to the site (slow-handler injection), 'oom:site' raises "
            "one synthetic XlaRuntimeError RESOURCE_EXHAUSTED at the "
            "dispatch site (the OOM-degrade drill), 'hang:site:SECS' "
            "sleeps once INSIDE the dispatch at the site (wedged-dispatch "
            "stand-in the hang watchdog trips on). '' = off"),
    "H2O3_TPU_RECOVERY": (
        "auto", "supervised auto-recovery (cluster/recovery.py): on a cloud "
                "failure — degraded latch, watchdog trip, coordination-"
                "service death signature, stale generation — supervised "
                "jobs with export_checkpoints_dir re-form the cloud "
                "(degraded -> recovering -> healthy, cloud_generation "
                "ticks) and resume from their latest interval snapshot "
                "with no operator in the path. 'auto'/'1' = on; '0' = off "
                "(restores the pure fail-stop contract: failures surface, "
                "the degraded latch stays one-way until clear_degraded)"),
    "H2O3_TPU_RECOVERY_MAX_RESTARTS": (
        "3", "supervised-recovery restart budget per job: after this many "
             "reform+resume attempts the failure surfaces "
             "(RecoveryExhausted) with the latest snapshot path in the "
             "message"),
    "H2O3_TPU_RECOVERY_BACKOFF": (
        "0.5", "supervised-recovery base backoff, seconds: delay = "
               "base * 2^attempt (capped at 30 s) plus up to +50% "
               "DETERMINISTIC jitter (keyed on job+attempt, identical "
               "run-to-run)"),
    "H2O3_TPU_RECOVERY_RESET_SECS": (
        "300", "supervised-recovery healthy window, seconds: a job that "
               "runs this long since its last relaunch without a cloud "
               "failure gets its restart budget back (attempt counter "
               "resets to 0) — a days-long job that restarted twice early "
               "on no longer dies on its 3rd unrelated transient. 0 = "
               "never reset (the lifetime budget of PR 10)"),
    "H2O3_TPU_FORMATION_MANIFEST": (
        "", "formation manifest path (cluster/multihost.py): every "
            "formation() writes the agreed member set + mesh shape here "
            "(atomic publish), and a RESTARTED rank compares the recorded "
            "process count against its env — a changed "
            "H2O3_TPU_NUM_PROCESSES is logged as an ELASTIC TRANSITION "
            "(scale-down after preemption / scale-up after autoscale) and "
            "the rank bootstraps into the NEW shape instead of "
            "crash-looping against the old barrier count; a rank whose "
            "ordinal fell off the shrunk formation exits cleanly (retired) "
            "instead of raising. '' = <tmpdir>/h2o3tpu_formation_<uid>."
            "json; '0' disables the manifest"),
    "H2O3_TPU_AUTOML_STEP_RETRIES": (
        "2", "AutoML poison-step guard: a plan step whose build has already "
             "crashed this many recorded attempts (the step manifest "
             "tracks per-step attempt counts across auto-resumes) is "
             "SKIPPED with a Log.warn instead of killing every resume at "
             "the same place forever. 0 = unlimited attempts (the "
             "pre-guard behavior)"),
    "H2O3_TPU_MAX_INFLIGHT": (
        "64", "REST admission gate: max concurrently executing mutating "
              "(POST/DELETE) requests; excess requests are shed with "
              "429 + Retry-After instead of piling up threads. 0 = unbounded"),
    "H2O3_TPU_MAX_QUEUED_JOBS": (
        "32", "REST admission gate: max live (pending+running) REST-created "
              "jobs; job-creating requests beyond it are shed with "
              "503 + Retry-After. 0 = unbounded"),
    "H2O3_TPU_OVERLOAD": (
        "1", "overload-survival plane (utils/overload.py): memory-aware "
             "admission with per-job HBM reservations "
             "(hbm_reserved_bytes{job}) and streamed-lane auto-routing, "
             "RESOURCE_EXHAUSTED catch-and-degrade (one supervised retry "
             "in streamed/halved-window mode, oom_degrades_total), the "
             "dispatch hang watchdog (dispatch_hangs_total), and computed "
             "Retry-After on shed responses. '0' disables the whole plane "
             "and pins pre-overload behavior bit-for-bit (static-window "
             "routing only, no reservations, no OOM retry, no watchdog, "
             "historical Retry-After constants)"),
    "H2O3_TPU_ADMIT_MIN_HEADROOM_BYTES": (
        "0", "REST admission memory gate: mutating requests are shed with "
             "503 + computed Retry-After (reason 'memory') while measured "
             "devmem.headroom() is below this many bytes — the cheap "
             "whole-server pressure valve in front of the per-job "
             "footprint check. 0 = off; backends without memory_stats "
             "(the CPU proxy) are never gated"),
    "H2O3_TPU_ADMIT_HEADROOM_FRAC": (
        "0.7", "share of measured device headroom the admission preflight "
               "treats as usable by job data (the rest stays free for "
               "compiled programs and temporaries — the capacity-model "
               "USABLE_FRACTION). Footprints are admitted resident against "
               "frac*headroom net of live reservations; larger jobs "
               "auto-route to the streamed lane; jobs that fit nowhere "
               "shed 503"),
    "H2O3_TPU_HANG_FACTOR": (
        "8", "dispatch hang watchdog trip multiplier: a dispatch open "
             "longer than FACTOR x its site's rolling mean completed "
             "duration (and past H2O3_TPU_HANG_MIN_SECS) is declared "
             "wedged — dispatch_hangs_total ticks, an incident bundle "
             "freezes the ring, the degraded latch trips and supervised "
             "jobs resume from their latest snapshot"),
    "H2O3_TPU_HANG_MIN_SECS": (
        "120", "dispatch hang watchdog floor, seconds: no dispatch is "
               "declared wedged before this age regardless of baseline — "
               "sites with fewer than 3 completed dispatches use ONLY the "
               "floor, so a legitimately long first compile never "
               "false-trips"),
    "H2O3_TPU_HANG_POLL_SECS": (
        "2", "dispatch hang watchdog poll cadence, seconds (background "
             "daemon installed by start_server/launch)"),
    "H2O3_TPU_REQUEST_READ_TIMEOUT": (
        "60", "REST per-connection socket read deadline, seconds — a client "
              "that stops sending mid-request cannot pin a handler thread "
              "forever. 0 = no deadline"),
    "H2O3_TPU_HANDLER_DEADLINE_SECS": (
        "300", "deadline for REST handlers that wait synchronously on a job "
               "(SplitFrame/CreateFrame/Interaction): past it the route "
               "returns 504 with the job key and the job keeps running "
               "(poll /3/Jobs). 0 = unbounded"),
    "H2O3_TPU_JOB_DEADLINE_SECS": (
        "0", "default deadline applied to every REST-created job, seconds; "
             "enforced between iterations via the soft-deadline plumbing "
             "(iterative builders truncate GRACEFULLY, keeping the partial "
             "model) and surfaced as 'deadline' on /3/Jobs. 0 = none"),
    "H2O3_TPU_SPMD_WATCHDOG_SECS": (
        "0", "collective watchdog: a replicated command still running after "
             "this many seconds is presumed wedged mid-collective and trips "
             "the fail-stop degraded latch (coordinator-side only — rank "
             "clocks diverge, so followers never arm it). 0 = disabled "
             "(the default: only an operator who knows the workload's "
             "longest legitimate command should set a budget)"),
    "H2O3_TPU_DRAIN_TIMEOUT_SECS": (
        "30", "graceful-drain bound for H2OServer.stop(drain=True) / "
              "POST /3/Shutdown?drain=true: how long to wait for running "
              "jobs to truncate and flush checkpoints before the listener "
              "closes anyway"),
    "H2O3_TPU_SCORE_BATCH_WINDOW_MS": (
        "2", "scoring tier micro-batch window: concurrent "
             "/3/Predictions/rows requests for one model coalesce for up to "
             "this many ms (or until H2O3_TPU_SCORE_BATCH_MAX rows) and "
             "dispatch as ONE device call. 0 = per-request dispatch (the "
             "unbatched control lane of the load-test A/B)"),
    "H2O3_TPU_SCORE_BATCH_MAX": (
        "4096", "scoring tier: max rows per batched dispatch — a full batch "
                "dispatches immediately without waiting out the window"),
    "H2O3_TPU_SCORE_DEADLINE_MS": (
        "2000", "per-request deadline on /3/Predictions/rows: a request "
                "that cannot be scored within this budget is shed with 504 "
                "+ Retry-After instead of queueing unboundedly (a late "
                "scoring answer is worthless). 0 = no deadline"),
    "H2O3_TPU_SCORE_QUEUE_MAX": (
        "32768", "scoring tier admission bound: max rows waiting in the "
                 "coalescing queue; arrivals beyond it are shed with 429 + "
                 "Retry-After. 0 = unbounded"),
    "H2O3_TPU_SERVE_REGISTRY": (
        "auto", "fleet serving registry (serving/registry.py): scoring "
                "replicas resolve /3/Predictions/rows model keys through a "
                "generation-tagged model registry fed by a watch-and-load "
                "loop over shared storage, so AutoML winners roll out with "
                "no operator action. 'auto' = on when "
                "H2O3_TPU_SERVE_WATCH_DIR is set; '1' = registry resolution "
                "on even without a watch dir (models enter via /3/Recover-"
                "style explicit loads); '0' = off — restores the PR-7 "
                "manual-load behavior bit-for-bit (models only via "
                "/99/Models.bin + DKV)"),
    "H2O3_TPU_SERVE_WATCH_DIR": (
        "", "shared model store the serving registry watches: every "
            "serialize_model file in this directory (the same files "
            "save_model / AutoML export_checkpoints_dir write) is loaded "
            "and kept current by mtime/size etag polling — a changed file "
            "swaps in as a NEW generation of its model key; in-flight "
            "batches finish on the old generation. '' = no watching "
            "(registry still serves explicitly loaded models under "
            "SERVE_REGISTRY=1)"),
    "H2O3_TPU_SERVE_POLL_SECS": (
        "5", "serving-registry watch poll period, seconds: an exported "
             "model is picked up within one poll (the rollout latency "
             "floor). Polling is one directory scan + per-file stat etag "
             "probes (persist.probe) — no bytes are read unless an etag "
             "changed"),
    "H2O3_TPU_SERVE_HBM_BYTES": (
        "0", "device-memory budget for resident scorer model payloads "
             "(serving/residency.py): the stacked forests / coefficient / "
             "MLP-parameter device arguments of compiled scorer lanes live "
             "in an LRU bounded by this many bytes — past it, "
             "least-recently-scored models demote to their host-RAM "
             "mirrors (page-in re-uploads on next score, "
             "serving_page_in_seconds) so one replica serves far more "
             "models than fit in HBM. The budget floor is one model: the "
             "model currently dispatching is never evicted. '0' (default) "
             "= unbounded, every scored model stays device-resident "
             "(the pre-fleet behavior)"),
    "H2O3_TPU_SCORE_IDLE_SECS": (
        "30", "scoring-tier idle reaping: a per-model batcher whose "
              "dispatcher thread saw no work for this many seconds retires "
              "the thread, drops the batcher from the per-model cache and "
              "demotes the model's scorer device arguments to host RAM — "
              "an idle model costs neither a parked thread nor HBM. The "
              "next request rebuilds the batcher and pages the scorer "
              "back in"),
    "H2O3_TPU_SERVE_WARM_MODELS": (
        "0", "serving-registry warm boot (serving/registry.py): at replica "
             "boot the watcher's FIRST poll pre-loads the newest N model "
             "files from the watch dir, pages their payloads into device "
             "residency and precompiles each model's smallest scoring "
             "shape bucket — a fresh HPA replica serves its first request "
             "at speed instead of paying model load + page-in + compile on "
             "the request path. 0 = no warm-up (load on first pickup, "
             "compile on first request — the pre-warm behavior)"),
    "H2O3_TPU_SERVE_BAD_GEN_ERRORS": (
        "3", "serving-registry rollout breaker: this many consecutive "
             "scoring failures on a freshly rolled-out model generation "
             "trip a rollback — the registry re-serves the previous "
             "generation and quarantines the bad file's etag (it will not "
             "be reloaded until the file changes). A successful score "
             "resets the count. 0 = never roll back"),
    "H2O3_TPU_FLIGHTREC_SIZE": (
        "4096", "incident flight recorder ring capacity, events "
                "(utils/flightrec.py): the always-on bounded ring of "
                "structured dispatch/collective/residency/cluster events "
                "every process keeps — O(µs) lock-free append, read once "
                "at import like H2O3_TPU_METRICS (the append is the hot "
                "path). Served over GET /3/FlightRecorder and frozen into "
                "incident bundles. '0' disables the ring (incident "
                "bundles still capture metrics/devmem/logs)"),
    "H2O3_TPU_DEVMEM_POLL_SECS": (
        "5", "device-memory ledger poll period, seconds "
             "(utils/devmem.py): how often device.memory_stats() is "
             "actually read — the ONE reader behind the "
             "device_hbm_bytes{device,kind} gauges, the computed "
             "hbm_owned_bytes{owner=unattributed} series, the "
             "hbm_headroom_bytes gauge and /3/Cloud's per-node memory "
             "fields. Dispatch boundaries and the background poller both "
             "refresh through this rate limit, so a hot loop never "
             "reads stats more than once per period"),
    "H2O3_TPU_INCIDENT_DIR": (
        "", "directory incident bundles are written to "
            "(utils/flightrec.py: ring dump + metrics snapshot + devmem "
            "attribution + log tail, atomic through persist — any persist "
            "scheme works, s3://... included). '' = "
            "<system tmp>/h2o3_incidents"),
    "H2O3_TPU_PREDICTIONS_RETAIN": (
        "64", "bounded retention of GENERATED /3/Predictions result frames: "
              "the newest N generated prediction frames stay in the DKV, "
              "older ones are removed (replicated delete) — serving load no "
              "longer grows the DKV without bound. Frames named explicitly "
              "via predictions_frame are never auto-evicted. 0 = keep all "
              "(the pre-retention behavior)"),
}


def get(name: str) -> str:
    default, _ = _KNOBS[name]
    return os.environ.get(name, default)


def get_int(name: str) -> int:
    return int(get(name))


def get_float(name: str) -> float:
    return float(get(name))


def get_bool(name: str) -> bool:
    return get(name) not in ("0", "false", "False", "")


def describe() -> str:
    lines = ["h2o3_tpu runtime configuration:"]
    for name, (default, doc) in _KNOBS.items():
        cur = os.environ.get(name)
        mark = f"{cur!r} (env)" if cur is not None else f"{default!r} (default)"
        lines.append(f"  {name:24s} = {mark:24s} — {doc}")
    return "\n".join(lines)
