"""ctypes binding + build driver for the native tmojo scoring runtime
(``native/tmojo_score.cpp``) — the C++ half of the genmodel successor
(SURVEY.md §2.3; upstream ships the equivalent as the h2o-genmodel Java
runtime [UNVERIFIED]).

``forest_blob(mojo)`` flattens a loaded tree tmojo's per-level arrays into
the contiguous layout the C ABI expects (done once per model, cached on the
MojoModel); ``score_forest`` then walks trees row-major with per-row early
exit. The library auto-builds with g++ on first use (cached beside the
source; rebuilt when the source is newer) — no Python build-time machinery
needed, and everything degrades to the numpy replay when no compiler is
available.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_LOCK = threading.Lock()
_LIB: ctypes.CDLL | None = None
_BUILD_FAILED = False

_SRC = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    "native", "tmojo_score.cpp")
_SO = os.path.join(os.path.dirname(_SRC), "libtmojo.so")

_I64P = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
_I32P = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
_U8P = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
_F32P = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
_F64P = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")


def _build() -> str | None:
    if os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
        return _SO
    # Compile to a process-unique temp name and publish atomically: the
    # threading lock above only covers THIS process, but parallel pytest
    # workers (or two servers) race on the shared .so path — a reader must
    # never CDLL a half-written file.
    tmp = f"{_SO}.{os.getpid()}.tmp"
    for flags in (["-fopenmp"], []):  # openmp when the toolchain has it
        cmd = ["g++", "-O3", "-shared", "-fPIC", *flags, _SRC, "-o", tmp]
        try:
            r = subprocess.run(cmd, capture_output=True, timeout=120)
            if r.returncode == 0:
                os.replace(tmp, _SO)
                return _SO
        except (OSError, subprocess.TimeoutExpired):
            break
        finally:
            if os.path.exists(tmp):
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
    return None


def get_lib() -> ctypes.CDLL | None:
    """The loaded native library, or None (no compiler / build failed)."""
    global _LIB, _BUILD_FAILED
    if _LIB is not None or _BUILD_FAILED:
        return _LIB
    with _LOCK:
        if _LIB is not None or _BUILD_FAILED:
            return _LIB
        so = _build()
        if so is None:
            _BUILD_FAILED = True
            return None
        try:
            lib = ctypes.CDLL(so)
        except OSError:
            # e.g. a concurrent process replaced the file mid-load, or a
            # stale/corrupt artifact — degrade to the numpy path like any
            # other build failure rather than crash enabled()/available()
            _BUILD_FAILED = True
            return None
        lib.tmojo_score_forest.restype = None
        lib.tmojo_score_forest.argtypes = [
            _U8P, ctypes.c_int64, ctypes.c_int64,          # bins, n, C
            ctypes.c_int64, ctypes.c_int64,                # n_trees, K
            _I64P, _I64P, _I64P,                           # starts, counts, offs
            _I32P, _I32P, _U8P, _U8P, ctypes.c_int64,      # col, bin, iscat, mask, B
            _U8P, _U8P, _F32P, _I32P,                      # naleft, leaf, val, child
            _F64P,                                         # out
        ]
        lib.tmojo_bin_numeric.restype = None
        lib.tmojo_bin_numeric.argtypes = [
            _F32P, ctypes.c_int64, _F32P, ctypes.c_int64, _U8P,
        ]
        _LIB = lib
        return _LIB


def available() -> bool:
    return get_lib() is not None


def enabled() -> bool:
    """Native path on: not opted out via H2O3_TPU_NATIVE=0 AND buildable."""
    from h2o3_tpu import config

    if not config.get_bool("H2O3_TPU_NATIVE"):
        return False
    return available()


def forest_blob(mojo) -> dict:
    """Flatten a tree tmojo's level arrays into the C layout (cached)."""
    blob = getattr(mojo, "_native_blob", None)
    if blob is not None:
        return blob
    a = mojo.arrays
    shapes = mojo.meta["tree_levels"]  # [tree][class] -> n_levels
    K = mojo.meta["n_tree_classes"]
    n_trees = len(shapes)

    starts = np.zeros(n_trees * K, np.int64)
    counts = np.zeros(n_trees * K, np.int64)
    offs: list[int] = []
    cols, bins_, iscat, naleft, leaf, child = [], [], [], [], [], []
    vals, masks = [], []
    B = None
    node_off = 0
    lvl_i = 0
    for ti in range(n_trees):
        for ki in range(K):
            starts[ti * K + ki] = lvl_i
            counts[ti * K + ki] = shapes[ti][ki]
            for li in range(shapes[ti][ki]):
                pre = f"t{ti}_k{ki}_l{li}_"
                sc = np.asarray(a[pre + "split_col"], np.int32)
                offs.append(node_off)
                node_off += len(sc)
                lvl_i += 1
                cols.append(sc)
                bins_.append(np.asarray(a[pre + "split_bin"], np.int32))
                iscat.append(np.asarray(a[pre + "is_cat"], np.uint8))
                m = np.asarray(a[pre + "cat_mask"], np.uint8)
                if B is None:
                    B = m.shape[1]
                masks.append(m)
                naleft.append(np.asarray(a[pre + "na_left"], np.uint8))
                leaf.append(np.asarray(a[pre + "leaf_now"], np.uint8))
                vals.append(np.asarray(a[pre + "leaf_val"], np.float32))
                child.append(np.asarray(a[pre + "child_base"], np.int32))

    blob = {
        "n_trees": n_trees, "K": K, "B": int(B or 1),
        "starts": starts, "counts": counts,
        "offs": np.asarray(offs, np.int64),
        "split_col": np.ascontiguousarray(np.concatenate(cols)),
        "split_bin": np.ascontiguousarray(np.concatenate(bins_)),
        "is_cat": np.ascontiguousarray(np.concatenate(iscat)),
        "cat_mask": np.ascontiguousarray(np.concatenate(masks, axis=0)).reshape(-1),
        "na_left": np.ascontiguousarray(np.concatenate(naleft)),
        "leaf_now": np.ascontiguousarray(np.concatenate(leaf)),
        "leaf_val": np.ascontiguousarray(np.concatenate(vals)),
        "child_base": np.ascontiguousarray(np.concatenate(child)),
    }
    mojo._native_blob = blob
    return blob


def score_forest(mojo, bins: np.ndarray) -> np.ndarray:
    """Walk the whole forest natively: (n, K) float64 leaf sums."""
    lib = get_lib()
    assert lib is not None, "native library unavailable"
    blob = forest_blob(mojo)
    bins_u8 = np.ascontiguousarray(bins.astype(np.uint8))
    n, C = bins_u8.shape
    out = np.zeros((n, blob["K"]), np.float64)
    lib.tmojo_score_forest(
        bins_u8, n, C, blob["n_trees"], blob["K"],
        blob["starts"], blob["counts"], blob["offs"],
        blob["split_col"], blob["split_bin"], blob["is_cat"],
        blob["cat_mask"], blob["B"],
        blob["na_left"], blob["leaf_now"], blob["leaf_val"],
        blob["child_base"], out,
    )
    return out


def bin_numeric(x: np.ndarray, edges: np.ndarray) -> np.ndarray:
    """Native float32 searchsorted binning (code 0 = NaN)."""
    lib = get_lib()
    assert lib is not None, "native library unavailable"
    xf = np.ascontiguousarray(x, np.float32)
    ef = np.ascontiguousarray(edges, np.float32)
    out = np.empty(len(xf), np.uint8)
    lib.tmojo_bin_numeric(xf, len(xf), ef, len(ef), out)
    return out
