"""Quantized collective lane + hierarchical reduction placement — the
wire-level successor of the PR-5/PR-8 sharded reduces (ROADMAP item 3).

Every hot cross-device reduction in the stack (the tree histogram
``hist_reduce``, the GLM Gram ``gram_reduce``, the DL gradient
``dl_grad_reduce``) used to move full-precision float32. EQuARX
(arXiv:2506.17615) shows a block-quantized allreduce inside XLA recovers
most of that bandwidth at negligible accuracy cost, and arXiv:2110.10548
shows reduction *placement* on hierarchical interconnects (reduce within
the cheap level first, cross the expensive one with less) is a second,
independent multiplier. This module provides both as drop-in wrappers for
``lax.psum`` / ``lax.psum_scatter`` (scatter dimension 0, tiled), used
inside the existing ``shard_map`` bodies:

- **Block quantization** (``H2O3_TPU_COLLECTIVE_QUANT``): each device's
  local contribution is split into per-chunk payloads, blocked
  (``H2O3_TPU_COLLECTIVE_QUANT_BLOCK`` elements per block), and encoded as
  an int8 payload + one f32 scale per block. The reduce itself decomposes
  into ``all_to_all`` (the int8 payload + scales really are what crosses
  the wire — this is not an emulation) followed by a dequantize-sum in
  f32. Scales are POWERS OF TWO: scaling is then exact in f32, so any
  block whose values are integers with magnitude <= 127 round-trips
  BIT-EXACTLY — which is precisely the regime of the PR-5 adversarial tie
  suites (unit weights, integer targets), so split decisions there stay
  bit-identical to the exact lane. ``passes=2`` adds a residual-correction
  pass (quantize and ship ``x - dequant(quant(x))`` too, ~14 effective
  mantissa bits): the gain/solve-critical reduces (GLM Gram, DL gradient)
  run with it so IRLS coefficients stay inside the pinned parity
  envelopes; when pass 1 is already exact the residual is exactly zero.
- **Exact side lanes**: small gain-critical payloads that feed argmaxes or
  solves directly (the packed GLM b/deviance psum, node totals, winner
  gathers, the solve's G all_gather, the DL updated-param gather) stay
  f32 — only the bulk reduce payload quantizes.
- **Hierarchical two-stage reduction** (``H2O3_TPU_COLLECTIVE_HIER``, mesh
  levels resolved by ``parallel/mesh.hier_inner``): stage 1 reduces
  exactly within each contiguous inner sub-axis group (the ICI level),
  stage 2 moves only the (quantized) chunk payloads across groups (the DCN
  level) via grouped ``all_to_all``. The tiled chunk-d-to-device-d
  contract of ``psum_scatter`` is preserved by remapping each device's
  outer-strided chunk set before the cross-group exchange.

Consistency invariant (load-bearing for the PR-5 parity suites): the
wrapped ``psum`` is implemented as the wrapped reduce-scatter over the same
P-chunk grid followed by an EXACT all_gather, so a replicated reduction's
chunk ``d`` is bit-identical to what the sharded lane hands device ``d`` —
for ANY data, quantized or not. ``H2O3_TPU_COLLECTIVE_QUANT=0`` (with the
hierarchy knob unset) routes every call straight to the stock primitives:
bit-for-bit the pre-lane programs.

This module also owns the trace-time collective byte tally (moved here
from ``ops/histogram.py``; the old names are re-exported there). Entries
now carry a ``lane`` (``quant``/``exact``) so
``tree_collective_bytes_total`` can expose the wire-compression claim as a
counter dimension, and a ``group`` tag replacing the old trace-time weight
multiplier: entries recorded under ``tally_group("sat")`` are scaled at
DISPATCH time by the saturated-region iterations the program actually
executed (read from the build stats), not by the trace-time upper bound.
"""

from __future__ import annotations

import contextlib
import contextvars

import jax
import jax.numpy as jnp

from h2o3_tpu.parallel.mesh import ROWS_AXIS

# ---------------------------------------------------------------------------
# collective byte tally — trace-time accounting of the cross-device payload
# the compiled programs move. Collectives live inside fused jitted programs,
# so per-execution host counting is impossible; instead every collective
# call site records, AT TRACE TIME, the bytes its one execution will move,
# and the dispatching caller (shared_tree._run_counted) captures the tally
# during the program's first trace and replays it per dispatch. The model is
# REPLICATION VOLUME — the reduced/gathered bytes the collective leaves on
# each device (psum: the full reduced tensor, psum_scatter: only the kept
# 1/P shard, all_gather: P x the local contribution) — except that the
# quant lane's reduce entries count the COMPRESSED payload (int8 + scales,
# the wire bytes a real quantized collective moves), which is the whole
# point of the lane. A 1-device mesh moves nothing and tallies 0.

_TALLY: contextvars.ContextVar[list | None] = contextvars.ContextVar(
    "h2o3_coll_tally", default=None
)
_TALLY_GROUP: contextvars.ContextVar[str] = contextvars.ContextVar(
    "h2o3_coll_group", default=""
)


@contextlib.contextmanager
def collective_tally(out: list):
    """Collect (phase, lane, group, bytes) entries recorded while tracing
    under this."""
    tok = _TALLY.set(out)
    try:
        yield out
    finally:
        _TALLY.reset(tok)


@contextlib.contextmanager
def tally_group(name: str):
    """Tag entries recorded inside with a dispatch-time weight group.

    The node_cap-saturated ``while_loop`` body traces ONCE but executes a
    data-dependent number of times; entries recorded under
    ``tally_group("sat")`` are multiplied at dispatch time by the EXECUTED
    iteration count the program returns (shared_tree._run_counted), so the
    counters report actual volume instead of the old n_sat upper bound."""
    tok = _TALLY_GROUP.set(name)
    try:
        yield
    finally:
        _TALLY_GROUP.reset(tok)


def record_collective(phase: str, nbytes: float, lane: str = "exact") -> None:
    lst = _TALLY.get()
    if lst is not None and nbytes > 0:
        lst.append((phase, lane, _TALLY_GROUP.get(), float(nbytes)))


def record_hbm(path: str, nbytes: float) -> None:
    """Trace-time tally of the MODELED per-device HBM traffic of the
    histogram+split phases (``tree_hist_hbm_bytes_total{path}``): one write
    per materialized intermediate plus one read per consumed one, recorded
    where the intermediates are created and replayed per dispatch by
    shared_tree._run_counted — the fused pipeline's acceptance metric. Rides
    the same tally as the collective bytes under an ``hbm/`` phase prefix."""
    record_collective("hbm/" + path, nbytes)


# ---------------------------------------------------------------------------
# lane configuration


def quant_enabled() -> bool:
    """Whether the block-quantized lane is on. ``auto`` (default) engages
    only when the mesh spans >1 process — the ICI+DCN regime EQuARX targets,
    where wire bytes are the binding constraint; ``1`` forces it anywhere
    (the A/B + parity-test lane); ``0`` restores the stock collectives
    bit-for-bit."""
    from h2o3_tpu import config

    v = config.get("H2O3_TPU_COLLECTIVE_QUANT").strip().lower()
    if v in ("auto", ""):
        return jax.process_count() > 1
    return v not in ("0", "false")


def quant_block() -> int:
    from h2o3_tpu import config

    return max(8, config.get_int("H2O3_TPU_COLLECTIVE_QUANT_BLOCK"))


def quant_key() -> tuple:
    """Program-cache component: the lane changes the traced collectives, so
    a program compiled under one (quant, block, hierarchy) setting must
    never serve another. Folded into ``parallel/mesh.mesh_key`` so every
    tree/GLM/DL program cache picks it up through the one chokepoint."""
    from h2o3_tpu.parallel.mesh import hier_inner, n_col_shards

    return (quant_enabled(), quant_block(), hier_inner(n_col_shards()))


def lane_active(n_dev: int) -> bool:
    from h2o3_tpu.parallel.mesh import hier_inner

    return n_dev > 1 and (quant_enabled() or hier_inner(n_dev) > 0)


def payload_bytes(nelem: int, quant: bool, block: int, passes: int) -> float:
    """Wire bytes of one ``nelem``-element reduce payload: int8 + one f32
    scale per block, per pass, vs plain f32."""
    if not quant:
        return nelem * 4.0
    return float(nelem) * passes * (1.0 + 4.0 / block)


def modeled_reduce_bytes(
    nelem: int, n_dev: int, *, passes: int = 1
) -> dict[str, float]:
    """Per-lane replication-volume model of ONE wrapped ``psum_scatter``
    over ``nelem`` elements — what the GLM/DL host tallies (which cannot
    ride the trace-time tally) record per executed iteration/minibatch.
    Mirrors the wrapper's own recording exactly, including the 2-D mesh's
    stage-1 exact rows-axis psum (``n_dev`` stays the TOTAL device count;
    the lane geometry is read from the process mesh)."""
    from h2o3_tpu.parallel.mesh import hier_inner, n_col_shards, n_row_groups

    if n_dev <= 1:
        return {}
    quant = quant_enabled()
    rows = n_row_groups()
    ncol = n_col_shards()
    inner = hier_inner(ncol)
    out = {"exact": 0.0, "quant": 0.0}
    if rows > 1:
        out["exact"] += nelem * 4.0  # stage-1 exact rows-axis reduce
    if ncol > 1:
        if not quant and not inner:
            out["exact"] += nelem * 4.0 / ncol
        else:
            if inner:
                out["exact"] += nelem * 4.0  # intra-group exact reduce
            out["quant" if quant else "exact"] += payload_bytes(
                nelem // ncol, quant, quant_block(), passes
            )
    return {k: v for k, v in out.items() if v}


# ---------------------------------------------------------------------------
# block quantizer (int8 payload + power-of-two f32 scale per block)


def _encode8(xb):
    """``xb``: (..., nblk, B) f32 → (int8 same shape, f32 (..., nblk)).

    The per-block scale is the smallest POWER OF TWO ``s`` with
    ``max|x|/s <= 127``: scaling by a power of two is exact in f32, so
    integer-valued blocks with magnitude <= 127 (the adversarial tie
    suites' regime) quantize losslessly. An all-zero block gets s=1."""
    amax = jnp.max(jnp.abs(xb), axis=-1)
    e = jnp.ceil(jnp.log2(jnp.maximum(amax, 1e-38) / 127.0))
    s = jnp.where(amax > 0, jnp.exp2(e), 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(xb / s[..., None]), -127.0, 127.0).astype(jnp.int8)
    return q, s


def _decode8(q, s):
    return q.astype(jnp.float32) * s[..., None]


# ---------------------------------------------------------------------------
# the lane core


def _exchange_sum(flat, axis_name, groups, n_peers: int, quant: bool,
                  block: int, passes: int):
    """The reduce step of a reduce-scatter among ``n_peers`` devices (the
    whole axis when ``groups`` is None, else each listed group): ``flat``
    is (n_peers, L) with row ``p`` destined for peer ``p``; returns the
    (L,) dequantized sum of the rows this device received. Payloads cross
    as int8 + f32 block scales when ``quant`` (plus an int8 residual pass
    when ``passes >= 2``); the dequantize-sum runs in f32 in ascending
    peer order — a fixed order shared by the replicated and sharded
    wrappers, which is what keeps their results bit-identical."""
    L = flat.shape[1]
    if not quant:
        ft = jax.lax.all_to_all(
            flat, axis_name, 0, 0, axis_index_groups=groups)
        return ft.sum(axis=0)
    Lp = -(-L // block) * block
    fp = jnp.pad(flat, ((0, 0), (0, Lp - L)))
    xb = fp.reshape(n_peers, Lp // block, block)
    parts = [_encode8(xb)]
    if passes >= 2:
        # residual-correction pass: exactly zero when pass 1 was lossless
        parts.append(_encode8(xb - _decode8(*parts[0])))
    acc = jnp.zeros_like(xb)
    for q, s in parts:
        qt = jax.lax.all_to_all(q, axis_name, 0, 0, axis_index_groups=groups)
        st = jax.lax.all_to_all(s, axis_name, 0, 0, axis_index_groups=groups)
        acc = acc + _decode8(qt, st)
    return acc.sum(axis=0).reshape(Lp)[:L]


def _scatter_lane(x, axis_name, n_dev: int, phase: str | None, passes: int,
                  lane_axis: int | None = None):
    """The wrapped tiled reduce-scatter over axis 0 (chunk d → device d),
    lane active. ``x`` axis 0 must be divisible by ``n_dev``.

    ``lane_axis`` names a STAT-LANE axis of ``x`` (e.g. the histogram's S
    axis, whose {w, wy, wh} lanes differ by orders of magnitude): it is
    moved next to the chunk axis before the per-chunk flattening so
    quantization blocks never straddle lanes — each lane gets scales
    matched to its own magnitude instead of the largest cohabitant's.
    Purely an internal re-blocking: the returned chunk is in ``x``'s
    layout, and the exact path ignores it entirely."""
    from h2o3_tpu.parallel.mesh import hier_groups, hier_inner

    if lane_axis is not None and quant_enabled():
        ax = lane_axis % x.ndim
        assert ax != 0, "lane_axis cannot be the scatter axis"
        moved = _scatter_lane(
            jnp.moveaxis(x, ax, 1), axis_name, n_dev, phase, passes)
        return jnp.moveaxis(moved, 1, ax)

    quant = quant_enabled()
    inner = hier_inner(n_dev)
    block = quant_block()
    nelem = int(x.size)
    M0 = x.shape[0]
    assert M0 % n_dev == 0, (M0, n_dev)
    chunk_shape = (M0 // n_dev,) + x.shape[1:]

    if inner:
        ig, xg = hier_groups(n_dev, inner)
        # stage 1: exact reduce within the (cheap, ICI-level) inner groups
        x1 = jax.lax.psum(x, axis_name, axis_index_groups=ig)
        if phase:
            record_collective(phase, nelem * 4.0, lane="exact")
        outer = n_dev // inner
        # stage 2: device d = (g, j) needs global chunk d = g*inner + j; the
        # chunks with index ≡ j (mod inner) live across the cross group
        # {(g', j)} — gather this device's outer-strided chunk set (ordered
        # by destination g') and exchange within the cross group
        xc = x1.reshape(n_dev, -1)
        j = jax.lax.axis_index(axis_name) % inner
        sel = j + inner * jnp.arange(outer)
        mine = jnp.take(xc, sel, axis=0)
        red = _exchange_sum(mine, axis_name, xg, outer, quant, block, passes)
    else:
        red = _exchange_sum(
            x.reshape(n_dev, -1), axis_name, None, n_dev, quant, block,
            passes)
    if phase:
        record_collective(
            phase, payload_bytes(nelem // n_dev, quant, block, passes),
            lane="quant" if quant else "exact")
    return red.reshape(chunk_shape)


# ---------------------------------------------------------------------------
# public wrappers (call inside shard_map bodies, like the lax primitives)


def _lane_geometry(mesh, axis_name: str | None, n_dev: int):
    """``(stage1_axis, lane_axis, lane_width)`` — the reduce decomposition
    for the current mesh. On a 2-D rows×cols mesh the wrappers first run an
    EXACT ``lax.psum`` over the ``rows`` axis (the contiguous-device /
    intra-host level — arXiv:2110.10548's placement expressed as mesh
    structure) and the lane proper (quantized, scattered) runs over
    ``cols`` alone; the legacy 1-D mesh keeps its single ``rows``-axis lane
    with the caller-passed ``n_dev`` width. An explicit ``axis_name`` pins
    a single-stage reduce over that axis (test/microbench lane)."""
    from h2o3_tpu.parallel.mesh import (
        COLS_AXIS, get_mesh, is_2d, n_row_groups,
    )

    if axis_name is not None:
        return None, axis_name, n_dev
    m = mesh or get_mesh()
    if is_2d(m):
        rows = n_row_groups(m)
        return (ROWS_AXIS if rows > 1 else None), COLS_AXIS, m.shape[COLS_AXIS]
    return None, ROWS_AXIS, n_dev


def psum_scatter(x, *, n_dev: int, phase: str | None = None,
                 passes: int = 1, lane_axis: int | None = None,
                 axis_name: str | None = None, mesh=None):
    """Drop-in for ``lax.psum_scatter(x, axis, scatter_dimension=0,
    tiled=True)`` routed through the quantized/hierarchical lane when
    active. ``phase`` (when given) records the byte tally — call sites
    whose dispatch loop tallies host-side (GLM/DL) pass None and use
    :func:`modeled_reduce_bytes`. ``passes=2`` adds the residual-correction
    pass (the solve-critical reduces); ``lane_axis`` keeps mixed-magnitude
    stat lanes in separate quantization blocks (see :func:`_scatter_lane`).

    ``n_dev`` is the TOTAL device count of the caller's mesh; on a 2-D
    rows×cols mesh the reduce decomposes as exact ``psum`` over ``rows`` +
    a ``cols``-wide scatter, so the result is sharded over the COLUMN-BLOCK
    axis (1/n_col_shards per device, replicated across rows groups)."""
    stage1, ax, ncol = _lane_geometry(mesh, axis_name, n_dev)
    if stage1 is not None:
        x = jax.lax.psum(x, stage1)
        if phase:
            record_collective(phase, x.size * 4.0, lane="exact")
    if ncol <= 1:
        return jax.lax.psum_scatter(
            x, ax, scatter_dimension=0, tiled=True)
    if not lane_active(ncol):
        if phase:
            record_collective(phase, x.size * 4.0 / ncol, lane="exact")
        return jax.lax.psum_scatter(
            x, ax, scatter_dimension=0, tiled=True)
    return _scatter_lane(x, ax, ncol, phase, passes, lane_axis)


def psum(x, *, n_dev: int, phase: str | None = None, passes: int = 1,
         lane_axis: int | None = None, axis_name: str | None = None,
         mesh=None):
    """Drop-in for ``lax.psum(x, axis)`` (leading-axis tensors). The lane
    form is reduce-scatter over the SAME chunk grid as
    :func:`psum_scatter` (axis 0 padded up to the lane width) + an EXACT
    all_gather — so a replicated reduction's chunk ``d`` stays
    bit-identical to the sharded lane's device-``d`` block, for any data.
    On a 2-D mesh both wrappers share the identical stage-1 rows-axis
    ``psum``, so the invariant carries over to the pod shape. The broadcast
    half stays f32 (exact lane) by design; the compression claim lives on
    the scatter pipeline, which is the default (``H2O3_TPU_SPLIT_SHARD=1``)."""
    stage1, ax, ncol = _lane_geometry(mesh, axis_name, n_dev)
    if stage1 is not None:
        x = jax.lax.psum(x, stage1)
        if phase:
            record_collective(phase, x.size * 4.0, lane="exact")
    if ncol <= 1:
        return jax.lax.psum(x, ax)
    if not lane_active(ncol):
        if phase:
            record_collective(phase, x.size * 4.0, lane="exact")
        return jax.lax.psum(x, ax)
    M0 = x.shape[0]
    M0p = -(-M0 // ncol) * ncol
    if M0p > M0:
        x = jnp.pad(x, ((0, M0p - M0),) + ((0, 0),) * (x.ndim - 1))
    red = _scatter_lane(x, ax, ncol, phase, passes, lane_axis)
    full = jax.lax.all_gather(red, ax, axis=0, tiled=True)
    if phase:  # the broadcast leaves the full reduced tensor on each device
        record_collective(phase, x.size * 4.0, lane="exact")
    return full[:M0]


def exact_psum(x, mesh=None):
    """Exact f32 ``psum`` over the FULL row-shard device set — the small
    gain/solve-critical side payloads (packed b/deviance, weight sums,
    losses). On a 2-D mesh it stages rows-then-cols so its float grouping
    matches the lane wrappers' stage-1 exactly; on the 1-D mesh it is the
    stock single-axis psum, bit-for-bit."""
    from h2o3_tpu.parallel.mesh import COLS_AXIS, get_mesh, is_2d

    m = mesh or get_mesh()
    if is_2d(m):
        return jax.lax.psum(jax.lax.psum(x, ROWS_AXIS), COLS_AXIS)
    return jax.lax.psum(x, ROWS_AXIS)


def exact_pmax(x, mesh=None, phase: str | None = None):
    """Exact ``pmax`` over the full row-shard device set — the min/max lanes
    of the sharded group-by segment reduce (extrema cannot ride the additive
    quant lane; they are exact by construction in any order). Staged
    rows-then-cols on a 2-D mesh like :func:`exact_psum`."""
    from h2o3_tpu.parallel.mesh import COLS_AXIS, get_mesh, is_2d

    m = mesh or get_mesh()
    if phase:
        record_collective(phase, x.size * 4.0, lane="exact")
    if is_2d(m):
        return jax.lax.pmax(jax.lax.pmax(x, ROWS_AXIS), COLS_AXIS)
    return jax.lax.pmax(x, ROWS_AXIS)


def exact_pmin(x, mesh=None, phase: str | None = None):
    """Exact ``pmin`` counterpart of :func:`exact_pmax`."""
    from h2o3_tpu.parallel.mesh import COLS_AXIS, get_mesh, is_2d

    m = mesh or get_mesh()
    if phase:
        record_collective(phase, x.size * 4.0, lane="exact")
    if is_2d(m):
        return jax.lax.pmin(jax.lax.pmin(x, ROWS_AXIS), COLS_AXIS)
    return jax.lax.pmin(x, ROWS_AXIS)


def all_to_all_exchange(x, *, axis_name: str = ROWS_AXIS,
                        phase: str | None = None):
    """Tiled ``all_to_all`` over leading axis 0 (bucket ``d`` of every
    device lands on device ``d``) with the trace-time byte tally — the
    radix-partition exchange step of the distributed hash join. Payloads
    stay exact (small int key codes + row indices; quantizing indices would
    corrupt the join), so the whole tensor counts as exact wire bytes."""
    if phase:
        record_collective(phase, x.size * x.dtype.itemsize, lane="exact")
    return jax.lax.all_to_all(x, axis_name, 0, 0, tiled=True)
