"""Device kernels: Gram accumulation, histogram builds (scatter-add on CPU,
MXU-matmul + Pallas kernels on TPU), segment reductions. The hot-loop successors
of ``hex.gram.Gram`` and ``hex.tree.ScoreBuildHistogram`` [UNVERIFIED
upstream paths]."""
