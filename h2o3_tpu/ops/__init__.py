"""Device kernels: Gram accumulation, histogram builds (XLA and Pallas
paths), segment reductions. The hot-loop successors of ``hex.gram.Gram`` and
``hex.tree.ScoreBuildHistogram`` [UNVERIFIED upstream paths]."""
