"""Histogram accumulation — successor of ``hex.tree.ScoreBuildHistogram2`` /
``DHistogram`` [UNVERIFIED upstream paths, SURVEY.md §2.2 §3.3], and the
replacement for the bundled XGBoost ``gpu_hist`` CUDA builder (§2.4).

The hot loop of tree building: for every row, look up its current leaf
``nid`` and scatter its per-stat values into (node, col, bin) cells; reduce
across row shards. Mapping:

- H2O's per-chunk fork-join map + pairwise reduce → per-device scatter-add
  + ``psum`` over the rows mesh axis (via ``shard_map``).
- The stat lanes are CALLER-DEFINED (``stats`` is a tuple of (n,) arrays):
  the GBM/DRF path passes {w, wy, wh} — 3 lanes, because the wy² term of
  H2O's DHistogram squared-error gain cancels exactly across
  parent−left−right and carrying it would be 33% more MXU/HBM work for a
  constant offset (see shared_tree._split_scan) — while uplift trees pass
  their 4 treatment/control lanes. Histogram cost is ∝ lanes, so every
  consumer pays exactly for what it reads.

Two device implementations, auto-selected by backend:
- scatter path (CPU mesh): one `.at[].add` scatter per column (vmapped) —
  fast on CPU, pathological on TPU (XLA serializes scatters; measured ~1.3s
  per 1M×20-col pass at 256 nodes vs ~0.1s for the matmul path).
- **matmul path (TPU)**: the histogram is recast as MXU work. Per row chunk,
  build ``A_s = onehot(nid) * stat_s`` (chunk, N) and the 0/1 col-bin
  indicator ``E`` (chunk, C·B); then ``hist_s = A_sᵀ @ E`` — a dense matmul
  the systolic array eats, no scatter at all. Rows are processed in
  ``lax.scan`` chunks so the (chunk, C·B) indicator transient stays ~100MB.
  Inactive rows (nid<0) match no one-hot column and vanish automatically.
  Inputs stay float32 (bf16 would quantize the gradient stats the split
  gains are computed from); XLA runs f32 dots as multi-pass bf16 on the MXU.
  This is the ScoreBuildHistogram→TPU redesign the north star asks for; the
  Pallas kernel (hist_pallas.py) fuses the indicator build into the dot.

``histogram_in_jit`` is the primary entry: a pure traced function usable
inside a larger jitted program (the tree level step), so histogram + split
scan + partition fuse into one compiled launch with zero host round-trips.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from h2o3_tpu.parallel.mesh import (
    col_block_spec,
    get_mesh,
    n_col_shards,
    pad_cols_to_shards,
    row_pspec,
    shard_map,
)

# The trace-time collective byte tally moved to ops/collectives.py (which
# also owns the quantized/hierarchical reduce lane the reductions below run
# through); re-exported here because this module is where the tally was
# born and half the stack imports it from here.
from h2o3_tpu.ops.collectives import (  # noqa: F401  (re-exports)
    collective_tally,
    record_collective,
    record_hbm,
    tally_group,
)

# Rows per scatter chunk: XLA materializes the vmapped scatter's updates as
# a (C, chunk, S) f32 broadcast (~1.2 KB/row at C=28, S=4 — measured 13.4 GB
# temp for the whole 10M-row tree program before chunking). 256k rows bounds
# the transient at ~115 MB; shards at or under the chunk take the
# single-chunk path, bit-identical to the unchunked original.
_SCATTER_ROW_CHUNK = 262_144


def _hist_scatter_local(bins_u8, nid, stats, n_nodes: int, n_bins: int):
    """Device-local scatter histogram: (C, n_nodes*n_bins, S).

    Rows with nid < 0 (finalized leaves / padding) MUST arrive with zeroed
    stats (``histogram_in_jit`` masks them): the scatter clamps their nid
    to 0 and a nonzero stat would pollute node 0.
    """
    S = stats.shape[1]
    nid_safe = jnp.maximum(nid, 0)

    def scatter_chunk(bins_c, nid_c, stats_c):
        def one_col(col):
            idx = nid_c * n_bins + col.astype(jnp.int32)
            out = jnp.zeros((n_nodes * n_bins, S), jnp.float32)
            return out.at[idx].add(stats_c)

        return jax.vmap(one_col, in_axes=1)(bins_c)  # (C, n_nodes*n_bins, S)

    n, C = bins_u8.shape
    if n <= _SCATTER_ROW_CHUNK:
        return scatter_chunk(bins_u8, nid_safe, stats)

    chunk = _SCATTER_ROW_CHUNK
    nchunks = -(-n // chunk)
    pad = nchunks * chunk - n
    if pad:  # padding rows carry zero stats — they land in bin 0 harmlessly
        bins_u8 = jnp.pad(bins_u8, ((0, pad), (0, 0)))
        nid_safe = jnp.pad(nid_safe, (0, pad))
        stats = jnp.pad(stats, ((0, pad), (0, 0)))

    def body(acc, args):
        return acc + scatter_chunk(*args), None

    acc0 = jnp.zeros((C, n_nodes * n_bins, S), jnp.float32)
    acc, _ = jax.lax.scan(
        body,
        acc0,
        (
            bins_u8.reshape(nchunks, chunk, C),
            nid_safe.reshape(nchunks, chunk),
            stats.reshape(nchunks, chunk, S),
        ),
    )
    return acc


def _select_local():
    """Backend-appropriate shard-local histogram implementation.

    Auto: scatter-add on CPU (fast there, pathological on TPU), the Pallas
    kernel (hist_pallas.py) on TPU. ``H2O3_TPU_HIST=matmul`` forces the
    plain-XLA MXU path, ``=scatter`` forces the scatter path, and
    ``=pallas`` forces the Pallas kernel (in the interpreter on CPU — the
    fused-pipeline parity/CI lane) on ANY backend, so A/B sweeps can reach
    all three local impls everywhere.
    """
    from h2o3_tpu import config

    override = config.get("H2O3_TPU_HIST")
    if override == "scatter":
        return _hist_scatter_local
    if override == "matmul":
        return _hist_matmul_local
    if override != "pallas" and jax.default_backend() == "cpu":
        return _hist_scatter_local

    def pallas_local(bins_u8, nid, stats, n_nodes, n_bins):
        from h2o3_tpu.ops.hist_pallas import hist_pallas_local, tiles_for

        return hist_pallas_local(
            bins_u8, nid, stats, n_nodes, n_bins,
            interpret=jax.default_backend() == "cpu",
            tiles=tiles_for(
                bins_u8.shape[1], n_nodes, n_bins, stats.shape[1]),
        )

    return pallas_local


def _local_is_pallas(local) -> bool:
    return local not in (_hist_scatter_local, _hist_matmul_local)


# ---------------------------------------------------------------------------
# int16 histogram accumulation lanes (ISSUE 16, H2O3_TPU_HIST_I16 —
# arXiv:1806.11248's quantized gradient/hessian accumulation). Each stat
# lane is rescaled per node so row values fit an int8-range code
# (scale = absmax/127; scale 1 — EXACT — when the node's lane is already
# small integers, the w/count lanes and the parity suites), accumulated
# through the unchanged local impl inside a ±32767 int16 cell budget, and
# rescaled back after. A node whose accumulated cells would exceed the
# budget trips the overflow latch: the whole shard-local pass recomputes in
# f32 on-device (lax.cond) and tree_hist_i16_overflows_total tallies. The
# rescale happens BEFORE the cross-device reduce, so per-shard scales need
# not agree and the collective lane (quantized or not) is untouched.

from h2o3_tpu.utils import metrics as _mx

_I16_OVERFLOWS = _mx.counter(
    "tree_hist_i16_overflows_total",
    "shard-local int16 histogram accumulations that tripped the overflow "
    "latch and recomputed in f32 (H2O3_TPU_HIST_I16)", always=True)


def _i16_enabled() -> bool:
    from h2o3_tpu import config

    return config.get_bool("H2O3_TPU_HIST_I16")


def _i16_overflow_cb(flag) -> None:
    if bool(flag):
        _I16_OVERFLOWS.inc()


def _i16_local(local, bins_u8, nid, stats, n_nodes: int, n_bins: int):
    """Quantized shard-local accumulation with the f32 overflow fallback."""
    S = stats.shape[1]
    nid_safe = jnp.maximum(nid, 0)
    amag = jnp.abs(stats)
    absmax = jnp.zeros((n_nodes, S), jnp.float32).at[nid_safe].max(
        amag, mode="drop")
    nonint = jnp.zeros((n_nodes, S), jnp.float32).at[nid_safe].max(
        (stats != jnp.round(stats)).astype(jnp.float32), mode="drop")
    exact = (absmax <= 127.0) & (nonint == 0.0)
    scale = jnp.where(exact, 1.0, jnp.maximum(absmax, 1e-30) / 127.0)
    q = jnp.round(stats / scale[nid_safe])
    hq = local(bins_u8, nid, q, n_nodes, n_bins)  # (C, n_nodes*n_bins, S)
    C = hq.shape[0]
    hq4 = hq.reshape(C, n_nodes, n_bins, S)
    overflow = (jnp.abs(hq4) > 32767.0).any()
    hist = jax.lax.cond(
        overflow,
        lambda _: local(bins_u8, nid, stats, n_nodes, n_bins),
        lambda _: (hq4 * scale[None, :, None, :]).reshape(
            C, n_nodes * n_bins, S),
        None,
    )
    jax.debug.callback(_i16_overflow_cb, overflow)
    return hist


def _maybe_i16(local):
    """Wrap a dense local impl in the i16 lane when the knob is on.

    The Pallas kernel accumulates in its own VMEM tiles and is left alone
    (documented in MIGRATION.md); read at trace time, so every program
    cache keyed on shared_tree._kernel_key retraces on a knob flip."""
    if not _i16_enabled() or _local_is_pallas(local):
        return local
    return partial(_i16_local, local)


_ROW_CHUNK = 8192  # rows per matmul chunk: (chunk, C*B) transient ≤ ~120MB


def _hist_matmul_local(bins_u8, nid, stats, n_nodes: int, n_bins: int):
    """MXU histogram for one shard: returns (C, n_nodes*n_bins, S)."""
    n, C = bins_u8.shape
    S = stats.shape[1]
    chunk = min(_ROW_CHUNK, n)
    nchunks = -(-n // chunk)
    pad = nchunks * chunk - n
    if pad:
        bins_u8 = jnp.pad(bins_u8, ((0, pad), (0, 0)))
        nid = jnp.pad(nid, (0, pad), constant_values=-1)
        stats = jnp.pad(stats, ((0, pad), (0, 0)))
    bins_ch = bins_u8.reshape(nchunks, chunk, C)
    nid_ch = nid.reshape(nchunks, chunk)
    stats_ch = stats.reshape(nchunks, chunk, S)

    iota_nodes = jnp.arange(n_nodes, dtype=jnp.int32)

    def body(acc, args):
        b_c, nid_c, s_c = args
        oh_nid = (nid_c[:, None] == iota_nodes[None, :]).astype(jnp.float32)
        # 0/1 (col,bin) indicator: each row lights exactly one bin per column
        oh_cb = (
            b_c[:, :, None].astype(jnp.int32)
            == jnp.arange(n_bins, dtype=jnp.int32)[None, None, :]
        ).astype(jnp.float32).reshape(chunk, C * n_bins)
        # stat-scaled nid one-hot with the S lanes folded into A's columns:
        # ONE (chunk, N*S) @ (chunk, C*B) dot instead of S separate dots —
        # same contraction over the same rows per output cell, so the result
        # is bit-identical, but the fused program carries one HLO dot per
        # chunk instead of S
        A = (oh_nid[:, :, None] * s_c[:, None, :]).reshape(chunk, -1)
        out = jax.lax.dot_general(
            A,
            oh_cb,
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).reshape(-1, S, C * n_bins)  # (N, S, C*B)
        return acc + jnp.transpose(out, (0, 2, 1)), None

    acc0 = jnp.zeros((n_nodes, C * n_bins, S), jnp.float32)
    acc, _ = jax.lax.scan(body, acc0, (bins_ch, nid_ch, stats_ch))
    # (N, C*B, S) -> (C, N*B, S) to match the scatter path's layout
    h = acc.reshape(n_nodes, C, n_bins, S)
    return jnp.transpose(h, (1, 0, 2, 3)).reshape(C, n_nodes * n_bins, S)


def histogram_in_jit(
    bins_u8, nid, stats, n_nodes: int, n_bins: int, mesh=None,
    *, col_sharded: bool = False, fused: bool = False,
):
    """Cross-device histogram, traceable inside a jitted program.

    ``stats`` is a TUPLE of (n,) row-sharded arrays — the stat lanes.
    Returns (n_nodes, C, n_bins, S), replicated across the mesh.

    ``col_sharded=True`` is the split-pipeline mode: the cross-device
    reduction ends in ``lax.psum_scatter`` over contiguous COLUMN blocks
    instead of a full ``psum`` — each device reduces (and keeps) only its
    C/P columns, moving 1/P of the all-reduce's replication volume — and the
    result comes back as (n_nodes, Cp, n_bins, S) with the column axis
    sharded over the mesh (Cp = C padded up to a multiple of the shard
    count; the padding columns hold all-zero histograms, are masked by the
    callers' column masks, and can never win a split). Each block's cells
    are bit-identical to the same slice of the replicated reduction, which
    is what lets the downstream per-block winner merge reproduce the
    replicated argmax exactly.

    ``fused=True`` (the ``H2O3_TPU_SPLIT_FUSE`` pipeline) returns
    ``(blk, layout)`` instead: the histogram in the Pallas kernel's NATIVE
    blocked tile layout (``hist_pallas.HistLayout``) with NO unscramble
    pass — the split kernel (``ops/split_pallas.py``) consumes the tiles
    directly in VMEM. Composes with ``col_sharded``: the reduce-scatter
    then runs over axis 0 (whole column tiles → contiguous column ranges
    per device) and the returned block is each device's 1/P slice; the full
    histogram never exists replicated anywhere. When the selected local
    impl is scatter/matmul (CPU CI, H2O3_TPU_HIST overrides) the dense
    result is re-blocked locally — a correctness lane, counted honestly by
    the HBM model.
    """
    mesh = mesh or get_mesh()
    local = _select_local()
    S = len(stats)
    n_dev = int(mesh.devices.size)
    n_col = n_col_shards(mesh)
    C = bins_u8.shape[1]
    Cp = pad_cols_to_shards(C, mesh) if col_sharded else C

    if fused:
        return _histogram_in_jit_fused(
            bins_u8, nid, stats, n_nodes, n_bins, mesh, local,
            col_sharded=col_sharded,
        )

    from h2o3_tpu.ops import collectives

    local_acc = _maybe_i16(local)

    def body(b, n, s):
        # retired/padding rows (nid < 0) carry zero stats into every impl
        s = jnp.where((n >= 0)[:, None], s, 0.0)
        h = local_acc(b, n, s, n_nodes, n_bins)
        # the cross-device reduction runs through the collective lane
        # (ops/collectives.py): stock psum/psum_scatter when the quant lane
        # is off — bit-for-bit the pre-lane program — or the block-
        # quantized / hierarchical variant when on; on a 2-D mesh the lane
        # itself stages an exact rows-axis psum first and scatters column
        # blocks over the cols axis only; the lane records the hist_reduce
        # byte tally (per lane) itself
        # lane_axis=-1: the S stat lanes {w, wy, wh} differ by orders of
        # magnitude and must not share quantization blocks
        if not col_sharded:
            return collectives.psum(
                h, n_dev=n_dev, phase="hist_reduce", lane_axis=-1, mesh=mesh)
        if Cp > C:
            # divisibility pad on the HISTOGRAM (cheap: hist-sized, not
            # bins-sized) so C < P and C % P != 0 stay correct with no
            # full-frame column padding anywhere
            h = jnp.pad(h, ((0, Cp - C), (0, 0), (0, 0)))
        return collectives.psum_scatter(
            h, n_dev=n_dev, phase="hist_reduce", lane_axis=-1, mesh=mesh)

    smat = jnp.stack(list(stats), axis=1)  # (n, S)

    # HBM model of the unfused pipeline (see record_hbm): the dense tensor
    # is written once and its (possibly column-sharded) slice re-read by the
    # split scan; the Pallas local impl additionally pays its two unscramble
    # passes over the padded kernel output. Terminal force-leaf levels skip
    # the scan read this counts — a deliberate (small) upper bound; the
    # saturated-region entries, by contrast, are scaled by the EXECUTED
    # iteration count at dispatch time (tally_group in collectives.py).
    dense_b = C * n_nodes * n_bins * S * 4
    scan_b = (Cp / n_col if col_sharded else C) * n_nodes * n_bins * S * 4
    if _local_is_pallas(local):
        from h2o3_tpu.ops.hist_pallas import plan_layout, tiles_for

        opad = plan_layout(
            C, n_nodes, n_bins, S, tiles=tiles_for(C, n_nodes, n_bins, S)
        ).nbytes
        record_hbm("pallas_unfused", 4 * opad + dense_b + scan_b)
    else:
        record_hbm("dense", dense_b + scan_b)

    # ph_hist: phase tag consumed by tools/profile_fused.py (HLO op_name
    # metadata carries the scope path into the profiler trace)
    rspec = row_pspec(mesh)
    with jax.named_scope("ph_hist"):
        h = shard_map(
            body,
            mesh=mesh,
            in_specs=(rspec, rspec, rspec),
            out_specs=col_block_spec(0, mesh) if col_sharded else P(),
            check_vma=False,
        )(bins_u8, nid, smat)  # (C[p], n_nodes*n_bins, S)
        return jnp.transpose(
            h.reshape(h.shape[0], n_nodes, n_bins, S), (1, 0, 2, 3)
        )  # (n_nodes, C[p], n_bins, S)


def _histogram_in_jit_fused(
    bins_u8, nid, stats, n_nodes: int, n_bins: int, mesh, local,
    *, col_sharded: bool,
):
    """Blocked-layout histogram body: see ``histogram_in_jit(fused=True)``."""
    from h2o3_tpu.ops.hist_pallas import (
        blocked_from_dense,
        hist_pallas_local,
        plan_layout,
        tiles_for,
    )

    S = len(stats)
    n_dev = int(mesh.devices.size)
    n_col = n_col_shards(mesh)
    C = bins_u8.shape[1]
    is_pallas = _local_is_pallas(local)
    layout = plan_layout(
        C, n_nodes, n_bins, S, tiles=tiles_for(C, n_nodes, n_bins, S),
        n_shards=n_col if col_sharded else 1,
    )

    from h2o3_tpu.ops import collectives

    def body(b, n, s):
        s = jnp.where((n >= 0)[:, None], s, 0.0)
        if is_pallas:
            h = hist_pallas_local(
                b, n, s, n_nodes, n_bins,
                interpret=jax.default_backend() == "cpu",
                blocked=True, tiles=layout.tiles,
                n_shards=n_col if col_sharded else 1,
            )
        else:
            h = blocked_from_dense(
                _maybe_i16(local)(b, n, s, n_nodes, n_bins), layout)
        # whole-column-tile reduce through the collective lane (quantized /
        # hierarchical when on, stock otherwise; 2-D meshes stage the exact
        # rows-axis psum first) — it records the hist_reduce tally per lane
        if not col_sharded:
            return collectives.psum(
                h, n_dev=n_dev, phase="hist_reduce", mesh=mesh)
        return collectives.psum_scatter(
            h, n_dev=n_dev, phase="hist_reduce", mesh=mesh)

    smat = jnp.stack(list(stats), axis=1)
    # HBM model (see record_hbm): the blocked tensor is written once by the
    # kernel and its (possibly 1/P) slice read once by the split kernel —
    # no unscramble pass exists. The dense-impl lane re-blocks locally and
    # pays for the dense intermediate it materializes.
    blk_scan = layout.nbytes / n_col if col_sharded else layout.nbytes
    if is_pallas:
        record_hbm("fused", layout.nbytes + blk_scan)
    else:
        dense_b = C * n_nodes * n_bins * S * 4
        record_hbm("fused_via_dense", 2 * dense_b + layout.nbytes + blk_scan)

    rspec = row_pspec(mesh)
    with jax.named_scope("ph_hist"):
        blk = shard_map(
            body,
            mesh=mesh,
            in_specs=(rspec, rspec, rspec),
            out_specs=col_block_spec(0, mesh) if col_sharded else P(),
            check_vma=False,
        )(bins_u8, nid, smat)
    return blk, layout


_BUILD_HIST_PROG: dict = {}


def build_histograms(bins_u8, nid, stats, n_nodes: int, n_bins: int):
    """Standalone jitted histogram (kept for tests / direct use).

    Cached per (shape statics, impl knobs): the local-impl selection and
    the i16 lane are trace-time decisions, so an env flip must reach a
    fresh program here just like in the tree builders."""
    from h2o3_tpu import config

    key = (n_nodes, n_bins, config.get("H2O3_TPU_HIST"), _i16_enabled(),
           jax.default_backend())
    prog = _BUILD_HIST_PROG.get(key)
    if prog is None:
        prog = jax.jit(
            partial(histogram_in_jit, n_nodes=n_nodes, n_bins=n_bins))
        _BUILD_HIST_PROG[key] = prog
    return prog(bins_u8, nid, stats)
