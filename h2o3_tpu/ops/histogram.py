"""Histogram accumulation — successor of ``hex.tree.ScoreBuildHistogram2`` /
``DHistogram`` [UNVERIFIED upstream paths, SURVEY.md §2.2 §3.3], and the
replacement for the bundled XGBoost ``gpu_hist`` CUDA builder (§2.4).

The hot loop of tree building: for every row, look up its current leaf
``nid`` and scatter its {w, wy, wy², wh} stats into (node, col, bin) cells;
reduce across row shards. Mapping:

- H2O's per-chunk fork-join map + pairwise reduce → per-device scatter-add
  + ``psum`` over the rows mesh axis (via ``shard_map``).
- Stats follow H2O's DHistogram ({Σw, Σwy, Σwy²} for split gain) plus Σwh
  (Newton denominator, the GammaPass numerator/denominator generalization)
  so distribution-specific leaf values come from the same pass.

Two device implementations, auto-selected by backend:
- scatter path (CPU mesh): one `.at[].add` scatter per column (vmapped) —
  fast on CPU, pathological on TPU (XLA serializes scatters; measured ~1.3s
  per 1M×20-col pass at 256 nodes vs ~0.1s for the matmul path).
- **matmul path (TPU)**: the histogram is recast as MXU work. Per row chunk,
  build ``A_s = onehot(nid) * stat_s`` (chunk, N) and the 0/1 col-bin
  indicator ``E`` (chunk, C·B); then ``hist_s = A_sᵀ @ E`` — a dense matmul
  the systolic array eats, no scatter at all. Rows are processed in
  ``lax.scan`` chunks so the (chunk, C·B) indicator transient stays ~100MB.
  Inactive rows (nid<0) match no one-hot column and vanish automatically.
  Inputs stay float32 (bf16 would quantize the gradient stats the split
  gains are computed from); XLA runs f32 dots as multi-pass bf16 on the MXU.
  This is the ScoreBuildHistogram→TPU redesign the north star asks for; a
  Pallas kernel that fuses the indicator construction into the dot is the
  planned next step.

``histogram_in_jit`` is the primary entry: a pure traced function usable
inside a larger jitted program (the tree level step), so histogram + split
scan + partition fuse into one compiled launch with zero host round-trips.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from h2o3_tpu.parallel.mesh import ROWS_AXIS, get_mesh

STATS = 4  # w, wy, wy2, wh


# Rows per scatter chunk: XLA materializes the vmapped scatter's updates as
# a (C, chunk, 4) f32 broadcast (~1.2 KB/row at C=28 — measured 13.4 GB temp
# for the whole 10M-row tree program before chunking). 256k rows bounds the
# transient at ~115 MB; shards at or under the chunk take the single-chunk
# path, bit-identical to the unchunked original.
_SCATTER_ROW_CHUNK = 262_144


def _hist_scatter_local(bins_u8, nid, w, wy, wy2, wh, n_nodes: int, n_bins: int):
    """Device-local scatter histogram: (C, n_nodes*n_bins, 4).

    Rows with nid < 0 (finalized leaves / padding) contribute via w=0.
    """
    active = nid >= 0
    nid_safe = jnp.where(active, nid, 0)
    stats = jnp.stack(
        [
            jnp.where(active, w, 0.0),
            jnp.where(active, wy, 0.0),
            jnp.where(active, wy2, 0.0),
            jnp.where(active, wh, 0.0),
        ],
        axis=1,
    )  # (n, 4)

    def scatter_chunk(bins_c, nid_c, stats_c):
        def one_col(col):
            idx = nid_c * n_bins + col.astype(jnp.int32)
            out = jnp.zeros((n_nodes * n_bins, STATS), jnp.float32)
            return out.at[idx].add(stats_c)

        return jax.vmap(one_col, in_axes=1)(bins_c)  # (C, n_nodes*n_bins, 4)

    n, C = bins_u8.shape
    if n <= _SCATTER_ROW_CHUNK:
        return scatter_chunk(bins_u8, nid_safe, stats)

    chunk = _SCATTER_ROW_CHUNK
    nchunks = -(-n // chunk)
    pad = nchunks * chunk - n
    if pad:  # padding rows carry zero stats — they land in bin 0 harmlessly
        bins_u8 = jnp.pad(bins_u8, ((0, pad), (0, 0)))
        nid_safe = jnp.pad(nid_safe, (0, pad))
        stats = jnp.pad(stats, ((0, pad), (0, 0)))

    def body(acc, args):
        return acc + scatter_chunk(*args), None

    acc0 = jnp.zeros((C, n_nodes * n_bins, STATS), jnp.float32)
    acc, _ = jax.lax.scan(
        body,
        acc0,
        (
            bins_u8.reshape(nchunks, chunk, C),
            nid_safe.reshape(nchunks, chunk),
            stats.reshape(nchunks, chunk, STATS),
        ),
    )
    return acc


def _select_local():
    """Backend-appropriate shard-local histogram implementation.

    CPU: scatter-add (fast there, pathological on TPU). TPU: the Pallas
    kernel (hist_pallas.py) unless ``H2O3_TPU_HIST=matmul`` forces the plain
    XLA fallback.
    """
    from h2o3_tpu import config

    if jax.default_backend() == "cpu":
        return _hist_scatter_local
    if config.get("H2O3_TPU_HIST") == "matmul":
        return _hist_matmul_local

    def pallas_local(bins_u8, nid, w, wy, wy2, wh, n_nodes, n_bins):
        from h2o3_tpu.ops.hist_pallas import hist_pallas_local

        return hist_pallas_local(bins_u8, nid, w, wy, wy2, wh, n_nodes, n_bins)

    return pallas_local


_ROW_CHUNK = 8192  # rows per matmul chunk: (chunk, C*B) transient ≤ ~120MB


def _hist_matmul_local(bins_u8, nid, w, wy, wy2, wh, n_nodes: int, n_bins: int):
    """MXU histogram for one shard: returns (C, n_nodes*n_bins, 4)."""
    n, C = bins_u8.shape
    chunk = min(_ROW_CHUNK, n)
    nchunks = -(-n // chunk)
    pad = nchunks * chunk - n
    stats = jnp.stack([w, wy, wy2, wh], axis=1)  # (n, 4)
    if pad:
        bins_u8 = jnp.pad(bins_u8, ((0, pad), (0, 0)))
        nid = jnp.pad(nid, (0, pad), constant_values=-1)
        stats = jnp.pad(stats, ((0, pad), (0, 0)))
    bins_ch = bins_u8.reshape(nchunks, chunk, C)
    nid_ch = nid.reshape(nchunks, chunk)
    stats_ch = stats.reshape(nchunks, chunk, STATS)

    iota_nodes = jnp.arange(n_nodes, dtype=jnp.int32)

    def body(acc, args):
        b_c, nid_c, s_c = args
        oh_nid = (nid_c[:, None] == iota_nodes[None, :]).astype(jnp.float32)
        # 0/1 (col,bin) indicator: each row lights exactly one bin per column
        oh_cb = (
            b_c[:, :, None].astype(jnp.int32)
            == jnp.arange(n_bins, dtype=jnp.int32)[None, None, :]
        ).astype(jnp.float32).reshape(chunk, C * n_bins)
        # per-stat scaled nid one-hot (chunk,N) @ indicator (chunk, C*B)
        outs = []
        for s in range(STATS):
            A = oh_nid * s_c[:, s : s + 1]
            outs.append(
                jax.lax.dot_general(
                    A,
                    oh_cb,
                    (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
            )  # (N, C*B)
        return acc + jnp.stack(outs, axis=-1), None

    acc0 = jnp.zeros((n_nodes, C * n_bins, STATS), jnp.float32)
    acc, _ = jax.lax.scan(body, acc0, (bins_ch, nid_ch, stats_ch))
    # (N, C*B, 4) -> (C, N*B, 4) to match the scatter path's layout
    h = acc.reshape(n_nodes, C, n_bins, STATS)
    return jnp.transpose(h, (1, 0, 2, 3)).reshape(C, n_nodes * n_bins, STATS)


def histogram_in_jit(bins_u8, nid, w, wy, wy2, wh, n_nodes: int, n_bins: int, mesh=None):
    """Cross-device histogram, traceable inside a jitted program.

    Returns (n_nodes, C, n_bins, 4), replicated across the mesh.
    """
    mesh = mesh or get_mesh()
    local = _select_local()

    def body(b, n, w_, wy_, wy2_, wh_):
        h = local(b, n, w_, wy_, wy2_, wh_, n_nodes, n_bins)
        return jax.lax.psum(h, ROWS_AXIS)

    # ph_hist: phase tag consumed by tools/profile_fused.py (HLO op_name
    # metadata carries the scope path into the profiler trace)
    with jax.named_scope("ph_hist"):
        h = jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(P(ROWS_AXIS),) * 6,
            out_specs=P(),
            check_vma=False,
        )(bins_u8, nid, w, wy, wy2, wh)  # (C, n_nodes*n_bins, 4)
        C = h.shape[0]
        return jnp.transpose(
            h.reshape(C, n_nodes, n_bins, STATS), (1, 0, 2, 3)
        )  # (n_nodes, C, n_bins, 4)


@partial(jax.jit, static_argnames=("n_nodes", "n_bins"))
def build_histograms(bins_u8, nid, w, wy, wy2, wh, n_nodes: int, n_bins: int):
    """Standalone jitted histogram (kept for tests / direct use)."""
    return histogram_in_jit(bins_u8, nid, w, wy, wy2, wh, n_nodes, n_bins)
