"""Histogram accumulation — successor of ``hex.tree.ScoreBuildHistogram2`` /
``DHistogram`` [UNVERIFIED upstream paths, SURVEY.md §2.2 §3.3], and the
replacement for the bundled XGBoost ``gpu_hist`` CUDA builder (§2.4).

The hot loop of tree building: for every row, look up its current leaf
``nid`` and scatter its per-stat values into (node, col, bin) cells; reduce
across row shards. Mapping:

- H2O's per-chunk fork-join map + pairwise reduce → per-device scatter-add
  + ``psum`` over the rows mesh axis (via ``shard_map``).
- The stat lanes are CALLER-DEFINED (``stats`` is a tuple of (n,) arrays):
  the GBM/DRF path passes {w, wy, wh} — 3 lanes, because the wy² term of
  H2O's DHistogram squared-error gain cancels exactly across
  parent−left−right and carrying it would be 33% more MXU/HBM work for a
  constant offset (see shared_tree._split_scan) — while uplift trees pass
  their 4 treatment/control lanes. Histogram cost is ∝ lanes, so every
  consumer pays exactly for what it reads.

Two device implementations, auto-selected by backend:
- scatter path (CPU mesh): one `.at[].add` scatter per column (vmapped) —
  fast on CPU, pathological on TPU (XLA serializes scatters; measured ~1.3s
  per 1M×20-col pass at 256 nodes vs ~0.1s for the matmul path).
- **matmul path (TPU)**: the histogram is recast as MXU work. Per row chunk,
  build ``A_s = onehot(nid) * stat_s`` (chunk, N) and the 0/1 col-bin
  indicator ``E`` (chunk, C·B); then ``hist_s = A_sᵀ @ E`` — a dense matmul
  the systolic array eats, no scatter at all. Rows are processed in
  ``lax.scan`` chunks so the (chunk, C·B) indicator transient stays ~100MB.
  Inactive rows (nid<0) match no one-hot column and vanish automatically.
  Inputs stay float32 (bf16 would quantize the gradient stats the split
  gains are computed from); XLA runs f32 dots as multi-pass bf16 on the MXU.
  This is the ScoreBuildHistogram→TPU redesign the north star asks for; the
  Pallas kernel (hist_pallas.py) fuses the indicator build into the dot.

``histogram_in_jit`` is the primary entry: a pure traced function usable
inside a larger jitted program (the tree level step), so histogram + split
scan + partition fuse into one compiled launch with zero host round-trips.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from h2o3_tpu.parallel.mesh import ROWS_AXIS, get_mesh, shard_map

# Rows per scatter chunk: XLA materializes the vmapped scatter's updates as
# a (C, chunk, S) f32 broadcast (~1.2 KB/row at C=28, S=4 — measured 13.4 GB
# temp for the whole 10M-row tree program before chunking). 256k rows bounds
# the transient at ~115 MB; shards at or under the chunk take the
# single-chunk path, bit-identical to the unchunked original.
_SCATTER_ROW_CHUNK = 262_144


def _hist_scatter_local(bins_u8, nid, stats, n_nodes: int, n_bins: int):
    """Device-local scatter histogram: (C, n_nodes*n_bins, S).

    Rows with nid < 0 (finalized leaves / padding) MUST arrive with zeroed
    stats (``histogram_in_jit`` masks them): the scatter clamps their nid
    to 0 and a nonzero stat would pollute node 0.
    """
    S = stats.shape[1]
    nid_safe = jnp.maximum(nid, 0)

    def scatter_chunk(bins_c, nid_c, stats_c):
        def one_col(col):
            idx = nid_c * n_bins + col.astype(jnp.int32)
            out = jnp.zeros((n_nodes * n_bins, S), jnp.float32)
            return out.at[idx].add(stats_c)

        return jax.vmap(one_col, in_axes=1)(bins_c)  # (C, n_nodes*n_bins, S)

    n, C = bins_u8.shape
    if n <= _SCATTER_ROW_CHUNK:
        return scatter_chunk(bins_u8, nid_safe, stats)

    chunk = _SCATTER_ROW_CHUNK
    nchunks = -(-n // chunk)
    pad = nchunks * chunk - n
    if pad:  # padding rows carry zero stats — they land in bin 0 harmlessly
        bins_u8 = jnp.pad(bins_u8, ((0, pad), (0, 0)))
        nid_safe = jnp.pad(nid_safe, (0, pad))
        stats = jnp.pad(stats, ((0, pad), (0, 0)))

    def body(acc, args):
        return acc + scatter_chunk(*args), None

    acc0 = jnp.zeros((C, n_nodes * n_bins, S), jnp.float32)
    acc, _ = jax.lax.scan(
        body,
        acc0,
        (
            bins_u8.reshape(nchunks, chunk, C),
            nid_safe.reshape(nchunks, chunk),
            stats.reshape(nchunks, chunk, S),
        ),
    )
    return acc


def _select_local():
    """Backend-appropriate shard-local histogram implementation.

    CPU: scatter-add (fast there, pathological on TPU). TPU: the Pallas
    kernel (hist_pallas.py) unless ``H2O3_TPU_HIST=matmul`` forces the plain
    XLA fallback.
    """
    from h2o3_tpu import config

    if jax.default_backend() == "cpu":
        return _hist_scatter_local
    if config.get("H2O3_TPU_HIST") == "matmul":
        return _hist_matmul_local

    def pallas_local(bins_u8, nid, stats, n_nodes, n_bins):
        from h2o3_tpu.ops.hist_pallas import hist_pallas_local

        return hist_pallas_local(bins_u8, nid, stats, n_nodes, n_bins)

    return pallas_local


_ROW_CHUNK = 8192  # rows per matmul chunk: (chunk, C*B) transient ≤ ~120MB


def _hist_matmul_local(bins_u8, nid, stats, n_nodes: int, n_bins: int):
    """MXU histogram for one shard: returns (C, n_nodes*n_bins, S)."""
    n, C = bins_u8.shape
    S = stats.shape[1]
    chunk = min(_ROW_CHUNK, n)
    nchunks = -(-n // chunk)
    pad = nchunks * chunk - n
    if pad:
        bins_u8 = jnp.pad(bins_u8, ((0, pad), (0, 0)))
        nid = jnp.pad(nid, (0, pad), constant_values=-1)
        stats = jnp.pad(stats, ((0, pad), (0, 0)))
    bins_ch = bins_u8.reshape(nchunks, chunk, C)
    nid_ch = nid.reshape(nchunks, chunk)
    stats_ch = stats.reshape(nchunks, chunk, S)

    iota_nodes = jnp.arange(n_nodes, dtype=jnp.int32)

    def body(acc, args):
        b_c, nid_c, s_c = args
        oh_nid = (nid_c[:, None] == iota_nodes[None, :]).astype(jnp.float32)
        # 0/1 (col,bin) indicator: each row lights exactly one bin per column
        oh_cb = (
            b_c[:, :, None].astype(jnp.int32)
            == jnp.arange(n_bins, dtype=jnp.int32)[None, None, :]
        ).astype(jnp.float32).reshape(chunk, C * n_bins)
        # per-stat scaled nid one-hot (chunk,N) @ indicator (chunk, C*B)
        outs = []
        for s in range(S):
            A = oh_nid * s_c[:, s : s + 1]
            outs.append(
                jax.lax.dot_general(
                    A,
                    oh_cb,
                    (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
            )  # (N, C*B)
        return acc + jnp.stack(outs, axis=-1), None

    acc0 = jnp.zeros((n_nodes, C * n_bins, S), jnp.float32)
    acc, _ = jax.lax.scan(body, acc0, (bins_ch, nid_ch, stats_ch))
    # (N, C*B, S) -> (C, N*B, S) to match the scatter path's layout
    h = acc.reshape(n_nodes, C, n_bins, S)
    return jnp.transpose(h, (1, 0, 2, 3)).reshape(C, n_nodes * n_bins, S)


def histogram_in_jit(bins_u8, nid, stats, n_nodes: int, n_bins: int, mesh=None):
    """Cross-device histogram, traceable inside a jitted program.

    ``stats`` is a TUPLE of (n,) row-sharded arrays — the stat lanes.
    Returns (n_nodes, C, n_bins, S), replicated across the mesh.
    """
    mesh = mesh or get_mesh()
    local = _select_local()
    S = len(stats)

    def body(b, n, s):
        # retired/padding rows (nid < 0) carry zero stats into every impl
        s = jnp.where((n >= 0)[:, None], s, 0.0)
        h = local(b, n, s, n_nodes, n_bins)
        return jax.lax.psum(h, ROWS_AXIS)

    smat = jnp.stack(list(stats), axis=1)  # (n, S)

    # ph_hist: phase tag consumed by tools/profile_fused.py (HLO op_name
    # metadata carries the scope path into the profiler trace)
    with jax.named_scope("ph_hist"):
        h = shard_map(
            body,
            mesh=mesh,
            in_specs=(P(ROWS_AXIS), P(ROWS_AXIS), P(ROWS_AXIS)),
            out_specs=P(),
            check_vma=False,
        )(bins_u8, nid, smat)  # (C, n_nodes*n_bins, S)
        C = h.shape[0]
        return jnp.transpose(
            h.reshape(C, n_nodes, n_bins, S), (1, 0, 2, 3)
        )  # (n_nodes, C, n_bins, S)


@partial(jax.jit, static_argnames=("n_nodes", "n_bins"))
def build_histograms(bins_u8, nid, stats, n_nodes: int, n_bins: int):
    """Standalone jitted histogram (kept for tests / direct use)."""
    return histogram_in_jit(bins_u8, nid, stats, n_nodes, n_bins)
