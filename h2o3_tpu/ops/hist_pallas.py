"""Pallas TPU histogram kernel — the ``gpu_hist`` successor proper
(SURVEY.md §2.4: the bundled XGBoost CUDA histogram builder is the one native
component the rebuild must replace with a TPU kernel).

Why the plain-XLA matmul path (``histogram._hist_matmul_local``) is slow: it
materializes a (row_chunk, C·B) one-hot indicator — ~235 MB at C=28, B=256 —
which cannot live in VMEM, so every chunk round-trips the indicator through
HBM and the pass is bandwidth-crippled (~1-3% MFU measured, BENCH_r02).

This kernel never materializes that transient:

- grid = (node_tiles, col_tiles, row_chunks), row-fastest, so the output
  block for one (node_tile, col_tile) stays resident in VMEM while every row
  chunk accumulates into it;
- per step, the (R, CT·B) indicator tile and the (R, NT·S) stat-scaled
  node-one-hot are built in VMEM by iota-compare (VPU) and immediately
  contracted on the MXU — one f32 dot per step, all S stats fused into the
  M dimension;
- rows with nid outside the tile (or nid = -1: retired/padding) match no
  one-hot column and contribute zero, so node tiling and row padding need no
  masking anywhere.

``S`` (the stat-lane count) is caller-defined: the GBM/DRF path runs S=3
{w, wy, wh} — the wy² lane of H2O's DHistogram cancels in the gain and
carrying it would be 33% more MXU work (see shared_tree._split_scan) —
while uplift trees run their 4 treatment/control lanes. Kernel cost is
∝ S, so each consumer pays exactly for what it reads.

Output layout matches the other local paths: (C, n_nodes·n_bins, S) per
shard; the caller (``histogram.histogram_in_jit``) psums across the mesh.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

ROW_TILE = 512  # rows per grid step
COL_TILE = 8  # feature columns per grid step
NODE_TILE = 64  # tree nodes per grid step (S·NT = 192-256 M-rows on the MXU)


def _cdiv(a: int, b: int) -> int:
    return -(-a // b)


def _hist_kernel(bins_ref, nid_ref, stats_ref, out_ref, *, nt, ct, bpad, ns):
    i_nt = pl.program_id(0)
    i_r = pl.program_id(2)

    r = bins_ref.shape[1]  # bins block is (1, R, CT)
    # Everything is built directly in 2D with lane-iota arithmetic: Mosaic
    # cannot relayout (R, k, m) → (R, k·m) for small trailing dims.

    # stat-scaled node one-hot, nodes of this tile only: (R, NT·S) with
    # column j ↦ (node = j//S, stat = j%S)
    node_base = i_nt * nt
    node_j = node_base + jax.lax.broadcasted_iota(jnp.int32, (r, nt * ns), 1) // ns
    nid_match = (nid_ref[:] == node_j).astype(jnp.float32)  # (R,1) broadcasts
    stat_tile = jnp.tile(stats_ref[:], (1, nt))  # (R, NT·S): [s0..s_{S-1}]×NT
    a = nid_match * stat_tile

    # (R, CT·Bpad) 0/1 bin indicator, lane j ↦ (bin = j//CT, col = j%CT) —
    # the tile-order jnp.tile lays out [c0..c(CT-1)] × Bpad blocks. The column
    # tile arrives via the BlockSpec from the (n_ct, npad, CT) layout
    # (lane-dim dynamic slices at non-128 offsets are not expressible
    # in-kernel, and a (R, CT) block would violate the lane-divisibility rule).
    bins_ct = bins_ref[0].astype(jnp.int32)  # (R, CT)
    colrep = jnp.tile(bins_ct, (1, bpad))  # (R, CT·Bpad)
    bin_j = jax.lax.broadcasted_iota(jnp.int32, (r, ct * bpad), 1) // ct
    e = (colrep == bin_j).astype(jnp.bfloat16)  # 0/1: exact in bf16

    # Manual 2-term bf16 split of the stats operand (~16 mantissa bits, ≈
    # Precision.HIGH, which Mosaic doesn't support): the indicator operand is
    # exact in bf16, so only `a` needs decomposing — 2 MXU passes instead of
    # HIGHEST's 6. Single-pass bf16 measurably corrupts split gains (2e-3).
    a_hi = a.astype(jnp.bfloat16)
    a_lo = (a - a_hi.astype(jnp.float32)).astype(jnp.bfloat16)
    dims = (((0,), (0,)), ((), ()))
    contrib = jax.lax.dot_general(
        a_hi, e, dims, preferred_element_type=jnp.float32
    ) + jax.lax.dot_general(
        a_lo, e, dims, preferred_element_type=jnp.float32
    )  # (NT·S, CT·Bpad)

    @pl.when(i_r == 0)
    def _():
        out_ref[:] = contrib

    @pl.when(i_r > 0)
    def _():
        out_ref[:] = out_ref[:] + contrib


@functools.partial(
    jax.jit, static_argnames=("n_nodes", "n_bins", "interpret")
)
def hist_pallas_local(
    bins_u8, nid, stats, n_nodes: int, n_bins: int, interpret: bool = False
):
    """Shard-local Pallas histogram: returns (C, n_nodes*n_bins, S) float32.

    ``stats`` is the (n, S) stat matrix (S static from its shape). Drop-in
    replacement for ``_hist_matmul_local`` / ``_hist_scatter_local``.
    ``interpret=True`` runs the kernel in the Pallas interpreter (CPU CI).
    """
    n, c = bins_u8.shape
    ns = stats.shape[1]
    nt = min(NODE_TILE, n_nodes)
    ct = min(COL_TILE, c)
    # pad bins axis so the lane dimension CT·Bpad is a multiple of 128
    bpad = _cdiv(n_bins, 16) * 16
    while (ct * bpad) % 128:
        bpad += 16
    n_nt = _cdiv(n_nodes, nt)
    n_ct = _cdiv(c, ct)
    cpad = n_ct * ct
    n_r = max(_cdiv(n, ROW_TILE), 1)
    npad = n_r * ROW_TILE

    if npad != n:
        bins_u8 = jnp.pad(bins_u8, ((0, npad - n), (0, 0)))
        nid = jnp.pad(nid, (0, npad - n), constant_values=-1)
        stats = jnp.pad(stats, ((0, npad - n), (0, 0)))
    if cpad != c:
        bins_u8 = jnp.pad(bins_u8, ((0, 0), (0, cpad - c)))
    # (npad, cpad) → (n_ct, npad, CT): each grid step's column tile is the
    # (full) last dim of its block, satisfying Mosaic's lane-divisibility rule
    bins3 = jnp.transpose(bins_u8.reshape(npad, n_ct, ct), (1, 0, 2))
    nid2 = nid.reshape(npad, 1)

    kernel = functools.partial(_hist_kernel, nt=nt, ct=ct, bpad=bpad, ns=ns)
    out = pl.pallas_call(
        kernel,
        grid=(n_nt, n_ct, n_r),
        in_specs=[
            pl.BlockSpec(
                (1, ROW_TILE, ct),
                lambda nt_, ct_, r_: (ct_, r_, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (ROW_TILE, 1), lambda nt_, ct_, r_: (r_, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (ROW_TILE, ns), lambda nt_, ct_, r_: (r_, 0), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (nt * ns, ct * bpad), lambda nt_, ct_, r_: (nt_, ct_), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((n_nt * nt * ns, cpad * bpad), jnp.float32),
        cost_estimate=pl.CostEstimate(
            flops=int(2 * npad * (nt * ns) * cpad * bpad),
            bytes_accessed=int(
                npad * cpad + npad * (ns + 1) * 4 + n_nt * nt * ns * cpad * bpad * 4
            ),
            transcendentals=0,
        ),
        interpret=interpret,
    )(bins3, nid2, stats)

    # unscramble: out rows = node·S+stat, lanes = ct-tile-major [bin//CT, col%CT]
    h5 = out.reshape(n_nt * nt, ns, n_ct, bpad, ct)
    h5 = jnp.transpose(h5, (2, 4, 0, 3, 1))  # (n_ct, ct, Npad, Bpad, S)
    h = h5.reshape(cpad, n_nt * nt, bpad, ns)[:c, :n_nodes, :n_bins, :]
    return h.reshape(c, n_nodes * n_bins, ns)
