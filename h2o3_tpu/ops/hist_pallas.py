"""Pallas TPU histogram kernel — the ``gpu_hist`` successor proper
(SURVEY.md §2.4: the bundled XGBoost CUDA histogram builder is the one native
component the rebuild must replace with a TPU kernel).

Why the plain-XLA matmul path (``histogram._hist_matmul_local``) is slow: it
materializes a (row_chunk, C·B) one-hot indicator — ~235 MB at C=28, B=256 —
which cannot live in VMEM, so every chunk round-trips the indicator through
HBM and the pass is bandwidth-crippled (~1-3% MFU measured, BENCH_r02).

This kernel never materializes that transient:

- grid = (node_tiles, col_tiles, row_chunks), row-fastest, so the output
  block for one (node_tile, col_tile) stays resident in VMEM while every row
  chunk accumulates into it;
- per step, the (R, CT·B) indicator tile and the (R, NT·S) stat-scaled
  node-one-hot are built in VMEM by iota-compare (VPU) and immediately
  contracted on the MXU — one f32 dot per step, all S stats fused into the
  M dimension;
- rows with nid outside the tile (or nid = -1: retired/padding) match no
  one-hot column and contribute zero, so node tiling and row padding need no
  masking anywhere.

``S`` (the stat-lane count) is caller-defined: the GBM/DRF path runs S=3
{w, wy, wh} — the wy² lane of H2O's DHistogram cancels in the gain and
carrying it would be 33% more MXU work (see shared_tree._split_scan) —
while uplift trees run their 4 treatment/control lanes. Kernel cost is
∝ S, so each consumer pays exactly for what it reads.

Two output modes:

- **dense** (default, back-compat): (C, n_nodes·n_bins, S) per shard — the
  layout the scatter/matmul paths emit. Reaching it costs two
  reshape/transpose "unscramble" passes over the full tensor in HBM.
- **blocked** (``blocked=True``, the fused split pipeline): the kernel's
  native tile layout, shipped untouched — ``(n_ct, NN·S, CT·Bpad)`` where
  block ``[i_ct]`` holds column tile ``i_ct`` (columns ``i_ct·CT ..``),
  rows are ``node·S + stat`` and lanes are ``bin·CT + col_in_tile``. No
  unscramble pass runs at all: the cross-device ``psum_scatter`` shards
  axis 0 (contiguous column ranges, exactly what the sharded split merge
  needs) and the split kernel (``ops/split_pallas.py``) consumes the very
  same tiles block-by-block in VMEM. The :class:`HistLayout` returned by
  :func:`plan_layout` is the single source of truth for the geometry.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

ROW_TILE = 512  # rows per grid step
COL_TILE = 8  # feature columns per grid step
NODE_TILE = 64  # tree nodes per grid step (S·NT = 192-256 M-rows on the MXU)


def _cdiv(a: int, b: int) -> int:
    return -(-a // b)


def _tiles() -> tuple[int, int, int]:
    """(ROW_TILE, COL_TILE, NODE_TILE), overridable via the
    ``H2O3_TPU_PALLAS_TILES`` knob ("row,col,node" — the tile-sweep hook:
    ``tools/bench_kernel_sweep.py`` and ``run_tpu_backlog.sh`` vary tiles
    through the environment instead of monkeypatching module globals).
    Callers pass the resolved tuple into :func:`hist_pallas_local` /
    :func:`plan_layout` as a static argument, so every tile choice gets its
    own jit cache entry — no stale-executable footgun.

    ``'auto'`` is the SHAPE-AWARE autotuner (ISSUE 15): this shapeless
    accessor then returns the built-in defaults; shape-aware call sites
    resolve through :func:`tiles_for`, which runs a first-build micro-sweep
    per (shape-bucket, mesh) and caches the winner persistently."""
    from h2o3_tpu import config

    spec = config.get("H2O3_TPU_PALLAS_TILES").strip()
    if not spec or spec == "auto":
        return (ROW_TILE, COL_TILE, NODE_TILE)
    parts = [int(x) for x in spec.split(",")]
    if len(parts) != 3 or any(p <= 0 for p in parts):
        raise ValueError(
            f"H2O3_TPU_PALLAS_TILES must be 'ROW,COL,NODE' positive ints "
            f"or 'auto', got {spec!r}"
        )
    return tuple(parts)


# ---------------------------------------------------------------------------
# tile autotuner (H2O3_TPU_PALLAS_TILES=auto, ISSUE 15 / ROADMAP 4b): a
# first-build micro-sweep over a small tile grid, cached per
# (shape-bucket, mesh) in the persistent compile-cache dir so the queued
# TPU window tunes itself and same-bucket rebuilds (and later processes)
# perform ZERO new sweeps. Explicit "ROW,COL,NODE" values bypass the sweep
# unchanged; '' keeps the built-in defaults.

from h2o3_tpu.utils import metrics as _mx

_TILE_SWEEPS = _mx.counter(
    "pallas_tile_sweeps_total",
    "tile-autotuner micro-sweeps executed (H2O3_TPU_PALLAS_TILES=auto; a "
    "same-bucket rebuild must add zero)", always=True)
_TUNED_TILES: dict = {}  # in-process cache: key -> (row, col, node)
_SWEEP_ROWS = 4096  # rows of synthetic data per sweep candidate


def _tile_cache_path() -> str:
    """The persistent winner store, colocated with the XLA compile cache
    (H2O3_TPU_COMPILE_CACHE, same default as cluster/cloud.py) so one warm
    volume carries both the executables and the tile choices."""
    import os

    from h2o3_tpu import config

    d = config.get("H2O3_TPU_COMPILE_CACHE")
    if not d:
        import h2o3_tpu

        d = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(
                h2o3_tpu.__file__))), ".jax_cache")
    return os.path.join(d, "pallas_tiles.json")


def _tile_bucket(c: int, n_nodes: int, n_bins: int, ns: int) -> tuple:
    """Shape bucket for the tuner cache: columns to the PR-1 ladder
    granularity (multiple of 8), nodes/bins to powers of two — the same
    coarsening the program caches already ride, so one sweep serves every
    shape that compiles to the same kernel geometry family."""
    cb = -(-c // 8) * 8
    nb = 1 << max(int(n_nodes - 1).bit_length(), 1)
    bb = 1 << max(int(n_bins - 1).bit_length(), 3)
    return (cb, nb, bb, ns)


def _sweep_grid(c: int, n_nodes: int) -> list:
    """The candidate triples: a small cross of row/col/node tiles clamped
    to the problem (12 candidates max — a first-build cost, paid once per
    bucket per mesh and then cached persistently)."""
    rows = (256, 512, 1024)
    cols = tuple(sorted({min(4, c), min(8, c)}))
    nodes = tuple(sorted({min(32, n_nodes), min(64, n_nodes)}))
    return [(r, ct, nt) for r in rows for ct in cols for nt in nodes]


def _run_tile_sweep(c, n_nodes, n_bins, ns, interpret: bool) -> tuple:
    """Time each candidate on synthetic data of the real geometry; return
    the fastest triple. Runs eagerly (concrete arrays) — safe to call from
    inside an outer trace, where it executes at trace time exactly once."""
    import time

    import numpy as np

    rng = np.random.default_rng(0)
    n = _SWEEP_ROWS
    bins = jnp.asarray(rng.integers(0, n_bins, (n, c)).astype(np.uint8))
    nid = jnp.asarray(rng.integers(0, n_nodes, n).astype(np.int32))
    stats = jnp.asarray(rng.normal(size=(n, ns)).astype(np.float32))
    best, best_t = None, None
    for tiles in _sweep_grid(c, n_nodes):
        try:
            fn = lambda: hist_pallas_local(
                bins, nid, stats, n_nodes, n_bins, interpret=interpret,
                blocked=True, tiles=tiles,
            )
            jax.block_until_ready(fn())  # compile
            t0 = time.perf_counter()
            jax.block_until_ready(fn())
            dt = time.perf_counter() - t0
        except Exception:  # a candidate the backend rejects: skip it
            continue
        if best_t is None or dt < best_t:
            best, best_t = tiles, dt
    # the candidate executables are one-shot — drop them (the winner
    # recompiles once inside the real program; keeping 11 losers loaded
    # per bucket would only grow the process's executable footprint)
    hist_pallas_local.clear_cache()
    return best or (ROW_TILE, COL_TILE, NODE_TILE)


def tiles_for(c: int, n_nodes: int, n_bins: int, ns: int) -> tuple:
    """The tile triple for a problem shape — THE shape-aware resolver.

    Explicit ``H2O3_TPU_PALLAS_TILES="ROW,COL,NODE"`` values (and the ''
    defaults) bypass the tuner unchanged; ``'auto'`` looks the shape bucket
    up in the in-process cache, then the persistent winner store, and only
    then runs the micro-sweep (``pallas_tile_sweeps_total`` counts actual
    sweeps — the same-bucket-rebuild-adds-zero pin)."""
    from h2o3_tpu import config

    spec = config.get("H2O3_TPU_PALLAS_TILES").strip()
    if spec != "auto":
        return _tiles()
    from h2o3_tpu.parallel.mesh import mesh_key

    bucket = _tile_bucket(c, n_nodes, n_bins, ns)
    key = (bucket, mesh_key(), jax.default_backend())
    hit = _TUNED_TILES.get(key)
    if hit is not None:
        return hit
    import json
    import os

    path = _tile_cache_path()
    skey = repr(key)
    try:
        with open(path) as f:
            stored = json.load(f)
    except (OSError, ValueError):
        stored = {}
    if skey in stored:
        tiles = tuple(int(x) for x in stored[skey])
        _TUNED_TILES[key] = tiles
        return tiles
    _TILE_SWEEPS.inc()
    tiles = _run_tile_sweep(
        # sweep at the BUCKET geometry so every shape in the bucket lands
        # on the same winner (and the cache key matches what was measured)
        bucket[0], bucket[1], min(bucket[2], 256), ns,
        interpret=jax.default_backend() == "cpu",
    )
    _TUNED_TILES[key] = tiles
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        stored[skey] = list(tiles)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(stored, f, indent=0, sort_keys=True)
        os.replace(tmp, path)  # atomic publish (the PR-2 persist idiom)
    except OSError:
        pass  # read-only cache volume: the in-process cache still holds
    from h2o3_tpu.utils.log import Log

    Log.info(
        f"Pallas tile autotuner: bucket {bucket} on "
        f"{jax.default_backend()} -> tiles {tiles}"
    )
    return tiles


@dataclass(frozen=True)
class HistLayout:
    """Static geometry of a blocked histogram tensor (see module docstring).

    The blocked tensor is ``(n_ct, NN·ns, ct·bpad)`` float32 with
    ``blk[i_ct, node·ns + stat, bin·ct + j] ==
    dense[i_ct·ct + j, node, bin, stat]`` — column tiles on axis 0 (so a
    ``psum_scatter`` over axis 0 hands each device a contiguous column
    range), node-major rows, bin-major lanes. ``NN >= n_nodes`` and
    ``cpad = n_ct·ct >= C`` and ``bpad >= n_bins`` are tile padding. Padded
    BIN and NODE cells are exactly zero (no row ever lands there). Padded
    COLUMNS carry the u8 pad code 0, i.e. their whole mass sits in the NA
    bin — their data bins are zero, so no candidate there passes min_rows
    with min_rows > 0, and split consumers additionally mask them through
    the column mask (the PR-5 pattern), so they can never win a split.
    """

    c: int          # real feature columns
    n_nodes: int    # real tree nodes
    n_bins: int     # real bins (bin 0 = NA)
    ns: int         # stat lanes
    ct: int         # columns per tile
    bpad: int       # padded bins per tile (ct*bpad % 128 == 0)
    nt: int         # nodes per tile
    n_ct: int       # column tiles (multiple of n_shards)
    n_nt: int       # node tiles
    tiles: tuple    # the (row, col, node) tile triple this plan came from

    @property
    def cpad(self) -> int:
        return self.n_ct * self.ct

    @property
    def nn(self) -> int:  # padded node count
        return self.n_nt * self.nt

    @property
    def shape(self) -> tuple[int, int, int]:
        return (self.n_ct, self.nn * self.ns, self.ct * self.bpad)

    @property
    def nbytes(self) -> int:
        import math

        return 4 * math.prod(self.shape)

    def local(self, n_shards: int) -> "HistLayout":
        """Layout of one device's block after a psum_scatter over axis 0.

        The local block covers the REAL columns that fall inside its range;
        ``c`` is kept as the full padded-local width (cpad/P) — callers mask
        pad columns via the column mask, exactly like the dense sharded
        scan."""
        import dataclasses

        assert self.n_ct % n_shards == 0, (self.n_ct, n_shards)
        n_ct_loc = self.n_ct // n_shards
        return dataclasses.replace(
            self, c=n_ct_loc * self.ct, n_ct=n_ct_loc
        )


def plan_layout(
    c: int, n_nodes: int, n_bins: int, ns: int,
    tiles: tuple[int, int, int] | None = None, n_shards: int = 1,
) -> HistLayout:
    """The blocked-histogram geometry for a problem shape.

    ``n_shards > 1`` rounds the column-tile count up to a multiple of the
    shard count so a tiled ``psum_scatter`` over axis 0 gives every device
    whole tiles (= a contiguous column range — load-bearing for the winner
    merge's lowest-global-index tie-break)."""
    tiles = tuple(tiles or _tiles())
    _, col_tile, node_tile = tiles
    nt = min(node_tile, n_nodes)
    ct = min(col_tile, c)
    if n_shards > 1:
        # the scatter hands each device WHOLE tiles: cap the tile at
        # ceil(C/P) columns so real columns spread over every device (a
        # wider tile on a narrow frame would park all real columns on
        # device 0 and pad the tensor with all-zero tiles for the rest)
        ct = min(ct, max(1, _cdiv(c, n_shards)))
    # pad bins so the lane dimension CT·Bpad is a multiple of 128
    bpad = _cdiv(n_bins, 16) * 16
    while (ct * bpad) % 128:
        bpad += 16
    n_ct = _cdiv(c, ct)
    if n_shards > 1:
        n_ct = _cdiv(n_ct, n_shards) * n_shards
    n_nt = _cdiv(n_nodes, nt)
    return HistLayout(
        c=c, n_nodes=n_nodes, n_bins=n_bins, ns=ns,
        ct=ct, bpad=bpad, nt=nt, n_ct=n_ct, n_nt=n_nt, tiles=tiles,
    )


def blocked_from_dense(dense, layout: HistLayout):
    """(C, n_nodes·n_bins, S) → blocked. The CPU-correctness lane for the
    fused split pipeline when the local histogram impl is scatter/matmul
    (H2O3_TPU_HIST override): the Pallas kernel emits blocked natively."""
    L = layout
    d = dense.reshape(L.c, L.n_nodes, L.n_bins, L.ns)
    d = jnp.pad(d, ((0, L.cpad - L.c), (0, L.nn - L.n_nodes),
                    (0, L.bpad - L.n_bins), (0, 0)))
    d = d.reshape(L.n_ct, L.ct, L.nn, L.bpad, L.ns)
    d = jnp.transpose(d, (0, 2, 4, 3, 1))  # (n_ct, NN, S, bpad, ct)
    return d.reshape(L.shape)


def dense_from_blocked(blk, layout: HistLayout):
    """Blocked → (C, n_nodes·n_bins, S) (tests / fallback consumers)."""
    L = layout
    d = blk.reshape(L.n_ct, L.nn, L.ns, L.bpad, L.ct)
    d = jnp.transpose(d, (0, 4, 1, 3, 2))  # (n_ct, ct, NN, bpad, S)
    d = d.reshape(L.cpad, L.nn, L.bpad, L.ns)[: L.c, : L.n_nodes, : L.n_bins]
    return d.reshape(L.c, L.n_nodes * L.n_bins, L.ns)


def blocked_cols_dense(blk, layout: HistLayout, cols: tuple[int, ...]):
    """Dense (N, len(cols), n_bins, S) view of a static column subset.

    The categorical-fallback hook of the fused split pipeline: the mean-sort
    categorical branch needs its columns as an ordinary (N, Cc, B, S)
    tensor. Only the tiles containing those columns are gathered and
    unscrambled — O(Cc·N·B·S) HBM, not the full histogram."""
    L = layout
    tile_ids = sorted({c // L.ct for c in cols})
    pos = {t: i for i, t in enumerate(tile_ids)}
    sub = blk[jnp.asarray(tile_ids)]  # (T, NN*ns, ct*bpad)
    sub = sub.reshape(len(tile_ids), L.nn, L.ns, L.bpad, L.ct)
    # (T, ct, NN, bpad, ns) → rows per (tile, col-in-tile)
    sub = jnp.transpose(sub, (0, 4, 1, 3, 2))
    sub = sub.reshape(len(tile_ids) * L.ct, L.nn, L.bpad, L.ns)
    rows = jnp.asarray([pos[c // L.ct] * L.ct + c % L.ct for c in cols])
    out = sub[rows][:, : L.n_nodes, : L.n_bins, :]  # (Cc, N, B, S)
    return jnp.transpose(out, (1, 0, 2, 3))


def blocked_node_totals(blk, layout: HistLayout):
    """Per-node {stat} totals from GLOBAL column 0 of a blocked histogram:
    (n_nodes, S). Column 0 lives in tile 0, lane positions ``bin·ct + 0`` —
    every row lights exactly one bin per column, so any single column's bin
    sum is the node total (the replicated `_split_scan` uses column 0)."""
    L = layout
    t0 = blk[0].reshape(L.nn, L.ns, L.bpad, L.ct)[:, :, :, 0]  # (NN, S, bpad)
    return t0.sum(axis=2)[: L.n_nodes]


def relayout_nodes(layout: HistLayout, n_nodes_to: int) -> HistLayout:
    """The layout of the SAME columns/bins re-planned for a different node
    count (node tiling re-derived from the stored tile triple; the column
    tiling — including any shard rounding baked into n_ct — is kept)."""
    import dataclasses

    p = plan_layout(layout.c, n_nodes_to, layout.n_bins, layout.ns,
                    tiles=layout.tiles)
    return dataclasses.replace(
        layout, n_nodes=n_nodes_to, nt=p.nt, n_nt=p.n_nt
    )


def blocked_pad_nodes(blk, layout: HistLayout, n_nodes_to: int) -> tuple:
    """Zero-pad the node axis to ``n_nodes_to`` (returns (blk2, layout2)).

    Used by the saturated-region carry in the fused tree builder: the first
    saturated level's parent frontier may be node_cap/2 wide and the
    while_loop needs a loop-invariant shape."""
    L = layout
    L2 = relayout_nodes(L, n_nodes_to)
    v = blk.reshape(L.n_ct, L.nn, L.ns, L.ct * L.bpad)
    v = jnp.pad(v, ((0, 0), (0, L2.nn - L.nn), (0, 0), (0, 0)))
    return v.reshape(L2.shape), L2


def blocked_coarsen(blk, layout: HistLayout, ds: int) -> tuple:
    """Sum adjacent data-bin groups of ``2**ds`` (NA bin passes through) —
    ``shared_tree._coarsen_hist`` for the blocked layout. Returns
    (blk2, layout2) at the coarsened bin count; the bin axis is a pure
    lane-reshape of the tile, so no transpose pass touches HBM."""
    import dataclasses

    if ds == 0:
        return blk, layout
    L = layout
    v = blk.reshape(L.n_ct, L.nn, L.ns, L.bpad, L.ct)
    na = v[:, :, :, :1, :]
    D = L.n_bins - 1
    data = v[:, :, :, 1 : 1 + D, :]
    group = 1 << ds
    Dc = -(-D // group)
    pad = Dc * group - D
    if pad:
        data = jnp.pad(data, ((0, 0),) * 3 + ((0, pad), (0, 0)))
    data = data.reshape(L.n_ct, L.nn, L.ns, Dc, group, L.ct).sum(4)
    nb_c = Dc + 1
    p = plan_layout(L.c, L.n_nodes, nb_c, L.ns, tiles=L.tiles)
    L2 = dataclasses.replace(L, n_bins=nb_c, bpad=p.bpad)
    out = jnp.concatenate(
        [na, data,
         jnp.zeros(data.shape[:3] + (L2.bpad - nb_c, L.ct), blk.dtype)],
        axis=3,
    )
    return out.reshape(L2.shape), L2


def _hist_kernel(bins_ref, nid_ref, stats_ref, out_ref, *, nt, ct, bpad, ns):
    i_nt = pl.program_id(0)
    i_r = pl.program_id(2)

    r = bins_ref.shape[1]  # bins block is (1, R, CT)
    # Everything is built directly in 2D with lane-iota arithmetic: Mosaic
    # cannot relayout (R, k, m) → (R, k·m) for small trailing dims.

    # stat-scaled node one-hot, nodes of this tile only: (R, NT·S) with
    # column j ↦ (node = j//S, stat = j%S)
    node_base = i_nt * nt
    node_j = node_base + jax.lax.broadcasted_iota(jnp.int32, (r, nt * ns), 1) // ns
    nid_match = (nid_ref[:] == node_j).astype(jnp.float32)  # (R,1) broadcasts
    stat_tile = jnp.tile(stats_ref[:], (1, nt))  # (R, NT·S): [s0..s_{S-1}]×NT
    a = nid_match * stat_tile

    # (R, CT·Bpad) 0/1 bin indicator, lane j ↦ (bin = j//CT, col = j%CT) —
    # the tile-order jnp.tile lays out [c0..c(CT-1)] × Bpad blocks. The column
    # tile arrives via the BlockSpec from the (n_ct, npad, CT) layout
    # (lane-dim dynamic slices at non-128 offsets are not expressible
    # in-kernel, and a (R, CT) block would violate the lane-divisibility rule).
    bins_ct = bins_ref[0].astype(jnp.int32)  # (R, CT)
    colrep = jnp.tile(bins_ct, (1, bpad))  # (R, CT·Bpad)
    bin_j = jax.lax.broadcasted_iota(jnp.int32, (r, ct * bpad), 1) // ct
    e = (colrep == bin_j).astype(jnp.bfloat16)  # 0/1: exact in bf16

    # Manual 2-term bf16 split of the stats operand (~16 mantissa bits, ≈
    # Precision.HIGH, which Mosaic doesn't support): the indicator operand is
    # exact in bf16, so only `a` needs decomposing — 2 MXU passes instead of
    # HIGHEST's 6. Single-pass bf16 measurably corrupts split gains (2e-3).
    a_hi = a.astype(jnp.bfloat16)
    a_lo = (a - a_hi.astype(jnp.float32)).astype(jnp.bfloat16)
    dims = (((0,), (0,)), ((), ()))
    contrib = jax.lax.dot_general(
        a_hi, e, dims, preferred_element_type=jnp.float32
    ) + jax.lax.dot_general(
        a_lo, e, dims, preferred_element_type=jnp.float32
    )  # (NT·S, CT·Bpad)

    @pl.when(i_r == 0)
    def _():
        out_ref[...] = contrib.reshape(out_ref.shape)

    @pl.when(i_r > 0)
    def _():
        out_ref[...] = out_ref[...] + contrib.reshape(out_ref.shape)


@functools.partial(
    jax.jit,
    static_argnames=("n_nodes", "n_bins", "interpret", "blocked", "tiles",
                     "n_shards"),
)
def hist_pallas_local(
    bins_u8, nid, stats, n_nodes: int, n_bins: int, interpret: bool = False,
    blocked: bool = False, tiles: tuple | None = None, n_shards: int = 1,
):
    """Shard-local Pallas histogram.

    ``stats`` is the (n, S) stat matrix (S static from its shape). Drop-in
    replacement for ``_hist_matmul_local`` / ``_hist_scatter_local``.
    ``interpret=True`` runs the kernel in the Pallas interpreter (CPU CI).

    ``blocked=False`` (default): returns (C, n_nodes*n_bins, S) float32 —
    reached through two unscramble passes over the full tensor in HBM.
    ``blocked=True``: returns the kernel's native tile layout
    (:class:`HistLayout`, see :func:`plan_layout`) with NO unscramble pass —
    the fused split pipeline consumes the tiles directly. ``tiles`` is the
    static (row, col, node) tile triple (callers resolve the
    ``H2O3_TPU_PALLAS_TILES`` knob via :func:`_tiles` so each tile choice
    compiles its own executable). ``n_shards`` pads the column-tile count
    for a downstream tiled psum_scatter (blocked mode only).
    """
    n, c = bins_u8.shape
    ns = stats.shape[1]
    row_tile = (tiles or _tiles())[0]
    # TILE GEOMETRY (ct/bpad/nt) comes from the sharded plan so the blocks
    # match what the downstream scatter/split kernel expects, but the grid
    # runs at the NATURAL tile count: the shard-rounding pad (blocked mode,
    # n_shards > 1) is applied to the OUTPUT tensor below — zero tiles cost
    # a cheap hist-sized pad instead of extra kernel grid work (the dense
    # pipeline pads its histogram the same way)
    lay_sh = plan_layout(c, n_nodes, n_bins, ns, tiles=tiles,
                         n_shards=n_shards if blocked else 1)
    nt, ct, bpad = lay_sh.nt, lay_sh.ct, lay_sh.bpad
    n_nt = lay_sh.n_nt
    n_ct = _cdiv(c, ct)  # natural (pre-shard-rounding) tile count
    cpad = n_ct * ct
    n_r = max(_cdiv(n, row_tile), 1)
    npad = n_r * row_tile

    if npad != n:
        bins_u8 = jnp.pad(bins_u8, ((0, npad - n), (0, 0)))
        nid = jnp.pad(nid, (0, npad - n), constant_values=-1)
        stats = jnp.pad(stats, ((0, npad - n), (0, 0)))
    if cpad != c:
        bins_u8 = jnp.pad(bins_u8, ((0, 0), (0, cpad - c)))
    # (npad, cpad) → (n_ct, npad, CT): each grid step's column tile is the
    # (full) last dim of its block, satisfying Mosaic's lane-divisibility rule
    bins3 = jnp.transpose(bins_u8.reshape(npad, n_ct, ct), (1, 0, 2))
    nid2 = nid.reshape(npad, 1)

    kernel = functools.partial(_hist_kernel, nt=nt, ct=ct, bpad=bpad, ns=ns)
    out_bytes = 4 * n_nt * nt * ns * cpad * bpad
    cost = pl.CostEstimate(
        flops=int(2 * npad * (nt * ns) * cpad * bpad),
        # Inputs re-stream once per revisiting grid dimension (bins per node
        # tile, nid/stats per (node, col) tile); the OUTPUT block is written
        # at row chunk 0 and read+rewritten on each of the following n_r - 1
        # chunks — 2·n_r − 1 accesses, not 1 (the old estimate undercounted
        # the dominant term and skewed the scheduler).
        bytes_accessed=int(
            npad * cpad * n_nt
            + npad * (ns + 1) * 4 * n_nt * n_ct
            + out_bytes * (2 * n_r - 1)
        ),
        transcendentals=0,
    )
    if blocked:
        blk_shape = (n_ct, lay_sh.nn * ns, ct * bpad)
        out = pl.pallas_call(
            kernel,
            grid=(n_nt, n_ct, n_r),
            in_specs=[
                pl.BlockSpec(
                    (1, row_tile, ct),
                    lambda nt_, ct_, r_: (ct_, r_, 0),
                    memory_space=pltpu.VMEM,
                ),
                pl.BlockSpec(
                    (row_tile, 1), lambda nt_, ct_, r_: (r_, 0),
                    memory_space=pltpu.VMEM,
                ),
                pl.BlockSpec(
                    (row_tile, ns), lambda nt_, ct_, r_: (r_, 0),
                    memory_space=pltpu.VMEM,
                ),
            ],
            out_specs=pl.BlockSpec(
                (1, nt * ns, ct * bpad),
                lambda nt_, ct_, r_: (ct_, nt_, 0),
                memory_space=pltpu.VMEM,
            ),
            out_shape=jax.ShapeDtypeStruct(blk_shape, jnp.float32),
            cost_estimate=cost,
            interpret=interpret,
        )(bins3, nid2, stats)
        if lay_sh.n_ct > n_ct:
            out = jnp.pad(out, ((0, lay_sh.n_ct - n_ct), (0, 0), (0, 0)))
        return out

    out = pl.pallas_call(
        kernel,
        grid=(n_nt, n_ct, n_r),
        in_specs=[
            pl.BlockSpec(
                (1, row_tile, ct),
                lambda nt_, ct_, r_: (ct_, r_, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (row_tile, 1), lambda nt_, ct_, r_: (r_, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (row_tile, ns), lambda nt_, ct_, r_: (r_, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (nt * ns, ct * bpad), lambda nt_, ct_, r_: (nt_, ct_),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((n_nt * nt * ns, cpad * bpad), jnp.float32),
        cost_estimate=cost,
        interpret=interpret,
    )(bins3, nid2, stats)

    # unscramble: out rows = node·S+stat, lanes = ct-tile-major [bin//CT, col%CT]
    h5 = out.reshape(n_nt * nt, ns, n_ct, bpad, ct)
    h5 = jnp.transpose(h5, (2, 4, 0, 3, 1))  # (n_ct, ct, Npad, Bpad, S)
    h = h5.reshape(cpad, n_nt * nt, bpad, ns)[:c, :n_nodes, :n_bins, :]
    return h.reshape(c, n_nodes * n_bins, ns)
