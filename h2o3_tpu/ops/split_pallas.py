"""Pallas split-scan kernel — the second half of the fused histogram→split
tree pipeline (``H2O3_TPU_SPLIT_FUSE``).

The unfused pipeline materializes the full (C, N·B, S) histogram in HBM
(via two unscramble transpose passes over the Pallas kernel's scrambled
output), then the XLA split scan streams the whole tensor back. The r5
trace puts ~66% of device time in the histogram phase and ~18% in the split
scan — most of it HBM bandwidth, not math. This kernel closes the loop:

- input is the histogram kernel's NATIVE blocked layout
  (``hist_pallas.HistLayout``): grid step (i_ct, i_nt) reads exactly the
  (NT·S, CT·Bpad) tile the histogram kernel emitted for that (column tile,
  node tile) — one VMEM-resident pass, no relayout in HBM;
- per (node, col) it runs DTree.findBestSplitPoint's numeric branch —
  bin prefix sums, NA-direction both ways, min_rows feasibility, gain vs
  the caller-passed GLOBAL node totals — and reduces over bins in VMEM;
- only the per-(node, col) winner candidates (gain, bin, NA dir, folded
  child stats) ever reach HBM: O(N·C) scalars instead of O(N·C·B·S).

The arithmetic mirrors ``shared_tree._split_scan``'s numeric branch
operation-for-operation (same ``fit``, same gain/feasibility masks, same
lowest-index argmax), so on the adversarial tie suites — where every sum is
exact in f32 — the fused pipeline's split decisions are bit-identical to
the unfused scan's (pinned by tests/test_split_pallas.py); elsewhere they
agree to the f64 accuracy bound of the histogram kernel.

Categorical columns keep the mean-sorted XLA branch (argsorts are not a
Pallas-friendly shape): :func:`fused_split_scan` gathers ONLY the
categorical columns' tiles into a small dense (N, Cc, B, S) tensor and runs
the existing formulas there — per-column routing, numeric stays on the
kernel. Monotone constraints (ISSUE 15) thread INTO the kernel grid step:
the per-bin feasibility mask — bound-clamped child Newton values must not
violate the column's direction — is mirrored op-for-op from
``_split_scan``'s ``mono`` branch (a per-column ``mono`` lane and per-node
``node_lo``/``node_hi`` bounds are extra kernel inputs), and the winner's
``mid``/``mono_col`` bound-propagation outputs are derived from the folded
child stats exactly as the unfused scan derives them. The unconstrained
kernel trace is untouched (the mono variant is a separate kernel).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from h2o3_tpu.ops.hist_pallas import (
    HistLayout,
    blocked_cols_dense,
    blocked_node_totals,
)

_NEG = -1e30  # must match shared_tree._NEG (same sentinel, same compares)


def _fit(s):
    """SE with the cancelling wy² term dropped — byte-for-byte the formula
    of ``shared_tree._split_scan``'s ``fit`` (parity depends on it)."""
    w = s[..., 0]
    return -jnp.where(w > 0, s[..., 1] ** 2 / jnp.maximum(w, 1e-30), 0.0)


def _split_kernel(
    blk_ref, tot_ref, mr_ref, gain_ref, t_ref, nal_ref, lst_ref, rst_ref,
    *, nt, ct, bpad, ns, n_bins, mono_ref=None, lo_ref=None, hi_ref=None,
):
    # one histogram tile, exactly as hist_pallas emitted it:
    # rows = node·S + stat, lanes = bin·CT + col
    h = blk_ref[0].reshape(nt, ns, bpad, ct)
    hh = jnp.transpose(h, (0, 3, 2, 1))  # (nt, ct, bpad, ns)
    na = hh[:, :, 0, :]  # (nt, ct, ns)
    data = hh[:, :, 1:, :]  # (nt, ct, bpad-1, ns)
    tot = tot_ref[...]  # (nt, ns) — GLOBAL column-0 node totals
    mr = mr_ref[0, 0]

    parent_fit = _fit(tot)  # (nt,)

    def gain_with_na(L, R):
        gl = _fit(L)
        gr = _fit(R)
        ok = (L[..., 0] >= mr) & (R[..., 0] >= mr)
        g = parent_fit[:, None, None] - gl - gr
        return jnp.where(ok, g, _NEG)

    cum = jnp.cumsum(data, axis=2)  # (nt, ct, bpad-1, ns)
    tot_nonna = cum[:, :, -1:, :]
    left = cum[:, :, :-1, :]  # split after data-bin t: left = bins 1..t+1
    right = tot_nonna - left

    g_nal = gain_with_na(left + na[:, :, None, :], right)
    g_nar = gain_with_na(left, right + na[:, :, None, :])
    if mono_ref is not None:
        # monotone feasibility, the same ops as _split_scan's mono branch:
        # bound-clamped child Newton values must not violate the direction
        mono = mono_ref[0].astype(jnp.int32)  # (ct,) this tile's columns
        lo = lo_ref[:, 0]  # (nt,) this tile's node bounds
        hi = hi_ref[:, 0]

        def child_val(s):  # wy/wh clamped to the node's [lo, hi]
            v = jnp.where(
                s[..., 2] > 0, s[..., 1] / jnp.maximum(s[..., 2], 1e-30), 0.0
            )
            return jnp.clip(v, lo[:, None, None], hi[:, None, None])

        m = mono[None, :, None]
        na_b = na[:, :, None, :]
        ok_nl = (m == 0) | (
            m * (child_val(right) - child_val(left + na_b)) >= 0)
        ok_nr = (m == 0) | (
            m * (child_val(right + na_b) - child_val(left)) >= 0)
        g_nal = jnp.where(ok_nl, g_nal, _NEG)
        g_nar = jnp.where(ok_nr, g_nar, _NEG)
    # candidates past the REAL bin range (bpad tile padding) must not exist:
    # with min_rows == 0 an all-left "split" on a pad slot would otherwise
    # become feasible, which the dense scan never even enumerates
    valid_t = (
        jax.lax.broadcasted_iota(jnp.int32, g_nal.shape, 2) < n_bins - 2
    )
    g_nal = jnp.where(valid_t, g_nal, _NEG)
    g_nar = jnp.where(valid_t, g_nar, _NEG)

    g = jnp.maximum(g_nal, g_nar)
    tbest = jnp.argmax(g, axis=2)  # (nt, ct) — lowest index on ties
    take = lambda a: jnp.take_along_axis(a, tbest[:, :, None], 2).squeeze(2)
    best_gain = take(g)
    nal = take(g_nal) >= take(g_nar)
    take3 = lambda a: jnp.take_along_axis(
        a, tbest[:, :, None, None], 2
    ).squeeze(2)  # (nt, ct, ns)
    Lraw, Rraw = take3(left), take3(right)
    Lst = Lraw + jnp.where(nal[:, :, None], na, 0.0)
    Rst = Rraw + jnp.where(~nal[:, :, None], na, 0.0)

    gain_ref[0] = best_gain
    t_ref[0] = tbest.astype(jnp.int32)
    nal_ref[0] = nal.astype(jnp.int32)
    # child stats ship in the layout's row convention: rows = node·S + stat
    lst_ref[0] = jnp.transpose(Lst, (0, 2, 1)).reshape(nt * ns, ct)
    rst_ref[0] = jnp.transpose(Rst, (0, 2, 1)).reshape(nt * ns, ct)


def _split_kernel_mono(
    blk_ref, tot_ref, mr_ref, mono_ref, lo_ref, hi_ref,
    gain_ref, t_ref, nal_ref, lst_ref, rst_ref,
    *, nt, ct, bpad, ns, n_bins,
):
    """Monotone-constrained grid step: the same kernel with the per-column
    direction lane and per-node bound inputs threaded through (the
    unconstrained trace above stays byte-identical — separate kernel)."""
    _split_kernel(
        blk_ref, tot_ref, mr_ref, gain_ref, t_ref, nal_ref, lst_ref, rst_ref,
        nt=nt, ct=ct, bpad=bpad, ns=ns, n_bins=n_bins,
        mono_ref=mono_ref, lo_ref=lo_ref, hi_ref=hi_ref,
    )


@functools.partial(
    jax.jit, static_argnames=("layout", "interpret")
)
def split_candidates(
    blk, node_totals, min_rows, layout: HistLayout, interpret: bool = False,
    mono=None, node_lo=None, node_hi=None,
):
    """Per-(node, col) numeric split candidates from a blocked histogram.

    Returns ``(gain, tbest, na_left, Lst, Rst)`` with shapes
    (N, cpad), (N, cpad) i32, (N, cpad) bool, (N, cpad, S), (N, cpad, S) —
    tiny next to the histogram. ``node_totals`` is (n_nodes, S): the GLOBAL
    column-0 totals every block's gains are computed against (the sharded
    merge's bit-exactness contract, see shared_tree._split_scan_sharded).

    ``mono`` ((cpad,) int {-1,0,1}) + ``node_lo``/``node_hi`` ((n_nodes,))
    select the monotone-constrained kernel variant: infeasible candidates
    are masked to ``_NEG`` inside the grid step, exactly as the unfused
    scan masks them before its argmax.
    """
    L = layout
    nt, ct, bpad, ns = L.nt, L.ct, L.bpad, L.ns
    tot = node_totals.astype(jnp.float32)
    if L.nn > L.n_nodes:  # pad nodes: zero totals, zero hists — never win
        tot = jnp.pad(tot, ((0, L.nn - L.n_nodes), (0, 0)))
    mr = jnp.asarray(min_rows, jnp.float32).reshape(1, 1)

    specs = [
        pl.BlockSpec(
            (1, nt * ns, ct * bpad),
            lambda ct_, nt_: (ct_, nt_, 0),
            memory_space=pltpu.VMEM,
        ),
        pl.BlockSpec(
            (nt, ns), lambda ct_, nt_: (nt_, 0), memory_space=pltpu.VMEM
        ),
        pl.BlockSpec(
            (1, 1), lambda ct_, nt_: (0, 0), memory_space=pltpu.VMEM
        ),
    ]
    args = [blk, tot, mr]
    if mono is not None:
        kernel = functools.partial(
            _split_kernel_mono, nt=nt, ct=ct, bpad=bpad, ns=ns,
            n_bins=L.n_bins,
        )
        mono_t = mono.astype(jnp.int32).reshape(L.n_ct, ct)
        # pad-node bounds are inert: their histograms are all zero, so no
        # candidate there is ever feasible regardless of the bound values
        lo = node_lo.astype(jnp.float32)
        hi = node_hi.astype(jnp.float32)
        if L.nn > L.n_nodes:
            lo = jnp.pad(lo, (0, L.nn - L.n_nodes),
                         constant_values=-jnp.inf)
            hi = jnp.pad(hi, (0, L.nn - L.n_nodes), constant_values=jnp.inf)
        specs += [
            pl.BlockSpec(
                (1, ct), lambda ct_, nt_: (ct_, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (nt, 1), lambda ct_, nt_: (nt_, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (nt, 1), lambda ct_, nt_: (nt_, 0), memory_space=pltpu.VMEM
            ),
        ]
        args += [mono_t, lo.reshape(L.nn, 1), hi.reshape(L.nn, 1)]
    else:
        kernel = functools.partial(
            _split_kernel, nt=nt, ct=ct, bpad=bpad, ns=ns, n_bins=L.n_bins
        )
    scalar_spec = lambda: pl.BlockSpec(
        (1, nt, ct), lambda ct_, nt_: (ct_, nt_, 0), memory_space=pltpu.VMEM
    )
    stat_spec = lambda: pl.BlockSpec(
        (1, nt * ns, ct), lambda ct_, nt_: (ct_, nt_, 0),
        memory_space=pltpu.VMEM,
    )
    gain, tbest, nal, lst, rst = pl.pallas_call(
        kernel,
        grid=(L.n_ct, L.n_nt),
        in_specs=specs,
        out_specs=[
            scalar_spec(), scalar_spec(), scalar_spec(),
            stat_spec(), stat_spec(),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((L.n_ct, L.nn, ct), jnp.float32),
            jax.ShapeDtypeStruct((L.n_ct, L.nn, ct), jnp.int32),
            jax.ShapeDtypeStruct((L.n_ct, L.nn, ct), jnp.int32),
            jax.ShapeDtypeStruct((L.n_ct, L.nn * ns, ct), jnp.float32),
            jax.ShapeDtypeStruct((L.n_ct, L.nn * ns, ct), jnp.float32),
        ],
        cost_estimate=pl.CostEstimate(
            # the whole blocked histogram streams through VMEM exactly once;
            # outputs are O(N·C) and negligible next to it
            flops=int(10 * L.nn * L.cpad * bpad * ns),
            bytes_accessed=int(4 * L.n_ct * L.nn * ns * ct * bpad),
            transcendentals=0,
        ),
        interpret=interpret,
    )(*args)

    N, Cp = L.n_nodes, L.cpad
    to_nc = lambda a: jnp.transpose(a, (1, 0, 2)).reshape(L.nn, Cp)[:N]
    lst = jnp.transpose(
        lst.reshape(L.n_ct, L.nn, ns, ct), (1, 0, 3, 2)
    ).reshape(L.nn, Cp, ns)[:N]
    rst = jnp.transpose(
        rst.reshape(L.n_ct, L.nn, ns, ct), (1, 0, 3, 2)
    ).reshape(L.nn, Cp, ns)[:N]
    return (
        to_nc(gain), to_nc(tbest), to_nc(nal).astype(bool), lst, rst
    )


def fused_split_scan(
    blk, layout: HistLayout, is_cat, col_mask, min_rows,
    min_split_improvement, cat_cols=(), node_totals=None,
    interpret: bool | None = None, mono=None, node_lo=None, node_hi=None,
):
    """Best split per node from a BLOCKED histogram — the drop-in fused
    replacement for ``shared_tree._split_scan`` (same return dict, same
    tie-breaking, no dense histogram ever assembled for numeric columns).

    ``is_cat``/``col_mask`` arrive at the REAL column count and are padded
    to the layout's ``cpad`` here (pad columns mask to gain ``_NEG``, so
    the column argmax resolves exactly as the dense scan's over C columns).
    ``cat_cols`` (static GLOBAL column indices) routes those columns to the
    mean-sorted fallback branch on a small dense gather; ``node_totals``
    overrides the column-0 totals exactly as in ``_split_scan``.

    ``mono`` ((C,) int {-1,0,1}) activates the monotone-constrained kernel
    variant with per-node ``node_lo``/``node_hi`` bounds; the result then
    carries ``mid``/``mono_col`` for child-bound propagation, mirroring the
    unfused scan (categorical winners carry ``mono_col`` 0 — the cat branch
    is unconstrained there too).
    """
    L = layout
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    N, Cp, B = L.n_nodes, L.cpad, L.n_bins
    C = is_cat.shape[0]
    if node_totals is None:
        node_totals = blocked_node_totals(blk, L)
    if Cp > C:
        is_cat = jnp.pad(is_cat, (0, Cp - C))
        col_mask = jnp.pad(col_mask, ((0, 0), (0, Cp - C)))
        if mono is not None:  # pad columns are unconstrained (and masked)
            mono = jnp.pad(mono, (0, Cp - C))

    num_best_gain, num_best_t, num_na_left, Lst_n, Rst_n = split_candidates(
        blk, node_totals, min_rows, layout=L, interpret=interpret,
        mono=mono, node_lo=node_lo, node_hi=node_hi,
    )

    if cat_cols:
        # ---- categorical fallback: mean-sorted prefix split on the cat
        # column subset only, gathered dense (O(N·Cc·B·S)) — formulas are
        # the same lines as _split_scan's categorical branch ----
        hist_c = blocked_cols_dense(blk, L, tuple(cat_cols))  # (N, Cc, B, S)
        cat_idx = jnp.asarray(np.asarray(cat_cols, np.int32))
        Cc = len(cat_cols)
        na_c = hist_c[:, :, 0, :]
        data_c = hist_c[:, :, 1:, :]
        parent_fit = _fit(node_totals[:, None, :]).squeeze(1)

        def gain_with_na(Lh, Rh):
            gl = _fit(Lh)
            gr = _fit(Rh)
            ok = (Lh[..., 0] >= min_rows) & (Rh[..., 0] >= min_rows)
            g = parent_fit[:, None, None] - gl - gr
            return jnp.where(ok, g, _NEG)

        w_bins = data_c[..., 0]
        mean = jnp.where(
            w_bins > 0, data_c[..., 1] / jnp.maximum(w_bins, 1e-30), jnp.inf
        )
        order = jnp.argsort(mean, axis=2)  # (N, Cc, B-1) empty (inf) last
        sdata = jnp.take_along_axis(data_c, order[..., None], axis=2)
        scum = jnp.cumsum(sdata, axis=2)
        s_tot = scum[:, :, -1:, :]
        s_left = scum[:, :, :-1, :]
        s_right = s_tot - s_left
        gc_naleft = gain_with_na(s_left + na_c[:, :, None, :], s_right)
        gc_naright = gain_with_na(s_left, s_right + na_c[:, :, None, :])
        g_cat = jnp.maximum(gc_naleft, gc_naright)
        cat_best_k = jnp.argmax(g_cat, axis=2)  # (N, Cc)
        cat_best_gain_c = jnp.take_along_axis(
            g_cat, cat_best_k[:, :, None], 2
        ).squeeze(2)
        cat_na_left_c = (
            jnp.take_along_axis(gc_naleft, cat_best_k[:, :, None], 2).squeeze(2)
            >= jnp.take_along_axis(gc_naright, cat_best_k[:, :, None], 2).squeeze(2)
        )
        cat_best_gain = jnp.full((N, Cp), _NEG, jnp.float32).at[
            :, cat_idx
        ].set(cat_best_gain_c)
        col_gain = jnp.where(is_cat[None, :], cat_best_gain, num_best_gain)
    else:
        col_gain = num_best_gain

    # ---- choose best column per node (identical argmax to _split_scan:
    # pad columns are col_mask 0 → _NEG; the all-_NEG argmax is 0 in both
    # the C-wide and the Cp-wide matrix) ----
    col_gain = jnp.where(col_mask > 0, col_gain, _NEG)
    best_col = jnp.argmax(col_gain, axis=1)  # (N,)
    best_gain = jnp.take_along_axis(col_gain, best_col[:, None], 1).squeeze(1)

    take = lambda a: jnp.take_along_axis(a, best_col[:, None], 1).squeeze(1)
    bc_t = take(num_best_t)
    split_bin = bc_t + 1

    take_s = lambda a: jnp.take_along_axis(
        a, best_col[:, None, None], 1
    ).squeeze(1)  # (N, S)
    Lst = take_s(Lst_n)
    Rst = take_s(Rst_n)

    if cat_cols:
        pos_of_col = np.zeros(Cp, np.int32)
        pos_of_col[list(cat_cols)] = np.arange(Cc, dtype=np.int32)
        bc_is_cat = is_cat[best_col]
        best_pos = jnp.asarray(pos_of_col)[best_col]  # (N,)
        take_c = lambda a: jnp.take_along_axis(a, best_pos[:, None], 1).squeeze(1)
        bc_k = take_c(cat_best_k)
        bc_na_left = jnp.where(
            bc_is_cat, take_c(cat_na_left_c), take(num_na_left)
        )
        ranks = jnp.argsort(order, axis=2)  # (N, Cc, B-1)
        idx = jnp.broadcast_to(best_pos[:, None, None], (N, 1, ranks.shape[2]))
        best_ranks = jnp.take_along_axis(ranks, idx, axis=1).squeeze(1)
        cat_left = best_ranks <= bc_k[:, None]
        cat_mask = jnp.concatenate([bc_na_left[:, None], cat_left], axis=1)
        cat_mask = jnp.where(bc_is_cat[:, None], cat_mask, False)
        gidx_c = best_pos[:, None, None, None]
        gcat = lambda arr: jnp.take_along_axis(
            jnp.take_along_axis(arr, gidx_c, 1).squeeze(1),
            bc_k[:, None, None], 1,
        ).squeeze(1)
        na_best = jnp.take_along_axis(na_c, best_pos[:, None, None], 1).squeeze(1)
        nl = bc_na_left[:, None]
        Lst_c = gcat(s_left) + jnp.where(nl, na_best, 0.0)
        Rst_c = gcat(s_right) + jnp.where(~nl, na_best, 0.0)
        Lst = jnp.where(bc_is_cat[:, None], Lst_c, Lst)
        Rst = jnp.where(bc_is_cat[:, None], Rst_c, Rst)
    else:
        bc_is_cat = jnp.zeros(N, bool)
        bc_na_left = take(num_na_left)
        cat_mask = jnp.zeros((N, B), bool)

    out = {
        "Lst": Lst,
        "Rst": Rst,
        "gain": best_gain,
        "ok": best_gain >= min_split_improvement,
        "col": best_col,
        "is_cat": bc_is_cat,
        "split_bin": split_bin,
        "na_left": bc_na_left,
        "cat_mask": cat_mask,
        "node_w": node_totals[:, 0],
        "node_wy": node_totals[:, 1],
        "node_wh": node_totals[:, 2],
    }
    if mono is not None:
        # chosen split's clamped child values -> mid for bound propagation;
        # same formulas as _split_scan's tail (categorical winners carry
        # mono_col 0, so their mid is never consumed)
        vL = jnp.clip(
            jnp.where(Lst[:, 2] > 0,
                      Lst[:, 1] / jnp.maximum(Lst[:, 2], 1e-30), 0.0),
            node_lo, node_hi,
        )
        vR = jnp.clip(
            jnp.where(Rst[:, 2] > 0,
                      Rst[:, 1] / jnp.maximum(Rst[:, 2], 1e-30), 0.0),
            node_lo, node_hi,
        )
        out["mid"] = 0.5 * (vL + vR)
        out["mono_col"] = jnp.where(bc_is_cat, 0, mono[best_col])
    return out
