"""Weighted Gram accumulation — successor of ``hex.gram.Gram`` [UNVERIFIED
upstream path, SURVEY.md §2.2].

H2O accumulates X'WX with a per-chunk outer-product MRTask and a pairwise
reduce, then Cholesky-solves on one node. Here the accumulation is a single
einsum over the row-sharded design matrix: XLA tiles it onto the MXU and
inserts the cross-chip ``psum`` automatically (the MRTask reduce). float32
with HIGHEST precision keeps the normal equations accurate; the (p,p) solve
happens host-side in float64 — same split as H2O (distributed accumulate,
local solve).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import scipy.linalg

_P = jax.lax.Precision.HIGHEST


@jax.jit
def weighted_gram(X, w, z):
    """Return (G, b) = (XᵀWX, XᵀWz) for diagonal W, plus the weight sum."""
    Xw = X * w[:, None]
    G = jnp.einsum("np,nq->pq", Xw, X, precision=_P)
    b = jnp.einsum("np,n->p", Xw, z, precision=_P)
    return G, b, w.sum(dtype=jnp.float32)


def solve_cholesky(G: np.ndarray, b: np.ndarray, ridge: float = 0.0) -> np.ndarray:
    """Host-side SPD solve with jitter escalation (Gram.Cholesky successor)."""
    G = np.asarray(G, np.float64)
    b = np.asarray(b, np.float64)
    p = G.shape[0]
    jitter = 0.0
    for _ in range(6):
        try:
            c, low = scipy.linalg.cho_factor(
                G + (ridge + jitter) * np.eye(p), lower=True
            )
            return scipy.linalg.cho_solve((c, low), b)
        except np.linalg.LinAlgError:
            jitter = max(1e-10, jitter * 10 or 1e-10)
    return np.linalg.lstsq(G + ridge * np.eye(p), b, rcond=None)[0]


def admm_elastic_net(
    G: np.ndarray,
    b: np.ndarray,
    l1: float,
    l2: float,
    intercept_idx: int | None,
    rho: float | None = None,
    iters: int = 500,
    tol: float = 1e-6,
    non_negative: bool = False,
) -> np.ndarray:
    """ADMM LASSO/elastic-net on the Gram — successor of
    ``hex.optimization.ADMM`` [UNVERIFIED]: minimize ½βᵀGβ − bᵀβ + l2/2‖β‖² +
    l1‖β‖₁ (intercept unpenalized)."""
    G = np.asarray(G, np.float64)
    b = np.asarray(b, np.float64)
    p = G.shape[0]
    if rho is None:
        rho = max(1e-3, np.mean(np.diag(G)))
    A = G + (l2 + rho) * np.eye(p)
    c, low = scipy.linalg.cho_factor(A, lower=True)
    x = np.zeros(p)
    z = np.zeros(p)
    u = np.zeros(p)
    thr = np.full(p, l1 / rho)
    if intercept_idx is not None:
        thr[intercept_idx] = 0.0
    for _ in range(iters):
        x = scipy.linalg.cho_solve((c, low), b + rho * (z - u))
        z_old = z
        v = x + u
        z = np.sign(v) * np.maximum(np.abs(v) - thr, 0.0)
        if non_negative:
            neg = np.arange(p) != (intercept_idx if intercept_idx is not None else -1)
            z = np.where(neg & (z < 0), 0.0, z)
        u = u + x - z
        if np.max(np.abs(z - z_old)) < tol and np.max(np.abs(x - z)) < tol:
            break
    return z
