"""Weighted Gram accumulation — successor of ``hex.gram.Gram`` [UNVERIFIED
upstream path, SURVEY.md §2.2].

H2O accumulates X'WX with a per-chunk outer-product MRTask and a pairwise
reduce, then Cholesky-solves on one node. Here the accumulation is a single
einsum over the row-sharded design matrix: XLA tiles it onto the MXU and
inserts the cross-chip ``psum`` automatically (the MRTask reduce). float32
with HIGHEST precision keeps the normal equations accurate; the (p,p) solve
happens host-side in float64 — same split as H2O (distributed accumulate,
local solve).

The fused whole-program IRLS lane (H2O3_TPU_GLM_FUSE, models/glm.py) uses
the explicit variants below instead: :func:`weighted_gram_sharded` ends in a
``psum_scatter`` of contiguous G row blocks over the rows mesh axis (each
device keeps p/P rows; the solve gathers them once — the hierarchical-
reduction placement of arXiv:2110.10548 at one mesh level), and
:func:`cho_solve_jitter_device` / :func:`admm_elastic_net_device` move the
per-iteration solve on-device (float32) so a K-iteration chunk runs with
zero host round-trips. The host float64 functions stay as the singular-tail
fallback lane.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import scipy.linalg

_P = jax.lax.Precision.HIGHEST


@jax.jit
def weighted_gram(X, w, z):
    """Return (G, b) = (XᵀWX, XᵀWz) for diagonal W, plus the weight sum."""
    Xw = X * w[:, None]
    G = jnp.einsum("np,nq->pq", Xw, X, precision=_P)
    b = jnp.einsum("np,n->p", Xw, z, precision=_P)
    return G, b, w.sum(dtype=jnp.float32)


def weighted_gram_sharded(X, w, z, mesh=None):
    """:func:`weighted_gram` with the MRTask reduce made explicit: each
    device contracts its local row block, the Gram reduction ends in a
    ``psum_scatter`` of contiguous (p/P, p) row blocks over the rows mesh
    axis, and one ``all_gather`` reassembles G for the (replicated) solve.

    Traceable inside a larger jitted program (the fused IRLS while_loop).
    Requires ``X.shape[1]`` divisible by the shard count (the caller pads —
    models/glm.py pads the design matrix columns to the shape-bucket ladder
    and then to the mesh). Row blocks are contiguous, so device d's slice
    is exactly rows [d·p/P, (d+1)·p/P) of the replicated-einsum G.
    """
    from h2o3_tpu.parallel.mesh import (
        col_axis_name, get_mesh, n_col_shards, row_pspec, shard_map,
    )
    from jax.sharding import PartitionSpec as Spec

    mesh = mesh or get_mesh()
    n_sh = int(mesh.devices.size)
    if n_sh <= 1:
        return weighted_gram(X, w, z)
    n_blk = n_col_shards(mesh)
    cax = col_axis_name(mesh)
    p = X.shape[1]
    assert p % n_blk == 0, f"gram width {p} not divisible by {n_blk} blocks"

    from h2o3_tpu.ops import collectives

    def local(Xl, wl, zl):
        Xw = Xl * wl[:, None]
        G_l = jnp.einsum("np,nq->pq", Xw, Xl, precision=_P)
        b_l = jnp.einsum("np,n->p", Xw, zl, precision=_P)
        # contiguous row blocks: col-block d keeps G rows [d*p/B, (d+1)*p/B)
        # (on a 2-D mesh an exact rows-axis psum runs first inside the
        # wrapper and the scatter deals blocks over the cols axis only).
        # The reduce runs through the collective lane (stock psum_scatter
        # when quant is off); passes=2 adds the residual-correction pass —
        # G feeds the solve directly, so it gets ~14 effective mantissa
        # bits instead of bare int8
        G_blk = collectives.psum_scatter(G_l, n_dev=n_sh, passes=2, mesh=mesh)
        # the solve needs the full (p, p) matrix exactly once per iteration
        # — and exactly as reduced: the gather stays f32 (exact lane)
        G = jax.lax.all_gather(G_blk, cax, axis=0, tiled=True)
        b = collectives.exact_psum(b_l, mesh)
        sw = collectives.exact_psum(wl.sum(dtype=jnp.float32), mesh)
        return G, b, sw

    rspec = row_pspec(mesh)
    return shard_map(
        local, mesh,
        in_specs=(row_pspec(mesh, ndim=2), rspec, rspec),
        out_specs=(Spec(), Spec(), Spec()),
        check_vma=False,
    )(X, w, z)


def gram_collective_bytes(p_pad: int, n_shards: int) -> dict:
    """Per-lane replication-volume model (the PR-5 accounting) of ONE
    sharded Gram pass: ``gram_reduce`` = the G psum_scatter (through the
    quantized lane when on — ``lane=quant`` wire bytes, with its
    residual-correction pass) + the exact b/sw (or packed b/deviance)
    psums, ``gram_gather`` = the one exact all_gather that reassembles G
    for the solve. Shape: {phase: {lane: bytes}}; empty lanes on a
    1-device mesh (nothing moves)."""
    from h2o3_tpu.ops.collectives import modeled_reduce_bytes

    if n_shards <= 1:
        return {"gram_reduce": {}, "gram_gather": {}}
    reduce_lanes = dict(modeled_reduce_bytes(
        p_pad * p_pad, n_shards, passes=2))
    reduce_lanes["exact"] = reduce_lanes.get("exact", 0.0) + (p_pad + 1) * 4.0
    return {
        "gram_reduce": reduce_lanes,
        "gram_gather": {"exact": p_pad * p_pad * 4.0},
    }


# jitter ladder mirroring solve_cholesky's host escalation: first try is
# bare, then max(1e-10, 10x) per retry — six attempts before the caller's
# lstsq fallback
_JITTERS = (0.0, 1e-10, 1e-9, 1e-8, 1e-7, 1e-6)


def cho_solve_jitter_device(G, b, extra_diag=None):
    """On-device SPD solve with jitter escalation — the traceable f32
    analog of :func:`solve_cholesky`. ``jax.scipy`` Cholesky reports
    non-SPD as NaNs instead of raising, so every rung of the ladder is
    factored and the first finite solution wins. Returns ``(x, ok)``;
    ``ok=False`` (no rung produced a finite solution) routes the caller to
    the host float64 lstsq fallback lane. ``extra_diag`` is a per-column
    additive diagonal (ridge wiring + the unit diagonal that keeps padded
    bucket columns invertible without touching real coefficients)."""
    p = G.shape[0]
    eye = jnp.eye(p, dtype=G.dtype)
    if extra_diag is not None:
        G = G + jnp.diag(extra_diag)
    x = jnp.zeros_like(b)
    ok = jnp.asarray(False)
    for j in _JITTERS:
        c, low = jax.scipy.linalg.cho_factor(G + j * eye, lower=True)
        xj = jax.scipy.linalg.cho_solve((c, low), b)
        okj = jnp.all(jnp.isfinite(xj))
        take = (~ok) & okj
        x = jnp.where(take, xj, x)
        ok = ok | okj
    return x, ok


@partial(jax.jit, static_argnames=("iters", "non_negative"))
def admm_elastic_net_device(
    G, b, l1, l2, icpt, pad_diag, real_p,
    rho=None, iters=500, tol=1e-6, non_negative=False,
):
    """Traceable f32 ADMM elastic net mirroring :func:`admm_elastic_net`
    op-for-op (same rho heuristic, same soft-threshold loop, same stopping
    rule) with a while_loop early exit. ``icpt`` is a DYNAMIC index (-1 for
    no intercept) so one compiled program serves every design width in a
    shape bucket; ``pad_diag`` adds a unit diagonal on padded bucket columns
    (their b entries are zero, so their coefficients stay exactly zero) and
    ``real_p`` is the true column count for the rho diagonal mean. Returns
    ``(z, ok)`` like the Cholesky lane."""
    p = G.shape[0]
    ar = jnp.arange(p)
    diag = jnp.diagonal(G)
    if rho is None:
        rho = jnp.maximum(
            1e-3, jnp.sum(diag * (1.0 - pad_diag)) / jnp.maximum(real_p, 1.0)
        )
    A = G + jnp.diag(pad_diag) + (l2 + rho) * jnp.eye(p, dtype=G.dtype)
    c, low = jax.scipy.linalg.cho_factor(A, lower=True)
    thr = jnp.where(ar == icpt, 0.0, l1 / rho)
    neg_mask = ar != icpt

    def body(carry):
        x, z, u, z_old, i, done = carry
        x = jax.scipy.linalg.cho_solve((c, low), b + rho * (z - u))
        v = x + u
        z_new = jnp.sign(v) * jnp.maximum(jnp.abs(v) - thr, 0.0)
        if non_negative:
            z_new = jnp.where(neg_mask & (z_new < 0), 0.0, z_new)
        done = (jnp.max(jnp.abs(z_new - z)) < tol) & (
            jnp.max(jnp.abs(x - z_new)) < tol
        )
        return x, z_new, u + x - z_new, z, i + 1, done

    def cond(carry):
        _, _, _, _, i, done = carry
        return (i < iters) & ~done

    z0 = jnp.zeros_like(b)
    x, z, u, _, _, _ = jax.lax.while_loop(
        cond, body, (z0, z0, z0, z0, jnp.int32(0), jnp.asarray(False))
    )
    ok = jnp.all(jnp.isfinite(z)) & jnp.all(jnp.isfinite(c))
    return z, ok


def solve_cholesky(G: np.ndarray, b: np.ndarray, ridge: float = 0.0) -> np.ndarray:
    """Host-side SPD solve with jitter escalation (Gram.Cholesky successor)."""
    G = np.asarray(G, np.float64)
    b = np.asarray(b, np.float64)
    p = G.shape[0]
    jitter = 0.0
    for _ in range(6):
        try:
            c, low = scipy.linalg.cho_factor(
                G + (ridge + jitter) * np.eye(p), lower=True
            )
            return scipy.linalg.cho_solve((c, low), b)
        except np.linalg.LinAlgError:
            jitter = max(1e-10, jitter * 10 or 1e-10)
    return np.linalg.lstsq(G + ridge * np.eye(p), b, rcond=None)[0]


def admm_elastic_net(
    G: np.ndarray,
    b: np.ndarray,
    l1: float,
    l2: float,
    intercept_idx: int | None,
    rho: float | None = None,
    iters: int = 500,
    tol: float = 1e-6,
    non_negative: bool = False,
) -> np.ndarray:
    """ADMM LASSO/elastic-net on the Gram — successor of
    ``hex.optimization.ADMM`` [UNVERIFIED]: minimize ½βᵀGβ − bᵀβ + l2/2‖β‖² +
    l1‖β‖₁ (intercept unpenalized)."""
    G = np.asarray(G, np.float64)
    b = np.asarray(b, np.float64)
    p = G.shape[0]
    if rho is None:
        rho = max(1e-3, np.mean(np.diag(G)))
    A = G + (l2 + rho) * np.eye(p)
    c, low = scipy.linalg.cho_factor(A, lower=True)
    x = np.zeros(p)
    z = np.zeros(p)
    u = np.zeros(p)
    thr = np.full(p, l1 / rho)
    if intercept_idx is not None:
        thr[intercept_idx] = 0.0
    for _ in range(iters):
        x = scipy.linalg.cho_solve((c, low), b + rho * (z - u))
        z_old = z
        v = x + u
        z = np.sign(v) * np.maximum(np.abs(v) - thr, 0.0)
        if non_negative:
            neg = np.arange(p) != (intercept_idx if intercept_idx is not None else -1)
            z = np.where(neg & (z < 0), 0.0, z)
        u = u + x - z
        if np.max(np.abs(z - z_old)) < tol and np.max(np.abs(x - z)) < tol:
            break
    return z
