from h2o3_tpu.frame.frame import Frame, Vec
from h2o3_tpu.frame.parse import import_file, upload_file, parse_setup

__all__ = ["Frame", "Vec", "import_file", "upload_file", "parse_setup"]
